"""Interactive console package (ref role: console/ + internal/jsre)."""
