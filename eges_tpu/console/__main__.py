"""``python -m eges_tpu.console --rpc http://127.0.0.1:9100`` — the
attach console (ref role: console/console.go + the geth ``attach``
command; a Python REPL over JSON-RPC instead of a JS VM).

Inside the REPL:
    rpc("eth_blockNumber")               # raw JSON-RPC
    eth.block_number()                   # namespaced helpers
    eth.balance("0x...")
    eth.get_block(3)
    thw.status() / thw.membership() / thw.metrics()
    debug.stacks() / debug.stats()
"""

from __future__ import annotations

import argparse
import code
import json
import urllib.request


class RpcClient:
    def __init__(self, url: str):
        self.url = url
        self._id = 0

    def __call__(self, method: str, *params):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method, "params": list(params)})
        req = urllib.request.Request(
            self.url, data=body.encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read())
        if "error" in out and out["error"]:
            raise RuntimeError(f"RPC error {out['error']}")
        return out.get("result")


class _Namespace:
    def __init__(self, rpc: RpcClient, prefix: str):
        self._rpc = rpc
        self._prefix = prefix

    def __getattr__(self, name: str):
        # snake_case helper -> camelCase RPC method (block_number ->
        # eth_blockNumber)
        parts = name.split("_")
        camel = parts[0] + "".join(p.title() for p in parts[1:])
        method = f"{self._prefix}_{camel}"
        return lambda *params: self._rpc(method, *params)


class Eth(_Namespace):
    """Sugar over the eth_* namespace."""

    def block_number(self) -> int:
        return int(self._rpc("eth_blockNumber"), 16)

    def balance(self, addr: str, tag: str = "latest") -> int:
        return int(self._rpc("eth_getBalance", addr, tag), 16)

    def get_block(self, n, full: bool = False):
        if isinstance(n, int):
            n = hex(n)
        return self._rpc("eth_getBlockByNumber", n, full)


# methods offered to tab completion beside live namespace attributes —
# the console.go autocomplete role (the server has no method-listing
# RPC, so the common surface is enumerated here)
_COMPLETIONS = [
    "rpc(", "eth.", "thw.", "net.", "debug.",
    "eth.block_number()", "eth.balance(", "eth.get_block(",
    "eth.get_transaction_receipt(", "eth.get_logs(", "eth.call(",
    "eth.gas_price()", "eth.chain_id()", "eth.send_raw_transaction(",
    "thw.status()", "thw.membership()", "thw.metrics()",
    "debug.stacks()", "debug.stats()", "debug.trace_transaction(",
    "net.version()",
]


def _setup_readline(ns: dict) -> None:
    """History + tab completion for the attach REPL (the
    console/console.go liner-history role; weak #6 of the round-3
    verdict).  No-op where readline is unavailable (non-tty pipes
    still work)."""
    import atexit
    import os
    try:
        import readline
        import rlcompleter
    except ImportError:
        return

    histfile = os.path.expanduser("~/.eges_tpu_console_history")
    try:
        readline.read_history_file(histfile)
    except OSError:
        pass
    readline.set_history_length(1000)
    atexit.register(lambda: _save_history(readline, histfile))

    python_completer = rlcompleter.Completer(ns)

    def complete(text: str, state: int):
        # namespace-aware suggestions first, then plain Python attrs
        matches = [c for c in _COMPLETIONS if c.startswith(text)]
        i = 0
        while True:
            m = python_completer.complete(text, i)
            if m is None:
                break
            if m not in matches:
                matches.append(m)
            i += 1
        return matches[state] if state < len(matches) else None

    readline.set_completer(complete)
    readline.parse_and_bind("tab: complete")


def _save_history(readline, histfile: str) -> None:
    try:
        readline.write_history_file(histfile)
    except OSError:
        pass


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="eges-tpu-console")
    p.add_argument("--rpc", default="http://127.0.0.1:8545")
    p.add_argument("--exec", default="",
                   help="evaluate one expression and exit (the geth "
                        "--exec attach mode)")
    args = p.parse_args(argv)

    rpc = RpcClient(args.rpc)
    ns = {
        "rpc": rpc,
        "eth": Eth(rpc, "eth"),
        "thw": _Namespace(rpc, "thw"),
        "net": _Namespace(rpc, "net"),
        "debug": _Namespace(rpc, "debug"),
        # JS literal aliases: geth console snippets built from method
        # calls, property access and bare literals — e.g.
        # `eth.getBalance(addr, "latest")` or `debug.verbosity(4) ==
        # null` — parse identically in Python once these three names
        # resolve.  JS-only SYNTAX (ternaries, `var`, `function`)
        # still needs rewriting; this is a literal shim, not a JS VM
        # (ref role: console/ otto surface).
        "true": True, "false": False, "null": None,
    }
    # contract ABI helpers (encode_call/decode_output/selector): lets an
    # operator do eth.call with real calldata from the console, the role
    # geth's console fills via web3.eth.abi
    from eges_tpu.core import abi as _abi

    ns["abi"] = _abi
    if args.exec:
        print(eval(args.exec, ns))  # noqa: S307 - operator-driven REPL
        return
    _setup_readline(ns)
    banner = (f"eges-tpu console — attached to {args.rpc}\n"
              "namespaces: rpc(method, *params), eth, thw, net, debug\n"
              "tab completes; history persists across sessions")

    class _Console(code.InteractiveConsole):
        # the REPL shares ns across statements, so `true = 5` would
        # rebind the JS-literal shim for the rest of the session; re-pin
        # the three literals after every statement (r4 advisor finding)
        def push(self, line, **kw):
            more = super().push(line, **kw)
            if not more:
                ns["true"], ns["false"], ns["null"] = True, False, None
            return more

    _Console(locals=ns).interact(banner=banner)


if __name__ == "__main__":
    main()
