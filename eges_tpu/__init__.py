"""eges-tpu: a TPU-native framework with the capabilities of socc2019-no92/eges.

The reference system is a go-ethereum 1.8.2 fork implementing the Geec
("trustedHW") permissioned-blockchain consensus engine.  This package is a
ground-up rebuild, not a port:

- The consensus control plane (leader election, validate/ACK gathering,
  registration/TTL membership, timeout/empty-block recovery, confidence
  finality) is implemented as deterministic, single-threaded, event-driven
  state machines with injectable clocks and transports
  (``eges_tpu.consensus``, ``eges_tpu.core``) instead of the reference's
  goroutine-and-mutex topology (ref: core/geec_state.go,
  consensus/geec/election/election_go.go).

- The cryptographic hot path -- secp256k1 ECDSA public-key recovery and
  Keccak-256 for transaction-sender recovery and vote checking (ref:
  crypto/secp256k1/secp256.go:105, core/types/transaction_signing.go:222) --
  is a batched JAX computation (``eges_tpu.ops``) that vmaps over signature
  rows and shards across TPU chips via ``jax.sharding`` (``eges_tpu.parallel``).
"""

__version__ = "0.1.0"
