"""Metrics registry: counters / gauges / meters / timers.

Role parity with the reference's ``metrics/`` fork (ref:
metrics/metrics.go:25 ``--metrics`` flag; instrumented in p2p/metrics.go,
eth/metrics.go, eth/downloader/metrics.go).  In-process registry with
snapshot export; the RPC layer and harness read snapshots instead of the
reference's influxdb/librato push exporters.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Meter:
    """Event rate: count + rate over the process lifetime and a 1-minute
    sliding window."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.count = 0
        self._start = clock()
        self._window: deque[tuple[float, int]] = deque()

    def mark(self, n: int = 1) -> None:
        self.count += n
        now = self._clock()
        self._window.append((now, n))
        cutoff = now - 60.0
        while self._window and self._window[0][0] < cutoff:
            self._window.popleft()

    @property
    def rate_mean(self) -> float:
        dt = self._clock() - self._start
        return self.count / dt if dt > 0 else 0.0

    @property
    def rate_1m(self) -> float:
        return sum(n for _, n in self._window) / 60.0


class Timer:
    """Duration accumulator with count/total/min/max/mean."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def update(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)

    def time(self):
        t0 = self._clock()
        timer = self

        class _Ctx:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                timer.update(timer._clock() - t0)

        return _Ctx()

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                if isinstance(m, Counter):
                    out[name] = m.value
                elif isinstance(m, Gauge):
                    out[name] = m.value
                elif isinstance(m, Meter):
                    out[name] = {"count": m.count,
                                 "rate_mean": round(m.rate_mean, 3),
                                 "rate_1m": round(m.rate_1m, 3)}
                elif isinstance(m, Timer):
                    out[name] = {"count": m.count,
                                 "mean_s": round(m.mean, 6),
                                 "max_s": round(m.max, 6)}
        return out


DEFAULT = Registry()
