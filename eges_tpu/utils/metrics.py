"""Metrics registry: counters / gauges / meters / timers / histograms.

Role parity with the reference's ``metrics/`` fork (ref:
metrics/metrics.go:25 ``--metrics`` flag; instrumented in p2p/metrics.go,
eth/metrics.go, eth/downloader/metrics.go).  In-process registry with
snapshot export; the RPC layer and harness read snapshots instead of the
reference's influxdb/librato push exporters, and ``prometheus_text``
renders the whole registry in Prometheus text exposition format 0.0.4
for the RPC server's ``GET /metrics``.

Label convention: the registry is flat, so labeled series are encoded in
the metric name as ``family;key=value,key2=value2`` (e.g.
``verifier.device_seconds;bucket=128``).  The Prometheus exporter parses
that back into real labels; ``snapshot()`` keeps the flat names.
"""

from __future__ import annotations

import random
import re
import threading
import time
from collections import deque

# Closed vocabulary of metric families emitted by the library (the part
# of the name before the ``;`` label separator).  Emit sites are checked
# against this set by ``python -m harness.analysis`` (vocabulary rule):
# an unregistered family, a family used as two different kinds, or a
# registered family with no emit site all fail the gate.
METRIC_FAMILIES = frozenset({
    # core/chain.py
    "chain.bad_blocks", "chain.blocks", "chain.fastsync_adoptions",
    "chain.geec_txns", "chain.height", "chain.insert",
    "chain.insert_seconds", "chain.txns",
    # consensus/
    "consensus.deferred_depth", "consensus.deferred_dropped",
    "consensus.elected", "consensus.forced_empties",
    "consensus.geec_txn_dropped", "consensus.ingress_oversized",
    "consensus.phase_seconds", "consensus.reg_req_dropped",
    "consensus.sealed", "membership.min_ttl", "membership.size",
    # net/ + sim/simnet.py
    "net.dead_letters", "net.direct_bytes", "net.direct_msgs",
    "net.gossip_bytes", "net.gossip_msgs", "net.peer_count",
    # sim/faults.py — deterministic fault injection
    "sim.faults_injected",
    # core/txpool.py
    "txpool.known_clears", "txpool.pending", "txpool.window_undecoded",
    # crypto/ verifiers
    "verifier.batches", "verifier.compile_cache_hits",
    "verifier.compile_cache_misses", "verifier.d2h_seconds",
    "verifier.device", "verifier.device_name", "verifier.device_seconds",
    "verifier.h2d_seconds", "verifier.host_rows", "verifier.native",
    "verifier.native_batches", "verifier.native_rows",
    "verifier.pad_waste", "verifier.padded_rows", "verifier.rows",
    # crypto/scheduler.py — coalescing scheduler + sender-recovery cache
    "verifier.cache_hits", "verifier.cache_misses",
    "verifier.prewarmed_buckets", "verifier.sched_batch_rows",
    "verifier.sched_occupancy", "verifier.sched_queue_wait_seconds",
    "verifier.singleton_batches",
    # crypto/scheduler.py — fail-safe circuit breaker around the device
    "verifier.breaker_probes", "verifier.breaker_state",
    "verifier.breaker_trips", "verifier.device_errors",
    # crypto/scheduler.py — mesh dispatch (per-device window lanes);
    # the per-device families carry a ``;device=N`` label
    "verifier.mesh_devices", "verifier.mesh_occupancy",
    "verifier.mesh_queue_depth", "verifier.mesh_rows",
    "verifier.mesh_straggler_diverts", "verifier.mesh_window_splits",
    # crypto/aotstore.py + crypto/verifier.py — AOT-serialized
    # executables: artifact save/load/export accounting, persistent
    # compile-cache hardening, and service cold-start time
    "verifier.aot_compiles", "verifier.aot_export_seconds",
    "verifier.aot_load_errors", "verifier.aot_load_seconds",
    "verifier.aot_loads", "verifier.aot_saves",
    "verifier.cold_start_seconds", "verifier.compile_cache_errors",
    # crypto/scheduler.py — double-buffered window pipeline: fraction
    # of lane windows whose H2D staging overlapped the previous
    # window's compute/D2H
    "verifier.pipeline_overlap_ratio",
    # crypto/scheduler.py — window flight recorder (bounded lifecycle
    # ring behind the thw_flight RPC)
    "verifier.flight_windows",
    # crypto/scheduler.py — flight-ring overflow (oldest window evicted
    # before anything read it; the ring's silent-loss signal)
    "verifier.flight_dropped",
    # crypto/scheduler.py — SLO-driven adaptive window controller:
    # chosen deadline/bucket per step plus the decision count
    "verifier.adapt_decisions", "verifier.sched_target_rows",
    "verifier.sched_window_ms",
    # crypto/scheduler.py — hedged re-dispatch of straggling windows:
    # speculative duplicates placed, duplicates that won, losers
    # cancelled before execution, losers that ran to waste
    "verifier.hedge_cancelled", "verifier.hedge_wasted",
    "verifier.hedge_wins", "verifier.hedges",
    # consensus/node.py — snapshot state sync: durable checkpoints,
    # O(tail) restarts, byzantine-tolerant live sync, and the billed,
    # bounded snapshot-serving plane
    "statesync.aborts", "statesync.checkpoint_bytes",
    "statesync.checkpoints", "statesync.oversized_reply",
    "statesync.pages_accepted", "statesync.pages_rejected",
    "statesync.pages_served", "statesync.poisoned",
    "statesync.reanchors", "statesync.restart_replayed",
    "statesync.resumes", "statesync.serve_throttled",
    # utils/timeseries.py + harness/collector.py — telemetry plane
    "telemetry.envelopes", "telemetry.samples",
    # harness/slo.py — burn-rate SLO engine
    "slo.alerts_firing", "slo.transitions",
    # harness/anatomy.py — commit critical-path assembler
    "anatomy.blocks",
    # eges_tpu/utils/ledger.py — ingress provenance ledger
    "ledger.evictions", "ledger.origins", "ledger.rejects",
    "ledger.rows", "ledger.snapshots",
    # eges_tpu/utils/profiler.py — continuous sampling profiler
    "profiler.dropped", "profiler.hz", "profiler.overhead_pct",
    "profiler.reports", "profiler.samples",
    # eges_tpu/utils/devstats.py — device-efficiency observatory; the
    # goodput and HBM-watermark families carry a ``;device=N`` label
    "devstats.goodput_ratio", "devstats.mem_bytes_in_use",
    "devstats.mem_limit_bytes", "devstats.mem_peak_bytes",
    "devstats.reports", "devstats.trace_captures",
})

# One-line help string per registered family, emitted as ``# HELP``
# lines by ``prometheus_text`` and kept exhaustive by the vocabulary
# checker (``python -m harness.analysis``): a family registered above
# without a help entry here fails the gate.
METRIC_HELP = {
    "chain.bad_blocks": "Blocks rejected by validation on insert.",
    "chain.blocks": "Canonical blocks inserted into the chain.",
    "chain.fastsync_adoptions": "Fast-sync snapshot adoptions.",
    "chain.geec_txns": "Geec control-plane transactions inserted.",
    "chain.height": "Current canonical chain height.",
    "chain.insert": "Block insert attempts.",
    "chain.insert_seconds": "Block insert latency in seconds.",
    "chain.txns": "Payload transactions inserted with blocks.",
    "consensus.deferred_depth": "Events parked on the deferred queue.",
    "consensus.deferred_dropped": "Oldest deferrals evicted at DEFER_MAX.",
    "consensus.elected": "Elections won by this node.",
    "consensus.forced_empties": "Empty blocks forced by round timeout.",
    "consensus.geec_txn_dropped": "UDP geec txns shed by size or backlog cap.",
    "consensus.ingress_oversized": "Datagrams dropped by the ingress "
                                   "byte cap before decode.",
    "consensus.reg_req_dropped": "Pending registrations evicted at "
                                 "REG_PENDING_MAX.",
    "consensus.phase_seconds": "Consensus phase duration in seconds.",
    "consensus.sealed": "Blocks sealed by this node.",
    "membership.min_ttl": "Minimum TTL across registered members.",
    "membership.size": "Registered committee members.",
    "net.dead_letters": "Messages dropped with no deliverable peer.",
    "net.direct_bytes": "Bytes sent over the direct (point-to-point) plane.",
    "net.direct_msgs": "Messages sent over the direct plane.",
    "net.gossip_bytes": "Bytes sent over the gossip plane.",
    "net.gossip_msgs": "Messages sent over the gossip plane.",
    "net.peer_count": "Currently connected peers.",
    "sim.faults_injected": "Scripted faults injected by the chaos harness.",
    "txpool.known_clears": "Coarse clears of the known-txn dedup set.",
    "txpool.pending": "Transactions pending in the pool.",
    "txpool.window_undecoded": (
        "Rows of a columnar ingest window dropped because the frame "
        "failed to decode."),
    "verifier.batches": "Signature verification batches dispatched.",
    "verifier.compile_cache_hits": "Verifier JIT compile-cache hits.",
    "verifier.compile_cache_misses": "Verifier JIT compile-cache misses.",
    "verifier.d2h_seconds": "Device-to-host transfer seconds.",
    "verifier.device": "Accelerator devices visible to the verifier.",
    "verifier.device_name": "Accelerator device platform/name label.",
    "verifier.device_seconds": "On-device compute seconds per batch.",
    "verifier.h2d_seconds": "Host-to-device transfer seconds.",
    "verifier.host_rows": "Rows verified on the host fallback path.",
    "verifier.native": "Whether the native host verifier is loaded.",
    "verifier.native_batches": "Batches served by the native host verifier.",
    "verifier.native_rows": "Rows served by the native host verifier.",
    "verifier.pad_waste": "Rows of padding added to reach bucket sizes.",
    "verifier.padded_rows": "Total rows after bucket padding.",
    "verifier.rows": "Signature rows submitted for verification.",
    "verifier.cache_hits": "Sender-recovery cache hits.",
    "verifier.cache_misses": "Sender-recovery cache misses.",
    "verifier.prewarmed_buckets": "Buckets compiled ahead of traffic.",
    "verifier.sched_batch_rows": "Rows per coalesced scheduler window.",
    "verifier.sched_occupancy": "Dispatched rows over padded bucket rows.",
    "verifier.sched_queue_wait_seconds":
        "Seconds a submission waited in the coalescing window.",
    "verifier.singleton_batches": "Single-row windows diverted to the host.",
    "verifier.breaker_probes": "Half-open circuit-breaker probe dispatches.",
    "verifier.breaker_state": "Circuit breaker state (0 closed, 1 open).",
    "verifier.breaker_trips": "Circuit breaker open transitions.",
    "verifier.device_errors": "Device dispatch failures.",
    "verifier.mesh_devices": "Device lanes in the mesh dispatcher.",
    "verifier.mesh_occupancy": "Per-device window occupancy.",
    "verifier.mesh_queue_depth": "Windows queued per device lane.",
    "verifier.mesh_rows": "Rows served per device lane.",
    "verifier.mesh_straggler_diverts":
        "Lane windows rescued to the host by the straggler policy.",
    "verifier.mesh_window_splits": "Windows split across device lanes.",
    "verifier.aot_compiles": "AOT executables compiled (cache miss).",
    "verifier.aot_export_seconds": "AOT artifact export seconds.",
    "verifier.aot_load_errors": "AOT artifact load failures.",
    "verifier.aot_load_seconds": "AOT artifact deserialize seconds.",
    "verifier.aot_loads": "AOT executables loaded from the artifact store.",
    "verifier.aot_saves": "AOT executables serialized to the artifact store.",
    "verifier.cold_start_seconds":
        "Service cold start: verifier ready after process start.",
    "verifier.compile_cache_errors": "Persistent compile-cache failures.",
    "verifier.pipeline_overlap_ratio":
        "Lane windows whose staging overlapped the previous compute.",
    "verifier.flight_windows":
        "Windows recorded by the lifecycle flight recorder.",
    "verifier.flight_dropped":
        "Flight-recorder windows evicted unread by ring overflow.",
    "verifier.adapt_decisions":
        "Window-sizing decisions taken by the adaptive controller.",
    "verifier.sched_target_rows":
        "Current adaptive target rows per coalesced window.",
    "verifier.sched_window_ms":
        "Current adaptive flush deadline in milliseconds.",
    "verifier.hedge_cancelled":
        "Hedged duplicates cancelled before execution (winner first).",
    "verifier.hedge_wasted":
        "Hedged duplicates that ran after the winner (wasted work).",
    "verifier.hedge_wins": "Straggling windows won by the hedge copy.",
    "verifier.hedges": "Speculative duplicate dispatches placed.",
    "statesync.aborts": "Fast syncs aborted back to full block replay.",
    "statesync.checkpoint_bytes": "Size of the newest durable checkpoint.",
    "statesync.checkpoints": "Durable state checkpoints written.",
    "statesync.oversized_reply": "State replies dropped by the pre-decode "
                                 "byte cap.",
    "statesync.pages_accepted": "State pages staged from serving peers.",
    "statesync.pages_rejected": "State pages rejected (unsolicited, "
                                "out-of-order, or unattributable).",
    "statesync.pages_served": "State pages served to fetching peers.",
    "statesync.poisoned": "Downloads rejected by the pivot root check.",
    "statesync.reanchors": "Downloads re-anchored on a fresh pivot/server.",
    "statesync.restart_replayed": "Tail blocks replayed on the last restart.",
    "statesync.resumes": "Syncs resumed from crash-staged pages.",
    "statesync.serve_throttled": "State fetches dropped by the per-peer "
                                 "serve rate limit.",
    "telemetry.envelopes": "Telemetry envelopes ingested by the collector.",
    "telemetry.samples": "Registry samples taken by the telemetry sampler.",
    "slo.alerts_firing": "SLO objectives currently in the firing state.",
    "slo.transitions": "SLO alert state-machine transitions journaled.",
    "anatomy.blocks": "Committed blocks assembled by the anatomy profiler.",
    "ledger.evictions": "Origins evicted by space-saving top-K tracking.",
    "ledger.origins": "Origins currently tracked by the ingress ledger.",
    "ledger.rejects": "Ingress rejects booked to origins by the ledger.",
    "ledger.rows": "Verifier rows booked to origins by the ledger.",
    "ledger.snapshots": "Per-block ingress_ledger snapshots journaled.",
    "profiler.dropped": "Profiler samples lost to walk races or stack caps.",
    "profiler.hz": "Configured stack-sampling rate of the CPU profiler.",
    "profiler.overhead_pct": "Profiler self-cost as % of elapsed wall time.",
    "profiler.reports": "profiler_report events folded by the collector.",
    "profiler.samples": "Thread stack samples captured by the CPU profiler.",
    "devstats.goodput_ratio":
        "Useful rows over padded device rows per lane (last tick).",
    "devstats.mem_bytes_in_use": "Device HBM bytes currently in use.",
    "devstats.mem_limit_bytes": "Device HBM allocation limit in bytes.",
    "devstats.mem_peak_bytes": "Device HBM peak bytes-in-use watermark.",
    "devstats.reports": "device_efficiency events folded by the collector.",
    "devstats.trace_captures": "On-demand device trace captures completed.",
}


def percentile(sorted_vals, q: float) -> float:
    """Linear-interpolation percentile over a pre-sorted sequence,
    matching numpy.percentile's default method."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if n == 1:
        return float(sorted_vals[0])
    rank = (q / 100.0) * (n - 1)
    lo = int(rank)
    frac = rank - lo
    if lo + 1 >= n:
        return float(sorted_vals[-1])
    return float(sorted_vals[lo]) + frac * (
        float(sorted_vals[lo + 1]) - float(sorted_vals[lo]))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Meter:
    """Event rate: count + rate over the process lifetime and a 1-minute
    sliding window."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.count = 0
        self._start = clock()
        self._window: deque[tuple[float, int]] = deque()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n
            now = self._clock()
            self._window.append((now, n))
            cutoff = now - 60.0
            while self._window and self._window[0][0] < cutoff:
                self._window.popleft()

    @property
    def rate_mean(self) -> float:
        dt = self._clock() - self._start
        return self.count / dt if dt > 0 else 0.0

    @property
    def rate_1m(self) -> float:
        with self._lock:
            return sum(n for _, n in self._window) / 60.0


class Timer:
    """Duration accumulator with count/total/min/max/mean."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)

    def time(self):
        t0 = self._clock()
        timer = self

        class _Ctx:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                timer.update(timer._clock() - t0)

        return _Ctx()

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class Histogram:
    """Reservoir-sampled distribution (Vitter's Algorithm R, fixed-size
    uniform reservoir) with exact count/total/min/max and interpolated
    percentiles over the sample.

    A seeded PRNG keeps test runs deterministic; below ``reservoir``
    observations the percentiles are exact.
    """

    RESERVOIR = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._rng = random.Random(0x5eed)
        self._sample: list[float] = []
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            if len(self._sample) < self.RESERVOIR:
                self._sample.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR:
                    self._sample[j] = v

    def percentile(self, q: float) -> float:
        with self._lock:
            vals = sorted(self._sample)
        return percentile(vals, q)

    def percentiles(self, qs=(50.0, 95.0, 99.0)) -> dict[float, float]:
        with self._lock:
            vals = sorted(self._sample)
        return {q: percentile(vals, q) for q in qs}

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        with self._lock:
            metrics = sorted(self._metrics.items())
        out = {}
        for name, m in metrics:
            if isinstance(m, Counter):
                out[name] = m.value
            elif isinstance(m, Gauge):
                out[name] = m.value
            elif isinstance(m, Meter):
                out[name] = {"count": m.count,
                             "rate_mean": round(m.rate_mean, 3),
                             "rate_1m": round(m.rate_1m, 3)}
            elif isinstance(m, Timer):
                out[name] = {"count": m.count,
                             "mean_s": round(m.mean, 6),
                             "min_s": round(m.min, 6) if m.count else 0.0,
                             "max_s": round(m.max, 6)}
            elif isinstance(m, Histogram):
                ps = m.percentiles()
                out[name] = {"count": m.count,
                             "mean": round(m.mean, 6),
                             "min": round(m.min, 6) if m.count else 0.0,
                             "max": round(m.max, 6),
                             "p50": round(ps[50.0], 6),
                             "p95": round(ps[95.0], 6),
                             "p99": round(ps[99.0], 6)}
        return out


# -- Prometheus text exposition (format 0.0.4) --------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _split_labels(name: str) -> tuple[str, dict[str, str]]:
    """``family;k=v,k2=v2`` -> (family, {k: v})."""
    if ";" not in name:
        return name, {}
    family, _, rest = name.partition(";")
    labels = {}
    for pair in rest.split(","):
        if "=" in pair:
            k, _, v = pair.partition("=")
            labels[k.strip()] = v.strip()
    return family, labels


def _prom_name(family: str) -> str:
    name = _NAME_RE.sub("_", family)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    quoted = ",".join(
        '%s="%s"' % (_prom_name(k),
                     str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in sorted(labels.items()))
    return "{" + quoted + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(f) if f != int(f) else str(int(f))


def prometheus_text(registry: "Registry | None" = None) -> str:
    """Render the registry in Prometheus text format.

    Counters/Meters become ``counter`` families, numeric Gauges become
    ``gauge``, Timers and Histograms become ``summary`` families (with
    quantile samples for Histograms).  Non-numeric gauges (e.g.
    ``verifier.device_name``) become ``<name>_info{value="..."} 1``.
    """
    reg = registry if registry is not None else DEFAULT
    with reg._lock:
        metrics = sorted(reg._metrics.items())

    families: dict[str, list[tuple[str, dict, object]]] = {}
    for name, m in metrics:
        family, labels = _split_labels(name)
        families.setdefault(_prom_name(family), []).append((name, labels, m))

    lines: list[str] = []
    for fam in sorted(families):
        members = families[fam]
        kind = type(members[0][2])
        # ``# HELP`` text keyed by the ORIGINAL (dotted) family name of
        # the first member; escaping per exposition format 0.0.4
        help_text = METRIC_HELP.get(_split_labels(members[0][0])[0], "")
        help_text = help_text.replace("\\", "\\\\").replace("\n", "\\n")

        def _help(suffix: str = "") -> None:
            if help_text:
                lines.append(f"# HELP {fam}{suffix} {help_text}")

        if kind is Counter:
            _help()
            lines.append(f"# TYPE {fam} counter")
            for _, labels, m in members:
                lines.append(f"{fam}{_fmt_labels(labels)} "
                             f"{_fmt_value(m.value)}")
        elif kind is Gauge:
            numeric = [(lb, m) for _, lb, m in members
                       if isinstance(m.value, (int, float))]
            info = [(lb, m) for _, lb, m in members
                    if not isinstance(m.value, (int, float))]
            if numeric:
                _help()
                lines.append(f"# TYPE {fam} gauge")
                for labels, m in numeric:
                    lines.append(f"{fam}{_fmt_labels(labels)} "
                                 f"{_fmt_value(m.value)}")
            if info:
                _help("_info")
                lines.append(f"# TYPE {fam}_info gauge")
                for labels, m in info:
                    lb = dict(labels)
                    lb["value"] = str(m.value)
                    lines.append(f"{fam}_info{_fmt_labels(lb)} 1")
        elif kind is Meter:
            _help("_total")
            lines.append(f"# TYPE {fam}_total counter")
            for _, labels, m in members:
                lines.append(f"{fam}_total{_fmt_labels(labels)} {m.count}")
            _help("_rate_1m")
            lines.append(f"# TYPE {fam}_rate_1m gauge")
            for _, labels, m in members:
                lines.append(f"{fam}_rate_1m{_fmt_labels(labels)} "
                             f"{_fmt_value(m.rate_1m)}")
        elif kind is Timer:
            _help()
            lines.append(f"# TYPE {fam} summary")
            for _, labels, m in members:
                lb = _fmt_labels(labels)
                lines.append(f"{fam}_count{lb} {m.count}")
                lines.append(f"{fam}_sum{lb} {_fmt_value(m.total)}")
        elif kind is Histogram:
            _help()
            lines.append(f"# TYPE {fam} summary")
            for _, labels, m in members:
                ps = m.percentiles()
                for q, key in ((50.0, "0.5"), (95.0, "0.95"), (99.0, "0.99")):
                    qlb = dict(labels)
                    qlb["quantile"] = key
                    lines.append(f"{fam}{_fmt_labels(qlb)} "
                                 f"{_fmt_value(ps[q])}")
                qlb = dict(labels)
                qlb["quantile"] = "1"
                mx = m.max if m.count else 0.0
                lines.append(f"{fam}{_fmt_labels(qlb)} {_fmt_value(mx)}")
                lb = _fmt_labels(labels)
                lines.append(f"{fam}_count{lb} {m.count}")
                lines.append(f"{fam}_sum{lb} {_fmt_value(m.total)}")
    return "\n".join(lines) + "\n"


DEFAULT = Registry()
