"""Span-based tracing: ids, parent links, attributes, ring-buffer export.

Role: the distributed half of the observability layer.  The metrics
registry (``utils/metrics.py``) answers "how long does phase X take in
aggregate"; this module answers "what happened to *this* transaction" by
stitching one trace id through tx ingest -> txpool admit -> verifier
batch -> election -> chain commit, across simnet and socket transports.

Wire format: trace context rides in front of the existing gossip/direct
payloads as a fixed 28-byte header::

    MAGIC (4B, b"\\xD7TRC") | trace_id (16B) | span_id (8B)

``inject_current`` prepends it when a span is active, ``extract`` strips
it on receipt, and ``payload_of`` lets protocol muxes peek the real RLP
payload without caring whether a header is present.  Nodes that predate
this header simply never see MAGIC and pass payloads through untouched.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass

from eges_tpu.utils import profiler

MAGIC = b"\xd7TRC"
_HEADER_LEN = len(MAGIC) + 16 + 8

_UNSET = object()


def _new_trace_id() -> str:
    # analysis: allow-determinism(trace ids are observability-only, never journaled)
    return os.urandom(16).hex()


def _new_span_id() -> str:
    # analysis: allow-determinism(span ids are observability-only, never journaled)
    return os.urandom(8).hex()


@dataclass(frozen=True)
class SpanContext:
    """Immutable (trace_id, span_id) pair — what crosses process/node
    boundaries and what children parent themselves to."""

    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars


class Span:
    """One timed operation.  Finished spans land in the tracer's ring
    buffer; unfinished ones are invisible to exporters."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "end_s", "attrs", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, start_s: float, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.start_s = start_s
        self.end_s: float | None = None
        self.attrs = attrs

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def end(self) -> None:
        if self.end_s is not None:
            return
        self.end_s = self._tracer._clock()
        self._tracer._finish(self)

    @property
    def duration_s(self) -> float:
        if self.end_s is None:
            return 0.0
        return self.end_s - self.start_s

    def to_dict(self) -> dict:
        return {"name": self.name, "trace": self.trace_id,
                "span": self.span_id, "parent": self.parent_id,
                "start_s": round(self.start_s, 6),
                "duration_s": round(self.duration_s, 6),
                "attrs": dict(self.attrs)}


class Tracer:
    """Span factory + bounded in-memory exporter.

    Finished spans go into a deque ring buffer (oldest dropped first, a
    dropped counter keeps the loss observable).  The "current" span is a
    contextvar, so nesting works across ``await`` points but — by design
    — not across ``SimClock.call_later`` hops; callers that cross a
    scheduler boundary carry a ``SpanContext`` explicitly (see
    ``core/txpool.py``).
    """

    def __init__(self, clock=time.monotonic, capacity: int = 4096):
        self._clock = clock
        self._lock = threading.Lock()
        self._finished: deque[dict] = deque(maxlen=capacity)
        self._current: ContextVar[SpanContext | None] = ContextVar(
            "geec_trace_ctx", default=None)
        self.started = 0
        self.dropped = 0

    # -- span lifecycle -------------------------------------------------
    def start_span(self, name: str, parent=_UNSET, **attrs) -> Span:
        """Open a span.  ``parent`` may be a SpanContext, None (force a
        new root), or omitted (inherit the current context)."""
        if parent is _UNSET:
            parent = self._current.get()
        if isinstance(parent, Span):
            parent = parent.context()
        if parent is None:
            trace_id, parent_id = _new_trace_id(), None
        else:
            trace_id, parent_id = parent.trace_id, parent.span_id
        with self._lock:
            self.started += 1
        return Span(self, name, trace_id, parent_id, self._clock(), attrs)

    @contextmanager
    def span(self, name: str, parent=_UNSET, **attrs):
        """Start a span, make it current for the body, end it on exit.

        Span names in ``profiler.SPAN_PHASES`` also tag the calling
        thread with the matching pipeline phase for the span body — the
        bridge that lets the continuous sampling profiler attribute
        CPU samples to ``pool_admit`` etc. without its own hooks on
        every ingest path (one dict probe per span when unmapped)."""
        sp = self.start_span(name, parent, **attrs)
        token = self._current.set(sp.context())
        ptok = profiler.tag_span(name)
        try:
            yield sp
        finally:
            if ptok is not None:
                profiler.pop_phase(ptok)
            self._current.reset(token)
            sp.end()

    def record_span(self, name: str, duration_s: float, parent=_UNSET,
                    **attrs) -> Span:
        """Record an already-measured duration as a finished span (used
        by virtual-clock phases where wall time is meaningless)."""
        sp = self.start_span(name, parent, **attrs)
        sp.start_s -= duration_s
        sp.end_s = sp.start_s + duration_s
        self._finish(sp)
        return sp

    def _finish(self, span: Span) -> None:
        with self._lock:
            if len(self._finished) == self._finished.maxlen:
                self.dropped += 1
            self._finished.append(span.to_dict())

    # -- context plumbing -----------------------------------------------
    def current_context(self) -> SpanContext | None:
        return self._current.get()

    @contextmanager
    def activate(self, ctx: SpanContext | None):
        """Make ``ctx`` the current context for the body (no-op if
        None — receivers call this unconditionally on every message)."""
        if ctx is None:
            yield
            return
        token = self._current.set(ctx)
        try:
            yield
        finally:
            self._current.reset(token)

    # -- export ---------------------------------------------------------
    def finished(self, limit: int = 0, trace: str | None = None) -> list[dict]:
        """Most-recent-last finished spans, optionally filtered by trace
        id and capped to the newest ``limit``."""
        with self._lock:
            spans = list(self._finished)
        if trace:
            spans = [s for s in spans if s["trace"] == trace]
        if limit and limit > 0:
            spans = spans[-limit:]
        return spans

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()

    def dump(self, path: str, drain: bool = True) -> int:
        """Append finished spans to ``path`` as JSONL; returns the number
        written.  ``drain`` empties the buffer so periodic dumps don't
        duplicate rows."""
        with self._lock:
            spans = list(self._finished)
            if drain:
                self._finished.clear()
        if not spans:
            return 0
        with open(path, "a", encoding="utf-8") as fh:
            for s in spans:
                fh.write(json.dumps(s, sort_keys=True) + "\n")
        return len(spans)

    def stats(self) -> dict:
        with self._lock:
            return {"started": self.started, "buffered": len(self._finished),
                    "dropped": self.dropped,
                    "capacity": self._finished.maxlen}


# -- wire-format helpers -----------------------------------------------

def inject(ctx: SpanContext | None, data: bytes) -> bytes:
    """Prepend the trace header for ``ctx`` (pass-through when None)."""
    if ctx is None:
        return data
    return (MAGIC + bytes.fromhex(ctx.trace_id)
            + bytes.fromhex(ctx.span_id) + data)


def inject_current(data: bytes, tracer: "Tracer | None" = None) -> bytes:
    """Prepend the *active* trace context, if any."""
    return inject((tracer or DEFAULT).current_context(), data)


def extract(data: bytes) -> tuple[SpanContext | None, bytes]:
    """Split an incoming payload into (context-or-None, real payload)."""
    if data[:4] == MAGIC and len(data) >= _HEADER_LEN:
        ctx = SpanContext(data[4:20].hex(), data[20:28].hex())
        return ctx, data[_HEADER_LEN:]
    return None, data


def payload_of(data: bytes) -> bytes:
    """The RLP payload regardless of a trace header — for protocol muxes
    that peek at message codes before dispatch."""
    if data[:4] == MAGIC and len(data) >= _HEADER_LEN:
        return data[_HEADER_LEN:]
    return data


DEFAULT = Tracer()
