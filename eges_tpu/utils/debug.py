"""Runtime debugging for long-running node processes.

Role parity with the reference's ``internal/debug`` (ref:
internal/debug/flags.go:37-83 — pprof HTTP server, cpuprofile, runtime
tracer, all runtime-togglable via the ``debug_*`` RPC namespace,
internal/debug/api.go).  Python equivalents:

* :func:`install_sigusr1` — ``kill -USR1 <pid>`` dumps every thread's
  stack and all asyncio tasks to stderr (the Go SIGQUIT-dump idiom) —
  the first tool for a wedged node.
* :class:`DebugController` — start/stop a cProfile CPU profile, dump
  stacks, snapshot GC/memory counters; surfaced over JSON-RPC as
  ``debug_startProfile`` / ``debug_stopProfile`` / ``debug_stacks`` /
  ``debug_stats`` (internal/debug/api.go's StartCPUProfile role).
"""

from __future__ import annotations

import signal
import sys
import threading
import traceback


def dump_stacks() -> str:
    """All thread stacks + pending asyncio tasks as one text blob."""
    out = []
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
    try:
        import asyncio

        loop = asyncio.get_running_loop()
        tasks = [t for t in asyncio.all_tasks(loop) if not t.done()]
        out.append(f"--- {len(tasks)} pending asyncio tasks ---")
        for t in tasks:
            out.append(repr(t))
    # analysis: allow-swallow(best-effort diagnostic dump; partial output ok)
    except Exception:
        pass
    return "\n".join(out)


def install_sigusr1() -> None:
    """SIGUSR1 -> stack dump on stderr (safe to call multiple times)."""

    def handler(signum, frame):
        sys.stderr.write("\n=== SIGUSR1 stack dump ===\n")
        sys.stderr.write(dump_stacks())
        sys.stderr.write("\n=== end dump ===\n")
        sys.stderr.flush()

    try:
        signal.signal(signal.SIGUSR1, handler)
    except (ValueError, OSError):
        pass  # not the main thread / unsupported platform


class DebugController:
    """Runtime-togglable profiling (the debug_* RPC surface)."""

    def __init__(self):
        self._profiler = None

    def start_profile(self) -> bool:
        """Begin a cProfile capture; False if one is already running."""
        import cProfile

        if self._profiler is not None:
            return False
        self._profiler = cProfile.Profile()
        self._profiler.enable()
        return True

    def stop_profile(self, top: int = 30) -> str:
        """Stop the capture and return a text report (top functions by
        cumulative time)."""
        import io
        import pstats

        if self._profiler is None:
            return "no profile running"
        self._profiler.disable()
        buf = io.StringIO()
        pstats.Stats(self._profiler, stream=buf).sort_stats(
            "cumulative").print_stats(top)
        self._profiler = None
        return buf.getvalue()

    def stacks(self) -> str:
        return dump_stacks()

    def stats(self) -> dict:
        """GC + interpreter counters (MemStats role)."""
        import gc

        counts = gc.get_count()
        out = {
            "gc_counts": list(counts),
            "gc_objects": len(gc.get_objects()),
            "threads": threading.active_count(),
        }
        try:
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF)
            out["max_rss_kb"] = ru.ru_maxrss
            out["user_cpu_s"] = round(ru.ru_utime, 3)
            out["sys_cpu_s"] = round(ru.ru_stime, 3)
        # analysis: allow-swallow(resource module optional; stats best-effort)
        except Exception:
            pass
        return out
