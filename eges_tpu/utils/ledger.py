"""Ingress provenance ledger: per-origin cost accounting + abuse forensics.

The paper's headline claim is *DoS-resistant* consensus, but the verify
pipeline accounts everything globally — the scheduler's cache hits,
invalid-signature early-outs, host diverts and device-ms say nothing
about WHICH peer or claimed sender consumed them.  This module is the
attribution substrate under the adversarial-load roadmap items: a
compact **origin tag** rides the thread from datagram/RPC ingest
(``sim/simnet.py`` stamps the delivering peer, ``consensus/node.py``
binds ``peer:<id>`` / ``rpc`` around its entry points) through txpool
admit/reject, scheduler window rows and consensus drops into one
:class:`IngressLedger` per node.

Two cooperating pieces:

* **Ambient origin context** (thread-local): :func:`peer` marks the
  delivering transport peer, :func:`bind` attaches (ledger, origin) for
  the duration of a handler, :func:`charge` books counts against the
  ambient origin and no-ops when unbound — instrumented layers never
  need a ledger reference threaded through their signatures.  Layers
  whose work completes on another thread or a later clock tick (txpool
  window flush, scheduler windows) capture :func:`current` at ingest
  and charge the captured pair at completion, so attribution survives
  the handoff.  Pool flushes fired by the clock timer carry per-txn
  captured origins; scheduler rows submitted OUTSIDE any bound handler
  (none today) simply stay unattributed.

* :class:`IngressLedger`: per-origin **exponentially decayed** counters
  (rows, admits, rejects, drops, deferred, cache hits/misses) plus
  wall-clock device/host milliseconds, under space-saving top-K
  tracking — evicting the lightest origin hands its weight to the
  newcomer as ``error``, the classic heavy-hitter bound, so a flood of
  one-shot origins can't wash out the real talkers.  Decay runs on the
  ledger's injected clock (virtual under the simulator), so the decayed
  counts are a pure function of the deterministic charge schedule.

Determinism contract: :meth:`IngressLedger.journal_snapshot` emits one
``ingress_ledger`` journal event per committed block (when anything
changed).  Every field is deterministic under the sim clock EXCEPT the
wall-clock ``costs`` account, which lives under that one top-level key
so the chaos canonical dump can strip it (``VOLATILE_KEYS``).  The
:class:`LedgerAssembler` below is a pure incremental function over the
sorted event stream — ``harness/collector.py`` feeds it live and in
replay in the same order, so the ledger section of the collector report
stays byte-identical between the two (the PR 9/11 invariant).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from eges_tpu.utils.metrics import DEFAULT as metrics

# decayed per-origin counter families (deterministic under the sim
# clock; wall-clock ms are accounted separately under "costs")
COUNT_KEYS = ("rows", "admits", "rejects", "drops", "deferred",
              "cache_hits", "cache_misses")
COST_KEYS = ("device_ms", "host_ms")

# distinct recovered/claimed senders remembered per origin — enough to
# tell one flooding key from a sender-cycling flood without letting an
# adversary grow the set unboundedly (beyond the cap only the count of
# remembered senders is reported, an undercount by design)
SENDER_CAP = 8


# -- ambient origin context ------------------------------------------------

_tls = threading.local()


# Origin strings come off the wire (transport peer ids, RPC client
# tags): clamp their length before they key ledger records or land in
# journal rows, so a hostile transport cannot inflate either.
_ORIGIN_MAX = 64


@contextlib.contextmanager
def peer(peer_id: str):
    """Mark ``peer_id`` as the delivering transport peer for the
    duration of a delivery callback (set by the network fabric, read by
    the receiving node's entry points via :func:`current_peer`)."""
    prev = getattr(_tls, "peer", "")
    _tls.peer = str(peer_id)[:_ORIGIN_MAX]
    try:
        yield
    finally:
        _tls.peer = prev


def current_peer() -> str:
    """The delivering peer id marked by :func:`peer`, or ``""``."""
    return getattr(_tls, "peer", "")


@contextlib.contextmanager
def bind(ledger: "IngressLedger", origin: str):
    """Attach ``(ledger, origin)`` as the ambient charge target for the
    duration of a handler (node entry points wrap their dispatch)."""
    prev = getattr(_tls, "bound", None)
    _tls.bound = (ledger, str(origin)[:_ORIGIN_MAX])
    try:
        yield
    finally:
        _tls.bound = prev


def current() -> tuple | None:
    """The ambient ``(ledger, origin)`` pair, or ``None`` unbound —
    capture this at ingest when the work completes on another thread."""
    return getattr(_tls, "bound", None)


def charge(**counts) -> None:
    """Book counts against the ambient origin; no-op when unbound (a
    layer driven outside any instrumented entry point, e.g. unit
    tests exercising the pool directly)."""
    bound = getattr(_tls, "bound", None)
    if bound is None:
        return
    led, origin = bound
    led.charge(origin, **counts)


# -- the per-node ledger ---------------------------------------------------

class IngressLedger:
    """Per-origin decayed cost counters with space-saving top-K.

    ``clock`` is a zero-arg callable (virtual under the simulator);
    decay is applied lazily at charge/snapshot time with half-life
    ``half_life_s``, so an origin that goes quiet fades instead of
    dominating the table forever.  At most ``k`` origins are tracked:
    adding one beyond that evicts the minimum-weight entry and the
    newcomer inherits its weight as ``error`` (the space-saving
    guarantee: a true heavy hitter is never displaced by churn).
    """

    def __init__(self, clock, *, k: int = 32, half_life_s: float = 60.0):
        self._clock = clock
        self.k = max(1, k)
        self.half_life_s = half_life_s
        # origin -> record; mutated only under the lock.  Metrics and
        # journal emits happen OUTSIDE it (fail-under-lock hygiene).
        self._origins: dict[str, dict] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._evictions = 0  # guarded-by: _lock
        # raw monotonic totals (ints): per-snapshot deltas drive the
        # invalid_sig_reject_ratio SLO and guarantee post-heal resolution
        # (decayed values never reach exactly zero)
        self._totals = {k2: 0 for k2 in COUNT_KEYS}
        self._last_emitted = dict(self._totals)

    def _decay(self, rec: dict, now: float) -> None:
        dt = now - rec["ts"]
        if dt <= 0:
            rec["ts"] = now
            return
        f = 0.5 ** (dt / self.half_life_s)
        for k2 in COUNT_KEYS + COST_KEYS:
            rec[k2] *= f
        rec["error"] *= f
        rec["ts"] = now

    @staticmethod
    def _weight(rec: dict) -> float:
        # deterministic counts only — never the wall-clock ms
        return sum(rec[k2] for k2 in COUNT_KEYS) + rec["error"]

    def charge(self, origin: str, *, rows: int = 0, admits: int = 0,
               rejects: int = 0, drops: int = 0, deferred: int = 0,
               cache_hits: int = 0, cache_misses: int = 0,
               device_ms: float = 0.0, host_ms: float = 0.0,
               sender: bytes | None = None) -> None:
        """Book one layer's outcome against ``origin``.  Thread-safe;
        cheap enough for per-row call sites (one lock, one decay)."""
        evicted = False
        with self._lock:
            now = self._clock()
            rec = self._origins.get(origin)
            if rec is None:
                error = 0.0
                if len(self._origins) >= self.k:
                    # space-saving eviction: drop the lightest origin
                    # (ties by name so the pick never depends on dict
                    # order) and inherit its weight as the error bound
                    victim = min(self._origins, key=lambda o: (
                        self._weight(self._origins[o]), o))
                    vrec = self._origins.pop(victim)
                    self._decay(vrec, now)
                    error = self._weight(vrec)
                    self._evictions += 1
                    evicted = True
                rec = self._origins[origin] = dict(
                    {k2: 0.0 for k2 in COUNT_KEYS + COST_KEYS},
                    error=error, ts=now, senders=set())
            else:
                self._decay(rec, now)
            rec["rows"] += rows
            rec["admits"] += admits
            rec["rejects"] += rejects
            rec["drops"] += drops
            rec["deferred"] += deferred
            rec["cache_hits"] += cache_hits
            rec["cache_misses"] += cache_misses
            rec["device_ms"] += device_ms
            rec["host_ms"] += host_ms
            if sender is not None and len(rec["senders"]) < SENDER_CAP:
                rec["senders"].add(bytes(sender))
            t = self._totals
            t["rows"] += rows
            t["admits"] += admits
            t["rejects"] += rejects
            t["drops"] += drops
            t["deferred"] += deferred
            t["cache_hits"] += cache_hits
            t["cache_misses"] += cache_misses
        if evicted:
            metrics.counter("ledger.evictions").inc()

    def _snapshot_locked(self) -> tuple[dict, dict]:
        now = self._clock()
        for rec in self._origins.values():
            self._decay(rec, now)
        order = sorted(self._origins,
                       key=lambda o: (-self._weight(self._origins[o]), o))
        origins = []
        costs = {}
        for o in order:
            rec = self._origins[o]
            row = {"origin": o}
            for k2 in COUNT_KEYS:
                row[k2] = round(rec[k2], 3)
            row["senders"] = len(rec["senders"])
            row["error"] = round(rec["error"], 3)
            origins.append(row)
            costs[o] = {"device_ms": round(rec["device_ms"], 3),
                        "host_ms": round(rec["host_ms"], 3)}
        deltas = {k2: self._totals[k2] - self._last_emitted[k2]
                  for k2 in COUNT_KEYS}
        snap = {
            "origins": origins,
            "tracked": len(origins),
            "evictions": self._evictions,
            "rows_delta": deltas["rows"],
            "admits_delta": deltas["admits"],
            "rejects_delta": deltas["rejects"],
            "drops_delta": deltas["drops"],
            # the ONE volatile account: wall-clock device/host time per
            # origin, stripped by the chaos canonical dump
            "costs": costs,
        }
        return snap, deltas

    def snapshot(self) -> dict:
        """Decayed per-origin state right now (does NOT advance the
        delta cursor — see :meth:`journal_snapshot`)."""
        with self._lock:
            return self._snapshot_locked()[0]

    def journal_snapshot(self, journal, *, blk: int) -> bool:
        """Journal one ``ingress_ledger`` event for block ``blk`` and
        advance the delta cursor; silent (returns False) when nothing
        was charged since the last emitted snapshot, so idle origins
        don't spam the stream."""
        with self._lock:
            if all(self._totals[k2] == self._last_emitted[k2]
                   for k2 in COUNT_KEYS):
                return False
            snap, deltas = self._snapshot_locked()
            self._last_emitted = dict(self._totals)
        # journal + metrics outside the ledger lock (fail-under-lock)
        if journal is not None:
            journal.record("ingress_ledger", blk=blk, **snap)
        metrics.counter("ledger.snapshots").inc()
        metrics.gauge("ledger.origins").set(snap["tracked"])
        if deltas["rows"]:
            metrics.counter("ledger.rows").inc(deltas["rows"])
        if deltas["rejects"]:
            metrics.counter("ledger.rejects").inc(deltas["rejects"])
        return True


# -- collector-side assembly ----------------------------------------------

# an offender needs SOME abuse mass before the verdict names anyone —
# one stray reject on a healthy cluster is noise, not an attacker
DOMINANT_MIN_ABUSE = 1.0


def _order_key(ev: dict) -> tuple:
    # identical to harness/collector._order_key; duplicated to keep the
    # assembler importable without pulling the collector's socket deps
    return (float(ev.get("ts", 0.0)), str(ev.get("node", "")),
            int(ev.get("seq", 0)), str(ev.get("type", "")))


class LedgerAssembler:
    """Incremental cluster-wide view over ``ingress_ledger`` events.

    Feed sorted events via :meth:`ingest` (the collector's barrier
    flush provides the order); each node's LATEST snapshot wins (the
    ledger is cumulative-decayed, not per-interval), and the report
    merges origins across nodes.  Pure function of the ingested
    stream — live push and journal replay byte-match.
    """

    def __init__(self):
        self._latest: dict[str, dict] = {}  # node -> latest event
        self._events = 0
        self._deltas = {"rows": 0, "admits": 0, "rejects": 0, "drops": 0}

    def ingest(self, ev: dict) -> None:
        if ev.get("type") != "ingress_ledger":
            return
        node = str(ev.get("node", "?"))
        self._latest[node] = ev
        self._events += 1
        for k2 in self._deltas:
            v = ev.get(k2 + "_delta")
            if isinstance(v, int):
                self._deltas[k2] += v

    def _merged(self) -> dict[str, dict]:
        per: dict[str, dict] = {}
        for node in sorted(self._latest):
            ev = self._latest[node]
            costs = ev.get("costs") or {}
            for row in ev.get("origins", ()):
                if not isinstance(row, dict):
                    continue
                o = str(row.get("origin", "?"))
                agg = per.setdefault(o, dict(
                    {k2: 0.0 for k2 in COUNT_KEYS + COST_KEYS},
                    senders=0, nodes=0))
                for k2 in COUNT_KEYS:
                    v = row.get(k2)
                    if isinstance(v, (int, float)):
                        agg[k2] += float(v)
                c = costs.get(o)
                if isinstance(c, dict):
                    for k2 in COST_KEYS:
                        v = c.get(k2)
                        if isinstance(v, (int, float)):
                            agg[k2] += float(v)
                agg["senders"] = max(agg["senders"],
                                     int(row.get("senders", 0) or 0))
                agg["nodes"] += 1
        return per

    @staticmethod
    def _score(agg: dict) -> float:
        return sum(agg[k2] for k2 in COUNT_KEYS)

    @staticmethod
    def _abuse(agg: dict) -> float:
        # the forensics signal: work the pipeline THREW AWAY for this
        # origin (invalid-sig rejects + duplicate/replacement drops)
        return agg["rejects"] + agg["drops"]

    def dominant(self) -> dict | None:
        """Name the top offender, or None when nobody crossed the abuse
        floor.  Deterministic: decayed counts only (already rounded at
        journal time), ties broken by origin name."""
        per = self._merged()
        total = sum(self._abuse(a) for a in per.values())
        if total < DOMINANT_MIN_ABUSE:
            return None
        name = min(per, key=lambda o: (-self._abuse(per[o]), o))
        agg = per[name]
        return {"origin": name,
                "share": round(self._abuse(agg) / total, 4),
                "rejects": round(agg["rejects"], 3),
                "drops": round(agg["drops"], 3)}

    def report(self) -> dict:
        per = self._merged()
        origins = []
        for o in sorted(per, key=lambda o: (-self._score(per[o]), o)):
            agg = per[o]
            attempts = agg["admits"] + agg["rejects"]
            row = {"origin": o}
            for k2 in COUNT_KEYS + COST_KEYS:
                row[k2] = round(agg[k2], 3)
            row["reject_ratio"] = (round(agg["rejects"] / attempts, 4)
                                   if attempts > 0 else 0.0)
            row["senders"] = agg["senders"]
            row["nodes"] = agg["nodes"]
            origins.append(row)
        return {
            "snapshots": self._events,
            "nodes": len(self._latest),
            "rows_delta_total": self._deltas["rows"],
            "admits_total": self._deltas["admits"],
            "rejects_total": self._deltas["rejects"],
            "drops_total": self._deltas["drops"],
            "origins": origins,
            "dominant": self.dominant(),
        }


def assemble(by_node: dict[str, list[dict]]) -> dict:
    """Offline ledger view over merged journal streams (the shape
    ``SimCluster.journals()`` / ``observatory.load_journals`` produce).
    Events feed in the same sorted order the live collector uses, so a
    replayed report byte-matches the live one."""
    asm = LedgerAssembler()
    merged: list[dict] = []
    for name in sorted(by_node):
        merged.extend(e for e in by_node[name] if isinstance(e, dict))
    for ev in sorted(merged, key=_order_key):
        asm.ingest(ev)
    return asm.report()


def _selftest() -> int:
    """Fast determinism smoke for ``make check`` (the ledger-smoke
    target): a 4-node txpool sim takes a gossip burst from an injected
    client peer — half valid-signed txns, half invalid-signature junk —
    and two assembler passes over the journals (one through a JSON
    round-trip) must byte-match, with the client's rejects attributed."""
    from eges_tpu.core.types import Transaction  # analysis: allow-layer-violation(selftest builds signed txns; not a runtime dependency)
    from eges_tpu.sim.cluster import SimCluster  # analysis: allow-layer-violation(selftest drives a sim cluster; not a runtime dependency)
    import eges_tpu.consensus.messages as M  # analysis: allow-layer-violation(selftest injects gossip frames; not a runtime dependency)

    cluster = SimCluster(4, seed=0, txn_per_block=4, txpool=True)
    cluster.net.join("client", "10.0.0.99", 9999,
                     lambda d: None, lambda d: None)
    priv = bytes([7]) * 32
    good = [Transaction(nonce=i, gas_price=1, gas_limit=21000,
                        to=bytes(20), value=0).signed(priv)
            for i in range(3)]
    # r=0 fails signature_parts' range check -> pool reject, never a
    # device row — the cheap-reject path the ledger must attribute
    bad = [Transaction(nonce=100 + i, gas_price=1, gas_limit=21000,
                       to=bytes(20), value=0, v=27, r=0, s=1)
           for i in range(6)]

    fired = [False]

    def burst():
        fired[0] = True
        cluster.net.deliver_gossip("client", M.pack_gossip(
            M.GOSSIP_TXNS, M.TxnsMsg(txns=tuple(good + bad))))

    # virtual time races ahead of wall time: the sim can reach height 3
    # in well under 0.1 virtual seconds, so the burst must land almost
    # immediately and the stop condition must wait for it — otherwise
    # the run ends before the timer ever fires
    cluster.clock.call_later(0.01, burst)
    cluster.start()
    cluster.run(600.0, stop_condition=lambda: fired[0]
                and cluster.min_height() >= 3)
    for sn in cluster.nodes:
        sn.node.stop()
    by_node = cluster.journals()
    pass1 = json.dumps(assemble(by_node), sort_keys=True)
    pass2 = json.dumps(assemble(json.loads(json.dumps(by_node))),
                       sort_keys=True)
    rep = json.loads(pass1)
    if pass1 != pass2:
        # analysis: allow-print(CLI selftest verdict for make check)
        print("ledger selftest: FAIL (passes differ)")
        return 1
    if not rep["snapshots"] or not rep["origins"]:
        # analysis: allow-print(CLI selftest verdict for make check)
        print("ledger selftest: FAIL (no ingress_ledger events assembled)")
        return 1
    client = [o for o in rep["origins"] if o["origin"] == "peer:client"]
    if not client or client[0]["rejects"] <= 0:
        # analysis: allow-print(CLI selftest verdict for make check)
        print("ledger selftest: FAIL (client rejects not attributed)")
        return 1
    dom = rep.get("dominant") or {}
    # analysis: allow-print(CLI selftest verdict for make check)
    print(f"ledger selftest: OK ({rep['snapshots']} snapshots, "
          f"{len(rep['origins'])} origins, "
          f"dominant {dom.get('origin')})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-origin ingress cost attribution report")
    ap.add_argument("--replay", metavar="DIR",
                    help="assemble from a journal dump directory "
                         "(observatory --dump format)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="fast determinism smoke (make check)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.replay:
        ap.error("--replay DIR or --selftest required")
    from harness.observatory import load_journals, render_ledger  # analysis: allow-layer-violation(selftest renders via the observatory; not a runtime dependency)
    rep = assemble(load_journals(args.replay))
    if args.json:
        # analysis: allow-print(CLI report output)
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        # analysis: allow-print(CLI report output)
        print(render_ledger(rep))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
