"""Continuous sampling profiler: phase-attributed CPU flamegraphs.

Role: the "which *functions* burn the time" half of the observability
plane.  Anatomy (``harness/anatomy.py``) attributes wall-clock to
pipeline phases from journal events; the flight recorder attributes
window latency to verifier lifecycle phases; neither can say whether
``pool_admit`` cost is RLP decode, LRU probes, or lock wait.  This
module can: a background thread walks ``sys._current_frames()`` at a
configurable rate (default ~97 Hz — prime, so it never beats with
periodic 10 ms/100 ms work), folds each observed stack into the
standard flamegraph format (``root;child;leaf N``), and tags every
sample with

* the **thread role**, recovered from the thread-name vocabulary the
  lockset plane already standardizes (``verifier-scheduler`` /
  ``verifier-lane-*`` / ``verifier-hedge`` / ``collector-*`` / the
  asyncio service loop), and
* the **pipeline phase**, a per-thread tag maintained by the
  ``phase()`` context manager and — the bridge to the span tracer —
  set automatically for the duration of any ``Tracer.span`` whose name
  appears in :data:`SPAN_PHASES` (``txpool.ingest``/``txpool.admit``
  -> ``pool_admit``).  The phase vocabulary is the anatomy plane's
  ``PHASE_ORDER`` plus the verify-window interior
  (``verify_stage``/``verify_compute``/``verify_collect``) so profile
  reports and anatomy reports speak the same language.

Because this is a *wall-clock* sampler (every live thread is sampled,
running or blocked), lock wait and queue wait show up as samples whose
leaf frame is the wait primitive — exactly the attribution the
wire-speed-ingest work needs.

Determinism contract: like the flight recorder, sampled stacks are
real-time by nature and are NEVER journaled into determinism-checked
streams.  Sims that want profile data in the collector plane call
``SimCluster.enable_profiling()``, which journals aggregate
``profiler_report`` events into a dedicated ``"profiler"`` stream the
chaos determinism checks never enable.  Live-push and ``--replay``
collector folds therefore agree on sample *counts* by construction
(both consume the same journaled reports); the stacks themselves are
volatile by contract.

Knobs: ``EGES_PROFILE_HZ`` overrides the sampling rate; ``0`` disables
the plane entirely (``start()`` spawns no thread).  The sampler keeps
its own cost observable: ``stats()["overhead_pct"]`` is cumulative
frame-walk time over elapsed wall time, and the tier-1 overhead guard
pins it under 5%.

Reference: geth ships this plane as ``--pprof`` +
``debug_cpuProfile``/``debug_goTrace`` (node/api.go); the folded
artifact this module dumps next to ``journal.jsonl`` is the
flamegraph-ready equivalent.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager

ENV_HZ = "EGES_PROFILE_HZ"
DEFAULT_HZ = 97.0       # prime-ish: avoids aliasing with periodic work
MAX_DEPTH = 48          # frames kept per stack (root-most are dropped)
FOLD_CAP = 20_000       # distinct folded stacks before new ones drop
TOP_CAP = 40            # (func, phase) self-time rows per report
SNAP_RING = 64          # report snapshots kept for the thw_profile RPC

# The closed phase vocabulary: anatomy's PHASE_ORDER (harness/anatomy.py)
# plus the verify-window interior the flight recorder times.  Closed on
# purpose — an unknown tag raises, like journal.record on an unknown
# event type, so the vocabulary cannot drift silently.
PROFILE_PHASES = frozenset({
    # anatomy macro phases (block pipeline)
    "pool_admit", "pool_queue", "election", "ack_quorum",
    "seal_other", "publish", "propagation",
    # verify-window interior (scheduler fill/dispatch, device compute
    # or host divert, blocking collect)
    "verify_stage", "verify_compute", "verify_collect",
    # threads carrying no tag
    "untagged",
})

# Span-tracer bridge: a Tracer.span() with one of these names tags the
# thread for the span body (see utils/tracing.py).  Only *live* spans
# appear here — consensus phases are record_span()'d after the fact
# from virtual-clock durations and have no live extent to sample.
SPAN_PHASES = {
    "txpool.ingest": "pool_admit",
    "txpool.admit": "pool_admit",
    "txpool.admit_window": "pool_admit",
}

# Host-vs-verify split used by the bench gate: what share of
# pipeline-attributed samples is host-side ingest work rather than the
# verify window itself.
POOL_PHASES = ("pool_admit", "pool_queue")
VERIFY_PHASES = ("verify_stage", "verify_compute", "verify_collect")

# Thread-name prefix -> role, reusing the lockset plane's thread-entry
# vocabulary (scheduler dispatch/lane/hedge workers, collector accept +
# per-connection workers).  The asyncio service loop runs consensus,
# the telemetry pusher and RPC handlers; its executor threads serve
# blocking RPC work.
_ROLE_PREFIXES = (
    ("verifier-scheduler", "dispatch"),
    ("verifier-lane", "lane"),
    ("verifier-hedge", "hedge"),
    ("collector", "collector"),
    ("profiler-sampler", "profiler"),
    ("telemetry", "telemetry"),
    ("journal-writer", "telemetry"),
    ("asyncio", "rpc"),
    ("ThreadPoolExecutor", "rpc"),
    ("MainThread", "main"),
)


def role_of(thread_name: str) -> str:
    """Map a thread name onto the role vocabulary (``other`` if none)."""
    for prefix, role in _ROLE_PREFIXES:
        if thread_name.startswith(prefix):
            return role
    return "other"


def configured_hz() -> float:
    """The env-resolved sampling rate (``0`` disables the plane)."""
    raw = os.environ.get(ENV_HZ)
    if raw is None or not raw.strip():
        return DEFAULT_HZ
    try:
        hz = float(raw)
    # analysis: allow-swallow(a malformed EGES_PROFILE_HZ falls back to the default rate)
    except ValueError:
        return DEFAULT_HZ
    return max(0.0, hz)


# -- per-thread phase tags ------------------------------------------------
# Keyed by thread ident.  Single-key dict reads/writes are GIL-atomic,
# and each thread only ever touches its own key, so no lock is needed;
# the sampler reads other threads' entries with a plain .get(), which
# at worst observes the previous tag for one sample.
_PHASES: dict[int, str | None] = {}


def push_phase(name: str):
    """Tag the calling thread with ``name``; returns a token for
    :func:`pop_phase`.  Raises on a name outside the closed
    vocabulary."""
    if name not in PROFILE_PHASES:
        raise ValueError(f"unknown profile phase {name!r}")
    ident = threading.get_ident()
    prev = _PHASES.get(ident)
    _PHASES[ident] = name
    return (ident, prev)


def pop_phase(token) -> None:
    """Restore the tag saved by :func:`push_phase` (exception-safe)."""
    ident, prev = token
    if prev is None:
        _PHASES.pop(ident, None)
    else:
        _PHASES[ident] = prev


@contextmanager
def phase(name: str):
    """Tag the calling thread with pipeline phase ``name`` for the
    body.  Nests: the previous tag is restored on exit."""
    token = push_phase(name)
    try:
        yield
    finally:
        pop_phase(token)


def tag_span(span_name: str):
    """Span-tracer hook: tag the thread if ``span_name`` maps to a
    phase; returns a pop token or None.  Called by ``Tracer.span``."""
    ph = SPAN_PHASES.get(span_name)
    if ph is None:
        return None
    return push_phase(ph)


def host_cpu_share(by_phase: dict) -> float | None:
    """``host_cpu_share_of_verify_pct``: the share of pipeline-tagged
    samples spent in host-side ingest phases rather than the verify
    window — the before/after number for the wire-speed-ingest work.
    None when no pipeline-tagged samples exist."""
    pool = sum(int(by_phase.get(p, 0)) for p in POOL_PHASES)
    verify = sum(int(by_phase.get(p, 0)) for p in VERIFY_PHASES)
    total = pool + verify
    if total <= 0:
        return None
    return 100.0 * pool / total


# -- the sampler ----------------------------------------------------------

class SamplingProfiler:
    """Background-thread wall-clock sampler with folded-stack
    aggregation and per-role/per-phase attribution.

    ``clock`` is injectable for tests; it times the sampler's own
    bookkeeping (overhead estimate, snapshot cadence) and defaults to
    real time — sampling is wall-clock by nature even under a virtual
    sim clock.
    """

    def __init__(self, hz: float | None = None, *,
                 clock=time.monotonic, snapshots: int = SNAP_RING):
        self.hz = float(configured_hz() if hz is None else max(0.0, hz))
        self._clock = clock
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop_evt = threading.Event()
        # sampler-thread-private ident -> name cache (refreshed from
        # threading.enumerate() when an unknown ident appears)
        self._names: dict[int, str] = {}
        # guarded-by: _lock
        self._folded: dict[tuple, int] = {}
        # guarded-by: _lock
        self._by_phase: dict[str, int] = {}
        # guarded-by: _lock
        self._by_role: dict[str, int] = {}
        # guarded-by: _lock  ((phase, leaf func) -> self samples)
        self._self: dict[tuple[str, str], int] = {}
        # guarded-by: _lock
        self._samples = 0
        # guarded-by: _lock
        self._dropped = 0
        # guarded-by: _lock  (cumulative seconds spent walking frames)
        self._walk_s = 0.0
        # guarded-by: _lock
        self._started_at: float | None = None
        # guarded-by: _lock  (delta baseline for snap())
        self._base = {"samples": 0, "dropped": 0, "by_phase": {},
                      "by_role": {}, "self": {}}
        # guarded-by: _lock
        self._snaps: deque[dict] = deque(maxlen=max(1, snapshots))
        # guarded-by: _lock
        self._snap_seq = 0

    # -- lifecycle --------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> bool:
        """Spawn the sampler daemon.  ``hz <= 0`` (the
        ``EGES_PROFILE_HZ=0`` kill switch) spawns NOTHING and returns
        False — zero threads is the disabled contract the thread
        hygiene tests audit."""
        if self.hz <= 0.0:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            if self._started_at is None:
                self._started_at = self._clock()
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="profiler-sampler", daemon=True)
            self._thread.start()
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.gauge("profiler.hz").set(self.hz)
        return True

    def stop(self, timeout: float = 2.0) -> None:
        """Stop and JOIN the sampler (daemonhood alone is not enough —
        a still-walking sampler after close would race interpreter
        teardown).  Aggregates survive for a final report/dump."""
        with self._lock:
            t = self._thread
            self._thread = None
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout)

    # thread-entry:profiler-sampler
    def _loop(self) -> None:
        period = 1.0 / self.hz
        evt = self._stop_evt
        while not evt.is_set():
            t0 = self._clock()
            self._sample_once()
            walked = self._clock() - t0
            with self._lock:
                self._walk_s += walked
            evt.wait(max(0.001, period - walked))

    def _sample_once(self) -> None:
        try:
            frames = sys._current_frames()
        # analysis: allow-swallow(a failed frame walk loses one sample tick, counted as dropped)
        except Exception:
            with self._lock:
                self._dropped += 1
            return
        me = threading.get_ident()
        names = self._names
        if any(ident not in names for ident in frames):
            names = {t.ident: t.name for t in threading.enumerate()
                     if t.ident is not None}
            self._names = names
        local: list[tuple[str, str, tuple]] = []
        bad = 0
        for ident, frame in frames.items():
            if ident == me:
                continue  # never sample the sampler
            role = role_of(names.get(ident, "?"))
            ph = _PHASES.get(ident) or "untagged"
            stack: list[str] = []
            f = frame
            try:
                while f is not None and len(stack) < MAX_DEPTH:
                    code = f.f_code
                    qual = getattr(code, "co_qualname", code.co_name)
                    stack.append(
                        f"{f.f_globals.get('__name__', '?')}.{qual}")
                    f = f.f_back
            # analysis: allow-swallow(a frame mutating mid-walk loses one sample, counted as dropped)
            except Exception:
                bad += 1
                continue
            stack.reverse()  # root-first, the folded convention
            local.append((role, ph, tuple(stack)))
        del frames
        capped = 0
        with self._lock:
            self._dropped += bad
            for role, ph, stack in local:
                self._samples += 1
                self._by_phase[ph] = self._by_phase.get(ph, 0) + 1
                self._by_role[role] = self._by_role.get(role, 0) + 1
                leaf = (ph, stack[-1] if stack else "?")
                self._self[leaf] = self._self.get(leaf, 0) + 1
                key = (role, ph, stack)
                n = self._folded.get(key)
                if n is None and len(self._folded) >= FOLD_CAP:
                    # stack-shape explosion guard: counts above stay
                    # exact, only the new *shape* is dropped
                    self._dropped += 1
                    capped += 1
                    continue
                self._folded[key] = (n or 0) + 1
        # emitted after release: counters take the registry lock
        from eges_tpu.utils.metrics import DEFAULT as metrics
        if local:
            metrics.counter("profiler.samples").inc(len(local) - capped)
        if bad or capped:
            metrics.counter("profiler.dropped").inc(bad + capped)

    # -- reporting --------------------------------------------------------
    def _overhead_pct_locked(self) -> float:
        if self._started_at is None:
            return 0.0
        elapsed = max(1e-9, self._clock() - self._started_at)
        return round(100.0 * self._walk_s / elapsed, 3)

    def stats(self) -> dict:
        """The ``thw_health`` block: rate, volume, loss, self-cost."""
        with self._lock:
            return {
                "running": self.running,
                "hz": self.hz,
                "samples": self._samples,
                "dropped": self._dropped,
                "stacks": len(self._folded),
                "snapshots": len(self._snaps),
                "overhead_pct": self._overhead_pct_locked(),
            }

    def report(self, top_n: int = TOP_CAP) -> dict:
        """Cumulative attribution report: per-phase and per-role sample
        shares plus the top self-time (phase, function) rows."""
        with self._lock:
            samples = self._samples
            by_phase = dict(self._by_phase)
            by_role = dict(self._by_role)
            top = sorted(self._self.items(),
                         key=lambda kv: (-kv[1], kv[0]))[:top_n]
            overhead = self._overhead_pct_locked()
        return {
            "samples": samples,
            "hz": self.hz,
            "overhead_pct": overhead,
            "by_phase": {k: by_phase[k] for k in sorted(by_phase)},
            "by_role": {k: by_role[k] for k in sorted(by_role)},
            "top": [{"func": func, "phase": ph, "samples": n}
                    for (ph, func), n in top],
            "host_cpu_share_of_verify_pct": host_cpu_share(by_phase),
        }

    def snap(self) -> dict:
        """One delta report since the previous ``snap()`` — the unit
        the ``thw_profile`` RPC pages through and the sim profiling
        plane journals.  Appended to a bounded ring."""
        with self._lock:
            base = self._base
            d_phase = {k: v - base["by_phase"].get(k, 0)
                       for k, v in self._by_phase.items()
                       if v - base["by_phase"].get(k, 0) > 0}
            d_role = {k: v - base["by_role"].get(k, 0)
                      for k, v in self._by_role.items()
                      if v - base["by_role"].get(k, 0) > 0}
            d_self = {k: v - base["self"].get(k, 0)
                      for k, v in self._self.items()
                      if v - base["self"].get(k, 0) > 0}
            snap = {
                "seq": self._snap_seq,
                "hz": self.hz,
                "samples": self._samples - base["samples"],
                "dropped": self._dropped - base["dropped"],
                "by_phase": {k: d_phase[k] for k in sorted(d_phase)},
                "by_role": {k: d_role[k] for k in sorted(d_role)},
                "top": [[func, ph, n] for (ph, func), n in
                        sorted(d_self.items(),
                               key=lambda kv: (-kv[1], kv[0]))[:TOP_CAP]],
                "overhead_pct": self._overhead_pct_locked(),
            }
            self._snap_seq += 1
            self._base = {"samples": self._samples,
                          "dropped": self._dropped,
                          "by_phase": dict(self._by_phase),
                          "by_role": dict(self._by_role),
                          "self": dict(self._self)}
            self._snaps.append(snap)
            overhead = snap["overhead_pct"]
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.gauge("profiler.overhead_pct").set(overhead)
        return snap

    def snapshots(self, limit: int = 0) -> list[dict]:
        """Oldest-first report snapshots (RPC callers reverse for the
        newest-first wire contract, like the flight recorder)."""
        with self._lock:
            out = list(self._snaps)
        if limit and limit > 0:
            out = out[-limit:]
        return out

    def journal_snapshot(self, journal, force: bool = False):
        """Take a :meth:`snap` and journal it as one aggregate
        ``profiler_report`` event.  Skips empty deltas unless
        ``force`` (the final flush always records, so a profiled run
        is never invisible to the collector fold)."""
        snap = self.snap()
        if snap["samples"] <= 0 and not force:
            return None
        return journal.record(
            "profiler_report", hz=snap["hz"], samples=snap["samples"],
            dropped=snap["dropped"], by_phase=snap["by_phase"],
            by_role=snap["by_role"], top=snap["top"],
            overhead_pct=snap["overhead_pct"])

    def folded(self) -> list[str]:
        """The cumulative profile as folded-stack lines —
        ``role;phase;root;...;leaf N``, highest count first.  Feed
        straight to any flamegraph renderer."""
        with self._lock:
            items = list(self._folded.items())
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        return [";".join((role, ph) + stack) + f" {n}"
                for (role, ph, stack), n in items]

    def dump_folded(self, path: str, header: dict | None = None) -> int:
        """Write (overwrite — the profile is cumulative) the folded
        artifact; returns the number of stack lines.  ``header`` is
        embedded as a ``# eges-profile-v1 {...}`` comment so every
        profiling artifact in the tree carries the same provenance
        stamp (see harness/profutil.py)."""
        import json

        lines = self.folded()
        with open(path, "w", encoding="utf-8") as fh:
            if header is not None:
                fh.write("# eges-profile-v1 "
                         + json.dumps(header, sort_keys=True) + "\n")
            for line in lines:
                fh.write(line + "\n")
        return len(lines)


# The process-wide profiler the node service starts and the RPC/health
# surfaces read.  Constructed from the environment; NOT started here —
# lifecycle belongs to NodeService (and to sims via enable_profiling).
DEFAULT = SamplingProfiler()


# -- collector-plane assembler --------------------------------------------

class ProfileAssembler:
    """Incremental fold of journaled ``profiler_report`` events into
    one cluster-wide attribution report — the profiler analog of
    ``AnatomyAssembler``.  Pure function of the event stream, so the
    live-push and ``--replay`` collector paths agree byte-for-byte on
    everything derived from sample counts."""

    def __init__(self):
        self._nodes: dict[str, int] = {}
        self._samples = 0
        self._dropped = 0
        self._hz = 0.0
        self._by_phase: dict[str, int] = {}
        self._by_role: dict[str, int] = {}
        self._self: dict[tuple[str, str], int] = {}

    def ingest(self, ev: dict) -> None:
        if ev.get("type") != "profiler_report":
            return
        node = str(ev.get("node", "?"))
        self._nodes[node] = self._nodes.get(node, 0) + 1
        self._samples += int(ev.get("samples", 0) or 0)
        self._dropped += int(ev.get("dropped", 0) or 0)
        self._hz = max(self._hz, float(ev.get("hz", 0.0) or 0.0))
        for ph, n in (ev.get("by_phase") or {}).items():
            self._by_phase[ph] = self._by_phase.get(ph, 0) + int(n)
        for role, n in (ev.get("by_role") or {}).items():
            self._by_role[role] = self._by_role.get(role, 0) + int(n)
        for row in (ev.get("top") or []):
            func, ph, n = row[0], row[1], int(row[2])
            key = (str(ph), str(func))
            self._self[key] = self._self.get(key, 0) + n
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("profiler.reports").inc()

    def report(self, top_n: int = 20) -> dict:
        samples = self._samples
        top = sorted(self._self.items(),
                     key=lambda kv: (-kv[1], kv[0]))[:top_n]
        return {
            "reports": sum(self._nodes.values()),
            "nodes": {k: self._nodes[k] for k in sorted(self._nodes)},
            "samples": samples,
            "dropped": self._dropped,
            "hz": self._hz,
            "by_phase": {k: self._by_phase[k]
                         for k in sorted(self._by_phase)},
            "by_role": {k: self._by_role[k]
                        for k in sorted(self._by_role)},
            "top_self": [
                {"func": func, "phase": ph, "samples": n,
                 "pct": round(100.0 * n / samples, 2) if samples else 0.0}
                for (ph, func), n in top],
            "host_cpu_share_of_verify_pct": host_cpu_share(self._by_phase),
        }


def assemble(by_node: dict[str, list[dict]]) -> dict:
    """Batch-mode fold over per-stream event lists (the observatory
    ``--replay`` path); mirrors ``anatomy.assemble``."""
    from harness.collector import _order_key  # analysis: allow-layer-violation(selftest assembles sim journals; not a runtime dependency)

    asm = ProfileAssembler()
    merged: list[dict] = []
    for events in by_node.values():
        merged.extend(e for e in events
                      if e.get("type") == "profiler_report")
    merged.sort(key=_order_key)
    for ev in merged:
        asm.ingest(ev)
    return asm.report()


# -- selftest (the `make profile` smoke) ----------------------------------

def _selftest() -> int:
    """~2 s self-profiled sim smoke: run a 4-node sim with the
    profiling plane enabled, then assert a non-empty folded artifact
    and that the journaled reports reassemble to the sampler's exact
    totals."""
    import tempfile

    from eges_tpu.sim.cluster import SimCluster  # analysis: allow-layer-violation(selftest drives a sim cluster; not a runtime dependency)

    try:
        from harness.profutil import artifact_header  # analysis: allow-layer-violation(shared folded-artifact header; instrumentation hook)
    except ImportError:  # running outside the repo tree
        def artifact_header(**extra):
            return dict(extra)

    # analysis: allow-determinism(selftest wall-clock pacing; never journaled)
    t0 = time.monotonic()
    cluster = SimCluster(4, seed=0, txn_per_block=4, txpool=True)
    prof = cluster.enable_profiling(hz=397.0, interval_s=1.0)
    assert prof.running, "sampler failed to start"
    cluster.start()
    cluster.run(600.0, stop_condition=lambda: cluster.min_height() >= 3)
    assert cluster.min_height() >= 3, cluster.heights()
    # pad to a full 2 s of wall time under the sampler so the folded
    # artifact is never racing an unusually fast sim
    # analysis: allow-determinism(selftest wall-clock pacing; never journaled)
    while time.monotonic() - t0 < 2.0:
        time.sleep(0.02)
    for sn in cluster.nodes:
        sn.node.stop()
    cluster.stop_profiling()

    st = prof.stats()
    assert st["samples"] > 0, st
    path = os.path.join(tempfile.mkdtemp(prefix="eges-profile-"),
                        "profile.folded")
    n = prof.dump_folded(path, header=artifact_header(source="selftest"))
    assert n > 0, "folded artifact is empty"
    with open(path, encoding="utf-8") as fh:
        first = fh.readline()
    assert first.startswith("# eges-profile-v1 "), first

    # every sample the sampler counted is accounted for in the
    # journaled reports — the collector plane sees the same totals
    asm = ProfileAssembler()
    for ev in cluster.journals().get("profiler", []):
        asm.ingest(ev)
    rep = asm.report()
    assert rep["samples"] == st["samples"], (rep["samples"], st)
    phases = ",".join(sorted(rep["by_phase"]))
    # analysis: allow-print(CLI selftest verdict for make check)
    print(f"profiler selftest OK: samples={st['samples']} stacks={n} "
          f"overhead={st['overhead_pct']:.2f}% phases=[{phases}] "
          f"artifact={path}")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="continuous profiling plane utilities")
    ap.add_argument("--selftest", action="store_true",
                    help="run the 2s self-profiled sim smoke")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
