"""Shared request-limit clamping for the telemetry RPC surface.

``thw_journal`` / ``thw_traces`` / ``thw_flight`` each accept a caller
``limit`` and each used to hand-roll the same ``max(1, min(limit,
4096))`` clamp.  One helper keeps the bounds in one place (and one
test), so a future RPC can't silently ship a different ceiling.
"""

from __future__ import annotations

RPC_LIMIT_MIN = 1
RPC_LIMIT_MAX = 4096


def clamp_rpc_limit(limit) -> int:
    """Clamp a caller-supplied row limit into ``[RPC_LIMIT_MIN,
    RPC_LIMIT_MAX]``; non-numeric input falls back to the minimum."""
    try:
        n = int(limit)
    except (TypeError, ValueError):
        return RPC_LIMIT_MIN
    return max(RPC_LIMIT_MIN, min(n, RPC_LIMIT_MAX))
