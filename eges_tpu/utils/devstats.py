"""Device-efficiency observatory: goodput, roofline, HBM, traces.

Role: the device-side half of the observability plane.  PR 16's
sampling profiler (``utils/profiler.py``) answers "which host
*functions* burn the time"; this module answers the symmetric device
question — "how many of the rows we paid device time for were useful,
and how close is each lane to the measured ceiling".  Three surfaces:

* the **goodput ledger** — every recorded scheduler window already
  knows its rows, padded bucket, cache-served/deduped companions and
  hedge outcome (``crypto/scheduler.py`` ``_record_window`` + the
  flight recorder).  :class:`GoodputLedger` folds those into per-lane,
  per-bucket counters whose headline is ``goodput_ratio`` = useful
  rows / padded device rows, and — anchored to the captured TPU bench
  in ``BENCH_tpu_capture.json`` — ``fraction_of_roofline`` = achieved
  rows/s / the per-bucket ceiling parsed from the capture's scaling
  note.

* **HBM/memory telemetry** — :func:`sample_memory` reads per-device
  ``memory_stats()`` watermarks (bytes-in-use, peak, limit) and
  publishes them as ``devstats.mem_*;device=N`` gauges the
  ``RegistrySampler`` tick picks up automatically.  Backends without
  the API (CPU devices return ``None``) degrade to *absent*, never to
  fake zeros.

* **on-demand device traces** — :class:`DeviceTraceArmer` arms a
  ``jax.profiler`` capture for the next N recorded windows (the
  ``thw_device_trace`` RPC), landing a versioned ``device_trace.NNN``
  artifact next to ``profile.folded``.

Determinism contract: like the profiler plane, only aggregate *count*
deltas are journaled — one ``device_efficiency`` event per device per
tick, into a dedicated ``"devstats"`` stream in sims (the chaos
determinism checks never enable it).  Live-push and ``--replay``
collector folds therefore agree byte-for-byte on everything derived
from counts; memory watermarks ride the events as point-in-time
readings and are absent on host-only runs.  Nothing in this module
reads a wall clock — rates come from journaled event timestamps.

Reference: geth ships the memory half as ``debug_memStats`` /
``metrics`` module gauges; the reference repo's ``grep.py`` throughput
loop is the manual ancestor of the roofline fraction reported here.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from collections import deque

SNAP_RING = 64          # delta snapshots kept for the thw_devices RPC
ROOFLINE_FILE = "BENCH_tpu_capture.json"

# repo root, resolved relative to this file (eges_tpu/utils/ -> repo)
_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# The closed per-device counter vocabulary: journaled verbatim in each
# device_efficiency event and summed verbatim by the assembler, so the
# two ends cannot drift.
_COUNTERS = (
    "windows", "rows", "bucket_rows", "cache_rows", "dedup_rows",
    "diverted_windows", "diverted_rows",
    "hedge_windows", "hedge_wasted_windows", "hedge_wasted_rows",
)

# -- roofline anchoring ---------------------------------------------------

# the capture's free-text scaling row: "... 3.7k/s @256, 12.9k/s @1024
# (p50 79.8 ms), 33.5k/s @4096, 54.3k/s @16384"
_SCALING_RE = re.compile(r"(\d+(?:\.\d+)?)k/s\s*@(\d+)")
_ROOFLINE_CACHE: dict[str, dict] = {}


def load_roofline(path: str | None = None) -> dict:
    """Per-bucket device ceilings (rows/s) from the captured TPU bench.

    The scaling row is parsed out of the capture's free-text ``note``
    and the headline ``value``/``batch`` pair overrides its own
    (note-rounded) bucket.  Returns ``{"source", "ceilings"}`` where
    ``ceilings`` maps bucket -> rows/s; empty when the capture is
    missing or unparseable — fraction-of-roofline simply goes
    unreported rather than anchoring to a guess."""
    import json

    if path is None:
        path = os.path.join(_REPO, ROOFLINE_FILE)
    cached = _ROOFLINE_CACHE.get(path)
    if cached is not None:
        return cached
    ceilings: dict[int, float] = {}
    try:
        with open(path, encoding="utf-8") as fh:
            cap = json.load(fh)
        for num, bucket in _SCALING_RE.findall(str(cap.get("note", ""))):
            ceilings[int(bucket)] = float(num) * 1000.0
        batch, value = cap.get("batch"), cap.get("value")
        if isinstance(batch, int) and isinstance(value, (int, float)):
            # the headline number is exact; the note rounds it
            ceilings[batch] = float(value)
    # analysis: allow-swallow(a missing/unparseable capture just disables roofline anchoring)
    except Exception:
        ceilings = {}
    out = {"source": os.path.basename(path), "ceilings": ceilings}
    _ROOFLINE_CACHE[path] = out
    return out


def roofline_ceiling(ceilings: dict[int, float],
                     bucket: int) -> float | None:
    """The rows/s ceiling for one bucket: exact when captured,
    log2-interpolated between captured buckets (throughput scales with
    log batch on the measured curve), linearly scaled below the
    smallest capture, clamped at the largest (the chip does not get
    faster past its peak batch)."""
    import math

    if not ceilings or bucket <= 0:
        return None
    exact = ceilings.get(bucket)
    if exact is not None:
        return exact
    pts = sorted(ceilings.items())
    b0, c0 = pts[0]
    if bucket < b0:
        return c0 * bucket / b0
    bn, cn = pts[-1]
    if bucket > bn:
        return cn
    for (lo, clo), (hi, chi) in zip(pts, pts[1:]):
        if lo < bucket < hi:
            t = ((math.log2(bucket) - math.log2(lo))
                 / (math.log2(hi) - math.log2(lo)))
            return clo + t * (chi - clo)
    return None


# -- on-demand device traces ----------------------------------------------

class DeviceTraceArmer:
    """Arms a ``jax.profiler`` device trace for the next N *recorded*
    windows.  ``step()`` is called once per recorded scheduler window
    (via :meth:`GoodputLedger.observe_window`); the first armed window
    starts the capture, the last one stops it, and the artifact lands
    as a versioned ``device_trace.NNN`` directory next to
    ``profile.folded`` (``dir`` is set by ``NodeService.start`` to the
    datadir; a tempdir otherwise).  Without jax the armer degrades to
    an ``error:*`` state instead of tracing — arming is always safe."""

    def __init__(self):
        self._lock = threading.Lock()
        # artifact directory; set by the node service, else tempdir
        self.dir: str | None = None
        # guarded-by: _lock
        self._remaining = 0
        # guarded-by: _lock
        self._active = False
        # guarded-by: _lock
        self._captures = 0
        # guarded-by: _lock  (idle | armed | tracing | captured | error:*)
        self._state = "idle"
        # guarded-by: _lock
        self._path: str | None = None

    def arm(self, windows: int, outdir: str | None = None) -> dict:
        """Arm a capture spanning the next ``windows`` recorded
        windows (already clamped by the RPC layer); returns status."""
        windows = max(1, int(windows))
        with self._lock:
            if outdir:
                self.dir = str(outdir)
            self._remaining = windows
            if not self._active:
                self._state = "armed"
        return self.status()

    def disarm(self) -> dict:
        """Cancel the armed window count; an in-flight capture stops
        (and counts as captured — the artifact is real)."""
        captured = False
        with self._lock:
            self._remaining = 0
            if self._active:
                captured = self._stop_locked()
            else:
                self._state = "idle"
        if captured:
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("devstats.trace_captures").inc()
        return self.status()

    def step(self) -> None:
        """One recorded window elapsed — start/advance/stop the
        capture as armed.  Cheap no-op (one lock round) when idle, so
        it sits on the window-recording path safely."""
        captured = False
        with self._lock:
            if self._remaining <= 0 and not self._active:
                return
            if not self._active and self._remaining > 0:
                self._start_locked()
            if self._active:
                self._remaining -= 1
                if self._remaining <= 0:
                    captured = self._stop_locked()
        if captured:
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("devstats.trace_captures").inc()

    def _start_locked(self) -> None:
        # lazy import: the hot path never pays for jax.profiler unless
        # a capture is actually armed
        try:
            from jax import profiler as jax_profiler
            import tempfile

            base = self.dir or tempfile.gettempdir()
            path = os.path.join(base,
                                "device_trace.%03d" % self._captures)
            os.makedirs(path, exist_ok=True)
            jax_profiler.start_trace(path)
        # analysis: allow-swallow(backends without jax.profiler report an error state instead of tracing)
        except Exception as exc:
            self._remaining = 0
            self._state = f"error:{type(exc).__name__}"
            return
        self._active = True
        self._path = path
        self._state = "tracing"

    def _stop_locked(self) -> bool:
        try:
            from jax import profiler as jax_profiler

            jax_profiler.stop_trace()
        # analysis: allow-swallow(a failed trace stop leaves the error visible in the armer state)
        except Exception as exc:
            self._active = False
            self._state = f"error:{type(exc).__name__}"
            return False
        self._active = False
        self._captures += 1
        self._state = "captured"
        return True

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "armed_windows": self._remaining,
                "active": self._active,
                "captures": self._captures,
                "path": self._path,
                "dir": self.dir,
            }


# -- the goodput ledger ---------------------------------------------------

class GoodputLedger:
    """Per-device window/row accounting fed by the scheduler's
    ``_record_window`` tail.  Counters only — no wall clock, no stacks
    — so the journaled deltas sit inside the determinism contract the
    collector fold relies on."""

    def __init__(self, *, snapshots: int = SNAP_RING):
        self._lock = threading.Lock()
        # guarded-by: _lock  (device -> cumulative counter dict)
        self._dev: dict[int, dict] = {}
        # guarded-by: _lock  ((device, bucket) -> [windows, rows, bucket_rows])
        self._buckets: dict[tuple[int, int], list[int]] = {}
        # guarded-by: _lock  (delta baselines for snap())
        self._base_dev: dict[int, dict] = {}
        # guarded-by: _lock
        self._base_buckets: dict[tuple[int, int], list[int]] = {}
        # guarded-by: _lock  (latest memory_stats watermarks per device)
        self._mem: dict[int, dict] = {}
        # guarded-by: _lock
        self._snaps: deque[dict] = deque(maxlen=max(1, snapshots))
        # guarded-by: _lock
        self._snap_seq = 0
        self.trace = DeviceTraceArmer()

    def _dev_locked(self, device: int) -> dict:
        d = self._dev.get(device)
        if d is None:
            d = {k: 0 for k in _COUNTERS}
            self._dev[device] = d
        return d

    # -- ingestion (scheduler hooks) --------------------------------------
    def observe_window(self, device: int, rows: int, bucket: int, *,
                       cache_rows: int = 0, dedup_rows: int = 0,
                       diverted: bool = False,
                       hedged: bool = False) -> None:  # hot-path-entry
        """One recorded (winner) scheduler window.  Host-served windows
        (singletons and breaker/straggler diverts) never padded a
        device bucket, so their rows stay out of the goodput
        denominator and land in the ``diverted_rows`` rescue column
        instead."""
        device, rows, bucket = int(device), int(rows), int(bucket)
        with self._lock:
            d = self._dev_locked(device)
            d["windows"] += 1
            d["cache_rows"] += int(cache_rows)
            d["dedup_rows"] += int(dedup_rows)
            if hedged:
                d["hedge_windows"] += 1
            if diverted:
                d["diverted_windows"] += 1
                d["diverted_rows"] += rows
            else:
                d["rows"] += rows
                d["bucket_rows"] += bucket
                bk = self._buckets.get((device, bucket))
                if bk is None:
                    bk = [0, 0, 0]
                    self._buckets[(device, bucket)] = bk
                bk[0] += 1
                bk[1] += rows
                bk[2] += bucket
        self.trace.step()

    def observe_hedge_waste(self, device: int, rows: int,
                            bucket: int) -> None:
        """A hedge LOSER ran a full padded window the winner made
        redundant — pure device waste, billed at the padded size."""
        with self._lock:
            d = self._dev_locked(int(device))
            d["hedge_wasted_windows"] += 1
            d["hedge_wasted_rows"] += int(bucket)

    def note_memory(self, by_device: dict) -> None:
        """Stash the latest :func:`sample_memory` watermarks so the
        next journaled delta carries them."""
        with self._lock:
            for dev, rec in by_device.items():
                self._mem[int(dev)] = dict(rec)

    # -- snapshots --------------------------------------------------------
    def _rebase_locked(self) -> None:
        self._base_dev = {d: dict(v) for d, v in self._dev.items()}
        self._base_buckets = {k: list(v)
                              for k, v in self._buckets.items()}

    def rebase(self) -> None:
        """Reset the delta baseline to the current totals WITHOUT
        recording a snapshot — called when a sim or the node service
        enables the plane, so windows recorded by earlier runs in the
        same process never leak into the first tick (the
        ``RegistrySampler`` baseline-at-attach discipline)."""
        with self._lock:
            self._rebase_locked()

    def snap(self) -> dict:
        """One delta report since the previous ``snap()`` — per-device
        counters plus their per-bucket split, the unit the
        ``thw_devices`` RPC pages through and the sim devstats plane
        journals.  Appended to a bounded ring."""
        with self._lock:
            devices: dict[int, dict] = {}
            for dev in sorted(self._dev):
                cur = self._dev[dev]
                base = self._base_dev.get(dev, {})
                delta = {k: cur[k] - base.get(k, 0) for k in _COUNTERS}
                if not any(delta.values()):
                    continue
                buckets: dict[str, list[int]] = {}
                for (bdev, bucket), bk in self._buckets.items():
                    if bdev != dev:
                        continue
                    bb = self._base_buckets.get((bdev, bucket),
                                                (0, 0, 0))
                    row = [bk[0] - bb[0], bk[1] - bb[1], bk[2] - bb[2]]
                    if any(row):
                        buckets[str(bucket)] = row
                delta["buckets"] = {k: buckets[k]
                                    for k in sorted(buckets, key=int)}
                mem = self._mem.get(dev)
                if mem:
                    delta["mem"] = dict(mem)
                devices[dev] = delta
            snap = {
                "seq": self._snap_seq,
                "devices": {str(d): devices[d] for d in sorted(devices)},
            }
            self._snap_seq += 1
            self._rebase_locked()
            self._snaps.append(snap)
            ratios = {d: (v["rows"], v["bucket_rows"])
                      for d, v in devices.items() if v["bucket_rows"]}
        # emitted after release: gauges take the registry lock
        from eges_tpu.utils.metrics import DEFAULT as metrics
        for dev, (r, br) in ratios.items():
            metrics.gauge(f"devstats.goodput_ratio;device={dev}") \
                .set(round(r / br, 4))
        return snap

    def snapshots(self, limit: int = 0) -> list[dict]:
        """Oldest-first delta snapshots (RPC callers reverse for the
        newest-first wire contract, like ``thw_profile``)."""
        with self._lock:
            out = list(self._snaps)
        if limit and limit > 0:
            out = out[-limit:]
        return out

    def journal_snapshot(self, journal) -> int:
        """Take a :meth:`snap` and journal one ``device_efficiency``
        event PER device with a non-empty delta, in device order (so
        event order is deterministic).  Returns the number of events
        recorded; an all-idle tick records nothing — unlike
        ``profiler_report`` there is no meaningful empty payload."""
        snap = self.snap()
        n = 0
        for dev_str, d in snap["devices"].items():
            attrs = {k: d[k] for k in _COUNTERS}
            attrs["device"] = int(dev_str)
            attrs["pad_rows"] = d["bucket_rows"] - d["rows"]
            attrs["buckets"] = d["buckets"]
            mem = d.get("mem")
            if mem:
                # point-in-time HBM watermarks ride the count event but
                # are volatile by nature; absent on backends without
                # memory_stats() (the CPU fallback stays green)
                attrs["mem"] = mem
            journal.record("device_efficiency", **attrs)
            n += 1
        return n

    def stats(self) -> dict:
        """The ``thw_health`` block: cumulative volume, goodput, trace
        armer state."""
        with self._lock:
            windows = sum(d["windows"] for d in self._dev.values())
            rows = sum(d["rows"] for d in self._dev.values())
            bucket_rows = sum(d["bucket_rows"]
                              for d in self._dev.values())
            snaps = len(self._snaps)
            mem_devices = len(self._mem)
            ndev = len(self._dev)
        return {
            "devices": ndev,
            "windows": windows,
            "rows": rows,
            "bucket_rows": bucket_rows,
            "goodput_ratio": (round(rows / bucket_rows, 4)
                              if bucket_rows else None),
            "snapshots": snaps,
            "mem_devices": mem_devices,
            "trace": self.trace.status(),
        }


# The process-wide ledger the scheduler feeds and the RPC/health
# surfaces read.  NOT baselined here — sims and the node service call
# rebase() when they enable the plane.
DEFAULT = GoodputLedger()


# -- HBM/memory telemetry -------------------------------------------------

def sample_memory(ledger: GoodputLedger | None = None,
                  devices=None) -> dict:
    """Read per-device ``memory_stats()`` watermarks and publish them
    as ``devstats.mem_*;device=N`` gauges (the ``RegistrySampler``
    tick then carries them in every ``telemetry_sample``).  Degrades
    to ``{}`` — publishing nothing — when jax was never imported, has
    no devices, or the backend lacks the API (CPU devices return
    ``None``): the host fallback stays green by being absent, not by
    faking zeros.  Never imports jax itself: if nothing else in the
    process paid the import cost, there is no device to meter."""
    led = DEFAULT if ledger is None else ledger
    if devices is None:
        jx = sys.modules.get("jax")
        if jx is None:
            return {}
        try:
            devices = jx.devices()
        # analysis: allow-swallow(an uninitializable backend means no devices to meter)
        except Exception:
            return {}
    out: dict[int, dict] = {}
    from eges_tpu.utils.metrics import DEFAULT as metrics
    for i, dev in enumerate(devices):
        fn = getattr(dev, "memory_stats", None)
        if not callable(fn):
            continue
        try:
            ms = fn()
        # analysis: allow-swallow(a backend erroring on memory_stats simply has no watermarks)
        except Exception:
            continue
        if not isinstance(ms, dict):
            continue  # CPU backends return None: no watermarks
        rec: dict[str, int] = {}
        val = ms.get("bytes_in_use")
        if val is not None:
            rec["bytes_in_use"] = int(val)
            metrics.gauge(f"devstats.mem_bytes_in_use;device={i}") \
                .set(int(val))
        val = ms.get("peak_bytes_in_use")
        if val is not None:
            rec["peak_bytes"] = int(val)
            metrics.gauge(f"devstats.mem_peak_bytes;device={i}") \
                .set(int(val))
        val = ms.get("bytes_limit")
        if val is not None:
            rec["limit_bytes"] = int(val)
            metrics.gauge(f"devstats.mem_limit_bytes;device={i}") \
                .set(int(val))
        if rec:
            out[i] = rec
    if out:
        led.note_memory(out)
    return out


# -- collector-plane assembler --------------------------------------------

class DevstatsAssembler:
    """Incremental fold of journaled ``device_efficiency`` events into
    one cluster-wide device-efficiency report — the devstats analog of
    ``ProfileAssembler``.  Pure function of the event stream, so the
    live-push and ``--replay`` collector paths agree byte-for-byte on
    everything derived from counts."""

    def __init__(self):
        self._nodes: dict[str, int] = {}
        self._dev: dict[int, dict] = {}
        self._buckets: dict[tuple[int, int], list[int]] = {}
        self._mem: dict[int, dict] = {}
        self._first_ts: dict[int, float] = {}
        self._last_ts: dict[int, float] = {}

    def ingest(self, ev: dict) -> None:
        if ev.get("type") != "device_efficiency":
            return
        node = str(ev.get("node", "?"))
        self._nodes[node] = self._nodes.get(node, 0) + 1
        dev = int(ev.get("device", 0) or 0)
        d = self._dev.get(dev)
        if d is None:
            d = {k: 0 for k in _COUNTERS}
            self._dev[dev] = d
        for k in _COUNTERS:
            d[k] += int(ev.get(k, 0) or 0)
        for bucket_s, row in (ev.get("buckets") or {}).items():
            key = (dev, int(bucket_s))
            bk = self._buckets.get(key)
            if bk is None:
                bk = [0, 0, 0]
                self._buckets[key] = bk
            bk[0] += int(row[0])
            bk[1] += int(row[1])
            bk[2] += int(row[2])
        mem = ev.get("mem")
        if isinstance(mem, dict):
            # last write wins — the collector feeds events in
            # (ts, node, seq) order, so this is the newest watermark
            self._mem[dev] = dict(mem)
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            self._first_ts.setdefault(dev, float(ts))
            self._last_ts[dev] = float(ts)
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("devstats.reports").inc()

    def report(self) -> dict:
        roof = load_roofline()
        ceilings = roof["ceilings"]
        devices: dict[str, dict] = {}
        for dev in sorted(self._dev):
            d = self._dev[dev]
            span = (self._last_ts.get(dev, 0.0)
                    - self._first_ts.get(dev, 0.0))
            rows_per_s = (round(d["rows"] / span, 3)
                          if span > 0 and d["rows"] else None)
            buckets: dict[str, dict] = {}
            for (bdev, bucket) in sorted(self._buckets):
                if bdev != dev:
                    continue
                w, r, br = self._buckets[(bdev, bucket)]
                ceil = roofline_ceiling(ceilings, bucket)
                buckets[str(bucket)] = {
                    "windows": w, "rows": r, "bucket_rows": br,
                    "goodput_ratio": round(r / br, 4) if br else None,
                    "ceiling_rows_per_s": (round(ceil, 1)
                                           if ceil else None),
                }
            entry = {k: d[k] for k in _COUNTERS}
            entry["pad_rows"] = d["bucket_rows"] - d["rows"]
            entry["goodput_ratio"] = (round(d["rows"] / d["bucket_rows"],
                                            4)
                                      if d["bucket_rows"] else None)
            entry["rows_per_s"] = rows_per_s
            # achieved rows/s against the ceiling of the device's
            # row-weighted mean bucket — the single-number headline the
            # per-bucket table decomposes
            frac = None
            dev_windows = d["windows"] - d["diverted_windows"]
            if rows_per_s and dev_windows > 0 and d["bucket_rows"]:
                ceil = roofline_ceiling(
                    ceilings, round(d["bucket_rows"] / dev_windows))
                if ceil:
                    frac = round(rows_per_s / ceil, 4)
            entry["fraction_of_roofline"] = frac
            entry["buckets"] = buckets
            if dev in self._mem:
                entry["mem"] = self._mem[dev]
            devices[str(dev)] = entry
        tot = {k: sum(d[k] for d in self._dev.values())
               for k in _COUNTERS}
        tot["pad_rows"] = tot["bucket_rows"] - tot["rows"]
        tot["goodput_ratio"] = (round(tot["rows"] / tot["bucket_rows"], 4)
                                if tot["bucket_rows"] else None)
        return {
            "reports": sum(self._nodes.values()),
            "nodes": {k: self._nodes[k] for k in sorted(self._nodes)},
            "roofline_source": roof["source"] if ceilings else None,
            "totals": tot,
            # where potential device rows went instead of useful work:
            # padding burned, cache served free, dedup merged, hedge
            # losers burned, host rescues
            "waste": {
                "pad_rows": tot["pad_rows"],
                "cache_rows": tot["cache_rows"],
                "dedup_rows": tot["dedup_rows"],
                "hedge_wasted_rows": tot["hedge_wasted_rows"],
                "diverted_rows": tot["diverted_rows"],
            },
            "devices": devices,
        }


def assemble(by_node: dict[str, list[dict]]) -> dict:
    """Batch-mode fold over per-stream event lists (the observatory
    ``--replay`` path); mirrors ``profiler.assemble``."""
    from harness.collector import _order_key  # analysis: allow-layer-violation(selftest assembles sim journals; not a runtime dependency)

    asm = DevstatsAssembler()
    merged: list[dict] = []
    for events in by_node.values():
        merged.extend(e for e in events
                      if e.get("type") == "device_efficiency")
    merged.sort(key=_order_key)
    for ev in merged:
        asm.ingest(ev)
    return asm.report()


# -- selftest (the `make devstats` smoke) ---------------------------------

def _selftest() -> int:
    """Sim smoke: run a 4-node sim on a 2-lane JAX-free host mesh with
    the devstats plane enabled, then assert the journaled
    ``device_efficiency`` events reassemble into a consistent goodput
    report anchored to the captured roofline."""
    from eges_tpu.sim.cluster import SimCluster  # analysis: allow-layer-violation(selftest drives a sim cluster; not a runtime dependency)

    roof = load_roofline()
    assert roof["ceilings"], "roofline scaling row failed to parse"
    assert roof["ceilings"][16384] == 54296.9, roof["ceilings"]
    assert roof["ceilings"][256] == 3700.0, roof["ceilings"]
    mid = roofline_ceiling(roof["ceilings"], 2048)
    lo, hi = roof["ceilings"][1024], roof["ceilings"][4096]
    assert lo < mid < hi, (lo, mid, hi)

    cluster = SimCluster(4, seed=0, txn_per_block=4, txpool=True,
                         mesh_devices=2)
    cluster.enable_devstats(interval_s=1.0)
    cluster.start()
    cluster.run(600.0, stop_condition=lambda: cluster.min_height() >= 3)
    assert cluster.min_height() >= 3, cluster.heights()
    for sn in cluster.nodes:
        sn.node.stop()
    cluster.stop_devstats()

    events = cluster.journals().get("devstats", [])
    assert events, "no device_efficiency events journaled"
    rep = assemble({"devstats": events})
    tot = rep["totals"]
    assert tot["windows"] > 0, tot
    assert tot["rows"] > 0, tot
    assert tot["bucket_rows"] >= tot["rows"], tot
    gp = tot["goodput_ratio"]
    assert gp is not None and 0.0 < gp <= 1.0, tot
    # the per-bucket split sums back to the device totals
    for entry in rep["devices"].values():
        assert sum(b["rows"] for b in entry["buckets"].values()) \
            == entry["rows"], entry
        assert sum(b["bucket_rows"] for b in entry["buckets"].values()) \
            == entry["bucket_rows"], entry
    # read the CANONICAL module's ledger: under ``python -m`` this file
    # is also loaded as ``__main__``, and the scheduler feeds the
    # ``eges_tpu.utils.devstats`` instance, not this shadow copy
    from eges_tpu.utils import devstats as _canon
    st = _canon.DEFAULT.stats()
    assert st["windows"] >= tot["windows"], (st, tot)
    # analysis: allow-print(CLI selftest verdict for make check)
    print(f"devstats selftest OK: windows={tot['windows']} "
          f"rows={tot['rows']} goodput={gp} "
          f"devices={sorted(rep['devices'])} "
          f"roofline={rep['roofline_source']}")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="device-efficiency observatory utilities")
    ap.add_argument("--selftest", action="store_true",
                    help="run the simulated 2-lane mesh smoke")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
