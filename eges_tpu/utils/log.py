"""Structured logging with the Geec extensions.

The reference adds two log levels to log15 — ``LvlGeec`` and ``Gdbug``
(ref: log/logger.go:23,85-88, log/root.go:63-68) — and emits its
``--breakdown`` phase timings as log lines harvested by ``grep.py``
(SURVEY §5: "observability is logging-first").  Same model here: stdlib
logging with two custom levels between the standard ones, key=value
formatting, and a helper the harness's grep-style assertions parse.
"""

from __future__ import annotations

import logging
import sys

# Between WARNING(30) and INFO(20), like the reference's ordering
GEEC = 25
GDBUG = 15

logging.addLevelName(GEEC, "GEEC")
logging.addLevelName(GDBUG, "GDBUG")


def _fmt_kv(kwargs: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in kwargs.items())


class GeecLogger(logging.LoggerAdapter):
    """``log.geec("Elected", blk=5, version=0)`` -> ``GEEC Elected blk=5 ...``"""

    def geec(self, msg: str, **kw) -> None:
        self.logger.log(GEEC, "%s %s", msg, _fmt_kv(kw))

    def gdbug(self, msg: str, **kw) -> None:
        self.logger.log(GDBUG, "%s %s", msg, _fmt_kv(kw))

    def info(self, msg: str, **kw) -> None:  # type: ignore[override]
        self.logger.info("%s %s", msg, _fmt_kv(kw))

    def warn(self, msg: str, **kw) -> None:
        self.logger.warning("%s %s", msg, _fmt_kv(kw))

    def breakdown(self, phase: str, dt: float, **kw) -> None:
        """Phase timing lines (ref: '[Breakdown 1] Election time',
        consensus/geec/geec.go:313-317).  Logged at GEEC level: these
        lines exist to be harvested from logs (grep.py workflow), so the
        default verbosity must not filter them."""
        self.logger.log(GEEC, "[Breakdown] %s time=%.6fs %s", phase, dt,
                        _fmt_kv(kw))


def get_logger(name: str, verbosity: int = 3,
               stream=None) -> GeecLogger:
    """Verbosity mapping follows geth --verbosity: 1=error..5=trace.

    Idempotent: repeated calls re-level the existing handler instead of
    keeping the first level forever, and a different ``stream`` retargets
    that handler rather than stacking a second one (which used to
    double every log line).
    """
    level = {1: logging.ERROR, 2: logging.WARNING, 3: GEEC,
             4: logging.DEBUG, 5: 1}.get(verbosity, GEEC)
    logger = logging.getLogger(name)
    logger.setLevel(level)
    ours = [h for h in logger.handlers if getattr(h, "_geec", False)]
    if not ours:
        h = logging.StreamHandler(stream or sys.stdout)
        h._geec = True
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)-5s %(name)s %(message)s",
            datefmt="%H:%M:%S"))
        logger.addHandler(h)
        ours = [h]
    for h in ours:
        h.setLevel(level)
        if stream is not None and h.stream is not stream:
            h.setStream(stream)
    return GeecLogger(logger, {})
