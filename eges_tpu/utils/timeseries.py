"""Bounded time-series sampling over the metrics registry.

The registry (``utils/metrics.py``) holds *aggregates* — a counter's
lifetime total, a histogram's reservoir.  This module turns those
aggregates into a *stream*: :class:`RegistrySampler` periodically
snapshots a :class:`~eges_tpu.utils.metrics.Registry` on an injectable
clock and emits one flat sample payload per step — counters and meter
counts as DELTAS since the previous step, numeric gauges and histogram
percentiles as point-in-time values — while retaining the last N steps
per metric family in a bounded ring (:class:`SeriesStore`).

The sample payload is what rides the telemetry push channel as a
``telemetry_sample`` journal event (see ``harness/collector.py``):
deltas make per-step payloads small and make cluster aggregation a
plain sum, and the injectable clock keeps sim-driven sampling on
virtual time so chaos runs stay byte-deterministic.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from eges_tpu.utils.metrics import (Counter, DEFAULT, Gauge, Histogram,
                                    Meter, Registry, Timer)


class Series:
    """One bounded (ts, value) ring for a single metric name."""

    __slots__ = ("name", "_points")

    def __init__(self, name: str, capacity: int = 512):
        self.name = name
        self._points: deque[tuple[float, float]] = deque(maxlen=capacity)

    def add(self, ts: float, value: float) -> None:
        self._points.append((ts, value))

    def points(self) -> list[tuple[float, float]]:
        return list(self._points)

    def latest(self) -> tuple[float, float] | None:
        return self._points[-1] if self._points else None

    def __len__(self) -> int:
        return len(self._points)


class SeriesStore:
    """Named bounded series, deterministic iteration order."""

    def __init__(self, capacity: int = 512):
        self._capacity = capacity
        self._series: dict[str, Series] = {}
        self._lock = threading.Lock()

    def add(self, name: str, ts: float, value: float) -> None:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = Series(name, self._capacity)
                self._series[name] = s
        s.add(ts, value)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> Series | None:
        with self._lock:
            return self._series.get(name)

    def as_dict(self) -> dict[str, list[list[float]]]:
        """``{name: [[ts, value], ...]}`` with sorted names — the
        JSON-stable shape the collector's report embeds."""
        with self._lock:
            items = sorted(self._series.items())
        return {name: [[ts, v] for ts, v in s.points()]
                for name, s in items}


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class RegistrySampler:
    """Periodic registry snapshotter: deltas for monotone aggregates,
    points for gauges/percentiles, last N steps per family retained.

    ``clock`` is injected (sim clusters pass virtual time); the default
    is only for standalone/real-node use.  ``sample()`` returns the flat
    payload for this step and folds every value into the bounded
    :class:`SeriesStore` reachable as :attr:`store`.
    """

    def __init__(self, registry: Registry | None = None, *,
                 clock=time.monotonic, capacity: int = 512):
        self._registry = registry if registry is not None else DEFAULT
        self._clock = clock
        self.store = SeriesStore(capacity)
        self.steps = 0
        # previous monotone readings, flat name -> value, for deltas —
        # baselined NOW so the first sample reports deltas since the
        # sampler was created, not registry lifetime totals (the
        # registry is process-global: without the baseline, back-to-back
        # sim runs in one process would leak the first run's counts into
        # the second run's first sample and break byte-determinism)
        self._prev: dict[str, float] = {}
        self._lock = threading.Lock()
        with self._registry._lock:
            items = list(self._registry._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                self._prev[name] = m.value
            elif isinstance(m, (Meter, Timer, Histogram)):
                self._prev[name] = m.count

    # -- one step -------------------------------------------------------
    def sample(self) -> dict:
        """Take one step: returns the flat payload for this step.

        Counters and Meter/Timer/Histogram counts appear as deltas
        (omitted when zero — an absent key IS a zero delta); numeric
        gauges always appear as points; histogram percentiles appear as
        points whenever the distribution saw new observations.
        """
        now = float(self._clock())
        metrics = self._registry
        # the sampler's own heartbeat: a delta of exactly 1 every step,
        # so an otherwise-idle node still produces a non-empty payload
        metrics.counter("telemetry.samples").inc()
        with self._registry._lock:
            items = sorted(self._registry._metrics.items())
        payload: dict[str, object] = {}
        with self._lock:
            self.steps += 1
            for name, m in items:
                if isinstance(m, Counter):
                    d = m.value - self._prev.get(name, 0)
                    self._prev[name] = m.value
                    if d:
                        payload[name] = d
                        self.store.add(name, now, d)
                elif isinstance(m, Gauge):
                    if _numeric(m.value):
                        payload[name] = m.value
                        self.store.add(name, now, float(m.value))
                elif isinstance(m, Meter):
                    d = m.count - self._prev.get(name, 0)
                    self._prev[name] = m.count
                    if d:
                        payload[name] = d
                        self.store.add(name, now, d)
                elif isinstance(m, Timer):
                    d = m.count - self._prev.get(name, 0)
                    self._prev[name] = m.count
                    if d:
                        payload[name] = {"count": d,
                                         "mean_s": round(m.mean, 6)}
                        self.store.add(name + ".count", now, d)
                        self.store.add(name + ".mean_s", now,
                                       round(m.mean, 6))
                elif isinstance(m, Histogram):
                    d = m.count - self._prev.get(name, 0)
                    self._prev[name] = m.count
                    if d:
                        ps = m.percentiles()
                        payload[name] = {"count": d,
                                         "p50": round(ps[50.0], 6),
                                         "p95": round(ps[95.0], 6),
                                         "p99": round(ps[99.0], 6)}
                        self.store.add(name + ".count", now, d)
                        for q in (50, 95, 99):
                            self.store.add("%s.p%d" % (name, q), now,
                                           round(ps[float(q)], 6))
        return payload


def fold_payload(store: SeriesStore, ts: float, payload: dict) -> None:
    """Fold one ``telemetry_sample`` payload (as produced by
    :meth:`RegistrySampler.sample`) into a :class:`SeriesStore` — the
    collector-side mirror of the sampler's own store, so a replay from
    journal events reconstructs identical series."""
    for name in sorted(payload):
        v = payload[name]
        if _numeric(v):
            store.add(name, ts, float(v))
        elif isinstance(v, dict):
            for sub in sorted(v):
                if _numeric(v[sub]):
                    store.add("%s.%s" % (name, sub), ts, float(v[sub]))
