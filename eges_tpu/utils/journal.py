"""Consensus event journal: typed, replayable protocol-control-plane log.

Role: the third observability generation.  The metrics registry
(``utils/metrics.py``) aggregates durations, the span tracer
(``utils/tracing.py``) follows one transaction — this module records
WHAT THE PROTOCOL DECIDED: elections started/won/lost, votes cast,
validate quorums, version bumps after failed rounds, block
confirm/commit, and the membership TTL economy.  The reference left
these as free-form log lines that ``grep.py`` scraped (SURVEY §5);
here they are typed events with monotonic sequence numbers that
``harness/observatory.py`` can merge across a cluster and replay
offline from JSONL dumps bit-for-bit.

Every event is a flat dict::

    {"seq": 17, "ts": 42.125, "node": "ab12cd34",
     "type": "election_won", "blk": 9, "version": 0, ...attrs}

``seq`` is per-journal monotonic (gap-free unless the ring dropped),
``ts`` comes from the injected clock (virtual time under the
simulator), ``blk``/``version`` correlate events to a consensus round,
and an active trace context adds ``trace`` so journal rows join the
span graph.  Event types are drawn from ONE registered set
(:data:`EVENT_TYPES`); ``record`` raises on an unknown type so emit
sites cannot drift from the observatory parser (the stringly-typed
drift the round-2 lint tests exist to prevent).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

# The single registered event vocabulary.  Emit sites (consensus/node.py,
# consensus/membership.py, core/chain.py, core/txpool.py) must use these
# literals and nothing else; tests/test_journal_observatory.py lints the
# sources against this set.
EVENT_TYPES = frozenset({
    # elections
    "election_started", "election_won", "election_lost",
    "vote_cast", "vote_stashed",
    # validate round
    "validate_request", "validate_reply", "validate_retry",
    "validate_quorum",
    # proposals
    "proposal_built", "proposal_aborted",
    # failed-round recovery
    "version_bump",
    # chain progress
    "block_confirmed", "block_committed",
    # membership TTL economy
    "member_registered", "member_renewed", "member_expired",
    # event-loop plumbing
    "deferred_drain",
    # txpool <-> chain coupling
    "txns_included",
    # verifier scheduler (crypto/scheduler.py): one coalesced dispatch
    # window flushed to the device or host-diverted
    "verifier_flush",
    # mesh dispatch (crypto/scheduler.py): one window/chunk served by a
    # specific device lane — device index, rows, queue wait, and whether
    # the lane host-diverted it (straggler rescue)
    "verifier_mesh_dispatch",
    # fault injection (sim/faults.py + harness/chaos.py): every
    # scripted fault lands in the journal stream so the observatory can
    # render the fault timeline next to the consensus events it caused
    "fault_crash", "fault_restart", "fault_partition", "fault_heal",
    "fault_link", "fault_net", "fault_skew", "fault_trigger",
    # verifier circuit breaker (crypto/scheduler.py): device declared
    # dead / half-open re-probe / recovered
    "fault_breaker",
    # AOT prewarm (node/service.py + sim/cluster.py restart): one
    # prewarm pass over the artifact store with load-vs-compile split
    # timing so the observatory can report cold-start time
    "verifier_aot_load",
    # telemetry plane (utils/timeseries.py + harness/collector.py): one
    # periodic registry sample — counters as deltas, gauges/percentiles
    # as points — riding the push channel to the cluster collector
    "telemetry_sample",
    # SLO burn-rate engine (harness/slo.py): alert state-machine
    # transitions, journaled so chaos scenarios assert on them and
    # --check-determinism byte-compares the alert stream
    "slo_pending", "slo_firing", "slo_resolved",
    # commit anatomy (harness/anatomy.py): per-block phase boundaries
    # emitted at three sites — the txpool's ingest/admit timestamps for
    # a block's included txns (stage="pool"), the proposer's
    # election/ack/seal split at seal time (stage="seal"), and one
    # verify-window interior per computed scheduler window
    # (stage="verify_window", wall-clock ms + lane; those attrs are
    # volatile-stripped by the chaos canonical dump)
    "commit_anatomy",
    # ingress provenance ledger (eges_tpu/utils/ledger.py): one
    # per-origin decayed cost snapshot journaled at each block commit
    # when anything was charged — deterministic counts/deltas plus the
    # wall-clock "costs" account the chaos canonical dump strips
    "ingress_ledger",
    # adaptive scheduler controller (crypto/scheduler.py): one
    # window-sizing decision per controller step — chosen flush deadline
    # and target rows plus the burn/latency inputs that drove it (the
    # timing-derived attrs are volatile-stripped by the chaos canonical
    # dump; the decision COUNT stays deterministic)
    "sched_adapt",
    # continuous sampling profiler (eges_tpu/utils/profiler.py): one
    # aggregate per-phase/per-role sample-count report per profiling
    # interval.  Sampled stacks are wall-clock by nature, so these are
    # journaled ONLY into the dedicated "profiler" stream created by
    # SimCluster.enable_profiling() (or a real node's journal) — never
    # into determinism-checked streams; chaos scenarios never enable
    # the plane
    "profiler_report",
    # snapshot state sync (consensus/node.py + core/statesync.py):
    # durable checkpoint written at the cadence boundary; O(tail)
    # restart anchored on a root-verified checkpoint; mid-sync crash
    # resume from staged pages; poisoned-page detection (final-root
    # mismatch → serving peer blacklisted); download re-anchored on a
    # fresh pivot/server; quiet-server rotation; bounded abort back to
    # full replay; successful snapshot adoption
    "statesync_checkpoint", "statesync_restart", "statesync_resume",
    "statesync_poisoned", "statesync_reanchor", "statesync_server_rotate",
    "statesync_abort", "statesync_adopted",
    # device-efficiency observatory (eges_tpu/utils/devstats.py): one
    # per-device delta of deterministic window/row/waste counts per
    # devstats tick — goodput numerators/denominators plus the
    # per-bucket split.  Journaled into the dedicated "devstats" stream
    # created by SimCluster.enable_devstats() (or a real node's
    # journal); chaos determinism scenarios never enable the plane.
    # The optional "mem" block carries point-in-time HBM watermarks
    # and is absent on backends without memory_stats().
    "device_efficiency",
})

# The registered ``_breakdown`` phase vocabulary (consensus/node.py);
# kept here beside EVENT_TYPES so the lint test checks both stringly
# namespaces against one module.
BREAKDOWN_PHASES = frozenset({"election", "ack", "seal_total"})


class Journal:
    """Bounded per-node event ring with JSONL persistence.

    One instance per consensus node (NOT a process-global default: a sim
    cluster runs many nodes in one process and their journals must stay
    separable for the observatory merge).
    """

    def __init__(self, node: str = "", clock=time.monotonic,
                 capacity: int = 65536):
        self.node = node
        self._clock = clock
        self._lock = threading.Lock()
        self._events: deque[dict] = deque(maxlen=capacity)
        self._seq = 0
        self.dropped = 0
        # restart replay re-runs historical inserts through the live emit
        # sites; flipping this off keeps replayed history out of the ring
        self.enabled = True
        # optional event tap: called with each recorded event dict AFTER
        # it is appended.  The fault injector's leader-targeted triggers
        # ("kill the winner the moment it wins") listen here.
        self.on_record = None

    # -- recording ------------------------------------------------------
    def record(self, type: str, blk: int | None = None,
               version: int | None = None, **attrs) -> dict | None:
        if type not in EVENT_TYPES:
            raise ValueError(f"unregistered journal event type: {type!r}")
        if not self.enabled:
            return None
        ev: dict = {"ts": round(float(self._clock()), 6),
                    "node": self.node, "type": type}
        if blk is not None:
            ev["blk"] = blk
        if version is not None:
            ev["version"] = version
        from eges_tpu.utils import tracing
        ctx = tracing.DEFAULT.current_context()
        if ctx is not None:
            ev["trace"] = ctx.trace_id
        ev.update(attrs)
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
        tap = self.on_record
        if tap is not None:
            tap(ev)  # outside the ring lock: taps may record elsewhere
        return ev

    # -- export ---------------------------------------------------------
    def events(self, limit: int = 0, since: int = 0) -> list[dict]:
        """Chronological events; ``since`` filters to ``seq >= since``
        (incremental polling), ``limit`` keeps only the newest N."""
        with self._lock:
            evs = list(self._events)
        if since:
            evs = [e for e in evs if e["seq"] >= since]
        if limit and limit > 0:
            evs = evs[-limit:]
        return evs

    def stats(self) -> dict:
        with self._lock:
            return {"seq": self._seq, "buffered": len(self._events),
                    "dropped": self.dropped,
                    "capacity": self._events.maxlen}

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: str, drain: bool = True) -> int:
        """Append buffered events to ``path`` as JSONL; returns the
        number written.  ``drain`` empties the ring so periodic dumps
        never duplicate rows (same contract as ``Tracer.dump``)."""
        with self._lock:
            evs = list(self._events)
            if drain:
                self._events.clear()
        if not evs:
            return 0
        with open(path, "a", encoding="utf-8") as fh:
            for e in evs:
                fh.write(json.dumps(e, sort_keys=True) + "\n")
        return len(evs)


def load(path: str) -> list[dict]:
    """Parse a journal JSONL dump; a torn tail row (a live dump racing
    the reader) is skipped, everything parsed before it is kept."""
    out: list[dict] = []
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
