"""Columnar txn ingest — the wire-speed front half (ROADMAP item 5).

The back half of the pipeline is batched to the hilt (one device call
per verify window); before this module, every row still paid per-tx
Python on the way in: a per-datagram RLP decode into a ``Transaction``
object, a per-tx ``signature_parts()`` re-encode, a per-tx cache probe
and ``Future`` in the scheduler, per-tx dict bookkeeping in the pool.
Here a whole gossip window of txn frames is decoded ONCE into columnar
numpy arrays — ``sighash32`` / ``sig65`` / ``txhash`` / ``gas_price`` /
``nonce`` columns plus validity masks — shaped exactly like the verify
path's staging buffers, so the window lands in the device staging pool
(``verifier.recover_addresses`` / ``scheduler.submit_window``) without
any per-row conversion.  ``Transaction`` object construction is
deferred to admission time (:meth:`TxColumns.txn`): rejected rows —
the flood case — never materialize an object at all, keeping the
cheap-reject path cheap at wire rate (arXiv 1808.02252's DoS contract;
arXiv 2112.02229's never-touch-a-scalar-path discipline).

Byte-identity contract: for every frame the per-row results here equal
the legacy scalar path exactly —

* ``txhash`` is ``keccak256(frame)``.  ``core/rlp.py`` rejects every
  non-canonical encoding, so a frame that decodes at all re-encodes to
  itself and this equals ``Transaction.decode(frame).hash``.
* ``sighash`` is built by slicing the first six field encodings
  straight out of the frame (one list header + optional EIP155
  suffix), which equals ``Transaction.sighash(chain_id)`` for the same
  canonicality reason — no re-encode, no Transaction.
* the ``valid`` mask applies the same v/r/s rules as
  ``Transaction.signature_parts()`` (mask-don't-raise), and the
  ``decoded`` mask the same width guards as ``Transaction.from_rlp``.

The tier-1 differential test (tests/test_columnar_ingest.py) holds the
two paths byte-identical end to end: admissions, stats, ledger
billing, journal dumps.
"""

from __future__ import annotations

import numpy as np

from eges_tpu.core import rlp
from eges_tpu.core.types import Transaction
from eges_tpu.crypto.keccak import keccak256

# Hard per-frame byte gate, applied BEFORE any parsing: an oversized
# frame must die without costing a decode or even a hash (the node's
# datagram path already enforces its own INGRESS_MAX_BYTES on the whole
# message; this is the per-row second fence for direct window callers).
FRAME_MAX_BYTES = 128 * 1024

# Hard row cap per window — the largest window the scheduler's staging
# pool is sized for; decode callers chunk above it.
WINDOW_MAX_ROWS = 16384

_SECP_MAX = 1 << 256


class TxColumns:
    """One decoded gossip window in columnar form.

    Arrays are row-aligned: row ``i`` of every column describes frame
    (or txn) ``i`` of the input.  ``decoded[i]`` is False when the
    frame failed the size gate or canonical decode (no identity — the
    row is untouchable); ``valid[i]`` is False when the row decoded
    but its v/r/s cannot form a wire signature (the cheap-reject rows
    the pool bills without ever building a ``Transaction``).
    """

    __slots__ = ("n", "sighash", "sig", "txhash", "gas_price", "nonce",
                 "decoded", "valid", "hashes", "_items", "_txns")

    def __init__(self, n: int):
        self.n = n
        self.sighash = np.zeros((n, 32), np.uint8)
        self.sig = np.zeros((n, 65), np.uint8)
        self.txhash = np.zeros((n, 32), np.uint8)
        self.gas_price = np.zeros((n,), np.uint64)
        self.nonce = np.zeros((n,), np.uint64)
        self.decoded = np.zeros((n,), bool)
        self.valid = np.zeros((n,), bool)
        # python-object mirror of ``txhash`` for set-based dedup (the
        # pool's ``_known`` difference is one C-level set op over these)
        self.hashes: list[bytes | None] = [None] * n
        self._items: list = [None] * n  # parsed RLP items, decode path
        self._txns: list = [None] * n   # materialized / original txns

    def txn(self, i: int) -> Transaction:
        """Materialize row ``i``'s ``Transaction`` — admission time
        only; rejected rows never pay this."""
        t = self._txns[i]
        if t is None:
            # direct field construction instead of from_rlp: the scan
            # already enforced every from_rlp guard (canonical uints,
            # r/s/v widths, `to` length), so int.from_bytes over the
            # raw payloads builds the identical object without a
            # second decode pass
            it = self._items[i]
            t = Transaction(
                nonce=int.from_bytes(it[0], "big"),
                gas_price=int.from_bytes(it[1], "big"),
                gas_limit=int.from_bytes(it[2], "big"),
                to=bytes(it[3]) if it[3] else None,
                value=int.from_bytes(it[4], "big"),
                payload=bytes(it[5]),
                is_geec=bool(int.from_bytes(it[6], "big")),
                v=int.from_bytes(it[7], "big"),
                r=int.from_bytes(it[8], "big"),
                s=int.from_bytes(it[9], "big"))
            h = self.hashes[i]
            if h is not None:
                # seed the memoized hash from the wire frame's keccak
                # (canonical RLP: keccak256(frame) == keccak256(
                # t.encode())) — admission never re-encodes the row
                t._SENDER_CACHE["hash"] = h
            self._txns[i] = t
        return t

    def gather(self, rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(sighash32, sig65) sub-arrays for ``rows`` — contiguous
        uint8 blocks that drop straight into the verifier's staging
        buffers (one fancy-index copy, zero per-row conversion)."""
        return self.sighash[rows], self.sig[rows]


def _scan_txn_frame(frame: bytes) -> tuple[list, list]:
    """Parse one canonical txn frame WITHOUT building a Transaction:
    returns ``(items, spans)`` where ``items[i]`` is field ``i``'s raw
    byte-string payload and ``spans[i] = (enc_start, enc_end)`` is the
    field's FULL encoding span inside ``frame`` (header included) —
    what the sighash preimage is sliced from.  Raises RLPError on
    anything ``Transaction.decode`` would reject."""
    if not frame:
        raise rlp.RLPError("empty frame")
    b0 = frame[0]
    if b0 < 0xC0:
        raise rlp.RLPError("txn frame must be a list")
    if b0 < 0xF8:
        pos, end = 1, 1 + (b0 - 0xC0)
    else:
        ln = b0 - 0xF7
        if 1 + ln > len(frame):
            raise rlp.RLPError("truncated length")
        lb = frame[1:1 + ln]
        if lb[:1] == b"\x00":
            raise rlp.RLPError("non-canonical length")
        n = int.from_bytes(lb, "big")
        if n < 56:
            raise rlp.RLPError("non-canonical long list")
        pos, end = 1 + ln, 1 + ln + n
    if end != len(frame):
        raise rlp.RLPError("trailing bytes")
    items, spans = [], []
    push_item, push_span = items.append, spans.append
    flen = len(frame)
    for _ in range(10):
        if pos >= end:
            raise rlp.RLPError("txn frame needs 10 fields")
        enc_start = pos
        # _scan_string_item's exact rules, inlined: ten calls per frame
        # is the decode loop's hottest edge
        b0 = frame[pos]
        if b0 < 0x80:
            ps, pe = pos, pos + 1
            pos += 1
        elif b0 < 0xB8:  # short string
            n = b0 - 0x80
            ps = pos + 1
            pe = ps + n
            if pe > flen:
                raise rlp.RLPError("truncated string")
            if n == 1 and frame[ps] < 0x80:
                raise rlp.RLPError("non-canonical single byte")
            pos = pe
        elif b0 < 0xC0:  # long string
            ln = b0 - 0xB7
            ps = pos + 1 + ln
            if ps > flen:
                raise rlp.RLPError("truncated length")
            lb = frame[pos + 1:ps]
            if lb[:1] == b"\x00":
                raise rlp.RLPError("non-canonical length")
            n = int.from_bytes(lb, "big")
            if n < 56:
                raise rlp.RLPError("non-canonical long string")
            pe = ps + n
            if pe > flen:
                raise rlp.RLPError("truncated string")
            pos = pe
        else:
            raise rlp.RLPError("txn field must be a string item")
        if pos > end:
            raise rlp.RLPError("list payload overrun")
        push_item(frame[ps:pe])
        push_span((enc_start, pos))
    if pos != end:
        raise rlp.RLPError("txn frame needs exactly 10 fields")
    # the from_rlp guards: r/s fit 256 bits, v fits 64 bits, `to` is
    # empty or a 20-byte address, uint fields carry no leading zero —
    # every frame that decodes here must also survive from_rlp, so a
    # deferred txn() at admission time can never raise
    if len(items[8]) > 32 or len(items[9]) > 32:
        raise rlp.RLPError("signature scalar wider than 256 bits")
    if len(items[7]) > 8:
        raise rlp.RLPError("v wider than 64 bits")
    if len(items[3]) not in (0, 20):
        raise rlp.RLPError("to must be empty or a 20-byte address")
    for idx in (0, 1, 2, 4, 6, 7, 8, 9):  # all but to(3)/payload(5)
        if items[idx][:1] == b"\x00":
            raise rlp.RLPError("non-canonical integer (leading zero)")
    return items, spans


def _list_header(n: int) -> bytes:
    """RLP list header for an ``n``-byte payload (encode-side mirror of
    the scanner above; kept local so no private reach into rlp)."""
    if n < 56:
        return bytes([0xC0 + n])
    lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0xC0 + 55 + len(lb)]) + lb


def _dispatch_keccak_many():
    """Prefer the native variable-length batch digest (ONE FFI call
    per window instead of one per hash); per-message :func:`keccak256`
    stays the golden fallback for old library builds."""
    try:
        from eges_tpu.crypto import native

        if native.available() and native.keccak256_multi(
                b"ab", (0, 1, 2)) == keccak256(b"a") + keccak256(b"b"):
            return native.keccak256_multi
    # analysis: allow-swallow(optional native-accel probe; falls back to python)
    except Exception:
        pass
    return None


_KECCAK_MULTI = _dispatch_keccak_many()


def _keccak_many(msgs: list) -> bytes:
    """Flat ``len(msgs)*32`` digest bytes for a list of messages."""
    if not msgs:
        return b""
    if _KECCAK_MULTI is None:
        return b"".join(keccak256(m) for m in msgs)
    offsets = [0]
    push = offsets.append
    total = 0
    for m in msgs:
        total += len(m)
        push(total)
    return _KECCAK_MULTI(b"".join(msgs), offsets)


def decode_window(frames) -> TxColumns:  # ingress-entry:bounded
    """Vectorized envelope/signature extraction: a whole window of raw
    txn frames (length-capped by the transport) into one
    :class:`TxColumns` — O(1) Python-level transitions per window on
    the downstream path instead of O(rows).

    Two passes.  Scan: per frame the byte gate (oversized frames die
    pre-decode, pre-hash), one canonical scan recording field spans,
    and ``signature_parts``'s exact v/r/s rules — the sighash preimage
    is sliced straight out of the frame (list header + first six field
    encodings + EIP155 suffix), no re-encode, no ``Transaction``.
    Fill: ONE batched keccak call digests every txhash and sighash in
    the window, then the columns fill with whole-array writes.  Decode
    or signature failures mask the row out instead of raising
    (mask-don't-raise, the batch contract); invalid-signature rows
    never pay a sighash keccak."""
    frames = list(frames)
    if len(frames) > WINDOW_MAX_ROWS:
        raise ValueError("window exceeds %d rows — chunk the caller"
                         % WINDOW_MAX_ROWS)
    cols = TxColumns(len(frames))
    dec_rows: list[int] = []    # row index per decoded frame
    dec_msgs: list[bytes] = []  # the frame bytes (txhash preimage)
    nonces: list[int] = []
    prices: list[int] = []
    sig_rows: list[int] = []    # row index per signature-valid row
    sig_blobs: list[bytes] = []  # 65-byte wire sig per valid row
    sig_pre: list[bytes] = []   # sighash preimage per valid row
    for i, frame in enumerate(frames):
        if not frame or len(frame) > FRAME_MAX_BYTES:
            continue  # oversized/empty: dead before any parse or copy
        frame = bytes(frame)  # bounded-by: len(frame) <= FRAME_MAX_BYTES (guard above)
        try:
            items, spans = _scan_txn_frame(frame)
        except rlp.RLPError:
            continue
        cols._items[i] = items
        dec_rows.append(i)
        dec_msgs.append(frame)
        nonces.append(min(int.from_bytes(items[0], "big"),
                          (1 << 64) - 1))
        prices.append(min(int.from_bytes(items[1], "big"),
                          (1 << 64) - 1))
        # signature_parts()'s exact v/r/s rules, span-sliced
        v = int.from_bytes(items[7], "big")
        protected = v not in (27, 28) and v != 0
        if protected and v < 35:
            continue  # the chain_id ValueError branch: 29..34 unassigned
        cid = (v - 35) // 2 if protected else None
        recid = v - 27 if cid is None else v - 35 - 2 * cid
        r = int.from_bytes(items[8], "big")
        s = int.from_bytes(items[9], "big")
        if not (0 <= recid <= 3 and 0 < r < _SECP_MAX
                and 0 < s < _SECP_MAX):
            continue
        sig_rows.append(i)
        sig_blobs.append(r.to_bytes(32, "big") + s.to_bytes(32, "big")
                         + bytes([recid]))
        body = frame[spans[0][0]:spans[5][1]]
        if cid is not None:
            body = body + rlp.encode(cid) + b"\x80\x80"
        sig_pre.append(_list_header(len(body)) + body)
    # one digest batch for the whole window: txhashes first, sighashes
    # after — sliced back apart by count
    digests = _keccak_many(dec_msgs + sig_pre)
    n_dec = len(dec_rows)
    if n_dec:
        rows = np.asarray(dec_rows, np.int64)
        cols.decoded[rows] = True
        th = digests[:32 * n_dec]
        cols.txhash[rows] = np.frombuffer(th, np.uint8).reshape(-1, 32)
        hashes = cols.hashes
        for k, i in enumerate(dec_rows):
            hashes[i] = th[32 * k:32 * k + 32]
        cols.nonce[rows] = nonces
        cols.gas_price[rows] = prices
    if sig_rows:
        rows = np.asarray(sig_rows, np.int64)
        cols.valid[rows] = True
        cols.sig[rows] = np.frombuffer(b"".join(sig_blobs),
                                       np.uint8).reshape(-1, 65)
        cols.sighash[rows] = np.frombuffer(digests[32 * n_dec:],
                                           np.uint8).reshape(-1, 32)
    return cols


def columns_from_txns(txns) -> TxColumns:  # ingress-entry:bounded
    """Columns for already-decoded ``Transaction`` objects (the gossip
    path hands the pool decoded txns): extraction only — the original
    objects are kept and returned by :meth:`TxColumns.txn`, so
    admission admits the exact objects the legacy path would."""
    txns = list(txns)
    if len(txns) > WINDOW_MAX_ROWS:
        raise ValueError("window exceeds %d rows — chunk the caller"
                         % WINDOW_MAX_ROWS)
    cols = TxColumns(len(txns))
    for i, t in enumerate(txns):
        h = t.hash
        cols.decoded[i] = True
        cols.hashes[i] = h
        cols.txhash[i] = np.frombuffer(h, np.uint8)
        cols._txns[i] = t
        cols.nonce[i] = min(t.nonce, (1 << 64) - 1)
        cols.gas_price[i] = min(t.gas_price, (1 << 64) - 1)
        parts = t.signature_parts()
        if parts is not None:
            sig, sighash = parts
            cols.sig[i] = np.frombuffer(sig, np.uint8)
            cols.sighash[i] = np.frombuffer(sighash, np.uint8)
            cols.valid[i] = True
    return cols
