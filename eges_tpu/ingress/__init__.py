"""The ingress perimeter facade — the blessed way to touch raw ingress.

Every function and handler that first receives attacker-controlled
bytes carries a ``# ingress-entry`` def-line mark (``:bounded`` when
the transport has already length-capped the frame).  Two analyses read
those marks as one source of truth: the taint pass seeds its lattice
from them, and the architecture pass (``harness/analysis/layers.py``,
rule ``perimeter-breach``) requires that

* every mark lives inside the declared perimeter modules
  (``harness/analysis/layermap.py``), and
* every marked name is registered in :data:`INGRESS_ENTRIES` below —
  the machine-checked inventory of the whole ingress surface, and
* no module outside the perimeter imports, calls, or takes a bound
  reference to a marked entry directly — outside callers go through
  the wrappers here.

This package is deliberately import-weightless: no eager imports, the
wrappers take the owning object as an argument.  ROADMAP item 5's
wire-speed ingest rebuild lands inside this module boundary — the
facade pre-digs it, so when the batched-ingest path replaces the
per-datagram handlers, outside callers don't move.
"""

from __future__ import annotations

# The complete ingress surface: every `# ingress-entry[:bounded]` mark
# in the tree, by leaf name.  The perimeter checker fails the gate
# when a mark exists that is not enumerated here (or vice versa a
# stale name lingers after the entry moved behind a new seam).
INGRESS_ENTRIES = frozenset({
    # consensus/node.py — datagram + txn entries (raw bytes)
    "on_gossip", "on_direct", "on_geec_txn",
    # consensus/node.py — RPC-worker admission (length-capped frames)
    "submit_txns", "broadcast_txns",
    # rpc/server.py — transport handlers (raw) and dispatch (bounded)
    "_handle_conn", "_handle_ws", "_handle_ipc",
    "dispatch", "_handle_body",
    # sim/simnet.py — simulated delivery into the node sinks
    "_fire_gossip", "_fire_direct",
    # core/txpool.py — the admission seam (validated, capped batches)
    "add_remotes", "add_locals",
})


# -- blessed wrappers ----------------------------------------------------
#
# Outside-perimeter callers hold a node / server / pool object and need
# a sink or a one-shot admission; they get it here instead of reaching
# for the marked methods directly.  Each wrapper is a single bound
# lookup — zero overhead, but the call site now names its intent and
# the perimeter checker can prove nothing else touches the surface.

def gossip_sink(node):
    """The node's gossip-datagram sink, for wiring into a transport
    (``simnet.join``, the UDP plane)."""
    return node.on_gossip


def direct_sink(node):
    """The node's direct-datagram sink (point-to-point frames)."""
    return node.on_direct


def txn_sink(node):
    """The node's raw-txn-payload sink (the geec txn gossip plane)."""
    return node.on_geec_txn


def submit_txns(node, txns) -> None:
    """RPC-worker txn submission into the consensus node (bounded:
    the RPC layer has already length-capped the batch)."""
    node.submit_txns(txns)


def broadcast_txns(node, txns) -> None:
    """RPC-worker txn broadcast through the consensus node."""
    node.broadcast_txns(txns)


def dispatch_rpc(server, method: str, params: list):
    """One RPC method dispatch on an in-process server object (the
    harness/bench path that skips the socket transport)."""
    return server.dispatch(method, params)


def admit_remotes(pool, txns) -> None:
    """Admit peer-origin transactions into a txpool (the validated,
    per-sender-capped seam)."""
    pool.add_remotes(txns)


def admit_locals(pool, txns) -> None:
    """Admit locally-submitted transactions into a txpool."""
    pool.add_locals(txns)
