"""The ingress perimeter facade — the blessed way to touch raw ingress.

Every function and handler that first receives attacker-controlled
bytes carries a ``# ingress-entry`` def-line mark (``:bounded`` when
the transport has already length-capped the frame).  Two analyses read
those marks as one source of truth: the taint pass seeds its lattice
from them, and the architecture pass (``harness/analysis/layers.py``,
rule ``perimeter-breach``) requires that

* every mark lives inside the declared perimeter modules
  (``harness/analysis/layermap.py``), and
* every marked name is registered in :data:`INGRESS_ENTRIES` below —
  the machine-checked inventory of the whole ingress surface, and
* no module outside the perimeter imports, calls, or takes a bound
  reference to a marked entry directly — outside callers go through
  the wrappers here.

This package is deliberately import-weightless: no eager imports, the
wrappers take the owning object as an argument (the columnar decoder
in :mod:`eges_tpu.ingress.columnar` loads lazily through its own
wrappers below).  ROADMAP item 5's wire-speed ingest rebuild now lives
here: ``columnar.decode_window`` turns a whole gossip window of txn
frames into numpy-backed columns (sighash32 / sig65 / txhash /
gas_price / nonce) with O(1) Python-level transitions per window,
``TxPool.add_remotes_window`` admits it with set-op dedup and
per-window bookkeeping, and ``VerifierScheduler.submit_window`` takes
the rows in one lock hold — the legacy per-tx path stays for
singletons and as the differential-test oracle.
"""

from __future__ import annotations

# The complete ingress surface: every `# ingress-entry[:bounded]` mark
# in the tree, by leaf name.  The perimeter checker fails the gate
# when a mark exists that is not enumerated here (or vice versa a
# stale name lingers after the entry moved behind a new seam).
INGRESS_ENTRIES = frozenset({
    # consensus/node.py — datagram + txn entries (raw bytes)
    "on_gossip", "on_direct", "on_geec_txn",
    # consensus/node.py — RPC-worker admission (length-capped frames)
    "submit_txns", "broadcast_txns",
    # rpc/server.py — transport handlers (raw) and dispatch (bounded)
    "_handle_conn", "_handle_ws", "_handle_ipc",
    "dispatch", "_handle_body",
    # sim/simnet.py — simulated delivery into the node sinks
    "_fire_gossip", "_fire_direct",
    # core/txpool.py — the admission seam (validated, capped batches)
    "add_remotes", "add_locals", "add_remotes_window",
    # ingress/columnar.py — the wire-speed columnar decoders (frames
    # are transport-length-capped; oversized rows die pre-decode)
    "decode_window", "columns_from_txns",
})


# -- blessed wrappers ----------------------------------------------------
#
# Outside-perimeter callers hold a node / server / pool object and need
# a sink or a one-shot admission; they get it here instead of reaching
# for the marked methods directly.  Each wrapper is a single bound
# lookup — zero overhead, but the call site now names its intent and
# the perimeter checker can prove nothing else touches the surface.

def gossip_sink(node):
    """The node's gossip-datagram sink, for wiring into a transport
    (``simnet.join``, the UDP plane)."""
    return node.on_gossip


def direct_sink(node):
    """The node's direct-datagram sink (point-to-point frames)."""
    return node.on_direct


def txn_sink(node):
    """The node's raw-txn-payload sink (the geec txn gossip plane)."""
    return node.on_geec_txn


def submit_txns(node, txns) -> None:
    """RPC-worker txn submission into the consensus node (bounded:
    the RPC layer has already length-capped the batch)."""
    node.submit_txns(txns)


def broadcast_txns(node, txns) -> None:
    """RPC-worker txn broadcast through the consensus node."""
    node.broadcast_txns(txns)


def dispatch_rpc(server, method: str, params: list):
    """One RPC method dispatch on an in-process server object (the
    harness/bench path that skips the socket transport)."""
    return server.dispatch(method, params)


def admit_remotes(pool, txns) -> None:
    """Admit peer-origin transactions into a txpool (the validated,
    per-sender-capped seam)."""
    pool.add_remotes(txns)


def admit_locals(pool, txns) -> None:
    """Admit locally-submitted transactions into a txpool."""
    pool.add_locals(txns)


# -- wire-speed columnar ingest (ROADMAP item 5) -------------------------

def decode_txn_window(frames):
    """Decode a whole window of raw txn frames into columnar arrays
    (``ingress.columnar.TxColumns``): one canonical scan + one keccak
    per frame, sighash preimages sliced straight out of the frame
    bytes, ``Transaction`` construction deferred to admission time."""
    from eges_tpu.ingress.columnar import decode_window

    return decode_window(frames)


def columns_of(txns):
    """Columns for already-decoded ``Transaction`` objects — the gossip
    relay path, where the codec decoded the bundle but admission should
    still run window-granular."""
    from eges_tpu.ingress.columnar import columns_from_txns

    return columns_from_txns(txns)


def admit_remotes_window(pool, cols) -> None:
    """Admit one decoded columnar window into a txpool: one lock hold,
    set-op dedup, one batched verify call per ``max_batch`` rows —
    byte-identical admission outcomes to :func:`admit_remotes` over the
    same rows."""
    pool.add_remotes_window(cols)
