"""Endpoint sanity for the discovery plane (the p2p/netutil role).

The reference gates which announced endpoints it will relay or dial:
special-purpose networks are rejected outright and a table never holds
too many nodes from one subnet (ref: p2p/netutil/net.go — IsLAN /
IsSpecialNetwork / DistinctNetSet).  This module is the same defense
for the bootnode registry: a permissioned committee is exactly the
kind of small table one hostile /24 could otherwise flood.

Classification is deliberately coarse — four buckets that drive
policy, not a full IANA registry walk:

    loopback   127/8            always dialable locally (dev clusters)
    lan        RFC1918 + link-local + CGN
    special    multicast, unspecified, reserved, broadcast
    routable   everything else
"""

from __future__ import annotations

import ipaddress


def classify(ip: str) -> str:
    try:
        a = ipaddress.ip_address(ip)
    except ValueError:
        return "special"
    if a.is_loopback:
        return "loopback"
    if a.is_multicast or a.is_unspecified or a.is_reserved \
            or ip == "255.255.255.255":
        return "special"
    if a.is_private or a.is_link_local:
        return "lan"
    return "routable"


def good_endpoint(ip: str, port: int) -> bool:
    """Would the reference relay this endpoint?  Ports must be real and
    the address must be something a peer could actually dial."""
    return 0 < port < 65536 and classify(ip) != "special"


class DistinctNetSet:
    """Bound how many tracked items share one subnet.

    ``bits`` is the prefix length defining "one subnet" (24 ⇒ /24) and
    ``limit`` the per-subnet cap.  Loopback addresses are exempt: local
    dev clusters put every node on 127.0.0.1 and are not a flooding
    vector.  (ref: p2p/netutil/net.go DistinctNetSet{Subnet,Limit})
    """

    def __init__(self, bits: int = 24, limit: int = 16):
        self.bits = bits
        self.limit = limit
        self._counts: dict[int, int] = {}

    def _key(self, ip: str) -> int | None:
        a = ipaddress.ip_address(ip)
        if a.is_loopback:
            return None
        return int(a) >> (32 - self.bits)

    def add(self, ip: str) -> bool:
        """Track ip; False (and no change) if its subnet is full."""
        k = self._key(ip)
        if k is None:
            return True
        n = self._counts.get(k, 0)
        if n >= self.limit:
            return False
        self._counts[k] = n + 1
        return True

    def remove(self, ip: str) -> None:
        k = self._key(ip)
        if k is None:
            return
        n = self._counts.get(k, 0)
        if n <= 1:
            self._counts.pop(k, None)
        else:
            self._counts[k] = n - 1

    def __len__(self) -> int:
        return sum(self._counts.values())
