"""Peer discovery: bootnode registry + announce/lookup client.

The permissioned-network replacement for the reference's Kademlia UDP
discovery (ref: p2p/discover/udp.go, p2p/discover/table.go) and its
``bootnode`` binary (ref: cmd/bootnode/main.go).  A full DHT is the
wrong tool for a committee-scale permissioned chain, so this is
Kademlia-lite: nodes ANNOUNCE themselves to one or more bootnodes
(signed, TTL'd) and poll GET_PEERS for a sample of live endpoints,
which feeds :meth:`GossipPlane.add_peer` — making ``--peers`` optional
(a node joins knowing only a bootnode).

Wire format (UDP, RLP):
    [code, payload...] with
    ANNOUNCE  = [0x01, pubkey64, gossip_ip, gossip_port,
                 consensus_ip, consensus_port, expiry_be, sig65]
                 sig over keccak(rlp([pubkey, gip, gport, cip, cport,
                 expiry])) — identity = address(pubkey)
    GET_PEERS = [0x02, nonce8]
    PEERS     = [0x03, nonce8, [[addr20, gip, gport, cip, cport], ...]]
    ENR_ANNOUNCE = [0x04, record]      signed node record (net/enr.py)
    GET_RECORDS  = [0x05, nonce8]
    RECORDS      = [0x06, nonce8, [record, ...]]

Bootnodes verify announce signatures and expiry, evict stale entries,
and never relay more than ``SAMPLE`` peers per query.  The record path
(codes 4-6) is the upgrade of the ad-hoc signed tuple: the bootnode
keeps the highest-``seq`` record per identity and lookups return the
peer's own signed statement, so a compromised bootnode cannot forge
endpoints — it can only withhold (ref: p2p/enr, p2p/discover/v4_udp.go
ENRRequest).  Both announce paths run endpoint sanity + per-subnet
caps from net/netutil.py (ref: p2p/netutil).
"""

from __future__ import annotations

import asyncio
import time

from eges_tpu.core import rlp
from eges_tpu.crypto.keccak import keccak256
from eges_tpu.utils.log import get_logger

# hostile/malformed datagrams are routine on an open UDP port: dropped
# at GDBUG so default verbosity stays quiet but a -v5 run shows them
log = get_logger("discovery")
from eges_tpu.net import enr as enrlib
from eges_tpu.net import netutil

ANNOUNCE = 1
GET_PEERS = 2
PEERS = 3
ENR_ANNOUNCE = 4
GET_RECORDS = 5
RECORDS = 6

ANNOUNCE_TTL_S = 60.0
SAMPLE = 16


def _sign_announce(priv: bytes, pub: bytes, gip: str, gport: int,
                   cip: str, cport: int, expiry: int) -> bytes:
    from eges_tpu.crypto import secp256k1 as secp

    h = keccak256(rlp.encode([pub, gip.encode(), gport, cip.encode(),
                              cport, expiry]))
    return secp.ecdsa_sign(h, priv)


def encode_announce(priv: bytes, pub: bytes, gip: str, gport: int,
                    cip: str, cport: int,
                    now: float | None = None) -> bytes:
    expiry = int((now if now is not None else time.time()) + ANNOUNCE_TTL_S)
    sig = _sign_announce(priv, pub, gip, gport, cip, cport, expiry)
    return rlp.encode([ANNOUNCE, pub, gip.encode(), gport, cip.encode(),
                       cport, expiry, sig])


class BootnodeService:
    """UDP peer registry (the cmd/bootnode role).

    ``python -m eges_tpu.bootnode --port 30301`` runs one standalone.
    """

    def __init__(self, bind_ip: str, port: int, *,
                 authorize=None, clock=time.time,
                 subnet_limit: int = 16):
        self.bind_ip = bind_ip
        self.port = port
        self.authorize = authorize  # callable(addr20) -> bool
        self.clock = clock
        # addr -> (gip, gport, cip, cport, expires_at)
        self.registry: dict[bytes, tuple] = {}
        # addr -> highest-seq verified Record for ENR announcers
        self.records: dict[bytes, enrlib.Record] = {}
        self._netset = netutil.DistinctNetSet(24, subnet_limit)
        self._transport = None

    # -- message handling (transport-independent, sim-testable) ----------

    def handle(self, data: bytes, reply) -> None:
        """``reply(bytes)`` sends back to the datagram source."""
        # one hostile datagram must never take down the registry, even
        # for direct (transportless) embeddings of handle(): the whole
        # dispatch is guarded, not just the RLP parse
        try:
            item = rlp.decode(data)
            code = rlp.decode_uint(item[0])
            now = self.clock()
            if code == ANNOUNCE:
                self._on_announce(item, now)
            elif code == ENR_ANNOUNCE and len(item) >= 2:
                self._on_enr_announce(bytes(item[1]), now)
            elif code == GET_PEERS and len(item) >= 2:
                self._evict(now)
                peers = [[a, gip.encode(), gp, cip.encode(), cp]
                         for a, (gip, gp, cip, cp, _) in
                         self._sample(self.registry)]
                reply(rlp.encode([PEERS, bytes(item[1]), peers]))
            elif code == GET_RECORDS and len(item) >= 2:
                self._evict(now)
                recs = [r.encode() for _, r in self._sample(self.records)]
                reply(rlp.encode([RECORDS, bytes(item[1]), recs]))
        except Exception as exc:
            log.gdbug("bootnode dropped datagram", nbytes=len(data),
                      err=repr(exc))
            return

    @staticmethod
    def _sample(table: dict) -> list:
        import random

        entries = list(table.items())
        if len(entries) > SAMPLE:
            # a RANDOM sample, not the first insertion-ordered slice:
            # otherwise members past the first SAMPLE are never
            # advertised and late joiners only ever learn one subset
            entries = random.sample(entries, SAMPLE)
        return entries

    def _on_announce(self, item: list, now: float) -> None:
        from eges_tpu.crypto import secp256k1 as secp

        try:
            _, pub, gip, gport, cip, cport, expiry, sig = item
            pub, sig = bytes(pub), bytes(sig)
            gip, cip = bytes(gip).decode(), bytes(cip).decode()
            gport, cport = rlp.decode_uint(gport), rlp.decode_uint(cport)
            expiry = rlp.decode_uint(expiry)
        except Exception as exc:
            log.gdbug("bootnode dropped malformed announce", err=repr(exc))
            return
        if expiry < now:
            return  # stale/replayed announce
        h = keccak256(rlp.encode([pub, gip.encode(), gport, cip.encode(),
                                  cport, expiry]))
        try:
            signer = secp.recover_address(h, sig)
        except Exception as exc:
            log.gdbug("bootnode dropped announce: bad signature",
                      err=repr(exc))
            return
        if signer != secp.pubkey_to_address(pub):
            return
        self._admit(signer, gip, gport, cip, cport, now)

    def _on_enr_announce(self, data: bytes, now: float) -> None:
        try:
            rec = enrlib.Record.decode(data)
        except enrlib.ENRError:
            return
        prev = self.records.get(rec.addr)
        if prev is not None:
            if rec.seq < prev.seq:
                return  # stale record
            if rec.seq == prev.seq and rec != prev:
                return  # conflicting content under one seq: keep first
            # identical record re-announced: fall through, refresh TTL
        gep, cep = rec.gossip_endpoint(), rec.consensus_endpoint()
        if gep is None or cep is None:
            return
        if self._admit(rec.addr, gep[0], gep[1], cep[0], cep[1], now):
            self.records[rec.addr] = rec

    def _admit(self, addr: bytes, gip: str, gport: int,
               cip: str, cport: int, now: float) -> bool:
        if not (netutil.good_endpoint(gip, gport)
                and netutil.good_endpoint(cip, cport)):
            return False
        if self.authorize is not None and not self.authorize(addr):
            return False
        old = self.registry.get(addr)
        if old is None or old[0] != gip:
            # release the identity's old slot BEFORE claiming the new
            # one: a node moving within an at-cap /24 must not be
            # bounced by its own old address (restore on failure)
            if old is not None:
                self._netset.remove(old[0])
            if not self._netset.add(gip):
                if old is not None:
                    self._netset.add(old[0])
                return False  # this /24 already holds its share
        self.registry[addr] = (gip, gport, cip, cport,
                               now + ANNOUNCE_TTL_S)
        return True

    def _evict(self, now: float) -> None:
        for a, rec in list(self.registry.items()):
            if rec[4] < now:
                self._netset.remove(rec[0])
                del self.registry[a]
                self.records.pop(a, None)

    # -- asyncio UDP server ----------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()

        service = self

        class _Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport

            def datagram_received(self, data, addr):
                try:
                    service.handle(
                        data, lambda out: self.transport.sendto(out, addr))
                except Exception as exc:
                    # handle() guards its own parse; this catches reply
                    # transmit failures (transport mid-close etc.)
                    log.gdbug("bootnode reply failed", peer=str(addr),
                              err=repr(exc))

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(self.bind_ip, self.port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()


class DiscoveryClient:
    """Announce/lookup loop against one or more bootnodes.

    Re-announces every ``interval_s`` (half the TTL), queries for peers,
    and calls ``on_peer(addr20, gossip_endpoint, consensus_endpoint)``
    for every newly-learned member — the NodeService wires this into
    ``GossipPlane.add_peer``.
    """

    def __init__(self, bootnodes: list[tuple[str, int]], priv: bytes,
                 gip: str, gport: int, cip: str, cport: int, *,
                 on_peer=None, interval_s: float = ANNOUNCE_TTL_S / 2):
        from eges_tpu.crypto import secp256k1 as secp

        self.bootnodes = list(bootnodes)
        self.priv = priv
        self.pub = secp.privkey_to_pubkey(priv)
        self.me = secp.pubkey_to_address(self.pub)
        self.endpoint = (gip, gport, cip, cport)
        # the node's own signed record.  seq must outrank every record
        # this identity ever announced before — a restart with a new
        # endpoint would otherwise be rejected as stale forever — so
        # without persistent state, wall-clock seconds is the seq (ref:
        # p2p/enr seq counters are persisted; geth's discv4 uses the
        # same timestamp trick for endpoint proofs)
        self.record = enrlib.Record.sign(
            priv, int(time.time()), ip=gip, tcp=gport, udp=cport, cip=cip)
        self.on_peer = on_peer
        self.interval_s = interval_s
        self.known: dict[bytes, tuple] = {}
        self.known_seq: dict[bytes, int] = {}
        self._transport = None
        self._task = None

    def _on_datagram(self, data: bytes) -> None:
        try:
            item = rlp.decode(data)
            code = rlp.decode_uint(item[0])
        except Exception as exc:
            log.gdbug("client dropped malformed datagram",
                      nbytes=len(data), err=repr(exc))
            return
        if code == RECORDS:
            try:
                recs = item[2]
            except Exception as exc:
                log.gdbug("client dropped truncated RECORDS", err=repr(exc))
                return
            for raw in recs:
                try:
                    self._on_record(bytes(raw))
                except Exception as exc:
                    # one bad record must not shadow the rest
                    log.gdbug("client skipped bad record", err=repr(exc))
                    continue
            return
        if code != PEERS:
            return
        try:
            peers = item[2]
        except Exception as exc:
            log.gdbug("client dropped truncated PEERS", err=repr(exc))
            return
        for p in peers:
            try:
                addr = bytes(p[0])
                gip, gport = bytes(p[1]).decode(), rlp.decode_uint(p[2])
                cip, cport = bytes(p[3]).decode(), rlp.decode_uint(p[4])
            except Exception as exc:
                log.gdbug("client skipped bad peer entry", err=repr(exc))
                continue
            self._learn(addr, gip, gport, cip, cport, seq=0)

    def _on_record(self, raw: bytes) -> None:
        try:
            rec = enrlib.Record.decode(raw)
        except enrlib.ENRError:
            return
        gep, cep = rec.gossip_endpoint(), rec.consensus_endpoint()
        if gep is None or cep is None:
            return
        self._learn(rec.addr, gep[0], gep[1], cep[0], cep[1],
                    seq=rec.seq)

    def _learn(self, addr: bytes, gip: str, gport: int,
               cip: str, cport: int, *, seq: int) -> None:
        if addr == self.me:
            return
        if not (netutil.good_endpoint(gip, gport)
                and netutil.good_endpoint(cip, cport)):
            return
        if addr in self.known:
            # a signed record with a higher seq may move a known peer's
            # endpoint; the unsigned legacy tuple (seq=0) never does
            if seq <= self.known_seq.get(addr, 0) \
                    or self.known[addr] == (gip, gport, cip, cport):
                return
        self.known[addr] = (gip, gport, cip, cport)
        self.known_seq[addr] = seq
        if self.on_peer is not None:
            self.on_peer(addr, (gip, gport), (cip, cport))

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _ClientProto(self._on_datagram), local_addr=("0.0.0.0", 0))
        self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        import secrets as _secrets

        rounds = 0
        while True:
            gip, gport, cip, cport = self.endpoint
            ann = encode_announce(self.priv, self.pub, gip, gport, cip, cport)
            enr_ann = rlp.encode([ENR_ANNOUNCE, self.record.encode()])
            query = rlp.encode([GET_PEERS, _secrets.token_bytes(8)])
            rquery = rlp.encode([GET_RECORDS, _secrets.token_bytes(8)])
            for bn in self.bootnodes:
                try:
                    # both generations: records are preferred, the
                    # legacy tuple keeps mixed clusters converging
                    self._transport.sendto(enr_ann, bn)
                    self._transport.sendto(ann, bn)
                    self._transport.sendto(rquery, bn)
                    self._transport.sendto(query, bn)
                except Exception as exc:
                    # a dead/unresolvable bootnode must not stall the
                    # announce loop for the remaining ones
                    log.gdbug("announce to bootnode failed", bootnode=bn,
                              err=repr(exc))
            rounds += 1
            # fast-start: tight announce/lookup rounds until the mesh
            # forms (peers only learn each other after BOTH have
            # announced — a cold cluster on the steady cadence would
            # take ~interval_s to converge), then settle down
            await asyncio.sleep(1.0 if rounds < 8 else self.interval_s)

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self._transport is not None:
            self._transport.close()


class _ClientProto(asyncio.DatagramProtocol):
    def __init__(self, on_datagram):
        self._on = on_datagram

    def datagram_received(self, data, addr):
        try:
            self._on(data)
        except Exception as exc:
            log.gdbug("client handler error", peer=str(addr),
                      err=repr(exc))
