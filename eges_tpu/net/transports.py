"""Real-socket network planes (asyncio).

The production counterpart of the simulator transports, mirroring the
reference's two planes (SURVEY §2.3):

* **Direct plane** — UDP datagrams, RLP payloads, exactly like the
  reference's election/reply sockets (consensus/geec/election/server.go
  binds ``--consensusPort``; replies dial ``ip:port`` from the request).
* **Gossip plane** — persistent TCP connections to a static peer list
  with length-prefixed frames.  The reference runs RLPx-encrypted devp2p
  here (p2p/rlpx.go); a permissioned deployment's transport security is
  orthogonal to consensus, so frames are plaintext for now and the
  handshake/encryption layer can be added beneath this interface
  (SURVEY §7 step 4: "discovery/RLPx crypto can come last").

Everything runs on one asyncio loop; inbound messages call straight into
the single-threaded :class:`~eges_tpu.consensus.node.GeecNode`, so the
no-locks design of the state machines carries over unchanged.
"""

from __future__ import annotations

import asyncio
import struct


class AsyncioClock:
    """Clock interface over the running asyncio loop."""

    def __init__(self, loop: asyncio.AbstractEventLoop | None = None):
        self._loop = loop or asyncio.get_event_loop()

    def now(self) -> float:
        return self._loop.time()

    def call_later(self, delay_s: float, fn):
        return self._loop.call_later(delay_s, fn)  # TimerHandle has .cancel()


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, on_datagram):
        self._on_datagram = on_datagram

    def datagram_received(self, data, addr):
        try:
            self._on_datagram(data)
        except Exception:
            pass  # one bad datagram must not kill the receive loop


class DirectPlane:
    """UDP send/receive for election messages and validate/query replies."""

    def __init__(self, bind_ip: str, bind_port: int, on_direct):
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self._on_direct = on_direct
        self._transport: asyncio.DatagramTransport | None = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self._on_direct),
            local_addr=(self.bind_ip, self.bind_port))

    def send(self, ip: str, port: int, data: bytes) -> None:
        if self._transport is not None:
            self._transport.sendto(data, (ip, port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()


class GossipPlane:
    """Static-peer-list TCP gossip with 4-byte length-prefixed frames.

    Reconnects with backoff; sends are fire-and-forget like the
    reference's per-peer ``p2p.Send`` loops (eth/handler.go:1071-1080).
    """

    MAX_FRAME = 64 * 1024 * 1024

    def __init__(self, bind_ip: str, bind_port: int, peers: list[tuple[str, int]],
                 on_gossip):
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.peers = [p for p in peers if p != (bind_ip, bind_port)]
        self._on_gossip = on_gossip
        self._server: asyncio.AbstractServer | None = None
        self._writers: dict[tuple[str, int], asyncio.StreamWriter] = {}
        self._tasks: list[asyncio.Task] = []
        self._closed = False

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.bind_ip, self.bind_port)
        for peer in self.peers:
            self._tasks.append(asyncio.create_task(self._dial_loop(peer)))

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = struct.unpack("<I", hdr)
                if n > self.MAX_FRAME:
                    break
                frame = await reader.readexactly(n)
                try:
                    self._on_gossip(frame)
                except Exception:
                    pass
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _dial_loop(self, peer: tuple[str, int]) -> None:
        backoff = 0.2
        while not self._closed:
            try:
                _, writer = await asyncio.open_connection(*peer)
                self._writers[peer] = writer
                backoff = 0.2
                # hold the connection; writer errors surface on send
                while not writer.is_closing() and not self._closed:
                    await asyncio.sleep(0.5)
            except (ConnectionError, OSError):
                pass
            self._writers.pop(peer, None)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)

    def broadcast(self, data: bytes) -> None:
        frame = struct.pack("<I", len(data)) + data
        for peer, writer in list(self._writers.items()):
            try:
                writer.write(frame)
            except Exception:
                self._writers.pop(peer, None)

    def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for w in self._writers.values():
            w.close()
        if self._server is not None:
            self._server.close()


class SocketTransport:
    """The Transport interface GeecNode expects, over the two planes."""

    def __init__(self, gossip: GossipPlane, direct: DirectPlane):
        self._gossip = gossip
        self._direct = direct

    def gossip(self, data: bytes) -> None:
        self._gossip.broadcast(data)

    def send_direct(self, ip: str, port: int, data: bytes) -> None:
        self._direct.send(ip, port, data)


class GeecTxnService:
    """UDP transaction-ingest API: every datagram on ``--geecTxnPort``
    becomes an unsigned Geec transaction (ref: consensus/geec/geec_api.go:11)."""

    def __init__(self, bind_ip: str, port: int, on_txn_payload):
        self.bind_ip = bind_ip
        self.port = port
        self._on_txn = on_txn_payload
        self._transport = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self._on_txn),
            local_addr=(self.bind_ip, self.port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
