"""Real-socket network planes (asyncio).

The production counterpart of the simulator transports, mirroring the
reference's two planes (SURVEY §2.3):

* **Direct plane** — UDP datagrams, RLP payloads, exactly like the
  reference's election/reply sockets (consensus/geec/election/server.go
  binds ``--consensusPort``; replies dial ``ip:port`` from the request).
* **Gossip plane** — persistent TCP connections to a static peer list
  with length-prefixed frames.  The reference runs RLPx-encrypted devp2p
  here (p2p/rlpx.go: ECDH handshake + AES-CTR framing + MAC); the
  RLPx-parity layer here is :class:`_FrameAuth`: an ECDSA-signed ECDH
  handshake derives per-direction session keys, every keyed frame is
  ENCRYPTED with a per-frame SHAKE-256 keystream and carries a 16-byte
  keccak-MAC over (key, sequence, ciphertext) — encrypt-then-MAC —
  with a per-direction monotonic sequence, so tampered, replayed,
  reordered, or readable-on-the-wire frames are all ruled out.  Three
  generations interop (v3 encrypted / v2 MAC-only / v1 symmetric);
  downgrades below the endpoint's best generation are rejected unless
  explicitly allowed (mixed-mode upgrade flags).

Everything runs on one asyncio loop; inbound messages call straight into
the single-threaded :class:`~eges_tpu.consensus.node.GeecNode`, so the
no-locks design of the state machines carries over unchanged.
"""

from __future__ import annotations

import asyncio
import struct
import time

from eges_tpu.utils.log import get_logger

# peer-facing parse/dispatch errors are routine against hostile or
# mid-upgrade peers: logged at GDBUG so -v5 shows them without letting
# default verbosity drown in them
log = get_logger("net")


class AsyncioClock:
    """Clock interface over the running asyncio loop."""

    def __init__(self, loop: asyncio.AbstractEventLoop | None = None):
        self._loop = loop or asyncio.get_event_loop()

    def now(self) -> float:
        return self._loop.time()

    def call_later(self, delay_s: float, fn):
        return self._loop.call_later(delay_s, fn)  # TimerHandle has .cancel()


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, on_datagram):
        self._on_datagram = on_datagram

    def datagram_received(self, data, addr):
        try:
            self._on_datagram(data)
        except Exception as exc:
            # one bad datagram must not kill the receive loop
            log.gdbug("direct datagram handler error", peer=str(addr),
                      err=repr(exc))


class DirectPlane:
    """UDP send/receive for election messages and validate/query replies."""

    def __init__(self, bind_ip: str, bind_port: int, on_direct):
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self._on_direct = on_direct
        self._transport: asyncio.DatagramTransport | None = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self._on_direct),
            local_addr=(self.bind_ip, self.bind_port))

    def send(self, ip: str, port: int, data: bytes) -> None:
        if self._transport is not None:
            self._transport.sendto(data, (ip, port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()


class AuthError(Exception):
    """Peer failed the gossip-plane handshake or sent a bad MAC."""


def _keystream(key: bytes, seq: int, n: int) -> bytes:
    """Per-frame keystream: SHAKE-256 as a XOF keyed by
    ``(enc_key, sequence)``.  One hashlib call emits the whole stream
    for a frame of any size, and the per-direction monotonic sequence
    guarantees the (key, nonce) pair is never reused — the stream-
    cipher contract.  Fills the AES-CTR role of the reference's RLPx
    framing (p2p/rlpx.go) with a primitive the stdlib provides."""
    import hashlib

    return hashlib.shake_256(key + seq.to_bytes(8, "big")).digest(n)


def _xor(a: bytes, b: bytes) -> bytes:
    n = len(a)
    return (int.from_bytes(a, "big") ^ int.from_bytes(b, "big")).to_bytes(
        n, "big") if n else b""


class _FrameAuth:
    """Per-connection frame authentication + encryption (the
    p2p/rlpx.go-parity layer).

    Three handshake generations:

    * **v3 (ECDH, encrypted — the default when a node key is present)**
      — each side sends ``MAGIC3 || pubkey64 || nonce16 || sig65``
      where ``sig`` signs ``keccak(MAGIC3 || pubkey || nonce)`` with
      the node key.  MAC and encryption keys derive per-direction from
      the ECDH shared secret mixed with both nonces, so every
      connection has fresh keys no other member can compute.  Frames
      are sealed ciphertext (encrypt-then-MAC): a wire observer sees
      lengths and nothing else.  The peer's recovered address is
      exposed as :attr:`peer_addr` for membership gating.
    * **v2 (ECDH, MAC-only)** — same handshake body under ``MAGIC2``,
      frames authenticated but plaintext.  Hellos cross simultaneously
      (both sides write first), so a v3 endpoint's MAGIC3 hello is
      already on the wire when a v2 hello arrives; mixed mode works
      because v2 endpoints accept the higher-generation magic (same
      body shape) while the v3 side accepts the v2 hello only with
      ``allow_v2`` — both then derive the MAC-only keys.  The session
      runs at the lower of the two offered generations, never silently
      below what a flag allows.
    * **v1 (symmetric)** — ``MAGIC || nonce16`` with keys
      ``keccak(secret || nonces)``; kept for keyless tooling, rejected
      by keyed endpoints unless ``allow_downgrade``.

    Every frame carries ``keccak(key || seq_be8 || body)[:16]`` with a
    per-direction monotonic sequence — tampered, replayed or reordered
    frames fail.  (A keccak prefix-MAC is sound: sponges have no
    length-extension weakness.)"""

    MAGIC = b"geec-gossip-v1\x00\x00"
    MAGIC2 = b"geec-gossip-v2\x00\x00"
    MAGIC3 = b"geec-gossip-v3\x00\x00"

    def __init__(self, secret: bytes, keypair: tuple[bytes, bytes] | None = None,
                 allow_downgrade: bool = False, allow_v2: bool = False,
                 version: int = 3):
        import secrets as _secrets

        self.secret = secret
        self.keypair = keypair  # (priv32, pub64) -> v2/v3 handshake
        # Round-3 advisor: a keyed side silently accepting a v1 hello
        # bypasses the authorize() membership gate (peer_addr never
        # set), and the default v1 secret is derivable from the public
        # genesis file.  Downgrade is therefore opt-in (mixed-mode
        # deployments mid-upgrade), never the default.  The same policy
        # guards v3 -> v2 (losing confidentiality).
        self.allow_downgrade = allow_downgrade
        self.allow_v2 = allow_v2
        self.version = version if keypair is not None else 1
        self.my_nonce = _secrets.token_bytes(16)
        self.send_key = b""
        self.recv_key = b""
        self.send_enc = b""      # v3: per-direction encryption keys
        self.recv_enc = b""
        self.encrypts = False
        self.send_seq = 0
        self.recv_seq = 0
        self.peer_addr: bytes | None = None  # v2/v3: authenticated identity

    def hello(self) -> bytes:
        if self.keypair is None:
            return self.MAGIC + self.my_nonce
        from eges_tpu.crypto import secp256k1 as secp
        from eges_tpu.crypto.keccak import keccak256

        priv, pub = self.keypair
        magic = self.MAGIC3 if self.version >= 3 else self.MAGIC2
        body = magic + pub + self.my_nonce
        sig = secp.ecdsa_sign(keccak256(body), priv)
        return body + sig

    def on_hello(self, data: bytes) -> None:
        """Derive session keys from the peer's hello.

        Version negotiation: both sides send their best generation
        simultaneously; the session runs at the LOWER of the two — but
        an endpoint only accepts a generation below its own when the
        matching mixed-mode flag allows it (``allow_v2`` for
        v3 endpoints meeting v2, ``allow_downgrade`` for keyed
        endpoints meeting keyless v1).  A keyless endpoint can parse a
        v2/v3 hello's nonce and derive the v1 keys, so keyless tooling
        interops with a flagged keyed peer instead of mutually
        AuthError-ing."""
        from eges_tpu.crypto.keccak import keccak256

        m2 = len(self.MAGIC2)
        keyed = (data[:m2] in (self.MAGIC2, self.MAGIC3)
                 and len(data) == m2 + 64 + 16 + 65)
        if keyed:
            peer_version = 3 if data[:m2] == self.MAGIC3 else 2
            peer_pub = data[m2 : m2 + 64]
            peer_nonce = data[m2 + 64 : m2 + 80]
            if self.keypair is not None:
                from eges_tpu.crypto import secp256k1 as secp

                if peer_version < 3 <= self.version and not self.allow_v2:
                    raise AuthError("v2 hello rejected (downgrade)")
                sig = data[m2 + 80 :]
                body = data[: m2 + 80]
                try:
                    signer = secp.recover_address(keccak256(body), sig)
                except Exception:
                    raise AuthError("bad hello signature")
                if signer != secp.pubkey_to_address(peer_pub):
                    raise AuthError("hello signature/pubkey mismatch")
                self.peer_addr = signer
                try:
                    shared = secp.ecdh_shared(self.keypair[0], peer_pub)
                except ValueError:
                    raise AuthError("bad peer pubkey")
                # mix the network secret in as a domain separator
                self.send_key = keccak256(shared + self.secret
                                          + self.my_nonce + peer_nonce)
                self.recv_key = keccak256(shared + self.secret
                                          + peer_nonce + self.my_nonce)
                if peer_version >= 3 and self.version >= 3:
                    self.send_enc = keccak256(b"enc" + shared + self.secret
                                              + self.my_nonce + peer_nonce)
                    self.recv_enc = keccak256(b"enc" + shared + self.secret
                                              + peer_nonce + self.my_nonce)
                    self.encrypts = True
                return
            # keyless side of a mixed pair: v1 keys from the v2/v3
            # hello's nonce (the keyed peer sees our v1 hello and,
            # when flagged, derives the same)
        elif data.startswith(self.MAGIC) and len(data) == len(self.MAGIC) + 16:
            peer_nonce = data[len(self.MAGIC):]
            if self.keypair is not None:
                if not self.allow_downgrade:
                    raise AuthError("v1 hello rejected (downgrade)")
                # keyed side of a mixed pair: fall back to v1
                self.keypair = None
        else:
            raise AuthError("bad hello")
        self.send_key = keccak256(self.secret + self.my_nonce + peer_nonce)
        self.recv_key = keccak256(self.secret + peer_nonce + self.my_nonce)

    def seal(self, payload: bytes) -> bytes:
        from eges_tpu.crypto.keccak import keccak256

        if self.encrypts:
            payload = _xor(payload, _keystream(self.send_enc,
                                               self.send_seq, len(payload)))
        mac = keccak256(self.send_key + self.send_seq.to_bytes(8, "big")
                        + payload)[:16]
        self.send_seq += 1
        return mac + payload

    def open(self, frame: bytes) -> bytes:
        import hmac as _hmac

        from eges_tpu.crypto.keccak import keccak256

        if len(frame) < 16:
            raise AuthError("short frame")
        mac, payload = frame[:16], frame[16:]
        want = keccak256(self.recv_key + self.recv_seq.to_bytes(8, "big")
                        + payload)[:16]
        if not _hmac.compare_digest(mac, want):  # constant-time compare
            raise AuthError("bad frame MAC")
        if self.encrypts:
            payload = _xor(payload, _keystream(self.recv_enc,
                                               self.recv_seq, len(payload)))
        self.recv_seq += 1
        return payload


class Protocol:
    """A named message-code space on the gossip plane (the p2p.Protocol
    / eth ProtocolManager role, ref: p2p/peer.go matchProtocols,
    eth/protocol.go:38-44 eth/62+63).

    ``versions`` is the full list this endpoint can speak; capability
    negotiation picks the highest version both ends offer — exactly how
    eth/62 and eth/63 co-exist in the reference.  ``codes`` is the set
    of frame codes the protocol owns; the mux refuses codes outside
    every negotiated protocol and scores the sender (ref: p2p/peer.go
    handle → DiscProtocolError)."""

    def __init__(self, name: str, versions: tuple[int, ...],
                 codes: frozenset[int] | set[int], handler):
        self.name = name
        self.versions = tuple(sorted(versions))
        self.codes = frozenset(codes)
        self.handler = handler


CAPS_MAGIC = b"geec-caps\x00"


def encode_caps(protocols: list[Protocol]) -> bytes:
    from eges_tpu.core import rlp

    return CAPS_MAGIC + rlp.encode(
        [[p.name.encode(), list(p.versions)] for p in protocols])


def decode_caps(data: bytes) -> dict[str, tuple[int, ...]]:
    from eges_tpu.core import rlp

    out: dict[str, tuple[int, ...]] = {}
    for entry in rlp.decode(data[len(CAPS_MAGIC):]):
        name = bytes(entry[0]).decode()
        out[name] = tuple(rlp.decode_uint(bytes(v)) for v in entry[1])
    return out


def shared_caps(mine: list[Protocol],
                theirs: dict[str, tuple[int, ...]]) -> dict[str, int]:
    """Highest mutually-offered version per protocol name."""
    shared: dict[str, int] = {}
    for p in mine:
        common = set(p.versions) & set(theirs.get(p.name, ()))
        if common:
            shared[p.name] = max(common)
    return shared


class _Session:
    """Per-connection state: auth layer, negotiated capabilities, and
    the misbehavior score (ref: p2p/peer.go per-peer protocol state)."""

    __slots__ = ("writer", "auth", "shared", "score", "dropped", "born")

    def __init__(self, writer, auth):
        self.writer = writer
        self.auth = auth
        self.shared: dict[str, int] | None = None  # None until caps
        #                                            frame (legacy peer:
        #                                            never arrives)
        self.score = 0
        self.dropped = False
        self.born = time.monotonic()


class GossipPlane:
    """Static-peer-list TCP gossip with 4-byte length-prefixed frames.

    Reconnects with backoff; sends are fire-and-forget like the
    reference's per-peer ``p2p.Send`` loops (eth/handler.go:1071-1080).
    With ``secret`` set, every connection runs the :class:`_FrameAuth`
    handshake — encrypted + MACed frames when keyed (the p2p/rlpx.go
    role, v3) — while ``secret=None`` keeps the plaintext wire for
    tests/local rigs.  ``version=2`` pins a keyed plane to the MAC-only
    generation (mixed-mode upgrades; pair with ``allow_v2_peers`` on
    the v3 side).

    With ``protocols`` set the plane runs the devp2p protocol-mux role:
    right after the transport handshake each side sends a capability
    frame listing its protocols' offered versions; frames then route by
    code to the owning protocol's handler, frames for un-negotiated or
    unknown codes raise the connection's misbehavior score, and a peer
    crossing :data:`MISBEHAVIOR_LIMIT` is disconnected (the reference's
    DiscProtocolError path).  Cap-less legacy peers interop: they are
    muxed against the full registered code set.
    """

    MAX_FRAME = 64 * 1024 * 1024
    MISBEHAVIOR_LIMIT = 100   # four strikes: protocol violations are
    #                           either a broken build or an attack, but
    #                           a one-off corrupt relay shouldn't sever

    def __init__(self, bind_ip: str, bind_port: int, peers: list[tuple[str, int]],
                 on_gossip, secret: bytes | None = None,
                 keypair: tuple[bytes, bytes] | None = None,
                 authorize=None, allow_v1_peers: bool = False,
                 allow_v2_peers: bool = False, version: int = 3,
                 protocols: list[Protocol] | None = None):
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.peers = [p for p in peers if p != (bind_ip, bind_port)]
        self._on_gossip = on_gossip
        self.secret = secret
        self.keypair = keypair if secret is not None else None
        self.authorize = authorize  # callable(addr20) -> bool, v2+ only
        self.allow_v1_peers = allow_v1_peers  # mixed-mode upgrades only
        self.allow_v2_peers = allow_v2_peers  # accept MAC-only peers
        self.version = version
        self.protocols = protocols
        self._code_to_proto: dict[int, Protocol] = {}
        for p in protocols or []:
            for c in p.codes:
                if c in self._code_to_proto:
                    raise ValueError("code %#x claimed twice" % c)
                self._code_to_proto[c] = p
        self._server: asyncio.AbstractServer | None = None
        self._writers: dict[tuple[str, int], _Session] = {}
        self._tasks: list[asyncio.Task] = []
        self._closed = False
        # dial + accept coroutines both bump this, but all of them run
        # on the plane's single asyncio loop — never concurrently
        self.auth_failures = 0  # guarded-by: event-loop
        self.peer_drops = 0       # misbehavior disconnects
        self._peer_gauge()  # register net.peer_count at 0

    def _peer_gauge(self) -> None:
        from eges_tpu.utils import metrics

        metrics.DEFAULT.gauge("net.peer_count").set(len(self._writers))

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.bind_ip, self.bind_port)
        for peer in self.peers:
            self._tasks.append(asyncio.create_task(self._dial_loop(peer)))

    def add_peer(self, peer: tuple[str, int]) -> None:
        """Dial a newly-discovered peer (the discovery plane feeds this);
        no-op for self or already-known peers."""
        if self._closed or peer == (self.bind_ip, self.bind_port):
            return
        if peer in self.peers:
            return
        self.peers.append(peer)
        # prune finished dial loops so re-homing churn (remove_peer /
        # add_peer cycles from discovery records) can't grow the task
        # list without bound over a long-lived node
        self._tasks = [t for t in self._tasks if not t.done()]
        self._tasks.append(asyncio.create_task(self._dial_loop(peer)))

    def remove_peer(self, peer: tuple[str, int]) -> None:
        """Stop dialing an endpoint a peer no longer lives at (a
        higher-seq discovery record re-homed it); closes any live
        connection so the dial loop winds down instead of redialing a
        dead address forever."""
        if peer not in self.peers:
            return
        self.peers.remove(peer)
        sess = self._writers.pop(peer, None)
        self._peer_gauge()
        if sess is not None:
            try:
                sess.writer.close()
            # analysis: allow-swallow(best-effort close of a possibly dead writer)
            except Exception:
                pass

    @staticmethod
    async def _read_frame(reader) -> bytes:
        hdr = await reader.readexactly(4)
        (n,) = struct.unpack("<I", hdr)
        if n > GossipPlane.MAX_FRAME:
            raise AuthError("oversized frame")
        return await reader.readexactly(n)

    @staticmethod
    def _frame(data: bytes) -> bytes:
        return struct.pack("<I", len(data)) + data

    async def _handshake(self, reader, writer) -> _Session:
        """Transport handshake + capability announcement; returns the
        connection's session (auth is None in plaintext mode)."""
        auth = None
        if self.secret is not None:
            auth = _FrameAuth(self.secret, keypair=self.keypair,
                              allow_downgrade=self.allow_v1_peers,
                              allow_v2=self.allow_v2_peers,
                              version=self.version)
            writer.write(self._frame(auth.hello()))
            await writer.drain()
            auth.on_hello(await asyncio.wait_for(self._read_frame(reader),
                                                 timeout=5.0))
            if (auth.peer_addr is not None and self.authorize is not None
                    and not self.authorize(auth.peer_addr)):
                raise AuthError("peer not authorized")
        sess = _Session(writer, auth)
        if self.protocols is not None:
            # first frame each way is the capability list (the devp2p
            # protocol handshake, ref: p2p/peer.go Hello/matchProtocols)
            caps = encode_caps(self.protocols)
            writer.write(self._frame(
                auth.seal(caps) if auth is not None else caps))
        return sess

    def _misbehave(self, sess: _Session, points: int) -> None:
        sess.score += points
        if sess.score >= self.MISBEHAVIOR_LIMIT and not sess.dropped:
            sess.dropped = True        # count ONE drop per connection,
            self.peer_drops += 1       # and stop dispatching its
            try:                       # already-buffered frames
                sess.writer.close()
            # analysis: allow-swallow(best-effort close of a misbehaving peer)
            except Exception:
                pass

    def _dispatch(self, sess: _Session, data: bytes) -> None:
        """Route one opened frame: caps handshake, then per-code mux."""
        if sess.dropped:
            return  # connection is being cut; drain without dispatching
        if data.startswith(CAPS_MAGIC):
            try:
                sess.shared = shared_caps(self.protocols or [],
                                          decode_caps(data))
            except Exception:
                self._misbehave(sess, 25)
            return
        if self.protocols is None:
            try:
                self._on_gossip(data)
            except Exception as exc:
                log.gdbug("gossip handler error", err=repr(exc))
            return
        from eges_tpu.core import rlp
        from eges_tpu.utils import tracing

        # peek past a possible trace header; handlers strip it themselves
        proto = self._code_to_proto.get(
            rlp.peek_first_uint(tracing.payload_of(data)))
        if proto is None:
            # a code outside every protocol we registered: out of
            # contract, score it (ref: p2p/peer.go invalid msg code)
            self._misbehave(sess, 25)
            return
        if sess.shared is not None and proto.name not in sess.shared:
            # a protocol WE speak but this pair didn't negotiate.  The
            # sender may legitimately not have our caps yet (its burst
            # can be in flight before our caps frame crosses), so this
            # is dropped, never scored — the negotiation race must not
            # cut honest mixed-version peers.
            return
        try:
            proto.handler(data)
        except Exception as exc:
            log.gdbug("protocol handler error", proto=proto.name,
                      err=repr(exc))

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            sess = await self._handshake(reader, writer)
            while True:
                frame = await self._read_frame(reader)
                if sess.auth is not None:
                    frame = sess.auth.open(frame)
                self._dispatch(sess, frame)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.TimeoutError):
            pass
        except AuthError:
            self.auth_failures += 1
        finally:
            writer.close()

    AUTH_RETRY_S = 60.0  # gate-rejected peers re-dial slowly: the
    #                      membership gate may admit them once they
    #                      register, but each attempt costs a full
    #                      ECDSA+ECDH handshake — not a transient error.
    #                      Two shapes of rejection: our own gate raises
    #                      AuthError (rejected below), and the REMOTE
    #                      gate just closes right after the handshake —
    #                      the dialer can't see why, so repeated
    #                      instant-closes escalate to the same slow
    #                      cadence.  Connection-refused (peer not up
    #                      yet: late joiners, restarts) never counts.

    async def _dial_loop(self, peer: tuple[str, int]) -> None:
        backoff = 0.2
        quick_closes = 0
        while not self._closed and peer in self.peers:
            rejected = False
            held = None
            try:
                reader, writer = await asyncio.open_connection(*peer)
                try:
                    sess = await self._handshake(reader, writer)
                except AuthError:
                    self.auth_failures += 1
                    rejected = True
                    raise ConnectionError
                self._writers[peer] = sess
                self._peer_gauge()
                t0 = time.monotonic()
                try:
                    # hold the connection, reading the acceptor's side
                    # of the stream: its capability frame arrives here
                    # (writer errors still surface on send).  The
                    # timeout wraps ONLY the 4-byte header read —
                    # readexactly is buffer-atomic, so a timed-out
                    # header consumes nothing, while a timeout spanning
                    # header+body could cancel between them and
                    # permanently desync the framing.  Once a header
                    # is committed the body read runs untimed; a stall
                    # mid-frame ends via remove_peer/close() closing
                    # the transport under it.
                    while not writer.is_closing() and not self._closed \
                            and peer in self.peers:
                        try:
                            hdr = await asyncio.wait_for(
                                reader.readexactly(4), timeout=0.5)
                        except asyncio.TimeoutError:
                            continue
                        (n,) = struct.unpack("<I", hdr)
                        if n > self.MAX_FRAME:
                            raise AuthError("oversized frame")
                        frame = await reader.readexactly(n)
                        if sess.auth is not None:
                            frame = sess.auth.open(frame)
                        self._dispatch(sess, frame)
                except (asyncio.IncompleteReadError, AuthError):
                    pass  # remote closed or broke framing: reconnect
                finally:
                    held = time.monotonic() - t0
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
            self._writers.pop(peer, None)
            self._peer_gauge()
            if held is not None and held >= 2.0:
                backoff, quick_closes = 0.2, 0  # was a real connection
            elif held is not None:
                quick_closes += 1
            await asyncio.sleep(
                self.AUTH_RETRY_S if rejected or quick_closes >= 3
                else backoff)
            backoff = min(backoff * 2, 5.0)

    CAPS_GRACE_S = 1.0  # how long a fresh session may lack the peer's
    #                     caps frame before we treat it as legacy.  In
    #                     devp2p no protocol msg flows before the Hello
    #                     exchange completes; holding broadcasts for
    #                     this window is the same ordering, and keeps a
    #                     mixed-version peer from scoring our burst as
    #                     misbehavior before it could tell us its caps.

    def broadcast(self, data: bytes) -> None:
        proto = None
        if self.protocols is not None:
            from eges_tpu.core import rlp
            from eges_tpu.utils import tracing

            proto = self._code_to_proto.get(
                rlp.peek_first_uint(tracing.payload_of(data)))
        now = time.monotonic()
        for peer, sess in list(self._writers.items()):
            if proto is not None and sess.shared is None \
                    and now - sess.born < self.CAPS_GRACE_S:
                continue  # caps still in flight; gossip retries cover it
            if (proto is not None and sess.shared is not None
                    and proto.name not in sess.shared):
                continue  # peer never negotiated this protocol — the
                #           reference sends eth msgs only to eth peers
            try:
                payload = (sess.auth.seal(data)
                           if sess.auth is not None else data)
                sess.writer.write(self._frame(payload))
            except Exception:
                self._writers.pop(peer, None)
                self._peer_gauge()

    def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for sess in self._writers.values():
            sess.writer.close()
        if self._server is not None:
            self._server.close()


class SocketTransport:
    """The Transport interface GeecNode expects, over the two planes."""

    def __init__(self, gossip: GossipPlane, direct: DirectPlane):
        self._gossip = gossip
        self._direct = direct

    def gossip(self, data: bytes) -> None:
        from eges_tpu.utils import tracing
        from eges_tpu.utils.metrics import DEFAULT as metrics
        data = tracing.inject_current(data)
        metrics.counter("net.gossip_bytes").inc(len(data))
        metrics.counter("net.gossip_msgs").inc()
        self._gossip.broadcast(data)

    def send_direct(self, ip: str, port: int, data: bytes) -> None:
        from eges_tpu.utils import tracing
        from eges_tpu.utils.metrics import DEFAULT as metrics
        data = tracing.inject_current(data)
        metrics.counter("net.direct_bytes").inc(len(data))
        metrics.counter("net.direct_msgs").inc()
        self._direct.send(ip, port, data)


class GeecTxnService:
    """UDP transaction-ingest API: every datagram on ``--geecTxnPort``
    becomes an unsigned Geec transaction (ref: consensus/geec/geec_api.go:11)."""

    def __init__(self, bind_ip: str, port: int, on_txn_payload):
        self.bind_ip = bind_ip
        self.port = port
        self._on_txn = on_txn_payload
        self._transport = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self._on_txn),
            local_addr=(self.bind_ip, self.port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
