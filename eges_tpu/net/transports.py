"""Real-socket network planes (asyncio).

The production counterpart of the simulator transports, mirroring the
reference's two planes (SURVEY §2.3):

* **Direct plane** — UDP datagrams, RLP payloads, exactly like the
  reference's election/reply sockets (consensus/geec/election/server.go
  binds ``--consensusPort``; replies dial ``ip:port`` from the request).
* **Gossip plane** — persistent TCP connections to a static peer list
  with length-prefixed frames.  The reference runs RLPx-encrypted devp2p
  here (p2p/rlpx.go); the RLPx-parity role in this permissioned design
  is an authenticated handshake + per-frame keyed MAC (see
  :class:`GossipPlane` with a ``secret``): nonce exchange derives
  per-direction session keys from a network secret, every frame carries
  a 16-byte keccak-MAC over (key, sequence, payload), and unauthentic
  or replayed frames drop the connection.  Confidentiality is NOT
  provided (consensus traffic is not secret in a permissioned
  deployment); authenticity and network isolation are.

Everything runs on one asyncio loop; inbound messages call straight into
the single-threaded :class:`~eges_tpu.consensus.node.GeecNode`, so the
no-locks design of the state machines carries over unchanged.
"""

from __future__ import annotations

import asyncio
import struct


class AsyncioClock:
    """Clock interface over the running asyncio loop."""

    def __init__(self, loop: asyncio.AbstractEventLoop | None = None):
        self._loop = loop or asyncio.get_event_loop()

    def now(self) -> float:
        return self._loop.time()

    def call_later(self, delay_s: float, fn):
        return self._loop.call_later(delay_s, fn)  # TimerHandle has .cancel()


class _UdpProtocol(asyncio.DatagramProtocol):
    def __init__(self, on_datagram):
        self._on_datagram = on_datagram

    def datagram_received(self, data, addr):
        try:
            self._on_datagram(data)
        except Exception:
            pass  # one bad datagram must not kill the receive loop


class DirectPlane:
    """UDP send/receive for election messages and validate/query replies."""

    def __init__(self, bind_ip: str, bind_port: int, on_direct):
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self._on_direct = on_direct
        self._transport: asyncio.DatagramTransport | None = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self._on_direct),
            local_addr=(self.bind_ip, self.bind_port))

    def send(self, ip: str, port: int, data: bytes) -> None:
        if self._transport is not None:
            self._transport.sendto(data, (ip, port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()


class AuthError(Exception):
    """Peer failed the gossip-plane handshake or sent a bad MAC."""


class _FrameAuth:
    """Per-connection frame authentication (the RLPx-parity layer).

    Handshake: each side sends ``MAGIC || nonce16``; both derive
    per-direction session keys ``keccak(secret || sender_nonce ||
    receiver_nonce)``.  Every frame then carries
    ``keccak(key || seq_be8 || payload)[:16]`` with a per-direction
    monotonically increasing sequence — a wrong network secret, a
    tampered payload, or a replayed/reordered frame all fail the check.
    (A keccak prefix-MAC is sound: sponge constructions are not subject
    to the length-extension attacks that force HMAC on SHA-2.)"""

    MAGIC = b"geec-gossip-v1\x00\x00"

    def __init__(self, secret: bytes):
        import secrets as _secrets

        self.secret = secret
        self.my_nonce = _secrets.token_bytes(16)
        self.send_key = b""
        self.recv_key = b""
        self.send_seq = 0
        self.recv_seq = 0

    def hello(self) -> bytes:
        return self.MAGIC + self.my_nonce

    def on_hello(self, data: bytes) -> None:
        from eges_tpu.crypto.keccak import keccak256

        if len(data) != len(self.MAGIC) + 16 or not data.startswith(self.MAGIC):
            raise AuthError("bad hello")
        peer_nonce = data[len(self.MAGIC):]
        self.send_key = keccak256(self.secret + self.my_nonce + peer_nonce)
        self.recv_key = keccak256(self.secret + peer_nonce + self.my_nonce)

    def seal(self, payload: bytes) -> bytes:
        from eges_tpu.crypto.keccak import keccak256

        mac = keccak256(self.send_key + self.send_seq.to_bytes(8, "big")
                        + payload)[:16]
        self.send_seq += 1
        return mac + payload

    def open(self, frame: bytes) -> bytes:
        import hmac as _hmac

        from eges_tpu.crypto.keccak import keccak256

        if len(frame) < 16:
            raise AuthError("short frame")
        mac, payload = frame[:16], frame[16:]
        want = keccak256(self.recv_key + self.recv_seq.to_bytes(8, "big")
                        + payload)[:16]
        if not _hmac.compare_digest(mac, want):  # constant-time compare
            raise AuthError("bad frame MAC")
        self.recv_seq += 1
        return payload


class GossipPlane:
    """Static-peer-list TCP gossip with 4-byte length-prefixed frames.

    Reconnects with backoff; sends are fire-and-forget like the
    reference's per-peer ``p2p.Send`` loops (eth/handler.go:1071-1080).
    With ``secret`` set, every connection runs the :class:`_FrameAuth`
    handshake and per-frame MAC (the p2p/rlpx.go role); ``secret=None``
    keeps the plaintext wire for tests/local rigs.
    """

    MAX_FRAME = 64 * 1024 * 1024

    def __init__(self, bind_ip: str, bind_port: int, peers: list[tuple[str, int]],
                 on_gossip, secret: bytes | None = None):
        self.bind_ip = bind_ip
        self.bind_port = bind_port
        self.peers = [p for p in peers if p != (bind_ip, bind_port)]
        self._on_gossip = on_gossip
        self.secret = secret
        self._server: asyncio.AbstractServer | None = None
        self._writers: dict[tuple[str, int], tuple] = {}  # peer -> (writer, auth)
        self._tasks: list[asyncio.Task] = []
        self._closed = False
        self.auth_failures = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.bind_ip, self.bind_port)
        for peer in self.peers:
            self._tasks.append(asyncio.create_task(self._dial_loop(peer)))

    @staticmethod
    async def _read_frame(reader) -> bytes:
        hdr = await reader.readexactly(4)
        (n,) = struct.unpack("<I", hdr)
        if n > GossipPlane.MAX_FRAME:
            raise AuthError("oversized frame")
        return await reader.readexactly(n)

    @staticmethod
    def _frame(data: bytes) -> bytes:
        return struct.pack("<I", len(data)) + data

    async def _handshake(self, reader, writer):
        """Returns a ready _FrameAuth, or None in plaintext mode."""
        if self.secret is None:
            return None
        auth = _FrameAuth(self.secret)
        writer.write(self._frame(auth.hello()))
        await writer.drain()
        auth.on_hello(await asyncio.wait_for(self._read_frame(reader),
                                             timeout=5.0))
        return auth

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            auth = await self._handshake(reader, writer)
            while True:
                frame = await self._read_frame(reader)
                if auth is not None:
                    frame = auth.open(frame)
                try:
                    self._on_gossip(frame)
                except Exception:
                    pass
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.TimeoutError):
            pass
        except AuthError:
            self.auth_failures += 1
        finally:
            writer.close()

    async def _dial_loop(self, peer: tuple[str, int]) -> None:
        backoff = 0.2
        while not self._closed:
            try:
                reader, writer = await asyncio.open_connection(*peer)
                try:
                    auth = await self._handshake(reader, writer)
                except AuthError:
                    self.auth_failures += 1
                    raise ConnectionError
                self._writers[peer] = (writer, auth)
                backoff = 0.2
                # hold the connection; writer errors surface on send
                while not writer.is_closing() and not self._closed:
                    await asyncio.sleep(0.5)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
            self._writers.pop(peer, None)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 5.0)

    def broadcast(self, data: bytes) -> None:
        for peer, (writer, auth) in list(self._writers.items()):
            try:
                payload = auth.seal(data) if auth is not None else data
                writer.write(self._frame(payload))
            except Exception:
                self._writers.pop(peer, None)

    def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for w, _ in self._writers.values():
            w.close()
        if self._server is not None:
            self._server.close()


class SocketTransport:
    """The Transport interface GeecNode expects, over the two planes."""

    def __init__(self, gossip: GossipPlane, direct: DirectPlane):
        self._gossip = gossip
        self._direct = direct

    def gossip(self, data: bytes) -> None:
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("net.gossip_bytes").inc(len(data))
        metrics.counter("net.gossip_msgs").inc()
        self._gossip.broadcast(data)

    def send_direct(self, ip: str, port: int, data: bytes) -> None:
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("net.direct_bytes").inc(len(data))
        metrics.counter("net.direct_msgs").inc()
        self._direct.send(ip, port, data)


class GeecTxnService:
    """UDP transaction-ingest API: every datagram on ``--geecTxnPort``
    becomes an unsigned Geec transaction (ref: consensus/geec/geec_api.go:11)."""

    def __init__(self, bind_ip: str, port: int, on_txn_payload):
        self.bind_ip = bind_ip
        self.port = port
        self._on_txn = on_txn_payload
        self._transport = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _UdpProtocol(self._on_txn),
            local_addr=(self.bind_ip, self.port))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
