"""Signed node records (the p2p/enr role, EIP-778 shaped).

The reference carries node identity + endpoint claims as Ethereum Node
Records (ref: p2p/enr/enr.go — RLP ``[sig, seq, k, v, ...]`` with
strictly sorted keys, a sequence number bumped on every change, and a
secp256k1 signature over the content).  This is the same design with
two deliberate divergences, both documented here:

- the signature is the 65-byte *recoverable* form our whole stack uses
  (ref uses 64-byte compact + a mandatory ``secp256k1`` pair to carry
  the pubkey; with recovery the identity is derivable from the
  signature itself, so the pubkey pair is optional redundancy), and
- the identity scheme tag is ``gv4`` to mark that difference on the
  wire.

Records ride the discovery plane (net/discovery.py codes 4-6): a node
announces its record, the bootnode keeps the highest-``seq`` copy per
identity, and lookups return full verified records so joiners learn
endpoints from a *signed* statement by the peer itself rather than
from whatever the bootnode claims.

Well-known pairs (all optional except ``id``):
    id     -> b"gv4"           identity scheme (required, checked)
    ip     -> 4-byte IPv4      gossip/consensus address
    tcp    -> uint             gossip (TCP) port
    udp    -> uint             consensus (UDP) port
    cip    -> 4-byte IPv4      consensus address, when != ip
    secp256k1 -> 64-byte pub   optional redundant pubkey (checked
                               against the recovered signer if present)
"""

from __future__ import annotations

import socket
import struct

from eges_tpu.core import rlp
from eges_tpu.crypto.keccak import keccak256

ID_SCHEME = b"gv4"
MAX_RECORD_SIZE = 300  # ref p2p/enr/enr.go SizeLimit


class ENRError(ValueError):
    pass


def _content(seq: int, pairs: dict[bytes, bytes]) -> list:
    items: list = [seq]
    for k in sorted(pairs):
        items.append(k)
        items.append(pairs[k])
    return items


def ip_to_bytes(ip: str) -> bytes:
    return socket.inet_aton(ip)


def ip_from_bytes(b: bytes) -> str:
    if len(b) != 4:
        raise ENRError("bad ip length")
    return socket.inet_ntoa(b)


class Record:
    """An immutable, signature-verified node record."""

    def __init__(self, seq: int, pairs: dict[bytes, bytes],
                 signature: bytes, signer: bytes):
        self.seq = seq
        self.pairs = dict(pairs)
        self.signature = signature
        self.addr = signer  # 20-byte identity derived from the signature

    # -- construction -----------------------------------------------------

    @classmethod
    def sign(cls, priv: bytes, seq: int, *, ip: str | None = None,
             tcp: int | None = None, udp: int | None = None,
             cip: str | None = None,
             extra: dict[bytes, bytes] | None = None) -> "Record":
        from eges_tpu.crypto import secp256k1 as secp

        pairs: dict[bytes, bytes] = {b"id": ID_SCHEME}
        if ip is not None:
            pairs[b"ip"] = ip_to_bytes(ip)
        if cip is not None and cip != ip:
            pairs[b"cip"] = ip_to_bytes(cip)
        if tcp is not None:
            pairs[b"tcp"] = _uint(tcp)
        if udp is not None:
            pairs[b"udp"] = _uint(udp)
        if extra:
            pairs.update(extra)
        pairs = {k: v for k, v in pairs.items() if v != b""}
        h = keccak256(rlp.encode(_content(seq, pairs)))
        sig = secp.ecdsa_sign(h, priv)
        signer = secp.pubkey_to_address(secp.privkey_to_pubkey(priv))
        rec = cls(seq, pairs, sig, signer)
        if len(rec.encode()) > MAX_RECORD_SIZE:
            raise ENRError("record exceeds %d bytes" % MAX_RECORD_SIZE)
        return rec

    def encode(self) -> bytes:
        return rlp.encode([self.signature] + _content(self.seq, self.pairs))

    @classmethod
    def decode(cls, data: bytes) -> "Record":
        from eges_tpu.crypto import secp256k1 as secp

        if len(data) > MAX_RECORD_SIZE:
            raise ENRError("oversize record")
        try:
            items = rlp.decode(data)
        except Exception as e:
            raise ENRError("bad rlp: %s" % e) from None
        if not isinstance(items, list) or len(items) < 2 or len(items) % 2:
            raise ENRError("bad record shape")
        # everything below handles attacker-shaped input: nested lists
        # where byte strings belong, non-canonical ints, wrong-length
        # values — all must surface as ENRError, never TypeError, so
        # every caller's `except ENRError` guard is airtight
        try:
            sig = bytes(items[0])
            seq = rlp.decode_uint(bytes(items[1]))
            pairs: dict[bytes, bytes] = {}
            prev = None
            for i in range(2, len(items), 2):
                k = bytes(items[i])
                if prev is not None and k <= prev:
                    raise ENRError("keys not strictly sorted")
                prev = k
                pairs[k] = bytes(items[i + 1])
        except ENRError:
            raise
        except Exception as e:
            raise ENRError("malformed record: %s" % e) from None
        if pairs.get(b"id") != ID_SCHEME:
            raise ENRError("unknown identity scheme")
        for key in (b"ip", b"cip"):
            if key in pairs and len(pairs[key]) != 4:
                raise ENRError("bad %s length" % key.decode())
        for key in (b"tcp", b"udp"):
            if key in pairs and int.from_bytes(pairs[key], "big") > 0xFFFF:
                raise ENRError("bad %s port" % key.decode())
        h = keccak256(rlp.encode(_content(seq, pairs)))
        try:
            signer = secp.recover_address(h, sig)
        except Exception:
            raise ENRError("unrecoverable signature") from None
        if b"secp256k1" in pairs:
            redundant = secp.pubkey_to_address(pairs[b"secp256k1"])
            if redundant != signer:
                raise ENRError("secp256k1 pair does not match signer")
        return cls(seq, pairs, sig, signer)

    # -- accessors --------------------------------------------------------

    def ip(self) -> str | None:
        b = self.pairs.get(b"ip")
        return ip_from_bytes(b) if b else None

    def consensus_ip(self) -> str | None:
        b = self.pairs.get(b"cip")
        return ip_from_bytes(b) if b else self.ip()

    def tcp(self) -> int | None:
        b = self.pairs.get(b"tcp")
        return int.from_bytes(b, "big") if b is not None else None

    def udp(self) -> int | None:
        b = self.pairs.get(b"udp")
        return int.from_bytes(b, "big") if b is not None else None

    def gossip_endpoint(self) -> tuple[str, int] | None:
        ip, port = self.ip(), self.tcp()
        return (ip, port) if ip and port else None

    def consensus_endpoint(self) -> tuple[str, int] | None:
        ip, port = self.consensus_ip(), self.udp()
        return (ip, port) if ip and port else None

    def __eq__(self, other) -> bool:
        return (isinstance(other, Record) and self.seq == other.seq
                and self.pairs == other.pairs
                and self.signature == other.signature)

    def __repr__(self) -> str:
        return "Record(addr=%s seq=%d %s)" % (
            self.addr.hex()[:8], self.seq,
            ",".join(k.decode() for k in sorted(self.pairs)))


_uint = rlp.encode_uint
