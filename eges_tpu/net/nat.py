"""External-address resolution (the p2p/nat role).

The reference resolves the address a node should *advertise* — as
opposed to the one it binds — through a pluggable NAT interface
selected by ``--nat`` (ref: p2p/nat/nat.go Parse: "none", "any",
"extip:<ip>", "upnp", "pmp").  The protocol-speaking traversal modes
(UPnP/NAT-PMP) assume consumer gateways; a permissioned committee
deployment pins addresses in config instead, so here those modes are
explicit unsupported errors rather than silent fallbacks, and "auto"
resolves the host's primary outbound interface locally:

    none           advertise the bind address unchanged
    extip:<ip>     advertise exactly <ip> (static NAT / public VIP)
    auto | any     advertise the primary outbound interface address,
                   discovered via a connected UDP socket (no packet is
                   sent — connect() on a datagram socket only selects
                   the route)

``resolve(spec, bind_ip)`` is the single entry point the node CLI
uses: it returns the IP to put in the signed node record.
"""

from __future__ import annotations

import socket


class NATError(ValueError):
    pass


class NAT:
    """Resolved advertisement policy."""

    def __init__(self, mode: str, extip: str | None = None):
        self.mode = mode
        self.extip = extip

    def external_ip(self, bind_ip: str) -> str:
        if self.mode == "none":
            return bind_ip
        if self.mode == "extip":
            return self.extip  # type: ignore[return-value]
        # auto: route-table lookup via an unconnected-send-free socket
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(1.0)  # connect() on UDP is local-only, but be safe
        try:
            s.connect(("192.0.2.1", 9))  # TEST-NET-1: never dialed
            ip = s.getsockname()[0]
        except OSError:
            ip = bind_ip
        finally:
            s.close()
        # a host with no route at all answers 0.0.0.0 — fall back
        return ip if ip != "0.0.0.0" else bind_ip


def parse(spec: str) -> NAT:
    spec = (spec or "none").strip().lower()
    if spec == "none":
        return NAT("none")
    if spec in ("auto", "any"):
        return NAT("auto")
    if spec.startswith("extip:"):
        ip = spec[len("extip:"):]
        try:
            socket.inet_aton(ip)
        except OSError:
            raise NATError("bad extip address: %r" % ip) from None
        return NAT("extip", ip)
    if spec in ("upnp", "pmp"):
        raise NATError(
            "%s is not supported in a pinned-address deployment; "
            "use extip:<ip> or auto" % spec)
    raise NATError("unknown nat spec: %r" % spec)


def resolve(spec: str, bind_ip: str) -> str:
    return parse(spec).external_ip(bind_ip)
