"""Deterministic fault injection over the simulated cluster.

The reference repo drove robustness testing with three shell scripts —
``kill.py`` (SIGKILL a geth by index), ``re-start.py`` (relaunch it on
the surviving datadir) and ``start.py`` (cluster bring-up) — run by hand
against a real cluster while ``grep.py`` scraped the logs.  This module
is that workflow made deterministic and composable: a :class:`FaultPlan`
is a timestamped script of fault actions, and a :class:`FaultInjector`
arms it against a :class:`~eges_tpu.sim.cluster.SimCluster` on the
virtual clock, so an entire kill/partition/corruption storm replays
bit-identically from its seed.

Actions (all virtual-time stamped, freely composable):

* ``crash`` / ``restart`` — tear a node down and rebuild it from its
  surviving chain (the GeecNode constructor replay path — the
  ``re-start.py`` analogue);
* ``partition`` / ``heal`` — symmetric cut of both planes;
* ``block_link`` / ``heal_link`` / ``set_link`` — ONE direction of a
  link (``A -> B`` drops while ``B -> A`` flows), with per-link
  loss/latency/corruption/duplication/reorder overrides;
* ``set_net`` — net-wide loss/jitter/corruption/duplication/reorder;
* ``skew`` — offset one node's local oscillator;
* ``kill_leader`` — a leader-targeted trigger: watch every node's
  journal for ``election_won`` and crash the winner the moment the
  event lands (optionally restarting it a fixed delay later).

Every executed action is recorded in the injector's own journal (the
synthetic ``faults`` node in ``SimCluster.journals()``) so the
observatory renders the fault timeline next to the consensus events it
caused.
"""

from __future__ import annotations

from eges_tpu.utils.journal import Journal

#: action kinds a FaultPlan accepts (anything else raises at add time,
#: mirroring the journal's closed event vocabulary)
ACTION_KINDS = frozenset({
    "crash", "restart", "partition", "heal", "block_link", "heal_link",
    "set_link", "set_net", "skew", "kill_leader",
})


class FaultPlan:
    """A timestamped, composable script of fault actions.

    Builder-style: every method returns ``self`` so plans read as one
    chained scenario description::

        plan = (FaultPlan()
                .set_net(2.0, drop_rate=0.2, jitter_s=0.05)
                .block_link(2.0, "node2", "node1")
                .kill_leader(1.0, restart_after=20.0)
                .heal_all(90.0))
    """

    def __init__(self):
        self.actions: list[tuple[float, int, str, dict]] = []

    def add(self, t: float, kind: str, **kw) -> "FaultPlan":
        if kind not in ACTION_KINDS:
            raise ValueError(f"unknown fault action kind: {kind!r}")
        # (t, insertion-seq) keys give same-timestamp actions a stable,
        # scripted order — determinism must not hinge on sort stability
        self.actions.append((float(t), len(self.actions), kind, kw))
        return self

    # -- sugar ----------------------------------------------------------

    def crash(self, t: float, node: str) -> "FaultPlan":
        return self.add(t, "crash", node=node)

    def restart(self, t: float, node: str) -> "FaultPlan":
        return self.add(t, "restart", node=node)

    def partition(self, t: float, node: str) -> "FaultPlan":
        return self.add(t, "partition", node=node)

    def heal(self, t: float, node: str) -> "FaultPlan":
        return self.add(t, "heal", node=node)

    def block_link(self, t: float, src: str, dst: str) -> "FaultPlan":
        return self.add(t, "block_link", src=src, dst=dst)

    def heal_link(self, t: float, src: str, dst: str) -> "FaultPlan":
        return self.add(t, "heal_link", src=src, dst=dst)

    def set_link(self, t: float, src: str, dst: str, **ov) -> "FaultPlan":
        return self.add(t, "set_link", src=src, dst=dst, overrides=ov)

    def set_net(self, t: float, **fields) -> "FaultPlan":
        return self.add(t, "set_net", fields=fields)

    def skew(self, t: float, node: str, skew_s: float) -> "FaultPlan":
        return self.add(t, "skew", node=node, skew_s=skew_s)

    def kill_leader(self, t: float, times: int = 1,
                    restart_after: float | None = None) -> "FaultPlan":
        """Arm the leader-targeted trigger at ``t``: the next ``times``
        ``election_won`` events each get their winner crashed on the
        spot; ``restart_after`` (seconds after the kill) brings each
        victim back via the restart-replay path."""
        return self.add(t, "kill_leader", times=times,
                        restart_after=restart_after)

    def heal_all(self, t: float) -> "FaultPlan":
        """Clear every net-wide knob, link rule and partition at ``t`` —
        the "then heal" step every recovery scenario ends with."""
        return self.add(t, "set_net", fields={
            "drop_rate": 0.0, "corrupt_rate": 0.0, "duplicate_rate": 0.0,
            "reorder_rate": 0.0}).add(t, "heal_link", src=None, dst=None) \
            .add(t, "heal", node=None)


class FaultInjector:
    """Arms a :class:`FaultPlan` against a live :class:`SimCluster`.

    All actions execute as virtual-clock callbacks; the injector's own
    :class:`~eges_tpu.utils.journal.Journal` (registered as
    ``cluster.fault_journal``) records one ``fault_*`` event per
    executed action, timestamped in virtual time, so two same-seed runs
    dump byte-identical fault timelines.
    """

    def __init__(self, cluster, journal: Journal | None = None):
        self.cluster = cluster
        self.journal = journal or Journal(node="faults",
                                          clock=cluster.clock.now)
        cluster.fault_journal = self.journal
        self._idx = {sn.name: i for i, sn in enumerate(cluster.nodes)}
        # node journals are keyed by coinbase prefix, sim nodes by name
        self._by_journal = {sn.addr.hex()[:8]: i
                            for i, sn in enumerate(cluster.nodes)}
        # leader-kill trigger state
        self._kill_budget = 0
        self._kill_restart_after: float | None = None
        self._armed = False
        self.fired: list[dict] = []   # executed actions, for tests

    # -- plan scheduling ------------------------------------------------

    def apply(self, plan: FaultPlan) -> None:
        """Schedule every plan action on the cluster's virtual clock
        (times are absolute virtual seconds; past times fire on the next
        tick)."""
        now = self.cluster.clock.now()
        for t, _seq, kind, kw in sorted(plan.actions,
                                        key=lambda a: (a[0], a[1])):
            self.cluster.clock.call_later(
                max(t - now, 0.0),
                (lambda k, a: lambda: self._fire(k, a))(kind, kw))

    def fire_now(self, kind: str, **kw) -> None:
        """Execute one action immediately (block-driven scenarios that
        cannot pre-compute the virtual time of a phase change, e.g.
        "heal once the TTL actually expired").  Journaled and counted
        exactly like a scheduled action."""
        self._fire(kind, kw)

    def _fire(self, kind: str, kw: dict) -> None:
        from eges_tpu.utils.metrics import DEFAULT as metrics

        getattr(self, "_do_" + kind)(**kw)
        metrics.counter("sim.faults_injected").inc()
        self.fired.append({"t": self.cluster.clock.now(),
                           "kind": kind, **kw})

    # -- actions --------------------------------------------------------

    def _do_crash(self, node: str) -> None:
        i = self._idx[node]
        if self.cluster.nodes[i].crashed:
            return
        self.journal.record("fault_crash", target=node)
        self.cluster.crash(i)

    def _do_restart(self, node: str) -> None:
        i = self._idx[node]
        if not self.cluster.nodes[i].crashed:
            return
        self.journal.record("fault_restart", target=node)
        self.cluster.restart(i)
        if self._armed:
            # the rebuilt node has a fresh journal: re-attach the
            # leader-kill tap or its next election win goes unseen
            self.cluster.nodes[i].node.journal.on_record = self._tap

    def _do_partition(self, node: str) -> None:
        self.journal.record("fault_partition", target=node)
        self.cluster.net.partition(node)

    def _do_heal(self, node: str | None) -> None:
        names = ([node] if node is not None
                 else sorted(self.cluster.net._partitioned))
        for name in names:
            self.journal.record("fault_heal", target=name)
            self.cluster.net.heal(name)

    def _do_block_link(self, src: str, dst: str) -> None:
        self.journal.record("fault_link", src=src, dst=dst, change="block")
        self.cluster.net.block_link(src, dst)

    def _do_heal_link(self, src: str | None, dst: str | None) -> None:
        if src is None or dst is None:
            # heal_all leg: drop every rule
            for s, d in sorted(self.cluster.net._links):
                self.journal.record("fault_link", src=s, dst=d,
                                    change="clear")
                self.cluster.net.clear_link(s, d)
            return
        self.journal.record("fault_link", src=src, dst=dst, change="clear")
        self.cluster.net.clear_link(src, dst)

    def _do_set_link(self, src: str, dst: str, overrides: dict) -> None:
        self.journal.record("fault_link", src=src, dst=dst, change="set",
                            **{k: v for k, v in sorted(overrides.items())})
        self.cluster.net.set_link(src, dst, **overrides)

    def _do_set_net(self, fields: dict) -> None:
        net = self.cluster.net
        for k in fields:
            if not hasattr(net, k) or k.startswith("_"):
                raise TypeError(f"unknown net field: {k!r}")
        self.journal.record("fault_net",
                            **{k: v for k, v in sorted(fields.items())})
        for k, v in fields.items():
            setattr(net, k, v)

    def _do_skew(self, node: str, skew_s: float) -> None:
        i = self._idx[node]
        self.journal.record("fault_skew", target=node, skew_s=skew_s)
        self.cluster.nodes[i].clock.skew_s = skew_s

    def _do_kill_leader(self, times: int,
                        restart_after: float | None) -> None:
        self._kill_budget += times
        self._kill_restart_after = restart_after
        self.journal.record("fault_trigger", event="armed",
                            kills=times, restart_after=restart_after)
        if not self._armed:
            self._armed = True
            for sn in self.cluster.live_nodes():
                sn.node.journal.on_record = self._tap

    # -- leader-targeted trigger ----------------------------------------

    def _tap(self, ev: dict) -> None:
        """Journal tap (runs inside the winning node's record call):
        schedule the kill for the next clock tick — tearing a node down
        from inside its own election handler would be reentrant."""
        if ev.get("type") != "election_won" or self._kill_budget <= 0:
            return
        i = self._by_journal.get(ev.get("node"))
        if i is None or self.cluster.nodes[i].crashed:
            return
        self._kill_budget -= 1
        name = self.cluster.nodes[i].name
        self.journal.record("fault_trigger", event="leader_kill",
                            target=name, blk=ev.get("blk"))
        self.cluster.clock.call_later(
            0.0, (lambda n: lambda: self._fire("crash", {"node": n}))(name))
        if self._kill_restart_after is not None:
            self.cluster.clock.call_later(
                self._kill_restart_after,
                (lambda n: lambda: self._fire("restart",
                                              {"node": n}))(name))
