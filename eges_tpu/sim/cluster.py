"""Simulated Geec cluster builder.

The in-process analogue of the reference's ``test.py`` local 3-node
harness (ref: test.py:1-138 — bootnode + N geth processes on distinct
ports) with deterministic keys, virtual time, and direct access to every
node's state.  Used by the consensus test-suite and by liveness/soak
checks (the ``test-sep-2.sh`` criterion: chain keeps advancing).
"""

from __future__ import annotations

from dataclasses import dataclass

from eges_tpu.consensus.config import BootstrapNode, ChainGeecConfig, NodeConfig
from eges_tpu.consensus.node import GeecNode
from eges_tpu.core.chain import BlockChain, make_genesis
from eges_tpu.crypto import secp256k1 as secp
from eges_tpu.ingress import columns_of, direct_sink, gossip_sink
from eges_tpu.sim.simnet import SimClock, SimNet, SkewedClock


@dataclass
class SimNode:
    name: str
    priv: bytes
    addr: bytes
    chain: BlockChain
    node: GeecNode
    clock: SkewedClock = None   # per-node (skewable) view of the clock
    crashed: bool = False


class SimCluster:
    def __init__(self, n_nodes: int = 3, *, n_bootstrap: int | None = None,
                 seed: int = 0, n_candidates: int = 3, n_acceptors: int = 4,
                 txn_per_block: int = 10, txn_size: int = 100,
                 block_timeout_s: float = 20.0, validate_timeout_ms: float = 500,
                 backoff_time_ms: float = 0.0, reg_timeout_s: float = 10.0,
                 drop_rate: float = 0.0, failure_test: bool = False,
                 verifier=None, mine=None, signed: bool = True,
                 alloc: dict | None = None, txpool: bool = False,
                 fast_sync: set | None = None, defer: set | None = None,
                 mesh_devices: int | None = None, sched_config=None,
                 columnar: bool = True, checkpoint_every: int = 0):
        self.clock = SimClock()
        self.net = SimNet(self.clock, seed=seed, drop_rate=drop_rate)
        self.nodes: list[SimNode] = []

        # mesh_devices builds an N-lane virtual mesh of host verifiers
        # (JAX-free), so sims and chaos runs exercise the scheduler's
        # per-device window lanes without an accelerator
        if verifier is None and mesh_devices:
            from eges_tpu.crypto.verify_host import NativeMeshVerifier
            verifier = NativeMeshVerifier(mesh_devices)

        # every node shares ONE coalescing scheduler + recovery cache
        # around the supplied verifier (crypto/scheduler.py): the same
        # vote signature verified by N sim nodes costs one device row
        # and N-1 cache hits.  A mesh verifier (device_targets()) makes
        # that shared scheduler a mesh dispatcher — one window lane per
        # device, shared by every sim node.  verifier=None (host
        # fallback) passes through untouched.
        # sched_config (a crypto.scheduler.SchedulerConfig) pins the
        # shared scheduler's knobs for this cluster — chaos scenarios
        # use it to enable adaptive windowing / hedging with the sim's
        # deterministic flush discipline instead of env overrides
        from eges_tpu.crypto.scheduler import scheduler_for
        kw = {"config": sched_config} if sched_config is not None else {}
        verifier = scheduler_for(verifier, **kw)
        self.verifier = verifier

        if n_bootstrap is None:
            n_bootstrap = n_nodes
        from eges_tpu.crypto.keys import deterministic_node_key
        privs = [deterministic_node_key(i) for i in range(n_nodes)]
        addrs = [secp.pubkey_to_address(secp.privkey_to_pubkey(p))
                 for p in privs]
        boot = tuple(
            BootstrapNode(account=addrs[i], ip="10.0.0.%d" % (i + 1),
                          port=8100 + i)
            for i in range(n_bootstrap))
        ccfg = ChainGeecConfig(bootstrap=boot,
                               validate_timeout_ms=validate_timeout_ms,
                               backoff_time_ms=backoff_time_ms,
                               reg_timeout_s=reg_timeout_s,
                               signed_votes=signed)
        genesis = make_genesis(alloc=alloc)

        self._deferred: set[int] = set(defer or ())
        self._ccfg = ccfg
        self._genesis = genesis
        self._mine = mine
        self._txpool = txpool
        self._columnar = columnar
        self._alloc = alloc
        # crashed nodes' journal history, preserved across the rebuild
        # so the observatory sees one continuous per-node stream
        self._archived: dict[str, list] = {}
        # chaos harness attaches its fault-injector journal here; it
        # rides journals() under the synthetic "faults" node name
        self.fault_journal = None
        # telemetry plane (enable_telemetry): the sampler's journal
        # rides journals() as "telemetry"; a harness-side SLO engine's
        # alert journal attaches to slo_journal and rides as "slo", so
        # chaos canonical dumps byte-compare the alert stream too
        self.telemetry_journal = None
        self.slo_journal = None
        self._telemetry_sampler = None
        self._telemetry_sink = None
        self._telemetry_interval = 0.0
        self._telemetry_cursor: dict[str, int] = {}
        # continuous profiling plane (enable_profiling): aggregate
        # profiler_report events ride journals() as "profiler" — a
        # DEDICATED stream, because sampled counts are wall-clock and
        # must never touch the determinism-checked node streams (chaos
        # scenarios never call enable_profiling)
        self.profiler = None
        self.profile_journal = None
        self._profile_interval = 0.0
        # device-efficiency plane (enable_devstats): per-device
        # device_efficiency count deltas ride journals() as "devstats"
        # — a dedicated stream like "profiler", never enabled by the
        # chaos determinism scenarios
        self.devstats_journal = None
        self._devstats_interval = 0.0
        for i in range(n_nodes):
            name = f"node{i}"
            ncfg = NodeConfig(
                coinbase=addrs[i], consensus_ip="10.0.0.%d" % (i + 1),
                consensus_port=8100 + i, n_candidates=n_candidates,
                n_acceptors=n_acceptors, txn_per_block=txn_per_block,
                txn_size=txn_size, block_timeout_s=block_timeout_s,
                total_nodes=n_nodes, failure_test=failure_test,
                privkey=privs[i] if signed else b"",
                fast_sync=bool(fast_sync and i in fast_sync),
                checkpoint_every=checkpoint_every)
            node_clock = SkewedClock(self.clock)
            chain = BlockChain(genesis=genesis, verifier=verifier,
                               alloc=alloc)
            node = GeecNode(chain, node_clock, None, ncfg, ccfg,
                            mine=(mine[i] if mine is not None else True),
                            verifier=verifier)
            if txpool:
                from eges_tpu.core.txpool import TxPool
                node.txpool = TxPool(node_clock, verifier=verifier)
                if columnar:
                    # the wire-speed ingest hook: relayed txn bundles go
                    # through the columnar admission seam.  Injected here
                    # (sim is L4) so the node (L2) never imports ingress
                    # (L3).  columnar=False keeps the per-tx legacy path
                    # — the differential test's oracle.
                    node.columnarize = columns_of
            if i not in self._deferred:
                # deferred nodes (late joiners) stay OFF the network —
                # no transport join, no gossip — until start_deferred()
                transport = self.net.join(name, ncfg.consensus_ip,
                                          ncfg.consensus_port,
                                          gossip_sink(node),
                                          direct_sink(node))
                node.transport = transport
            self.nodes.append(SimNode(name=name, priv=privs[i],
                                      addr=addrs[i], chain=chain, node=node,
                                      clock=node_clock))

    def start(self) -> None:
        for i, sn in enumerate(self.nodes):
            if i not in self._deferred:
                sn.node.start()

    def start_deferred(self, i: int) -> None:
        """Bring a deferred node online mid-run: the late-joiner leg of
        the sync scenarios (fast sync's raison d'être)."""
        assert i in self._deferred, f"node{i} was not deferred"
        self._deferred.discard(i)
        sn = self.nodes[i]
        ncfg = sn.node.cfg
        sn.node.transport = self.net.join(
            sn.name, ncfg.consensus_ip, ncfg.consensus_port,
            gossip_sink(sn.node), direct_sink(sn.node))
        sn.node.start()

    def crash(self, i: int) -> None:
        """Tear a node down mid-run: cancel its timers, detach it from
        the chain, unbind it from both network planes.  Its BlockChain
        (the "datadir") survives for :meth:`restart` to replay."""
        sn = self.nodes[i]
        assert not sn.crashed, f"{sn.name} already crashed"
        sn.node.stop()
        sn.chain.remove_listener(sn.node._on_new_block)
        self.net.leave(sn.name)
        # keep the dead node's journal history for the observatory merge
        self._archived.setdefault(sn.name, []).extend(
            sn.node.journal.events())
        # a cluster-shared scheduler journaling into this node's stream
        # re-attaches to whichever node adopts it next
        if self.verifier is not None and \
                getattr(self.verifier, "journal", None) is sn.node.journal:
            self.verifier.journal = None
        sn.crashed = True

    def restart(self, i: int) -> None:
        """Rebuild a crashed node from its surviving chain — the same
        restart-replay path a real process takes on boot (GeecNode's
        constructor re-ingests every canonical block with the journal
        gated off), then rejoin both planes and start."""
        sn = self.nodes[i]
        assert sn.crashed, f"{sn.name} is not crashed"
        ncfg = sn.node.cfg
        # the surviving store IS the datadir: rebuild the chain FROM it,
        # exactly as a real process boot does, so a durable checkpoint
        # sidecar anchors the state replay (O(tail) rejoin) instead of
        # inheriting the dead node's in-memory snapshots
        sn.chain = BlockChain(store=sn.chain.store, genesis=self._genesis,
                              verifier=self.verifier, alloc=self._alloc)
        node = GeecNode(sn.chain, sn.clock, None, ncfg, self._ccfg,
                        mine=(self._mine[i] if self._mine is not None
                              else True),
                        verifier=self.verifier)
        if self._txpool:
            from eges_tpu.core.txpool import TxPool
            node.txpool = TxPool(sn.clock, verifier=self.verifier)
            if self._columnar:
                node.columnarize = columns_of
        node.transport = self.net.join(sn.name, ncfg.consensus_ip,
                                       ncfg.consensus_port,
                                       gossip_sink(node),
                                       direct_sink(node))
        sn.node = node
        sn.crashed = False
        # AOT prewarm before serving: a jax-backed verifier reloads its
        # serialized (op, bucket) executables from the artifact store —
        # seconds of deserialize instead of minutes of recompile — and
        # the rejoin cost lands in the journal for the observatory and
        # the chaos rejoin bound.  Native verifiers have no aot_prewarm;
        # the no-op keeps chaos runs byte-deterministic.
        backing = self.verifier
        if backing is not None:
            backing = getattr(backing, "_verifier", backing)
        warm = getattr(backing, "aot_prewarm", None)
        if callable(warm):
            import time as _time
            # analysis: allow-determinism(real AOT reload cost; cold_start_s is volatile-stripped)
            t0 = _time.monotonic()
            info = warm(buckets=(16,))
            # analysis: allow-determinism(real AOT reload cost; cold_start_s is volatile-stripped)
            cold = round(_time.monotonic() - t0, 3)
            node.journal.record(
                "verifier_aot_load", buckets=info["buckets"],
                aot_loads=info["aot_loads"],
                aot_compiles=info["aot_compiles"],
                load_s=round(info["load_s"], 3),
                compile_s=round(info["compile_s"], 3),
                cold_start_s=cold, device_kind=info["device_kind"],
                restart=True)
        node.start()

    def live_nodes(self) -> list[SimNode]:
        return [sn for sn in self.nodes if not sn.crashed]

    def run(self, seconds: float, stop_condition=None) -> None:
        self.clock.run_until(self.clock.now() + seconds, stop_condition)

    def heights(self) -> list[int]:
        return [sn.chain.height() for sn in self.nodes]

    def min_height(self) -> int:
        return min(self.heights())

    def net_stats(self) -> dict:
        """SimNet delivery counters (gossip/direct/dropped/dead_letter/
        corrupted/duplicated/reordered) for the cluster report."""
        return dict(self.net.stats)

    # -- telemetry push channel (utils/timeseries.py) -------------------

    def enable_telemetry(self, *, sink=None, interval_s: float = 5.0,
                         capacity: int = 512):
        """Turn on the periodic registry sampler and (optionally) the
        push channel to a collector.

        Every ``interval_s`` of VIRTUAL time one registry sample lands
        as a ``telemetry_sample`` event in the cluster's "telemetry"
        journal (the process-wide registry is shared by every sim node,
        so the cluster samples once — the per-process analogue of a real
        node's sampler), and ``sink`` — typically
        ``harness.collector.ClusterCollector.ingest`` — receives one
        envelope per journal stream carrying the events recorded since
        the previous tick.  Delivery runs synchronously on the sim
        clock: the deterministic stand-in for the socket push channel
        real nodes use (``node/service.py``).

        Returns the telemetry journal.
        """
        from eges_tpu.utils.journal import Journal
        from eges_tpu.utils.metrics import DEFAULT
        from eges_tpu.utils.timeseries import RegistrySampler

        self.telemetry_journal = Journal("telemetry", clock=self.clock.now)
        self._telemetry_sampler = RegistrySampler(
            DEFAULT, clock=self.clock.now, capacity=capacity)
        self._telemetry_sink = sink
        self._telemetry_interval = interval_s
        self.clock.call_later(interval_s, self._telemetry_tick)
        return self.telemetry_journal

    def _telemetry_tick(self, reschedule: bool = True) -> None:
        from eges_tpu.utils import devstats as devstats_mod

        now = self.clock.now()
        # refresh HBM watermark gauges (no-op on host-only runs) so the
        # registry sample below carries them — the sim analogue of the
        # real node's pre-sample hook in node/service.py
        devstats_mod.sample_memory()
        payload = self._telemetry_sampler.sample()
        self.telemetry_journal.record(
            "telemetry_sample", step=self._telemetry_sampler.steps,
            metrics=payload)
        sink = self._telemetry_sink
        if sink is not None:
            streams = self.journals()
            streams.pop("slo", None)  # the collector's own output
            for name in sorted(streams):
                evs = streams[name]
                cursor = self._telemetry_cursor.get(name, 0)
                fresh = evs[cursor:]
                if fresh:
                    sink({"node": name, "ts": now, "events": fresh})
                self._telemetry_cursor[name] = len(evs)
        if reschedule:
            self.clock.call_later(self._telemetry_interval,
                                  self._telemetry_tick)

    def flush_telemetry(self) -> None:
        """One final sample + push outside the periodic schedule, so a
        collector holds every event the journals hold (the round-trip
        test's precondition).  No-op when telemetry is off."""
        if self._telemetry_sampler is not None:
            self._telemetry_tick(reschedule=False)

    # -- continuous profiling plane (utils/profiler.py) -----------------

    def enable_profiling(self, *, hz: float | None = None,
                         interval_s: float = 5.0, profiler=None):
        """Start a sampling profiler for the sim process and journal
        one aggregate ``profiler_report`` per ``interval_s`` of VIRTUAL
        time into a dedicated "profiler" stream (like the telemetry
        plane, the process is shared so the cluster profiles once).

        The sampler itself runs on REAL time — stacks are wall-clock
        by nature — which is exactly why the reports get their own
        stream: chaos determinism checks byte-compare node streams and
        never enable this plane.  ``hz=None`` resolves EGES_PROFILE_HZ
        (default ~97); 0 leaves the plane off (no thread, empty
        stream).  Returns the profiler.
        """
        from eges_tpu.utils.journal import Journal
        from eges_tpu.utils.profiler import SamplingProfiler

        self.profiler = profiler or SamplingProfiler(hz=hz)
        self.profile_journal = Journal("profiler", clock=self.clock.now)
        self._profile_interval = interval_s
        self.profiler.start()
        self.clock.call_later(interval_s, self._profile_tick)
        return self.profiler

    def _profile_tick(self, reschedule: bool = True) -> None:
        self.profiler.journal_snapshot(self.profile_journal)
        if reschedule:
            self.clock.call_later(self._profile_interval,
                                  self._profile_tick)

    def stop_profiling(self) -> None:
        """Join the sampler and journal the final report (forced, so a
        profiled run is never invisible to the collector fold).  No-op
        when profiling is off."""
        if self.profiler is None:
            return
        self.profiler.stop()
        self.profiler.journal_snapshot(self.profile_journal, force=True)

    # -- device-efficiency plane (utils/devstats.py) ---------------------

    def enable_devstats(self, *, interval_s: float = 5.0):
        """Journal per-device ``device_efficiency`` count deltas every
        ``interval_s`` of VIRTUAL time into a dedicated "devstats"
        stream (the goodput ledger is process-wide like the metrics
        registry, so the cluster journals once).

        The ledger is rebased first so windows recorded by earlier
        runs in the same process never leak into the first tick.  Pair
        with ``mesh_devices=N`` at construction to give the scheduler
        real per-device lanes to account.  Returns the journal."""
        from eges_tpu.utils import devstats as devstats_mod
        from eges_tpu.utils.journal import Journal

        self.devstats_journal = Journal("devstats", clock=self.clock.now)
        self._devstats_interval = interval_s
        devstats_mod.DEFAULT.rebase()
        self.clock.call_later(interval_s, self._devstats_tick)
        return self.devstats_journal

    def _devstats_tick(self, reschedule: bool = True) -> None:
        from eges_tpu.utils import devstats as devstats_mod

        devstats_mod.sample_memory()
        devstats_mod.DEFAULT.journal_snapshot(self.devstats_journal)
        if reschedule:
            self.clock.call_later(self._devstats_interval,
                                  self._devstats_tick)

    def stop_devstats(self) -> None:
        """Journal the final delta outside the periodic schedule so
        windows recorded after the last tick still reach the collector
        fold.  No-op when the plane is off."""
        if self.devstats_journal is None:
            return
        self._devstats_tick(reschedule=False)

    def journals(self) -> dict[str, list[dict]]:
        """Per-node consensus event journals, keyed by sim node name —
        the live-poll source ``harness/observatory.py`` merges (the
        RPC-less analogue of hitting ``thw_journal`` on every node).
        Crashed-then-restarted nodes contribute their archived pre-crash
        events plus the rebuilt node's stream; an attached fault
        injector's journal rides along as the "faults" node."""
        out = {}
        for sn in self.nodes:
            out[sn.name] = (self._archived.get(sn.name, [])
                            + sn.node.journal.events())
        if self.fault_journal is not None:
            out["faults"] = self.fault_journal.events()
        if self.telemetry_journal is not None:
            out["telemetry"] = self.telemetry_journal.events()
        if self.slo_journal is not None:
            out["slo"] = self.slo_journal.events()
        if self.profile_journal is not None:
            out["profiler"] = self.profile_journal.events()
        if self.devstats_journal is not None:
            out["devstats"] = self.devstats_journal.events()
        return out
