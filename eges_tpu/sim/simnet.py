"""Deterministic in-process multi-node simulator.

The analogue of the reference's ``p2p/simulations`` framework (SURVEY §4:
"in-memory net or exec'd nodes ... NOT used for Geec" — the fork only
ever tested Geec with real clusters + log grepping).  This build makes
the deterministic simulator the *primary* consensus test vehicle: virtual
time, seeded latency/loss, full-mesh gossip and addressed direct
datagrams, every run reproducible from its seed.

* :class:`SimClock` — a heap of (due, seq, fn) callbacks; ``run_until``
  executes them in timestamp order, advancing virtual time instantly.
* :class:`SimNet` — in-memory transports: ``gossip`` fans out to every
  other node's gossip inbox (the RLPx/TCP plane), ``send_direct``
  delivers to the (ip, port) owner (the raw-UDP plane).  Configurable
  per-message latency jitter and drop rate model the planes' real
  characteristics (UDP loss is what the reference's retry ladders exist
  for).
"""

from __future__ import annotations

import heapq
import itertools
import random
from dataclasses import dataclass

from eges_tpu.utils import ledger


class _Timer:
    __slots__ = ("fn", "cancelled")

    def __init__(self, fn):
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class SimClock:
    def __init__(self, start: float = 0.0):
        self._now = start
        self._heap: list = []
        self._seq = itertools.count()

    def now(self) -> float:
        return self._now

    def call_later(self, delay_s: float, fn) -> _Timer:
        t = _Timer(fn)
        heapq.heappush(self._heap, (self._now + max(delay_s, 0.0),
                                    next(self._seq), t))
        return t

    def run_until(self, deadline: float, stop_condition=None) -> None:
        """Execute due callbacks in order until virtual ``deadline``."""
        while self._heap and self._heap[0][0] <= deadline:
            due, _, timer = heapq.heappop(self._heap)
            self._now = due
            if not timer.cancelled:
                timer.fn()
            if stop_condition is not None and stop_condition():
                return
        self._now = max(self._now, deadline)

    def pending(self) -> int:
        return sum(1 for _, _, t in self._heap if not t.cancelled)


class SkewedClock:
    """Per-node clock view over a shared :class:`SimClock`.

    Models a skewed local oscillator: ``now()`` is offset by ``skew_s``
    (mutable mid-run — the fault layer's clock-skew action), while
    timers still fire on the shared virtual timeline, so a skewed node
    mis-timestamps blocks/journal rows without desynchronizing the
    event heap."""

    def __init__(self, base: SimClock, skew_s: float = 0.0):
        self._base = base
        self.skew_s = skew_s

    def now(self) -> float:
        return self._base.now() + self.skew_s

    def call_later(self, delay_s: float, fn) -> _Timer:
        return self._base.call_later(delay_s, fn)


@dataclass
class LinkRule:
    """Per-(sender, receiver) delivery overrides — one DIRECTION of a
    link, so ``A -> B`` can drop while ``B -> A`` flows (the asymmetric
    partition the symmetric ``SimNet.partition`` cannot express).
    ``None`` fields fall back to the net-wide defaults."""

    blocked: bool = False
    drop_rate: float | None = None
    latency_s: float | None = None
    jitter_s: float | None = None
    duplicate_rate: float = 0.0
    reorder_rate: float = 0.0
    reorder_max_s: float = 0.05
    corrupt_rate: float = 0.0


class SimTransport:
    """Per-node transport handle bound to a :class:`SimNet`."""

    def __init__(self, net: "SimNet", node_id: str):
        self._net = net
        self.node_id = node_id

    def gossip(self, data: bytes) -> None:
        from eges_tpu.utils import tracing
        from eges_tpu.utils.metrics import DEFAULT as metrics
        data = tracing.inject_current(data)
        metrics.counter("net.gossip_bytes").inc(len(data))
        metrics.counter("net.gossip_msgs").inc()
        self._net.deliver_gossip(self.node_id, data)

    def send_direct(self, ip: str, port: int, data: bytes) -> None:
        from eges_tpu.utils import tracing
        from eges_tpu.utils.metrics import DEFAULT as metrics
        data = tracing.inject_current(data)
        metrics.counter("net.direct_bytes").inc(len(data))
        metrics.counter("net.direct_msgs").inc()
        self._net.deliver_direct(self.node_id, (ip, port), data)


class SimNet:
    def __init__(self, clock: SimClock, *, seed: int = 0,
                 latency_s: float = 0.002, jitter_s: float = 0.002,
                 drop_rate: float = 0.0):
        self.clock = clock
        self.rng = random.Random(seed)
        self.latency_s = latency_s
        self.jitter_s = jitter_s
        self.drop_rate = drop_rate
        # net-wide fault knobs (the per-link LinkRule overrides these)
        self.corrupt_rate = 0.0
        self.duplicate_rate = 0.0
        self.reorder_rate = 0.0
        self.reorder_max_s = 0.05
        self._gossip_sinks: dict[str, object] = {}   # node_id -> fn(bytes)
        self._direct_sinks: dict[tuple, object] = {}  # (ip, port) -> fn(bytes)
        self._partitioned: set[str] = set()
        self._links: dict[tuple[str, str], LinkRule] = {}
        self.stats = {"gossip": 0, "direct": 0, "dropped": 0,
                      "dead_letter": 0, "corrupted": 0, "duplicated": 0,
                      "reordered": 0, "gossip_bytes": 0, "direct_bytes": 0}

    def join(self, node_id: str, ip: str, port: int, on_gossip, on_direct):
        transport = SimTransport(self, node_id)
        self._gossip_sinks[node_id] = on_gossip
        self._direct_sinks[(ip, port)] = (node_id, on_direct)
        return transport

    def leave(self, node_id: str) -> None:
        """Unbind a node from both planes (crash injection): its sends
        vanish, and datagrams already in flight toward it arrive at a
        closed port."""
        self._gossip_sinks.pop(node_id, None)
        for addr in [a for a, (nid, _) in self._direct_sinks.items()
                     if nid == node_id]:
            del self._direct_sinks[addr]

    def partition(self, node_id: str) -> None:
        """Cut a node off both planes (crash/partition injection)."""
        self._partitioned.add(node_id)

    def heal(self, node_id: str) -> None:
        self._partitioned.discard(node_id)

    # -- per-link rules (asymmetric: (src, dst) is one direction) ---------

    def set_link(self, src: str, dst: str, **overrides) -> LinkRule:
        """Create or update the ``src -> dst`` rule; the reverse
        direction is untouched (asymmetric by construction)."""
        rule = self._links.setdefault((src, dst), LinkRule())
        for k, v in overrides.items():
            if not hasattr(rule, k):
                raise TypeError(f"unknown link override: {k!r}")
            setattr(rule, k, v)
        return rule

    def block_link(self, src: str, dst: str) -> None:
        """Drop everything ``src -> dst`` while ``dst -> src`` flows."""
        self.set_link(src, dst, blocked=True)

    def clear_link(self, src: str, dst: str) -> None:
        self._links.pop((src, dst), None)

    def _delay(self) -> float:
        return self.latency_s + self.rng.random() * self.jitter_s

    def _dropped(self) -> bool:
        return self.drop_rate > 0 and self.rng.random() < self.drop_rate

    def _mangle(self, data: bytes) -> bytes:
        """Deterministic datagram corruption: truncate or flip one bit.
        Receivers must reject it in decode/auth — never crash."""
        if len(data) < 2 or self.rng.random() < 0.5:
            return data[: max(1, len(data) // 2)]
        i = self.rng.randrange(len(data))
        return data[:i] + bytes([data[i] ^ (1 << self.rng.randrange(8))]) \
            + data[i + 1:]

    def _send(self, src: str, dst: str, data: bytes, plane: str,
              fire) -> None:
        """One directed delivery decision: link rule -> drop -> delay ->
        corruption/reorder/duplication.  All randomness draws from the
        one seeded rng, in a fixed order, so a fault plan replays
        bit-identically; with no faults configured the rng stream is
        exactly the legacy drop+delay sequence."""
        rule = self._links.get((src, dst))
        if rule is not None and rule.blocked:
            self.stats["dropped"] += 1
            return
        drop = (rule.drop_rate if rule is not None
                and rule.drop_rate is not None else self.drop_rate)
        if drop > 0 and self.rng.random() < drop:
            self.stats["dropped"] += 1
            return
        lat = (rule.latency_s if rule is not None
               and rule.latency_s is not None else self.latency_s)
        jit = (rule.jitter_s if rule is not None
               and rule.jitter_s is not None else self.jitter_s)
        delay = lat + self.rng.random() * jit
        corrupt = rule.corrupt_rate if rule is not None \
            and rule.corrupt_rate else self.corrupt_rate
        if corrupt and self.rng.random() < corrupt:
            data = self._mangle(data)
            self.stats["corrupted"] += 1
        reorder = rule.reorder_rate if rule is not None \
            and rule.reorder_rate else self.reorder_rate
        reorder_max = rule.reorder_max_s if rule is not None \
            else self.reorder_max_s
        if reorder and self.rng.random() < reorder:
            # bounded reordering: a late copy overtakes nothing beyond
            # the window, mirroring real UDP queue churn
            delay += self.rng.random() * reorder_max
            self.stats["reordered"] += 1
        dup = rule.duplicate_rate if rule is not None \
            and rule.duplicate_rate else self.duplicate_rate
        if dup and self.rng.random() < dup:
            self.stats["duplicated"] += 1
            extra = delay + self.rng.random() * reorder_max
            self.clock.call_later(extra,
                                  (lambda f, d: lambda: f(d))(fire, data))
        self.stats[plane] += 1
        self.stats[plane + "_bytes"] += len(data)
        self.clock.call_later(delay,
                              (lambda f, d: lambda: f(d))(fire, data))

    def deliver_gossip(self, sender_id: str, data: bytes) -> None:
        if sender_id in self._partitioned \
                or sender_id not in self._gossip_sinks:
            return
        for node_id in list(self._gossip_sinks):
            if node_id == sender_id or node_id in self._partitioned:
                continue
            self._send(sender_id, node_id, data, "gossip",
                       (lambda nid, src:
                        lambda d: self._fire_gossip(nid, d, src))
                       (node_id, sender_id))

    def deliver_gossip_many(self, sender_id: str, frames) -> None:
        """Inject one WINDOW of gossip datagrams from a (possibly
        external) sender in a single call — the wire-speed ingest
        test/chaos idiom.  Each frame rides the normal per-datagram
        fault model (drop/corrupt/duplicate/reorder), so a window
        injection is byte-identical to the equivalent loop of
        :meth:`deliver_gossip` calls."""
        for data in frames:
            self.deliver_gossip(sender_id, data)

    def _fire_gossip(self, node_id: str, data: bytes,  # ingress-entry
                     sender_id: str = "") -> None:
        # delivery-time lookup: the receiver may have crashed (left the
        # net) while this datagram was in flight
        sink = self._gossip_sinks.get(node_id)
        if sink is None:
            self.stats["dropped"] += 1
            return
        # provenance stamp: the receiving node's entry point reads the
        # delivering peer (utils/ledger.py) to tag ingress cost
        with ledger.peer(sender_id):  # bounded-by: _ORIGIN_MAX (ledger.peer clamps)
            sink(data)

    def deliver_direct(self, sender_id: str, addr: tuple, data: bytes) -> None:
        if sender_id in self._partitioned:
            return
        entry = self._direct_sinks.get(addr)
        if entry is None:
            # dead letter, like a UDP datagram to a closed port — now
            # counted, so chaos reports can see retries hitting a
            # crashed node's port
            self.stats["dead_letter"] += 1
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("net.dead_letters").inc()
            return
        node_id, _sink = entry
        if node_id in self._partitioned:
            self.stats["dropped"] += 1
            return
        self._send(sender_id, node_id, data, "direct",
                   (lambda a, src: lambda d: self._fire_direct(a, d, src))
                   (addr, sender_id))

    def _fire_direct(self, addr: tuple, data: bytes,  # ingress-entry
                     sender_id: str = "") -> None:
        entry = self._direct_sinks.get(addr)
        if entry is None:
            self.stats["dead_letter"] += 1
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("net.dead_letters").inc()
            return
        with ledger.peer(sender_id):  # bounded-by: _ORIGIN_MAX (ledger.peer clamps)
            entry[1](data)
