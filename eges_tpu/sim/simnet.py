"""Deterministic in-process multi-node simulator.

The analogue of the reference's ``p2p/simulations`` framework (SURVEY §4:
"in-memory net or exec'd nodes ... NOT used for Geec" — the fork only
ever tested Geec with real clusters + log grepping).  This build makes
the deterministic simulator the *primary* consensus test vehicle: virtual
time, seeded latency/loss, full-mesh gossip and addressed direct
datagrams, every run reproducible from its seed.

* :class:`SimClock` — a heap of (due, seq, fn) callbacks; ``run_until``
  executes them in timestamp order, advancing virtual time instantly.
* :class:`SimNet` — in-memory transports: ``gossip`` fans out to every
  other node's gossip inbox (the RLPx/TCP plane), ``send_direct``
  delivers to the (ip, port) owner (the raw-UDP plane).  Configurable
  per-message latency jitter and drop rate model the planes' real
  characteristics (UDP loss is what the reference's retry ladders exist
  for).
"""

from __future__ import annotations

import heapq
import itertools
import random


class _Timer:
    __slots__ = ("fn", "cancelled")

    def __init__(self, fn):
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class SimClock:
    def __init__(self, start: float = 0.0):
        self._now = start
        self._heap: list = []
        self._seq = itertools.count()

    def now(self) -> float:
        return self._now

    def call_later(self, delay_s: float, fn) -> _Timer:
        t = _Timer(fn)
        heapq.heappush(self._heap, (self._now + max(delay_s, 0.0),
                                    next(self._seq), t))
        return t

    def run_until(self, deadline: float, stop_condition=None) -> None:
        """Execute due callbacks in order until virtual ``deadline``."""
        while self._heap and self._heap[0][0] <= deadline:
            due, _, timer = heapq.heappop(self._heap)
            self._now = due
            if not timer.cancelled:
                timer.fn()
            if stop_condition is not None and stop_condition():
                return
        self._now = max(self._now, deadline)

    def pending(self) -> int:
        return sum(1 for _, _, t in self._heap if not t.cancelled)


class SimTransport:
    """Per-node transport handle bound to a :class:`SimNet`."""

    def __init__(self, net: "SimNet", node_id: str):
        self._net = net
        self.node_id = node_id

    def gossip(self, data: bytes) -> None:
        from eges_tpu.utils import tracing
        from eges_tpu.utils.metrics import DEFAULT as metrics
        data = tracing.inject_current(data)
        metrics.counter("net.gossip_bytes").inc(len(data))
        metrics.counter("net.gossip_msgs").inc()
        self._net.deliver_gossip(self.node_id, data)

    def send_direct(self, ip: str, port: int, data: bytes) -> None:
        from eges_tpu.utils import tracing
        from eges_tpu.utils.metrics import DEFAULT as metrics
        data = tracing.inject_current(data)
        metrics.counter("net.direct_bytes").inc(len(data))
        metrics.counter("net.direct_msgs").inc()
        self._net.deliver_direct(self.node_id, (ip, port), data)


class SimNet:
    def __init__(self, clock: SimClock, *, seed: int = 0,
                 latency_s: float = 0.002, jitter_s: float = 0.002,
                 drop_rate: float = 0.0):
        self.clock = clock
        self.rng = random.Random(seed)
        self.latency_s = latency_s
        self.jitter_s = jitter_s
        self.drop_rate = drop_rate
        self._gossip_sinks: dict[str, object] = {}   # node_id -> fn(bytes)
        self._direct_sinks: dict[tuple, object] = {}  # (ip, port) -> fn(bytes)
        self._partitioned: set[str] = set()
        self.stats = {"gossip": 0, "direct": 0, "dropped": 0}

    def join(self, node_id: str, ip: str, port: int, on_gossip, on_direct):
        transport = SimTransport(self, node_id)
        self._gossip_sinks[node_id] = on_gossip
        self._direct_sinks[(ip, port)] = (node_id, on_direct)
        return transport

    def partition(self, node_id: str) -> None:
        """Cut a node off both planes (crash/partition injection)."""
        self._partitioned.add(node_id)

    def heal(self, node_id: str) -> None:
        self._partitioned.discard(node_id)

    def _delay(self) -> float:
        return self.latency_s + self.rng.random() * self.jitter_s

    def _dropped(self) -> bool:
        return self.drop_rate > 0 and self.rng.random() < self.drop_rate

    def deliver_gossip(self, sender_id: str, data: bytes) -> None:
        if sender_id in self._partitioned:
            return
        for node_id, sink in self._gossip_sinks.items():
            if node_id == sender_id or node_id in self._partitioned:
                continue
            if self._dropped():
                self.stats["dropped"] += 1
                continue
            self.stats["gossip"] += 1
            self.clock.call_later(self._delay(),
                                  (lambda s, d: lambda: s(d))(sink, data))

    def deliver_direct(self, sender_id: str, addr: tuple, data: bytes) -> None:
        if sender_id in self._partitioned:
            return
        entry = self._direct_sinks.get(addr)
        if entry is None:
            return  # dead letter, like a UDP datagram to a closed port
        node_id, sink = entry
        if node_id in self._partitioned or self._dropped():
            self.stats["dropped"] += 1
            return
        self.stats["direct"] += 1
        self.clock.call_later(self._delay(),
                              (lambda s, d: lambda: s(d))(sink, data))
