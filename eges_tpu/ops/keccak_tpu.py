"""Batched Keccak-256 on TPU.

The reference computes Keccak-256 with amd64 assembly on the host
(ref: crypto/sha3/keccakf_amd64.s, fronted by crypto/crypto.go:43
Keccak256).  On TPU there is no 64-bit integer datapath, so each 64-bit
lane of the 5x5 Keccak state is a **pair of uint32 words** ``(lo, hi)``,
and the whole 25-lane state is a pair of ``[..., 25]`` uint32 arrays.

theta/rho/pi/chi are expressed as lane-axis rolls, constant-index
gathers, and per-lane constant-amount rotations — so one round is ~60
vector ops and the 24 rounds run in a single `lax.fori_loop` (the
round constant indexed per iteration).  This keeps the compiled graph
tiny (the fully unrolled scalar form trips XLA CPU's slow-compile
alarm) while the VPU still sees wide elementwise work: batch x 25 lanes.

Primary in-graph consumer: pubkey -> address (``keccak256(x || y)[12:]``)
at the tail of batched ecrecover (ref: crypto/signature_cgo.go:31 +
crypto/crypto.go:194), keeping the whole sender-recovery hot path
(SURVEY §3.5) on-device.  Fixed input length per call site; multi-block
absorption unrolls at trace time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

RATE = 136  # bytes, Keccak-256 (capacity 512)

_RC = np.array([
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
], dtype=np.uint64)
# NB: keep module-level constants as NUMPY arrays, converting to jnp only
# inside a trace.  A module-level jnp array closed over by a jitted
# function is a captured device buffer, and on the TPU runtime a loop
# body referencing a captured buffer falls off the fast path (~1000x:
# measured 64 ms instead of 60 us for this very function, and it drags
# every other loop in the same executable down with it).
_RC_LO_NP = (_RC & 0xFFFFFFFF).astype(np.uint32)
_RC_HI_NP = (_RC >> 32).astype(np.uint32)

# lane index l = x + 5*y
_X = np.arange(25) % 5
_Y = np.arange(25) // 5

# rho rotation offsets per lane (ref layout: offset[x][y])
_ROT_TBL = np.array([
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
])
# pi: B[y + 5*((2x+3y)%5)] = rot(A[x+5y], ROT[x][y]).  Express as a
# gather: for destination lane dl, SRC[dl] is the source lane and
# ROT[dl] the rotation applied.
_PI_SRC = np.zeros(25, np.int32)
_PI_ROT = np.zeros(25, np.int32)
for _x in range(5):
    for _y in range(5):
        _dl = _y + 5 * ((2 * _x + 3 * _y) % 5)
        _PI_SRC[_dl] = _x + 5 * _y
        _PI_ROT[_dl] = _ROT_TBL[_x][_y]


def _rotl_pairs(lo, hi, amounts: np.ndarray):
    """Rotate 64-bit (lo, hi) pairs left by per-lane CONSTANT amounts."""
    r = amounts % 64
    swap = r >= 32
    rr = jnp.asarray((r % 32).astype(np.uint32))
    l0 = jnp.where(jnp.asarray(swap), hi, lo)
    h0 = jnp.where(jnp.asarray(swap), lo, hi)
    # rr == 0 lanes must not shift by 32
    nz = jnp.asarray((r % 32 != 0))
    inv = jnp.asarray(((32 - (r % 32)) % 32).astype(np.uint32))
    nl = jnp.where(nz, (l0 << rr) | (h0 >> inv), l0)
    nh = jnp.where(nz, (h0 << rr) | (l0 >> inv), h0)
    return nl, nh


def _keccak_f(lo: jnp.ndarray, hi: jnp.ndarray):
    """Keccak-f[1600]: state as ``[..., 25]`` uint32 pairs."""

    rc_lo = jnp.asarray(_RC_LO_NP)  # trace-time constants (see note above)
    rc_hi = jnp.asarray(_RC_HI_NP)

    def round_fn(rnd, state):
        lo, hi = state
        # theta
        grid_lo = lo.reshape(*lo.shape[:-1], 5, 5)  # [..., y, x]
        grid_hi = hi.reshape(*hi.shape[:-1], 5, 5)
        c_lo = jax.lax.reduce(grid_lo, jnp.uint32(0), jax.lax.bitwise_xor,
                              [grid_lo.ndim - 2])
        c_hi = jax.lax.reduce(grid_hi, jnp.uint32(0), jax.lax.bitwise_xor,
                              [grid_hi.ndim - 2])
        rot_lo = (c_lo << 1) | (c_hi >> 31)
        rot_hi = (c_hi << 1) | (c_lo >> 31)
        d_lo = jnp.roll(c_lo, 1, axis=-1) ^ jnp.roll(rot_lo, -1, axis=-1)
        d_hi = jnp.roll(c_hi, 1, axis=-1) ^ jnp.roll(rot_hi, -1, axis=-1)
        lo = lo ^ jnp.tile(d_lo, (*([1] * (d_lo.ndim - 1)), 5))
        hi = hi ^ jnp.tile(d_hi, (*([1] * (d_hi.ndim - 1)), 5))
        # rho + pi (constant gather + constant-amount rotations)
        src = jnp.asarray(_PI_SRC)
        b_lo = jnp.take(lo, src, axis=-1)
        b_hi = jnp.take(hi, src, axis=-1)
        b_lo, b_hi = _rotl_pairs(b_lo, b_hi, _PI_ROT)
        # chi: A[x] = B[x] ^ (~B[x+1] & B[x+2]) along each row of 5
        g_lo = b_lo.reshape(*b_lo.shape[:-1], 5, 5)
        g_hi = b_hi.reshape(*b_hi.shape[:-1], 5, 5)
        lo = (g_lo ^ (~jnp.roll(g_lo, -1, axis=-1)
                      & jnp.roll(g_lo, -2, axis=-1))).reshape(lo.shape)
        hi = (g_hi ^ (~jnp.roll(g_hi, -1, axis=-1)
                      & jnp.roll(g_hi, -2, axis=-1))).reshape(hi.shape)
        # iota
        lo = lo.at[..., 0].set(lo[..., 0] ^ rc_lo[rnd])
        hi = hi.at[..., 0].set(hi[..., 0] ^ rc_hi[rnd])
        return lo, hi

    return jax.lax.fori_loop(0, 24, round_fn, (lo, hi))


def keccak256_fixed(data: jnp.ndarray) -> jnp.ndarray:
    """Batched Keccak-256 of fixed-length messages.

    ``data``: ``[..., L]`` uint8 with a static trailing length L.  Returns
    ``[..., 32]`` uint8 digests.  Matches the legacy (pre-NIST) Keccak
    padding the reference uses (crypto/sha3: domain byte 0x01).
    """
    L = data.shape[-1]
    batch = data.shape[:-1]
    nblocks = L // RATE + 1  # last block holds padding, always present

    padded_len = nblocks * RATE
    pad = jnp.zeros((*batch, padded_len - L), jnp.uint8)
    buf = jnp.concatenate([data, pad], axis=-1)
    buf = buf.at[..., L].set(jnp.uint8(0x01))
    buf = buf.at[..., padded_len - 1].set(buf[..., padded_len - 1]
                                          | jnp.uint8(0x80))

    lo = jnp.zeros((*batch, 25), jnp.uint32)
    hi = jnp.zeros((*batch, 25), jnp.uint32)
    b32 = buf.astype(jnp.uint32)
    words = b32.reshape(*batch, nblocks, RATE // 4, 4)
    lanes = (words[..., 0] | (words[..., 1] << 8) | (words[..., 2] << 16)
             | (words[..., 3] << 24))  # [..., nblocks, 34] LE 32-bit words
    # fused-kernel variant: the single-block case (the ecrecover
    # address tail) runs the whole permutation as one Mosaic kernel
    from eges_tpu.ops.pallas_kernels import (
        keccak_block_pallas, ladder_kernels_enabled,
    )
    if nblocks == 1 and len(batch) == 1 and ladder_kernels_enabled():
        out_words = keccak_block_pallas(lanes[..., 0, :])
        shifts = jnp.asarray([0, 8, 16, 24], jnp.uint32)
        out = ((out_words[..., :, None] >> shifts) & 0xFF).astype(jnp.uint8)
        return out.reshape(*batch, 32)

    for blk in range(nblocks):
        w = lanes[..., blk, :]  # [..., 34]
        blo = w[..., 0::2]      # 17 lanes' low words
        bhi = w[..., 1::2]
        lo = lo.at[..., :17].set(lo[..., :17] ^ blo)
        hi = hi.at[..., :17].set(hi[..., :17] ^ bhi)
        lo, hi = _keccak_f(lo, hi)

    # squeeze 32 bytes = lanes 0..3
    out_words = jnp.stack([lo[..., 0], hi[..., 0], lo[..., 1], hi[..., 1],
                           lo[..., 2], hi[..., 2], lo[..., 3], hi[..., 3]],
                          axis=-1)  # [..., 8] u32 LE
    shifts = jnp.asarray([0, 8, 16, 24], jnp.uint32)
    out = ((out_words[..., :, None] >> shifts) & 0xFF).astype(jnp.uint8)
    return out.reshape(*batch, 32)


def pubkey_to_address(qx_bytes: jnp.ndarray, qy_bytes: jnp.ndarray) -> jnp.ndarray:
    """Batched ``keccak256(x || y)[12:]`` — Ethereum address derivation
    (ref: crypto/crypto.go:194 PubkeyToAddress)."""
    pub = jnp.concatenate([qx_bytes, qy_bytes], axis=-1)
    return keccak256_fixed(pub)[..., 12:]
