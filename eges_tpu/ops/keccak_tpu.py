"""Batched Keccak-256 on TPU.

The reference computes Keccak-256 with amd64 assembly on the host
(ref: crypto/sha3/keccakf_amd64.s, fronted by crypto/crypto.go:43
Keccak256).  On TPU there is no 64-bit integer datapath, so each 64-bit
lane of the 5x5 Keccak state is a **pair of uint32 words** ``(lo, hi)``;
all of theta/rho/pi/chi/iota decompose into 32-bit XOR/AND/NOT/shifts,
which the VPU executes lane-parallel over the batch dimension.

Rotation amounts and round constants are trace-time Python constants, so
the 24 rounds unroll into straight-line vector code — no data-dependent
control flow, fixed shapes, arbitrary leading batch dims.

Primary in-graph consumer: pubkey -> address (``keccak256(x || y)[12:]``)
at the tail of batched ecrecover (ref: crypto/signature_cgo.go:31 +
crypto/crypto.go:194), which keeps the whole sender-recovery hot path
(SURVEY §3.5) on-device.  Fixed input length per call site; multi-block
absorption is unrolled at trace time for lengths >= the 136-byte rate.
"""

from __future__ import annotations

import jax.numpy as jnp

RATE = 136  # bytes, Keccak-256 (capacity 512)

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rho rotation offsets, indexed [x][y] (column-major state layout A[x,y])
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_M32 = jnp.uint32(0xFFFFFFFF)


def _rotl64(lo, hi, r: int):
    """Rotate a (lo, hi) uint32 pair left by a constant r in [0, 64)."""
    r %= 64
    if r == 0:
        return lo, hi
    if r == 32:
        return hi, lo
    if r > 32:
        lo, hi = hi, lo
        r -= 32
    nl = ((lo << r) | (hi >> (32 - r))) & _M32
    nh = ((hi << r) | (lo >> (32 - r))) & _M32
    return nl, nh


def _keccak_f(lanes_lo, lanes_hi):
    """Keccak-f[1600] permutation on lists of 25 lane pairs.

    ``lanes_lo/hi[x + 5*y]`` are batched uint32 arrays.
    """
    A_lo = list(lanes_lo)
    A_hi = list(lanes_hi)
    for rnd in range(24):
        # theta
        C_lo = [A_lo[x] ^ A_lo[x + 5] ^ A_lo[x + 10] ^ A_lo[x + 15] ^ A_lo[x + 20]
                for x in range(5)]
        C_hi = [A_hi[x] ^ A_hi[x + 5] ^ A_hi[x + 10] ^ A_hi[x + 15] ^ A_hi[x + 20]
                for x in range(5)]
        for x in range(5):
            rl, rh = _rotl64(C_lo[(x + 1) % 5], C_hi[(x + 1) % 5], 1)
            d_lo = C_lo[(x + 4) % 5] ^ rl
            d_hi = C_hi[(x + 4) % 5] ^ rh
            for y in range(5):
                A_lo[x + 5 * y] = A_lo[x + 5 * y] ^ d_lo
                A_hi[x + 5 * y] = A_hi[x + 5 * y] ^ d_hi
        # rho + pi
        B_lo = [None] * 25
        B_hi = [None] * 25
        for x in range(5):
            for y in range(5):
                nl, nh = _rotl64(A_lo[x + 5 * y], A_hi[x + 5 * y], _ROT[x][y])
                B_lo[y + 5 * ((2 * x + 3 * y) % 5)] = nl
                B_hi[y + 5 * ((2 * x + 3 * y) % 5)] = nh
        # chi
        for y in range(5):
            row_lo = [B_lo[x + 5 * y] for x in range(5)]
            row_hi = [B_hi[x + 5 * y] for x in range(5)]
            for x in range(5):
                A_lo[x + 5 * y] = row_lo[x] ^ (~row_lo[(x + 1) % 5] & row_lo[(x + 2) % 5])
                A_hi[x + 5 * y] = row_hi[x] ^ (~row_hi[(x + 1) % 5] & row_hi[(x + 2) % 5])
        # iota
        rc = _RC[rnd]
        A_lo[0] = A_lo[0] ^ jnp.uint32(rc & 0xFFFFFFFF)
        A_hi[0] = A_hi[0] ^ jnp.uint32(rc >> 32)
    return A_lo, A_hi


def keccak256_fixed(data: jnp.ndarray) -> jnp.ndarray:
    """Batched Keccak-256 of fixed-length messages.

    ``data``: ``[..., L]`` uint8 with a static trailing length L.  Returns
    ``[..., 32]`` uint8 digests.  Matches the legacy (pre-NIST) Keccak
    padding the reference uses (crypto/sha3: domain byte 0x01).
    """
    L = data.shape[-1]
    batch = data.shape[:-1]
    nblocks = L // RATE + 1  # last block holds padding, always present

    padded_len = nblocks * RATE
    pad = jnp.zeros((*batch, padded_len - L), jnp.uint8)
    buf = jnp.concatenate([data, pad], axis=-1)
    buf = buf.at[..., L].set(jnp.uint8(0x01))
    buf = buf.at[..., padded_len - 1].set(buf[..., padded_len - 1] | jnp.uint8(0x80))

    zeros = jnp.zeros(batch, jnp.uint32)
    A_lo = [zeros] * 25
    A_hi = [zeros] * 25
    b32 = buf.astype(jnp.uint32)
    for blk in range(nblocks):
        off = blk * RATE
        for lane in range(RATE // 8):
            base = off + 8 * lane
            lo = (b32[..., base] | (b32[..., base + 1] << 8)
                  | (b32[..., base + 2] << 16) | (b32[..., base + 3] << 24))
            hi = (b32[..., base + 4] | (b32[..., base + 5] << 8)
                  | (b32[..., base + 6] << 16) | (b32[..., base + 7] << 24))
            A_lo[lane] = A_lo[lane] ^ lo
            A_hi[lane] = A_hi[lane] ^ hi
        A_lo, A_hi = _keccak_f(A_lo, A_hi)

    out = []
    for lane in range(4):  # 32 bytes = 4 lanes
        for word in (A_lo[lane], A_hi[lane]):
            for shift in (0, 8, 16, 24):
                out.append(((word >> shift) & 0xFF).astype(jnp.uint8))
    return jnp.stack(out, axis=-1)


def pubkey_to_address(qx_bytes: jnp.ndarray, qy_bytes: jnp.ndarray) -> jnp.ndarray:
    """Batched ``keccak256(x || y)[12:]`` — Ethereum address derivation
    (ref: crypto/crypto.go:194 PubkeyToAddress)."""
    pub = jnp.concatenate([qx_bytes, qy_bytes], axis=-1)
    return keccak256_fixed(pub)[..., 12:]
