"""secp256k1 group law on TPU: batched Jacobian point arithmetic.

TPU-native replacement for the compute core of the reference's C
libsecp256k1 (ref: crypto/secp256k1/secp256.go:20-37 wraps it via cgo; the
group law lives in its src/group_impl.h role).  Instead of one point at a
time in 64-bit limbs, every function here is batched: a point is a triple
of ``[..., 16]`` uint32 limb arrays (Jacobian X, Y, Z over
:class:`eges_tpu.ops.bigint.FieldP`), rows ride the VPU lanes, and the
whole ECDSA-recover pipeline becomes one fused XLA computation per batch.

Design notes (TPU-first, not a translation):

* **Branchless exceptional cases.**  libsecp256k1 branches on
  infinity/equal/opposite inputs; XLA cannot.  Each add computes the
  generic path, the doubling path and the trivial selections, then picks
  per row with masks.  Cost is ~2x field muls per add, won back many times
  over by batching.
* **Infinity encoding** is ``Z == 0`` (Y forced to 1 so formulas stay
  non-degenerate).
* **Scalar mul** is a GLV-split Strauss ladder: both recovery scalars
  decompose through the lambda endomorphism into ~128-bit halves, and a
  single 33-window `lax.fori_loop` (4-bit windows, four stacked table
  operands ±G/±lamG/±R/±lamR) does half the doublings of a plain
  256-bit ladder.  The compiled graph stays one loop body.
* No data-dependent shapes anywhere: invalid rows flow through with a
  validity mask instead of raising, matching the batch-verifier contract
  (the reference raises per call, secp256.go:105-124).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from eges_tpu.ops import bigint
from eges_tpu.ops.bigint import FP, FN, NLIMBS, int_to_limbs, select, eq, is_zero

# Generator (affine), as trace-time limb constants.
GX_INT = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY_INT = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
SEVEN = 7


def _const(x: int, like: jnp.ndarray) -> jnp.ndarray:
    # ``like*0 + const`` (not broadcast_to) so the result's varying-axes
    # type matches ``like`` under shard_map — these constants seed fori_loop
    # carries (strauss accumulators), which must keep a consistent type.
    return like * 0 + jnp.asarray(int_to_limbs(x))


# A Jacobian point batch is the tuple (X, Y, Z), each [..., 16] uint32.


def infinity(like: jnp.ndarray):
    """Batch of points at infinity, batch shape taken from ``like``."""
    z = jnp.zeros_like(like)
    return _const(0, like), _const(1, like), z


def is_infinity(pt) -> jnp.ndarray:
    return FP.is_zero_mod(pt[2])


def jac_double(pt):
    """Point doubling, a=0 curve (dbl-2009-l).  Handles infinity and
    2-torsion (y=0 cannot occur on secp256k1, but Y=0 rows yield Z3=0)."""
    X1, Y1, Z1 = pt
    A = FP.sqr(X1)
    B = FP.sqr(Y1)
    C = FP.sqr(B)
    t = FP.sqr(FP.add(X1, B))
    D = FP.mul_small(FP.sub(FP.sub(t, A), C), 2)
    E = FP.mul_small(A, 3)
    F = FP.sqr(E)
    X3 = FP.sub(F, FP.mul_small(D, 2))
    Y3 = FP.sub(FP.mul(E, FP.sub(D, X3)), FP.mul_small(C, 8))
    Z3 = FP.mul_small(FP.mul(Y1, Z1), 2)
    return X3, Y3, Z3


def jac_add_mixed(pt, x2: jnp.ndarray, y2: jnp.ndarray):
    """Mixed addition ``pt + (x2, y2)`` with (x2, y2) affine (Z2 = 1).

    Branchless over the exceptional cases:
      * pt at infinity          -> (x2, y2, 1)
      * same point (H=0, r=0)   -> doubling path
      * opposite (H=0, r!=0)    -> infinity
    (madd-2007-bl for the generic path.)
    """
    X1, Y1, Z1 = pt
    Z1Z1 = FP.sqr(Z1)
    U2 = FP.mul(x2, Z1Z1)
    S2 = FP.mul(FP.mul(y2, Z1), Z1Z1)
    H = FP.sub(U2, X1)
    r = FP.sub(S2, Y1)

    # generic path
    HH = FP.sqr(H)
    I = FP.mul_small(HH, 4)
    J = FP.mul(H, I)
    rr = FP.mul_small(r, 2)
    V = FP.mul(X1, I)
    X3 = FP.sub(FP.sub(FP.sqr(rr), J), FP.mul_small(V, 2))
    Y3 = FP.sub(FP.mul(rr, FP.sub(V, X3)), FP.mul_small(FP.mul(Y1, J), 2))
    Z3 = FP.mul(FP.mul_small(Z1, 2), H)

    # doubling path (pt == (x2,y2) as group elements)
    DX, DY, DZ = jac_double(pt)

    h0 = FP.is_zero_mod(H)
    r0 = FP.is_zero_mod(r)
    p1_inf = FP.is_zero_mod(Z1)
    dbl = h0 * r0
    opp = h0 * (1 - r0)

    one = _const(1, Z1)
    X = select(dbl, DX, X3)
    Y = select(dbl, DY, Y3)
    Z = select(dbl, DZ, Z3)
    Z = select(opp, jnp.zeros_like(Z), Z)
    Y = select(opp, one, Y)
    X = select(p1_inf, x2, X)
    Y = select(p1_inf, y2, Y)
    Z = select(p1_inf, one, Z)
    return X, Y, Z


def jac_add(p, q):
    """Full Jacobian addition ``p + q``, branchless exceptional cases
    (add-2007-bl for the generic path)."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = FP.sqr(Z1)
    Z2Z2 = FP.sqr(Z2)
    U1 = FP.mul(X1, Z2Z2)
    U2 = FP.mul(X2, Z1Z1)
    S1 = FP.mul(FP.mul(Y1, Z2), Z2Z2)
    S2 = FP.mul(FP.mul(Y2, Z1), Z1Z1)
    H = FP.sub(U2, U1)
    r = FP.sub(S2, S1)

    HH = FP.sqr(H)
    I = FP.mul_small(HH, 4)
    J = FP.mul(H, I)
    rr = FP.mul_small(r, 2)
    V = FP.mul(U1, I)
    X3 = FP.sub(FP.sub(FP.sqr(rr), J), FP.mul_small(V, 2))
    Y3 = FP.sub(FP.mul(rr, FP.sub(V, X3)), FP.mul_small(FP.mul(S1, J), 2))
    Z3 = FP.mul(FP.mul(FP.mul_small(FP.mul(Z1, Z2), 2), H), _const(1, H))

    DX, DY, DZ = jac_double(p)

    h0 = FP.is_zero_mod(H)
    r0 = FP.is_zero_mod(r)
    p_inf = FP.is_zero_mod(Z1)
    q_inf = FP.is_zero_mod(Z2)
    both = p_inf * q_inf
    dbl = h0 * r0 * (1 - p_inf) * (1 - q_inf)
    opp = h0 * (1 - r0) * (1 - p_inf) * (1 - q_inf)

    one = _const(1, Z1)
    X = select(dbl, DX, X3)
    Y = select(dbl, DY, Y3)
    Z = select(dbl, DZ, Z3)
    Z = select(opp, jnp.zeros_like(Z), Z)
    Y = select(opp, one, Y)
    # p infinite -> q; q infinite -> p; both -> infinity
    X = select(p_inf, X2, X)
    Y = select(p_inf, Y2, Y)
    Z = select(p_inf, Z2, Z)
    X = select(q_inf * (1 - p_inf), X1, X)
    Y = select(q_inf * (1 - p_inf), Y1, Y)
    Z = select(q_inf * (1 - p_inf), Z1, Z)
    Z = select(both, jnp.zeros_like(Z), Z)
    return X, Y, Z


def to_affine(pt):
    """Jacobian -> affine ``(x, y, ok)``; infinity rows get x=y=0, ok=0.
    Uses Montgomery batch inversion over the batch axis (one Fermat
    inverse per batch instead of per row)."""
    X, Y, Z = pt
    inf = FP.is_zero_mod(Z)
    zi = FP.inv_batched(Z)
    zi2 = FP.sqr(zi)
    x = FP.canon(FP.mul(X, zi2))
    y = FP.canon(FP.mul(Y, FP.mul(zi, zi2)))
    zero = jnp.zeros_like(x)
    return select(inf, zero, x), select(inf, zero, y), (1 - inf)


def on_curve(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Per-row flag: ``y^2 == x^3 + 7`` in F_P."""
    lhs = FP.sqr(y)
    rhs = FP.add(FP.mul(FP.sqr(x), x), _const(SEVEN, x))
    return FP.eq_mod(lhs, rhs)


WINDOW = 4
N_WINDOWS = 256 // WINDOW  # 64 base-16 digits

# -- GLV endomorphism constants (secp256k1's lambda/beta: lam^3 = 1 mod N,
# beta^3 = 1 mod P, lam*(x, y) = (beta*x, y)).  Published curve constants
# (the reference's libsecp26k1 uses the same split in ecmult_endo) -------
GLV_LAM = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
GLV_BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
_G_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_G_B1N = 0xE4437ED6010E88286F547FA90ABFE4C3   # -b1 (b1 is negative)
_G_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_G_B2 = _G_A1
# c_i = (k * g_i) >> 384 approximates round(k * b_i / N): the classic
# mul-and-shift rounding (off-by-one keeps |k1|,|k2| < 2^129, which the
# 33-window ladder covers)
_G_G1 = ((_G_B2 << 384) + bigint.N // 2) // bigint.N
_G_G2 = ((_G_B1N << 384) + bigint.N // 2) // bigint.N
GLV_WINDOWS = 33  # 132 bits covers |k| <= 2^129


def _scalar_digits(k: jnp.ndarray) -> jnp.ndarray:
    """``[..., 16]`` limbs -> ``[..., 64]`` base-16 digits, LSD first."""
    shifts = jnp.arange(0, bigint.LIMB_BITS, WINDOW, dtype=jnp.uint32)
    digs = (k[..., :, None] >> shifts[None, :]) & 0xF  # [..., 16, 4]
    return digs.reshape(*k.shape[:-1], N_WINDOWS)


@functools.lru_cache(maxsize=1)
def _g_table16() -> tuple[np.ndarray, np.ndarray]:
    """Fixed-base window table ``T[d] = d * G`` affine, d in 0..15.

    The TPU-native analogue of libsecp256k1's precomputed ecmult_gen
    table: computed once host-side with the golden model, baked into the
    graph as ``[16, 16]`` uint32 constants; per-row digit lookups become
    gathers.  The d=0 row is a dummy, masked out by the caller.  (The
    doubling chain is shared with the variable-base operand, so the table
    is unscaled — one table, not one per window.)"""
    from eges_tpu.crypto import secp256k1 as host

    tx = np.zeros((16, NLIMBS), np.uint32)
    ty = np.zeros((16, NLIMBS), np.uint32)
    pt = None
    for d in range(1, 16):
        pt = host.point_add(pt, (GX_INT, GY_INT))
        tx[d] = int_to_limbs(pt[0])
        ty[d] = int_to_limbs(pt[1])
    return tx, ty


def _build_point_table(px: jnp.ndarray, py: jnp.ndarray):
    """Per-row variable-base table ``d * P`` for d in 0..15, Jacobian,
    stacked ``[16, B..., 16]`` (15 mixed adds via one `lax.scan` so the
    add body compiles once, not 14 times).  Fused-kernel variant: the
    scan (the last multi-thousand-launch loop on that path) runs as one
    streamed kernel (pallas_kernels.point_table_pallas)."""
    inf = infinity(px)
    one = (px, py, _const(1, px))

    from eges_tpu.ops.pallas_kernels import (
        ladder_kernels_enabled, point_table_pallas,
    )
    if ladder_kernels_enabled() and px.ndim == 2:
        rest = point_table_pallas(px, py)
    else:
        def step(cur, _):
            nxt = jac_add_mixed(cur, px, py)
            return nxt, nxt

        _, rest = jax.lax.scan(step, one, None, length=14)
    tx = jnp.concatenate([jnp.stack([inf[0], one[0]]), rest[0]])
    ty = jnp.concatenate([jnp.stack([inf[1], one[1]]), rest[1]])
    tz = jnp.concatenate([jnp.stack([inf[2], one[2]]), rest[2]])
    return tx, ty, tz


def _build_affine_table(px: jnp.ndarray, py: jnp.ndarray):
    """Affine variable-base table ``d * P``, d in 0..15: the Jacobian
    table batch-normalized with ONE inversion scan over all 16*B entries.

    Buying affine entries up front lets the Strauss loop use the cheap
    mixed add for the R operand too (the full ``jac_add`` + its embedded
    doubling path leave the loop body) — fewer field muls per iteration
    AND a much smaller compiled graph.  Rows for d=0 are infinity; the
    caller masks them by digit anyway (d*P is never infinity for d in
    1..15 on a prime-order curve).
    """
    tx, ty, tz = _build_point_table(px, py)
    zi = FP.inv_batched(tz)
    zi2 = FP.sqr(zi)
    ax = FP.mul(tx, zi2)
    ay = FP.mul(ty, FP.mul(zi, zi2))
    return ax, ay


def _table_lookup(table, digit: jnp.ndarray):
    """Per-row gather from a ``[16, ..., 16]`` stacked Jacobian table."""
    idx = digit[None, ..., None]
    return tuple(
        jnp.take_along_axis(t, jnp.broadcast_to(idx, (1, *t.shape[1:])),
                            axis=0)[0]
        for t in table)


def _glv_decompose(k: jnp.ndarray):
    """``k`` (16 limbs, mod N) -> ``(k1_abs, neg1, k2_abs, neg2)`` with
    ``k = ±k1 + lam*(±k2) (mod N)`` and both magnitudes < 2^129.

    The scalar split that halves the ladder's doubling count (ref role:
    libsecp256k1's secp256k1_scalar_split_lambda).  Sign is a per-row
    flag; magnitudes stay far below N, so negativity of the mod-N
    residue is detected by size (anything above 2^140 must be N-small).
    """
    from eges_tpu.ops.pallas_kernels import (
        ladder_kernels_enabled, mulhi8_pallas,
    )
    if ladder_kernels_enabled() and k.ndim >= 2:
        # fused variant: (k * g) >> 384 as ONE launch per constant (the
        # 512-bit schoolbook product alone executed as ~600 dispatches)
        c1 = mulhi8_pallas(k.reshape(-1, NLIMBS),
                           _G_G1).reshape(*k.shape[:-1], 8)
        c2 = mulhi8_pallas(k.reshape(-1, NLIMBS),
                           _G_G2).reshape(*k.shape[:-1], 8)
    else:
        g1 = jnp.broadcast_to(jnp.asarray(int_to_limbs(_G_G1, 16)), k.shape)
        g2 = jnp.broadcast_to(jnp.asarray(int_to_limbs(_G_G2, 16)), k.shape)
        c1 = bigint.big_mul(k, g1)[..., 24:32]  # >> 384, fits 8 limbs
        c2 = bigint.big_mul(k, g2)[..., 24:32]
    pad = [(0, 0)] * (k.ndim - 1) + [(0, 8)]
    c1 = jnp.pad(c1, pad)
    c2 = jnp.pad(c2, pad)
    a1 = FN.const(_G_A1, k)
    a2 = FN.const(_G_A2, k)
    b1n = FN.const(_G_B1N, k)
    b2 = FN.const(_G_B2, k)
    # k1 = k - c1*a1 - c2*a2 (mod N);  k2 = c1*(-b1) - c2*b2 (mod N)
    k1 = FN.sub(FN.sub(k, FN.mul(c1, a1)), FN.mul(c2, a2))
    k2 = FN.sub(FN.mul(c1, b1n), FN.mul(c2, b2))
    thresh = jnp.broadcast_to(jnp.asarray(int_to_limbs(1 << 140)), k.shape)

    def sign_split(v):
        neg = 1 - bigint.big_lt(v, thresh)
        mag = select(neg, FN.neg(v), v)
        return mag, neg

    k1_abs, neg1 = sign_split(k1)
    k2_abs, neg2 = sign_split(k2)
    return k1_abs, neg1, k2_abs, neg2


def _digits33(k: jnp.ndarray) -> jnp.ndarray:
    """``[..., 16]`` limbs -> ``[..., 33]`` base-16 digits, LSD first
    (132 bits: the GLV half-scalar width)."""
    return _scalar_digits(k)[..., :GLV_WINDOWS]


@functools.lru_cache(maxsize=1)
def _g_lam_table16() -> tuple[np.ndarray, np.ndarray]:
    """Constant affine table ``d * (lam*G) = (beta*Gx_d, Gy_d)``."""
    tx, ty = _g_table16()
    ltx = tx.copy()
    for d in range(1, 16):
        x = bigint.limbs_to_int(tx[d])
        ltx[d] = int_to_limbs(GLV_BETA * x % bigint.P)
    return ltx, ty.copy()


def _strauss_prelude(u1, u2, rx, ry):
    """Shared front half of the GLV/Strauss ladder: scalar split,
    window digits, and the four operand tables.  Factored out so the
    streamed-kernel path, the XLA loop path, and the differential
    tests all consume identical inputs."""
    # one traced decomposition over both scalars (stacked leading axis —
    # the split subgraph is sizeable and must not appear twice)
    k1s, n1s, k2s, n2s = _glv_decompose(jnp.stack([u1, u2]))
    n1g, n1r = n1s[0], n1s[1]
    n2g, n2r = n2s[0], n2s[1]
    d_g1 = _digits33(k1s[0])
    d_g2 = _digits33(k2s[0])
    d_r1 = _digits33(k1s[1])
    d_r2 = _digits33(k2s[1])

    tgx_np, tgy_np = _g_table16()
    tlx_np, tly_np = _g_lam_table16()
    tgx, tgy = jnp.asarray(tgx_np), jnp.asarray(tgy_np)
    tlx, tly = jnp.asarray(tlx_np), jnp.asarray(tly_np)
    trx, try_ = _build_affine_table(rx, ry)
    tlrx = FP.mul(trx, FP.const(GLV_BETA, trx))  # beta * x per entry
    return ((d_g1, d_g2, d_r1, d_r2), (n1g, n2g, n1r, n2r),
            (tgx, tgy), (tlx, tly), (trx, try_, tlrx))


def strauss_gR(u1: jnp.ndarray, u2: jnp.ndarray, rx: jnp.ndarray, ry: jnp.ndarray):
    """GLV/Strauss ``u1*G + u2*R``: both scalars split by the lambda
    endomorphism, then one 33-window ladder over FOUR table operands
    (±G, ±lam*G, ±R, ±lam*R) — half the doublings of the plain 64-window
    ladder for the same adds (ref role: libsecp256k1 ecmult with endo).

    R is affine per-row; the lam*R table is the R table with beta-scaled
    x.  Negative half-scalars negate the looked-up point's y per row.
    """
    # Fused path (round-4 v2): TWO launches own the whole double-scalar
    # multiply.  The GLV kernel turns both scalars into ladder digits +
    # signs (ops/pallas_kernels.py glv_digits_pallas); the ladder kernel
    # does its OWN table lookups in VMEM (strauss_tab) — the former XLA
    # split/gather/sign-fold/pack stage was ~200 dispatches and two
    # [W, 64, B] operand arrays re-uploaded per call, and on this
    # backend every dispatch with fresh content is a round trip.
    from eges_tpu.ops.pallas_kernels import (
        glv_digits_pallas, ladder_kernels_enabled, strauss_tab,
    )
    if ladder_kernels_enabled() and rx.ndim == 2:
        B = rx.shape[0]
        dig, neg = glv_digits_pallas(u1, u2)
        trx, try_ = _build_affine_table(rx, ry)
        tlrx = FP.mul(trx, FP.const(GLV_BETA, trx))
        return strauss_tab(dig, neg, _table_rows(trx, B),
                           _table_rows(tlrx, B), _table_rows(try_, B), B)

    (d_g1, d_g2, d_r1, d_r2), (n1g, n2g, n1r, n2r), \
        (tgx, tgy), (tlx, tly), (trx, try_, tlrx) = \
        _strauss_prelude(u1, u2, rx, ry)

    acc = infinity(rx)
    negs = jnp.stack([jnp.broadcast_to(n1g, d_g1.shape[:-1]),
                      jnp.broadcast_to(n2g, d_g1.shape[:-1]),
                      jnp.broadcast_to(n1r, d_g1.shape[:-1]),
                      jnp.broadcast_to(n2r, d_g1.shape[:-1])])

    def body(i, acc):
        j = GLV_WINDOWS - 1 - i
        acc = jax.lax.fori_loop(0, WINDOW,
                                lambda _, a: jac_double(a), acc)
        dj = [jax.lax.dynamic_index_in_dim(d, j, axis=-1, keepdims=False)
              for d in (d_g1, d_g2, d_r1, d_r2)]
        # stacked operands so the conditional mixed add traces ONCE
        xs = jnp.stack([jnp.take(tgx, dj[0], axis=0),
                        jnp.take(tlx, dj[1], axis=0),
                        _table_lookup((trx,), dj[2])[0],
                        _table_lookup((tlrx,), dj[3])[0]])
        ys = jnp.stack([jnp.take(tgy, dj[0], axis=0),
                        jnp.take(tly, dj[1], axis=0),
                        _table_lookup((try_,), dj[2])[0],
                        _table_lookup((try_,), dj[3])[0]])
        nzs = jnp.stack([(d != 0).astype(jnp.uint32) for d in dj])

        def add_step(t, a):
            y_t = select(negs[t], FP.neg(ys[t]), ys[t])
            added = jac_add_mixed(a, xs[t], y_t)
            return tuple(select(nzs[t], n, o) for n, o in zip(added, a))

        return jax.lax.fori_loop(0, 4, add_step, acc)

    return jax.lax.fori_loop(0, GLV_WINDOWS, body, acc)


def _table_rows(tab: jnp.ndarray, B: int) -> jnp.ndarray:
    """``[16, B, 16]`` entry-stacked affine table -> ``[256, Bpad]``
    (row ``16*d + k`` = limb k of entry d), the strauss_tab layout."""
    from eges_tpu.ops.pallas_kernels import LANE_BLOCK

    pad = (-B) % LANE_BLOCK
    return jnp.pad(jnp.transpose(tab, (0, 2, 1)).reshape(-1, B),
                   ((0, 0), (0, pad)))


def pack_strauss_tab_inputs(digits, negs, r_tab):
    """Inputs for the self-gathering ladder kernel (strauss_tab) built
    from the XLA prelude's digit/sign arrays: window digits as one
    ``[W, 8, Bpad]`` array (rows 0-3: g1/g2/r1/r2, MSD-first), signs as
    ``[8, Bpad]``, and the three affine R tables re-rowed.  Production
    uses glv_digits_pallas instead; this path pins the two digit
    pipelines against each other in tests."""
    from eges_tpu.ops.pallas_kernels import LANE_BLOCK

    d_g1, d_g2, d_r1, d_r2 = digits
    n1g, n2g, n1r, n2r = negs
    trx, try_, tlrx = r_tab
    B, W = d_g1.shape
    pad = (-B) % LANE_BLOCK
    dig = jnp.stack([d[..., ::-1] for d in (d_g1, d_g2, d_r1, d_r2)])
    dig = jnp.pad(jnp.transpose(dig, (2, 0, 1)), ((0, 0), (0, 4), (0, pad)))
    neg = jnp.pad(jnp.stack([
        jnp.broadcast_to(n, (B,)).astype(jnp.uint32)
        for n in (n1g, n2g, n1r, n2r)]), ((0, 4), (0, pad)))
    return dig, neg, _table_rows(trx, B), _table_rows(tlrx, B), \
        _table_rows(try_, B)


def strauss_gR_plain(u1: jnp.ndarray, u2: jnp.ndarray, rx: jnp.ndarray, ry: jnp.ndarray):
    """Windowed Shamir/Strauss ``u1*G + u2*R`` (R affine, per-row).

    The double-scalar multiplication at the core of ECDSA recovery
    (ref: libsecp256k1 ecmult's role, consumed by secp256.go:105
    RecoverPubkey).  4-bit windows: 64 iterations of (4 doublings + a
    fixed-base table add + a variable-base table add) replace 256
    per-bit iterations with two conditional adds each — ~2.5x fewer
    field multiplications, and the fixed-base adds hit trace-time
    constant tables instead of runtime doublings.
    """
    d1 = _scalar_digits(u1)  # [..., 64]
    d2 = _scalar_digits(u2)
    tgx_np, tgy_np = _g_table16()
    tgx = jnp.asarray(tgx_np)
    tgy = jnp.asarray(tgy_np)
    trx, try_ = _build_affine_table(rx, ry)
    acc = infinity(rx)

    def body(i, acc):
        j = N_WINDOWS - 1 - i
        acc = jax.lax.fori_loop(0, WINDOW, lambda _, a: jac_double(a), acc)
        dj1 = jax.lax.dynamic_index_in_dim(d1, j, axis=-1, keepdims=False)
        dj2 = jax.lax.dynamic_index_in_dim(d2, j, axis=-1, keepdims=False)
        # fixed-base gather (constant table) and variable-base gather
        # (per-row affine table), stacked so the conditional mixed add
        # below traces ONCE for both operands — the add body is by far
        # the largest subgraph in the loop (graph size ~= compile time)
        gx = jnp.take(tgx, dj1, axis=0)
        gy = jnp.take(tgy, dj1, axis=0)
        rx_d, ry_d = _table_lookup((trx, try_), dj2)
        xs = jnp.stack([gx, rx_d])
        ys = jnp.stack([gy, ry_d])
        nzs = jnp.stack([(dj1 != 0).astype(jnp.uint32),
                         (dj2 != 0).astype(jnp.uint32)])

        def add_step(t, a):
            added = jac_add_mixed(a, xs[t], ys[t])
            nz = nzs[t]
            return tuple(select(nz, n, o) for n, o in zip(added, a))

        return jax.lax.fori_loop(0, 2, add_step, acc)

    return jax.lax.fori_loop(0, N_WINDOWS, body, acc)


def scalar_mul(k: jnp.ndarray, px: jnp.ndarray, py: jnp.ndarray):
    """Windowed ``k * P`` for an affine per-row point (used by tests and
    the batched classic-verify path)."""
    digs = _scalar_digits(k)
    tpx, tpy = _build_affine_table(px, py)
    acc = infinity(px)

    def body(i, acc):
        j = N_WINDOWS - 1 - i
        acc = jax.lax.fori_loop(0, WINDOW, lambda _, a: jac_double(a), acc)
        dj = jax.lax.dynamic_index_in_dim(digs, j, axis=-1, keepdims=False)
        px_d, py_d = _table_lookup((tpx, tpy), dj)
        added = jac_add_mixed(acc, px_d, py_d)
        nz = (dj != 0).astype(jnp.uint32)
        return tuple(select(nz, n, o) for n, o in zip(added, acc))

    return jax.lax.fori_loop(0, N_WINDOWS, body, acc)


def ecrecover_point_fused(sigs: jnp.ndarray, hashes: jnp.ndarray):
    """Fused-kernel twin of :func:`ecrecover_point` (TPU backends),
    wire bytes in: the whole pipeline is ~12 launches — composite stage
    kernels around the two pow ladders and the self-gathering Strauss
    kernel — instead of the general path's per-op graph.  The prelude
    kernel unpacks r/s/v/z itself (the byte shuffles ran as ~14 XLA
    dispatches).  Returns ``(qx, qy, ok, words)`` where ``words [34,
    Bpad]`` is the ready-padded keccak block of ``qx || qy`` (the
    finish kernel packs bytes in-kernel so the address tail needs no
    XLA byte shuffling).  Outputs are value-identical to the general
    path; every kernel's math is the ``_k_*`` mirror of the graph ops
    (differential-tested in numpy and on hardware)."""
    from eges_tpu.ops import bigint as bg
    from eges_tpu.ops.pallas_kernels import (
        pow_mod_pallas, recover_finish_pallas, recover_prelude_pallas,
        u1u2_pallas, y_fix_pallas,
    )

    x, y_sq, ok0, r, s, z, v = recover_prelude_pallas(sigs, hashes)
    root = pow_mod_pallas(y_sq, (bg.P + 1) // 4, "p")
    y, y_ok = y_fix_pallas(root, y_sq, v)
    r_inv = pow_mod_pallas(r, bg.N - 2, "n")
    u1, u2 = u1u2_pallas(z, s, r_inv)
    q = strauss_gR(u1, u2, x, y)
    zi_raw = pow_mod_pallas(q[2], bg.P - 2, "p")
    return recover_finish_pallas(q[0], q[1], q[2], zi_raw, ok0 * y_ok)


def ecrecover_point(z: jnp.ndarray, r: jnp.ndarray, s: jnp.ndarray,
                    v: jnp.ndarray):
    """Batched core of public-key recovery (ref: secp256.go:105).

    Inputs: ``z`` message-hash, ``r``/``s`` signature scalars (all
    ``[..., 16]`` limbs), ``v`` recovery id ``[...]`` uint32 in [0, 4).
    Returns affine ``(qx, qy, ok)`` with ``ok`` a 0/1 validity mask —
    invalid rows (r/s out of range, r not an x-coordinate, point at
    infinity) are masked, never raised.
    """
    one = _const(1, r)
    n_lim = jnp.broadcast_to(FN.m_limbs, r.shape)
    p_lim = jnp.broadcast_to(FP.m_limbs, r.shape)

    r_ok = (1 - is_zero(r)) * bigint.big_lt(r, n_lim)
    s_ok = (1 - is_zero(s)) * bigint.big_lt(s, n_lim)
    v_ok = (v < 4).astype(jnp.uint32)

    # x = r + (v >= 2 ? N : 0), must be < P
    hi = (v >= 2).astype(jnp.uint32)
    x_wide = bigint.big_add(r, select(hi, n_lim, jnp.zeros_like(r)), NLIMBS + 1)
    x_ok = is_zero(x_wide[..., NLIMBS:]) * bigint.big_lt(x_wide[..., :NLIMBS], p_lim)
    x = x_wide[..., :NLIMBS]

    # y from x^3 + 7, parity fixed to v&1
    y_sq = FP.add(FP.mul(FP.sqr(x), x), _const(SEVEN, x))
    y, y_ok = FP.sqrt(y_sq)
    y = FP.canon(y)  # parity is only meaningful on the canonical value
    want_odd = (v & 1).astype(jnp.uint32)
    y_odd = (y[..., 0] & 1).astype(jnp.uint32)
    y = select(want_odd ^ y_odd, FP.neg(y), y)

    # u1 = -z/r mod N, u2 = s/r mod N
    r_inv = FN.inv_batched(r)
    z_mod = FN.red(jnp.pad(z, [(0, 0)] * (z.ndim - 1) + [(0, 1)]))
    u1 = FN.neg(FN.mul(z_mod, r_inv))
    u2 = FN.mul(s, r_inv)

    q = strauss_gR(u1, u2, x, y)
    qx, qy, not_inf = to_affine(q)
    ok = r_ok * s_ok * v_ok * x_ok * y_ok * not_inf
    zero = jnp.zeros_like(qx)
    return select(ok, qx, zero), select(ok, qy, zero), ok


def ecdsa_verify_point(z: jnp.ndarray, r: jnp.ndarray, s: jnp.ndarray,
                       qx: jnp.ndarray, qy: jnp.ndarray) -> jnp.ndarray:
    """Batched classic ECDSA verify against known pubkeys
    (ref: secp256.go:126 VerifySignature; rejects high-s malleable sigs
    the same way libsecp256k1's normalized verify does)."""
    n_lim = jnp.broadcast_to(FN.m_limbs, r.shape)
    half_n = _const((FN.m - 1) // 2 + 1, r)  # s < ceil(N/2)+? use s <= N//2
    r_ok = (1 - is_zero(r)) * bigint.big_lt(r, n_lim)
    s_ok = (1 - is_zero(s)) * bigint.big_lt(s, half_n)
    q_ok = on_curve(qx, qy)

    s_inv = FN.inv_batched(s)
    z_mod = FN.red(jnp.pad(z, [(0, 0)] * (z.ndim - 1) + [(0, 1)]))
    u1 = FN.mul(z_mod, s_inv)
    u2 = FN.mul(r, s_inv)
    pt = strauss_gR(u1, u2, qx, qy)
    px, _, not_inf = to_affine(pt)
    # compare px mod N with r
    px_mod = FN.red(jnp.pad(px, [(0, 0)] * (px.ndim - 1) + [(0, 1)]))
    return r_ok * s_ok * q_ok * not_inf * eq(px_mod, r)
