"""Pallas TPU kernels for the bignum hot loop (SURVEY §7 step 1:
"secp256k1 batch ops as JAX/Pallas kernels").

The XLA graph form of the verifier (ops/bigint.py, ops/ec.py) already
keeps everything fused on-device, but it pays twice for being a graph:
~66k StableHLO ops (45-85 s compiles) and per-op dispatch granularity.
These kernels collapse the Strauss ladder's window step — the ~4000
field multiplies per recovered signature — into TWO hand-tiled Mosaic
kernels:

* ``ladder_double4``: four chained Jacobian doublings (the per-window
  doubling run) with the accumulator resident in VMEM throughout.
* ``ladder_add_mixed``: one conditional mixed add — table operand,
  per-row y-negation (GLV sign), the branchless exceptional cases of
  ``ec.jac_add_mixed`` (infinity/double/opposite) and the digit!=0
  select, all fused.

Layout: the graph stores a field element as ``[B, 16]`` u32 limbs (rows
on sublanes).  Kernels TRANSPOSE to ``[16, B]`` — 16 limbs land exactly
on two 8-sublane rows and the batch rides the 128-wide lane axis, so
every limb row is one natural VPU vector.  The in-kernel field library
(``_k_*``) mirrors ``bigint.FieldP`` bit-for-bit — same fold constants,
same carry chains, same relaxed representation — so kernel and graph
agree exactly.  Testing strategy (tests/test_pallas_kernels.py): the
small F_P-mul kernel is differential-tested through ``pallas_call`` in
interpret mode (covering the shared tiling/transpose plumbing); the
fused ladder kernels' MATH is differential-tested in pure numpy via the
``xp`` namespace parameter (identical uint32 wrap semantics, runs in
milliseconds where interpret-mode XLA compiles of the flat graphs take
tens of minutes on a 1-core host); the kernels themselves are exercised
end-to-end only on a real TPU (Mosaic), where ``harness/tpu_watch.py``
A/Bs them the moment the tunnel answers.

Dispatch: ``EGES_TPU_PALLAS=1`` keeps the historical per-multiply
kernel hook in ``FieldP.mul``; ``EGES_TPU_PALLAS=ladder`` routes the
``strauss_gR`` window step through the fused kernels — on the TPU
backend only (interpret mode lowers kernels back to per-block HLO,
which would re-explode the CPU graph the rolled loops were built to
avoid).

Ref role: crypto/secp256k1/libsecp256k1/src/ecmult_impl.h (the windowed
ladder the reference runs in C); consumed by secp256.go:105.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from eges_tpu.ops.bigint import MASK, NLIMBS, P, int_to_limbs

LANE_BLOCK = 256  # batch columns per kernel invocation

_P_LIMBS = [int(v) for v in int_to_limbs(P)]
_SUBC_LIMBS = [int(v) for v in int_to_limbs((1 << 256) - 2 * ((1 << 256) - P) + 1)]
_ONE_LIMBS = [1] + [0] * 15


# ---------------------------------------------------------------------------
# in-kernel field library: a value is a Python list of 16 [B]-wide u32
# vectors (limb-major).  Bit-identical to bigint.FieldP's relaxed form.
# ---------------------------------------------------------------------------

def _k_carry_tail(cols, xp=jnp):
    """16 columns (each < 2^31) -> relaxed 16-limb value; the shared
    reduction tail of ``FieldP._reduce_cols`` (two full carry chains +
    delta folds + the closing 5-step mini-chain).

    All ``_k_*`` helpers take an array namespace ``xp``: ``jnp`` when
    tracing inside a kernel, ``numpy`` in the differential tests — the
    flat unrolled math is far too large for XLA CPU to compile in
    reasonable time (compile cost grows superlinearly in flat-graph
    size; measured 9 s for one in-kernel multiply, 84 s for four), but
    numpy executes it in milliseconds with the exact same uint32 wrap
    semantics, pinning the math bit-for-bit against the graph path.
    """
    mask = xp.uint32(MASK)
    c977 = xp.uint32(977)
    out = []
    c = xp.zeros_like(cols[0])
    for k in range(16):
        t = cols[k] + c
        out.append(t & mask)
        c = t >> 16
    out[0] = out[0] + c * c977
    out[2] = out[2] + c
    c = xp.zeros_like(c)
    for k in range(16):
        t = out[k] + c
        out[k] = t & mask
        c = t >> 16
    out[0] = out[0] + c * c977
    out[2] = out[2] + c
    cc = xp.zeros_like(c)
    for k in range(5):
        t = out[k] + cc
        out[k] = t & mask
        cc = t >> 16
    return out


def _k_mul(a, b, xp=jnp):
    """Schoolbook 16x16 product columns + delta folds + carry tail
    (mirrors ``big_mul_cols`` + ``FieldP._reduce_cols``)."""
    mask = xp.uint32(MASK)
    c977 = xp.uint32(977)
    zero = xp.zeros_like(a[0])
    cols = [zero] * 32
    for i in range(NLIMBS):
        ai = a[i]
        for j in range(NLIMBS):
            p = ai * b[j]
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)
    # fold columns >= 16 via delta = 2^32 + 977 (two passes suffice)
    for _ in range(2):
        if len(cols) <= 16:
            break
        hi = cols[16:]
        lo = cols[:16] + [zero] * max(0, len(hi) + 2 - 16)
        for j, h in enumerate(hi):
            lo[j] = lo[j] + h * c977
            lo[j + 2] = lo[j + 2] + h
        cols = lo[: max(16, len(hi) + 2)]
    return _k_carry_tail(cols, xp)


def _k_sqr(a, xp=jnp):
    return _k_mul(a, a, xp)


def _k_add(a, b, xp=jnp):
    return _k_carry_tail([x + y for x, y in zip(a, b)], xp)


def _k_sub(a, b, xp=jnp):
    """Branchless a - b: a + (0xFFFF - b) + (2^256 - 2*delta + 1),
    mirroring ``FieldP.sub``."""
    mask = xp.uint32(MASK)
    return _k_carry_tail([
        x + (mask - y) + xp.uint32(_SUBC_LIMBS[k])
        for k, (x, y) in enumerate(zip(a, b))], xp)


def _k_neg(a, xp=jnp):
    return _k_sub([xp.zeros_like(v) for v in a], a, xp)


def _k_mul_small(a, k: int, xp=jnp):
    assert k < 16
    return _k_carry_tail([v * xp.uint32(k) for v in a], xp)


def _k_is_zero_mod(a, xp=jnp):
    """Relaxed a ≡ 0 (mod P): exactly 0 or exactly P (u32 0/1 vector)."""
    z = a[0] == 0
    p = a[0] == xp.uint32(_P_LIMBS[0])
    for k in range(1, 16):
        z = z & (a[k] == 0)
        p = p & (a[k] == xp.uint32(_P_LIMBS[k]))
    return (z | p).astype(xp.uint32)


def _k_select(flag, a, b, xp=jnp):
    """flag ? a : b, flag a [B] u32 0/1 vector."""
    f = flag.astype(bool)
    return [xp.where(f, x, y) for x, y in zip(a, b)]


def _k_jac_double(X1, Y1, Z1, xp=jnp):
    """Mirror of ``ec.jac_double`` (dbl-2009-l, a=0)."""
    A = _k_sqr(X1, xp)
    B = _k_sqr(Y1, xp)
    C = _k_sqr(B, xp)
    t = _k_sqr(_k_add(X1, B, xp), xp)
    D = _k_mul_small(_k_sub(_k_sub(t, A, xp), C, xp), 2, xp)
    E = _k_mul_small(A, 3, xp)
    F = _k_sqr(E, xp)
    X3 = _k_sub(F, _k_mul_small(D, 2, xp), xp)
    Y3 = _k_sub(_k_mul(E, _k_sub(D, X3, xp), xp), _k_mul_small(C, 8, xp), xp)
    Z3 = _k_mul_small(_k_mul(Y1, Z1, xp), 2, xp)
    return X3, Y3, Z3


def _k_jac_add_mixed(X1, Y1, Z1, x2, y2, xp=jnp):
    """Mirror of ``ec.jac_add_mixed`` (madd-2007-bl + branchless
    exceptional cases)."""
    Z1Z1 = _k_sqr(Z1, xp)
    U2 = _k_mul(x2, Z1Z1, xp)
    S2 = _k_mul(_k_mul(y2, Z1, xp), Z1Z1, xp)
    H = _k_sub(U2, X1, xp)
    r = _k_sub(S2, Y1, xp)

    HH = _k_sqr(H, xp)
    I = _k_mul_small(HH, 4, xp)
    J = _k_mul(H, I, xp)
    rr = _k_mul_small(r, 2, xp)
    V = _k_mul(X1, I, xp)
    X3 = _k_sub(_k_sub(_k_sqr(rr, xp), J, xp), _k_mul_small(V, 2, xp), xp)
    Y3 = _k_sub(_k_mul(rr, _k_sub(V, X3, xp), xp),
                _k_mul_small(_k_mul(Y1, J, xp), 2, xp), xp)
    Z3 = _k_mul(_k_mul_small(Z1, 2, xp), H, xp)

    DX, DY, DZ = _k_jac_double(X1, Y1, Z1, xp)

    h0 = _k_is_zero_mod(H, xp)
    r0 = _k_is_zero_mod(r, xp)
    p1_inf = _k_is_zero_mod(Z1, xp)
    dbl = h0 * r0
    opp = h0 * (1 - r0)

    onef = [xp.broadcast_to(xp.uint32(v), X1[0].shape)
            for v in _ONE_LIMBS]
    zerof = [xp.zeros_like(v) for v in X1]
    X = _k_select(dbl, DX, X3, xp)
    Y = _k_select(dbl, DY, Y3, xp)
    Z = _k_select(dbl, DZ, Z3, xp)
    Z = _k_select(opp, zerof, Z, xp)
    Y = _k_select(opp, onef, Y, xp)
    X = _k_select(p1_inf, x2, X, xp)
    Y = _k_select(p1_inf, y2, Y, xp)
    Z = _k_select(p1_inf, onef, Z, xp)
    return X, Y, Z


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _read16(ref):
    return [ref[k, :] for k in range(NLIMBS)]


def _write16(ref, val):
    for k in range(NLIMBS):
        ref[k, :] = val[k]


def _fp_mul_kernel(a_ref, b_ref, out_ref):
    """One [16, LANE_BLOCK] tile: out = a * b mod P (relaxed form)."""
    _write16(out_ref, _k_mul(_read16(a_ref), _read16(b_ref)))


def _double4_kernel(x_ref, y_ref, z_ref, ox_ref, oy_ref, oz_ref):
    """Four chained Jacobian doublings — the WINDOW=4 doubling run of a
    Strauss window step — with the point resident in VMEM throughout."""
    X, Y, Z = _read16(x_ref), _read16(y_ref), _read16(z_ref)
    for _ in range(4):
        X, Y, Z = _k_jac_double(X, Y, Z)
    _write16(ox_ref, X)
    _write16(oy_ref, Y)
    _write16(oz_ref, Z)


def _add_mixed_kernel(x_ref, y_ref, z_ref, px_ref, py_ref,
                      neg_ref, nz_ref, ox_ref, oy_ref, oz_ref):
    """One fused conditional table add: y-negation by the GLV sign flag,
    the full branchless mixed add, then the digit!=0 select."""
    X, Y, Z = _read16(x_ref), _read16(y_ref), _read16(z_ref)
    px, py = _read16(px_ref), _read16(py_ref)
    neg = neg_ref[0, :]
    nz = nz_ref[0, :]
    py = _k_select(neg, _k_neg(py), py)
    AX, AY, AZ = _k_jac_add_mixed(X, Y, Z, px, py)
    _write16(ox_ref, _k_select(nz, AX, X))
    _write16(oy_ref, _k_select(nz, AY, Y))
    _write16(oz_ref, _k_select(nz, AZ, Z))


# ---------------------------------------------------------------------------
# wrappers: [B, 16] graph layout <-> [16, B] kernel tiles
# ---------------------------------------------------------------------------

def _as_tiles(arrs, flags, B):
    pad = (-B) % LANE_BLOCK
    ats = [jnp.pad(a, ((0, pad), (0, 0))).T for a in arrs]
    fts = [jnp.pad(f.astype(jnp.uint32), (0, pad)).reshape(1, -1)
           for f in flags]
    return ats, fts, ats[0].shape[1] // LANE_BLOCK


def _pallas(kernel, ats, fts, n_blocks, n_out, interpret):
    from jax.experimental import pallas as pl

    wide = ats[0].shape[1]
    specs = ([pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))] * len(ats)
             + [pl.BlockSpec((1, LANE_BLOCK), lambda i: (0, i))] * len(fts))
    return pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32)
                        for _ in range(n_out)),
        grid=(n_blocks,),
        in_specs=specs,
        out_specs=tuple(pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))
                        for _ in range(n_out)),
        interpret=interpret,
    )(*ats, *fts)


def fp_mul_pallas(a: jnp.ndarray, b: jnp.ndarray, *,
                  interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 16] x [B, 16] -> [B, 16]`` F_P multiply via the Pallas
    kernel; bit-identical to ``bigint.FP.mul`` (relaxed outputs)."""
    if interpret is None:
        # axon is the tunnel's TPU platform — real Mosaic, not interpret
        interpret = jax.default_backend() not in ("tpu", "axon")
    B = a.shape[0]
    ats, _, nb = _as_tiles([a, b], [], B)
    out, = _pallas(_fp_mul_kernel, ats, [], nb, 1, interpret)
    return out.T[:B]


def ladder_double4(pt, *, interpret: bool | None = None):
    """Four doublings of a Jacobian point batch ``(X, Y, Z)`` each
    ``[B, 16]``; bit-identical to four ``ec.jac_double`` calls."""
    if interpret is None:
        # axon is the tunnel's TPU platform — real Mosaic, not interpret
        interpret = jax.default_backend() not in ("tpu", "axon")
    B = pt[0].shape[0]
    ats, _, nb = _as_tiles(list(pt), [], B)
    out = _pallas(_double4_kernel, ats, [], nb, 3, interpret)
    return tuple(o.T[:B] for o in out)


def ladder_add_mixed(pt, px, py, neg, nz, *,
                     interpret: bool | None = None):
    """Fused conditional mixed add: ``pt + (px, ±py)`` where the sign is
    ``neg`` per row, rows with ``nz == 0`` keep ``pt``.  Bit-identical
    to the select/neg/``ec.jac_add_mixed`` composition in
    ``strauss_gR``'s add step."""
    if interpret is None:
        # axon is the tunnel's TPU platform — real Mosaic, not interpret
        interpret = jax.default_backend() not in ("tpu", "axon")
    B = pt[0].shape[0]
    ats, fts, nb = _as_tiles(list(pt) + [px, py], [neg, nz], B)
    out = _pallas(_add_mixed_kernel, ats, fts, nb, 3, interpret)
    return tuple(o.T[:B] for o in out)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def pallas_enabled() -> bool:
    """Historical opt-in: ``EGES_TPU_PALLAS=1`` routes ``FP.mul`` on 2-D
    batches through the per-multiply kernel (``bigint.FieldP.mul``)."""
    return os.environ.get("EGES_TPU_PALLAS", "") == "1"


@functools.lru_cache(maxsize=1)
def ladder_kernels_enabled() -> bool:
    """``EGES_TPU_PALLAS=ladder`` fuses the Strauss window step into the
    double4/add kernels — TPU backend only (interpret mode would lower
    each kernel back to per-block HLO and re-explode the CPU graph)."""
    return (os.environ.get("EGES_TPU_PALLAS", "") == "ladder"
            and jax.default_backend() in ("tpu", "axon"))


# ---------------------------------------------------------------------------
# order-N (scalar field) multiply kernel: mirrors OrderN.mul =
# _red_cols(big_mul_cols(a, b)) — the mod-N arithmetic of the scalar
# recovery prelude (u1/u2, GLV decomposition)
# ---------------------------------------------------------------------------

from eges_tpu.ops.bigint import N as _ORDER_N  # noqa: E402

_N_LIMBS_C = [int(v) for v in int_to_limbs(_ORDER_N)]
_N_DELTA = (1 << 256) - _ORDER_N
_N_DELTA_LIMBS = [int(v)
                  for v in int_to_limbs(_N_DELTA,
                                        (_N_DELTA.bit_length() + 15) // 16)]


def _k_carry(cols, n_out, xp=jnp):
    """Generic carry chain over small (< 2^31) columns -> n_out limbs."""
    mask = xp.uint32(MASK)
    out = []
    c = xp.zeros_like(cols[0])
    for k in range(len(cols)):
        t = cols[k] + c
        out.append(t & mask)
        c = t >> 16
    while len(out) < n_out:
        out.append(c & mask)
        c = c >> 16
    return out[:n_out]


def _k_mul_cols(a, b_const, xp=jnp):
    """Uncarried schoolbook columns of (limb list a) x (Python-int limb
    constants b_const); mirrors ``big_mul_cols``."""
    mask = xp.uint32(MASK)
    zero = xp.zeros_like(a[0])
    cols = [zero] * (len(a) + len(b_const))
    for i, ai in enumerate(a):
        for j, bj in enumerate(b_const):
            p = ai * xp.uint32(bj)
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)
    return cols


def _k_mul_cols_vv(a, b, xp=jnp):
    """Uncarried schoolbook columns, both operands limb lists."""
    mask = xp.uint32(MASK)
    zero = xp.zeros_like(a[0])
    cols = [zero] * (len(a) + len(b))
    for i, ai in enumerate(a):
        for j, bj in enumerate(b):
            p = ai * bj
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)
    return cols


def _k_cond_sub_n(a, xp=jnp):
    """One conditional subtract of N (borrow chain + select)."""
    mask = xp.uint32(MASK)
    out = []
    borrow = xp.zeros_like(a[0])
    for k in range(16):
        t = a[k] + xp.uint32(1 << 16) - xp.uint32(_N_LIMBS_C[k]) - borrow
        out.append(t & mask)
        borrow = xp.uint32(1) - (t >> 16)
    return _k_select(borrow, a, out, xp)


def _k_fn_mul(a, b, xp=jnp):
    """Canonical mod-N product; mirrors ``OrderN.mul`` fold-for-fold
    (three delta folds 32 -> 26 -> 20 -> 16+eps, then two top-limb
    folds and two conditional subtracts)."""
    cols = _k_mul_cols_vv(a, b, xp)
    while len(cols) > 16:
        lo = cols[:16]
        hi = _k_carry(cols[16:], len(cols) - 16 + 1, xp)
        prod = _k_mul_cols(hi, _N_DELTA_LIMBS, xp)
        w = max(16, len(prod))
        zero = xp.zeros_like(cols[0])
        lo_w = lo + [zero] * (w - 16)
        pr_w = prod + [zero] * (w - len(prod))
        cols = [x + y for x, y in zip(lo_w, pr_w)]
    a17 = _k_carry(cols, 17, xp)
    for _ in range(2):
        top = a17[16]
        fold = _k_mul_cols([top], _N_DELTA_LIMBS, xp)[:16]
        zero = xp.zeros_like(top)
        fold = fold + [zero] * (16 - len(fold))
        a17 = _k_carry([x + y for x, y in zip(a17[:16], fold)], 17, xp)
    out = a17[:16]
    out = _k_cond_sub_n(out, xp)
    return _k_cond_sub_n(out, xp)


def _fn_mul_kernel(a_ref, b_ref, out_ref):
    """One [16, LANE_BLOCK] tile: out = a * b mod N (canonical)."""
    _write16(out_ref, _k_fn_mul(_read16(a_ref), _read16(b_ref)))


def fn_mul_pallas(a: jnp.ndarray, b: jnp.ndarray, *,
                  interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 16] x [B, 16] -> [B, 16]`` mod-N multiply via the Pallas
    kernel; bit-identical to ``bigint.FN.mul``."""
    if interpret is None:
        # axon is the tunnel's TPU platform — real Mosaic, not interpret
        interpret = jax.default_backend() not in ("tpu", "axon")
    B = a.shape[0]
    ats, _, nb = _as_tiles([a, b], [], B)
    out, = _pallas(_fn_mul_kernel, ats, [], nb, 1, interpret)
    return out.T[:B]
