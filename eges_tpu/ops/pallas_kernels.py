"""Pallas TPU kernels for the bignum hot loop (SURVEY §7 step 1:
"secp256k1 batch ops as JAX/Pallas kernels").

The XLA graph form of the verifier (ops/bigint.py, ops/ec.py) already
keeps everything fused on-device; these kernels are the next rung —
hand-placed VMEM tiles for the single hottest primitive, the F_P
modular multiply, which the Strauss ladder executes ~4000x per
recovered signature.

Layout: the graph stores a field element as ``[B, 16]`` u32 limbs
(rows on sublanes).  The kernel TRANSPOSES to ``[16, B]`` — 16 limbs
land exactly on a float32-tile's 8x128 sublane granularity (two
sublanes of 8) and the batch rides the 128-wide lane axis, so every
limb row is one natural VPU vector.  The schoolbook product unrolls
256 mul-adds over Python-static sublane indices; the pseudo-Mersenne
reduction mirrors ``FieldP._reduce_cols`` bit-for-bit (same fold
constants, same carry chains), so kernel and graph agree exactly.

The kernel is opt-in (`EGES_TPU_PALLAS=1` or ``use_pallas=True``
callers) and falls back to the jnp path off-TPU; correctness is pinned
by a differential test in interpret mode (tests/test_pallas_kernels.py).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from eges_tpu.ops.bigint import MASK, NLIMBS

LANE_BLOCK = 256  # batch columns per kernel invocation


def _fp_mul_kernel(a_ref, b_ref, out_ref):
    """One [16, LANE_BLOCK] tile: out = a * b mod P (relaxed form).

    Mirrors ``big_mul_cols`` + ``FieldP._reduce_cols``: column sums of
    the 16x16 limb products (anti-diagonal accumulation), two
    delta-folds of the high columns (delta_P = 2^32 + 977), two full
    carry chains and the closing 5-step mini-chain.
    """
    a = a_ref[:, :]  # [16, B]
    b = b_ref[:, :]
    mask = jnp.uint32(MASK)

    # schoolbook columns: cols[k] = sum_{i+j=k} lo(a_i b_j)
    #                             + sum_{i+j=k-1} hi(a_i b_j)   (< 2^21)
    zero = jnp.zeros_like(a[0])
    cols = [zero] * 32
    for i in range(NLIMBS):
        ai = a[i]
        for j in range(NLIMBS):
            p = ai * b[j]
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)

    # fold 1: columns 16..31 via delta = 2^32 + 977  (w = 18 wide)
    c977 = jnp.uint32(977)
    for _ in range(2):
        w = len(cols)
        if w <= 16:
            break
        hi = cols[16:]
        lo = cols[:16] + [zero] * max(0, len(hi) + 2 - 16)
        for j, h in enumerate(hi):
            lo[j] = lo[j] + h * c977
            lo[j + 2] = lo[j + 2] + h
        cols = lo[: max(16, len(hi) + 2)]

    # first full carry
    out = []
    carry = zero
    for k in range(16):
        t = cols[k] + carry
        out.append(t & mask)
        carry = t >> 16
    out[0] = out[0] + carry * c977
    out[2] = out[2] + carry
    # second full carry
    carry = zero
    for k in range(16):
        t = out[k] + carry
        out[k] = t & mask
        carry = t >> 16
    out[0] = out[0] + carry * c977
    out[2] = out[2] + carry
    # closing mini-chain
    carry = zero
    for k in range(5):
        t = out[k] + carry
        out[k] = t & mask
        carry = t >> 16

    for k in range(16):
        out_ref[k, :] = out[k]


def fp_mul_pallas(a: jnp.ndarray, b: jnp.ndarray, *,
                  interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 16] x [B, 16] -> [B, 16]`` F_P multiply via the Pallas
    kernel; bit-identical to ``bigint.FP.mul`` (relaxed outputs)."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B = a.shape[0]
    pad = (-B) % LANE_BLOCK
    at = jnp.pad(a, ((0, pad), (0, 0))).T  # [16, B+pad]
    bt = jnp.pad(b, ((0, pad), (0, 0))).T
    n_blocks = at.shape[1] // LANE_BLOCK

    out = pl.pallas_call(
        _fp_mul_kernel,
        out_shape=jax.ShapeDtypeStruct(at.shape, jnp.uint32),
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
                  pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))],
        out_specs=pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
        interpret=interpret,
    )(at, bt)
    return out.T[:B]


def pallas_enabled() -> bool:
    """Opt-in switch: ``EGES_TPU_PALLAS=1`` at import time routes
    ``FP.mul`` on 2-D batches through the kernel (see
    ``bigint.FieldP.mul``'s dispatch)."""
    return os.environ.get("EGES_TPU_PALLAS", "") == "1"
