"""Pallas TPU kernels for the bignum hot loop (SURVEY §7 step 1:
"secp256k1 batch ops as JAX/Pallas kernels").

The XLA graph form of the verifier (ops/bigint.py, ops/ec.py) already
keeps everything fused on-device, but it pays twice for being a graph:
~66k StableHLO ops (45-85 s compiles) and per-op dispatch granularity.
Round-4 measurement on the live chip showed dispatch is the WHOLE
story on this backend (~40-100 us per executed kernel): the plain
graph ran 20 verifies/s at 256 rows, and a first 2-kernel-per-window
variant only 3.5x that.  So these kernels fuse entire LOOPS, not
steps, each a single ``pallas_call`` whose grid streams per-iteration
operands while the carried state stays resident in VMEM/output refs:

* ``strauss_tab``: the whole 33-window GLV/Strauss ladder (4 doublings
  + 4 conditional mixed adds per window) with IN-KERNEL one-hot table
  lookups — fixed-base operands from trace-time constants, the R
  tables VMEM-resident across the window walk.
* ``pow_mod_pallas``: constant-exponent windowed pow (a^e mod P or
  mod N) — covers FP.sqrt, FP inverse and FN inverse, replacing three
  rolled 256-bit square-and-multiply ladders.
* ``keccak_block_pallas``: the single-block Keccak-f[1600] of the
  address-derivation tail, all 24 rounds in one kernel.
* the GLUE kernels (``fp_add/sub/neg/mul_small/canon``, ``fn_sub/neg/
  red17``, ``mulhi8``): after the loops were fused, the recover graph
  STILL executed as ~3.8k XLA fusions of prelude/GLV/pack/finish
  arithmetic (harness/hlo_census.py), each its own dispatch — 97% of
  batch wall time.  Routing every remaining field-op call site through
  a one-launch kernel took the chip from 826.8 to 33.5k verifies/s at
  4096 rows (54.0k/s at 16384) in the round-4 A/B.

Layout: the graph stores a field element as ``[B, 16]`` u32 limbs (rows
on sublanes).  Kernels TRANSPOSE to ``[16, B]`` — 16 limbs land exactly
on two 8-sublane rows and the batch rides the 128-wide lane axis, so
every limb row is one natural VPU vector.  The in-kernel field library
(``_k_*``) mirrors ``bigint.FieldP`` bit-for-bit — same fold constants,
same carry chains, same relaxed representation — so kernel and graph
agree exactly.  Testing strategy (tests/test_pallas_kernels.py): the
small F_P-mul kernel is differential-tested through ``pallas_call`` in
interpret mode (covering the shared tiling/transpose plumbing); the
fused ladder kernels' MATH is differential-tested in pure numpy via the
``xp`` namespace parameter (identical uint32 wrap semantics, runs in
milliseconds where interpret-mode XLA compiles of the flat graphs take
tens of minutes on a 1-core host); the kernels themselves are exercised
end-to-end only on a real TPU (Mosaic), where ``harness/tpu_watch.py``
A/Bs them the moment the tunnel answers.

Dispatch: ``EGES_TPU_PALLAS=1`` keeps the historical per-multiply
kernel hook in ``FieldP.mul``; ``EGES_TPU_PALLAS=ladder`` routes the
ladder, the three pow ladders and the keccak tail through the fused
kernels — on the TPU backend only (interpret mode lowers kernels back
to per-block HLO, which would re-explode the CPU graph the rolled
loops were built to avoid).

Ref role: crypto/secp256k1/libsecp256k1/src/ecmult_impl.h (the windowed
ladder the reference runs in C); consumed by secp256.go:105.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from eges_tpu.ops.bigint import MASK, NLIMBS, P, int_to_limbs

# Batch columns per kernel grid step.  Env-tunable for hardware A/B:
# larger blocks mean fewer grid steps (and more VMEM per step — the
# strauss_tab tables cost 3 x 1 KB per column).  256 is the proven
# default; override with EGES_TPU_LANE_BLOCK=1024 to test.
LANE_BLOCK = int(os.environ.get("EGES_TPU_LANE_BLOCK", "256"))
if LANE_BLOCK <= 0 or LANE_BLOCK % 128:
    raise ValueError(
        f"EGES_TPU_LANE_BLOCK={LANE_BLOCK}: must be a positive multiple "
        "of 128 (TPU lane width)")

_P_LIMBS = [int(v) for v in int_to_limbs(P)]
_SUBC_LIMBS = [int(v) for v in int_to_limbs((1 << 256) - 2 * ((1 << 256) - P) + 1)]
_ONE_LIMBS = [1] + [0] * 15


# ---------------------------------------------------------------------------
# in-kernel field library: a value is a Python list of 16 [B]-wide u32
# vectors (limb-major).  Bit-identical to bigint.FieldP's relaxed form.
# ---------------------------------------------------------------------------

def _k_carry_tail(cols, xp=jnp):
    """16 columns (each < 2^31) -> relaxed 16-limb value; the shared
    reduction tail of ``FieldP._reduce_cols`` (two full carry chains +
    delta folds + the closing 5-step mini-chain).

    All ``_k_*`` helpers take an array namespace ``xp``: ``jnp`` when
    tracing inside a kernel, ``numpy`` in the differential tests — the
    flat unrolled math is far too large for XLA CPU to compile in
    reasonable time (compile cost grows superlinearly in flat-graph
    size; measured 9 s for one in-kernel multiply, 84 s for four), but
    numpy executes it in milliseconds with the exact same uint32 wrap
    semantics, pinning the math bit-for-bit against the graph path.
    """
    mask = xp.uint32(MASK)
    c977 = xp.uint32(977)
    out = []
    c = xp.zeros_like(cols[0])
    for k in range(16):
        t = cols[k] + c
        out.append(t & mask)
        c = t >> 16
    out[0] = out[0] + c * c977
    out[2] = out[2] + c
    c = xp.zeros_like(c)
    for k in range(16):
        t = out[k] + c
        out[k] = t & mask
        c = t >> 16
    out[0] = out[0] + c * c977
    out[2] = out[2] + c
    cc = xp.zeros_like(c)
    for k in range(5):
        t = out[k] + cc
        out[k] = t & mask
        cc = t >> 16
    return out


def _k_mul(a, b, xp=jnp):  # api: _k_mul
    """Schoolbook 16x16 product columns + delta folds + carry tail
    (mirrors ``big_mul_cols`` + ``FieldP._reduce_cols``)."""
    mask = xp.uint32(MASK)
    c977 = xp.uint32(977)
    zero = xp.zeros_like(a[0])
    cols = [zero] * 32
    for i in range(NLIMBS):
        ai = a[i]
        for j in range(NLIMBS):
            p = ai * b[j]
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)
    # fold columns >= 16 via delta = 2^32 + 977 (two passes suffice)
    for _ in range(2):
        if len(cols) <= 16:
            break
        hi = cols[16:]
        lo = cols[:16] + [zero] * max(0, len(hi) + 2 - 16)
        for j, h in enumerate(hi):
            lo[j] = lo[j] + h * c977
            lo[j + 2] = lo[j + 2] + h
        cols = lo[: max(16, len(hi) + 2)]
    return _k_carry_tail(cols, xp)


def _k_sqr(a, xp=jnp):
    return _k_mul(a, a, xp)


def _k_add(a, b, xp=jnp):
    return _k_carry_tail([x + y for x, y in zip(a, b)], xp)


def _k_sub(a, b, xp=jnp):
    """Branchless a - b: a + (0xFFFF - b) + (2^256 - 2*delta + 1),
    mirroring ``FieldP.sub``."""
    mask = xp.uint32(MASK)
    return _k_carry_tail([
        x + (mask - y) + xp.uint32(_SUBC_LIMBS[k])
        for k, (x, y) in enumerate(zip(a, b))], xp)


def _k_neg(a, xp=jnp):
    return _k_sub([xp.zeros_like(v) for v in a], a, xp)


def _k_mul_small(a, k: int, xp=jnp):
    assert k < 16
    return _k_carry_tail([v * xp.uint32(k) for v in a], xp)


def _k_is_zero_mod(a, xp=jnp):
    """Relaxed a ≡ 0 (mod P): exactly 0 or exactly P (u32 0/1 vector)."""
    z = a[0] == 0
    p = a[0] == xp.uint32(_P_LIMBS[0])
    for k in range(1, 16):
        z = z & (a[k] == 0)
        p = p & (a[k] == xp.uint32(_P_LIMBS[k]))
    return (z | p).astype(xp.uint32)


def _k_select(flag, a, b, xp=jnp):
    """flag ? a : b, flag a [B] u32 0/1 vector."""
    f = flag.astype(bool)
    return [xp.where(f, x, y) for x, y in zip(a, b)]


def _k_jac_double(X1, Y1, Z1, xp=jnp):
    """Mirror of ``ec.jac_double`` (dbl-2009-l, a=0)."""
    A = _k_sqr(X1, xp)
    B = _k_sqr(Y1, xp)
    C = _k_sqr(B, xp)
    t = _k_sqr(_k_add(X1, B, xp), xp)
    D = _k_mul_small(_k_sub(_k_sub(t, A, xp), C, xp), 2, xp)
    E = _k_mul_small(A, 3, xp)
    F = _k_sqr(E, xp)
    X3 = _k_sub(F, _k_mul_small(D, 2, xp), xp)
    Y3 = _k_sub(_k_mul(E, _k_sub(D, X3, xp), xp), _k_mul_small(C, 8, xp), xp)
    Z3 = _k_mul_small(_k_mul(Y1, Z1, xp), 2, xp)
    return X3, Y3, Z3


def _k_jac_add_mixed(X1, Y1, Z1, x2, y2, xp=jnp):
    """Mirror of ``ec.jac_add_mixed`` (madd-2007-bl + branchless
    exceptional cases)."""
    Z1Z1 = _k_sqr(Z1, xp)
    U2 = _k_mul(x2, Z1Z1, xp)
    S2 = _k_mul(_k_mul(y2, Z1, xp), Z1Z1, xp)
    H = _k_sub(U2, X1, xp)
    r = _k_sub(S2, Y1, xp)

    HH = _k_sqr(H, xp)
    I = _k_mul_small(HH, 4, xp)
    J = _k_mul(H, I, xp)
    rr = _k_mul_small(r, 2, xp)
    V = _k_mul(X1, I, xp)
    X3 = _k_sub(_k_sub(_k_sqr(rr, xp), J, xp), _k_mul_small(V, 2, xp), xp)
    Y3 = _k_sub(_k_mul(rr, _k_sub(V, X3, xp), xp),
                _k_mul_small(_k_mul(Y1, J, xp), 2, xp), xp)
    Z3 = _k_mul(_k_mul_small(Z1, 2, xp), H, xp)

    DX, DY, DZ = _k_jac_double(X1, Y1, Z1, xp)

    h0 = _k_is_zero_mod(H, xp)
    r0 = _k_is_zero_mod(r, xp)
    p1_inf = _k_is_zero_mod(Z1, xp)
    dbl = h0 * r0
    opp = h0 * (1 - r0)

    onef = [xp.broadcast_to(xp.uint32(v), X1[0].shape)
            for v in _ONE_LIMBS]
    zerof = [xp.zeros_like(v) for v in X1]
    X = _k_select(dbl, DX, X3, xp)
    Y = _k_select(dbl, DY, Y3, xp)
    Z = _k_select(dbl, DZ, Z3, xp)
    Z = _k_select(opp, zerof, Z, xp)
    Y = _k_select(opp, onef, Y, xp)
    X = _k_select(p1_inf, x2, X, xp)
    Y = _k_select(p1_inf, y2, Y, xp)
    Z = _k_select(p1_inf, onef, Z, xp)
    return X, Y, Z


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _read16(ref):
    return [ref[k, :] for k in range(NLIMBS)]


def _write16(ref, val):
    for k in range(NLIMBS):
        ref[k, :] = val[k]


def _fp_mul_kernel(a_ref, b_ref, out_ref):
    """One [16, LANE_BLOCK] tile: out = a * b mod P (relaxed form)."""
    _write16(out_ref, _k_mul(_read16(a_ref), _read16(b_ref)))


# ---------------------------------------------------------------------------
# wrappers: [B, 16] graph layout <-> [16, B] kernel tiles
# ---------------------------------------------------------------------------

def _default_interpret() -> bool:
    # axon is the tunnel's TPU platform — real Mosaic, not interpret
    return jax.default_backend() not in ("tpu", "axon")


def fp_mul_pallas(a: jnp.ndarray, b: jnp.ndarray, *,
                  interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 16] x [B, 16] -> [B, 16]`` F_P multiply via the Pallas
    kernel; bit-identical to ``bigint.FP.mul`` (relaxed outputs)."""
    return _ew(_fp_mul_kernel, [a, b], interpret=interpret)


# operand layout of the ladder kernels
STRAUSS_OPS = 4  # ±G, ±lam*G, ±R, ±lam*R


# ---------------------------------------------------------------------------
# self-gathering ladder kernel (round-4 v2): the per-window table
# lookups move INSIDE the kernel as one-hot selects, so the XLA
# pre-gather/sign-fold/pack stage (~150 dispatches and two [W, 64, B]
# operand arrays — 280 MB per 16k batch — re-uploaded per call)
# disappears entirely.  Fixed-base operands (±G, ±lam*G) select from
# trace-time scalar constants; variable-base operands (±R, ±lam*R)
# select rows of the R-table refs, which stay VMEM-resident across the
# whole window walk (their index map is constant in w).  Digits arrive
# MSD-first as one tiny [W, 8, B] array; signs as [8, B].
# ---------------------------------------------------------------------------


def _k_onehot_const(dig, tab_rows, xp=jnp):
    """Per-lane lookup of a 16-entry x 16-limb CONSTANT table by digit
    vector: limbs[k] = sum_d (dig == d) * tab[d][k].  Entry 0 of every
    table is the zero row, so the d = 0 term is skipped."""
    out = []
    oh = [(dig == xp.uint32(d)).astype(xp.uint32) for d in range(1, 16)]
    for k in range(NLIMBS):
        s = xp.zeros_like(dig)
        for d in range(1, 16):
            c = tab_rows[d][k]
            if c:
                s = s + oh[d - 1] * xp.uint32(c)
        out.append(s)
    return out


def _k_onehot_ref(dig, read_row, xp=jnp):
    """Same, for a per-row table in a ref: ``read_row(d, k)`` yields the
    [B]-vector of limb k of entry d."""
    oh = [(dig == xp.uint32(d)).astype(xp.uint32) for d in range(1, 16)]
    out = []
    for k in range(NLIMBS):
        s = xp.zeros_like(dig)
        for d in range(1, 16):
            s = s + oh[d - 1] * read_row(d, k)
        out.append(s)
    return out


@functools.lru_cache(maxsize=1)
def _strauss_tab_kernel():
    # G/lam*G affine tables as trace-time int constants (entry 0 zero)
    from eges_tpu.ops.ec import _g_lam_table16, _g_table16

    tgx, tgy = _g_table16()
    tlx, _ = _g_lam_table16()
    gx_rows = tuple(tuple(int(v) for v in row) for row in tgx)
    gy_rows = tuple(tuple(int(v) for v in row) for row in tgy)
    lx_rows = tuple(tuple(int(v) for v in row) for row in tlx)

    def kernel(dig_ref, neg_ref, trx_ref, tlrx_ref, try_ref,
               ox_ref, oy_ref, oz_ref):
        w = pl.program_id(1)

        @pl.when(w == 0)
        def _init():
            zero = jnp.zeros((LANE_BLOCK,), jnp.uint32)
            one = jnp.ones((LANE_BLOCK,), jnp.uint32)
            for k in range(NLIMBS):
                ox_ref[k, :] = zero
                oy_ref[k, :] = one if k == 0 else zero
                oz_ref[k, :] = zero

        X, Y, Z = _read16(ox_ref), _read16(oy_ref), _read16(oz_ref)
        for _ in range(4):
            X, Y, Z = _k_jac_double(X, Y, Z)
        for t in range(STRAUSS_OPS):
            dig = dig_ref[0, t, :]
            if t == 0:
                px = _k_onehot_const(dig, gx_rows)
                py = _k_onehot_const(dig, gy_rows)
            elif t == 1:
                px = _k_onehot_const(dig, lx_rows)
                py = _k_onehot_const(dig, gy_rows)
            else:
                xref = trx_ref if t == 2 else tlrx_ref
                px = _k_onehot_ref(dig, lambda d, k: xref[16 * d + k, :])
                py = _k_onehot_ref(dig, lambda d, k: try_ref[16 * d + k, :])
            py = _k_select(neg_ref[t, :], _k_neg(py), py)
            nz = (dig != 0).astype(jnp.uint32)
            AX, AY, AZ = _k_jac_add_mixed(X, Y, Z, px, py)
            X = _k_select(nz, AX, X)
            Y = _k_select(nz, AY, Y)
            Z = _k_select(nz, AZ, Z)
        _write16(ox_ref, X)
        _write16(oy_ref, Y)
        _write16(oz_ref, Z)

    return kernel


def strauss_tab(dig: jnp.ndarray, neg: jnp.ndarray, trx: jnp.ndarray,
                tlrx: jnp.ndarray, try_: jnp.ndarray, batch: int, *,
                interpret: bool | None = None):
    """Self-gathering ladder: ``dig [W, 8, Bpad]`` (rows 0-3: window
    digits of g1/g2/r1/r2, MSD-first), ``neg [8, Bpad]`` (rows 0-3:
    half-scalar signs), ``trx/tlrx/try_ [256, Bpad]`` (R / lam*R x and
    shared y affine tables, row ``16*d + k`` = limb k of entry d).
    Returns Jacobian ``(X, Y, Z)`` each ``[batch, 16]``."""
    if rows8_enabled():
        return strauss_tab_rows8(dig, neg, trx, tlrx, try_, batch,
                                 interpret=interpret)
    if interpret is None:
        interpret = _default_interpret()
    W, _, wide = dig.shape
    nb = wide // LANE_BLOCK
    outs = pl.pallas_call(
        _strauss_tab_kernel(),
        out_shape=tuple(jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32)
                        for _ in range(3)),
        grid=(nb, W),
        in_specs=[
            pl.BlockSpec((1, 8, LANE_BLOCK), lambda b, w: (w, 0, b)),
            pl.BlockSpec((8, LANE_BLOCK), lambda b, w: (0, b)),
            pl.BlockSpec((16 * NLIMBS, LANE_BLOCK), lambda b, w: (0, b)),
            pl.BlockSpec((16 * NLIMBS, LANE_BLOCK), lambda b, w: (0, b)),
            pl.BlockSpec((16 * NLIMBS, LANE_BLOCK), lambda b, w: (0, b)),
        ],
        out_specs=tuple(
            pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda b, w: (0, b))
            for _ in range(3)),
        interpret=interpret,
    )(dig, neg, trx, tlrx, try_)
    return tuple(o.T[:batch] for o in outs)


def strauss_tab_np(dig: np.ndarray, neg: np.ndarray, trx: np.ndarray,
                   tlrx: np.ndarray, try_: np.ndarray):
    """Numpy twin of the self-gathering ladder kernel's math."""
    from eges_tpu.ops.ec import _g_lam_table16, _g_table16

    tgx, tgy = _g_table16()
    tlx, _ = _g_lam_table16()
    gx_rows = tuple(tuple(int(v) for v in row) for row in tgx)
    gy_rows = tuple(tuple(int(v) for v in row) for row in tgy)
    lx_rows = tuple(tuple(int(v) for v in row) for row in tlx)
    W, _, wide = dig.shape
    X = [np.zeros(wide, np.uint32) for _ in range(NLIMBS)]
    Y = [np.zeros(wide, np.uint32) for _ in range(NLIMBS)]
    Y[0] = np.ones(wide, np.uint32)
    Z = [np.zeros(wide, np.uint32) for _ in range(NLIMBS)]
    for w in range(W):
        for _ in range(4):
            X, Y, Z = _k_jac_double(X, Y, Z, np)
        for t in range(STRAUSS_OPS):
            d = dig[w, t, :]
            if t == 0:
                px = _k_onehot_const(d, gx_rows, np)
                py = _k_onehot_const(d, gy_rows, np)
            elif t == 1:
                px = _k_onehot_const(d, lx_rows, np)
                py = _k_onehot_const(d, gy_rows, np)
            else:
                xt = trx if t == 2 else tlrx
                px = _k_onehot_ref(d, lambda e, k: xt[16 * e + k, :], np)
                py = _k_onehot_ref(d, lambda e, k: try_[16 * e + k, :], np)
            py = _k_select(neg[t, :], _k_neg(py, np), py, np)
            nz = (d != 0).astype(np.uint32)
            AX, AY, AZ = _k_jac_add_mixed(X, Y, Z, px, py, np)
            X = _k_select(nz, AX, X, np)
            Y = _k_select(nz, AY, Y, np)
            Z = _k_select(nz, AZ, Z, np)
    return X, Y, Z


# ---------------------------------------------------------------------------
# streamed windowed-pow kernel: a^e for a constant exponent, one launch.
# Covers the three remaining launch-heavy loops of the recover graph —
# FP.sqrt (e = (P+1)/4), FP inverse (P-2) and FN inverse (N-2): each is
# a 256-bit square-and-multiply that the XLA path runs as a rolled
# fori_loop of tiny ops (~2k launches per pow on this backend).  Here
# the grid's last dim walks 64 4-bit windows; the per-row power table
# a^0..a^15 (a^0 = 1, so digit 0 needs no conditional) is built once
# per batch block into VMEM scratch at w == 0, and the window digit —
# a compile-time constant — arrives as a tiny one-hot block shared by
# every batch block.
# ---------------------------------------------------------------------------

POW_WINDOWS = 64


def _make_pow_kernel(mul_fn):
    def kernel(sel_ref, a_ref, o_ref, tab_ref):
        w = pl.program_id(1)

        @pl.when(w == 0)
        def _init():
            A = _read16(a_ref)
            one0 = jnp.ones_like(A[0])
            zero = jnp.zeros_like(A[0])
            for k in range(NLIMBS):
                tab_ref[k, :] = one0 if k == 0 else zero        # a^0 = 1
                tab_ref[NLIMBS + k, :] = A[k]                   # a^1
                o_ref[k, :] = one0 if k == 0 else zero          # acc = 1
            cur = A
            for e in range(2, 16):
                cur = mul_fn(cur, A)
                for k in range(NLIMBS):
                    tab_ref[NLIMBS * e + k, :] = cur[k]

        acc = _read16(o_ref)
        for _ in range(4):
            acc = mul_fn(acc, acc)
        sel = [sel_ref[0, e, :] for e in range(16)]
        op = []
        for k in range(NLIMBS):
            s = sel[0] * tab_ref[k, :]
            for e in range(1, 16):
                s = s + sel[e] * tab_ref[NLIMBS * e + k, :]
            op.append(s)
        acc = mul_fn(acc, op)
        _write16(o_ref, acc)

    return kernel


@functools.lru_cache(maxsize=2)
def _pow_kernel_for(modulus: str):
    # lazy: _k_fn_mul is defined in the order-N section below
    return _make_pow_kernel(_k_mul if modulus == "p" else _k_fn_mul)


@functools.lru_cache(maxsize=None)
def _pow_onehot(e: int) -> np.ndarray:
    """[64, 16, LANE_BLOCK] u32 one-hot of e's 4-bit digits, MSD first."""
    sel = np.zeros((POW_WINDOWS, 16, LANE_BLOCK), np.uint32)
    for w in range(POW_WINDOWS):
        d = (e >> (4 * (POW_WINDOWS - 1 - w))) & 0xF
        sel[w, d, :] = 1
    return sel


def pow_mod_pallas(a: jnp.ndarray, e: int, modulus: str, *,
                   interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 16] -> [B, 16]``: per-row ``a^e`` mod P (relaxed) or mod N
    (canonical), matching ``FieldP.pow_const`` / ``OrderN.pow_const``
    outputs up to the field's representation contract."""
    from jax.experimental.pallas import tpu as pltpu

    if rows8_enabled():
        return pow_mod_rows8(a, e, modulus, interpret=interpret)
    if interpret is None:
        interpret = _default_interpret()
    assert e.bit_length() <= 4 * POW_WINDOWS
    B = a.shape[0]
    pad = (-B) % LANE_BLOCK
    at = jnp.pad(a, ((0, pad), (0, 0))).T
    wide = at.shape[1]
    sel = jnp.asarray(_pow_onehot(e))
    out = pl.pallas_call(
        _pow_kernel_for(modulus),
        out_shape=jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32),
        grid=(wide // LANE_BLOCK, POW_WINDOWS),
        in_specs=[
            pl.BlockSpec((1, 16, LANE_BLOCK), lambda b, w: (w, 0, 0)),
            pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda b, w: (0, b)),
        ],
        out_specs=pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda b, w: (0, b)),
        scratch_shapes=[pltpu.VMEM((16 * NLIMBS, LANE_BLOCK), jnp.uint32)],
        interpret=interpret,
    )(sel, at)
    return out.T[:B]


def pow_mod_np(a: np.ndarray, e: int, modulus: str) -> np.ndarray:
    """Numpy twin of the pow kernel's math for differential tests."""
    mul = _k_mul if modulus == "p" else _k_fn_mul
    A = [a[:, k].copy() for k in range(NLIMBS)]
    one0 = np.ones_like(A[0])
    zero = np.zeros_like(A[0])
    tab = [[one0 if k == 0 else zero for k in range(NLIMBS)], A]
    cur = A
    for _ in range(2, 16):
        cur = mul(cur, A, np)
        tab.append(cur)
    acc = [one0 if k == 0 else zero for k in range(NLIMBS)]
    for w in range(POW_WINDOWS):
        d = (e >> (4 * (POW_WINDOWS - 1 - w))) & 0xF
        for _ in range(4):
            acc = mul(acc, acc, np)
        acc = mul(acc, tab[d], np)
    return np.stack(acc, axis=-1)


# ---------------------------------------------------------------------------
# table-build kernel: entries 2..15 of the per-row variable-base window
# table (d*R).  The graph form is a lax.scan of 14 mixed adds — the
# last multi-thousand-launch loop on the fused path.  Grid walks the
# entries; the running point lives in VMEM scratch and each step's
# result lands in that entry's output block.
# ---------------------------------------------------------------------------

def _table_kernel(px_ref, py_ref, ox_ref, oy_ref, oz_ref, cur_ref):
    d = pl.program_id(1)
    px, py = _read16(px_ref), _read16(py_ref)

    @pl.when(d == 0)
    def _init():  # cur = 1*R (affine lifted to Jacobian)
        one0 = jnp.ones((LANE_BLOCK,), jnp.uint32)
        zero = jnp.zeros((LANE_BLOCK,), jnp.uint32)
        for k in range(NLIMBS):
            cur_ref[k, :] = px[k]
            cur_ref[NLIMBS + k, :] = py[k]
            cur_ref[2 * NLIMBS + k, :] = one0 if k == 0 else zero

    X = [cur_ref[k, :] for k in range(NLIMBS)]
    Y = [cur_ref[NLIMBS + k, :] for k in range(NLIMBS)]
    Z = [cur_ref[2 * NLIMBS + k, :] for k in range(NLIMBS)]
    X, Y, Z = _k_jac_add_mixed(X, Y, Z, px, py)
    for k in range(NLIMBS):
        cur_ref[k, :] = X[k]
        cur_ref[NLIMBS + k, :] = Y[k]
        cur_ref[2 * NLIMBS + k, :] = Z[k]
    _write16(ox_ref, X)
    _write16(oy_ref, Y)
    _write16(oz_ref, Z)


def point_table_pallas(px: jnp.ndarray, py: jnp.ndarray, *,
                       interpret: bool | None = None):
    """``[B, 16]`` affine R -> Jacobian entries ``d*R`` for d in 2..15,
    each ``[14, B, 16]`` (X, Y, Z); bit-identical to the lax.scan of
    ``ec.jac_add_mixed`` in ``_build_point_table``."""
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _default_interpret()
    B = px.shape[0]
    pad = (-B) % LANE_BLOCK
    pxt = jnp.pad(px, ((0, pad), (0, 0))).T
    pyt = jnp.pad(py, ((0, pad), (0, 0))).T
    wide = pxt.shape[1]
    outs = pl.pallas_call(
        _table_kernel,
        out_shape=tuple(jax.ShapeDtypeStruct((14 * NLIMBS, wide),
                                             jnp.uint32) for _ in range(3)),
        grid=(wide // LANE_BLOCK, 14),
        in_specs=[pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda b, d: (0, b)),
                  pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda b, d: (0, b))],
        out_specs=tuple(
            pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda b, d: (d, b))
            for _ in range(3)),
        scratch_shapes=[pltpu.VMEM((3 * NLIMBS, LANE_BLOCK), jnp.uint32)],
        interpret=interpret,
    )(pxt, pyt)
    # [14*16, wide] -> [14, B, 16]
    return tuple(o.reshape(14, NLIMBS, wide).transpose(0, 2, 1)[:, :B]
                 for o in outs)


def point_table_np(px: np.ndarray, py: np.ndarray):
    """Numpy twin of the table kernel."""
    B = px.shape[0]
    pxl = [px[:, k].copy() for k in range(NLIMBS)]
    pyl = [py[:, k].copy() for k in range(NLIMBS)]
    X, Y = list(pxl), list(pyl)
    Z = [np.ones(B, np.uint32) if k == 0 else np.zeros(B, np.uint32)
         for k in range(NLIMBS)]
    outs = []
    for _ in range(14):
        X, Y, Z = _k_jac_add_mixed(X, Y, Z, pxl, pyl, np)
        outs.append((np.stack(X, -1), np.stack(Y, -1), np.stack(Z, -1)))
    return (np.stack([o[0] for o in outs]), np.stack([o[1] for o in outs]),
            np.stack([o[2] for o in outs]))


# ---------------------------------------------------------------------------
# keccak-f[1600] kernel: the address-derivation tail of ecrecover
# (keccak256(x||y)[12:]).  The XLA form is already a rolled 24-round
# fori_loop (~1.5k executed ops per batch, ops/keccak_tpu.py); once the
# ladder and pow loops are fused that tail becomes a visible share of
# the launch bill, so the single-block permutation gets a kernel too.
# In-kernel the 25x2 u32 state is a Python list of [B]-vectors: every
# theta/rho/pi/chi index is a compile-time constant, so there are no
# gathers at all — just vector xor/and/shift.  Rounds unroll at trace
# time (24 x ~150 vector ops: well inside Mosaic's comfort zone).
# ---------------------------------------------------------------------------

_KECCAK_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_KECCAK_ROT = [[0, 36, 3, 41, 18], [1, 44, 10, 45, 2],
               [62, 6, 43, 15, 61], [28, 55, 25, 21, 56],
               [27, 20, 39, 8, 14]]  # [x][y], lane l = x + 5y


def _k_rot64(lo, hi, r: int, xp=jnp):
    r %= 64
    if r == 0:
        return lo, hi
    if r >= 32:
        lo, hi = hi, lo
        r -= 32
        if r == 0:
            return lo, hi
    rs, inv = xp.uint32(r), xp.uint32(32 - r)
    return ((lo << rs) | (hi >> inv)), ((hi << rs) | (lo >> inv))


def _k_keccak_words(w, xp=jnp):
    """34 LE u32 words (one padded 136-byte block) -> 8 digest words.
    State lanes as (lo, hi) u32 pairs, all indices constant."""
    zero = xp.zeros_like(w[0])
    lo = [w[2 * l] for l in range(17)] + [zero] * 8
    hi = [w[2 * l + 1] for l in range(17)] + [zero] * 8
    for rnd in range(24):
        # theta
        clo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20]
               for x in range(5)]
        chi_ = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20]
                for x in range(5)]
        for x in range(5):
            rl, rh = _k_rot64(clo[(x + 1) % 5], chi_[(x + 1) % 5], 1, xp)
            dlo, dhi = clo[(x + 4) % 5] ^ rl, chi_[(x + 4) % 5] ^ rh
            for y in range(5):
                lo[x + 5 * y] = lo[x + 5 * y] ^ dlo
                hi[x + 5 * y] = hi[x + 5 * y] ^ dhi
        # rho + pi
        blo, bhi = [None] * 25, [None] * 25
        for x in range(5):
            for y in range(5):
                dl = y + 5 * ((2 * x + 3 * y) % 5)
                blo[dl], bhi[dl] = _k_rot64(lo[x + 5 * y], hi[x + 5 * y],
                                            _KECCAK_ROT[x][y], xp)
        # chi
        for y in range(5):
            row_l = [blo[x + 5 * y] for x in range(5)]
            row_h = [bhi[x + 5 * y] for x in range(5)]
            for x in range(5):
                lo[x + 5 * y] = row_l[x] ^ (~row_l[(x + 1) % 5]
                                            & row_l[(x + 2) % 5])
                hi[x + 5 * y] = row_h[x] ^ (~row_h[(x + 1) % 5]
                                            & row_h[(x + 2) % 5])
        # iota
        lo[0] = lo[0] ^ xp.uint32(_KECCAK_RC[rnd] & 0xFFFFFFFF)
        hi[0] = hi[0] ^ xp.uint32(_KECCAK_RC[rnd] >> 32)
    return [lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]]


def _keccak_kernel(w_ref, o_ref):
    out = _k_keccak_words([w_ref[k, :] for k in range(34)])
    for k in range(8):
        o_ref[k, :] = out[k]


def keccak_block_pallas(words: jnp.ndarray, *,
                        interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 34]`` LE u32 words of one padded block -> ``[B, 8]``
    digest words (matches keccak_tpu's squeeze order)."""
    B = words.shape[0]
    pad = (-B) % LANE_BLOCK
    wt = jnp.pad(words, ((0, pad), (0, 0))).T  # [34, wide]
    return keccak_rows_pallas(wt, interpret=interpret).T[:B]


# ---------------------------------------------------------------------------
# rows8 experiment (EGES_TPU_ROWS8=1): (8, 128)-packed limb rows for
# the two compute-heaviest kernels.  The default layout keeps each limb
# as a [LANE]-wide 1-D vector, which Mosaic lays out (1, LANE) — one of
# eight sublanes live, so the VPU idles 7/8 of its datapath on every
# op.  Here one batch block is 1024 rows shaped (8, 128): a value is 16
# limbs x one full (8, 128) vreg each, array row ``limb*8 + sublane``.
# The ``_k_*`` math is shape-agnostic, so these kernels only change the
# ref plumbing.  Gated off by default until the on-chip A/B (the bench
# correctness gate runs before any timing is trusted).  Validation
# story: the re-lay index contract is pinned by
# test_rows8_layout_roundtrip; the kernel bodies reuse the twin-tested
# _k_* math; interpret mode is NOT a viable differential here (the
# (8,128)-block flat graphs take >15 min to compile on the 1-core
# host), so end-to-end proof is the hardware gate, as with LANE_BLOCK.
# ---------------------------------------------------------------------------

ROWS8_BLOCK = 1024  # rows per grid step: 8 sublanes x 128 lanes


def rows8_enabled() -> bool:
    if os.environ.get("EGES_TPU_ROWS8", "") != "1":
        return False
    if LANE_BLOCK % ROWS8_BLOCK:
        raise ValueError(
            "EGES_TPU_ROWS8=1 requires EGES_TPU_LANE_BLOCK to be a "
            f"multiple of {ROWS8_BLOCK} (got {LANE_BLOCK}) so every "
            "padded batch width re-lays into (8, 128) tiles")
    return True


def _r8_read(ref, k: int):
    """Limb k of a (1, 128, 128) value block -> (8, 128)."""
    return ref[0, 8 * k:8 * (k + 1), :]


def _r8_read16(ref):
    return [_r8_read(ref, k) for k in range(NLIMBS)]


def _r8_write16(ref, val):
    for k in range(NLIMBS):
        ref[0, 8 * k:8 * (k + 1), :] = val[k]


def _to_rows8(a: jnp.ndarray) -> jnp.ndarray:
    """``[B, 16]`` (B a ROWS8_BLOCK multiple) -> ``[nb, 128, 128]``
    with row ``limb*8 + sublane``; batch b = block*1024 + s*128 + l."""
    B = a.shape[0]
    nb = B // ROWS8_BLOCK
    return (a.T.reshape(NLIMBS, nb, 8, 128).transpose(1, 0, 2, 3)
            .reshape(nb, NLIMBS * 8, 128))


def _from_rows8(a: jnp.ndarray, B: int) -> jnp.ndarray:
    nb = a.shape[0]
    return (a.reshape(nb, NLIMBS, 8, 128).transpose(1, 0, 2, 3)
            .reshape(NLIMBS, nb * ROWS8_BLOCK).T[:B])


def _pad_rows8(a: jnp.ndarray) -> tuple[jnp.ndarray, int]:
    B = a.shape[0]
    pad = (-B) % ROWS8_BLOCK
    return jnp.pad(a, ((0, pad), (0, 0))), B


@functools.lru_cache(maxsize=2)
def _pow_kernel_rows8(modulus: str):
    mul_fn = _k_mul if modulus == "p" else _k_fn_mul

    def kernel(sel_ref, a_ref, o_ref, tab_ref):
        w = pl.program_id(1)

        @pl.when(w == 0)
        def _init():
            A = _r8_read16(a_ref)
            one0 = jnp.ones_like(A[0])
            zero = jnp.zeros_like(A[0])
            for k in range(NLIMBS):
                tab_ref[8 * k:8 * (k + 1), :] = one0 if k == 0 else zero
                tab_ref[8 * (NLIMBS + k):8 * (NLIMBS + k) + 8, :] = A[k]
                o_ref[0, 8 * k:8 * (k + 1), :] = one0 if k == 0 else zero
            cur = A
            for e in range(2, 16):
                cur = mul_fn(cur, A)
                for k in range(NLIMBS):
                    r0 = 8 * (NLIMBS * e + k)
                    tab_ref[r0:r0 + 8, :] = cur[k]

        acc = _r8_read16(o_ref)
        for _ in range(4):
            acc = mul_fn(acc, acc)
        sel = [sel_ref[0, e, :] for e in range(16)]  # (128,) rows
        op = []
        for k in range(NLIMBS):
            s = sel[0] * tab_ref[8 * k:8 * (k + 1), :]
            for e in range(1, 16):
                r0 = 8 * (NLIMBS * e + k)
                s = s + sel[e] * tab_ref[r0:r0 + 8, :]
            op.append(s)
        acc = mul_fn(acc, op)
        _r8_write16(o_ref, acc)

    return kernel


def pow_mod_rows8(a: jnp.ndarray, e: int, modulus: str, *,
                  interpret: bool | None = None) -> jnp.ndarray:
    """rows8 twin of :func:`pow_mod_pallas` — same contract."""
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _default_interpret()
    assert e.bit_length() <= 4 * POW_WINDOWS
    ap, B = _pad_rows8(a)
    at = _to_rows8(ap)
    nb = at.shape[0]
    sel = jnp.asarray(_pow_onehot(e)[:, :, :128])
    out = pl.pallas_call(
        _pow_kernel_rows8(modulus),
        out_shape=jax.ShapeDtypeStruct((nb, NLIMBS * 8, 128), jnp.uint32),
        grid=(nb, POW_WINDOWS),
        in_specs=[
            pl.BlockSpec((1, 16, 128), lambda b, w: (w, 0, 0)),
            pl.BlockSpec((1, NLIMBS * 8, 128), lambda b, w: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, NLIMBS * 8, 128), lambda b, w: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((16 * NLIMBS * 8, 128), jnp.uint32)],
        interpret=interpret,
    )(sel, at)
    return _from_rows8(out, B)


@functools.lru_cache(maxsize=1)
def _strauss_tab_kernel_rows8():
    from eges_tpu.ops.ec import _g_lam_table16, _g_table16

    tgx, tgy = _g_table16()
    tlx, _ = _g_lam_table16()
    gx_rows = tuple(tuple(int(v) for v in row) for row in tgx)
    gy_rows = tuple(tuple(int(v) for v in row) for row in tgy)
    lx_rows = tuple(tuple(int(v) for v in row) for row in tlx)

    def kernel(dig_ref, neg_ref, trx_ref, tlrx_ref, try_ref,
               ox_ref, oy_ref, oz_ref):
        w = pl.program_id(1)

        @pl.when(w == 0)
        def _init():
            zero = jnp.zeros((8, 128), jnp.uint32)
            one = jnp.ones((8, 128), jnp.uint32)
            for k in range(NLIMBS):
                ox_ref[0, 8 * k:8 * k + 8, :] = zero
                oy_ref[0, 8 * k:8 * k + 8, :] = one if k == 0 else zero
                oz_ref[0, 8 * k:8 * k + 8, :] = zero

        X = _r8_read16(ox_ref)
        Y = _r8_read16(oy_ref)
        Z = _r8_read16(oz_ref)
        for _ in range(4):
            X, Y, Z = _k_jac_double(X, Y, Z)
        for t in range(STRAUSS_OPS):
            dig = dig_ref[0, 0, 8 * t:8 * t + 8, :]
            if t == 0:
                px = _k_onehot_const(dig, gx_rows)
                py = _k_onehot_const(dig, gy_rows)
            elif t == 1:
                px = _k_onehot_const(dig, lx_rows)
                py = _k_onehot_const(dig, gy_rows)
            else:
                xref = trx_ref if t == 2 else tlrx_ref

                def rr(d, k, ref=xref):
                    r0 = 8 * (16 * d + k)
                    return ref[0, r0:r0 + 8, :]

                px = _k_onehot_ref(dig, rr)
                py = _k_onehot_ref(
                    dig, lambda d, k: try_ref[0, 8 * (16 * d + k):
                                              8 * (16 * d + k) + 8, :])
            py = _k_select(neg_ref[0, 8 * t:8 * t + 8, :], _k_neg(py), py)
            nz = (dig != 0).astype(jnp.uint32)
            AX, AY, AZ = _k_jac_add_mixed(X, Y, Z, px, py)
            X = _k_select(nz, AX, X)
            Y = _k_select(nz, AY, Y)
            Z = _k_select(nz, AZ, Z)
        _r8_write16(ox_ref, X)
        _r8_write16(oy_ref, Y)
        _r8_write16(oz_ref, Z)

    return kernel


def strauss_tab_rows8(dig: jnp.ndarray, neg: jnp.ndarray, trx: jnp.ndarray,
                      tlrx: jnp.ndarray, try_: jnp.ndarray, batch: int, *,
                      interpret: bool | None = None):
    """rows8 twin of :func:`strauss_tab`: same [W, 8, Bpad]/[8, Bpad]/
    [256, Bpad] inputs (Bpad a ROWS8_BLOCK multiple), re-laid here."""
    if interpret is None:
        interpret = _default_interpret()
    W, _, wide = dig.shape
    nb = wide // ROWS8_BLOCK

    def lay(rows):  # [R, wide] -> [nb, R*8, 128], row r*8 + sublane
        R = rows.shape[0]
        return (rows.reshape(R, nb, 8, 128).transpose(1, 0, 2, 3)
                .reshape(nb, R * 8, 128))

    digl = (dig.reshape(W, 8, nb, 8, 128).transpose(2, 0, 1, 3, 4)
            .reshape(nb, W, 64, 128))
    negl = lay(neg)
    outs = pl.pallas_call(
        _strauss_tab_kernel_rows8(),
        out_shape=tuple(
            jax.ShapeDtypeStruct((nb, NLIMBS * 8, 128), jnp.uint32)
            for _ in range(3)),
        grid=(nb, W),
        in_specs=[
            pl.BlockSpec((1, 1, 64, 128), lambda b, w: (b, w, 0, 0)),
            pl.BlockSpec((1, 64, 128), lambda b, w: (b, 0, 0)),
            pl.BlockSpec((1, 16 * NLIMBS * 8, 128), lambda b, w: (b, 0, 0)),
            pl.BlockSpec((1, 16 * NLIMBS * 8, 128), lambda b, w: (b, 0, 0)),
            pl.BlockSpec((1, 16 * NLIMBS * 8, 128), lambda b, w: (b, 0, 0)),
        ],
        out_specs=tuple(
            pl.BlockSpec((1, NLIMBS * 8, 128), lambda b, w: (b, 0, 0))
            for _ in range(3)),
        interpret=interpret,
    )(digl, negl, lay(trx), lay(tlrx), lay(try_))
    return tuple(_from_rows8(o, batch) for o in outs)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def pallas_enabled() -> bool:
    """Historical opt-in: ``EGES_TPU_PALLAS=1`` routes ``FP.mul`` on 2-D
    batches through the per-multiply kernel (``bigint.FieldP.mul``)."""
    return os.environ.get("EGES_TPU_PALLAS", "") == "1"


@functools.lru_cache(maxsize=1)
def ladder_kernels_enabled() -> bool:
    """Route the recover pipeline through the fused kernels (the
    composite stage kernels, glv_digits, strauss_tab, the pow ladders,
    the R-table build, the keccak tail, the one-launch glue ops) — TPU
    backend only (interpret mode would lower each kernel back to
    per-block HLO and re-explode the CPU graph).

    DEFAULT ON for TPU backends since the round-4 hardware A/B
    (LADDER_AB.json): 826.8 verifies/s vs the plain graph's 20.1/s at
    256 rows on a v5e (this backend executes each HLO op as its own
    dispatch, so per-launch overhead dominates the un-fused graph), with
    the bench correctness gate passing.  ``EGES_TPU_PALLAS=off`` (or
    ``0``) opts out; ``ladder`` forces the historical explicit opt-in;
    ``1`` selects the per-multiply hook instead (see
    :func:`pallas_enabled`)."""
    val = os.environ.get("EGES_TPU_PALLAS", "")
    if val in ("off", "0", "1"):
        return False
    return (val in ("", "ladder")
            and jax.default_backend() in ("tpu", "axon"))


# ---------------------------------------------------------------------------
# order-N (scalar field) multiply kernel: mirrors OrderN.mul =
# _red_cols(big_mul_cols(a, b)) — the mod-N arithmetic of the scalar
# recovery prelude (u1/u2, GLV decomposition)
# ---------------------------------------------------------------------------

from eges_tpu.ops.bigint import N as _ORDER_N  # noqa: E402

_N_LIMBS_C = [int(v) for v in int_to_limbs(_ORDER_N)]
_N_DELTA = (1 << 256) - _ORDER_N
_N_DELTA_LIMBS = [int(v)
                  for v in int_to_limbs(_N_DELTA,
                                        (_N_DELTA.bit_length() + 15) // 16)]


def _k_carry(cols, n_out, xp=jnp):
    """Generic carry chain over small (< 2^31) columns -> n_out limbs."""
    mask = xp.uint32(MASK)
    out = []
    c = xp.zeros_like(cols[0])
    for k in range(len(cols)):
        t = cols[k] + c
        out.append(t & mask)
        c = t >> 16
    while len(out) < n_out:
        out.append(c & mask)
        c = c >> 16
    return out[:n_out]


def _k_mul_cols(a, b_const, xp=jnp):
    """Uncarried schoolbook columns of (limb list a) x (Python-int limb
    constants b_const); mirrors ``big_mul_cols``."""
    mask = xp.uint32(MASK)
    zero = xp.zeros_like(a[0])
    cols = [zero] * (len(a) + len(b_const))
    for i, ai in enumerate(a):
        for j, bj in enumerate(b_const):
            p = ai * xp.uint32(bj)
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)
    return cols


def _k_mul_cols_vv(a, b, xp=jnp):
    """Uncarried schoolbook columns, both operands limb lists."""
    mask = xp.uint32(MASK)
    zero = xp.zeros_like(a[0])
    cols = [zero] * (len(a) + len(b))
    for i, ai in enumerate(a):
        for j, bj in enumerate(b):
            p = ai * bj
            cols[i + j] = cols[i + j] + (p & mask)
            cols[i + j + 1] = cols[i + j + 1] + (p >> 16)
    return cols


def _k_sub_const_chain(a, m_limbs, xp=jnp):
    """Borrow-chain ``a - const``: returns (diff_limbs, borrow_flag);
    borrow == 1 iff a < const.  The one borrow chain shared by the
    conditional subtracts and the range checks."""
    mask = xp.uint32(MASK)
    out = []
    borrow = xp.zeros_like(a[0])
    for k in range(16):
        t = a[k] + xp.uint32(1 << 16) - xp.uint32(m_limbs[k]) - borrow
        out.append(t & mask)
        borrow = xp.uint32(1) - (t >> 16)
    return out, borrow


def _k_cond_sub(a, m_limbs, xp=jnp):
    """One conditional subtract of the constant ``m_limbs``; shared by
    the mod-N and mod-P variants."""
    out, borrow = _k_sub_const_chain(a, m_limbs, xp)
    return _k_select(borrow, a, out, xp)


def _k_cond_sub_n(a, xp=jnp):
    return _k_cond_sub(a, _N_LIMBS_C, xp)


def _k_fn_mul(a, b, xp=jnp):
    """Canonical mod-N product; mirrors ``OrderN.mul`` fold-for-fold
    (three delta folds 32 -> 26 -> 20 -> 16+eps, then two top-limb
    folds and two conditional subtracts)."""
    return _k_fn_red_cols(_k_mul_cols_vv(a, b, xp), xp)


def _fn_mul_kernel(a_ref, b_ref, out_ref):
    """One [16, LANE_BLOCK] tile: out = a * b mod N (canonical)."""
    _write16(out_ref, _k_fn_mul(_read16(a_ref), _read16(b_ref)))


def fn_mul_pallas(a: jnp.ndarray, b: jnp.ndarray, *,
                  interpret: bool | None = None) -> jnp.ndarray:
    """``[B, 16] x [B, 16] -> [B, 16]`` mod-N multiply via the Pallas
    kernel; bit-identical to ``bigint.FN.mul``."""
    return _ew(_fn_mul_kernel, [a, b], interpret=interpret)


# ---------------------------------------------------------------------------
# glue kernels: every remaining field op of the recover pipeline.
#
# Round-4 on-chip census (harness/hlo_census.py): with the LOOPS fused,
# the recover graph still executed as ~3.8k XLA fusions — carry chains
# of the scalar prelude, the GLV split, the y-recovery, the table
# normalization and the affine tail — and on this backend each fusion
# is its own ~0.1 ms dispatch, so the glue cost ~25x the kernels' own
# arithmetic (65 ms of kernel time inside a 1.9 s batch at 1024 rows).
# Each helper below turns one field-op call site into ONE launch; the
# in-kernel math reuses the ``_k_*`` library above bit-for-bit, so the
# fused and plain paths stay differential-testable against each other.
# ---------------------------------------------------------------------------

def _k_cond_sub_p(a, xp=jnp):
    """In-kernel twin of ``Mod._cond_sub_m`` for the field prime."""
    return _k_cond_sub(a, _P_LIMBS, xp)


def _k_fn_red_cols(cols, xp=jnp):
    """Small (< 2^31) columns, any width in (16, 32] -> canonical mod-N
    value; the reduction tail of ``_k_fn_mul`` (mirrors
    ``OrderN._red_cols`` fold-for-fold)."""
    while len(cols) > 16:
        lo = cols[:16]
        hi = _k_carry(cols[16:], len(cols) - 16 + 1, xp)
        prod = _k_mul_cols(hi, _N_DELTA_LIMBS, xp)
        w = max(16, len(prod))
        zero = xp.zeros_like(cols[0])
        lo_w = lo + [zero] * (w - 16)
        pr_w = prod + [zero] * (w - len(prod))
        cols = [x + y for x, y in zip(lo_w, pr_w)]
    a17 = _k_carry(cols, 17, xp)
    for _ in range(2):
        top = a17[16]
        fold = _k_mul_cols([top], _N_DELTA_LIMBS, xp)[:16]
        zero = xp.zeros_like(top)
        fold = fold + [zero] * (16 - len(fold))
        a17 = _k_carry([x + y for x, y in zip(a17[:16], fold)], 17, xp)
    out = _k_cond_sub_n(a17[:16], xp)
    return _k_cond_sub_n(out, xp)


# C with cols_k = a_k + (MASK - b_k) + C_k giving a - b + (2^256 - 1) + C
# ≡ a - b + 2N (mod N): borrow-free per-limb subtraction mod N.
_FN_SUBC = (2 * _ORDER_N) - (1 << 256) + 1
_FN_SUBC_LIMBS = [int(v) for v in int_to_limbs(_FN_SUBC)]


def _k_fn_sub(a, b, xp=jnp):
    """Canonical a - b mod N (both canonical)."""
    mask = xp.uint32(MASK)
    cols = [x + (mask - y) + xp.uint32(_FN_SUBC_LIMBS[k])
            for k, (x, y) in enumerate(zip(a, b))]
    return _k_fn_red_cols(cols, xp)


def _k_is_zero(a, xp=jnp):
    z = a[0] == 0
    for k in range(1, 16):
        z = z & (a[k] == 0)
    return z.astype(xp.uint32)


def _k_fn_neg(a, xp=jnp):
    """Canonical -a mod N (0 -> 0)."""
    out = _k_fn_sub([xp.zeros_like(v) for v in a], a, xp)
    return _k_select(_k_is_zero(a, xp), [xp.zeros_like(v) for v in a],
                     out, xp)


# glue kernel bodies (each one [rows, LANE_BLOCK] tile set)

def _fp_add_kernel(a_ref, b_ref, o_ref):
    _write16(o_ref, _k_add(_read16(a_ref), _read16(b_ref)))


def _fp_sub_kernel(a_ref, b_ref, o_ref):
    _write16(o_ref, _k_sub(_read16(a_ref), _read16(b_ref)))


def _fp_neg_kernel(a_ref, o_ref):
    _write16(o_ref, _k_neg(_read16(a_ref)))


def _fp_canon_kernel(a_ref, o_ref):
    _write16(o_ref, _k_cond_sub_p(_read16(a_ref)))


def _fn_sub_kernel(a_ref, b_ref, o_ref):
    _write16(o_ref, _k_fn_sub(_read16(a_ref), _read16(b_ref)))


def _fn_neg_kernel(a_ref, o_ref):
    _write16(o_ref, _k_fn_neg(_read16(a_ref)))


def _fn_red17_kernel(a_ref, o_ref):
    cols = [a_ref[k, :] for k in range(17)]
    _write16(o_ref, _k_fn_red_cols(cols))


@functools.lru_cache(maxsize=4)
def _mulhi8_kernel_for(g: int):
    """Kernel: high limbs 24..31 of a 16-limb value times the 16-limb
    constant ``g`` (the GLV rounding step ``(k * g) >> 384``)."""
    g_limbs = [int(v) for v in int_to_limbs(g)]

    def kernel(a_ref, o_ref):
        cols = _k_mul_cols(_read16(a_ref), g_limbs)
        limbs = _k_carry(cols, 32)
        for k in range(8):
            o_ref[k, :] = limbs[24 + k]

    return kernel


def _rows_call(kernel, arrs, in_rows, out_rows, interpret):
    """Shared launch plumbing for the glue kernels: each operand is a
    ``[rows_i, B]`` array tiled over LANE_BLOCK batch columns."""
    wide = arrs[0].shape[-1]
    nb = wide // LANE_BLOCK
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct((r, wide), jnp.uint32)
                        for r in out_rows),
        grid=(nb,),
        in_specs=[pl.BlockSpec((r, LANE_BLOCK), lambda i: (0, i))
                  for r in in_rows],
        out_specs=tuple(pl.BlockSpec((r, LANE_BLOCK), lambda i: (0, i))
                        for r in out_rows),
        interpret=interpret,
    )(*arrs)
    return outs


def _ew(kernel, ins, out_limbs=NLIMBS, *, interpret=None):
    """Elementwise-style glue launch: ``ins`` are ``[B, rows_i]`` limb
    arrays (same B), output ``[B, out_limbs]``."""
    if interpret is None:
        interpret = _default_interpret()
    B = ins[0].shape[0]
    pad = (-B) % LANE_BLOCK
    ats = [jnp.pad(a, ((0, pad), (0, 0))).T for a in ins]
    out, = _rows_call(kernel, ats, [a.shape[1] for a in ins],
                      [out_limbs], interpret)
    return out.T[:B]


def fp_add_pallas(a, b, **kw):
    return _ew(_fp_add_kernel, [a, b], **kw)


def fp_sub_pallas(a, b, **kw):
    return _ew(_fp_sub_kernel, [a, b], **kw)


def fp_neg_pallas(a, **kw):
    return _ew(_fp_neg_kernel, [a], **kw)


def fp_canon_pallas(a, **kw):
    return _ew(_fp_canon_kernel, [a], **kw)


def fn_sub_pallas(a, b, **kw):
    return _ew(_fn_sub_kernel, [a, b], **kw)


def fn_neg_pallas(a, **kw):
    return _ew(_fn_neg_kernel, [a], **kw)


def fn_red17_pallas(a, **kw):
    """``[B, 17]`` small-column value -> canonical mod-N ``[B, 16]``."""
    return _ew(_fn_red17_kernel, [a], **kw)


def mulhi8_pallas(a, g: int, **kw):
    """``[B, 16] -> [B, 8]``: limbs 24..31 of ``a * g`` for constant g."""
    return _ew(_mulhi8_kernel_for(g), [a], out_limbs=8, **kw)


# ---------------------------------------------------------------------------
# GLV-decompose kernel (round-4 v2): both recovery scalars -> ladder
# digits + signs in ONE launch, emitted directly in the strauss_tab
# input layout.  Absorbs what the XLA graph ran as ~60 dispatches: two
# (k*g)>>384 rounding products per scalar, four mod-N muls, the k1/k2
# lattice subtractions, the sign splits (|k| < 2^140 test + negate)
# and the 33-window digit extraction/transpose/pack.
# ---------------------------------------------------------------------------

_GLV_WINDOWS = 33


def _k_glv_track(u, consts, xp=jnp):
    """One scalar's GLV split: canonical mod-N ``u`` (16 limbs) ->
    (k1_digits, neg1, k2_digits, neg2), digits MSD-first length 33.
    Mirrors ``ec._glv_decompose`` + ``_digits33`` value-for-value."""
    g1, g2, a1, a2, b1n, b2 = consts

    def mulhi8(a, g_limbs):
        limbs = _k_carry(_k_mul_cols(a, g_limbs, xp), 32, xp)
        return limbs[24:32] + [xp.zeros_like(a[0])] * 8

    def fn_mul_const(a, c_limbs):
        return _k_fn_red_cols(_k_mul_cols(a, c_limbs, xp), xp)

    c1 = mulhi8(u, g1)
    c2 = mulhi8(u, g2)
    k1 = _k_fn_sub(_k_fn_sub(u, fn_mul_const(c1, a1), xp),
                   fn_mul_const(c2, a2), xp)
    k2 = _k_fn_sub(fn_mul_const(c1, b1n), fn_mul_const(c2, b2), xp)

    def sign_split(v):
        # negative residues are detected by size: |k| < 2^140 always
        hi = v[8] >> xp.uint32(12)
        for k in range(9, 16):
            hi = hi | v[k]
        neg = (hi != 0).astype(xp.uint32)
        mag = _k_select(neg, _k_fn_neg(v, xp), v, xp)
        return mag, neg

    k1m, n1 = sign_split(k1)
    k2m, n2 = sign_split(k2)

    def digits(v):
        # MSD-first 4-bit windows of a 132-bit magnitude
        out = []
        for w in range(_GLV_WINDOWS):
            j = _GLV_WINDOWS - 1 - w           # LSD window index
            out.append((v[j // 4] >> xp.uint32(4 * (j % 4))) & xp.uint32(0xF))
        return out

    return digits(k1m), n1, digits(k2m), n2


@functools.lru_cache(maxsize=1)
def _glv_kernel():
    from eges_tpu.ops.ec import (
        _G_A1, _G_A2, _G_B1N, _G_B2, _G_G1, _G_G2,
    )

    def limbs(x):
        return tuple(int(v) for v in int_to_limbs(x))

    consts = (limbs(_G_G1), limbs(_G_G2), limbs(_G_A1), limbs(_G_A2),
              limbs(_G_B1N), limbs(_G_B2))

    def kernel(u1_ref, u2_ref, dig_ref, neg_ref):
        dg1, n1g, dg2, n2g = _k_glv_track(_read16(u1_ref), consts)
        dr1, n1r, dr2, n2r = _k_glv_track(_read16(u2_ref), consts)
        zero = jnp.zeros((LANE_BLOCK,), jnp.uint32)
        for w in range(_GLV_WINDOWS):
            dig_ref[w, 0, :] = dg1[w]
            dig_ref[w, 1, :] = dg2[w]
            dig_ref[w, 2, :] = dr1[w]
            dig_ref[w, 3, :] = dr2[w]
            for t in range(4, 8):
                dig_ref[w, t, :] = zero
        for t, n in enumerate((n1g, n2g, n1r, n2r)):
            neg_ref[t, :] = n
        for t in range(4, 8):
            neg_ref[t, :] = zero

    return kernel


def glv_digits_pallas(u1: jnp.ndarray, u2: jnp.ndarray, *,
                      interpret: bool | None = None):
    """``u1/u2 [B, 16]`` canonical mod-N scalars -> ``(dig [33, 8,
    Bpad], neg [8, Bpad])`` ready for :func:`strauss_tab`."""
    if interpret is None:
        interpret = _default_interpret()
    B = u1.shape[0]
    pad = (-B) % LANE_BLOCK
    u1t = jnp.pad(u1, ((0, pad), (0, 0))).T
    u2t = jnp.pad(u2, ((0, pad), (0, 0))).T
    wide = u1t.shape[1]
    dig, neg = pl.pallas_call(
        _glv_kernel(),
        out_shape=(jax.ShapeDtypeStruct((_GLV_WINDOWS, 8, wide), jnp.uint32),
                   jax.ShapeDtypeStruct((8, wide), jnp.uint32)),
        grid=(wide // LANE_BLOCK,),
        in_specs=[pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))] * 2,
        out_specs=(pl.BlockSpec((_GLV_WINDOWS, 8, LANE_BLOCK),
                                lambda i: (0, 0, i)),
                   pl.BlockSpec((8, LANE_BLOCK), lambda i: (0, i))),
        interpret=interpret,
    )(u1t, u2t)
    return dig, neg


def glv_digits_np(u1: np.ndarray, u2: np.ndarray):
    """Numpy twin of the GLV-decompose kernel (unpadded)."""
    from eges_tpu.ops.ec import (
        _G_A1, _G_A2, _G_B1N, _G_B2, _G_G1, _G_G2,
    )

    def limbs(x):
        return tuple(int(v) for v in int_to_limbs(x))

    consts = (limbs(_G_G1), limbs(_G_G2), limbs(_G_A1), limbs(_G_A2),
              limbs(_G_B1N), limbs(_G_B2))
    B = u1.shape[0]
    t1 = [u1[:, k].copy() for k in range(NLIMBS)]
    t2 = [u2[:, k].copy() for k in range(NLIMBS)]
    dg1, n1g, dg2, n2g = _k_glv_track(t1, consts, np)
    dr1, n1r, dr2, n2r = _k_glv_track(t2, consts, np)
    dig = np.zeros((_GLV_WINDOWS, 8, B), np.uint32)
    for w in range(_GLV_WINDOWS):
        dig[w, 0], dig[w, 1] = dg1[w], dg2[w]
        dig[w, 2], dig[w, 3] = dr1[w], dr2[w]
    neg = np.zeros((8, B), np.uint32)
    for t, n in enumerate((n1g, n2g, n1r, n2r)):
        neg[t] = n
    return dig, neg


@functools.lru_cache(maxsize=8)
def _mul_small_kernel_for(k: int):
    def kernel(a_ref, o_ref):
        _write16(o_ref, _k_mul_small(_read16(a_ref), k))

    return kernel


def fp_mul_small_pallas(a, k: int, **kw):
    return _ew(_mul_small_kernel_for(k), [a], **kw)


# ---------------------------------------------------------------------------
# recover-pipeline composite kernels (round-4 v2): the scalar prelude,
# the y-fix after sqrt, the u1/u2 scalars after the mod-N inverse, and
# the affine/keccak-prep finish — each a whole pipeline STAGE as one
# launch.  The per-op glue kernels above cut the graph from ~3.8k to
# ~640 dispatches; these composites absorb the remaining carry chains,
# range checks, parity fixes and byte packing that still ran as
# separate fusions (each a fresh round trip on the tunnel backend).
# ---------------------------------------------------------------------------


def _k_lt_const(a, m_limbs, xp=jnp):
    """Borrow-chain a < const flag ([B] u32 0/1); mirrors big_lt."""
    return _k_sub_const_chain(a, m_limbs, xp)[1]


def _k_unpack_be(rows, off, xp=jnp):
    """32 big-endian byte rows (u32 values < 256) starting at ``off``
    -> 16 LE 16-bit limbs; mirrors ``bigint.bytes_be_to_limbs``."""
    return [rows[off + 31 - 2 * k] | (rows[off + 30 - 2 * k] << xp.uint32(8))
            for k in range(16)]


def _k_recover_prelude(r, s, v, xp=jnp):
    """Checks + x-candidate + y^2 for the whole batch: mirrors the
    front of ``ec.ecrecover_point`` value-for-value.  ``v`` is the
    recovery id as a [B] u32 vector.  Returns (x, y_sq, ok)."""
    r_ok = (xp.uint32(1) - _k_is_zero(r, xp)) * _k_lt_const(r, _N_LIMBS_C, xp)
    s_ok = (xp.uint32(1) - _k_is_zero(s, xp)) * _k_lt_const(s, _N_LIMBS_C, xp)
    v_ok = (v < 4).astype(xp.uint32)
    hi = (v >= 2).astype(xp.uint32)
    # x = r + (v >= 2 ? N : 0), 17-limb carry chain
    mask = xp.uint32(MASK)
    x = []
    c = xp.zeros_like(r[0])
    for k in range(16):
        t = r[k] + hi * xp.uint32(_N_LIMBS_C[k]) + c
        x.append(t & mask)
        c = t >> 16
    x_ok = (c == 0).astype(xp.uint32) * _k_lt_const(x, _P_LIMBS, xp)
    y_sq = _k_mul(_k_sqr(x, xp), x, xp)
    seven = [xp.uint32(7) if k == 0 else xp.uint32(0) for k in range(16)]
    y_sq = _k_carry_tail([a + b for a, b in zip(y_sq, seven)], xp)
    return x, y_sq, r_ok * s_ok * v_ok * x_ok


def _recover_prelude_kernel(sig_ref, hash_ref, x_ref, ysq_ref, ok_ref,
                            r_ref, s_ref, z_ref, v_ref):
    """Wire bytes in, scalar-stage outputs out: unpacks r/s/v/z from
    the 65-byte signature + 32-byte hash rows IN-KERNEL (the byte
    shuffles ran as ~14 separate XLA dispatches), then the checks and
    y^2 candidate."""
    srows = [sig_ref[k, :] for k in range(65)]
    r = _k_unpack_be(srows, 0)
    s = _k_unpack_be(srows, 32)
    v = srows[64]
    z = _k_unpack_be([hash_ref[k, :] for k in range(32)], 0)
    x, y_sq, ok = _k_recover_prelude(r, s, v)
    _write16(x_ref, x)
    _write16(ysq_ref, y_sq)
    ok_ref[0, :] = ok
    _write16(r_ref, r)
    _write16(s_ref, s)
    _write16(z_ref, z)
    v_ref[0, :] = v


def recover_prelude_pallas(sigs, hashes, *, interpret=None):
    """``sigs [B, 65]`` u8 wire signatures, ``hashes [B, 32]`` u8 ->
    ``(x, y_sq, ok, r, s, z, v)`` — the unpacked limb fields ride out
    of the same launch that checks them."""
    if interpret is None:
        interpret = _default_interpret()
    B = sigs.shape[0]
    pad = (-B) % LANE_BLOCK
    st = jnp.pad(sigs.astype(jnp.uint32), ((0, pad), (0, 0))).T
    ht = jnp.pad(hashes.astype(jnp.uint32), ((0, pad), (0, 0))).T
    wide = st.shape[1]
    lim = jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32)
    row = jax.ShapeDtypeStruct((1, wide), jnp.uint32)
    lspec = pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))
    rspec = pl.BlockSpec((1, LANE_BLOCK), lambda i: (0, i))
    x, ysq, ok, r, s, z, v = pl.pallas_call(
        _recover_prelude_kernel,
        out_shape=(lim, lim, row, lim, lim, lim, row),
        grid=(wide // LANE_BLOCK,),
        in_specs=[pl.BlockSpec((65, LANE_BLOCK), lambda i: (0, i)),
                  pl.BlockSpec((32, LANE_BLOCK), lambda i: (0, i))],
        out_specs=(lspec, lspec, rspec, lspec, lspec, lspec, rspec),
        interpret=interpret,
    )(st, ht)
    return (x.T[:B], ysq.T[:B], ok[0, :B],
            r.T[:B], s.T[:B], z.T[:B], v[0, :B])


def _k_y_fix(root, y_sq, v, xp=jnp):
    """After the sqrt pow: canonicalize the root, verify it, fix parity
    to v&1.  Mirrors FP.sqrt's check + ecrecover_point's parity select.
    Returns (y, y_ok)."""
    rc = _k_cond_sub_p(_k_sqr(root, xp), xp)
    ac = _k_cond_sub_p(y_sq, xp)
    y_ok = xp.ones_like(root[0])
    for g, w in zip(rc, ac):
        y_ok = y_ok * (g == w).astype(xp.uint32)
    y0 = _k_cond_sub_p(root, xp)
    want_odd = v & xp.uint32(1)
    flip = want_odd ^ (y0[0] & xp.uint32(1))
    y = _k_select(flip, _k_neg(y0, xp), y0, xp)
    return y, y_ok


def _y_fix_kernel(root_ref, ysq_ref, v_ref, y_ref, ok_ref):
    y, ok = _k_y_fix(_read16(root_ref), _read16(ysq_ref), v_ref[0, :])
    _write16(y_ref, y)
    ok_ref[0, :] = ok


def y_fix_pallas(root, y_sq, v, *, interpret=None):
    """``(root, y_sq) [B, 16]`` relaxed, ``v [B]`` -> ``(y [B, 16],
    y_ok [B])``."""
    if interpret is None:
        interpret = _default_interpret()
    B = root.shape[0]
    pad = (-B) % LANE_BLOCK
    rt = jnp.pad(root, ((0, pad), (0, 0))).T
    at = jnp.pad(y_sq, ((0, pad), (0, 0))).T
    vt = jnp.pad(v.astype(jnp.uint32), (0, pad)).reshape(1, -1)
    wide = rt.shape[1]
    y, ok = pl.pallas_call(
        _y_fix_kernel,
        out_shape=(jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32),
                   jax.ShapeDtypeStruct((1, wide), jnp.uint32)),
        grid=(wide // LANE_BLOCK,),
        in_specs=[pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
                  pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
                  pl.BlockSpec((1, LANE_BLOCK), lambda i: (0, i))],
        out_specs=(pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
                   pl.BlockSpec((1, LANE_BLOCK), lambda i: (0, i))),
        interpret=interpret,
    )(rt, at, vt)
    return y.T[:B], ok[0, :B]


def _k_u1u2(z, s, r_inv, xp=jnp):
    """u1 = -(z mod N) * r^-1, u2 = s * r^-1 (all canonical mod N);
    mirrors the u1/u2 block of ``ec.ecrecover_point``."""
    z_mod = _k_fn_red_cols(list(z) + [xp.zeros_like(z[0])], xp)
    u1 = _k_fn_neg(_k_fn_mul(z_mod, r_inv, xp), xp)
    u2 = _k_fn_mul(s, r_inv, xp)
    return u1, u2


def _u1u2_kernel(z_ref, s_ref, rinv_ref, u1_ref, u2_ref):
    u1, u2 = _k_u1u2(_read16(z_ref), _read16(s_ref), _read16(rinv_ref))
    _write16(u1_ref, u1)
    _write16(u2_ref, u2)


def u1u2_pallas(z, s, r_inv, *, interpret=None):
    if interpret is None:
        interpret = _default_interpret()
    B = z.shape[0]
    pad = (-B) % LANE_BLOCK
    ats = [jnp.pad(a, ((0, pad), (0, 0))).T for a in (z, s, r_inv)]
    wide = ats[0].shape[1]
    u1, u2 = pl.pallas_call(
        _u1u2_kernel,
        out_shape=tuple(jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32)
                        for _ in range(2)),
        grid=(wide // LANE_BLOCK,),
        in_specs=[pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))] * 3,
        out_specs=tuple(pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))
                        for _ in range(2)),
        interpret=interpret,
    )(*ats)
    return u1.T[:B], u2.T[:B]


def _k_limbs_to_words_be(a, xp=jnp):
    """16 LE 16-bit limbs (one 256-bit value) -> 8 LE u32 words of the
    value's BIG-endian byte string (keccak input order)."""
    out = []
    for w in range(8):
        # BE bytes 4w..4w+3 come from limbs 15-2w (hi) and 14-2w (lo)
        hi_l = a[15 - 2 * w]
        lo_l = a[14 - 2 * w]
        b0 = hi_l >> xp.uint32(8)
        b1 = hi_l & xp.uint32(0xFF)
        b2 = lo_l >> xp.uint32(8)
        b3 = lo_l & xp.uint32(0xFF)
        out.append(b0 | (b1 << xp.uint32(8)) | (b2 << xp.uint32(16))
                   | (b3 << xp.uint32(24)))
    return out


def _k_recover_finish(X, Y, Z, zi_raw, ok_in, xp=jnp):
    """Jacobian result + raw (relaxed) Z-inverse + accumulated validity
    -> affine (qx, qy), final ok, and the padded keccak block words of
    qx||qy.  Mirrors ``to_affine`` + the final selects of
    ``ecrecover_point`` + the keccak prep of ``pubkey_to_address``."""
    inf = _k_is_zero_mod(Z, xp)
    zi = _k_cond_sub_p(zi_raw, xp)   # inv_batched canonicalizes
    zi2 = _k_sqr(zi, xp)
    x = _k_cond_sub_p(_k_mul(X, zi2, xp), xp)
    y = _k_cond_sub_p(_k_mul(Y, _k_mul(zi, zi2, xp), xp), xp)
    zero = [xp.zeros_like(x[0])] * 16
    x = _k_select(inf, zero, x, xp)
    y = _k_select(inf, zero, y, xp)
    ok = ok_in * (xp.uint32(1) - inf)
    qx = _k_select(ok, x, zero, xp)
    qy = _k_select(ok, y, zero, xp)
    words = (_k_limbs_to_words_be(qx, xp) + _k_limbs_to_words_be(qy, xp))
    # keccak padding for a 64-byte message in a 136-byte rate block:
    # byte 64 = 0x01 (word 16 lsb), byte 135 = 0x80 (word 33 msb)
    z0 = xp.zeros_like(words[0])
    words.append(z0 + xp.uint32(1))
    words += [z0] * 16
    words.append(z0 + xp.uint32(0x80000000))
    return qx, qy, ok, words


def _recover_finish_kernel(x_ref, y_ref, z_ref, zi_ref, ok_ref,
                           qx_ref, qy_ref, oko_ref, w_ref):
    qx, qy, ok, words = _k_recover_finish(
        _read16(x_ref), _read16(y_ref), _read16(z_ref), _read16(zi_ref),
        ok_ref[0, :])
    _write16(qx_ref, qx)
    _write16(qy_ref, qy)
    oko_ref[0, :] = ok
    for k in range(34):
        w_ref[k, :] = words[k]


def recover_finish_pallas(X, Y, Z, zi_raw, ok_in, *, interpret=None):
    """``(X, Y, Z, zi_raw) [B, 16]``, ``ok_in [B]`` -> ``(qx, qy
    [B, 16] canonical/masked, ok [B], words [34, Bpad])``."""
    if interpret is None:
        interpret = _default_interpret()
    B = X.shape[0]
    pad = (-B) % LANE_BLOCK
    ats = [jnp.pad(a, ((0, pad), (0, 0))).T for a in (X, Y, Z, zi_raw)]
    okt = jnp.pad(ok_in.astype(jnp.uint32), (0, pad)).reshape(1, -1)
    wide = ats[0].shape[1]
    qx, qy, ok, words = pl.pallas_call(
        _recover_finish_kernel,
        out_shape=(jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32),
                   jax.ShapeDtypeStruct((NLIMBS, wide), jnp.uint32),
                   jax.ShapeDtypeStruct((1, wide), jnp.uint32),
                   jax.ShapeDtypeStruct((34, wide), jnp.uint32)),
        grid=(wide // LANE_BLOCK,),
        in_specs=[pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i))] * 4
        + [pl.BlockSpec((1, LANE_BLOCK), lambda i: (0, i))],
        out_specs=(pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
                   pl.BlockSpec((NLIMBS, LANE_BLOCK), lambda i: (0, i)),
                   pl.BlockSpec((1, LANE_BLOCK), lambda i: (0, i)),
                   pl.BlockSpec((34, LANE_BLOCK), lambda i: (0, i))),
        interpret=interpret,
    )(*ats, okt)
    return qx.T[:B], qy.T[:B], ok[0, :B], words


def _keccak_round_kernel(w_ref, st_ref):
    """ONE keccak-f round per grid step (grid = (batch, 24)).

    The unrolled 24-round body is the largest Mosaic kernel in the
    pipeline (~3.6k vector ops) and a prime suspect for the ~150 s
    per-batch-size compile on the tunnel backend (r5 verdict item 4):
    rolling rounds onto the grid gives Mosaic a 24x smaller body to
    compile while keeping ONE pallas_call.  The 25x2 u32 state lives in
    the output ref, revisited across round steps (rounds are the minor
    grid dim, so the block stays resident); the final digest rows are
    gathered by the wrapper.  Gated by EGES_TPU_KECCAK_GRID until the
    on-chip compile-time A/B picks a default."""
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        zero = jnp.zeros_like(w_ref[0, :])
        for l in range(25):
            st_ref[l, :] = w_ref[2 * l, :] if l < 17 else zero
            st_ref[25 + l, :] = w_ref[2 * l + 1, :] if l < 17 else zero

    lo = [st_ref[l, :] for l in range(25)]
    hi = [st_ref[25 + l, :] for l in range(25)]
    # theta
    clo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20]
           for x in range(5)]
    chi_ = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20]
            for x in range(5)]
    for x in range(5):
        rl, rh = _k_rot64(clo[(x + 1) % 5], chi_[(x + 1) % 5], 1, jnp)
        dlo, dhi = clo[(x + 4) % 5] ^ rl, chi_[(x + 4) % 5] ^ rh
        for y in range(5):
            lo[x + 5 * y] = lo[x + 5 * y] ^ dlo
            hi[x + 5 * y] = hi[x + 5 * y] ^ dhi
    # rho + pi
    blo, bhi = [None] * 25, [None] * 25
    for x in range(5):
        for y in range(5):
            dl = y + 5 * ((2 * x + 3 * y) % 5)
            blo[dl], bhi[dl] = _k_rot64(lo[x + 5 * y], hi[x + 5 * y],
                                        _KECCAK_ROT[x][y], jnp)
    # chi
    for y in range(5):
        row_l = [blo[x + 5 * y] for x in range(5)]
        row_h = [bhi[x + 5 * y] for x in range(5)]
        for x in range(5):
            lo[x + 5 * y] = row_l[x] ^ (~row_l[(x + 1) % 5]
                                        & row_l[(x + 2) % 5])
            hi[x + 5 * y] = row_h[x] ^ (~row_h[(x + 1) % 5]
                                        & row_h[(x + 2) % 5])
    # iota — the only per-round constant: a 24-way scalar select chain
    # beats plumbing an SMEM table through the call for 2 u32s
    rc_lo = jnp.uint32(0)
    rc_hi = jnp.uint32(0)
    for i, c in enumerate(_KECCAK_RC):
        rc_lo = jnp.where(r == i, jnp.uint32(c & 0xFFFFFFFF), rc_lo)
        rc_hi = jnp.where(r == i, jnp.uint32(c >> 32), rc_hi)
    lo[0] = lo[0] ^ rc_lo
    hi[0] = hi[0] ^ rc_hi
    for l in range(25):
        st_ref[l, :] = lo[l]
        st_ref[25 + l, :] = hi[l]


def keccak_grid_enabled() -> bool:
    return os.environ.get("EGES_TPU_KECCAK_GRID", "") == "1"


def keccak_rows_pallas(words: jnp.ndarray, *,
                       interpret: bool | None = None) -> jnp.ndarray:
    """``[34, wide]`` block words (already limb-major) -> ``[8, wide]``
    digest words; the transpose-free twin of keccak_block_pallas for
    the fused pipeline."""
    if interpret is None:
        interpret = _default_interpret()
    wide = words.shape[1]
    if keccak_grid_enabled():
        st = pl.pallas_call(
            _keccak_round_kernel,
            out_shape=jax.ShapeDtypeStruct((50, wide), jnp.uint32),
            grid=(wide // LANE_BLOCK, 24),
            in_specs=[pl.BlockSpec((34, LANE_BLOCK), lambda b, r: (0, b))],
            out_specs=pl.BlockSpec((50, LANE_BLOCK), lambda b, r: (0, b)),
            interpret=interpret,
        )(words)
        # digest order lo0 hi0 lo1 hi1 … (squeeze order of the flat twin)
        return st[jnp.array([0, 25, 1, 26, 2, 27, 3, 28], jnp.int32), :]
    return pl.pallas_call(
        _keccak_kernel,
        out_shape=jax.ShapeDtypeStruct((8, wide), jnp.uint32),
        grid=(wide // LANE_BLOCK,),
        in_specs=[pl.BlockSpec((34, LANE_BLOCK), lambda b: (0, b))],
        out_specs=pl.BlockSpec((8, LANE_BLOCK), lambda b: (0, b)),
        interpret=interpret,
    )(words)
