"""Fixed-width 256-bit modular arithmetic for TPU (JAX).

The reference does all of this inside C libsecp256k1 with 64-bit limbs and
carry chains (ref: crypto/secp256k1/libsecp256k1/src/field_5x52_impl.h role).
TPUs have no native 64-bit integer datapath, so the TPU-native design is
different: a 256-bit integer is a vector of **16 little-endian limbs of 16
bits each, stored as uint32**.  Every op below is shape-polymorphic over
leading batch dimensions (``[..., 16]``), so a batch of B field elements is a
``[B, 16]`` uint32 array — rows map onto VPU lanes, and the whole pipeline
stays in native int32 hardware ops (no XLA 64-bit emulation):

* 16b x 16b limb products are < 2^32: a single uint32 multiply never wraps.
* Column accumulation splits products into lo/hi 16-bit halves, so every
  partial sum stays far below 2^32 (max ~2^21 for a 16x16 schoolbook).
* Carry propagation is a short static chain of shifts/masks.

Reduction uses the pseudo-Mersenne shape of both secp256k1 moduli
(``m = 2^256 - delta``): fold ``hi * delta`` back into the low words a fixed
number of times, then conditionally subtract.  Inverse and sqrt go through
Fermat (``a^(m-2)``, ``a^((m+1)/4)``) with a rolled ``lax.fori_loop`` over the
constant exponent bits so the compiled graph stays small.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

LIMB_BITS = 16
NLIMBS = 16  # 256 bits
MASK = (1 << LIMB_BITS) - 1

# secp256k1 field prime and group order (ref: crypto/secp256k1 constants).
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


# ---------------------------------------------------------------------------
# host-side conversions (trace-time constants and tests)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    """Python int -> n little-endian 16-bit limbs (numpy uint32)."""
    if x < 0 or x >= 1 << (LIMB_BITS * n):
        raise ValueError("out of range")
    return np.array([(x >> (LIMB_BITS * i)) & MASK for i in range(n)], dtype=np.uint32)


def limbs_to_int(a) -> int:
    """Limb array (last axis) -> Python int.  Host/test use only."""
    a = np.asarray(a)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(a.reshape(-1)))


def bytes_be_to_limbs(b: jnp.ndarray) -> jnp.ndarray:
    """``[..., 32]`` big-endian bytes (uint8) -> ``[..., 16]`` limbs (uint32).

    In-graph unpacking for wire-format inputs (r/s/hash fields of the 65-byte
    signatures the reference passes to RecoverPubkey, secp256.go:105).
    """
    le = b[..., ::-1].astype(jnp.uint32)  # little-endian bytes
    pairs = le.reshape(*le.shape[:-1], NLIMBS, 2)
    return pairs[..., 0] | (pairs[..., 1] << 8)


def limbs_to_bytes_be(a: jnp.ndarray) -> jnp.ndarray:
    """``[..., 16]`` limbs -> ``[..., 32]`` big-endian bytes (uint8)."""
    lo = (a & 0xFF).astype(jnp.uint8)
    hi = ((a >> 8) & 0xFF).astype(jnp.uint8)
    le = jnp.stack([lo, hi], axis=-1).reshape(*a.shape[:-1], 2 * NLIMBS)
    return le[..., ::-1]


# ---------------------------------------------------------------------------
# carry chains and wide helpers
# ---------------------------------------------------------------------------

def _carry(cols: jnp.ndarray, n_out: int) -> jnp.ndarray:
    """Propagate carries over a column vector of small (<2^31) sums.

    Sequential but only ``cols.shape[-1]`` static steps of shift/mask.
    """
    out = []
    c = jnp.zeros(cols.shape[:-1], jnp.uint32)
    for k in range(cols.shape[-1]):
        t = cols[..., k] + c
        out.append(t & MASK)
        c = t >> LIMB_BITS
    while len(out) < n_out:
        out.append(c & MASK)
        c = c >> LIMB_BITS
    return jnp.stack(out[:n_out], axis=-1)


def big_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product of two limb vectors: ``[..., na] x [..., nb] -> [..., na+nb]``.

    Diagonal-gather column sums (no scatter ops — ``.at[].add`` lowered
    to thousands of scatters across the recover graph and dominated its
    compile time) followed by one carry chain; all accumulators stay far
    below 2^32 (col sums < 2^21 for 16x16).
    """
    na, nb = a.shape[-1], b.shape[-1]
    return _carry(big_mul_cols(a, b), na + nb)


def big_add(a: jnp.ndarray, b: jnp.ndarray, n_out: int | None = None) -> jnp.ndarray:
    """Uncarried limb add then carry-fix; output width ``n_out``."""
    na, nb = a.shape[-1], b.shape[-1]
    w = max(na, nb)
    if n_out is None:
        n_out = w + 1
    pa = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, w - na)])
    pb = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, w - nb)])
    return _carry(pa + pb, n_out)


def big_sub(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``a - b`` with borrow chain (same width).  Returns (diff, borrow_flag).

    borrow_flag is 1 where ``a < b`` (diff then holds ``a - b + 2^(16n)``).
    """
    n = a.shape[-1]
    assert b.shape[-1] == n
    out = []
    borrow = jnp.zeros(a.shape[:-1], jnp.uint32)
    for k in range(n):
        # Work in uint32: add 2^16 headroom so the subtraction never wraps.
        t = a[..., k] + jnp.uint32(1 << LIMB_BITS) - b[..., k] - borrow
        out.append(t & MASK)
        borrow = jnp.uint32(1) - (t >> LIMB_BITS)
    return jnp.stack(out, axis=-1), borrow


def big_lt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Per-row ``a < b`` as a uint32 0/1 flag."""
    _, borrow = big_sub(a, b)
    return borrow


def select(flag: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limb-select: ``flag ? a : b`` with flag broadcast over the limb axis."""
    return jnp.where(flag[..., None].astype(bool), a, b)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Per-row all-limbs-zero flag (uint32 0/1)."""
    return (jnp.max(a, axis=-1) == 0).astype(jnp.uint32)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Per-row limbwise equality flag (uint32 0/1)."""
    return jnp.all(a == b, axis=-1).astype(jnp.uint32)


# ---------------------------------------------------------------------------
# modular arithmetic for a fixed pseudo-Mersenne modulus
# ---------------------------------------------------------------------------

class Mod:
    """Arithmetic mod a constant ``m = 2^256 - delta`` (secp256k1 P or N).

    All methods take/return ``[..., 16]`` uint32 limb arrays with values in
    ``[0, m)`` and are safe under jit/vmap.  Exponents for :meth:`pow_const`
    are Python-int constants, rolled into a ``fori_loop`` over their bits.
    """

    def __init__(self, m: int, n_folds: int):
        self.m = m
        delta = (1 << 256) - m
        self.delta_limbs_np = int_to_limbs(delta, (delta.bit_length() + 15) // 16)
        self.m_limbs_np = int_to_limbs(m)
        self.n_folds = n_folds

    @property
    def m_limbs(self) -> jnp.ndarray:
        return jnp.asarray(self.m_limbs_np)

    def _cond_sub_m(self, a: jnp.ndarray) -> jnp.ndarray:
        """One conditional subtract of m from a 16-limb value in [0, 2m)."""
        diff, borrow = big_sub(a, jnp.broadcast_to(self.m_limbs, a.shape))
        return select(borrow, a, diff)

    def red(self, wide: jnp.ndarray) -> jnp.ndarray:
        """Reduce a wide (>16 limb) value mod m via delta-folding.

        ``n_folds`` folds shrink a 512-bit value to ``< 2^256 + small``; one
        extra fold then guarantees the limbs above 256 bits are exactly zero
        (if the top limb was 1, the new value is ``old - m < m``), so the
        truncation below is lossless and two conditional subtracts finish.
        """
        delta = jnp.asarray(self.delta_limbs_np)
        for _ in range(self.n_folds + 1):
            if wide.shape[-1] <= NLIMBS:
                break
            lo = wide[..., :NLIMBS]
            hi = wide[..., NLIMBS:]
            prod = big_mul(hi, jnp.broadcast_to(delta, (*hi.shape[:-1], delta.shape[-1])))
            wide = big_add(lo, prod)
        a = wide[..., :NLIMBS]
        a = self._cond_sub_m(a)
        a = self._cond_sub_m(a)
        return a

    def add(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        s = big_add(a, b, NLIMBS + 1)
        return self.red(s)

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        # a - b mod m with a,b in [0, m): add m then subtract, always >= 0.
        am = big_add(a, jnp.broadcast_to(self.m_limbs, a.shape), NLIMBS + 1)
        bp = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, 1)])
        diff, _ = big_sub(am, bp)
        return self.red(diff)

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        z = jnp.zeros_like(a)
        return select(is_zero(a), z, self.sub(z, a))

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return self.red(big_mul(a, b))

    def sqr(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mul(a, a)

    def mul_small(self, a: jnp.ndarray, k: int) -> jnp.ndarray:
        """Multiply by a small Python-int constant (k < 2^16)."""
        kl = jnp.full((*a.shape[:-1], 1), k, jnp.uint32)
        return self.red(big_mul(a, kl))

    def pow_const(self, a: jnp.ndarray, e: int) -> jnp.ndarray:
        """``a ** e mod m`` for a constant exponent, via a rolled bit loop."""
        nbits = e.bit_length()
        bits = jnp.asarray([(e >> i) & 1 for i in range(nbits)], dtype=jnp.uint32)
        # Derive the constant from ``a`` (a*0 + 1) so its varying-axes type
        # matches ``a`` under shard_map: a fori_loop carry must keep a
        # consistent type across iterations (mixing an unvarying constant
        # with a device-varying base trips the vma check).
        one = a * 0 + jnp.asarray(int_to_limbs(1))

        def body(i, state):
            result, base = state
            bit = bits[i]
            result = select(jnp.broadcast_to(bit, result.shape[:-1]),
                            self.mul(result, base), result)
            base = self.sqr(base)
            return result, base

        result, _ = jax.lax.fori_loop(0, nbits, body, (one, a))
        return result

    def inv(self, a: jnp.ndarray) -> jnp.ndarray:
        """Fermat inverse ``a^(m-2)``; returns 0 for input 0."""
        return self.pow_const(a, self.m - 2)

    def batch_inv(self, a: jnp.ndarray) -> jnp.ndarray:
        """Montgomery batch inversion over the leading batch axis.

        A Fermat inverse costs ~512 field muls *per row*; the batch trick
        replaces that with a handful of full-width muls plus ONE Fermat
        inverse of the whole batch's product.  Implemented as rolled
        Hillis-Steele prefix/suffix product scans (``fori_loop`` whose
        body is a single batched mul — the earlier Python-unrolled
        product tree traced ~80k HLO ops and dominated compile time):

            P[i] = x[0] * ... * x[i]        (log2 B rolled steps)
            S[i] = x[i] * ... * x[B-1]      (log2 B rolled steps)
            inv[i] = P[i-1] * S[i+1] * (P[B-1])^-1

        Zero rows pass through as 0 (same contract as :meth:`inv`).
        ``a`` must be ``[B, 16]``; any B >= 1.
        """
        B = a.shape[0]
        if B == 1:
            return self.inv(a)
        one = jnp.broadcast_to(jnp.asarray(int_to_limbs(1)), a.shape)
        zero_mask = self.is_zero_mod(a)
        x = select(zero_mask, one, a)  # make every row invertible
        idx = jnp.arange(B, dtype=jnp.uint32)
        nlev = (B - 1).bit_length()

        def scan(v):
            def step(k, p):
                sh = (jnp.uint32(1) << k).astype(jnp.uint32)
                rolled = jnp.roll(p, sh.astype(jnp.int32), axis=0)
                contrib = select(idx >= sh, rolled, one)
                return self.mul(p, contrib)

            return jax.lax.fori_loop(0, nlev, step, v)

        prefix = scan(x)
        suffix = scan(x[::-1])[::-1]
        total_inv = self.inv(prefix[-1:])  # [1, 16]
        p_prev = select(idx >= 1, jnp.roll(prefix, 1, axis=0), one)
        s_next = select(idx < B - 1, jnp.roll(suffix, -1, axis=0), one)
        inv = self.mul(self.mul(p_prev, s_next),
                       jnp.broadcast_to(total_inv, a.shape))
        inv = self.canon(inv)
        return select(zero_mask, jnp.zeros_like(a), inv)

    def inv_batched(self, a: jnp.ndarray) -> jnp.ndarray:
        """Shape-polymorphic front door for :meth:`batch_inv`: flattens
        leading dims; falls back to Fermat for unbatched inputs.

        Under the fused-kernel variant (EGES_TPU_PALLAS=ladder, TPU
        backend) this routes to the streamed pow kernel instead: a
        direct per-row Fermat inverse costs more field muls than the
        Montgomery scan trick, but runs as ONE kernel launch where the
        scan + rolled pow pay thousands of tiny dispatches — and launch
        overhead, not arithmetic, bounds this backend (BENCH r4)."""
        if a.ndim < 2:
            return self.inv(a)
        flat = a.reshape(-1, NLIMBS)
        from eges_tpu.ops.pallas_kernels import (
            ladder_kernels_enabled, pow_mod_pallas,
        )
        if ladder_kernels_enabled() and self.m in (P, N):
            out = pow_mod_pallas(flat, self.m - 2,
                                 "p" if self.m == P else "n")
            if self.m == P:
                # batch_inv canonicalizes; match it bit-for-bit so the
                # fused variant stays differential-testable against the
                # graph path (the mod-N kernel is canonical already)
                out = self.canon(out)
            return out.reshape(a.shape)
        return self.batch_inv(flat).reshape(a.shape)

    def const(self, x: int, like: jnp.ndarray) -> jnp.ndarray:
        """Broadcast a Python-int constant to the batch shape of ``like``."""
        return jnp.broadcast_to(jnp.asarray(int_to_limbs(x % self.m)), like.shape)

    # canonical-representation hooks; FieldP overrides for its relaxed form
    def canon(self, a: jnp.ndarray) -> jnp.ndarray:
        return a

    def is_zero_mod(self, a: jnp.ndarray) -> jnp.ndarray:
        return is_zero(a)

    def eq_mod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return eq(a, b)


# ---------------------------------------------------------------------------
# fast path for F_P: diagonal-gather column products + fold-in-column-space
# reduction + relaxed representation
# ---------------------------------------------------------------------------
#
# The generic Mod path above scatters 32 partial rows into a column vector
# and walks three carry/borrow chains per multiply (~100 sequential steps,
# ~800 HLO ops).  The F_P fast path below does the same work as:
#   * ONE constant-index gather that lines the 16x16 partial-product matrix
#     up along its anti-diagonals plus a single sum-reduce ("column sums"),
#   * delta-folding performed directly on the (uncarried) columns —
#     977*hi and hi<<2 vector adds, exploiting delta_P = 2^32 + 977 having
#     a single tiny limb,
#   * exactly two 16-step carry chains and one 5-step mini-chain.
# Outputs are RELAXED: in [0, 2^256), possibly >= P.  All F_P ops accept
# relaxed inputs; canonicalize (one conditional subtract) only at compare/
# output sites via canon()/is_zero_mod()/eq_mod().  This matches how
# libsecp26k1's field_5x52 representation defers normalization — re-derived
# here for 16-bit lanes and XLA (no borrowed code; ref role:
# crypto/secp256k1/libsecp256k1/src/field_5x52_impl.h).


@functools.lru_cache(maxsize=None)
def _diag_idx(na: int, nb: int):
    """Constant gather indices/masks aligning M[i, j] along k = i + j."""
    k = np.arange(na + nb - 1)[None, :]
    i = np.arange(na)[:, None]
    j = k - i
    mask = ((j >= 0) & (j < nb)).astype(np.uint32)
    idx = np.clip(j, 0, nb - 1).astype(np.int32)
    return idx, mask  # numpy constants (jnp values must not be cached
    #                   across traces — they would leak tracers)


def big_mul_cols(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Uncarried column sums of ``a * b``: ``[..., na+nb]`` uint32.

    Column k holds ``sum_{i+j=k} lo(a_i b_j) + sum_{i+j=k-1} hi(a_i b_j)``
    < 2^21 for na = nb = 16.
    """
    na, nb = a.shape[-1], b.shape[-1]
    prod = a[..., :, None] * b[..., None, :]  # [., na, nb]
    lo = prod & MASK
    hi = prod >> LIMB_BITS
    idx_np, mask_np = _diag_idx(na, nb)
    idx, mask = jnp.asarray(idx_np), jnp.asarray(mask_np)
    K = na + nb - 1
    bidx = jnp.broadcast_to(idx, (*prod.shape[:-2], na, K))
    lo_d = (jnp.take_along_axis(lo, bidx, axis=-1) * mask).sum(axis=-2)
    hi_d = (jnp.take_along_axis(hi, bidx, axis=-1) * mask).sum(axis=-2)
    zero = jnp.zeros((*lo_d.shape[:-1], 1), jnp.uint32)
    return (jnp.concatenate([lo_d, zero], axis=-1)
            + jnp.concatenate([zero, hi_d], axis=-1))


class FieldP(Mod):
    """The base field F_P: fast relaxed arithmetic + sqrt (P ≡ 3 mod 4)."""

    def __init__(self):
        super().__init__(P, n_folds=3)
        # constant for branchless subtraction: a - b ≡
        #   a + (0xFFFF - b) + (2^256 - 2*delta + 1)  (mod P), see sub()
        self._subc_np = int_to_limbs((1 << 256) - 2 * ((1 << 256) - P) + 1)
        # EGES_TPU_PALLAS=1 routes equal-shape batched multiplies through
        # the hand-tiled Pallas kernel (ops/pallas_kernels.py) — a
        # measurement hook for TPU A/B runs, not a default (per-mul
        # pallas_call boundaries forgo XLA fusion between field ops)
        import os as _os
        self._use_pallas = _os.environ.get("EGES_TPU_PALLAS", "") == "1"

    # -- the shared reduction tail ---------------------------------------

    def _reduce_cols(self, cols: jnp.ndarray) -> jnp.ndarray:
        """Columns (each < 2^31, width <= 32) -> relaxed 16-limb value.

        Bound contract: the two fold iterations below stay under 2^32
        when input columns are < 2^21 (multiplication) or < 2^19
        (add/sub/mul_small); see the inline bounds.
        """
        # fold columns >= 16 into the low 16 via delta = 2^32 + 977
        # (pad-and-add, NOT .at[].add — scatters are poison for both
        # XLA compile time and TPU lowering)
        while cols.shape[-1] > 16:
            lo = cols[..., :16]
            hi = cols[..., 16:]
            h = hi.shape[-1]
            w = max(16, h + 2)
            pad = [(0, 0)] * (cols.ndim - 1)
            lo_w = jnp.concatenate(
                [lo, jnp.zeros((*lo.shape[:-1], w - 16), jnp.uint32)],
                axis=-1) if w > 16 else lo
            # col j   += 977 * hi_j   (j < h;    977*2^21 < 2^31)
            t977 = jnp.pad(hi * jnp.uint32(977), pad + [(0, w - h)])
            # col j+2 += hi_j         (2^21)
            tsh = jnp.pad(hi, pad + [(2, w - h - 2)])
            cols = lo_w + t977 + tsh
        # first full carry: 16 columns < 2^32 -> limbs + c_top < 2^16+eps
        out = []
        c = jnp.zeros(cols.shape[:-1], jnp.uint32)
        for k in range(16):
            t = cols[..., k] + c
            out.append(t & MASK)
            c = t >> LIMB_BITS
        # fold c_top * 2^256 ≡ c_top * delta
        out[0] = out[0] + c * jnp.uint32(977)  # < 2^16 + 2^26
        out[2] = out[2] + c
        # second full carry
        c = jnp.zeros_like(c)
        for k in range(16):
            t = out[k] + c
            out[k] = t & MASK
            c = t >> LIMB_BITS
        # possible final wrap: value was < 2^256 + 2^49, so if c == 1 the
        # remaining limbs above index 3 are zero and a 5-step chain closes
        out[0] = out[0] + c * jnp.uint32(977)
        out[2] = out[2] + c
        cc = jnp.zeros_like(c)
        for k in range(5):
            t = out[k] + cc
            out[k] = t & MASK
            cc = t >> LIMB_BITS
        return jnp.stack(out, axis=-1)

    # -- relaxed ops ------------------------------------------------------

    @staticmethod
    def _glue(*arrs) -> bool:
        """Route this call site through its one-launch Pallas glue
        kernel?  True on the fused-kernel variant (TPU backends) for
        batched same-shape 16-limb operands — the round-4 census showed
        the XLA forms of these ops execute as ~3.8k separate dispatches
        per recover on hardware (harness/hlo_census.py)."""
        from eges_tpu.ops.pallas_kernels import ladder_kernels_enabled
        if not ladder_kernels_enabled():
            return False
        first = arrs[0]
        return all(getattr(a, "ndim", 0) >= 2 and a.shape == first.shape
                   and a.shape[-1] == NLIMBS for a in arrs)

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        if (self._use_pallas or self._glue(a, b)) \
                and a.ndim >= 2 and a.shape == b.shape:
            from eges_tpu.ops.pallas_kernels import fp_mul_pallas
            flat = fp_mul_pallas(a.reshape(-1, NLIMBS),
                                 b.reshape(-1, NLIMBS))
            return flat.reshape(a.shape)
        return self._reduce_cols(big_mul_cols(a, b))

    def sqr(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mul(a, a)

    def add(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        if self._glue(a, b):
            from eges_tpu.ops.pallas_kernels import fp_add_pallas
            return fp_add_pallas(a.reshape(-1, NLIMBS),
                                 b.reshape(-1, NLIMBS)).reshape(a.shape)
        return self._reduce_cols(a + b)  # cols < 2^17

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Branchless: a + (0xFFFF - b) + C where C = 2^256 - 2*delta + 1,
        so the column value is a - b + 2P >= 0 — no borrow chain."""
        if self._glue(a, b):
            from eges_tpu.ops.pallas_kernels import fp_sub_pallas
            return fp_sub_pallas(a.reshape(-1, NLIMBS),
                                 b.reshape(-1, NLIMBS)).reshape(a.shape)
        comp = jnp.uint32(MASK) - b
        subc = jnp.broadcast_to(jnp.asarray(self._subc_np), a.shape)
        return self._reduce_cols(a + comp + subc)  # cols < 3*2^16

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        if self._glue(a):
            from eges_tpu.ops.pallas_kernels import fp_neg_pallas
            return fp_neg_pallas(a.reshape(-1, NLIMBS)).reshape(a.shape)
        return self.sub(jnp.zeros_like(a), a)

    def mul_small(self, a: jnp.ndarray, k: int) -> jnp.ndarray:
        assert k < 16
        if self._glue(a):
            from eges_tpu.ops.pallas_kernels import fp_mul_small_pallas
            return fp_mul_small_pallas(
                a.reshape(-1, NLIMBS), k).reshape(a.shape)
        return self._reduce_cols(a * jnp.uint32(k))  # cols < 2^20

    # -- canonicalization ------------------------------------------------

    def canon(self, a: jnp.ndarray) -> jnp.ndarray:
        """Relaxed [0, 2^256) -> canonical [0, P): one conditional
        subtract (2^256 - P < P, so one is always enough)."""
        if self._glue(a):
            from eges_tpu.ops.pallas_kernels import fp_canon_pallas
            return fp_canon_pallas(a.reshape(-1, NLIMBS)).reshape(a.shape)
        return self._cond_sub_m(a)

    def is_zero_mod(self, a: jnp.ndarray) -> jnp.ndarray:
        """a ≡ 0 (mod P) for relaxed a: value is exactly 0 or P."""
        return (is_zero(a) | eq(a, jnp.broadcast_to(self.m_limbs, a.shape)))

    def eq_mod(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return eq(self.canon(a), self.canon(b))

    def sqrt(self, a: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Square root via ``a^((P+1)/4)``.  Returns (root, exists_flag).

        Fused-kernel variant: the rolled 254-bit pow ladder becomes one
        streamed kernel launch (callers canonicalize the root before
        consuming its bits, so the two paths' relaxed encodings may
        differ while the residue — and every downstream bit — agrees)."""
        from eges_tpu.ops.pallas_kernels import (
            ladder_kernels_enabled, pow_mod_pallas,
        )
        if ladder_kernels_enabled() and a.ndim == 2:
            r = pow_mod_pallas(a, (P + 1) // 4, "p")
        else:
            r = self.pow_const(a, (P + 1) // 4)
        ok = self.eq_mod(self.sqr(r), a)
        return r, ok


class OrderN(Mod):
    """The scalar field mod the group order N, with a column-space fast
    multiply: the generic ``big_mul + red`` path walks ~6 carry chains
    per multiply; here each delta-fold carries the high part once and
    accumulates the fold product as uncarried columns, so a full modular
    multiply costs 3 short chains total (delta_N is 129 bits = 9 limbs,
    so three folds shrink 512 -> <257 bits: 32 -> 26 -> 20 -> 16+eps)."""

    def __init__(self):
        super().__init__(N, n_folds=3)

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        # EGES_TPU_PALLAS=ladder on hardware: the mod-N multiply rides
        # its Mosaic kernel alongside the fused ladder step (only ~8
        # calls per recover — the win is uniformity, not throughput)
        from eges_tpu.ops.pallas_kernels import ladder_kernels_enabled
        if ladder_kernels_enabled() and a.ndim >= 2 and a.shape == b.shape:
            from eges_tpu.ops.pallas_kernels import fn_mul_pallas
            return fn_mul_pallas(a.reshape(-1, NLIMBS),
                                 b.reshape(-1, NLIMBS)).reshape(a.shape)
        return self._red_cols(big_mul_cols(a, b))

    def sqr(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mul(a, a)

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        if FieldP._glue(a, b):
            from eges_tpu.ops.pallas_kernels import fn_sub_pallas
            return fn_sub_pallas(a.reshape(-1, NLIMBS),
                                 b.reshape(-1, NLIMBS)).reshape(a.shape)
        return super().sub(a, b)

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        if FieldP._glue(a):
            from eges_tpu.ops.pallas_kernels import fn_neg_pallas
            return fn_neg_pallas(a.reshape(-1, NLIMBS)).reshape(a.shape)
        return super().neg(a)

    def red(self, wide: jnp.ndarray) -> jnp.ndarray:
        # the 17-limb reduction (z mod N, px mod N) as one glue launch
        from eges_tpu.ops.pallas_kernels import ladder_kernels_enabled
        if (ladder_kernels_enabled() and getattr(wide, "ndim", 0) >= 2
                and wide.shape[-1] == NLIMBS + 1):
            from eges_tpu.ops.pallas_kernels import fn_red17_pallas
            return fn_red17_pallas(
                wide.reshape(-1, NLIMBS + 1)).reshape(*wide.shape[:-1],
                                                      NLIMBS)
        # carried limbs are valid (small) columns — same fast reducer
        return self._red_cols(wide)

    def _red_cols(self, cols: jnp.ndarray) -> jnp.ndarray:
        """Uncarried columns (< 2^22 each) -> canonical [0, N)."""
        delta = jnp.asarray(self.delta_limbs_np)  # 9 limbs
        nd = delta.shape[-1]
        pad = [(0, 0)] * (cols.ndim - 1)
        while cols.shape[-1] > 16:
            lo = cols[..., :16]
            # carry the high columns into clean limbs before multiplying
            # by delta (uncarried cols x delta limbs would overflow u32)
            hi = _carry(cols[..., 16:], cols.shape[-1] - 16 + 1)
            prod = big_mul_cols(hi, jnp.broadcast_to(
                delta, (*hi.shape[:-1], nd)))  # uncarried, < 2^21
            w = max(16, prod.shape[-1])
            lo_w = jnp.pad(lo, pad + [(0, w - 16)])
            pr_w = jnp.pad(prod, pad + [(0, w - prod.shape[-1])])
            cols = lo_w + pr_w
        a = _carry(cols, 17)
        # fold the top limb twice: the first fold can still push the
        # value past 2^256 (top < 2^7 here), the second cannot (top <= 1)
        for _ in range(2):
            top = a[..., 16:17]
            fold = jnp.pad(top * delta, pad + [(0, 16 - nd)])
            a = _carry(a[..., :16] + fold, 17)
        a = a[..., :16]
        a = self._cond_sub_m(a)
        return self._cond_sub_m(a)


FP = FieldP()
FN = OrderN()
