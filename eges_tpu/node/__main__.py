"""CLI entry: ``python -m eges_tpu.node`` — the geth-command equivalent.

Flag set mirrors the reference's Geec CLI surface
(ref: cmd/utils/flags.go:540-591, registered cmd/geth/main.go:125-135),
plus the transport flags the permissioned static-peer design needs.
"""

from __future__ import annotations

import argparse
import asyncio

from eges_tpu.consensus.config import NodeConfig
from eges_tpu.node.service import NodeService, ServiceConfig


def parse_peers(spec: str) -> tuple[tuple[str, int], ...]:
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, port = part.rsplit(":", 1)
        out.append((host, int(port)))
    return tuple(out)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="eges-tpu-node",
        description="TPU-native Geec consensus node")
    p.add_argument("--datadir", required=True)
    p.add_argument("--genesis", required=True, help="genesis JSON with config.thw")
    p.add_argument("--keyhex", required=True, help="32-byte private key, hex")
    p.add_argument("--mine", action="store_true")
    p.add_argument("--verbosity", type=int, default=3)
    # Geec flags (ref: cmd/utils/flags.go:540-591)
    p.add_argument("--consensusIP", default="127.0.0.1")
    p.add_argument("--consensusPort", type=int, default=8100)
    p.add_argument("--geecTxnPort", type=int, default=0)
    p.add_argument("--nCandidates", type=int, default=3)
    p.add_argument("--nAcceptors", type=int, default=4)
    p.add_argument("--blockTimeout", type=float, default=20.0)
    p.add_argument("--txnPerBlock", type=int, default=1000)
    p.add_argument("--txnSize", type=int, default=100)
    p.add_argument("--breakdown", action="store_true")
    p.add_argument("--failureTest", action="store_true")
    p.add_argument("--totalNodes", type=int, default=3)
    p.add_argument("--syncmode", default="full", choices=["full", "fast"],
                   help="fast: a late joiner downloads the state at a "
                        "quorum-certified pivot block and replays only "
                        "the tail — O(state) not O(chain) (ref: "
                        "eth/downloader/statesync.go role)")
    # transport
    p.add_argument("--gossipIP", default="127.0.0.1")
    p.add_argument("--gossipPort", type=int, default=6190)
    p.add_argument("--peers", default="", help="ip:port,ip:port gossip peers")
    p.add_argument("--nat", default="none",
                   help="advertised-address policy for discovery: "
                        "none | auto | extip:<ip> (ref p2p/nat)")
    p.add_argument("--bootnodes", default="",
                   help="ip:port,... discovery bootnodes (makes --peers "
                        "optional)")
    p.add_argument("--tpuVerify", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="batch-verify signatures on the JAX device "
                        "(--no-tpuVerify to run host-only)")
    p.add_argument("--verifier", default="", choices=["", "jax", "native",
                                                      "none"],
                   help="verifier backend override: jax device batches "
                        "(default), native C++ batches, or none")
    p.add_argument("--rpcPort", type=int, default=0,
                   help="JSON-RPC HTTP port (0 = disabled)")
    p.add_argument("--collector", default="",
                   help="host:port of a telemetry collector "
                        "(harness/collector.py CollectorServer); the "
                        "node pushes sampled metric deltas + its "
                        "journal tail there every "
                        "--telemetryInterval seconds")
    p.add_argument("--telemetryInterval", type=float, default=5.0,
                   help="seconds between telemetry pushes")
    p.add_argument("--netSecret", default="",
                   help="hex gossip-plane auth secret (default: derived "
                        "from the genesis hash)")
    p.add_argument("--plaintextGossip", action="store_true",
                   help="disable the gossip auth layer")
    p.add_argument("--gossipAllowlist", default="",
                   help="comma-separated hex addresses; when set, only "
                        "listed peers or current members may hold gossip "
                        "connections (membership gate on the v2 "
                        "handshake identity)")
    p.add_argument("--allowV1Peers", action="store_true",
                   help="accept legacy v1 symmetric hellos on a keyed "
                        "node (mixed-mode upgrades; bypasses per-peer "
                        "identity, so off by default)")
    p.add_argument("--allowV2Peers", action="store_true",
                   help="accept MAC-only v2 hellos on a v3 node "
                        "(mixed-mode upgrades; those links lose "
                        "confidentiality, so off by default)")
    p.add_argument("--gossipVersion", type=int, default=3, choices=[2, 3],
                   help="gossip-plane generation: 3 = encrypted frames "
                        "(default), 2 = MAC-only (staged upgrades)")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    node_cfg = NodeConfig(
        consensus_ip=args.consensusIP, consensus_port=args.consensusPort,
        geec_txn_port=args.geecTxnPort, n_candidates=args.nCandidates,
        n_acceptors=args.nAcceptors, block_timeout_s=args.blockTimeout,
        txn_per_block=args.txnPerBlock, txn_size=args.txnSize,
        breakdown=args.breakdown, failure_test=args.failureTest,
        total_nodes=args.totalNodes, fast_sync=args.syncmode == "fast")
    cfg = ServiceConfig(
        datadir=args.datadir, genesis_path=args.genesis, key_hex=args.keyhex,
        gossip_ip=args.gossipIP, gossip_port=args.gossipPort,
        peers=parse_peers(args.peers), node=node_cfg, mine=args.mine,
        verbosity=args.verbosity, use_tpu_verifier=args.tpuVerify,
        rpc_port=args.rpcPort, net_secret_hex=args.netSecret,
        plaintext_gossip=args.plaintextGossip,
        allow_v1_peers=args.allowV1Peers,
        allow_v2_peers=args.allowV2Peers,
        gossip_version=args.gossipVersion,
        gossip_allowlist=tuple(a for a in args.gossipAllowlist.split(",")
                               if a),
        bootnodes=parse_peers(args.bootnodes),
        nat=args.nat,
        verifier_mode=args.verifier,
        collector_addr=args.collector,
        telemetry_interval_s=args.telemetryInterval)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    service = NodeService(cfg)
    try:
        loop.run_until_complete(service.run_forever())
    except KeyboardInterrupt:
        pass
    finally:
        service.close()


if __name__ == "__main__":
    main()
