"""Node service container: the ``geth``-process equivalent.

Assembles a full Geec node from a genesis file + flags (the role of
node.Node + eth.New, ref: node/node.go:138, eth/backend.go:105-185):
durable chain over a datadir FileStore, the consensus state machine,
both network planes, the UDP txn-ingest service, and the TPU batch
verifier — then runs the asyncio loop.
"""

from __future__ import annotations

import asyncio
import json
import os
from dataclasses import dataclass

from eges_tpu.consensus.config import ChainGeecConfig, NodeConfig
from eges_tpu.consensus.node import GeecNode
from eges_tpu.core.chain import BlockChain, FileStore, make_genesis
from eges_tpu.crypto import secp256k1 as secp
from eges_tpu.ingress import direct_sink, gossip_sink, txn_sink
from eges_tpu.net.transports import (
    AsyncioClock, DirectPlane, GeecTxnService, GossipPlane, SocketTransport,
)
from eges_tpu.utils.log import get_logger


@dataclass
class ServiceConfig:
    datadir: str
    genesis_path: str
    key_hex: str                       # 32-byte private key (hex)
    gossip_ip: str = "127.0.0.1"
    gossip_port: int = 6190
    peers: tuple[tuple[str, int], ...] = ()  # static gossip peer list
    node: NodeConfig = None            # Geec knobs (coinbase filled in)
    mine: bool = True
    verbosity: int = 3
    use_tpu_verifier: bool = True      # device batch verify on acceptors
    verifier_mode: str = ""            # "" -> "jax" if use_tpu_verifier
    #                                    else "none"; "native" = C++ batch
    #                                    verifier (no JAX import — for
    #                                    hosts without an accelerator)
    rpc_port: int = 0                  # 0 = RPC disabled
    net_secret_hex: str = ""           # gossip-plane auth secret; ""
    #                                    derives one from the genesis hash
    checkpoint_every: int = 256        # durable state-checkpoint cadence
    #                                    (blocks): every Nth commit writes
    #                                    a snapshot sidecar into the
    #                                    datadir so a restart replays only
    #                                    the tail past it; 0 disables.
    #                                    An explicit NodeConfig value
    #                                    overrides this service default.
    plaintext_gossip: bool = False     # disable the auth layer entirely
    allow_v1_peers: bool = False       # accept legacy v1 (symmetric)
    #                                    hellos on keyed nodes — mixed-
    #                                    mode upgrades only; bypasses
    #                                    per-peer identity, so never on
    #                                    by default
    allow_v2_peers: bool = False       # accept MAC-only (unencrypted)
    #                                    v2 hellos on v3 nodes — mixed-
    #                                    mode upgrades only; loses
    #                                    confidentiality on those links
    gossip_version: int = 3            # pin the plane's generation
    #                                    (2 = MAC-only, for staged
    #                                    upgrades of a running network)
    gossip_allowlist: tuple[str, ...] = ()  # hex addresses; when set,
    #                                    gossip connections are admitted
    #                                    only for peers whose handshake
    #                                    identity is listed here OR is a
    #                                    current member — the membership
    #                                    gate the v2 handshake's
    #                                    peer_addr exists to serve
    bootnodes: tuple[tuple[str, int], ...] = ()  # discovery; makes
    #                                    --peers optional (ref:
    #                                    p2p/discover + cmd/bootnode)
    nat: str = "none"                  # advertised-address policy for
    #                                    discovery announces: none /
    #                                    auto / extip:<ip> (ref:
    #                                    p2p/nat/nat.go Parse)
    collector_addr: str = ""           # host:port of a telemetry
    #                                    collector (harness/collector.py
    #                                    CollectorServer); enables the
    #                                    push plane: journal tail +
    #                                    periodic telemetry_sample
    #                                    envelopes over TCP, replacing
    #                                    per-node /metrics polling for
    #                                    cluster views
    telemetry_interval_s: float = 5.0  # push cadence when enabled


def load_genesis_config(path: str) -> tuple[ChainGeecConfig, dict]:
    """Parse the genesis JSON's ``config.thw`` section
    (ref: params/config.go:124, core/genesis.go SetupGenesisBlock)."""
    with open(path) as f:
        doc = json.load(f)
    thw = doc.get("config", {}).get("thw", {})
    return ChainGeecConfig.from_json(thw), doc


class _TelemetryPusher:
    """Push plane for a real node: samples the process metrics registry
    on the wall clock, tails the consensus journal through its
    ``on_record`` tap, and ships newline-JSON envelopes to a
    ``harness/collector.py`` CollectorServer.  A node-local
    :class:`harness.slo.SLOEngine` rides along (attached as
    ``node.slo_engine``) so the ``thw_health`` RPC surfaces live alert
    states without a collector round-trip.

    Delivery is best-effort telemetry, not a durability channel: when
    the collector is unreachable the envelope for that tick is dropped
    and the connection is retried on the next one.
    """

    def __init__(self, node, addr: tuple[str, int], *,
                 interval_s: float = 5.0, log=None):
        import time as _t
        from collections import deque

        from eges_tpu.utils.metrics import DEFAULT as registry
        from eges_tpu.utils.timeseries import RegistrySampler
        self.node = node
        self.addr = addr
        self.interval_s = interval_s
        self.log = log
        self.sampler = RegistrySampler(registry, clock=_t.time)
        # journal tail: the tap enqueues every event as it is recorded,
        # so a drain (journal.dump) between ticks cannot lose envelopes
        # bounded deque shared tap->tick: append/popleft are GIL-atomic,
        # so the journal-writer and service-loop roles need no lock
        self._pending = deque(maxlen=8192)  # guarded-by: gil-atomic-deque
        self._prev_tap = node.journal.on_record
        node.journal.on_record = self._tap
        self._sock = None
        self.engine = None
        try:
            from harness.slo import SLOEngine  # analysis: allow-layer-violation(optional burn-rate SLO instrumentation hook)
            self.engine = SLOEngine()
            node.slo_engine = self.engine
        except ImportError:
            self.engine = None  # deployed without the harness package

    def _tap(self, ev: dict) -> None:  # thread-entry:journal-writer
        self._pending.append(ev)
        prev = self._prev_tap
        if prev is not None:
            prev(ev)

    def tick(self) -> None:  # thread-entry:service-loop
        """Sample, journal the sample, evaluate the local SLO engine,
        and push the journal tail as one envelope."""
        # refresh HBM watermark gauges first (utils/devstats.py) so the
        # registry sample below carries them; absent on backends
        # without memory_stats()
        from eges_tpu.utils import devstats as devstats_mod
        devstats_mod.sample_memory()
        payload = self.sampler.sample()
        sample = self.node.journal.record(
            "telemetry_sample", step=self.sampler.steps, metrics=payload)
        if sample is None:
            return  # journal disabled (restart replay)
        evs = []
        while self._pending:
            evs.append(self._pending.popleft())
        if self.engine is not None:
            for ev in evs:
                self.engine.ingest(ev)
            self.engine.evaluate(float(sample.get("ts", 0.0)))
        self._send({"node": str(sample.get("node", "?")),
                    "ts": sample.get("ts", 0.0), "events": evs})

    def _send(self, envelope: dict) -> None:
        import socket as _socket
        data = json.dumps(envelope).encode() + b"\n"
        try:
            if self._sock is None:
                self._sock = _socket.create_connection(
                    self.addr, timeout=2.0)
                self._sock.settimeout(2.0)
            self._sock.sendall(data)
        except OSError:
            # collector down/unreachable: drop this tick's envelope and
            # reconnect on the next one
            sock, self._sock = self._sock, None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass  # already torn down
            if self.log is not None:
                self.log.geec("telemetry push failed",
                              addr=f"{self.addr[0]}:{self.addr[1]}")

    def close(self) -> None:
        # one final push so the collector sees the tail, then restore
        # the tap chain and tear the socket down
        self.tick()
        self.node.journal.on_record = self._prev_tap
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass  # already closed


class NodeService:
    def __init__(self, cfg: ServiceConfig):
        self.cfg = cfg
        priv = bytes.fromhex(cfg.key_hex)
        self.coinbase = secp.pubkey_to_address(secp.privkey_to_pubkey(priv))
        self.log = get_logger(f"geec.{self.coinbase.hex()[:8]}",
                              cfg.verbosity)

        chain_cfg, genesis_doc = load_genesis_config(cfg.genesis_path)
        extra = genesis_doc.get("extraData", "") or "geec-genesis"
        if isinstance(extra, str):
            extra = extra.encode()
        genesis = make_genesis(
            extra=extra,
            time=int(genesis_doc.get("timestamp", "0x0"), 16)
            if isinstance(genesis_doc.get("timestamp"), str)
            else int(genesis_doc.get("timestamp", 0)))

        mode = cfg.verifier_mode or ("jax" if cfg.use_tpu_verifier
                                     else "none")
        verifier = None
        if mode == "jax":
            # share compiled verifier graphs across node processes and
            # restarts (the recover graph is the expensive compile);
            # hardened per BENCH_r02: a broken cache logs + counts
            # verifier.compile_cache_errors and the node runs uncached
            from eges_tpu.crypto.aotstore import enable_persistent_cache
            enable_persistent_cache()
            from eges_tpu.crypto.verifier import default_verifier
            verifier = default_verifier()
        elif mode == "native":
            from eges_tpu.crypto.verify_host import NativeBatchVerifier
            verifier = NativeBatchVerifier()
        self._verifier_mode = mode
        # the coalescing scheduler + sender-recovery cache fronts the
        # device for every consumer below (chain body validation, the
        # consensus node's vote paths, the txpool flush): concurrent RPC
        # submissions and consensus checks merge into one device batch
        # per micro-window, and commit-time re-verification of gossiped
        # signatures becomes a cache hit
        self._raw_verifier = verifier
        if verifier is not None:
            from eges_tpu.crypto.scheduler import scheduler_for
            verifier = scheduler_for(verifier)
            # a mesh verifier (default_verifier over >1 visible device)
            # turns the scheduler into the mesh dispatcher: one window
            # lane per device.  Surface the topology in the service log
            # so an operator can see the fan-out without scraping stats.
            lanes = verifier.stats()["lanes"]
            if lanes > 1:
                self.log.geec("verifier mesh dispatch enabled",
                              devices=lanes)

        os.makedirs(cfg.datadir, exist_ok=True)
        store = FileStore(os.path.join(cfg.datadir, "chaindata"))
        self.chain = BlockChain(store=store, genesis=genesis,
                                verifier=verifier)

        import dataclasses
        ncfg = dataclasses.replace(cfg.node or NodeConfig(),
                                   coinbase=self.coinbase,
                                   privkey=priv)
        if ncfg.checkpoint_every == 0 and cfg.checkpoint_every:
            # service-level durability default: periodic checkpoints
            # into the datadir unless the node config pinned a cadence
            ncfg = dataclasses.replace(
                ncfg, checkpoint_every=cfg.checkpoint_every)

        self.clock = AsyncioClock(asyncio.get_event_loop())
        self.node = GeecNode(self.chain, self.clock, None, ncfg, chain_cfg,
                             mine=cfg.mine, verifier=verifier,
                             log=self._node_log)

        self.direct = DirectPlane(ncfg.consensus_ip, ncfg.consensus_port,
                                  direct_sink(self.node))
        # gossip-plane auth secret (the RLPx role): operator-provided, or
        # derived from the genesis hash — isolating networks and blocking
        # casual frame injection even without an explicit secret
        if cfg.plaintext_gossip:
            secret = None
        elif cfg.net_secret_hex:
            secret = bytes.fromhex(cfg.net_secret_hex)
        else:
            from eges_tpu.crypto.keccak import keccak256
            secret = keccak256(b"geec/net-secret" + genesis.hash)
        # ECDH per-connection keys (v3 handshake) whenever auth is on:
        # encrypted frames + session keys no other member can compute,
        # identity = node key.
        # With an allowlist configured, that identity feeds the
        # membership gate: a peer must be explicitly listed or already a
        # registered member (joiners register THROUGH an allowlisted
        # seed, so bootstrap still works).  Without one, the plane is
        # authenticated but open — any keyholder may connect.
        authorize = None
        if cfg.gossip_allowlist:
            # an allowlist only binds when every connection carries a v2
            # identity: plaintext mode never handshakes, and v1 hellos
            # have no identity — both would silently void the gate
            if cfg.plaintext_gossip:
                raise ValueError("--gossipAllowlist requires the auth "
                                 "layer; remove --plaintextGossip")
            if cfg.allow_v1_peers:
                raise ValueError("--gossipAllowlist is unenforceable for "
                                 "identity-less v1 peers; remove "
                                 "--allowV1Peers")
            allowed = set()
            for a in cfg.gossip_allowlist:
                raw = bytes.fromhex(a.removeprefix("0x"))
                if len(raw) != 20:
                    raise ValueError(f"allowlist entry {a!r} is not a "
                                     "20-byte address")
                allowed.add(raw)
            authorize = (lambda addr: addr in allowed
                         or addr in self.node.membership)
        # the gossip plane's protocol table (the eth/62+63 capability
        # split, ref: eth/protocol.go:38-44): consensus control msgs,
        # chain sync, and txn exchange negotiate independently, so a
        # future sync-v2 peer still exchanges geec msgs with a sync-v1
        # one.  All handlers funnel into the node's single-threaded
        # dispatch — the mux contributes negotiation + misbehavior
        # scoring, not concurrency.
        from eges_tpu.consensus import messages as M
        from eges_tpu.net.transports import Protocol
        gossip = gossip_sink(self.node)
        protocols = [
            Protocol("geec", (1,),
                     {M.GOSSIP_VALIDATE_REQ, M.GOSSIP_QUERY,
                      M.GOSSIP_REGISTER_REQ, M.GOSSIP_CONFIRM_BLOCK},
                     gossip),
            Protocol("sync", (1,),
                     {M.GOSSIP_GET_BLOCKS, M.GOSSIP_BLOCKS_REPLY,
                      M.GOSSIP_GET_HEADERS, M.GOSSIP_HEADERS_REPLY},
                     gossip),
            Protocol("txn", (1,), {M.GOSSIP_TXNS}, gossip),
        ]
        self.gossip = GossipPlane(cfg.gossip_ip, cfg.gossip_port,
                                  list(cfg.peers), gossip,
                                  secret=secret,
                                  keypair=(priv, secp.privkey_to_pubkey(priv)),
                                  allow_v1_peers=cfg.allow_v1_peers,
                                  allow_v2_peers=cfg.allow_v2_peers,
                                  version=cfg.gossip_version,
                                  authorize=authorize,
                                  protocols=protocols)
        self.node.transport = SocketTransport(self.gossip, self.direct)

        self.discovery = None
        if cfg.bootnodes:
            from eges_tpu.net import nat as natlib
            from eges_tpu.net.discovery import DiscoveryClient
            # announce the NAT-resolved address, bind the configured one
            adv_gip = natlib.resolve(cfg.nat, cfg.gossip_ip)
            adv_cip = natlib.resolve(cfg.nat, ncfg.consensus_ip)
            disc_eps: dict[bytes, tuple[str, int]] = {}

            def _on_disc_peer(addr, gep, cep):
                # a higher-seq record can re-home a peer: retire the
                # dial loop on the old endpoint before adding the new
                old = disc_eps.get(addr)
                if old is not None and old != gep:
                    self.gossip.remove_peer(old)
                disc_eps[addr] = gep
                self.gossip.add_peer(gep)

            self.discovery = DiscoveryClient(
                list(cfg.bootnodes), priv,
                adv_gip, cfg.gossip_port,
                adv_cip, ncfg.consensus_port,
                on_peer=_on_disc_peer)

        self.txn_service = None
        if ncfg.geec_txn_port:
            self.txn_service = GeecTxnService(
                ncfg.consensus_ip, ncfg.geec_txn_port, txn_sink(self.node))

        from eges_tpu.core.txpool import TxPool
        self.txpool = TxPool(
            self.clock, verifier=verifier,
            journal_path=os.path.join(cfg.datadir, "transactions.rlp"))
        self.txpool.owner = self.coinbase.hex()[:8]
        loaded = self.txpool.load_journal()
        if loaded:
            self.log.geec("txpool journal", reloaded=loaded)
        self.node.txpool = self.txpool

        self.rpc = None
        if cfg.rpc_port:
            from eges_tpu.rpc.server import RpcServer
            self.rpc = RpcServer(self.chain, node=self.node,
                                 txpool=self.txpool,
                                 bind_ip=cfg.gossip_ip, port=cfg.rpc_port)

        self._telemetry = None
        if cfg.collector_addr:
            host, _, port = cfg.collector_addr.rpartition(":")
            self._telemetry = _TelemetryPusher(
                self.node, (host or "127.0.0.1", int(port)),
                interval_s=cfg.telemetry_interval_s, log=self.log)

        self._height_task = None

    def _node_log(self, kind: str, **kw) -> None:
        if kind == "breakdown":
            self.log.breakdown(kw.pop("phase", "?"), kw.pop("dt", 0.0), **kw)
        else:
            self.log.geec(kind, **kw)

    async def start(self) -> None:
        from eges_tpu.utils.debug import install_sigusr1
        install_sigusr1()  # kill -USR1 dumps stacks (pprof-dump parity)
        # continuous sampling profiler (geth --pprof parity): always on
        # unless EGES_PROFILE_HZ=0; serves thw_profile/thw_health and
        # the periodic profile.folded dump below
        from eges_tpu.utils import profiler as profiler_mod
        if profiler_mod.DEFAULT.start():
            self.log.geec("profiler started", hz=profiler_mod.DEFAULT.hz)
        # device-efficiency plane (utils/devstats.py): baseline the
        # process-wide goodput ledger at service start and point the
        # on-demand trace armer at the datadir, so thw_device_trace
        # captures land as device_trace.NNN next to profile.folded
        from eges_tpu.utils import devstats as devstats_mod
        devstats_mod.DEFAULT.rebase()
        devstats_mod.DEFAULT.trace.dir = self.cfg.datadir
        if self._verifier_mode == "jax" and self._raw_verifier is not None:
            # warm the smallest recover graph NOW: the first jit compile
            # can take minutes on a small host, and letting it happen
            # lazily inside a consensus message handler wedges the event
            # loop mid-election (diagnosed via the SIGUSR1 dump).  The
            # warm goes through the AOT artifact store: a node restarted
            # on a machine that compiled before deserializes the stored
            # executable in milliseconds instead of recompiling (and a
            # first-ever compile leaves an artifact behind for the next
            # process).  The next few buckets warm on a background
            # thread — off the critical path, so the first non-trivial
            # block doesn't stall either.
            import time as _t

            from eges_tpu.crypto.aotstore import default_store
            from eges_tpu.utils.metrics import DEFAULT as metrics

            store = default_store()
            t0 = _t.monotonic()
            info = self._raw_verifier.aot_prewarm(buckets=(16,),
                                                  store=store)
            cold = round(_t.monotonic() - t0, 3)
            metrics.gauge("verifier.cold_start_seconds").set(cold)
            self.log.geec("verifier warmup", dt=cold,
                          aot_loads=info["aot_loads"],
                          aot_compiles=info["aot_compiles"])
            self.node.journal.record(
                "verifier_aot_load", buckets=info["buckets"],
                aot_loads=info["aot_loads"],
                aot_compiles=info["aot_compiles"],
                load_s=round(info["load_s"], 3),
                compile_s=round(info["compile_s"], 3),
                cold_start_s=cold, device_kind=info["device_kind"])
            self._raw_verifier.aot_prewarm(buckets=(32, 64, 128),
                                           store=store, background=True)
        await self.direct.start()
        await self.gossip.start()
        if self.discovery is not None:
            await self.discovery.start()
        if self.txn_service is not None:
            await self.txn_service.start()
        if self.rpc is not None:
            # HTTP + the geth.ipc-convention unix socket in the datadir
            await self.rpc.start(
                ipc_path=os.path.join(self.cfg.datadir, "geec.ipc"))
        # give gossip dials a moment, like the reference's block-1 grace
        # sleep (consensus/geec/geec.go:296)
        await asyncio.sleep(1.0)
        self.node.start()
        self.log.geec("node started", coinbase=self.coinbase.hex(),
                      height=self.chain.height(), mine=self.cfg.mine)
        self._height_task = asyncio.ensure_future(self._height_loop())

    async def _height_loop(self) -> None:
        last = -1
        last_metrics = 0.0
        last_push = 0.0
        while True:
            h = self.chain.height()
            if h != last:
                blk = self.chain.head()
                self.log.geec("head", height=h,
                              hash=blk.hash.hex()[:12],
                              geec_txns=len(blk.geec_txns),
                              fake_txns=len(blk.fake_txns))
                last = h
            import time as _time
            if self._telemetry is not None and \
                    _time.monotonic() - last_push > \
                    self._telemetry.interval_s:
                last_push = _time.monotonic()
                self._telemetry.tick()
            if _time.monotonic() - last_metrics > 30.0:
                last_metrics = _time.monotonic()
                from eges_tpu.utils.metrics import DEFAULT as metrics
                snap = metrics.snapshot()
                if snap:
                    self.log.geec("metrics", **{
                        k.replace(".", "_"): v for k, v in snap.items()
                        if not isinstance(v, dict)})
                # drain finished spans to the datadir so multi-node runs
                # leave per-node JSONL dumps breakdown_report.py can merge
                from eges_tpu.utils import tracing
                try:
                    tracing.DEFAULT.dump(
                        os.path.join(self.cfg.datadir, "spans.jsonl"))
                except OSError:
                    pass
                # same drain pattern for the consensus event journal:
                # per-node journal.jsonl feeds observatory.py --replay
                try:
                    self.node.journal.dump(
                        os.path.join(self.cfg.datadir, "journal.jsonl"))
                except OSError:
                    pass
                self._dump_profile()
            await asyncio.sleep(0.5)

    def _dump_profile(self) -> None:
        """Journal one aggregate profiler report (rides the telemetry
        push like every other journal event) and rewrite the cumulative
        ``profile.folded`` flamegraph artifact next to journal.jsonl.
        A real node's journal is not a determinism-checked stream, so
        the report lands inline — sims use a dedicated stream instead
        (sim/cluster.py enable_profiling)."""
        from eges_tpu.utils import devstats as devstats_mod
        from eges_tpu.utils import profiler as profiler_mod
        # one device-efficiency delta per dump interval, same inline
        # placement as the profiler report (and independent of whether
        # the sampler is running — the goodput ledger has no thread)
        devstats_mod.sample_memory()
        devstats_mod.DEFAULT.journal_snapshot(self.node.journal)
        prof = profiler_mod.DEFAULT
        if not prof.running:
            return
        prof.journal_snapshot(self.node.journal)
        try:
            from harness.profutil import artifact_header  # analysis: allow-layer-violation(profiler artifact emission; instrumentation hook)
            header = artifact_header(source="node-service")
        except ImportError:  # installed without the harness tree
            header = {"source": "node-service"}
        try:
            prof.dump_folded(
                os.path.join(self.cfg.datadir, "profile.folded"),
                header=header)
        except OSError:
            pass  # an unwritable datadir must not kill the height loop

    async def run_forever(self) -> None:
        await self.start()
        while True:
            await asyncio.sleep(3600)

    def close(self) -> None:
        if self._height_task is not None:
            self._height_task.cancel()
        if self._telemetry is not None:
            self._telemetry.close()
        from eges_tpu.utils import tracing
        try:
            tracing.DEFAULT.dump(
                os.path.join(self.cfg.datadir, "spans.jsonl"))
        except OSError:
            pass
        # final profile report BEFORE the journal drain below (so it
        # lands in journal.jsonl), then join the sampler — a
        # still-walking sampler would race interpreter shutdown
        from eges_tpu.utils import profiler as profiler_mod
        self._dump_profile()
        profiler_mod.DEFAULT.stop()
        try:
            self.node.journal.dump(
                os.path.join(self.cfg.datadir, "journal.jsonl"))
        except OSError:
            pass
        if self.discovery is not None:
            self.discovery.close()
        if self.rpc is not None:
            self.rpc.close()
        self.node.stop()
        if self.chain.verifier is not None and \
                hasattr(self.chain.verifier, "close"):
            # drain the scheduler's pending futures and join its
            # dispatch thread before the transports go away
            self.chain.verifier.close()
        self.txpool.close()
        self.gossip.close()
        self.direct.close()
        if self.txn_service is not None:
            self.txn_service.close()
        self.chain.store.close()
