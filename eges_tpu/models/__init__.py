"""The framework's "model" registry.

This domain's flagship model is the batched signature-verification
pipeline (SURVEY §3.5's hot path as one fused device program); the
registry gives the driver entry point, the benchmark, and tests one
shared definition of "the model" and its example inputs.
"""

from eges_tpu.models.flagship import (  # noqa: F401
    example_batch, flagship_forward,
)
