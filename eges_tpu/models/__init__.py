"""The framework's "model" registry.

This domain's "models" are the verification workload families the
device executes as fused programs (SURVEY §3.5's hot path and its
siblings).  The registry gives the driver entry point, the benchmark
and tests one shared definition of each and its example inputs:

* ``ecrecover`` (flagship) — batched sender/signer recovery,
  ``(sigs [N,65], hashes [N,32]) -> (addrs, pubs, ok)``.
* ``classic_verify`` — batched ECDSA verify against known pubkeys
  (the VerifySignature role, ref: crypto/secp256k1/secp256.go:126).
* ``keccak256`` — batched fixed-length Keccak-256 (the address/bloom
  hashing substrate, ref: crypto/crypto.go:43).
"""

from eges_tpu.models.flagship import (  # noqa: F401
    example_batch, flagship_forward,
)


def model(name: str):
    """Named jittable forward steps (the model-family registry)."""
    if name in ("ecrecover", "flagship"):
        return flagship_forward()
    if name == "classic_verify":
        from eges_tpu.crypto.verifier import verify_batch

        return verify_batch
    if name == "keccak256":
        from eges_tpu.ops.keccak_tpu import keccak256_fixed

        return keccak256_fixed
    raise KeyError(f"unknown model {name!r}")


MODELS = ("ecrecover", "classic_verify", "keccak256")
