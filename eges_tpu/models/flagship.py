"""Flagship model: batched secp256k1 sender recovery.

One shared definition of the jittable forward step and its example
inputs, used by ``__graft_entry__.entry()``, ``bench.py`` and tests —
so "the model" the driver compiles is exactly what the benchmark
measures and the consensus layer runs (ref: the cgo hot path it
replaces, crypto/secp256k1/secp256.go:105 +
core/types/transaction_signing.go:222-241).
"""

from __future__ import annotations

import secrets


def flagship_forward():
    """The jittable forward step: ``(sigs [N,65] u8, hashes [N,32] u8)
    -> (addrs [N,20] u8, pubs [N,64] u8, ok [N] u32)``."""
    from eges_tpu.crypto.verifier import ecrecover_batch

    return ecrecover_batch


def example_batch(n: int, invalid_every: int = 0, n_keys: int = 64):
    """Build an ``n``-row workload of real signatures (plus optional
    invalid rows every ``invalid_every``) with the expected addresses.

    Returns ``(sigs [n,65] u8, hashes [n,32] u8, valid [n] bool,
    expect list[bytes|None])`` — ``expect[i]`` is None for rows whose
    recovered address is defined but differs (corrupted-s rows).
    """
    import numpy as np

    from eges_tpu.crypto import secp256k1 as host

    n_keys = min(n_keys, max(n, 1))
    msgs = [secrets.token_bytes(32) for _ in range(n_keys)]
    privs = [secrets.token_bytes(32) for _ in range(n_keys)]
    sig_cache = [np.frombuffer(host.ecdsa_sign(m, p), np.uint8)
                 for m, p in zip(msgs, privs)]
    addr_cache = [host.pubkey_to_address(host.privkey_to_pubkey(p))
                  for p in privs]

    sigs = np.zeros((n, 65), np.uint8)
    hashes = np.zeros((n, 32), np.uint8)
    valid = np.ones(n, bool)
    expect: list = [b""] * n
    for i in range(n):
        k = i % n_keys
        sigs[i] = sig_cache[k]
        hashes[i] = np.frombuffer(msgs[k], np.uint8)
        expect[i] = addr_cache[k]
        if invalid_every and i % invalid_every == 5:
            valid[i] = False
            if i % 2:
                sigs[i, 40] ^= 0xFF  # corrupt s: recovers a wrong address
                expect[i] = None
            else:
                sigs[i, 64] = 9       # invalid recovery id: masked row
                expect[i] = b"\0" * 20
    return sigs, hashes, valid, expect
