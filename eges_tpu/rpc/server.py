"""JSON-RPC server: the user-facing API layer.

Covers the capability role of the reference's ``rpc/`` + ``internal/
ethapi`` stack (ref: rpc/server.go, internal/ethapi/api.go:489+) for
the Geec path, plus the ``thw`` namespace the engine registers
(ref: consensus/geec/geec.go:450-457).  JSON-RPC 2.0 over HTTP on
asyncio streams — no external web framework, single event loop shared
with the consensus node.

Transports: HTTP (keep-alive, batch requests) and a geth.ipc-style
unix socket (newline-delimited JSON).

Methods:
  eth_blockNumber, eth_getBlockByNumber, eth_getBlockByHash,
  eth_getBalance, eth_getTransactionCount, eth_getTransactionReceipt,
  eth_getCode, eth_getStorageAt, eth_call, eth_estimateGas,
  eth_gasPrice, eth_getLogs, eth_newFilter, eth_newBlockFilter,
  eth_getFilterChanges, eth_uninstallFilter, eth_sendRawTransaction,
  net_version, web3_clientVersion,
  thw_register, thw_membership, thw_status, thw_pendingGeecTxns,
  thw_metrics, thw_traces, thw_health, thw_journal, thw_ledger,
  debug_startProfile, debug_stopProfile, debug_stacks, debug_stats

Plain HTTP ``GET /metrics`` on the same port serves the whole metrics
registry in Prometheus text exposition format (the pull-based analogue
of the reference's influxdb push exporters behind ``--metrics``).
"""

from __future__ import annotations

import asyncio
import json

from eges_tpu.core import rlp
from eges_tpu.core.types import Block, Transaction
from eges_tpu.utils.limits import clamp_rpc_limit

# Closed vocabulary of dispatched JSON-RPC methods.  The static-analysis
# vocabulary rule checks this both ways against the ``method == "..."``
# dispatch comparisons below: an unregistered dispatch literal and a
# registered method with no dispatch site both fail the gate.  The
# ``debug_*`` namespace goes through a prefix dispatcher and is exempt.
RPC_METHODS = frozenset({
    "eth_blockNumber", "eth_call", "eth_chainId", "eth_estimateGas",
    "eth_gasPrice", "eth_getBalance", "eth_getBlockByHash",
    "eth_getBlockByNumber", "eth_getCode", "eth_getFilterChanges",
    "eth_getLogs", "eth_getStorageAt", "eth_getTransactionByHash",
    "eth_getTransactionCount", "eth_getTransactionReceipt",
    "eth_newBlockFilter", "eth_newFilter", "eth_sendRawTransaction",
    "eth_subscribe", "eth_uninstallFilter", "eth_unsubscribe",
    "net_version", "thw_device_trace", "thw_devices", "thw_flight",
    "thw_health", "thw_journal", "thw_ledger", "thw_membership",
    "thw_metrics", "thw_pendingGeecTxns", "thw_profile",
    "thw_register", "thw_status", "thw_traces", "web3_clientVersion",
})


def _hex(n: int) -> str:
    return hex(n)


def _profiler_stats() -> dict:
    """The process-wide sampling profiler's health block (hz, samples,
    dropped, overhead estimate) — all zeros/False when disabled."""
    from eges_tpu.utils import profiler as profiler_mod
    return profiler_mod.DEFAULT.stats()


def _devstats_stats() -> dict:
    """The device-efficiency ledger's health block (window/row volume,
    cumulative goodput, trace armer state) — zeros until a scheduler
    window has been recorded."""
    from eges_tpu.utils import devstats as devstats_mod
    return devstats_mod.DEFAULT.stats()


def _block_json(b: Block, full: bool) -> dict:
    h = b.header
    return {
        "number": _hex(h.number),
        "hash": "0x" + b.hash.hex(),
        "parentHash": "0x" + h.parent_hash.hex(),
        "stateRoot": "0x" + h.root.hex(),
        "transactionsRoot": "0x" + h.tx_hash.hex(),
        "receiptsRoot": "0x" + h.receipt_hash.hex(),
        "miner": "0x" + h.coinbase.hex(),
        "difficulty": _hex(h.difficulty),
        "gasLimit": _hex(h.gas_limit),
        "gasUsed": _hex(h.gas_used),
        "timestamp": _hex(h.time),
        "extraData": "0x" + h.extra.hex(),
        "trustRand": _hex(h.trust_rand),
        "registrations": [
            {"account": "0x" + r.account.hex(), "ip": r.ip, "port": r.port,
             "renew": r.renew} for r in h.regs],
        "geecTxnCount": len(b.geec_txns),
        "fakeTxnCount": len(b.fake_txns),
        "confirm": None if b.confirm is None else {
            "blockNumber": b.confirm.block_number,
            "hash": "0x" + b.confirm.hash.hex(),
            "confidence": b.confirm.confidence,
            "supporters": ["0x" + s.hex() for s in b.confirm.supporters],
            "emptyBlock": b.confirm.empty_block,
        },
        "transactions": (
            [_txn_json(t) for t in b.transactions] if full
            else ["0x" + t.hash.hex() for t in b.transactions]),
    }


def _txn_json(t: Transaction) -> dict:
    return {
        "hash": "0x" + t.hash.hex(),
        "nonce": _hex(t.nonce),
        "gasPrice": _hex(t.gas_price),
        "gas": _hex(t.gas_limit),
        "to": None if t.to is None else "0x" + t.to.hex(),
        "value": _hex(t.value),
        "input": "0x" + t.payload.hex(),
        "isGeec": t.is_geec,
        "v": _hex(t.v), "r": _hex(t.r), "s": _hex(t.s),
    }


class RpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class RpcServer:
    def __init__(self, chain, node=None, txpool=None, *,
                 bind_ip: str = "127.0.0.1", port: int = 8545,
                 chain_id: int = 930412):
        self.chain = chain
        self.node = node
        self.txpool = txpool
        self.bind_ip = bind_ip
        self.port = port
        self.chain_id = chain_id
        self._server = None
        self._filters: dict = {}
        self._filter_seq = 0
        self._ws_conns: list = []  # (writer, subscriptions) per WS conn
        chain.add_listener(self._on_block_for_ws)

    # -- method handlers --------------------------------------------------

    def _resolve_block(self, tag) -> Block | None:
        if tag in ("latest", "pending", None):
            return self.chain.head()
        if tag == "earliest":
            return self.chain.get_block_by_number(0)
        return self.chain.get_block_by_number(int(tag, 16))

    def _state_for(self, tag):
        blk = self._resolve_block(tag)
        if blk is None:
            raise RpcError(-32602, "unknown block")
        st = self.chain.state_at(blk.hash)
        if st is None:
            raise RpcError(-32000, "state pruned for that block")
        return st

    def _receipt_json(self, txn_hash: bytes):
        """O(1) via the chain's txn-hash index (the LevelDB lookup-entry
        role, ref: core/database_util.go GetTxLookupEntry)."""
        hit = self.chain.lookup_txn(txn_hash)
        if hit is None:
            return None
        blk, i, r = hit
        if r is None:
            return None
        receipts = self.chain.receipts_of(blk.hash)
        return {
            "transactionHash": "0x" + txn_hash.hex(),
            "blockNumber": _hex(blk.number),
            "blockHash": "0x" + blk.hash.hex(),
            "transactionIndex": _hex(i),
            "status": _hex(r.status),
            "cumulativeGasUsed": _hex(r.cumulative_gas_used),
            "gasUsed": _hex(
                r.cumulative_gas_used
                - (receipts[i - 1].cumulative_gas_used if i else 0)),
            "logs": [{"address": "0x" + a.hex(),
                      "topics": ["0x" + t.hex() for t in ts],
                      "data": "0x" + d.hex()}
                     for (a, ts, d) in getattr(r, "logs", ())],
        }

    def dispatch(self, method: str, params: list):  # ingress-entry:bounded
        if method == "eth_blockNumber":
            return _hex(self.chain.height())
        if method == "eth_getBlockByNumber":
            blk = self._resolve_block(params[0])
            full = bool(params[1]) if len(params) > 1 else False
            return None if blk is None else _block_json(blk, full)
        if method == "eth_getBlockByHash":
            blk = self.chain.get_block(bytes.fromhex(params[0][2:]))
            full = bool(params[1]) if len(params) > 1 else False
            return None if blk is None else _block_json(blk, full)
        if method == "eth_sendRawTransaction":
            if self.txpool is None:
                raise RpcError(-32000, "no transaction pool")
            raw = bytes.fromhex(params[0][2:])
            try:
                txn = Transaction.decode(raw)
            except rlp.RLPError as e:
                raise RpcError(-32602, f"invalid transaction RLP: {e}")
            if self.node is not None and self.node.txpool is self.txpool:
                # pool admission + gossip broadcast to peers
                # (ref: eth/handler.go:742-759 TxMsg fan-out)
                self.node.submit_txns([txn])
            else:
                self.txpool.add_remotes([txn])
                if self.node is not None:  # still broadcast to peers
                    self.node.broadcast_txns([txn])
            return "0x" + txn.hash.hex()
        if method == "eth_getBalance":
            st = self._state_for(params[1] if len(params) > 1 else "latest")
            return _hex(st.balance(bytes.fromhex(params[0][2:])))
        if method == "eth_getTransactionByHash":
            hit = self.chain.lookup_txn(bytes.fromhex(params[0][2:]))
            if hit is None:
                return None
            blk, i, _ = hit
            out = _txn_json(blk.transactions[i])
            out["blockNumber"] = _hex(blk.number)
            out["blockHash"] = "0x" + blk.hash.hex()
            out["transactionIndex"] = _hex(i)
            return out
        if method == "eth_chainId":
            return _hex(self.chain_id)
        if method == "eth_getTransactionCount":
            st = self._state_for(params[1] if len(params) > 1 else "latest")
            return _hex(st.nonce(bytes.fromhex(params[0][2:])))
        if method == "eth_getCode":
            st = self._state_for(params[1] if len(params) > 1 else "latest")
            return "0x" + st.code(bytes.fromhex(params[0][2:])).hex()
        if method == "eth_getStorageAt":
            st = self._state_for(params[2] if len(params) > 2 else "latest")
            v = st.storage_at(bytes.fromhex(params[0][2:]),
                              int(params[1], 16))
            return "0x" + v.to_bytes(32, "big").hex()
        if method == "eth_call":
            return self._eth_call(params[0],
                                  params[1] if len(params) > 1 else "latest")
        if method == "eth_estimateGas":
            return _hex(self._estimate_gas(
                params[0], params[1] if len(params) > 1 else "latest"))
        if method == "eth_gasPrice":
            return _hex(self._gas_price())
        if method == "eth_getLogs":
            return self._get_logs(params[0] if params else {})
        if method in ("eth_newFilter", "eth_newBlockFilter"):
            return self._new_filter(method,
                                    params[0] if params else {})
        if method == "eth_getFilterChanges":
            return self._filter_changes(params[0])
        if method == "eth_uninstallFilter":
            return self._filters.pop(params[0], None) is not None
        if method == "eth_getTransactionReceipt":
            return self._receipt_json(bytes.fromhex(params[0][2:]))
        if method == "net_version":
            return str(self.chain_id)
        if method == "web3_clientVersion":
            return "eges-tpu/0.1.0"
        if method == "thw_register":
            # (ref: consensus/geec/api.go Register)
            if self.node is None:
                raise RpcError(-32000, "no consensus node")
            self.node.request_registration()
            return True
        if method == "thw_membership":
            if self.node is None:
                raise RpcError(-32000, "no consensus node")
            return [{"account": "0x" + m.addr.hex(), "ip": m.ip,
                     "port": m.port, "ttl": m.ttl,
                     "joinedBlock": m.joined_block}
                    for m in self.node.membership.members()]
        if method == "thw_status":
            if self.node is None:
                raise RpcError(-32000, "no consensus node")
            return {
                "height": self.chain.height(),
                "workingBlock": self.node.wb.blk_num,
                "maxConfirmed": self.node.max_confirmed_block,
                "registered": self.node.registered,
                "members": len(self.node.membership),
                "pendingGeecTxns": len(self.node.pending_geec_txns),
                "badBlocks": self.chain.bad_blocks,
            }
        if method == "thw_pendingGeecTxns":
            if self.node is None:
                raise RpcError(-32000, "no consensus node")
            return len(self.node.pending_geec_txns)
        if method == "thw_metrics":
            # process-wide observability snapshot (ref: the reference's
            # metrics registry + --metrics flag, metrics/metrics.go:25)
            from eges_tpu.utils.metrics import DEFAULT as metrics
            out = metrics.snapshot()
            # on-device verify share (BASELINE.md north star: > 95% of
            # secp256k1 verifies on TPU).  Three row classes: device
            # (JAX batch verifier), native (C++ host batch — still host
            # work, round-3 verdict weak #3), and per-call host
            # fallbacks.  device_share counts DEVICE rows only;
            # batched_share is the routing share either batch path hits.
            def _rows(key):
                v = out.get(key, {})
                return v.get("count", 0) if isinstance(v, dict) else v

            dev = _rows("verifier.rows")
            native = _rows("verifier.native_rows")
            host = out.get("verifier.host_rows", 0)
            total = dev + native + host
            out["verifier.device_share"] = (
                round(dev / total, 4) if total else None)
            out["verifier.batched_share"] = (
                round((dev + native) / total, 4) if total else None)
            if self.txpool is not None:
                out["txpool"] = dict(self.txpool.stats,
                                     pending=len(self.txpool))
            from eges_tpu.utils import tracing
            out["tracing"] = tracing.DEFAULT.stats()
            return out
        if method == "thw_traces":
            # finished spans from the in-process ring buffer, NEWEST
            # FIRST; params: [] | [limit] | [{"limit": n,
            # "trace": "<32-hex id>"}].  ``limit`` is clamped to
            # [1, 4096] so a long-running node can never ship its whole
            # span ring in one JSON-RPC reply.
            from eges_tpu.utils import tracing
            limit, trace = 256, None
            if params:
                p = params[0]
                if isinstance(p, dict):
                    limit = int(p.get("limit", limit))
                    trace = p.get("trace")
                else:
                    limit = int(p)
            limit = clamp_rpc_limit(limit)
            spans = tracing.DEFAULT.finished(limit=limit, trace=trace)
            spans.reverse()
            return spans
        if method == "thw_health":
            return self._health()
        if method == "thw_journal":
            # consensus event journal, chronological, with the same
            # bounded pagination thw_traces has; params: [] | [limit] |
            # [{"limit": n, "since_seq": seq}].  ``limit`` is clamped to
            # [1, 4096] (matching thw_traces) so a long-running node can
            # never ship its whole ring in one reply; ``since_seq`` is
            # the cursor for incremental polling (events with
            # seq >= since_seq).  ``since`` stays as a legacy alias.
            if self.node is None:
                raise RpcError(-32000, "no consensus node")
            limit, since = 1024, 0
            if params:
                p = params[0]
                if isinstance(p, dict):
                    limit = int(p.get("limit", limit))
                    since = int(p.get("since_seq", p.get("since", since)))
                else:
                    limit = int(p)
            limit = clamp_rpc_limit(limit)
            return self.node.journal.events(limit=limit, since=since)
        if method == "thw_ledger":
            # ingress provenance snapshots (eges_tpu/utils/ledger.py),
            # NEWEST FIRST like thw_traces; params: [] | [limit] |
            # [{"limit": n, "since_seq": seq}].  ``limit`` is clamped
            # to [1, 4096]; ``since_seq`` is the incremental-polling
            # cursor thw_journal uses (events with seq >= since_seq).
            if self.node is None:
                raise RpcError(-32000, "no consensus node")
            limit, since = 256, 0
            if params:
                p = params[0]
                if isinstance(p, dict):
                    limit = int(p.get("limit", limit))
                    since = int(p.get("since_seq", since))
                else:
                    limit = int(p)
            limit = clamp_rpc_limit(limit)
            evs = [e for e in self.node.journal.events(since=since)
                   if e.get("type") == "ingress_ledger"]
            evs = evs[-limit:]
            evs.reverse()
            return evs
        if method == "thw_flight":
            # verifier window flight recorder (crypto/scheduler.py),
            # NEWEST FIRST like thw_traces; params: [] | [limit] |
            # [{"limit": n}].  Empty when the chain has no scheduler
            # (host-fallback verifier) or no window flew yet.
            limit = 256
            if params:
                p = params[0]
                if isinstance(p, dict):
                    limit = int(p.get("limit", limit))
                else:
                    limit = int(p)
            limit = clamp_rpc_limit(limit)
            recorder = getattr(self.chain, "verifier", None)
            flights = getattr(recorder, "flights", None)
            if not callable(flights):
                return []
            out = flights(limit=limit)
            out.reverse()
            return out
        if method == "thw_profile":
            # continuous-profiler report snapshots (utils/profiler.py):
            # per-phase/per-role sample deltas + top self-time rows,
            # NEWEST FIRST like thw_flight; params: [] | [limit] |
            # [{"limit": n}].  Empty when the plane is disabled
            # (EGES_PROFILE_HZ=0) or no snapshot interval elapsed yet.
            from eges_tpu.utils import profiler as profiler_mod
            limit = 64
            if params:
                p = params[0]
                if isinstance(p, dict):
                    limit = int(p.get("limit", limit))
                else:
                    limit = int(p)
            limit = clamp_rpc_limit(limit)
            out = profiler_mod.DEFAULT.snapshots(limit=limit)
            out.reverse()
            return out
        if method == "thw_devices":
            # device-efficiency delta snapshots (utils/devstats.py):
            # per-device window/row/waste counts with per-bucket split,
            # NEWEST FIRST like thw_profile; params: [] | [limit] |
            # [{"limit": n}].  Empty until a scheduler window has been
            # recorded and a snapshot taken.
            from eges_tpu.utils import devstats as devstats_mod
            limit = 64
            if params:
                p = params[0]
                if isinstance(p, dict):
                    limit = int(p.get("limit", limit))
                else:
                    limit = int(p)
            limit = clamp_rpc_limit(limit)
            out = devstats_mod.DEFAULT.snapshots(limit=limit)
            out.reverse()
            return out
        if method == "thw_device_trace":
            # arm an on-demand jax.profiler device trace spanning the
            # next N recorded windows (utils/devstats.py); the capture
            # lands as a versioned device_trace.NNN artifact next to
            # profile.folded.  params: [] | [windows] |
            # [{"windows": n, "dir": path, "disarm": true}]; the window
            # count clamps to [1, 4096] like every list limit.  Safe
            # without jax — the armer reports an error state instead of
            # tracing.
            from eges_tpu.utils import devstats as devstats_mod
            armer = devstats_mod.DEFAULT.trace
            windows, outdir = 4, None
            if params:
                p = params[0]
                if isinstance(p, dict):
                    if p.get("disarm"):
                        return armer.disarm()
                    windows = int(p.get("windows", windows))
                    outdir = p.get("dir")
                else:
                    windows = int(p)
            windows = clamp_rpc_limit(windows)
            return armer.arm(windows, outdir=outdir)
        if method.startswith("debug_"):
            return self._debug(method, params)
        raise RpcError(-32601, f"method {method} not found")

    # -- node health (thw_health) -----------------------------------------

    def _health(self) -> dict:
        """One-call cluster-operator snapshot: chain head + confirm lag,
        the node's current consensus role, election win/loss tallies,
        queue depths, membership economy, and a stall flag (no commit
        for 3 block timeouts).  ``harness/observatory.py`` polls this on
        every node; keys here are the documented contract its tests
        assert."""
        node = self.node
        if node is None:
            raise RpcError(-32000, "no consensus node")
        height = self.chain.height()
        blk_num = node.wb.blk_num
        # role: what this node is for the CURRENT working block
        from eges_tpu.consensus.node import BACKOFF, ELECTING, VALIDATING
        if node._phase == ELECTING:
            role = "electing"
        elif node._phase in (VALIDATING, BACKOFF):
            role = "sealing"
        elif not node.registered or node.coinbase not in node.membership:
            role = "observer"
        elif node.is_committee(blk_num, node.wb.max_version):
            role = "committee"
        elif node.is_acceptor(blk_num):
            role = "acceptor"
        else:
            role = "follower"
        members = node.membership.members()
        last_commit_age = node.clock.now() - node._last_commit_t
        return {
            "height": height,
            "headHash": "0x" + self.chain.head().hash.hex(),
            "lag": max(0, node.max_confirmed_block - height),
            "role": role,
            "electionsWon": node.elections_won,
            "electionsLost": node.elections_lost,
            "txpoolPending": len(self.txpool) if self.txpool is not None
            else 0,
            "deferredDepth": len(node._deferred),
            "members": len(members),
            "minTtl": min((m.ttl for m in members), default=0),
            "lastCommitAge": round(last_commit_age, 6),
            "stalled": last_commit_age > 3 * node.cfg.block_timeout_s,
            "journal": node.journal.stats(),
            # latest SLO alert state per objective from the node-local
            # burn-rate engine (harness/slo.py), attached by the service
            # when telemetry push is enabled; {} when not running
            "sloAlerts": (engine.alert_states()
                          if (engine := getattr(node, "slo_engine",
                                                None)) is not None
                          else {}),
            # continuous sampling profiler: rate, sample volume, loss,
            # and the self-cost estimate the <5% overhead guard pins
            "profiler": _profiler_stats(),
            # device-efficiency ledger: window/row volume, cumulative
            # goodput ratio, and the on-demand trace armer state
            "devstats": _devstats_stats(),
        }

    # -- read-only EVM execution (ref: internal/ethapi/api.go Call) -------

    def _call_raw(self, obj: dict, tag) -> tuple[bytes, int]:
        from eges_tpu.core.evm import EVM
        from eges_tpu.core.state import block_ctx

        st = self._state_for(tag)
        blk = self._resolve_block(tag)
        sender = (bytes.fromhex(obj["from"][2:]) if obj.get("from")
                  else bytes(20))
        to = bytes.fromhex(obj["to"][2:]) if obj.get("to") else None
        data = bytes.fromhex(obj.get("data", "0x")[2:])
        value = int(obj.get("value", "0x0"), 16)
        gas = int(obj.get("gas", "0x1c9c380"), 16)  # default 30M
        e = EVM(st.copy(), block_ctx(blk.header),
                verifier=self.chain.verifier)
        if to is None:
            res = e.create(sender, value, data, gas, st.nonce(sender))
        else:
            res = e.call(sender, to, value, data, gas)
        if not res.success and res.output:
            raise RpcError(-32000, "execution reverted: 0x"
                           + res.output.hex())
        if not res.success:
            raise RpcError(-32000, "execution failed (out of gas?)")
        from eges_tpu.core.evm import intrinsic_gas
        return res.output, intrinsic_gas(data, to is None) + res.gas_used

    def _eth_call(self, obj: dict, tag) -> str:
        out, _ = self._call_raw(obj, tag)
        return "0x" + out.hex()

    def _estimate_gas(self, obj: dict, tag) -> int:
        """Binary-search the smallest sufficient gas limit (the 63/64
        call-gas rule means measured usage at a high limit can be too
        little to actually run — ref: internal/ethapi/api.go
        DoEstimateGas's binary search)."""
        from eges_tpu.core.evm import intrinsic_gas

        _, used = self._call_raw(obj, tag)  # raises if it cannot run at cap
        lo, hi = used, max(used, int(obj.get("gas", "0x1c9c380"), 16))
        intr = intrinsic_gas(bytes.fromhex(obj.get("data", "0x")[2:]),
                             not obj.get("to"))

        def runs(limit: int) -> bool:
            # a txn with gas_limit=limit gives the EVM (limit - intrinsic)
            trial = dict(obj, gas=hex(max(limit - intr, 0)))
            try:
                self._call_raw(trial, tag)
                return True
            except RpcError:
                return False

        if runs(lo):
            return lo
        if not runs(hi):
            # even the cap cannot execute it as a txn (intrinsic tax +
            # 63/64 rule); geth errors the same way
            raise RpcError(-32000, "gas required exceeds allowance")
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            if runs(mid):
                hi = mid
            else:
                lo = mid
        return hi

    # -- gas oracle (ref: eth/gasprice/gasprice.go SuggestPrice) ----------

    def _gas_price(self) -> int:
        prices = []
        h = self.chain.height()
        for n in range(h, max(0, h - 20), -1):
            blk = self.chain.get_block_by_number(n)
            if blk is None:
                continue
            prices.extend(t.gas_price for t in blk.transactions
                          if not t.is_geec)
        if not prices:
            return 1
        prices.sort()
        return max(1, prices[len(prices) // 2])

    # -- log filters (ref: eth/filters/filter.go + filter_system.go) ------

    def _match_log(self, log, addresses, topics) -> bool:
        """``topics`` entries are pre-parsed byte-sets (or None)."""
        addr, ltopics, _ = log
        if addresses and addr not in addresses:
            return False
        for i, want in enumerate(topics):
            if want is None:
                continue
            if i >= len(ltopics) or ltopics[i] not in want:
                return False
        return True

    def _bloom_skip(self, header, addresses, topics) -> bool:
        """True when the header bloom PROVES no log can match (the
        bloombits-index role, ref: core/bloombits/ + eth/filters
        bloomFilter); false positives fall through to the receipt scan."""
        from eges_tpu.core.state import bloom_may_contain

        if header.bloom == bytes(256):
            return bool(addresses or any(t is not None for t in topics))
        if addresses and not any(bloom_may_contain(header.bloom, a)
                                 for a in addresses):
            return True
        for want in topics:
            if want is not None and not any(
                    bloom_may_contain(header.bloom, t) for t in want):
                return True
        return False

    def _logs_in_range(self, from_n: int, to_n: int, addresses,
                       topics) -> list:
        """Logs matching a filter over ``[from_n, to_n]``.

        Candidate blocks come from the chain's sectioned bloom index
        (3 index rows per filter value, ref core/bloombits role) — not
        a header walk; unindexed gaps (old stores) fall back to the
        linear header-bloom scan.  Index false positives are filtered
        by the per-header bloom, then the receipts themselves."""
        from_n = max(0, from_n)
        if to_n < from_n:
            return []
        idx = getattr(self.chain, "bloom_index", None)
        if idx is None:
            numbers, gaps = [], [(from_n, to_n)]
        else:
            numbers, gaps = idx.candidates(from_n, to_n, addresses, topics)
        for lo, hi in gaps:
            numbers.extend(range(lo, hi + 1))  # bounded-by: hi <= to_n <= chain.height() (clamped in _parse_filter)
        out = []
        for n in sorted(numbers):
            blk = self.chain.get_block_by_number(n)
            if blk is None:
                continue
            if self._bloom_skip(blk.header, addresses, topics):
                continue
            receipts = self.chain.receipts_of(blk.hash)
            log_index = 0
            for ti, r in enumerate(receipts):
                for log in getattr(r, "logs", ()):
                    if self._match_log(log, addresses, topics):
                        addr, ltopics, data = log
                        out.append({
                            "address": "0x" + addr.hex(),
                            "topics": ["0x" + t.hex() for t in ltopics],
                            "data": "0x" + data.hex(),
                            "blockNumber": _hex(n),
                            "blockHash": "0x" + blk.hash.hex(),
                            "transactionHash":
                                "0x" + blk.transactions[ti].hash.hex(),
                            "transactionIndex": _hex(ti),
                            "logIndex": _hex(log_index),
                        })
                    log_index += 1
        return out

    def _parse_filter(self, obj: dict):
        def block_num(tag, default):
            if tag in (None, "latest", "pending"):
                return default
            if tag == "earliest":
                return 0
            return int(tag, 16)

        h = self.chain.height()
        from_n = block_num(obj.get("fromBlock"), h)
        # clamp to the canonical height: a far-future toBlock must not
        # size the block scan in _logs_in_range (eth_getLogs DoS vector)
        to_n = min(block_num(obj.get("toBlock"), h), h)
        addrs = obj.get("address")
        if isinstance(addrs, str):
            addrs = [addrs]
        addresses = {bytes.fromhex(a[2:]) for a in (addrs or [])}
        # pre-parse topic filters once (hex -> byte-sets); each position
        # is None (wildcard) or a set of acceptable topics
        topics = []
        for want in obj.get("topics", []):
            if want is None:
                topics.append(None)
            else:
                alts = want if isinstance(want, list) else [want]
                topics.append({bytes.fromhex(a[2:]) for a in alts})
        return from_n, to_n, addresses, topics

    def _get_logs(self, obj: dict) -> list:
        from_n, to_n, addresses, topics = self._parse_filter(obj)
        return self._logs_in_range(from_n, to_n, addresses, topics)

    FILTER_TTL_S = 300.0   # unpolled filters expire (geth's 5-min timeout)
    FILTER_MAX = 256       # hard cap on installed filters per node
    HTTP_MAX_BODY = 16 * 1024 * 1024  # request-body cap (matches the WS cap)

    def _expire_filters(self) -> None:
        import time

        now = time.monotonic()
        for fid in [k for k, f in self._filters.items()
                    if now - f["touched"] > self.FILTER_TTL_S]:
            del self._filters[fid]
        while len(self._filters) > self.FILTER_MAX:
            oldest = min(self._filters, key=lambda k:
                         self._filters[k]["touched"])
            del self._filters[oldest]

    def _new_filter(self, method: str, obj: dict) -> str:
        import time

        self._expire_filters()
        self._filter_seq += 1
        fid = _hex(self._filter_seq)
        self._filters[fid] = {  # bounded-by: FILTER_MAX (_expire_filters above)
            "kind": "logs" if method == "eth_newFilter" else "blocks",
            "obj": obj,
            "last": self.chain.height(),
            "touched": time.monotonic(),
        }
        return fid

    def _filter_changes(self, fid: str):
        import time

        self._expire_filters()
        f = self._filters.get(fid)
        if f is None:
            raise RpcError(-32000, "filter not found")
        f["touched"] = time.monotonic()
        h = self.chain.height()
        start, f["last"] = f["last"] + 1, h
        if start > h:
            return []
        if f["kind"] == "blocks":
            out = []
            for n in range(start, h + 1):
                blk = self.chain.get_block_by_number(n)
                if blk is not None:
                    out.append("0x" + blk.hash.hex())
            return out
        from_n, to_n, addresses, topics = self._parse_filter(f["obj"])
        # honor the filter's own explicit block bounds (absent/"latest"
        # bounds mean "everything new since install"); a toBlock in the
        # past means no new logs can ever match
        explicit = lambda tag: tag not in (None, "latest", "pending")
        lo = max(start, from_n) if explicit(f["obj"].get("fromBlock")) \
            else start
        hi = min(h, to_n) if explicit(f["obj"].get("toBlock")) else h
        if lo > hi:
            return []
        return self._logs_in_range(lo, hi, addresses, topics)

    def _debug(self, method: str, params: list):
        """Runtime debug namespace (ref: internal/debug/api.go —
        StartCPUProfile/StopCPUProfile/Stacks/MemStats roles)."""
        from eges_tpu.utils.debug import DebugController

        if not hasattr(self, "_debug_ctl"):
            self._debug_ctl = DebugController()
        if method == "debug_startProfile":
            return self._debug_ctl.start_profile()
        if method == "debug_stopProfile":
            return self._debug_ctl.stop_profile(
                int(params[0]) if params else 30)
        if method == "debug_stacks":
            return self._debug_ctl.stacks()
        if method == "debug_stats":
            return self._debug_ctl.stats()
        if method == "debug_traceTransaction":
            return self._trace_transaction(params[0], *params[1:2])
        raise RpcError(-32601, f"method {method} not found")

    def _trace_transaction(self, txh_hex: str, config: dict | None = None):
        """Replay a mined transaction against its parent state with the
        struct-log tracer attached (ref: eth/tracers/tracer.go +
        internal/ethapi TraceTransaction): preceding txns of the block
        re-execute untraced to reconstruct the exact pre-state, then the
        target runs with per-opcode capture."""
        from eges_tpu.core.state import apply_txn, block_ctx, recover_senders
        from eges_tpu.core.tracer import (
            CallTracer, FourByteTracer, PrestateTracer, StructLogTracer,
        )

        found = self.chain.lookup_txn(bytes.fromhex(txh_hex[2:]))
        if found is None:
            raise RpcError(-32000, "transaction not found")
        blk, index, _receipt = found
        parent_state = self.chain.state_at(blk.header.parent_hash)
        if parent_state is None:
            raise RpcError(-32000, "parent state pruned; restart replays "
                                   "it or trace a more recent transaction")
        senders = recover_senders(blk.transactions, self.chain.verifier)
        state = parent_state.copy()
        ctx = block_ctx(blk.header)
        gas = 0
        for i in range(index):  # bounded-by: index < len(blk.transactions) (lookup_txn invariant)
            r = apply_txn(state, blk.transactions[i], senders[i],
                          blk.header.coinbase, gas, ctx=ctx,
                          verifier=self.chain.verifier)
            gas = r.cumulative_gas_used
        # named tracers (the bundled-tracer surface of the reference,
        # eth/tracers/internal/tracers/*.js selected via config.tracer;
        # native Python here — see core/tracer.py design note)
        name = (config or {}).get("tracer", "")
        if name == "callTracer":
            tracer = CallTracer()
        elif name == "prestateTracer":
            # the traced txn runs on a COPY so ``state`` stays the
            # untouched pre-state reference the tracer reads from
            tracer = PrestateTracer(state, coinbase=blk.header.coinbase)
            state = state.copy()
        elif name == "4byteTracer":
            tracer = FourByteTracer()
        elif name:
            raise RpcError(-32602, f"unknown tracer {name!r}; built-ins: "
                                   "callTracer, prestateTracer, "
                                   "4byteTracer (custom tracers are "
                                   "Python FrameTracer subclasses, not "
                                   "JS — core/tracer.py)")
        else:
            tracer = StructLogTracer(
                with_stack=not (config or {}).get("disableStack", False))
        r = apply_txn(state, blk.transactions[index], senders[index],
                      blk.header.coinbase, gas, ctx=ctx,
                      verifier=self.chain.verifier, tracer=tracer)
        return tracer.result(gas_used=r.cumulative_gas_used - gas,
                             failed=r.status == 0, output=b"")

    # -- JSON-RPC plumbing ------------------------------------------------

    def _handle_body(self, body: bytes) -> bytes:  # ingress-entry:bounded
        try:
            req = json.loads(body)
        except json.JSONDecodeError:
            return json.dumps({"jsonrpc": "2.0", "id": None,
                               "error": {"code": -32700,
                                         "message": "parse error"}}).encode()
        batch = isinstance(req, list)
        reqs = req if batch else [req]
        out = []
        for r in reqs:
            rid = r.get("id")
            try:
                result = self.dispatch(r.get("method", ""),
                                       r.get("params", []) or [])
                out.append({"jsonrpc": "2.0", "id": rid, "result": result})
            except RpcError as e:
                out.append({"jsonrpc": "2.0", "id": rid,
                            "error": {"code": e.code, "message": e.message}})
            except Exception as e:  # robustness: malformed params etc.
                out.append({"jsonrpc": "2.0", "id": rid,
                            "error": {"code": -32603, "message": str(e)}})
        return json.dumps(out if batch else out[0]).encode()

    async def _handle_conn(self, reader: asyncio.StreamReader,  # ingress-entry
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                # minimal HTTP/1.1 request parsing
                line = await reader.readline()
                if not line:
                    break
                try:
                    http_method, path, _ = \
                        line.decode("latin-1").split(" ", 2)
                except ValueError:
                    http_method, path = "POST", "/"
                headers = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                if headers.get("upgrade", "").lower() == "websocket":
                    await self._handle_ws(reader, writer, headers)
                    return
                length = int(headers.get("content-length", 0))
                if length > self.HTTP_MAX_BODY:
                    # refuse before buffering anything: the client's
                    # declared content-length must not size the read
                    writer.write(
                        b"HTTP/1.1 413 Payload Too Large\r\n"
                        b"Content-Length: 0\r\nConnection: close\r\n\r\n")
                    await writer.drain()
                    break
                body = await reader.readexactly(length) if length else b""
                if http_method == "GET":
                    # Prometheus scrape endpoint; everything else 404s
                    if path.split("?", 1)[0] == "/metrics":
                        from eges_tpu.utils.metrics import prometheus_text
                        resp = prometheus_text().encode()
                        writer.write(
                            b"HTTP/1.1 200 OK\r\nContent-Type: text/plain; "
                            b"version=0.0.4; charset=utf-8\r\n"
                            + f"Content-Length: {len(resp)}\r\n".encode()
                            + b"Connection: keep-alive\r\n\r\n" + resp)
                    else:
                        writer.write(
                            b"HTTP/1.1 404 Not Found\r\n"
                            b"Content-Length: 0\r\n"
                            b"Connection: keep-alive\r\n\r\n")
                    await writer.drain()
                    continue
                resp = self._handle_body(body)
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    + f"Content-Length: {len(resp)}\r\n".encode()
                    + b"Connection: keep-alive\r\n\r\n" + resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            writer.close()

    # -- WebSocket transport + eth_subscribe push (ref: rpc/websocket.go
    # + eth/filters/filter_system.go subscription events) ----------------

    @staticmethod
    def _ws_frame(payload: bytes, opcode: int = 1) -> bytes:
        n = len(payload)
        head = bytes([0x80 | opcode])
        if n < 126:
            head += bytes([n])
        elif n < 1 << 16:
            head += bytes([126]) + n.to_bytes(2, "big")
        else:
            head += bytes([127]) + n.to_bytes(8, "big")
        return head + payload

    @staticmethod
    async def _ws_read_raw(reader) -> tuple[int, int, bytes] | None:
        try:
            h = await reader.readexactly(2)
        except asyncio.IncompleteReadError:
            return None
        fin = h[0] & 0x80
        opcode = h[0] & 0x0F
        masked = h[1] & 0x80
        n = h[1] & 0x7F
        if n == 126:
            n = int.from_bytes(await reader.readexactly(2), "big")
        elif n == 127:
            n = int.from_bytes(await reader.readexactly(8), "big")
        if n > 16 * 1024 * 1024:
            return None
        mask = await reader.readexactly(4) if masked else b""
        data = await reader.readexactly(n)
        if masked:
            data = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        return fin, opcode, data

    async def _ws_read_frame(self, reader) -> tuple[int, bytes] | None:
        """One complete MESSAGE: reassembles fragmented frames (FIN=0
        text/binary + opcode-0 continuations); control frames interleave
        and are returned as-is."""
        buf = b""
        first_opcode = None
        while True:
            raw = await self._ws_read_raw(reader)
            if raw is None:
                return None
            fin, opcode, data = raw
            if opcode >= 8:  # control frames never fragment
                return opcode, data
            if first_opcode is None:
                first_opcode = opcode or 1
            buf += data
            if len(buf) > 16 * 1024 * 1024:
                return None
            if fin:
                return first_opcode, buf

    async def _handle_ws(self, reader, writer, headers: dict) -> None:  # ingress-entry
        import base64
        import hashlib

        key = headers.get("sec-websocket-key", "")
        accept = base64.b64encode(hashlib.sha1(
            (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
        ).digest()).decode()
        writer.write((
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
        await writer.drain()

        subs: dict[str, dict] = {}  # sub id -> {"kind", "obj"}
        self._ws_conns.append((writer, subs))
        try:
            while True:
                frame = await self._ws_read_frame(reader)
                if frame is None:
                    break
                opcode, data = frame
                if opcode == 8:  # close
                    break
                if opcode == 9:  # ping -> pong
                    writer.write(self._ws_frame(data, opcode=10))
                    await writer.drain()
                    continue
                if opcode not in (1, 2):
                    continue
                try:
                    req = json.loads(data)
                except ValueError:
                    continue
                method = req.get("method", "")
                params = req.get("params", []) or []
                rid = req.get("id")
                try:
                    if method == "eth_subscribe":
                        if not params:
                            raise RpcError(-32602, "missing subscription kind")
                        kind = params[0]
                        if kind not in ("newHeads", "logs"):
                            raise RpcError(-32602, f"unsupported: {kind}")
                        obj = params[1] if len(params) > 1 else {}
                        if kind == "logs":
                            try:  # validate ONCE here, not on every push
                                self._parse_filter(obj)
                            except Exception:
                                raise RpcError(-32602, "invalid log filter")
                        self._filter_seq += 1
                        sid = _hex(self._filter_seq)
                        subs[sid] = {"kind": kind, "obj": obj}
                        result = sid
                    elif method == "eth_unsubscribe":
                        if not params:
                            raise RpcError(-32602, "missing subscription id")
                        result = subs.pop(params[0], None) is not None
                    else:
                        result = self.dispatch(method, params)
                    out = {"jsonrpc": "2.0", "id": rid, "result": result}
                except RpcError as e:
                    out = {"jsonrpc": "2.0", "id": rid,
                           "error": {"code": e.code, "message": e.message}}
                except Exception as e:  # malformed params must not kill
                    out = {"jsonrpc": "2.0", "id": rid,  # the connection
                           "error": {"code": -32603, "message": str(e)}}
                writer.write(self._ws_frame(json.dumps(out).encode()))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._ws_conns = [(w, s) for w, s in self._ws_conns
                              if w is not writer]
            writer.close()

    def _on_block_for_ws(self, block) -> None:
        """Chain listener: push newHeads/logs notifications to every
        subscribed WS connection (fire-and-forget writes on the shared
        event loop)."""
        if not self._ws_conns:
            return
        head_json = None
        for writer, subs in list(self._ws_conns):
            for sid, sub in subs.items():
                try:
                    if sub["kind"] == "newHeads":
                        if head_json is None:
                            head_json = _block_json(block, False)
                        result = head_json
                    else:
                        from_n = to_n = block.number
                        _, _, addrs, topics = self._parse_filter(sub["obj"])
                        logs = self._logs_in_range(from_n, to_n, addrs,
                                                   topics)
                        if not logs:
                            continue
                        result = logs
                    msg = {"jsonrpc": "2.0", "method": "eth_subscription",
                           "params": {"subscription": sid,
                                      "result": result}}
                    transport = writer.transport
                    if (transport is not None and
                            transport.get_write_buffer_size() > 4 << 20):
                        # a subscriber that stopped reading must not grow
                        # our buffers without bound: drop it
                        writer.close()
                        continue
                    writer.write(self._ws_frame(json.dumps(msg).encode()))
                # analysis: allow-swallow(dead subscriber; reaped on next pass)
                except Exception:
                    pass

    IPC_LIMIT = 16 * 1024 * 1024  # max request line (large raw txns)

    async def _handle_ipc(self, reader: asyncio.StreamReader,  # ingress-entry
                          writer: asyncio.StreamWriter) -> None:
        """IPC framing: newline-delimited raw JSON-RPC (no HTTP
        envelope), matching geth's geth.ipc convention."""
        try:
            while True:
                try:
                    line = await reader.readline()
                except ValueError:
                    # over-limit request: answer with a JSON-RPC error
                    # instead of silently dropping the connection
                    writer.write(json.dumps({
                        "jsonrpc": "2.0", "id": None,
                        "error": {"code": -32600,
                                  "message": "request too large"},
                    }).encode() + b"\n")
                    await writer.drain()
                    break
                if not line:
                    break
                writer.write(self._handle_body(line) + b"\n")
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def start(self, ipc_path: str | None = None) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.bind_ip, self.port)
        if ipc_path:
            import os
            import socket as _socket
            if os.path.exists(ipc_path):
                # refuse to sever a LIVE endpoint (a second node on the
                # same datadir); only clear stale leftover sockets
                probe = _socket.socket(_socket.AF_UNIX)
                try:
                    probe.settimeout(0.5)
                    probe.connect(ipc_path)
                    probe.close()
                    raise RpcError(
                        -32000, f"ipc endpoint {ipc_path} is in use "
                                "(another node on this datadir?)")
                except (ConnectionRefusedError, FileNotFoundError, OSError):
                    probe.close()
                    try:
                        os.unlink(ipc_path)
                    except FileNotFoundError:
                        pass
            self._ipc_server = await asyncio.start_unix_server(
                self._handle_ipc, path=ipc_path, limit=self.IPC_LIMIT)
            self._ipc_path = ipc_path

    def close(self) -> None:
        self.chain.remove_listener(self._on_block_for_ws)
        if self._server is not None:
            self._server.close()
        if getattr(self, "_ipc_server", None) is not None:
            self._ipc_server.close()
            import os
            try:
                os.unlink(self._ipc_path)
            except OSError:
                pass
