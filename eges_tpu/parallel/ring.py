"""Ring collectives over the device mesh (ICI-riding, ppermute-based).

The long-context/sequence-parallel story of this domain (SURVEY §5
"long-context"): the scaling axis is the signature batch, and the
multi-chip layouts are

* **row-sharding + psum** — the default (`shard_rows`), one tree
  all-reduce for the ACK tally;
* **ring reduce** (this module) — the tally circulates the ring with
  `lax.ppermute`, the ring-attention communication pattern applied to
  the verify pipeline: each hop overlaps a neighbor exchange with local
  work, which on real hardware keeps traffic on nearest-neighbor ICI
  links instead of a global tree (the mental model of the public
  scaling-book recipe: pick a mesh, lay shardings so collectives ride
  ICI, let XLA schedule);
* **ring gather** — every device ends with the full result row-set
  (all-gather built from N-1 neighbor hops), for the follower path
  where every node wants every verdict.

On this permissioned chain these replace the reference's vote fan-in
over UDP (ref: core/geec_state.go:1184-1227 handleVerifyReplies) when
the tally happens ON-DEVICE across chips.
"""

from __future__ import annotations

import functools

import numpy as np


def ring_perm(n: int) -> list[tuple[int, int]]:
    """The +1 ring permutation for an ``n``-device axis."""
    return [(i, (i + 1) % n) for i in range(n)]


def _shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """shard_map with the replication check off: a ring accumulation is
    replicated by construction (every device sums the same N pieces),
    but the static varying-axes analysis cannot see through the
    ppermute chain."""
    import inspect

    from eges_tpu.parallel import shard_map_fn

    smap = shard_map_fn()
    kw = {}
    params = inspect.signature(smap).parameters
    if "check_vma" in params:
        kw["check_vma"] = False
    elif "check_rep" in params:
        kw["check_rep"] = False
    return smap(fn, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, **kw)


@functools.lru_cache(maxsize=None)
def ring_tally(fn, mesh, axis: str = "dp", *, n_in: int, n_out: int,
               tally_out: int):
    """Like :func:`~eges_tpu.parallel.shard_rows` but the tally is a
    RING all-reduce: N-1 `ppermute` hops, each adding the neighbor's
    partial sum — bitwise-identical result to `psum`, nearest-neighbor
    traffic pattern.

    Memoized on ``(fn, mesh, axis, arity)``: dispatch-path callers get
    the same wrapper (and jit cache) back instead of re-tracing a fresh
    collective graph per window."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as PS

    n_dev = mesh.shape[axis]
    perm = ring_perm(n_dev)

    def shard_fn(*args):
        outs = fn(*args)
        outs = (outs,) if not isinstance(outs, tuple) else outs
        acc = jnp.sum(outs[tally_out])
        piece = acc

        def hop(_, carry):
            acc, piece = carry
            piece = jax.lax.ppermute(piece, axis, perm)
            return acc + piece, piece

        acc, _ = jax.lax.fori_loop(0, n_dev - 1, hop, (acc, piece))
        return (*outs, acc)

    import jax as _jax
    return _jax.jit(_shard_map_unchecked(
        shard_fn, mesh, tuple([PS(axis)] * n_in),
        tuple([PS(axis)] * n_out + [PS()])))


@functools.lru_cache(maxsize=None)
def all_to_all_resplit(fn, mesh, axis: str = "dp", *, n_in: int,
                       feature_axis: int = 1):
    """The Ulysses-style layout swap: inputs arrive ROW-sharded, an
    ``all_to_all`` re-splits them FEATURE-sharded (every device sees all
    rows for its feature slice), ``fn`` runs on the feature shard, and a
    second ``all_to_all`` restores row sharding.

    In this domain the "features" are the 65 signature bytes / 16 limbs
    of a row; the layout matters when a stage's reduction runs across
    rows (e.g. a cross-row histogram or a bytewise transform) rather
    than within them.  The pattern is the all-to-all half of the
    sequence-parallel toolbox (ring collectives being the other), kept
    here as a first-class, tested layout the verifier pipeline can adopt
    per-stage (ref role: the reference has no SP — SURVEY §5 maps the
    axis to the signature batch).

    ``fn`` maps ``n_in`` arrays of shape ``[rows, F/n]`` to one array of
    the same leading shape; the wrapper returns the row-sharded result.
    The mesh size must divide both the row count and the feature dim.
    """
    import jax
    from jax.sharding import PartitionSpec as PS

    def shard_fn(*args):
        # [rows/n, F] per device -> all_to_all -> [rows, F/n]
        resplit = [
            jax.lax.all_to_all(a, axis, split_axis=feature_axis,
                               concat_axis=0, tiled=True)
            for a in args
        ]
        out = fn(*resplit)
        # back: [rows, F/n] -> [rows/n, F]
        return jax.lax.all_to_all(out, axis, split_axis=0,
                                  concat_axis=feature_axis, tiled=True)

    from eges_tpu.parallel import shard_map_fn
    return jax.jit(shard_map_fn()(
        shard_fn, mesh=mesh, in_specs=tuple([PS(axis)] * n_in),
        out_specs=PS(axis)))


@functools.lru_cache(maxsize=None)
def ring_gather(fn, mesh, axis: str = "dp", *, n_in: int,
                gather_out: int = 0):
    """Row-sharded map whose ``gather_out`` output is ring-all-gathered:
    after N-1 neighbor hops every device holds ALL rows of that output
    (each hop forwards the chunk received last — the classic ring
    all-gather schedule).  Returns the gathered array unsharded."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as PS

    n_dev = mesh.shape[axis]
    perm = ring_perm(n_dev)

    def shard_fn(*args):
        outs = fn(*args)
        outs = (outs,) if not isinstance(outs, tuple) else outs
        local = outs[gather_out]  # [rows/n, ...]
        idx = jax.lax.axis_index(axis)
        chunks = jnp.zeros((n_dev, *local.shape), local.dtype)
        chunks = chunks.at[idx].set(local)
        moving = local

        def hop(k, carry):
            chunks, moving = carry
            moving = jax.lax.ppermute(moving, axis, perm)
            src = (idx - k - 1) % n_dev  # whose chunk just arrived
            chunks = jax.lax.dynamic_update_index_in_dim(
                chunks, moving, src, axis=0)
            return chunks, moving

        chunks, _ = jax.lax.fori_loop(0, n_dev - 1, hop, (chunks, moving))
        return chunks.reshape((-1, *local.shape[1:]))

    # every device computes the full gathered array -> replicated
    import jax as _jax
    return _jax.jit(_shard_map_unchecked(
        shard_fn, mesh, tuple([PS(axis)] * n_in), PS()))


# -- topology-aware collective choice (JAX-free) --------------------------

# heuristic fallback when no measured A/B exists: a tree all-reduce wins
# on small axes, nearest-neighbor ring traffic wins once the axis is
# wide enough that the tree's fan-in hops dominate
_RING_MIN_DEVICES = 8


def _scaling_path() -> str:
    import os
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, os.pardir, "MESH_SCALING.json")


def load_collective_table(path: str | None = None) -> dict:
    """Measured psum/ring A/B from MESH_SCALING.json as
    ``{devices: [(rows, psum_rows_per_s, ring_rows_per_s), ...]}``.
    Missing/unreadable artifact -> empty table (callers fall back to the
    heuristic)."""
    import json
    import os

    out: dict[int, list] = {}
    p = path or _scaling_path()
    if not os.path.exists(p):
        return out
    try:
        with open(p) as f:
            doc = json.load(f)
        for pt in doc.get("points", []):
            psum = (pt.get("psum") or {}).get("rows_per_s")
            ring = (pt.get("ring") or {}).get("rows_per_s")
            if psum is None or ring is None:
                continue
            out.setdefault(int(pt["devices"]), []).append(
                (int(pt.get("rows", 0)), float(psum), float(ring)))
    # analysis: allow-swallow(a malformed scaling artifact must never
    # break verifier construction — the heuristic fallback takes over)
    except Exception:
        return {}
    return out


def preferred_collective(n_devices: int, bucket: int,
                         path: str | None = None) -> str:
    """Topology-aware psum-vs-ring choice for the ACK-tally all-reduce.

    Resolution order:

    1. ``EGES_MESH_COLLECTIVE=psum|ring`` pins the choice (``auto`` or
       unset falls through);
    2. the measured A/B in MESH_SCALING.json — the point with the
       nearest device count (exact match preferred), then the nearest
       ``rows`` to the requested bucket, wins by ``rows_per_s``;
    3. heuristic: psum below ``_RING_MIN_DEVICES`` devices, ring at or
       above (nearest-neighbor ICI traffic beats the tree fan-in on
       wide axes).
    """
    import os

    env = os.environ.get("EGES_MESH_COLLECTIVE", "auto").strip().lower()
    if env in ("psum", "ring"):
        return env
    table = load_collective_table(path)
    if table:
        devs = min(table, key=lambda d: (abs(d - n_devices), -d))
        rows, psum, ring = min(table[devs],
                               key=lambda e: abs(e[0] - bucket))
        return "psum" if psum >= ring else "ring"
    return "psum" if n_devices < _RING_MIN_DEVICES else "ring"
