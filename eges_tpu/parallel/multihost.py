"""Multi-host (DCN) device mesh: the distributed communication backend.

SURVEY §2.3 maps the reference's host plane — NCCL/MPI-style fan-out of
batches across machines (ref: eth/handler.go:1058-1103 per-peer send
loops; the Geec deployment scatters verify work the same way) — onto
``jax.distributed``: every host runs one process, the processes
rendezvous at a coordinator, and their local chips form ONE global
:class:`jax.sharding.Mesh`.  Collectives over the mesh axis then ride
ICI within a host and DCN between hosts, inserted by XLA from the same
``shard_map`` program that drives the single-host path — no second code
path for "networked" mode, which is the whole point of the design.

Two layers:

* :func:`initialize` / :func:`global_mesh` — library surface a real
  multi-host deployment calls once at startup (mirrors
  ``jax.distributed.initialize``; the node CLI exposes it via
  ``--coordinator/--processId/--numProcesses``).
* :func:`dryrun_multihost` — the CI proof: spawns N real OS processes
  on this machine (CPU backend, a few virtual devices each), forms the
  global mesh across them, runs the sharded batch verifier with its
  cross-process ``psum`` tally, and checks every process sees the same
  correct global count.  This exercises the actual multi-process
  runtime (coordination service, cross-host collectives), not a
  single-process simulation of it.
"""

from __future__ import annotations

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def initialize(coordinator: str, num_processes: int, process_id: int,
               platform: str = "") -> None:
    """Join the distributed runtime (call before any other jax use).

    ``coordinator`` is ``host:port`` of process 0 — the DCN rendezvous
    point.  On CPU backends the cross-process collective transport is
    gloo (the only one the wheel ships); TPU backends use the native
    ICI/DCN stack and ignore it.
    """
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if (platform or "cpu") == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # analysis: allow-swallow(older jax: single implementation, no knob)
        except Exception:
            pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    # LOCALHOST rigs only (the dryrun launcher sets the env): a real
    # multi-machine CPU deployment must NOT pin gloo to loopback, or
    # every cross-host connect dials the wrong machine.
    if os.environ.get("EGES_TPU_GLOO_LOOPBACK") == "1":
        _pin_gloo_loopback()


def _pin_gloo_loopback() -> None:
    """Re-register the CPU backend factory with gloo collectives pinned
    to the loopback interface.

    jax builds gloo with ``hostname=None, interface=None`` and gloo then
    binds a NIC from its own discovery; inside this sandboxed host that
    picked an interface whose worker-to-worker connects time out
    ("Gloo context initialization failed: Connect timeout") even though
    the hostname resolves to 127.0.0.1.  The dry run is strictly
    localhost, so pin both ends to loopback.  Harmless on real
    multi-host TPO deployments: those use the native ICI/DCN stack, not
    the CPU gloo transport.

    Uses jax PRIVATE internals (jax._src.{distributed,xla_bridge},
    xla_client._xla.make_gloo_tcp_collectives) — written against the
    baked-in jax 0.5.x; a jax upgrade may rename any of them.  That
    must degrade to the default gloo factory with a readable log line,
    not an opaque dryrun crash (r4 advisor finding)."""
    try:
        from jax._src import distributed, xla_bridge
        from jaxlib import xla_client

        def make(*_a, **_kw):
            collectives = xla_client._xla.make_gloo_tcp_collectives(
                distributed_client=distributed.global_state.client,
                hostname="127.0.0.1")
            return xla_bridge.make_cpu_client(collectives=collectives)

        # same flags as jax's own cpu registration; the factory table is
        # keyed by name, so this simply replaces the default factory (it
        # must run before the first backend use or jax raises)
        xla_bridge.register_backend_factory("cpu", make, priority=0,
                                            fail_quietly=False)
    except Exception as exc:  # AttributeError/ImportError on jax bump
        print(f"multihost: gloo loopback pin unavailable on this jax "
              f"version ({exc!r}); using the default gloo factory — "
              f"cross-process connects may pick a non-loopback NIC",
              file=sys.stderr, flush=True)


def global_mesh(axis: str = "dp"):
    """One mesh over every device of every process, in id order."""
    import numpy as np
    import jax

    return jax.sharding.Mesh(np.array(jax.devices()), (axis,))


def make_global_rows(mesh, axis: str, *arrays):
    """Lift host-resident global batches into row-sharded global
    ``jax.Array``s.  Every process passes the SAME full batch (consensus
    batches are deterministic — each host derived them from the same
    block); the callback hands each local device only its row slice, so
    nothing materializes twice."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = []
    for a in arrays:
        spec = P(axis, *([None] * (a.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        out.append(jax.make_array_from_callback(
            a.shape, sharding, lambda idx, a=a: a[idx]))
    return tuple(out)


def _worker_body(process_id: int, num_processes: int,
                 coordinator: str) -> None:
    """One process of the dry run: join, mesh, verify, tally, check."""
    initialize(coordinator, num_processes, process_id, platform="cpu")

    import numpy as np
    import jax

    from eges_tpu.crypto import secp256k1 as host
    from eges_tpu.crypto.verifier import make_sharded_ecrecover

    mesh = global_mesh("dp")
    n_devices = mesh.shape["dp"]
    rows = 2 * n_devices

    sigs = np.zeros((rows, 65), np.uint8)
    hashes = np.zeros((rows, 32), np.uint8)
    privs = []
    for i in range(rows):
        msg = bytes([(i % 255) + 1]) * 32
        priv = bytes([(i % 200) + 5]) * 32
        privs.append(priv)
        sigs[i] = np.frombuffer(host.ecdsa_sign(msg, priv), np.uint8)
        hashes[i] = np.frombuffer(msg, np.uint8)

    gsigs, ghashes = make_global_rows(mesh, "dp", sigs, hashes)
    fn = make_sharded_ecrecover(mesh, "dp")
    # Compile ahead-of-time, then meet at a COORDINATION-SERVICE
    # barrier (not a collective) before the first execution.  The gloo
    # transport rendezvouses lazily at the first collective with ~30 s
    # timeouts; on a 1-core host one worker can hit the persistent
    # compile cache while the other compiles from scratch, and that
    # skew alone blew the rendezvous ("Gloo context initialization
    # failed: Connect timeout / GetKeyValue() timed out").
    compiled = fn.lower(gsigs, ghashes).compile()  # fn is jitted already
    from jax._src import distributed as _dist
    _dist.global_state.client.wait_at_barrier("eges_compiled",
                                              timeout_in_ms=900_000)
    addrs, _pubs, ok, tally = compiled(gsigs, ghashes)

    # the psum tally is replicated: every process holds the global count
    assert int(tally) == rows, f"pid {process_id}: tally {int(tally)} != {rows}"
    # outputs are globally sharded; each process checks the rows it owns
    checked = 0
    # slice objects are unhashable before py3.12 — key by their bounds
    ok_shards = {(s.index[0].start, s.index[0].stop): np.asarray(s.data)
                 for s in ok.addressable_shards}
    for shard in addrs.addressable_shards:
        rs = shard.index[0]
        data = np.asarray(shard.data)
        assert ok_shards[(rs.start, rs.stop)].all(), (
            f"pid {process_id}: rejected valid rows")
        for j, i in enumerate(range(*rs.indices(rows))):
            want = host.pubkey_to_address(host.privkey_to_pubkey(privs[i]))
            assert bytes(data[j]) == want, (
                f"pid {process_id}: row {i} address mismatch")
            checked += 1
    print(f"dryrun_multihost OK pid={process_id}/{num_processes} "
          f"devices={n_devices} tally={int(tally)} local_rows={checked}",
          flush=True)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.settimeout(1.0)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def dryrun_multihost(num_processes: int = 2, devices_per_proc: int = 4,
                     timeout: float = 1800.0) -> None:
    """Prove the DCN path: ``num_processes`` OS processes, one global
    mesh, sharded verify + cross-process psum, every process asserting
    the global tally.  CPU backend; the same program shape runs
    unchanged on real multi-host TPU (ICI inside a host, DCN between).
    """
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU-tunnel plugin in workers
    # the dryrun is strictly localhost: have the workers rebuild their
    # gloo collectives pinned to loopback (see _pin_gloo_loopback)
    env["EGES_TPU_GLOO_LOOPBACK"] = "1"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={devices_per_proc}"]
    ).strip()
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(_REPO, ".jax_cache"))

    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "eges_tpu.parallel.multihost",
             "--worker", str(pid), str(num_processes), coordinator],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in range(num_processes)
    ]
    outs = []
    failed = False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            out, _ = p.communicate()
            failed = True
        outs.append(out or "")
        failed = failed or p.returncode != 0
    for pid, out in enumerate(outs):
        sys.stdout.write(out)
        if f"dryrun_multihost OK pid={pid}" not in out:
            failed = True
    if failed:
        raise RuntimeError(
            "dryrun_multihost failed; worker output above (last worker: "
            f"{outs[-1][-500:]!r})")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker_body(int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
        sys.exit(0)
    dryrun_multihost(int(sys.argv[1]) if len(sys.argv) > 1 else 2,
                     int(sys.argv[2]) if len(sys.argv) > 2 else 4)
