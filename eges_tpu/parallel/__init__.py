"""Device-mesh parallelism utilities (the SPMD layer).

The "data parallelism" of this domain is sharding signature-batch ROWS
across chips (SURVEY §2.3: the reference's per-tx verify loop maps to
the batch dimension; multi-chip = `shard_map` over a 1-axis mesh with
XLA collectives riding ICI).  These helpers are the generic layer under
:func:`eges_tpu.crypto.verifier.make_sharded_ecrecover`.
"""

from __future__ import annotations

import functools

import numpy as np


def shard_map_fn():
    """Resolve ``shard_map`` across JAX versions: new releases export it
    as ``jax.shard_map``; the pinned toolchain here still ships it under
    ``jax.experimental.shard_map``.  Every shard_map user in the tree
    goes through this one resolver so a JAX bump touches one line."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map
    return shard_map


def data_parallel_mesh(devices=None, axis: str = "dp"):
    """A 1-axis mesh over ``devices`` (default: all local devices)."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    return jax.sharding.Mesh(np.array(devs), (axis,))


@functools.lru_cache(maxsize=None)
def shard_rows(fn, mesh, axis: str = "dp", *, n_in: int, n_out: int,
               tally_out: int | None = None):
    """Wrap a row-batched function in `shard_map` over ``mesh[axis]``.

    Memoized on ``(fn, mesh, axis, arity)`` — the wrapper (and its jit
    cache) is built once per distinct graph, so calling this from the
    dispatch path never re-traces.

    ``fn`` maps ``n_in`` row-sharded arrays to ``n_out`` row-sharded
    arrays; each device runs the identical fused kernel on its shard
    (pure data parallel — XLA inserts no collectives for the map).
    When ``tally_out`` names an output index, that output is additionally
    `psum`-reduced over the mesh axis into an unsharded scalar appended
    to the outputs — the on-device ACK-tally reduction
    (ref: core/geec_state.go:1184-1227 handleVerifyReplies).
    """
    import jax
    from jax.sharding import PartitionSpec as PS

    def shard_fn(*args):
        outs = fn(*args)
        outs = (outs,) if not isinstance(outs, tuple) else outs
        if tally_out is not None:
            import jax.numpy as jnp

            tally = jax.lax.psum(jnp.sum(outs[tally_out]), axis)
            outs = (*outs, tally)
        return outs

    out_specs = tuple([PS(axis)] * n_out
                      + ([PS()] if tally_out is not None else []))
    return jax.jit(
        shard_map_fn()(shard_fn, mesh=mesh,
                       in_specs=tuple([PS(axis)] * n_in),
                       out_specs=out_specs))
