"""Consensus-engine seam — re-export of :mod:`eges_tpu.core.engine`.

The interface definition lives in ``core`` (the chain layer consumes
it, and L1 must not import L2 — see the architecture manifest in
``harness/analysis/layermap.py``); this module keeps the historical
``eges_tpu.consensus.engine`` import path working for the consensus
layer and external callers.  Importing core from consensus is the
legal direction, so the shim itself is layer-clean.
"""

from eges_tpu.core.engine import (  # noqa: F401
    DevEngine,
    Engine,
    EngineError,
    GeecEngine,
    PowEngine,
)

__all__ = ["DevEngine", "Engine", "EngineError", "GeecEngine",
           "PowEngine"]
