"""Pluggable consensus-engine seam.

Role parity with the reference's ``consensus.Engine`` interface
(ref: consensus/consensus.go:57 — VerifyHeader/Prepare/Finalize/Seal,
implemented by ethash, clique and geec): the chain layer calls the
engine for header verification and block assembly, so the Geec state
machine is ONE engine rather than a hardwired assumption.

Engines here:

* :class:`GeecEngine` — the production engine: header verification is
  intentionally near-no-op (ancestry only, ref: consensus/geec/
  geec.go:186-210 verifyHeader); sealing is driven by the event-loop
  consensus node (:mod:`eges_tpu.consensus.node`), not a Seal() call.
* :class:`DevEngine` — single-authority instant-seal PoA (the clique
  role, ref: consensus/clique/clique.go's signed-extra scheme,
  re-designed: one signer, no epoch/voting): every sealed header
  carries the authority's signature over the header's signing hash in
  ``extra``; verification recovers and checks the signer.  This is the
  dev-chain mode (geth --dev analogue) and proves the seam carries a
  second, structurally different engine.
"""

from __future__ import annotations

import dataclasses

from eges_tpu.core.types import Block, Header, new_block


class EngineError(Exception):
    """Header/seal verification failure."""


class Engine:
    """The minimal engine surface the chain layer consumes."""

    name = "base"

    def verify_header(self, chain, header: Header) -> None:
        """Raise :class:`EngineError` on a bad header.  Ancestry/number
        checks are the chain layer's; engines add their own rules."""

    def prepare(self, chain, header: Header) -> Header:
        """Fill engine-owned header fields before execution."""
        return header

    def seal(self, chain, block: Block) -> Block:
        """Produce the sealed block (synchronous engines only)."""
        return block


class GeecEngine(Engine):
    """Geec: verification rides the quorum certificates, not the header
    (ref: geec.go:186-210 — the header check is deliberately minimal;
    VerifySeal is a stub, geec.go:223-226).  Sealing happens in the
    consensus node's phase machine, so :meth:`seal` is unused."""

    name = "geec"

    def verify_header(self, chain, header: Header) -> None:
        if header.number > 0 and header.time == 0:
            raise EngineError("missing timestamp")


class DevEngine(Engine):
    """Single-authority instant seal.  ``extra`` carries the 65-byte
    authority signature over the unsigned header hash."""

    name = "dev"

    def __init__(self, authority: bytes, priv: bytes | None = None):
        self.authority = authority  # 20-byte address
        self.priv = priv            # present on the sealing node only

    @staticmethod
    def _signing_hash(header: Header) -> bytes:
        from eges_tpu.core import rlp
        from eges_tpu.crypto.keccak import keccak256

        bare = dataclasses.replace(header, extra=b"")
        return keccak256(rlp.encode(bare.to_rlp()))

    def verify_header(self, chain, header: Header) -> None:
        from eges_tpu.crypto import secp256k1 as secp

        if header.number == 0:
            return
        if len(header.extra) != 65:
            raise EngineError("dev seal missing")
        try:
            signer = secp.recover_address(self._signing_hash(header),
                                          header.extra)
        except Exception:
            raise EngineError("unrecoverable dev seal")
        if signer != self.authority:
            raise EngineError("dev seal from a non-authority signer")

    def seal(self, chain, block: Block) -> Block:
        from eges_tpu.crypto import secp256k1 as secp

        if self.priv is None:
            raise EngineError("not the authority (no key)")
        sig = secp.ecdsa_sign(self._signing_hash(block.header), self.priv)
        header = dataclasses.replace(block.header, extra=sig)
        return dataclasses.replace(block, header=header)

    def seal_next(self, chain, txs=(), coinbase: bytes | None = None) -> Block:
        """Convenience dev-chain block producer: preview ``txs`` on the
        head state, assemble, seal, and offer — the geth --dev
        instant-mining loop collapsed to one call."""
        coinbase = coinbase if coinbase is not None else self.authority
        parent = chain.head()
        kept, root, receipt_hash, gas, bloom = chain.execute_preview(
            list(txs), coinbase)
        header = Header(parent_hash=parent.hash, number=parent.number + 1,
                        coinbase=coinbase, time=parent.header.time + 1,
                        root=root, receipt_hash=receipt_hash, gas_used=gas,
                        bloom=bloom)
        block = self.seal(chain, new_block(header, txs=kept))
        inserted = chain.offer(block)
        if not inserted:
            raise EngineError(f"dev block rejected: {chain.last_error}")
        return block
