"""Geec consensus configuration.

Merges the reference's two config tiers into explicit dataclasses:

* chain-wide consensus config from the genesis ``"thw"`` section
  (ref: params/config.go:154-174 GeecConfig) — consensus-critical,
  must agree across nodes;
* per-node operational knobs from CLI flags -> node.Config
  (ref: cmd/utils/flags.go:540-591, node/config.go:152-163).

Time quantities keep the reference's (mixed) units, documented per field.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class BootstrapNode:
    """Genesis committee seed entry (ref: params/config.go:156-161)."""

    account: bytes  # 20-byte address
    ip: str
    port: int

    @classmethod
    def from_json(cls, obj: dict) -> "BootstrapNode":
        return cls(account=bytes.fromhex(obj["account"]), ip=obj["ip"],
                   port=int(obj["port"]))

    def to_json(self) -> dict:
        return {"account": self.account.hex(), "ip": self.ip,
                "port": str(self.port)}


@dataclass(frozen=True)
class ChainGeecConfig:
    """The genesis ``"thw"`` section (ref: params/config.go:154-174)."""

    bootstrap: tuple[BootstrapNode, ...] = ()
    max_reg_per_blk: int = 10          # reg_per_blk
    reg_timeout_s: float = 10.0        # registration_timeout (seconds)
    validate_timeout_ms: float = 500.0  # validate_timeout (ms) — ACK retry
    election_timeout_ms: float = 100.0  # election_timeout (ms)
    backoff_time_ms: float = 0.0       # backoff_time (ms) before confirm
    # This build's upgrade over the reference's trustedHW assumption
    # (unsigned ValidateReply, core/geec_state.go:528-591): when True,
    # election votes / ACKs / query replies / confirms must carry valid
    # secp256k1 signatures, tallied through the device batch verifier.
    # Consensus-critical: must agree across the chain.  ON by default;
    # set "signed_votes": false in genesis for reference-parity
    # trustedHW-style deployments.
    signed_votes: bool = True

    @classmethod
    def from_json(cls, obj: dict) -> "ChainGeecConfig":
        if "bootstrap" in obj and "signed_votes" not in obj:
            # consensus-critical default: a genesis that omits the key is
            # ambiguous across build generations — pin it explicitly
            from eges_tpu.utils.log import get_logger
            get_logger("geec.config").warn(
                "genesis thw section omits 'signed_votes'; defaulting to "
                "true — pin it explicitly so every node generation agrees")
        return cls(
            bootstrap=tuple(BootstrapNode.from_json(n)
                            for n in obj.get("bootstrap", [])),
            max_reg_per_blk=int(obj.get("reg_per_blk", 10)),
            reg_timeout_s=float(obj.get("registration_timeout", 10)),
            validate_timeout_ms=float(obj.get("validate_timeout", 500)),
            election_timeout_ms=float(obj.get("election_timeout", 100)),
            backoff_time_ms=float(obj.get("backoff_time", 0)),
            signed_votes=bool(obj.get("signed_votes", True)),
        )

    def to_json(self) -> dict:
        return {
            "bootstrap": [n.to_json() for n in self.bootstrap],
            "reg_per_blk": self.max_reg_per_blk,
            "registration_timeout": self.reg_timeout_s,
            "validate_timeout": self.validate_timeout_ms,
            "election_timeout": self.election_timeout_ms,
            "backoff_time": self.backoff_time_ms,
            "signed_votes": self.signed_votes,
        }


@dataclass(frozen=True)
class NodeConfig:
    """Per-node Geec knobs (ref: node/config.go:152-163 + flags)."""

    coinbase: bytes = bytes(20)
    consensus_ip: str = "127.0.0.1"     # --consensusIP
    consensus_port: int = 8100          # --consensusPort (UDP control plane)
    geec_txn_port: int = 0              # --geecTxnPort (0 = no txn service)
    n_candidates: int = 3               # --nCandidates (committee size)
    n_acceptors: int = 4                # --nAcceptors (validator set size)
    block_timeout_s: float = 20.0       # --blockTimeout (seconds)
    txn_per_block: int = 1000           # --txnPerBlock
    txn_size: int = 100                 # --txnSize (fake txn payload bytes)
    breakdown: bool = False             # --breakdown (phase timing logs)
    failure_test: bool = False          # --failureTest (TTL economy on)
    total_nodes: int = 3                # --totalNodes
    privkey: bytes = b""                # consensus signing key (32 bytes)
    #                                     — required when the chain runs
    #                                     with signed_votes
    fast_sync: bool = False             # --syncmode fast: a late joiner
    #                                     downloads the state at a pivot
    #                                     block (root-verified against a
    #                                     quorum-certified header) and
    #                                     replays only the tail — O(state)
    #                                     not O(chain).  Requires
    #                                     signed_votes for the cert check.

    checkpoint_every: int = 0           # durable state-checkpoint cadence
    #                                     in blocks (0 = off): every Nth
    #                                     committed block writes a
    #                                     root-verified snapshot sidecar so
    #                                     a restart replays only the tail
    #                                     past the newest checkpoint —
    #                                     O(tail), not O(chain)

    # TPU-native addition: verify signatures in device batches of up to
    # this many rows (the reference has no analogue — it verifies one
    # cgo call at a time, crypto/secp256k1/secp256.go:105).
    verify_batch_rows: int = 1024


def ttl_params(total_nodes: int) -> dict:
    """TTL economy constants (ref: core/geec_state.go:262-272)."""
    if total_nodes > 200:
        initial = 200
    elif total_nodes < 50:
        initial = 50
    else:
        initial = total_nodes
    return dict(initial_ttl=initial, bonus_ttl=20, renew_ttl_threshold=20,
                max_ttl=initial, ttl_interval=10)


# Consensus constants (ref: core/geec_state.go:230, geecCore/utils.go:5-11)
CONFIDENCE_THRESHOLD = 9999
CONFIDENCE_STEP = 1000
CONFIDENCE_CAP = 10000


def calc_confidence(parent_confidence: int) -> int:
    """(ref: core/geecCore/utils.go:5-11)"""
    return min(parent_confidence + CONFIDENCE_STEP, CONFIDENCE_CAP)
