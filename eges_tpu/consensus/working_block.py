"""Per-height consensus scratchpad.

Port of the reference's ``WorkingBlock`` (ref: core/geecCore/geec_wb.go)
minus its mutex/condvar protocol: here exactly one event loop owns the
struct, and the reference's ``Wait(blk)`` (block the goroutine until the
working height catches up, geec_wb.go:118) becomes *deferral* — the node
queues messages addressed to future heights and replays them on
:meth:`advance` (the ``Move``/``Cond.Broadcast`` analogue, geec_wb.go:84).

``my_rand`` is drawn from a per-node deterministic PRNG seeded by the
coinbase (geec_wb.go:66-68), so election tie-breaks are reproducible in
the simulator.
"""

from __future__ import annotations

import random

# Election states (ref: core/geecCore/geec_wb.go:14-18)
ELEC_CANDIDATE = 0x01
ELEC_VOTED = 0x02
ELEC_ELECTED = 0x03

# Wait verdicts (ref: geec_wb.go:74-78)
WB_PASSED = 0x00
WB_CURRENT = 0x01
WB_FUTURE = 0x02  # caller must defer (reference blocks instead)


class WorkingBlock:
    def __init__(self, coinbase: bytes):
        self.coinbase = coinbase
        self._rng = random.Random(int.from_bytes(coinbase[-8:], "big"))
        self.blk_num = 0
        self.advance(1)

    def advance(self, blk_num: int) -> None:
        """(ref: Move, geec_wb.go:84-106)"""
        self.blk_num = blk_num
        self.max_version = -1
        self.max_validate_retry = -1
        self.max_query_retry = -1
        # election
        self.elect_state = ELEC_CANDIDATE
        self.supporters: set[bytes] = set()
        # signed-vote mode: up to 2 distinct (signing_hash, sig) entries
        # per claimed voter, batch-verified when the threshold is reached
        # — multiple entries so a spoofed garbage-sig vote can neither
        # squat the slot nor overwrite the genuine one
        self.supporter_votes: dict[bytes, list[tuple[bytes, bytes]]] = {}
        self.my_rand = self._rng.getrandbits(64)
        self.delegator: bytes = self.coinbase
        self.delegator_ip: str = ""
        self.delegator_port: int = 0
        self.max_election_retry = 0
        self.n_candidates = 0
        self.election_threshold = 1 << 62
        # validation (proposer side) — up to 2 distinct stored replies per
        # claimed author (see supporter_votes note)
        self.is_proposer = False
        self.validate_replies: dict[bytes, list] = {}  # addr -> [ValidateReply]
        self.validate_threshold = 1 << 62
        self.validate_succeeded = False
        # signed-vote mode: the verified ACK signature per supporter,
        # harvested at quorum time — becomes the confirm's quorum cert
        self.validate_cert: dict[bytes, bytes] = {}
        # query (recovery side)
        self.query_replies: dict[bytes, list] = {}  # addr -> [QueryReply]
        # quorum-verified reply and signature per author (set at tally)
        self.query_verified: dict[bytes, object] = {}
        self.query_cert: dict[bytes, bytes] = {}
        self.query_empty_count = 0
        self.query_nonempty_count = 0
        self.query_threshold = 1 << 62
        self.query_recv_majority = False

    def classify(self, blk_num: int) -> int:
        """Old / current / future for an incoming message's height
        (the Wait() verdict, geec_wb.go:118-135)."""
        if blk_num < self.blk_num:
            return WB_PASSED
        if blk_num == self.blk_num:
            return WB_CURRENT
        return WB_FUTURE

    def bump_version(self, version: int) -> None:
        """Entering a higher re-election version resets retry dedup
        (ref: election_go.go:49-55, handler.go:917-922)."""
        if version > self.max_version:
            self.max_version = version
            self.max_query_retry = -1
            self.max_validate_retry = -1
            self.elect_state = ELEC_CANDIDATE
            self.supporters.clear()
            self.supporter_votes.clear()
