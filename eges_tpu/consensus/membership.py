"""Membership registry: the sorted candidate list, seeded committee /
acceptor windows, and the TTL economy.

Semantics ported from the reference's treemap-based membership
(ref: core/geec_state.go:325-521,770-861,1088-1129), re-expressed as a
plain sorted structure owned by one event loop (no locks — the reference
enforces "call with lock held" by comment, SURVEY §5 flags that as the
fragility to remove).

Window rule (ref: getAllCommittee, geec_state.go:358-419): members sorted
by address; ``start = seed % size``; if the window fits, take
``[start, start+n)``; if it wraps, take ``[0, n-size+start)`` plus
``[start, size)``.  The same rule with ``n_candidates`` gives the
committee (proposer-electable set) and with ``n_acceptors`` the validator
set.  If fewer members than ``n`` exist, everyone is in.

Versioned re-election derives a new seed from the base seed —
``float64(seed) ** version`` in the reference (geec_state.go:700,
IsCommittee uses ``version+1``, ElectForProposer uses ``version``; the two
disagree there — a reference inconsistency).  Here both sides use ONE
transform so recovered leaders always know they are committee members:
``derive_seed(seed, version)``, identical on every node.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field


@dataclass
class Member:
    """(ref: core/geecCore/Types.go:9-17 GeecMember)"""

    addr: bytes
    ip: str
    port: int
    referee: bytes = b""
    joined_block: int = 0
    ttl: int = 0
    renewed_times: int = 0


def derive_seed(seed: int, version: int) -> int:
    """Seed for version>0 re-elections.  Integer arithmetic (not the
    reference's float64 ``math.Pow``, which loses precision above 2^53 and
    differs between call sites); deterministic on every host."""
    if version == 0:
        return seed
    return pow(seed, version + 1, (1 << 64) - 59)  # largest 64-bit prime


class Membership:
    """Sorted-by-address member registry with window selection and TTL."""

    def __init__(self, n_candidates: int, n_acceptors: int, *,
                 initial_ttl: int = 50, bonus_ttl: int = 20,
                 renew_ttl_threshold: int = 20, max_ttl: int = 50,
                 ttl_interval: int = 10):
        self.n_candidates = n_candidates
        self.n_acceptors = n_acceptors
        self.initial_ttl = initial_ttl
        self.bonus_ttl = bonus_ttl
        self.renew_ttl_threshold = renew_ttl_threshold
        self.max_ttl = max_ttl
        self.ttl_interval = ttl_interval
        self._members: dict[bytes, Member] = {}
        self._sorted_addrs: list[bytes] = []
        self._flat: bytes | None = None  # packed sorted addrs (native path)
        # owning GeecNode attaches its event journal (utils/journal.py)
        # so the TTL economy shows up in the consensus observatory
        self.journal = None

    def _record(self, type: str, **attrs) -> None:
        if self.journal is not None:
            self.journal.record(type, **attrs)

    def _update_gauges(self) -> None:
        from eges_tpu.utils import metrics

        metrics.DEFAULT.gauge("membership.size").set(len(self._members))
        min_ttl = min((m.ttl for m in self._members.values()), default=0)
        metrics.DEFAULT.gauge("membership.min_ttl").set(min_ttl)

    # -- registry ---------------------------------------------------------

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, addr: bytes) -> bool:
        return addr in self._members

    def get(self, addr: bytes) -> Member | None:
        return self._members.get(addr)

    def members(self) -> list[Member]:
        return [self._members[a] for a in self._sorted_addrs]

    def add(self, member: Member) -> None:
        """Insert or renew (ref: AddGeecMember geec_state.go:326-353 —
        renewal stacks TTL up to max_ttl)."""
        existing = self._members.get(member.addr)
        if existing is not None:
            existing.renewed_times = member.renewed_times
            existing.ttl = min(existing.ttl + member.ttl, self.max_ttl)
            existing.ip = member.ip or existing.ip
            existing.port = member.port or existing.port
            self._record("member_renewed", addr=member.addr.hex()[:8],
                         ttl=existing.ttl)
            self._update_gauges()
            return
        self._members[member.addr] = member
        bisect.insort(self._sorted_addrs, member.addr)
        self._flat = None
        self._record("member_registered", addr=member.addr.hex()[:8],
                     ttl=member.ttl, joined_block=member.joined_block)
        self._update_gauges()

    def remove(self, addr: bytes) -> None:
        if addr in self._members:
            del self._members[addr]
            self._sorted_addrs.remove(addr)
            self._flat = None
            self._update_gauges()

    # -- windows ----------------------------------------------------------

    def _window(self, seed: int, n: int) -> list[bytes]:
        size = len(self._sorted_addrs)
        if size == 0:
            return []
        if size < n:
            return list(self._sorted_addrs)
        start = seed % size
        if start + n > size:
            head = self._sorted_addrs[: n - size + start]
            tail = self._sorted_addrs[start:]
            return head + tail
        return self._sorted_addrs[start : start + n]

    def committee(self, seed: int, version: int = 0) -> list[Member]:
        """Proposer-electable window (ref: getAllCommittee)."""
        addrs = self._window(derive_seed(seed, version), self.n_candidates)
        return [self._members[a] for a in addrs]

    def _window_check(self, addr: bytes, seed: int, n: int) -> bool:
        """Membership-in-window check; native binary search when the
        C++ election component is built (native/election.cpp — the
        reference's own measured hot spot, its --breakdown logs
        "ChecMembership Time", core/geec_state.go:1092)."""
        from eges_tpu.crypto import native

        size = len(self._sorted_addrs)
        if size == 0:
            return False
        if native.has_election():
            if self._flat is None:
                self._flat = b"".join(self._sorted_addrs)
            return native.window_check(self._flat, size, seed % size, n,
                                       addr)
        return addr in self._window(seed, n)

    def is_committee(self, addr: bytes, seed: int, version: int = 0) -> bool:
        """(ref: IsCommittee geec_state.go:770-861)"""
        if addr not in self._members:
            return False
        return self._window_check(addr, derive_seed(seed, version),
                                  self.n_candidates)

    def acceptors(self, seed: int) -> list[Member]:
        addrs = self._window(seed, self.n_acceptors)
        return [self._members[a] for a in addrs]

    def is_acceptor(self, addr: bytes, seed: int) -> bool:
        """(ref: IsValidator geec_state.go:439-521)"""
        if addr not in self._members:
            return False
        return self._window_check(addr, seed, self.n_acceptors)

    def acceptor_count(self) -> int:
        """(ref: getAcceptorCount geec_state.go:421-428)"""
        return min(len(self._members), self.n_acceptors)

    # -- thresholds (ref: geec_state.go:651, election_go.go:66) -----------

    def validate_threshold(self) -> int:
        """ceil((acceptors + 1) / 2) — proposer needs this many ACKs."""
        n = self.acceptor_count()
        return -(-(n + 1) // 2)

    def election_threshold(self, n_committee: int) -> int:
        """ceil((committee + 1) / 2) - 1 votes (self-vote is implicit)."""
        return -(-(n_committee + 1) // 2) - 1

    # -- TTL economy (ref: CheckMembership geec_state.go:1088-1129) --------

    def reward(self, addrs) -> None:
        """Bonus TTL for a confirmed block's supporters + proposer."""
        for addr in addrs:
            m = self._members.get(addr)
            if m is not None:
                m.ttl = min(m.ttl + self.bonus_ttl, self.max_ttl)
        self._update_gauges()

    def decay(self) -> list[bytes]:
        """Periodic TTL decay + eviction; returns evicted addresses.
        Call every ``ttl_interval`` blocks."""
        evicted = []
        for addr in list(self._sorted_addrs):
            m = self._members[addr]
            if m.ttl <= self.ttl_interval:
                self.remove(addr)
                evicted.append(addr)
                self._record("member_expired", addr=addr.hex()[:8])
            else:
                m.ttl -= self.ttl_interval
        self._update_gauges()
        return evicted

    def needs_renewal(self, addr: bytes) -> bool:
        m = self._members.get(addr)
        return m is not None and m.ttl <= self.renew_ttl_threshold
