"""Consensus wire messages — both network planes.

The reference splits Geec traffic over two planes (SURVEY §2.3):

* **gossip plane** (RLPx/TCP in the reference): ``ValidateReqMsg`` /
  ``QueryMsg`` / ``RegisterReqMsg`` / ``ConfirmBlockMsg``, devp2p codes
  0x11/0x12/0x14/0x15 (ref: eth/protocol.go:67-73), relayed to all peers
  with retry/version dedup gating.
* **direct plane** (raw UDP + RLP): election messages and validate/query
  replies sent point-to-point to ``ip:port`` carried inside the request
  (ref: consensus/geec/election/server.go:70-120,
  core/geec_state.go:584-591), wrapped in ``GeecUDPMsg`` envelopes with
  codes 0x01/0x02/0x03 (ref: core/geecCore/Types.go:59-63).

Every message is a frozen dataclass with RLP to/from, so the same bytes
flow over the in-process simulator, real sockets, and tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from eges_tpu.core import rlp
from eges_tpu.core.types import (
    Block, ConfirmBlockMsg, Header, QueryBlockMsg, Registration,
)
from eges_tpu.crypto.keccak import keccak256

# Direct-plane (UDP envelope) codes (ref: core/geecCore/Types.go:59-63)
UDP_EXAMINE_REPLY = 0x01
UDP_ELECT = 0x02
UDP_QUERY_REPLY = 0x03
UDP_BLOCKS = 0x04      # backfill reply (this build; see BlockFetchReq)
UDP_GET_BLOCKS = 0x05  # peer-directed backfill request (sync protocol)
UDP_GET_HEADERS = 0x06  # header-first skeleton request (same req shape)
UDP_HEADERS = 0x07      # header+cert reply (see HeadersReply)
UDP_GET_STATE = 0x08    # fast-sync state page request (StateFetchReq)
UDP_STATE = 0x09        # fast-sync state page reply (StateChunkReply)

# Election sub-codes (ref: consensus/geec/election/election_go.go:15-18)
MSG_ELECT = 0x01
MSG_VOTE = 0x02

# Gossip-plane codes (ref: eth/protocol.go:67-73)
GOSSIP_VALIDATE_REQ = 0x11
GOSSIP_QUERY = 0x12
GOSSIP_REGISTER_REQ = 0x14
GOSSIP_CONFIRM_BLOCK = 0x15
GOSSIP_GET_BLOCKS = 0x16  # backfill request (broadcast fallback of the
#                           sync protocol; cf. the reference's downloader
#                           body sync, eth/downloader/queue.go:65-67)
GOSSIP_BLOCKS_REPLY = 0x18  # bulk backfill reply over TCP — block
#   batches exceed a UDP datagram at the 1000-txn operating point, so
#   sync replies ride the reliable plane (the reference ships blocks
#   over devp2p TCP too, eth/handler.go:562-590 body exchange)
GOSSIP_TXNS = 0x17  # transaction gossip (ref: TxMsg, eth/protocol.go:38 +
#                     eth/handler.go:742-759 -> TxPool.AddRemotes)
GOSSIP_GET_HEADERS = 0x19  # header-first skeleton request (broadcast
#                            fallback, cf. GetBlockHeadersMsg
#                            eth/protocol.go:67)
GOSSIP_HEADERS_REPLY = 0x1A  # header+cert batches over TCP
GOSSIP_GET_STATE = 0x1B      # fast-sync state request, broadcast fallback
GOSSIP_STATE_REPLY = 0x1C    # fast-sync state page over TCP (big chunks)


@dataclass(frozen=True)
class ElectMessage:
    """Election announce / vote (ref: election/election_go.go electMessage).

    ``code`` MSG_ELECT announces candidacy with ``rand``; MSG_VOTE carries a
    vote for ``author`` (on transfer, ``author`` stays the ORIGINAL voter —
    the vote-transfer semantics of election_go.go:276-310).

    ``sig`` signs :meth:`signing_hash` — the stable election content
    (code, height, author, rand, version) but NOT transport details
    (ip/port/retry), so retries and vote transfer keep the original
    signature valid."""

    code: int
    block_num: int
    author: bytes
    rand: int = 0
    version: int = 0
    retry: int = 0
    ip: str = ""
    port: int = 0
    sig: bytes = b""

    def to_rlp(self) -> list:
        return [self.code, self.block_num, self.author, self.rand,
                self.version, self.retry, self.ip.encode(), self.port,
                self.sig]

    @classmethod
    def from_rlp(cls, item: list) -> "ElectMessage":
        code, blk, author, rand, version, retry, ip, port = item[:8]
        return cls(code=rlp.decode_uint(code), block_num=rlp.decode_uint(blk),
                   author=bytes(author), rand=rlp.decode_uint(rand),
                   version=rlp.decode_uint(version),
                   retry=rlp.decode_uint(retry), ip=ip.decode(),
                   port=rlp.decode_uint(port),
                   sig=bytes(item[8]) if len(item) > 8 else b"")

    def signing_hash(self) -> bytes:
        return keccak256(b"geec/elect" + rlp.encode(
            [self.code, self.block_num, self.author, self.rand,
             self.version]))


@dataclass(frozen=True)
class ValidateRequest:
    """Proposer -> everyone: please ACK this block
    (ref: core/geecCore/Types.go:20-30).  Carries the full block plus the
    proposer's direct-plane return address and the empty-block numbers the
    proposer wants backfilled (``empty_list``)."""

    block_num: int
    author: bytes
    block: Block
    ip: str
    port: int
    retry: int = 0
    version: int = 0
    empty_list: tuple[int, ...] = ()
    sig: bytes = b""  # proposer's signature over signing_hash()

    def to_rlp(self) -> list:
        return [self.block_num, self.author, self.block.to_rlp(),
                self.ip.encode(), self.port, self.retry, self.version,
                list(self.empty_list), self.sig]

    @classmethod
    def from_rlp(cls, item: list) -> "ValidateRequest":
        blk_num, author, block, ip, port, retry, version, empties = item[:8]
        return cls(block_num=rlp.decode_uint(blk_num), author=bytes(author),
                   block=Block.from_rlp(block), ip=ip.decode(),
                   port=rlp.decode_uint(port), retry=rlp.decode_uint(retry),
                   version=rlp.decode_uint(version),
                   empty_list=tuple(rlp.decode_uint(e) for e in empties),
                   sig=bytes(item[8]) if len(item) > 8 else b"")

    def signing_hash(self) -> bytes:
        """Binds proposer, height, version and the exact proposed block
        (by hash) — retry and transport fields excluded so rebroadcasts
        reuse one signature."""
        return keccak256(b"geec/validate-req" + rlp.encode(
            [self.block_num, self.author, self.block.hash, self.version]))


@dataclass(frozen=True)
class ValidateReply:
    """Acceptor -> proposer ACK, direct plane
    (ref: core/geecCore/Types.go:32-38).  ``fill_blocks`` backfills the
    empty blocks the request asked for (geec_state.go:555-564)."""

    block_num: int
    author: bytes
    accepted: bool = True
    retry: int = 0
    fill_blocks: tuple[Block, ...] = ()
    block_hash: bytes = bytes(32)  # the exact proposal being ACKed
    sig: bytes = b""               # acceptor's signature over signing_hash()

    def to_rlp(self) -> list:
        return [self.block_num, self.author, int(self.accepted), self.retry,
                [b.to_rlp() for b in self.fill_blocks], self.block_hash,
                self.sig]

    @classmethod
    def from_rlp(cls, item: list) -> "ValidateReply":
        blk, author, acc, retry, fills = item[:5]
        return cls(block_num=rlp.decode_uint(blk), author=bytes(author),
                   accepted=bool(rlp.decode_uint(acc)),
                   retry=rlp.decode_uint(retry),
                   fill_blocks=tuple(Block.from_rlp(b) for b in fills),
                   block_hash=bytes(item[5]) if len(item) > 5 else bytes(32),
                   sig=bytes(item[6]) if len(item) > 6 else b"")

    def signing_hash(self) -> bytes:
        """An ACK binds (height, acceptor, verdict, block hash): a vote
        for proposal X must never count for proposal Y."""
        return keccak256(b"geec/ack" + rlp.encode(
            [self.block_num, self.author, int(self.accepted),
             self.block_hash]))


@dataclass(frozen=True)
class QueryReply:
    """Acceptor -> querier, direct plane (ref: core/geecCore/Types.go:42-49).
    ``empty=True`` means "I have no pending block at that height"."""

    block_num: int
    author: bytes
    version: int
    retry: int = 0
    empty: bool = True
    block_hash: bytes = bytes(32)
    sig: bytes = b""  # acceptor's signature over signing_hash()

    def to_rlp(self) -> list:
        return [self.block_num, self.author, self.version, self.retry,
                int(self.empty), self.block_hash, self.sig]

    @classmethod
    def from_rlp(cls, item: list) -> "QueryReply":
        blk, author, version, retry, empty, h = item[:6]
        return cls(block_num=rlp.decode_uint(blk), author=bytes(author),
                   version=rlp.decode_uint(version),
                   retry=rlp.decode_uint(retry),
                   empty=bool(rlp.decode_uint(empty)), block_hash=bytes(h),
                   sig=bytes(item[6]) if len(item) > 6 else b"")

    def signing_hash(self) -> bytes:
        return keccak256(b"geec/query-reply" + rlp.encode(
            [self.block_num, self.author, self.version, int(self.empty),
             self.block_hash]))


@dataclass(frozen=True)
class BlockFetchReq:
    """Backfill: "send me canonical blocks [start, start+count)".

    A node that learns (via a ConfirmBlockMsg) that the quorum is ahead of
    its head asks peers to stream the gap back on the direct plane.  This
    replaces the reference's downloader sync for the Geec capability path
    (SURVEY §5 checkpoint/resume: "full-sync + downloader backfill
    re-joins after downtime")."""

    start: int
    count: int
    ip: str
    port: int

    def to_rlp(self) -> list:
        return [self.start, self.count, self.ip.encode(), self.port]

    @classmethod
    def from_rlp(cls, item: list) -> "BlockFetchReq":
        start, count, ip, port = item
        return cls(start=rlp.decode_uint(start), count=rlp.decode_uint(count),
                   ip=ip.decode(), port=rlp.decode_uint(port))


@dataclass(frozen=True)
class BlocksReply:
    """Backfill payload: contiguous canonical blocks with their stored
    confirm messages attached."""

    blocks: tuple[Block, ...]

    def to_rlp(self) -> list:
        return [[b.to_rlp() for b in self.blocks]]

    @classmethod
    def from_rlp(cls, item: list) -> "BlocksReply":
        (blocks,) = item
        return cls(blocks=tuple(Block.from_rlp(b) for b in blocks))


@dataclass(frozen=True)
class HeadersReply:
    """Header-first sync payload: ``(header, confirm)`` pairs with no
    bodies (the reference's header skeleton,
    eth/downloader/downloader.go:931, with bodies filled by separate
    lanes, queue.go:65-67).  Quorum certificates ride along so a joiner
    batch-verifies the WHOLE gap's signatures in a few large device
    batches before any body arrives — bodies then only need to hash
    onto the pinned skeleton."""

    headers: tuple  # of (Header, ConfirmBlockMsg | None)

    def to_rlp(self) -> list:
        return [[[h.to_rlp(), [] if c is None else c.to_rlp()]
                 for h, c in self.headers]]

    @classmethod
    def from_rlp(cls, item: list) -> "HeadersReply":
        (pairs,) = item
        return cls(headers=tuple(
            (Header.from_rlp(h),
             ConfirmBlockMsg.from_rlp(c) if c else None)
            for h, c in pairs))


@dataclass(frozen=True)
class TxnsMsg:
    """Transaction gossip payload (ref: TxMsg eth/protocol.go:38)."""

    txns: tuple

    def to_rlp(self) -> list:
        return [[t.to_rlp() for t in self.txns]]

    @classmethod
    def from_rlp(cls, item: list) -> "TxnsMsg":
        from eges_tpu.core.types import Transaction

        (txns,) = item
        return cls(txns=tuple(Transaction.from_rlp(t) for t in txns))


@dataclass(frozen=True)
class StateFetchReq:
    """Fast-sync state request (ref role: eth/downloader/statesync.go:1
    state download; GetNodeDataMsg in eth/protocol.go — redesigned at
    ACCOUNT granularity instead of trie-node granularity, since this
    build's snapshots are in-memory account maps, not a node database).

    ``block_num = 0`` lets the SERVER choose the pivot (its head minus a
    stability lag) — the first reply pins it and the joiner keeps asking
    for that block.  ``cursor`` indexes into the pivot snapshot's
    address-sorted account list."""

    block_num: int
    cursor: int
    ip: str
    port: int

    def to_rlp(self) -> list:
        return [self.block_num, self.cursor, self.ip.encode(), self.port]

    @classmethod
    def from_rlp(cls, item: list) -> "StateFetchReq":
        blk, cur, ip, port = item
        return cls(block_num=rlp.decode_uint(blk),
                   cursor=rlp.decode_uint(cur), ip=ip.decode(),
                   port=rlp.decode_uint(port))


@dataclass(frozen=True)
class StateChunkReply:
    """One page of the pivot state snapshot.

    ``accounts`` is a tuple of
    ``(addr, nonce, balance, code_hash, ((hashed_slot, value_rlp)…))``
    in address-sorted order starting at ``cursor``; ``codes`` carries the
    bytecode blobs for any code hashes first referenced in this page.
    Nothing in a reply is trusted: the joiner rebuilds the account and
    storage tries and verifies the final root against a
    quorum-CERTIFIED pivot header before adopting anything."""

    block_num: int
    root: bytes
    cursor: int
    total: int
    accounts: tuple
    codes: tuple

    def to_rlp(self) -> list:
        return [self.block_num, self.root, self.cursor, self.total,
                [[a, n, b, ch, [[k, v] for k, v in slots]]
                 for a, n, b, ch, slots in self.accounts],
                list(self.codes)]

    @classmethod
    def from_rlp(cls, item: list) -> "StateChunkReply":
        blk, root, cur, total, accounts, codes = item
        return cls(
            block_num=rlp.decode_uint(blk), root=bytes(root),
            cursor=rlp.decode_uint(cur), total=rlp.decode_uint(total),
            accounts=tuple(
                (bytes(a), rlp.decode_uint(n), rlp.decode_uint(b),
                 bytes(ch), tuple((bytes(k), bytes(v)) for k, v in slots))
                for a, n, b, ch, slots in accounts),
            codes=tuple(bytes(c) for c in codes))


@dataclass(frozen=True)
class UdpEnvelope:
    """Direct-plane envelope (ref: core/geecCore/Types.go:68-72)."""

    code: int
    author: bytes
    payload: bytes

    def encode(self) -> bytes:
        return rlp.encode([self.code, self.author, self.payload])

    @classmethod
    def decode(cls, data: bytes) -> "UdpEnvelope":
        code, author, payload = rlp.decode(data)
        return cls(code=rlp.decode_uint(code), author=bytes(author),
                   payload=bytes(payload))


_DIRECT_BODY = {
    UDP_EXAMINE_REPLY: ValidateReply,
    UDP_ELECT: ElectMessage,
    UDP_QUERY_REPLY: QueryReply,
    UDP_BLOCKS: BlocksReply,
    UDP_GET_BLOCKS: BlockFetchReq,
    UDP_GET_HEADERS: BlockFetchReq,
    UDP_HEADERS: HeadersReply,
    UDP_GET_STATE: StateFetchReq,
    UDP_STATE: StateChunkReply,
}


def pack_direct(code: int, author: bytes, msg) -> bytes:
    return UdpEnvelope(code=code, author=author,
                       payload=rlp.encode(msg.to_rlp())).encode()


def unpack_direct(data: bytes):
    """-> (code, author, message object)"""
    env = UdpEnvelope.decode(data)
    body = _DIRECT_BODY[env.code].from_rlp(rlp.decode(env.payload))
    return env.code, env.author, body


_GOSSIP_BODY = {
    GOSSIP_VALIDATE_REQ: ValidateRequest,
    GOSSIP_QUERY: QueryBlockMsg,
    GOSSIP_REGISTER_REQ: Registration,
    GOSSIP_CONFIRM_BLOCK: ConfirmBlockMsg,
    GOSSIP_GET_BLOCKS: BlockFetchReq,
    GOSSIP_BLOCKS_REPLY: BlocksReply,
    GOSSIP_TXNS: TxnsMsg,
    GOSSIP_GET_HEADERS: BlockFetchReq,
    GOSSIP_HEADERS_REPLY: HeadersReply,
    GOSSIP_GET_STATE: StateFetchReq,
    GOSSIP_STATE_REPLY: StateChunkReply,
}


def pack_gossip(code: int, msg) -> bytes:
    return rlp.encode([code, msg.to_rlp()])


def unpack_gossip(data: bytes):
    """-> (code, message object)"""
    code, body = rlp.decode(data)
    code = rlp.decode_uint(code)
    return code, _GOSSIP_BODY[code].from_rlp(body)
