"""The Geec consensus node: one event-loop state machine per node.

This is the TPU-native re-architecture of the reference's goroutine soup
— ``GeecState`` + its four loops (``blockLoop``/``handleVerifyReplies``/
``handleQueryReply``/election ``HandleMessage``, core/geec_state.go:315-318),
the engine's blocking ``Seal`` (consensus/geec/geec.go:282-370) and the
ProtocolManager's worker goroutines (eth/handler.go:897-1056) — collapsed
into ONE single-threaded, non-blocking state machine per node with
injectable clock and transport (SURVEY §7 step 3: "replace the
comment-enforced lock soup with event loops and explicit messages").

Everything the reference does with a blocking wait becomes a timer or a
deferred message:

* ``Wb.Wait(blk)`` (condvar)            -> defer queue drained on advance
* ``Seal`` blocking on election/ACKs    -> proposer phase machine + timers
* ``time.Sleep(backoff)``               -> backoff timer
* ``blockLoop`` select timeout ladder   -> block-timeout timer, 3x
  committee re-election then forced empty block (geec_state.go:1140-1180)

The consensus-critical semantics (versioned retries, vote transfer,
confidence, TTL economy, membership windows) follow the reference
line-for-line in *behavior*; citations sit on each method.

Signature verification is where TPUs enter: acceptors verify a proposed
block's signed txns as one device batch before ACKing (the reference's
acceptor replies unconditionally, ``valResult := true``,
core/geec_state.go:545 — verification actually happening is this build's
north-star upgrade), and the insert path batch-recovers senders
(core/state_processor.go:93's per-tx loop, batched).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from dataclasses import dataclass

from eges_tpu.consensus import messages as M
from eges_tpu.consensus.config import (
    ChainGeecConfig, NodeConfig, calc_confidence, ttl_params,
    CONFIDENCE_THRESHOLD,
)
from eges_tpu.consensus.membership import Member, Membership, derive_seed
from eges_tpu.consensus.working_block import (
    WorkingBlock, ELEC_CANDIDATE, ELEC_ELECTED, ELEC_VOTED,
    WB_CURRENT, WB_FUTURE, WB_PASSED,
)
from eges_tpu.core.chain import BlockChain
from eges_tpu.utils import ledger
from eges_tpu.utils import tracing
from eges_tpu.core.types import (
    Block, ConfirmBlockMsg, Header, QueryBlockMsg, Registration, Transaction,
    fake_txn, EMPTY_ADDR, new_block,
)


def addr_to_int(addr: bytes) -> int:
    """Election tie-break key (ref: election/server.go:122-125)."""
    return (int.from_bytes(addr[0:8], "big") + int.from_bytes(addr[8:16], "big")
            + int.from_bytes(addr[16:20], "big")) % (1 << 64)


# Proposer phases
IDLE, ELECTING, VALIDATING, BACKOFF = range(4)


class GeecNode:
    """One consensus participant.

    Wire-in points: ``transport`` must call :meth:`on_gossip` /
    :meth:`on_direct` for inbound traffic; the chain calls
    :meth:`_on_new_block` via its listener hook.  ``clock`` provides
    ``now()`` and ``call_later(delay_s, fn) -> cancelable handle``.
    """

    # Ingress hardening caps: every attacker-fed byte path or container
    # is bounded up front; overflow is shed oldest-first with a counted
    # ``*_dropped`` metric so floods stay visible, cheap, and non-fatal
    # (cf. geth's message-size limits and fetcher/txpool caps).
    INGRESS_MAX_BYTES = 1 << 20       # one datagram's decode budget
    DEFER_MAX = 4096                  # deferred-thunk queue depth
    GEEC_TXN_MAX_BYTES = 1 << 20      # one UDP txn payload
    GEEC_PENDING_MAX = 1 << 14        # pending UDP txn backlog
    REG_PENDING_MAX = 4096            # pending registration requests
    FASTSYNC_MAX_ACCOUNTS = 1 << 20   # fast-sync state staging rows
    HEIGHT_WINDOW = 8192              # retained per-height bookkeeping

    def __init__(self, chain: BlockChain, clock, transport,
                 node_cfg: NodeConfig, chain_cfg: ChainGeecConfig, *,
                 mine: bool = True, verifier=None, log=None):
        self.chain = chain
        self.clock = clock
        self.transport = transport
        self.cfg = node_cfg
        self.ccfg = chain_cfg
        self.mine = mine
        self.verifier = verifier
        self.coinbase = node_cfg.coinbase
        self._log = log or (lambda *a, **k: None)

        # structured protocol event journal (utils/journal.py): one per
        # node, virtual-time aware, shared with this node's chain /
        # membership / txpool so every control-plane decision lands in
        # one replayable stream
        from eges_tpu.utils.journal import Journal
        self.journal = Journal(node=self.coinbase.hex()[:8],
                               clock=clock.now)
        # ingress provenance ledger (utils/ledger.py): per-origin decayed
        # cost counters charged by every layer this node drives — the
        # entry points below bind it as the ambient charge target, and
        # each committed block journals one ingress_ledger snapshot
        self.ledger = ledger.IngressLedger(clock=clock.now)
        # a VerifierScheduler (crypto/scheduler.py) journals its flush
        # decisions; a cluster-shared scheduler lands in the stream of
        # the FIRST node that adopts it (the device owner's view)
        if verifier is not None and \
                getattr(verifier, "journal", b"") is None:
            verifier.journal = self.journal
        self.elections_won = 0
        self.elections_lost = 0
        self._last_commit_t = clock.now()
        chain.journal = self.journal

        # signed-vote mode (ChainGeecConfig.signed_votes): every election
        # vote / ACK / query reply / confirm carries a secp256k1 signature
        # and quorum tallies run through the device batch verifier —
        # BASELINE config 3's "vote-sig batch verify on TPU"
        self._signing = bool(chain_cfg.signed_votes)
        if self._signing and mine and len(node_cfg.privkey) != 32:
            raise ValueError("signed_votes chain requires a 32-byte privkey")

        tp = ttl_params(node_cfg.total_nodes)
        self.membership = Membership(node_cfg.n_candidates,
                                     node_cfg.n_acceptors, **tp)
        self.membership.journal = self.journal
        # genesis bootstrap membership (ref: geec_state.go:275-289)
        for bn in chain_cfg.bootstrap:
            self.membership.add(Member(addr=bn.account, ip=bn.ip, port=bn.port,
                                       referee=bn.account, joined_block=0,
                                       ttl=tp["initial_ttl"]))

        # One re-entrant monitor guards every mutable consensus field
        # below.  The state machine is single-threaded on the event
        # loop, but the RPC server runs its handlers on another thread
        # and enters through submit_txns / broadcast_txns /
        # request_registration — every entry point (inbound dispatch,
        # chain listener, timer fire, RPC surface) takes this lock, so
        # those two threads serialize.  The attached TxPool shares THIS
        # lock (see the txpool setter) — one lock domain, no ordering
        # hazards between pool window flushes and RPC submissions.
        self._lock = threading.RLock()
        self.wb = WorkingBlock(self.coinbase)
        self.trust_rands: dict[int, int] = {0: 0}
        self.pending_blocks: dict[int, Block] = {}
        self.max_confirmed_block = 0
        self.unconfirmed: list[Block] = []
        self.empty_block_list: list[int] = []
        self.pending_regs: dict[bytes, Registration] = {}
        self.registered = self.coinbase in self.membership
        # deque, not list: the flood path sheds oldest-first and a
        # list.pop(0) there is O(backlog) per shed row.  The cap check
        # stays explicit (no maxlen=) — eviction must bill the ledger
        # and bump the dropped counter, and chaos scenarios retune the
        # cap per instance at runtime.
        self.pending_geec_txns: deque[Transaction] = deque()
        self._proposal_geec_txns: list[Transaction] = []
        self._txn_seen: set[bytes] = set()
        self._sync_target = 0
        self._sync_progress = False
        # fetched-ahead staging: certified blocks beyond the chain's
        # out-of-order window wait here (the downloader queue role,
        # ref: eth/downloader/queue.go — bounded, lowest numbers kept)
        self._sync_stash: dict[int, Block] = {}
        # header-first skeleton (ref: eth/downloader/downloader.go:931):
        # number -> header hash whose quorum certificate batch-verified
        # ahead of its body; bodies hashing onto a pin skip per-reply
        # certificate verification, mismatches drop
        self._sync_skel: dict[int, bytes] = {}
        self._skel_req_upto = 0  # header-request watermark
        # fast-sync (statesync.go role): live download state, one-shot
        # per session — a failed/poisoned attempt falls back to full
        # replay rather than looping against a byzantine serving peer
        self._fs: dict | None = None
        self._fs_done = False
        # serving peers whose pages failed the pivot root check: never
        # re-anchor a download on one (byzantine-server quarantine)
        self._fs_blacklist: set[bytes] = set()
        self._snap_cache: tuple | None = None  # serving-side page cache
        # per-origin token buckets for the snapshot-serving plane, so a
        # flood of StateFetchReqs cannot turn this node into a DoS
        # amplifier; bounded-by: SERVE_TOKENS_MAX (oldest evicted)
        self._serve_tokens: dict[str, tuple[float, float]] = {}
        self.geec_txn_sink = None  # app-layer callback for confirmed geec txns
        self.txpool = None  # optional TxPool; proposals drain it
        #                     (property: attaching one wires the journal)
        # columnar ingest hook (ROADMAP item 5): an injectable
        # txns -> TxColumns extractor (eges_tpu.ingress.columns_of).
        # Injected rather than imported — consensus sits below the
        # ingress package in the layer map — by whatever wires the node
        # (sim/cluster.py, the node runner).  When set, multi-txn
        # gossip bundles admit window-granular via add_remotes_window;
        # singletons keep the legacy per-tx path.
        self.columnarize = None

        # deferred messages for future working blocks (Wait() analogue);
        # deque for the same O(1) oldest-first shedding as above
        self._deferred: deque[tuple[int, object]] = deque()  # (blk_num, thunk)

        # proposer phase state
        self._phase = IDLE
        self._proposal: Block | None = None
        self._proposal_version = 0
        self._validate_req: M.ValidateRequest | None = None
        self._seal_t0 = 0.0
        self._elect_t = 0.0
        self._ack_t = 0.0
        # commit-anatomy phase splits for the in-flight proposal: the
        # election and ack-quorum durations land here when each phase
        # completes, and _finish_seal journals them as ONE
        # ``commit_anatomy`` stage="seal" event so the critical-path
        # assembler (harness/anatomy.py) can segment seal time without
        # re-joining three breakdown spans
        self._election_dt = 0.0
        self._ack_dt = 0.0

        # timers
        self._timers: dict[str, object] = {}
        self._timeout_times = 0

        chain.add_listener(self._on_new_block)
        # restart path: rebuild membership/trust-rand/working-block state
        # from the durable chain (blocks already canonical are final here;
        # the journal stays quiet — replayed history is not live protocol
        # activity and would double-count in the observatory).  When the
        # chain anchored on a root-verified checkpoint sidecar carrying a
        # consensus section, seed the soft state from it and replay only
        # the tail past the anchor — O(tail), not O(chain).  A missing
        # block below an anchorless pivot (fast-synced store) is skipped:
        # the live node never ingested it either.
        self.journal.enabled = False
        anchor = 0
        cons = getattr(chain, "snapshot_consensus", None)
        if cons is not None and getattr(chain, "snapshot_anchor", 0) > 0:
            anchor = chain.snapshot_anchor
            self._seed_from_checkpoint(cons)
        replayed = 0
        for n in range(anchor + 1, chain.height() + 1):
            blk = chain.get_block_by_number(n)
            if blk is None:
                continue
            self._ingest_block(blk, replay=True)
            replayed += 1
        self.journal.enabled = True
        self.max_confirmed_block = chain.height()
        if self.coinbase in self.membership:
            self.registered = True
        if chain.height() > 0:
            from eges_tpu.utils.metrics import DEFAULT as metrics
            self.journal.record("statesync_restart", blk=chain.height(),
                                snapshot_blk=anchor, replayed=replayed)
            metrics.gauge("statesync.restart_replayed").set(replayed)

    def _seed_from_checkpoint(self, cons: dict) -> None:
        """Re-seed consensus soft state from a checkpoint's consensus
        section.  Existing entries (the genesis bootstrap members added
        above) are overwritten in place — routing them through
        ``Membership.add`` would take its RENEWAL path and stack TTLs
        the live run never granted."""
        for (addr, referee, ip, port, joined, ttl, renewed) in \
                cons.get("members", ()):
            m = self.membership.get(addr)
            if m is None:
                self.membership.add(Member(addr=addr, ip=ip, port=port,
                                           referee=referee,
                                           joined_block=joined, ttl=ttl,
                                           renewed_times=renewed))
                m = self.membership.get(addr)
                if m is None:
                    continue
            m.ip, m.port, m.referee = ip, port, referee
            m.joined_block, m.ttl = joined, ttl
            m.renewed_times = renewed
        self.trust_rands.update(cons.get("trust_rands", ()))
        self.empty_block_list = list(cons.get("empty_blocks", ()))
        # the restored queue stays bounded-by: SYNC_STASH_MAX — a
        # damaged sidecar must not inflate the unconfirmed window
        for n in cons.get("unconfirmed", ()):
            if len(self.unconfirmed) >= self.SYNC_STASH_MAX:
                break
            blk = self.chain.get_block_by_number(n)
            if blk is not None:
                self.unconfirmed.append(blk)
        if cons.get("registered"):
            self.registered = True

    # ------------------------------------------------------------------
    # vote authentication (signed-vote mode)
    # ------------------------------------------------------------------

    def _sign(self, sighash: bytes) -> bytes:
        if not self._signing or len(self.cfg.privkey) != 32:
            return b""
        from eges_tpu.crypto import secp256k1 as host
        return host.ecdsa_sign(sighash, self.cfg.privkey)

    def _verify_single(self, sighash: bytes, sig: bytes,
                       author: bytes) -> bool:
        """One-off signature check (candidacies, proposals, confirms).

        With a VerifierScheduler wired (sim cluster / node service),
        ``recover_signers`` delegates into its cache + coalescing
        window, so a lone check is a cache hit (gossip re-delivery), a
        row in someone else's batch, or one host recover — never the
        padded 1-row device dispatch this path used to cost.  Consensus
        blocks on this check, so it rides the scheduler's high-priority
        window class."""
        if not self._signing:
            return True
        if len(sig) != 65:
            return False
        from eges_tpu.crypto.verify_host import recover_signers
        return recover_signers([(sighash, sig)], self.verifier,
                               priority="consensus")[0] == author

    def _recover_entries(self, entries) -> list:
        """Recover the signer of each ``(author, sighash, sig)`` entry in
        ONE verifier batch (or one scheduler window, where the cache
        strips already-seen votes before the device sees them); per-entry
        result is the claimed author when the signature checks out, else
        None.  With signing off every entry passes.  Election acks and
        QC checks block consensus progress, so the rows enter the
        scheduler's consensus priority class: they flush ahead of bulk
        tx-ingest rows and their windows preempt bulk windows at lane
        placement."""
        if not self._signing:
            return [a for a, _, _ in entries]
        from eges_tpu.crypto.verify_host import recover_signers
        rec = recover_signers([(h, s) for _, h, s in entries], self.verifier,
                              priority="consensus")
        return [a if r == a else None
                for (a, _, _), r in zip(entries, rec)]

    def _verify_quorum(self, entries) -> dict[bytes, bytes]:
        """Quorum tally over possibly-multiple entries per author:
        returns ``{author: verified_sig}`` for every author with at least
        one valid entry (sig is ``b""`` when signing is off)."""
        out: dict[bytes, bytes] = {}
        for (a, _, s), r in zip(entries, self._recover_entries(entries)):
            if r is not None and a not in out:
                out[a] = s if self._signing else b""
        return out

    # ------------------------------------------------------------------
    # timers
    # ------------------------------------------------------------------

    def _set_timer(self, name: str, delay_s: float, fn) -> None:
        self._cancel_timer(name)

        def fire():
            # timer callbacks join the same monitor as the message and
            # RPC entry points; re-entrancy keeps nested arming from
            # already-locked regions cheap
            with self._lock:
                fn()

        self._timers[name] = self.clock.call_later(delay_s, fire)

    def _cancel_timer(self, name: str) -> None:
        h = self._timers.pop(name, None)
        if h is not None:
            h.cancel()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            self._arm_block_timeout()
            if self.mine:
                if not self.registered:
                    self._start_registration(renew=0)
                self._try_propose()

    def stop(self) -> None:
        with self._lock:
            for name in list(self._timers):
                self._cancel_timer(name)

    def _breakdown(self, phase: str, dt: float, **kw) -> None:
        """One phase timing, three sinks: the legacy ``[Breakdown]`` log
        line (only under --breakdown, so grep.py-style harvesting keeps
        working), a percentile histogram, and a finished span."""
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.histogram(f"consensus.phase_seconds;phase={phase}").observe(dt)
        tracing.DEFAULT.record_span(f"consensus.{phase}", dt,
                                    node=self.coinbase.hex()[:8], **kw)
        if self.cfg.breakdown:
            self._log("breakdown", phase=phase, dt=dt, **kw)

    def _bump_version(self, version: int) -> None:
        """Single funnel for version bumps so the journal sees every
        failed round (the observatory's failed-round rate counts
        these).  version 0 is the normal first attempt of a block, not
        a failed round — it stays out of the journal."""
        self.wb.bump_version(version)
        if version > 0:
            self.journal.record("version_bump", blk=self.wb.blk_num,
                                version=version)

    @property
    def txpool(self):
        return self._txpool

    @txpool.setter
    def txpool(self, pool) -> None:
        self._txpool = pool
        if pool is not None:
            pool.event_journal = self.journal
            # one lock domain for node + pool: the RPC thread holds the
            # node lock through submit_txns -> add_locals while the
            # clock thread's window flush re-enters the node through the
            # on_admitted broadcast hook — two separate locks would be
            # taken in opposite orders on those paths (deadlock); one
            # shared re-entrant lock serializes both.
            pool._lock = self._lock

    # ------------------------------------------------------------------
    # inbound dispatch
    # ------------------------------------------------------------------

    def on_gossip(self, data: bytes) -> None:  # ingress-entry
        ctx, data = tracing.extract(data)
        # ingress provenance: every cost this datagram incurs (pool
        # admits/rejects, verifier rows, deferred/duplicate drops) bills
        # to the delivering peer stamped by the transport fabric
        src = ledger.current_peer()
        with self._lock, tracing.DEFAULT.activate(ctx), \
                ledger.bind(self.ledger, f"peer:{src}" if src else "net"):
            self._on_gossip(data)

    def _on_gossip(self, data: bytes) -> None:
        if len(data) > self.INGRESS_MAX_BYTES:
            # decode budget enforced before ANY byte is parsed: an
            # oversized datagram costs one length check, billed to its
            # origin, and never reaches RLP (DoS-resistance contract)
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("consensus.ingress_oversized").inc()
            ledger.charge(drops=1)
            self._log("oversized gossip dropped", nbytes=len(data))
            return
        if not self._state_reply_fits(data):
            return
        try:
            code, msg = M.unpack_gossip(data)
        except Exception as exc:
            # malformed datagram from a peer must not kill the loop
            self._log("malformed gossip", nbytes=len(data), err=repr(exc))
            return
        try:
            self._dispatch_gossip(code, msg)
        except Exception as exc:
            # a datagram that unpacks but whose payload fails deeper
            # decode/auth (bit-flip corruption) is a peer-supplied input:
            # reject it, never crash the node (DoS-resistance contract)
            self._log("gossip handler rejected", code=code, err=repr(exc))

    def _dispatch_gossip(self, code: int, msg) -> None:
        if code == M.GOSSIP_VALIDATE_REQ:
            self._handle_validate_request(msg)
        elif code == M.GOSSIP_QUERY:
            self._handle_query(msg)
        elif code == M.GOSSIP_REGISTER_REQ:
            self._append_reg_req(msg)
        elif code == M.GOSSIP_CONFIRM_BLOCK:
            self._handle_confirm(msg)
        elif code == M.GOSSIP_GET_BLOCKS:
            self._serve_block_fetch(msg)
        elif code == M.GOSSIP_BLOCKS_REPLY:
            self._handle_blocks_reply(msg)
        elif code == M.GOSSIP_GET_HEADERS:
            self._serve_header_fetch(msg)
        elif code == M.GOSSIP_HEADERS_REPLY:
            self._handle_headers_reply(msg)
        elif code == M.GOSSIP_GET_STATE:
            self._serve_state_fetch(msg)
        elif code == M.GOSSIP_STATE_REPLY:
            # gossip replies carry no authenticated author; the pinned
            # server check in the handler accepts them only when they
            # answer the cursor this node actually asked for
            self._handle_state_chunk(msg, author=b"")
        elif code == M.GOSSIP_TXNS:
            self._handle_txns(msg)

    def on_direct(self, data: bytes) -> None:  # ingress-entry
        ctx, data = tracing.extract(data)
        src = ledger.current_peer()
        with self._lock, tracing.DEFAULT.activate(ctx), \
                ledger.bind(self.ledger, f"peer:{src}" if src else "net"):
            self._on_direct(data)

    def _on_direct(self, data: bytes) -> None:
        if len(data) > self.INGRESS_MAX_BYTES:
            # same decode budget as the gossip plane
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("consensus.ingress_oversized").inc()
            ledger.charge(drops=1)
            self._log("oversized direct dropped", nbytes=len(data))
            return
        if not self._state_reply_fits(data):
            return
        try:
            code, author, msg = M.unpack_direct(data)
        except Exception as exc:
            # malformed/unauthenticated datagram: drop, but leave a trace
            self._log("malformed direct", nbytes=len(data), err=repr(exc))
            return
        try:
            self._dispatch_direct(code, msg, author)
        except Exception as exc:
            # same contract as the gossip plane: corrupted-but-unpackable
            # payloads get rejected by the handler, not fatal
            self._log("direct handler rejected", code=code, err=repr(exc))

    def _state_reply_fits(self, data: bytes) -> bool:
        """Pre-decode byte cap for state-sync replies: a state page is
        the one message class whose legitimate size dwarfs every other
        frame, so the global INGRESS_MAX_BYTES budget would let a
        byzantine server feed ~1 MiB of junk per datagram into the RLP
        decoder.  Peek ONLY the leading message code (no body decode)
        and drop oversized state replies before any account parses;
        bounded-by: STATE_REPLY_MAX_BYTES."""
        if len(data) <= self.STATE_REPLY_MAX_BYTES:
            return True
        from eges_tpu.core import rlp as rlp_mod
        code = rlp_mod.peek_first_uint(data)
        if code in (M.GOSSIP_STATE_REPLY, M.UDP_STATE):
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("statesync.oversized_reply").inc()
            ledger.charge(drops=1)
            self._log("oversized state reply dropped", nbytes=len(data))
            return False
        return True

    def _dispatch_direct(self, code: int, msg, author: bytes = b"") -> None:
        if code == M.UDP_ELECT:
            self._handle_elect_message(msg)
        elif code == M.UDP_EXAMINE_REPLY:
            self._handle_validate_reply(msg)
        elif code == M.UDP_QUERY_REPLY:
            self._handle_query_reply(msg)
        elif code == M.UDP_BLOCKS:
            self._handle_blocks_reply(msg)
        elif code == M.UDP_GET_BLOCKS:
            self._serve_block_fetch(msg)
        elif code == M.UDP_GET_HEADERS:
            self._serve_header_fetch(msg)
        elif code == M.UDP_HEADERS:
            self._handle_headers_reply(msg)
        elif code == M.UDP_GET_STATE:
            self._serve_state_fetch(msg)
        elif code == M.UDP_STATE:
            self._handle_state_chunk(msg, author=author)

    def on_geec_txn(self, payload: bytes) -> None:  # ingress-entry
        """UDP txn ingest (ref: consensus/geec/geec_api.go:28-41)."""
        from eges_tpu.core.types import geec_txn
        from eges_tpu.utils.metrics import DEFAULT as metrics
        if len(payload) > self.GEEC_TXN_MAX_BYTES:
            metrics.counter("consensus.geec_txn_dropped").inc()
            ledger.charge(drops=1)
            return
        with self._lock:
            if len(self.pending_geec_txns) >= self.GEEC_PENDING_MAX:
                # backlog full: shed the oldest so a txn flood cannot
                # pin memory ahead of the next proposal drain — O(1)
                # on the deque even at flood scale
                self.pending_geec_txns.popleft()
                metrics.counter("consensus.geec_txn_dropped").inc()
                ledger.charge(drops=1)
            self.pending_geec_txns.append(geec_txn(payload))

    # defer a thunk until the working block reaches ``blk`` (Wait analogue)
    def _defer(self, blk: int, thunk) -> None:
        from eges_tpu.utils.metrics import DEFAULT as metrics
        if len(self._deferred) >= self.DEFER_MAX:
            # depth cap: a peer stuffing far-future waits evicts the
            # oldest deferral instead of growing the queue unboundedly
            self._deferred.popleft()
            metrics.counter("consensus.deferred_dropped").inc()
            ledger.charge(drops=1)
        self._deferred.append((blk, thunk))
        # a deferred message is buffered work the sender imposed on us —
        # billed to the ambient ingress origin (no-op on internal paths)
        ledger.charge(deferred=1)
        metrics.gauge("consensus.deferred_depth").set(len(self._deferred))

    def _drain_deferred(self) -> None:
        ready = [(b, t) for (b, t) in self._deferred if b <= self.wb.blk_num]
        self._deferred = deque((b, t) for (b, t) in self._deferred
                               if b > self.wb.blk_num)
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.gauge("consensus.deferred_depth").set(len(self._deferred))
        if ready:
            self.journal.record("deferred_drain", blk=self.wb.blk_num,
                                drained=len(ready))
        for b, t in ready:
            if b == self.wb.blk_num:
                t()

    # ------------------------------------------------------------------
    # trust rand / committee helpers
    # ------------------------------------------------------------------

    def seed_for(self, blk_num: int) -> int | None:
        """Committee seed for height ``blk_num`` = TrustRand(blk_num-1).
        The reference stubs GetTrustRand to return the block number
        (core/geec_state.go:156-171); here the real header-recorded rand
        is used — the stub's determinism comes from the simulator's
        seeded PRNGs instead."""
        return self.trust_rands.get(blk_num - 1)

    def is_committee(self, blk_num: int, version: int = 0) -> bool:
        seed = self.seed_for(blk_num)
        if seed is None:
            return False
        return self.membership.is_committee(self.coinbase, seed, version)

    def is_acceptor(self, blk_num: int) -> bool:
        seed = self.seed_for(blk_num)
        if seed is None:
            return False
        return self.membership.is_acceptor(self.coinbase, seed)

    # ------------------------------------------------------------------
    # proposer pipeline (the event-driven Seal, ref: geec.go:282-370)
    # ------------------------------------------------------------------

    def _try_propose(self, version: int = 0) -> None:
        if not self.mine or self._phase != IDLE:
            return
        h = self.wb.blk_num
        if not self.is_committee(h, version):
            return  # ErrNoCommittee path (geec.go:262): stay follower
        self._seal_t0 = self.clock.now()
        self._start_election(h, version)

    def _start_election(self, blk_num: int, version: int) -> None:
        """(ref: ElectForProposer geec_state.go:606-651 + Elect
        election_go.go:37-175)"""
        wb = self.wb
        if blk_num != wb.blk_num:
            return
        seed = self.seed_for(blk_num)
        committee = self.membership.committee(seed, version)
        if version > wb.max_version:
            self._bump_version(version)
        elif wb.elect_state == ELEC_VOTED:
            return  # already voted on this version (election_go.go:56-59)
        wb.n_candidates = len(committee)
        wb.election_threshold = self.membership.election_threshold(len(committee))
        self._phase = ELECTING
        self._proposal_version = version
        self._elect_t = self.clock.now()
        self.journal.record("election_started", blk=blk_num, version=version,
                            committee=len(committee),
                            threshold=wb.election_threshold)
        self._election_retry(blk_num, version, committee, retry=0)

    def _election_retry(self, blk_num: int, version: int, committee,
                        retry: int) -> None:
        wb = self.wb
        if (blk_num != wb.blk_num or wb.max_version > version
                or wb.elect_state == ELEC_VOTED):
            self._abort_proposal()
            return
        if (len(wb.supporters) >= wb.election_threshold
                and self._on_elected()):
            return
        em = M.ElectMessage(code=M.MSG_ELECT, block_num=blk_num,
                            author=self.coinbase, rand=wb.my_rand,
                            version=version, retry=retry,
                            ip=self.cfg.consensus_ip,
                            port=self.cfg.consensus_port)
        em = dataclasses.replace(em, sig=self._sign(em.signing_hash()))
        payload = M.pack_direct(M.UDP_ELECT, self.coinbase, em)
        for m in committee:
            if m.addr == self.coinbase:
                continue  # never to self (election_go.go:83)
            self.transport.send_direct(m.ip, m.port, payload)
        # 1 s retry loop (election_go.go:150)
        self._set_timer("election", 1.0,
                        lambda: self._election_retry(blk_num, version,
                                                     committee, retry + 1))

    def _on_elected(self) -> bool:
        """Threshold of votes reached -> verify the vote signatures as one
        device batch, then build + broadcast the proposal.  Returns False
        (election continues) if pruning forged votes drops the count back
        below the threshold."""
        wb = self.wb
        if self._phase != ELECTING:
            return False
        if self._signing:
            items = [(a, h, s) for a in wb.supporters
                     for (h, s) in wb.supporter_votes.get(a, ())]
            valid = self._verify_quorum(items)
            for a in list(wb.supporters):
                if a not in valid:
                    wb.supporters.discard(a)
                    wb.supporter_votes.pop(a, None)
            if len(wb.supporters) < wb.election_threshold:
                return False
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("consensus.elected").inc()
        wb.elect_state = ELEC_ELECTED
        wb.is_proposer = True
        wb.validate_threshold = self.membership.validate_threshold()
        self._cancel_timer("election")
        dt = self.clock.now() - self._elect_t
        self._breakdown("election", dt, blk=wb.blk_num)
        self._election_dt = dt
        self.elections_won += 1
        self.journal.record("election_won", blk=wb.blk_num,
                            version=self._proposal_version, dt=dt,
                            votes=len(wb.supporters))
        if self._proposal_version > 0:
            # recovered leader: query what happened first
            self._start_query(wb.blk_num, self._proposal_version)
            return True
        self._build_and_validate(wb.blk_num, self._proposal_version)
        return True

    def _build_proposal(self, blk_num: int) -> Block:
        """Assemble header+body (ref: Prepare geec.go:228-264 + Seal's txn
        attachment geec.go:319-339 + Finalize geec.go:268-279)."""
        parent = self.chain.head()
        regs = tuple(self.pending_regs[a] for a in
                     sorted(self.pending_regs)[: self.ccfg.max_reg_per_blk])
        n = min(len(self.pending_geec_txns), self.cfg.txn_per_block)
        geec_txns = tuple(self.pending_geec_txns.popleft()
                          for _ in range(n))
        # remember the drained txns so an aborted proposal re-queues them
        # instead of silently dropping UDP-ingested transactions
        self._proposal_geec_txns = list(geec_txns)
        fakes = tuple(fake_txn(self.cfg.txn_size, seq=i)
                      for i in range(self.cfg.txn_per_block - n))
        # signed txns execute: dry-run them on the head state for the
        # header's state/receipt/gas commitments (L3; worker.go:463-467)
        txs = (tuple(self.txpool.pending_txns(
            self.cfg.txn_per_block, state=self.chain.head_state()))
               if self.txpool is not None else ())
        # the header's time/difficulty are fixed BEFORE the preview so
        # the dry-run executes with the exact BlockCtx validation will
        # re-derive from the sealed header (TIMESTAMP/DIFFICULTY reads
        # must see the same values, or the state root won't reproduce)
        difficulty = 100
        blk_time = max(int(self.clock.now()), parent.header.time + 1)
        if txs:
            from eges_tpu.core.evm import BlockCtx
            ctx = BlockCtx(coinbase=self.coinbase, number=blk_num,
                           time=blk_time, difficulty=difficulty)
            txs, root, receipt_hash, gas_used, bloom = \
                self.chain.execute_preview(txs, self.coinbase, ctx=ctx)
        else:
            from eges_tpu.core.trie import EMPTY_ROOT
            root, receipt_hash, gas_used = (parent.header.root, EMPTY_ROOT, 0)
            bloom = bytes(256)
        header = Header(
            parent_hash=parent.hash, number=blk_num,
            coinbase=self.coinbase, difficulty=difficulty,
            time=blk_time,
            root=root, receipt_hash=receipt_hash, gas_used=gas_used,
            bloom=bloom, regs=regs,
            trust_rand=self.wb._rng.getrandbits(64),  # seed for NEXT block
        )
        return new_block(header, txs=txs, geec_txns=geec_txns,
                         fake_txns=fakes)

    def _build_and_validate(self, blk_num: int, version: int) -> None:
        if blk_num != self.wb.blk_num:
            self._abort_proposal()
            return
        self._proposal = self._build_proposal(blk_num)
        self.journal.record("proposal_built", blk=blk_num, version=version,
                            txns=len(self._proposal.transactions),
                            geec_txns=len(self._proposal.geec_txns))
        req = M.ValidateRequest(
            block_num=blk_num, author=self.coinbase, block=self._proposal,
            ip=self.cfg.consensus_ip, port=self.cfg.consensus_port,
            retry=0, version=version,
            empty_list=tuple(self.empty_block_list),
        )
        req = dataclasses.replace(req, sig=self._sign(req.signing_hash()))
        self._ask_for_ack(req)

    def _ask_for_ack(self, req: M.ValidateRequest) -> None:
        """(ref: AskForAck geec.go:373-419 — gossip the full block, retry
        on validate_timeout with bumped retry counter)"""
        self._phase = VALIDATING
        self._validate_req = req
        self.wb.validate_replies.clear()
        self.wb.validate_cert = {}
        self.wb.validate_succeeded = False
        self._ack_t = self.clock.now()
        self.journal.record("validate_request", blk=req.block_num,
                            version=req.version,
                            threshold=self.wb.validate_threshold)
        self._validate_retry(req.block_num, req.version, 0)

    def _validate_retry(self, blk_num: int, version: int, retry: int) -> None:
        if blk_num != self.wb.blk_num or self._phase != VALIDATING:
            return
        if retry > 0:
            self.journal.record("validate_retry", blk=blk_num,
                                version=version, retry=retry)
        req = dataclasses.replace(self._validate_req, retry=retry)
        self.transport.gossip(M.pack_gossip(M.GOSSIP_VALIDATE_REQ, req))
        self._set_timer("validate", self.ccfg.validate_timeout_ms / 1e3,
                        lambda: self._validate_retry(blk_num, version,
                                                     retry + 1))

    def _handle_validate_reply(self, reply: M.ValidateReply) -> None:
        """Tally ACKs (ref: handleVerifyReplies geec_state.go:1184-1227).

        Only replies from the seeded acceptor window for this height may
        count toward the quorum (the reference gates acceptor identity via
        IsValidator on the reply path, geec_state.go:439-521) — otherwise
        a single peer could fabricate a validate quorum."""
        wb = self.wb
        if reply.block_num != wb.blk_num:
            return
        seed = self.seed_for(reply.block_num)
        if seed is None or not self.membership.is_acceptor(reply.author, seed):
            return
        # backfilled empty blocks ride the same certification gate as the
        # sync plane — an unverified reply must not inject history
        fills = (self._filter_certified(list(reply.fill_blocks))
                 if self._signing else reply.fill_blocks)
        for blk in fills:
            self.chain.offer(blk)
        if not reply.accepted:
            return  # an explicit NACK never counts toward the quorum
        if (self._proposal is not None
                and reply.block_hash != self._proposal.hash):
            return  # an ACK binds a specific block; not ours -> not ours
        # up to 2 distinct stored replies per author (spoof-squat defense)
        lst = wb.validate_replies.setdefault(reply.author, [])
        if len(lst) < 2 and all(r.sig != reply.sig for r in lst):
            lst.append(reply)
        if (len(wb.validate_replies) >= wb.validate_threshold
                and not wb.validate_succeeded and self._phase == VALIDATING):
            if self._signing:
                # the config-3 batch point: recover every collected ACK
                # signature in ONE device call, prune forgeries, and only
                # then trip the quorum.  The verified signatures become
                # the confirm's quorum certificate.
                items = [(r.author, r.signing_hash(), r.sig)
                         for rl in wb.validate_replies.values() for r in rl]
                cert = self._verify_quorum(items)
                for a in list(wb.validate_replies):
                    if a not in cert:
                        del wb.validate_replies[a]
                if len(wb.validate_replies) < wb.validate_threshold:
                    return  # keep collecting; retry loop re-solicits
                wb.validate_cert = cert
            wb.validate_succeeded = True
            self._cancel_timer("validate")
            dt = self.clock.now() - self._ack_t
            self._breakdown("ack", dt, blk=wb.blk_num)
            self._ack_dt = dt
            self.journal.record("validate_quorum", blk=wb.blk_num, dt=dt,
                                acks=len(wb.validate_replies))
            self._phase = BACKOFF
            supporters = tuple(wb.validate_replies.keys())
            self._set_timer("backoff", self.ccfg.backoff_time_ms / 1e3,
                            lambda: self._finish_seal(supporters))

    def _finish_seal(self, supporters: tuple[bytes, ...]) -> None:
        """Confirm + self-insert + broadcast (ref: Seal tail geec.go:356-368
        + worker.wait/minedBroadcastLoop eth/handler.go:1183-1209)."""
        block = self._proposal
        if block is None or block.number != self.wb.blk_num:
            self._abort_proposal()
            return
        parent = self.chain.head()
        parent_conf = parent.confirm.confidence if parent.confirm else 0
        confirm = ConfirmBlockMsg(
            block_number=block.number, hash=block.hash,
            confidence=calc_confidence(parent_conf), supporters=supporters,
            empty_block=False,
            supporter_sigs=tuple(self.wb.validate_cert.get(a, b"")
                                 for a in supporters)
            if self._signing else ())
        confirm = dataclasses.replace(confirm,
                                      sig=self._sign(confirm.signing_hash()))
        sealed = block.with_confirm(confirm)
        self._phase = IDLE
        self._proposal = None
        self._proposal_geec_txns = []  # included in the sealed block
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("consensus.sealed").inc()
        seal_s = self.clock.now() - self._seal_t0
        self._breakdown("seal_total", seal_s, blk=block.number)
        # commit-anatomy seal stage: the proposer-side phase split of
        # this block's seal, on the virtual clock.  t_seal_start lets
        # the assembler place the segment absolutely; election/ack are
        # the measured sub-phases, the remainder is build/backoff.
        self.journal.record(
            "commit_anatomy", blk=block.number, stage="seal",
            t_seal_start=round(self._seal_t0, 6),
            seal_s=round(seal_s, 6),
            election_s=round(self._election_dt, 6),
            ack_s=round(self._ack_dt, 6))
        self.chain.offer(sealed)  # our own insert funnel
        self.transport.gossip(M.pack_gossip(M.GOSSIP_CONFIRM_BLOCK, confirm))

    def _abort_proposal(self) -> None:
        if self._phase != IDLE:
            # only a live proposal attempt journals an abort — the
            # belt-and-braces calls on every block ingest would be noise
            self.journal.record("proposal_aborted", blk=self.wb.blk_num,
                                phase=self._phase)
        self._phase = IDLE
        self._proposal = None
        drained = getattr(self, "_proposal_geec_txns", None)
        if drained:
            # an aborted proposal returns its geec txns to the front of
            # the queue; duplicates vs a block that actually included
            # them are removed again at ingest time
            self.pending_geec_txns.extendleft(reversed(drained))
        self._proposal_geec_txns = []
        self._cancel_timer("election")
        self._cancel_timer("validate")
        self._cancel_timer("backoff")
        self._cancel_timer("query")

    # ------------------------------------------------------------------
    # election message handling (ref: handleElectMessage
    # election_go.go:178-310)
    # ------------------------------------------------------------------

    def _handle_elect_message(self, em: M.ElectMessage) -> None:
        wb = self.wb
        verdict = wb.classify(em.block_num)
        if verdict == WB_PASSED:
            return
        if verdict == WB_FUTURE:
            self._defer(em.block_num, lambda: self._handle_elect_message(em))
            return
        if wb.max_version > em.version:
            return  # old version (election_go.go:205)
        # Elections are a committee-only protocol: both candidacies and
        # votes must come from the seeded committee window for this
        # height/version, or one outside peer could seed itself as
        # delegator / fabricate an election quorum.
        seed = self.seed_for(em.block_num)
        if (seed is None
                or not self.membership.is_committee(em.author, seed,
                                                    em.version)):
            return
        if wb.max_version < em.version:
            self._bump_version(em.version)
            if self._phase in (ELECTING, VALIDATING):
                self._abort_proposal()

        if em.code == M.MSG_ELECT:
            # a forged candidacy would steal this node's vote — verify
            # the candidate's signature before voting for it
            if not self._verify_single(em.signing_hash(), em.sig, em.author):
                return
            if wb.elect_state == ELEC_CANDIDATE:
                if (wb.my_rand > em.rand
                        or (wb.my_rand == em.rand
                            and addr_to_int(self.coinbase) > addr_to_int(em.author))):
                    return  # I have the larger rand — ignore
                wb.elect_state = ELEC_VOTED
                wb.delegator = em.author
                wb.delegator_ip = em.ip
                wb.delegator_port = em.port
                if self._phase == ELECTING:
                    # we were campaigning and a larger rand beat us
                    self.elections_lost += 1
                    self.journal.record("election_lost", blk=em.block_num,
                                        version=em.version,
                                        winner=em.author.hex()[:8])
                    self._abort_proposal()
                self._vote(em.block_num, em.ip, em.port, em.version)
            elif wb.elect_state == ELEC_VOTED:
                # re-vote on delegator retry or after two extra rounds
                if (em.author == wb.delegator
                        or em.retry > wb.max_election_retry + 1):
                    self._vote(em.block_num, wb.delegator_ip,
                               wb.delegator_port, em.version)
                    wb.max_election_retry = em.retry
        elif em.code == M.MSG_VOTE:
            # votes are stashed with their signatures and batch-verified
            # when the threshold is reached (_on_elected)
            if wb.elect_state == ELEC_CANDIDATE or self._phase == ELECTING:
                wb.supporters.add(em.author)
                self._stash_vote(em)
                if (len(wb.supporters) >= wb.election_threshold
                        and self._phase == ELECTING):
                    self._on_elected()
            elif wb.elect_state == ELEC_VOTED:
                # vote transfer: forward the original author's vote with
                # its original signature (the signed fields exclude
                # transport details, so the signature stays valid)
                wb.supporters.add(em.author)
                self._stash_vote(em)
                fwd = M.ElectMessage(code=M.MSG_VOTE, block_num=em.block_num,
                                     author=em.author, rand=em.rand,
                                     version=em.version,
                                     ip=self.cfg.consensus_ip,
                                     port=self.cfg.consensus_port,
                                     sig=em.sig)
                self.transport.send_direct(
                    wb.delegator_ip, wb.delegator_port,
                    M.pack_direct(M.UDP_ELECT, self.coinbase, fwd))

    def _stash_vote(self, em: M.ElectMessage) -> None:
        """Keep up to 2 distinct (sighash, sig) entries per claimed voter
        so a spoofed garbage-sig vote can neither squat the slot nor
        overwrite the genuine signature before the tally verifies."""
        lst = self.wb.supporter_votes.setdefault(em.author, [])
        entry = (em.signing_hash(), em.sig)
        if len(lst) < 2 and entry not in lst:
            lst.append(entry)
            self.journal.record("vote_stashed", blk=em.block_num,
                                version=em.version,
                                voter=em.author.hex()[:8])

    def _vote(self, blk_num: int, ip: str, port: int, version: int) -> None:
        """(ref: vote election_go.go:312-340)"""
        self.journal.record("vote_cast", blk=blk_num, version=version)
        reply = M.ElectMessage(code=M.MSG_VOTE, block_num=blk_num,
                               author=self.coinbase, version=version,
                               ip=self.cfg.consensus_ip,
                               port=self.cfg.consensus_port)
        reply = dataclasses.replace(reply,
                                    sig=self._sign(reply.signing_hash()))
        self.transport.send_direct(ip, port,
                                   M.pack_direct(M.UDP_ELECT, self.coinbase,
                                                 reply))

    # ------------------------------------------------------------------
    # acceptor side: validate requests (ref: HandleValidateRequest
    # eth/handler.go:1000-1056 + Validate geec_state.go:528-591)
    # ------------------------------------------------------------------

    def _handle_validate_request(self, req: M.ValidateRequest) -> None:
        wb = self.wb
        verdict = wb.classify(req.block_num)
        if verdict == WB_PASSED:
            return
        if verdict == WB_FUTURE:
            self._defer(req.block_num,
                        lambda: self._handle_validate_request(req))
            return
        if req.version < wb.max_version:
            return
        # Only the elected proposer — a committee member for this
        # height/version — may ask for ACKs; gate before relaying or
        # stashing the block so an unauthenticated peer cannot seed
        # pending_blocks with crafted blocks.
        seed = self.seed_for(req.block_num)
        if (seed is None
                or not self.membership.is_committee(req.author, seed,
                                                    req.version)):
            return
        # the proposal itself must be signed by the claimed proposer
        if not self._verify_single(req.signing_hash(), req.sig, req.author):
            return
        if req.version > wb.max_version:
            self._bump_version(req.version)
        if req.retry <= wb.max_validate_retry:
            return  # already relayed/answered this retry round
        # gossip-relay with dedup (handler.go:1025-1037)
        self.transport.gossip(M.pack_gossip(M.GOSSIP_VALIDATE_REQ, req))
        if req.block.number > self.max_confirmed_block:
            self.pending_blocks[req.block.number] = req.block
        wb.max_validate_retry = req.retry

        if not self.is_acceptor(req.block_num):
            return
        accepted = self._validate_block(req.block)
        if not accepted:
            self._log("reject", blk=req.block_num)
            self.journal.record("validate_reply", blk=req.block_num,
                                version=req.version, accepted=False)
            return
        self.journal.record("validate_reply", blk=req.block_num,
                            version=req.version, accepted=True)
        fills = []
        for n in req.empty_list:  # backfill requested empties
            b = self.chain.get_block_by_number(n)
            if b is not None:
                fills.append(b)
        reply = M.ValidateReply(block_num=req.block_num, author=self.coinbase,
                                accepted=True, retry=req.retry,
                                fill_blocks=tuple(fills),
                                block_hash=req.block.hash)
        reply = dataclasses.replace(reply,
                                    sig=self._sign(reply.signing_hash()))
        self.transport.send_direct(
            req.ip, req.port,
            M.pack_direct(M.UDP_EXAMINE_REPLY, self.coinbase, reply))

    def _validate_block(self, block: Block) -> bool:
        """Acceptor-side block check.  The reference ACKs unconditionally
        (``valResult := true``, geec_state.go:545); here the full insert
        validation runs BEFORE ACKing: ancestry, tx root, batched sender
        recovery on device, and the state/receipt/gas commitments — the
        capability BASELINE.json targets."""
        return self.chain.validate_candidate(block)

    # ------------------------------------------------------------------
    # confirm handling (ref: eth/handler.go:785-871)
    # ------------------------------------------------------------------

    # accept confirm effects only this far ahead of our head: a forged
    # confirm with a huge block_number must not wedge max_confirmed_block
    # (confirms are unauthenticated gossip until the signed-vote layer)
    CONFIRM_WINDOW = 256

    def _handle_confirm(self, confirm: ConfirmBlockMsg) -> None:
        if confirm.block_number <= self.max_confirmed_block:
            return
        if confirm.block_number > self.chain.height() + self.CONFIRM_WINDOW:
            # too far ahead to act on: if it's real we are badly behind —
            # sync first (rate-limited), and let later confirms land
            # normally once the gap closes; if forged, nothing was harmed
            self._request_backfill(confirm.block_number)
            return
        if self._signing and not self._confirm_ok(confirm):
            return
        if confirm.empty_block:
            for n in sorted(self.pending_blocks):
                if n <= confirm.block_number:
                    # an empty confirm vouches for no pending hash below
                    # it; dropped pendings are healed by backfill
                    del self.pending_blocks[n]
            if self.chain.height() == confirm.block_number - 1:
                empty = self.chain.make_empty_block().with_confirm(confirm)
                self.chain.offer(empty)
        else:
            # A confirm vouches for exactly one suffix: walk parent_hash
            # back from the confirmed hash and apply only pending blocks
            # on that path (cf. the hash check on the query path,
            # geec_state.go:1370).  A losing proposal stashed at a lower
            # height — e.g. confirm(N+1) arriving before confirm(N) while
            # a competing block is pending at N — must never be inserted:
            # it would wedge the chain under an 'unknown ancestor' that
            # backfill cannot displace.
            chained: dict[int, Block] = {}
            want = confirm.hash
            n = confirm.block_number
            while n > 0:
                blk = self.pending_blocks.get(n)
                if blk is None or blk.hash != want:
                    break
                chained[n] = blk
                want = blk.header.parent_hash
                n -= 1
            for n in list(self.pending_blocks):
                if n <= confirm.block_number:
                    del self.pending_blocks[n]
            # every block on the vouched suffix gets the confirm stamped,
            # ancestors included — the reference attaches the same
            # ConfirmMessage to all pendings it pops (eth/handler.go:
            # 785-871), and downstream consumers (replace_suffix's
            # "replacements must be confirmed", TTL rewards) rely on a
            # non-None confirm
            for n in sorted(chained):
                self.chain.offer(chained[n].with_confirm(confirm))
        self.max_confirmed_block = confirm.block_number
        self.journal.record("block_confirmed", blk=confirm.block_number,
                            empty=confirm.empty_block,
                            confidence=confirm.confidence)
        # unconditional re-broadcast; loop broken by max_confirmed gate
        self.transport.gossip(M.pack_gossip(M.GOSSIP_CONFIRM_BLOCK, confirm))
        behind = self.chain.height() < confirm.block_number
        local = self.chain.get_block_by_number(confirm.block_number)
        forked = (not confirm.empty_block and local is not None
                  and local.hash != confirm.hash)
        if behind or forked:
            # a fork at (or below) our head needs a target beyond our
            # height or the sync tick would no-op before the overlapping
            # request can expose the fork point to replace_suffix
            target = confirm.block_number + (0 if behind else 1)
            self._request_backfill(target)

    def _confirm_cert_entries(self, confirm: ConfirmBlockMsg):
        """Reconstruct the per-supporter signing hashes of a confirm's
        quorum certificate, or None if structurally invalid.

        ``version == 0``: supporters signed ACKs (ValidateReply sighash,
        which binds height + acceptor + the exact block hash).
        ``version > 0``: supporters signed query replies for the
        timeout-recovery outcome.  Receivers can therefore re-verify the
        quorum with NO trust in the proposer — the upgrade over the
        reference's trustedHW assumption (and over a single-member
        signature, which one malicious member could mint alone)."""
        sups, sigs = confirm.supporters, confirm.supporter_sigs
        if (len(sups) != len(sigs) or len(set(sups)) != len(sups)
                or len(sups) < self.membership.validate_threshold()):
            return None
        entries = []
        for a, s in zip(sups, sigs):
            if confirm.version == 0:
                h = M.ValidateReply(block_num=confirm.block_number, author=a,
                                    accepted=True,
                                    block_hash=confirm.hash).signing_hash()
            else:
                h = M.QueryReply(
                    block_num=confirm.block_number, author=a,
                    version=confirm.version, empty=confirm.empty_block,
                    block_hash=bytes(32) if confirm.empty_block
                    else confirm.hash).signing_hash()
            entries.append((a, h, s))
        return entries

    def _confirm_ok(self, confirm: ConfirmBlockMsg) -> bool:
        """Signed-vote mode: a gossiped confirm is accepted only with a
        valid quorum certificate (>= validate_threshold verified
        supporter signatures; acceptor-window-checked when the seed for
        that height is known) AND a member signature from its builder
        (binds the confidence/supporter packaging to a member key).

        The threshold is evaluated against membership as currently known.
        A syncing node's membership starts at the genesis bootstrap list
        and grows in step with the blocks it applies, so historical certs
        meet the as-of-then threshold; the one rough edge is a live
        confirm racing a threshold-raising membership change, which the
        timeout/re-election ladder recovers from."""
        entries = self._confirm_cert_entries(confirm)
        if entries is None:
            return False
        valid = [a for a in self._recover_entries(entries) if a is not None]
        need = self.membership.validate_threshold()
        if len(valid) < need:
            return False
        seed = self.seed_for(confirm.block_number)
        if seed is not None and sum(
                1 for a in valid
                if self.membership.is_acceptor(a, seed)) < need:
            return False
        if len(confirm.sig) != 65:
            return False
        from eges_tpu.crypto.verify_host import recover_signers
        signer = recover_signers(
            [(confirm.signing_hash(), confirm.sig)], self.verifier,
            priority="consensus")[0]
        return signer is not None and signer in self.membership

    # ------------------------------------------------------------------
    # transaction gossip (ref: TxMsg eth/handler.go:742-759 ->
    # TxPool.AddRemotes; relay-once dedup by txn hash)
    # ------------------------------------------------------------------

    _TXN_SEEN_CAP = 1 << 16

    def submit_txns(self, txns) -> None:  # thread-entry (RPC worker); ingress-entry:bounded
        """Local ingress (RPC eth_sendRawTransaction): admit to our pool
        via the journaled local path (they survive a restart, ref:
        core/tx_pool.go journal); admitted txns are broadcast via the
        pool's admission hook."""
        txns = list(txns)
        with self._lock, ledger.bind(self.ledger, "rpc"):
            if self.txpool is not None:
                self._ensure_pool_relay()
                self.txpool.add_locals(txns)
            else:
                self.broadcast_txns(txns)

    def broadcast_txns(self, txns) -> None:  # thread-entry (RPC worker); ingress-entry:bounded
        """Gossip txns to peers with relay-once dedup."""
        with self._lock:
            fresh = [t for t in txns if t.hash not in self._txn_seen]
            if not fresh:
                return
            self._mark_seen_txns(fresh)
            self.transport.gossip(
                M.pack_gossip(M.GOSSIP_TXNS, M.TxnsMsg(txns=tuple(fresh))))

    def _handle_txns(self, msg: M.TxnsMsg) -> None:
        fresh = [t for t in msg.txns if t.hash not in self._txn_seen]
        dupes = len(msg.txns) - len(fresh)
        if dupes:
            # relay-once dedup drops: re-gossiped txns billed to the
            # peer that delivered this redundant copy
            ledger.charge(drops=dupes)
        if not fresh:
            return
        if self.txpool is not None:
            # relay AFTER admission (signature verified in the pool's
            # batch window) — an attacker's junk txns must not get
            # network-wide fan-out amplification (the reference relays
            # only pool-accepted txns, eth/handler.go:742-759)
            self._ensure_pool_relay()
            if self.columnarize is not None and len(fresh) > 1:
                # wire-speed path: one columnar extraction + one
                # window-granular admission for the whole bundle
                self.txpool.add_remotes_window(self.columnarize(fresh))
            else:
                self.txpool.add_remotes(fresh)
        else:
            # pool-less follower: relay with dedup so txns still
            # propagate through it (marked seen either way)
            self.broadcast_txns(fresh)

    def _ensure_pool_relay(self) -> None:
        """Hook the pool's admission callback to broadcast admitted txns
        (chained with any existing callback)."""
        if getattr(self, "_pool_relay_hooked", None) is self.txpool:
            return
        prev = self.txpool.on_admitted

        def hook(t, sender, _prev=prev):
            if _prev is not None:
                _prev(t, sender)
            self.broadcast_txns([t])

        self.txpool.on_admitted = hook
        self._pool_relay_hooked = self.txpool

    def _mark_seen_txns(self, txns) -> None:
        if len(self._txn_seen) > self._TXN_SEEN_CAP:
            self._txn_seen.clear()  # coarse LRU: dupes re-relay once
        self._txn_seen.update(t.hash for t in txns)

    # ------------------------------------------------------------------
    # sync (the downloader role, ref: eth/downloader/downloader.go:931 —
    # ranged, retried, peer-tracked; SURVEY §5 checkpoint/resume)
    # ------------------------------------------------------------------

    SYNC_BATCH = 128       # blocks per request (served cap matches)
    SYNC_MAX_STALL = 8     # fruitless retries before giving up
    SYNC_FANOUT = 3        # concurrent ranged requests to distinct peers
    SYNC_STASH_MAX = 2048  # fetched-ahead blocks held for the funnel
    HDR_BATCH = 256        # headers per skeleton request (headers+certs
    #                        are ~50x smaller than 1000-txn bodies)
    HDR_FANOUT = 2         # concurrent header lanes
    SKEL_AHEAD = 4096      # skeleton prefetch horizon past the head
    SKEL_MAX = 16384       # pinned hashes cap (32B each)
    # fast-sync knobs (statesync.go role)
    FASTSYNC_MIN_GAP = 128   # replaying fewer blocks than this is cheaper
    #                          than a state download round-trip
    PIVOT_LAG = 32           # serve state this far behind head: deep
    #                          enough to be reorg-stable, shallow enough
    #                          that the tail replay stays short
    STATE_PAGE_BYTES = 36_000  # per-reply account payload budget (UDP)
    STATE_PAGE_MAX = 512       # accounts per page cap
    # byzantine-tolerance knobs for the live state download
    STATE_REPLY_MAX_BYTES = 192_000  # pre-decode byte cap on one state
    #                                  reply (FASTSYNC_MAX_ACCOUNTS caps
    #                                  rows; this caps BYTES before RLP)
    STATESYNC_MAX_REANCHORS = 3      # pivot/server re-anchors before the
    #                                  sync aborts to full replay
    STATESYNC_MAX_RETRIES = 64       # total fruitless ticks across the
    #                                  whole download before clean abort
    SERVE_RATE_PAGES_S = 4.0         # per-origin serving refill rate
    SERVE_BURST = 8                  # per-origin serving burst
    SERVE_TOKENS_MAX = 256           # tracked serving origins (oldest
    #                                  evicted; bounds the bucket dict)

    def _request_backfill(self, target: int, start: int | None = None) -> None:
        """Start (or extend) a sync toward ``target``.

        One outstanding request at a time; each retry rotates to another
        member peer (direct UDP), with a gossip broadcast as every third
        fallback for peers not in the membership.  Progress (blocks
        applied) resets the retry budget; a target that yields no blocks
        after SYNC_MAX_STALL rotations is abandoned (a forged confirm
        number must not keep the node polling forever)."""
        self._sync_target = max(getattr(self, "_sync_target", 0), target)
        # fast-sync entry (statesync.go role): a large-enough gap on a
        # fast_sync node downloads the pivot STATE instead of replaying
        # every block; certificates (signed votes) are what let the
        # joiner trust the pivot root, so unsigned chains always replay
        if (self.cfg.fast_sync and self._signing and not self._fs_done
                and target - self.chain.height() > self.FASTSYNC_MIN_GAP):
            if self._fs is None:
                self._fastsync_start(target)
            return
        if self._fs is not None:
            return  # the state download owns sync until it resolves
        if "backfill" not in self._timers:
            self._sync_progress = False
            self._sync_tick(start=start, retry=0)

    def _sync_tick(self, start: int | None, retry: int) -> None:
        height = self.chain.height()
        if height >= self._sync_target:
            self._cancel_timer("backfill")
            self._sync_skel.clear()
            self._skel_req_upto = 0
            return
        if self._sync_progress:
            retry = 0  # a reply delivered blocks: reset the stall budget
            self._sync_progress = False
        elif retry >= self.SYNC_MAX_STALL:
            # no peer served anything across a full rotation: the target
            # is unreachable (e.g. a forged confirm number) — abandon it
            # AND drop the fetched-ahead staging (unapplied peer-supplied
            # blocks must not squat memory after the sync dies)
            self._cancel_timer("backfill")
            self._sync_target = 0
            self._sync_stash.clear()
            self._sync_skel.clear()
            self._skel_req_upto = 0
            return
        if start is None:
            # overlap a few blocks behind our head so the reply exposes
            # the fork point when our tail is locally-forced empties
            # (replace_suffix needs the anchor)
            start = max(1, height - 7)
        # concurrent per-peer ranged fetch (the downloader's parallel
        # queues, ref: eth/downloader/downloader.go fetchParts role):
        # split the outstanding range into SYNC_FANOUT chunks and ask a
        # DIFFERENT member peer for each; arrivals beyond the insert
        # window stage in _sync_stash until the head catches up
        for lane in range(self.SYNC_FANOUT):
            lane_start = start + lane * self.SYNC_BATCH
            if lane_start > self._sync_target:
                break
            count = max(min(self._sync_target - lane_start + 1,
                            self.SYNC_BATCH), 1)
            req = M.BlockFetchReq(start=lane_start, count=count,
                                  ip=self.cfg.consensus_ip,
                                  port=self.cfg.consensus_port)
            peer = self._pick_sync_peer(retry + lane)
            if peer is not None and retry % 3 != 2:
                self.transport.send_direct(
                    peer.ip, peer.port,
                    M.pack_direct(M.UDP_GET_BLOCKS, self.coinbase, req))
            elif lane == 0:
                # every third rotation (or with no member peers) the
                # first lane broadcasts instead — the gossip fallback
                # for peers outside the membership
                self.transport.gossip(
                    M.pack_gossip(M.GOSSIP_GET_BLOCKS, req))
        # header-first skeleton prefetch (ref: downloader.go:931): pull
        # the gap's headers+certificates ahead of bodies so the whole
        # range's signatures batch-verify on the device at once and the
        # body lanes skip per-reply verification (they hash onto pins).
        # Watermark-gated: lost header replies just mean those numbers
        # fall back to the certified body path — no retry machinery.
        for n in [k for k in self._sync_skel if k <= height]:
            del self._sync_skel[n]
        if self._signing and len(self._sync_skel) < self.SKEL_MAX:
            want_hi = min(self._sync_target, height + self.SKEL_AHEAD)
            hdr_start = max(height + 1, self._skel_req_upto + 1)
            for lane in range(self.HDR_FANOUT):
                lane_start = hdr_start + lane * self.HDR_BATCH
                if lane_start > want_hi:
                    break
                count = min(want_hi - lane_start + 1, self.HDR_BATCH)
                hreq = M.BlockFetchReq(start=lane_start, count=count,
                                       ip=self.cfg.consensus_ip,
                                       port=self.cfg.consensus_port)
                peer = self._pick_sync_peer(retry + 7 * lane + 3)
                if peer is not None:
                    self.transport.send_direct(
                        peer.ip, peer.port,
                        M.pack_direct(M.UDP_GET_HEADERS, self.coinbase,
                                      hreq))
                else:
                    self.transport.gossip(
                        M.pack_gossip(M.GOSSIP_GET_HEADERS, hreq))
                self._skel_req_upto = lane_start + count - 1
        self._set_timer("backfill", self.ccfg.validate_timeout_ms / 1e3,
                        lambda: self._sync_tick(None, retry + 1))

    def _pick_sync_peer(self, retry: int):
        peers = [m for m in self.membership.members()
                 if m.addr != self.coinbase and m.ip]
        if not peers:
            return None
        self._sync_rr = getattr(self, "_sync_rr", 0) + 1
        return peers[(self._sync_rr + retry) % len(peers)]

    # UDP datagrams cap near 64 KB; a batch of blocks at the 1000-txn
    # operating point is far larger (the in-process sim has no MTU,
    # which hid this — a real-socket joiner stalled at height 0 while
    # its peers' replies were silently dropped).  Small chunks go
    # direct; anything bigger rides the TCP gossip plane (receivers
    # that are not syncing dedupe via chain.offer).
    UDP_BUDGET = 40_000

    def _send_chunked(self, req, items, enc_len, make_reply,
                      udp_code, gossip_code, max_items: int) -> None:
        """Chunk sync reply ``items`` under the UDP budget — shared by
        the block and header serve paths so the MTU handling can never
        drift between the planes.  A single item too big for any
        datagram rides the TCP gossip plane alone."""
        chunk: list = []
        size = 0
        for it in items + [None]:
            enc = enc_len(it) if it is not None else 0
            if chunk and (it is None or size + enc > self.UDP_BUDGET
                          or len(chunk) >= max_items):
                reply = make_reply(tuple(chunk))
                packed = M.pack_direct(udp_code, self.coinbase, reply)
                if len(packed) <= self.UDP_BUDGET + 1024:
                    self.transport.send_direct(req.ip, req.port, packed)
                else:
                    self.transport.gossip(
                        M.pack_gossip(gossip_code, reply))
                chunk, size = [], 0
            if it is not None:
                if enc > self.UDP_BUDGET:
                    self.transport.gossip(M.pack_gossip(
                        gossip_code, make_reply((it,))))
                else:
                    chunk.append(it)
                    size += enc

    def _serve_block_fetch(self, req: M.BlockFetchReq) -> None:
        blocks = []
        for n in range(req.start, req.start + min(req.count,
                                                  self.SYNC_BATCH)):
            b = self.chain.get_block_by_number(n)
            if b is None:
                break
            blocks.append(b)
        if not blocks:
            return
        self._send_chunked(
            req, blocks, lambda b: len(b.encode()),
            lambda t: M.BlocksReply(blocks=t),
            M.UDP_BLOCKS, M.GOSSIP_BLOCKS_REPLY, max_items=32)

    def _certified_mask(self, items) -> list[bool]:
        """For ``(number, obj_hash, confirm)`` triples: True when the
        quorum certificate verifies AND actually certifies the object in
        hand (or none is required — confidence-0 local empties carry
        none legitimately).  The binding matters as much as the
        signatures: a replayed GENUINE certificate paired with a
        fabricated header/block must fail here, so the confirm's claimed
        number and hash are checked against the object before any
        signature work.  The one certificate shape that cannot bind a
        hash — version>0 empty-block recovery, whose supporters signed
        the zero hash — is handled by the callers (bodies must be empty;
        headers are never pinned on it).  All certificates across the
        batch are recovered in ONE verifier batch — during catch-up this
        is where a whole gap's signatures land on the device together."""
        need = self.membership.validate_threshold()
        spans = []          # (item_index, entry_span) needing verification
        all_entries = []
        keep = [True] * len(items)
        for i, (number, obj_hash, confirm) in enumerate(items):
            if confirm is None or confirm.confidence == 0:
                continue
            if confirm.block_number != number or (
                    confirm.hash != obj_hash
                    and self._cert_binds_hash(confirm)):
                keep[i] = False  # certificate is for a different object
                continue
            entries = self._confirm_cert_entries(confirm)
            if entries is None:
                keep[i] = False
                continue
            spans.append((i, len(all_entries), len(entries)))
            all_entries.extend(entries)
        recovered = self._recover_entries(all_entries) if all_entries else []
        for i, start, n in spans:
            valid = [a for a in recovered[start:start + n] if a is not None]
            ok = len(valid) >= need
            if ok:
                seed = self.seed_for(items[i][0])
                if seed is not None and sum(
                        1 for a in valid
                        if self.membership.is_acceptor(a, seed)) < need:
                    ok = False
            keep[i] = ok
        return keep

    @staticmethod
    def _cert_binds_hash(confirm) -> bool:
        """False for the one certificate shape whose supporter
        signatures do not cover a block hash: version>0 empty-block
        recovery signs the zero hash — it certifies "empty at N", not
        any particular bytes."""
        return not (confirm.version > 0 and confirm.empty_block)

    def _serve_header_fetch(self, req: M.BlockFetchReq) -> None:
        """Serve a header-skeleton request: (header, confirm) pairs, no
        bodies (ref: eth/handler.go GetBlockHeadersMsg role).  Chunked
        like block replies: small chunks ride UDP back to the asker,
        oversized ones the TCP gossip plane."""
        from eges_tpu.core import rlp as rlp_mod

        pairs = []
        for n in range(req.start, req.start + min(req.count,
                                                  2 * self.HDR_BATCH)):
            b = self.chain.get_block_by_number(n)
            if b is None:
                break
            pairs.append((b.header, b.confirm))
        if not pairs:
            return
        self._send_chunked(
            req, pairs,
            lambda p: (len(rlp_mod.encode(p[0].to_rlp()))
                       + (len(rlp_mod.encode(p[1].to_rlp()))
                          if p[1] else 1)),
            lambda t: M.HeadersReply(headers=t),
            M.UDP_HEADERS, M.GOSSIP_HEADERS_REPLY, max_items=128)

    # ------------------------------------------------------------------
    # fast sync (the fast/state-sync mode of the reference downloader,
    # ref: eth/downloader/statesync.go:1, downloader.go:1353 — account-
    # granular pages instead of trie nodes; design in core/statesync.py)
    # ------------------------------------------------------------------

    def _fastsync_start(self, target: int) -> None:
        self._fs = {"target": target, "pivot": 0, "root": b"",
                    "accounts": [], "codes": [], "total": None,
                    "headers": {}, "block": None, "progress": False,
                    # byzantine-tolerance state: the pinned serving peer
                    # (every page of one download comes from ONE server,
                    # so a poisoned download is attributable), plus the
                    # bounded re-anchor / total-retry budgets
                    "server": None, "reanchors": 0, "retries": 0}
        self._fastsync_load_staging()
        self._log("FASTSYNC start", gap=target - self.chain.height())
        self._fastsync_tick(retry=0)

    def _fastsync_load_staging(self) -> None:
        """Mid-sync crash resume: pages a previous process accepted and
        staged to the store re-enter the download, so a crash at cursor
        N resumes at N instead of 0.  Only a consistent prefix loads —
        same pivot/root throughout, cursors contiguous from 0; the
        first torn or inconsistent blob truncates the resume there."""
        from eges_tpu.core import statesync as _ss
        from eges_tpu.utils.metrics import DEFAULT as metrics

        fs = self._fs
        try:
            blobs = self.chain.store.load_sync_pages()
        # analysis: allow-swallow(staging is an optimization; an unreadable log just restarts the download from cursor 0)
        except Exception:
            return
        pages = 0
        for blob in blobs:
            try:
                pivot, root, cursor, total, accounts, codes = \
                    _ss.decode_page(blob)
            except _ss.StateSyncError:
                break  # torn staged tail: keep the consistent prefix
            if pages == 0:
                if cursor != 0:
                    break
                fs["pivot"], fs["root"] = pivot, root
            elif (pivot != fs["pivot"] or root != fs["root"]
                    or cursor != len(fs["accounts"])):
                break
            if (len(fs["accounts"]) + len(accounts)
                    > self.FASTSYNC_MAX_ACCOUNTS):
                break  # an overgrown staging log never resumes past the
                       # same row budget the live download enforces
            fs["accounts"].extend(accounts)
            fs["codes"].extend(codes)
            fs["total"] = total
            pages += 1
        if pages:
            self.journal.record("statesync_resume", blk=fs["pivot"],
                                pages=pages, rows=len(fs["accounts"]))
            metrics.counter("statesync.resumes").inc()
            self._log("FASTSYNC resume", pivot=fs["pivot"], pages=pages,
                      rows=len(fs["accounts"]))

    def _clear_sync_staging(self) -> None:
        try:
            self.chain.store.clear_sync_staging()
        # analysis: allow-swallow(staging cleanup is best-effort; stale pages fail the consistency check on the next load)
        except Exception:
            pass

    def _fastsync_abort(self, why: str) -> None:
        """Fall back to full replay — once per session; a byzantine or
        pruned serving peer can delay a fast sync, never wedge it."""
        from eges_tpu.utils.metrics import DEFAULT as metrics

        fs, self._fs = self._fs, None
        self._fs_done = True
        self._cancel_timer("fastsync")
        if fs is not None:
            # drop the staged rows NOW: an armed timer or in-flight
            # closure still holding ``fs`` must not pin up to
            # FASTSYNC_MAX_ACCOUNTS rows until the next sync
            fs["accounts"].clear()
            fs["codes"].clear()
            fs["headers"].clear()
            fs["block"] = None
        self._clear_sync_staging()
        self.journal.record("statesync_abort", why=why)
        metrics.counter("statesync.aborts").inc()
        self._log("FASTSYNC abandoned", why=why)
        if fs is not None:
            self._request_backfill(fs["target"])

    def _fastsync_pick_server(self, retry: int):
        """Serving-peer choice for the state download: the usual member
        rotation, EXCLUDING peers that already served a poisoned page."""
        peers = [m for m in self.membership.members()
                 if m.addr != self.coinbase and m.ip
                 and m.addr not in self._fs_blacklist]
        if not peers:
            return None
        self._sync_rr = getattr(self, "_sync_rr", 0) + 1
        return peers[(self._sync_rr + retry) % len(peers)]

    def _fastsync_rotate_server(self, retry: int) -> None:
        """The pinned server went quiet for a full stall ladder: move
        the download to another peer.  Staged pages answer the OLD
        server's pivot snapshot, so rotation with pages on hand
        re-anchors the whole download (bounded by the re-anchor
        budget); with nothing staged it just unpins."""
        fs = self._fs
        old = fs["server"]
        self.journal.record(
            "statesync_server_rotate", blk=fs["pivot"],
            server=old.addr.hex()[:8] if old is not None else "",
            retry=retry)
        if fs["accounts"] or fs["pivot"]:
            self._fastsync_reanchor("server quiet", blacklist=False)
        else:
            fs["server"] = None

    def _fastsync_reanchor(self, why: str, *, blacklist: bool) -> None:
        """Restart the download from cursor 0 on a fresh pivot/server,
        optionally quarantining the current server first.  Budgeted:
        crossing STATESYNC_MAX_REANCHORS aborts to full replay."""
        from eges_tpu.utils.metrics import DEFAULT as metrics

        fs = self._fs
        srv = fs["server"]
        if blacklist and srv is not None:
            self._fs_blacklist.add(srv.addr)
        fs["reanchors"] += 1
        metrics.counter("statesync.reanchors").inc()
        self.journal.record("statesync_reanchor", blk=fs["pivot"],
                            count=fs["reanchors"], why=why)
        self._log("FASTSYNC reanchor", why=why, count=fs["reanchors"])
        if fs["reanchors"] > self.STATESYNC_MAX_REANCHORS:
            self._fastsync_abort("re-anchor budget exhausted")
            return
        fs.update(pivot=0, root=b"", accounts=[], codes=[], total=None,
                  block=None, progress=False, server=None)
        fs["headers"].clear()
        self._clear_sync_staging()

    def _fastsync_tick(self, retry: int) -> None:
        fs = self._fs
        if fs is None:
            return
        if fs["progress"]:
            retry = 0
            fs["progress"] = False
        else:
            if retry > 0:
                fs["retries"] += 1
            if fs["retries"] >= self.STATESYNC_MAX_RETRIES:
                # total-retry budget across the whole download, however
                # many servers it rotated through: clean abort-to-replay
                self._fastsync_abort("retry budget exhausted")
                return
            if retry >= self.SYNC_MAX_STALL:
                self._fastsync_rotate_server(retry)
                fs = self._fs
                if fs is None:
                    return
                retry = 0
        if fs["server"] is None:
            fs["server"] = self._fastsync_pick_server(retry)
            if fs["server"] is None:
                self._fastsync_abort("no serving peer")
                return
        srv = fs["server"]
        req = M.StateFetchReq(block_num=fs["pivot"],
                              cursor=len(fs["accounts"]),
                              ip=self.cfg.consensus_ip,
                              port=self.cfg.consensus_port)
        self.transport.send_direct(
            srv.ip, srv.port,
            M.pack_direct(M.UDP_GET_STATE, self.coinbase, req))
        if fs["pivot"]:
            # the pivot header (for the certified root) and the pivot
            # block (the new head) ride the existing sync lanes
            breq = M.BlockFetchReq(start=fs["pivot"], count=1,
                                   ip=self.cfg.consensus_ip,
                                   port=self.cfg.consensus_port)
            if fs["pivot"] not in fs["headers"]:
                peer2 = self._pick_sync_peer(retry + 1)
                if peer2 is not None:
                    self.transport.send_direct(
                        peer2.ip, peer2.port,
                        M.pack_direct(M.UDP_GET_HEADERS, self.coinbase,
                                      breq))
                else:
                    self.transport.gossip(
                        M.pack_gossip(M.GOSSIP_GET_HEADERS, breq))
            if fs["block"] is None:
                peer3 = self._pick_sync_peer(retry + 2)
                if peer3 is not None:
                    self.transport.send_direct(
                        peer3.ip, peer3.port,
                        M.pack_direct(M.UDP_GET_BLOCKS, self.coinbase,
                                      breq))
                else:
                    self.transport.gossip(
                        M.pack_gossip(M.GOSSIP_GET_BLOCKS, breq))
        # per-peer backoff: each fruitless retry against the pinned
        # server stretches the re-ask interval (deterministic ladder)
        delay = (self.ccfg.validate_timeout_ms / 1e3
                 * min(retry + 1, 4))
        self._set_timer("fastsync", delay,
                        lambda: self._fastsync_tick(retry + 1))

    def _handle_state_chunk(self, reply: M.StateChunkReply,
                            author: bytes = b"") -> None:
        from eges_tpu.utils.metrics import DEFAULT as metrics

        fs = self._fs
        if fs is None:
            return
        srv = fs["server"]
        if author and srv is not None and author != srv.addr:
            # authenticated page from a peer this download is NOT
            # anchored on: one interleaved poisoned page would fail the
            # final root check and waste the whole download — reject it
            # and bill the sender.  (Gossip replies carry no author and
            # pass; the cursor/pivot checks below still gate them, and
            # the root check backstops everything.)
            metrics.counter("statesync.pages_rejected").inc()
            ledger.charge(rejects=1)
            return
        if fs["pivot"] == 0:
            if reply.cursor != 0 or reply.block_num <= self.chain.height():
                return
            fs["pivot"], fs["root"] = reply.block_num, reply.root
        elif reply.block_num != fs["pivot"] or reply.root != fs["root"]:
            if reply.cursor == 0 and reply.block_num > fs["pivot"]:
                # server pruned our pivot and re-anchored: restart there
                fs.update(pivot=reply.block_num, root=reply.root,
                          accounts=[], codes=[], total=None, block=None)
                self._clear_sync_staging()
            else:
                metrics.counter("statesync.pages_rejected").inc()
                return
        if reply.cursor != len(fs["accounts"]):
            # duplicate or out-of-order page (benign under re-asks);
            # the tick re-requests the cursor it actually needs
            metrics.counter("statesync.pages_rejected").inc()
            return
        if (len(fs["accounts"]) + len(reply.accounts)
                > self.FASTSYNC_MAX_ACCOUNTS):
            # a malicious state server claiming an absurd account count
            # cannot balloon the staging buffers: quarantine it and
            # re-anchor the download on another server (budgeted)
            self._log("fastsync state too large",
                      staged=len(fs["accounts"]))
            self._fastsync_reanchor("state too large", blacklist=True)
            if self._fs is not None:
                self._fastsync_tick(retry=0)
            return
        fs["accounts"].extend(reply.accounts)
        fs["codes"].extend(reply.codes)
        fs["total"] = reply.total
        fs["progress"] = True
        metrics.counter("statesync.pages_accepted").inc()
        # fetching is ingress work too: bill the staged rows to the
        # origin that delivered them (ambient bind at the perimeter)
        ledger.charge(rows=len(reply.accounts), admits=1)
        self._stage_sync_page(reply)
        self._fastsync_maybe_finish()
        if self._fs is not None:
            self._fastsync_tick(retry=0)  # next page immediately

    def _stage_sync_page(self, reply: M.StateChunkReply) -> None:
        """Persist one accepted page to the store's staging log (the
        crash-resume source read back by ``_fastsync_load_staging``)."""
        from eges_tpu.core import statesync as _ss

        fs = self._fs
        try:
            self.chain.store.append_sync_page(_ss.encode_page(
                fs["pivot"], fs["root"], reply.cursor, reply.total,
                reply.accounts, reply.codes))
        # analysis: allow-swallow(staging is an optimization; a page that failed to stage just re-downloads after a crash)
        except Exception:
            pass

    def _fastsync_take_blocks(self, blocks) -> None:
        """During a state download the block lanes only feed the pivot
        block; everything else re-fetches after adoption."""
        fs = self._fs
        want = [b for b in blocks if b.number == fs["pivot"]]
        if not want or fs["block"] is not None:
            return
        ok = self._filter_certified(want)
        if ok:
            fs["block"] = ok[0]
            fs["progress"] = True
            self._fastsync_maybe_finish()

    def _fastsync_maybe_finish(self) -> None:
        from eges_tpu.core import statesync as _ss
        from eges_tpu.utils.metrics import DEFAULT as metrics

        fs = self._fs
        if (fs is None or fs["total"] is None
                or len(fs["accounts"]) < fs["total"]):
            return
        hdr = fs["headers"].get(fs["pivot"])
        blk = fs["block"]
        if hdr is None or blk is None:
            return  # the tick keeps requesting them
        if blk.hash != hdr.hash:
            fs["block"] = None  # block from a liar peer; re-fetch
            return
        state = None
        try:
            state = _ss.assemble(fs["accounts"], fs["codes"])
        except Exception as exc:
            # structurally-invalid pages (bad storage pairs, torn rows)
            # are the same class of attack as a wrong balance: poison
            self._log("fastsync assemble failed", err=repr(exc))
        if state is None or state.root() != hdr.root:
            # pages were poisoned: certificates bound the header, the
            # rebuilt tries disagree — nothing was adopted.  Every page
            # came from the pinned server, so the poisoning is
            # attributable: quarantine it, bill the wasted rows to it,
            # and re-anchor the download on an honest peer (budgeted;
            # the re-anchor path aborts to full replay when exhausted)
            srv = fs["server"]
            label = srv.addr.hex()[:8] if srv is not None else "?"
            self.journal.record("statesync_poisoned", blk=fs["pivot"],
                                server=label, rows=len(fs["accounts"]))
            metrics.counter("statesync.poisoned").inc()
            self.ledger.charge(f"server:{label}",
                               rejects=max(len(fs["accounts"]), 1))
            self._fastsync_reanchor(
                "state root mismatch vs certified header",
                blacklist=True)
            if self._fs is not None:
                self._fastsync_tick(retry=0)
            return
        target = fs["target"]
        pivot = fs["pivot"]
        rows = len(fs["accounts"])
        self.chain.adopt_snapshot(blk, state)
        self._clear_sync_staging()
        self._fs = None
        self._fs_done = True
        self._cancel_timer("fastsync")
        self.journal.record("statesync_adopted", blk=pivot,
                            accounts=rows, target=target)
        self._log("FASTSYNC adopted", pivot=pivot,
                  root=hdr.root.hex()[:12], accounts=len(state),
                  target=target)
        self._request_backfill(max(target, pivot), start=pivot + 1)

    def _serve_state_fetch(self, req: M.StateFetchReq) -> None:
        """Serve one address-sorted page of a pivot state snapshot.

        The pivot is head−PIVOT_LAG on first contact (block_num=0); on
        later pages the exact requested block, falling back to a fresh
        cursor-0 pivot when ours got pruned (the joiner restarts).  The
        flattened account list is cached per pivot hash — paging is a
        slice, not a re-walk."""
        from eges_tpu.core import rlp as rlp_mod
        from eges_tpu.core import statesync as _ss
        from eges_tpu.utils.metrics import DEFAULT as metrics

        # serving is rate-limited per origin: snapshot pages are the
        # most expensive reply this node produces, and an unthrottled
        # serve loop would let one cheap StateFetchReq stream turn this
        # node into a DoS amplifier against itself
        origin = ledger.current_peer() or f"{req.ip}:{req.port}"
        if not self._serve_tokens_take(origin):
            metrics.counter("statesync.serve_throttled").inc()
            ledger.charge(drops=1)
            return
        height = self.chain.height()
        n, cursor = req.block_num, req.cursor
        blk = state = None
        if n:
            blk = self.chain.get_block_by_number(n)
            state = self.chain.state_at(blk.hash) if blk else None
        if state is None:
            n, cursor = max(1, height - self.PIVOT_LAG), 0
            while n <= height:
                blk = self.chain.get_block_by_number(n)
                state = self.chain.state_at(blk.hash) if blk else None
                if state is not None:
                    break
                n += 1
        if state is None or blk is None:
            return
        cache = self._snap_cache
        if cache is None or cache[0] != blk.hash:
            accounts = _ss.snapshot_accounts(state)
            self._snap_cache = (blk.hash, accounts)
        else:
            accounts = cache[1]
        if cursor > len(accounts):
            return
        page, size = [], 0
        for item in accounts[cursor:]:
            enc = len(rlp_mod.encode(
                [item[0], item[1], item[2], item[3],
                 [[k, v] for k, v in item[4]]]))
            if page and (size + enc > self.STATE_PAGE_BYTES
                         or len(page) >= self.STATE_PAGE_MAX):
                break
            page.append(item)
            size += enc
        reply = M.StateChunkReply(
            block_num=n, root=blk.header.root, cursor=cursor,
            total=len(accounts), accounts=tuple(page),
            codes=_ss.codes_for(state, page))
        packed = M.pack_direct(M.UDP_STATE, self.coinbase, reply)
        if len(packed) <= self.UDP_BUDGET + 1024:
            self.transport.send_direct(req.ip, req.port, packed)
        else:
            self.transport.gossip(M.pack_gossip(M.GOSSIP_STATE_REPLY,
                                                reply))
        # serving is billable work driven by the requester
        metrics.counter("statesync.pages_served").inc()
        ledger.charge(rows=len(page), admits=1)

    def _serve_tokens_take(self, origin: str) -> bool:
        """Per-origin token bucket for the snapshot-serving plane, on
        the node clock (virtual in sims, so deterministic).  The bucket
        dict is bounded-by: SERVE_TOKENS_MAX (oldest origin evicted)."""
        now = self.clock.now()
        tokens, last = self._serve_tokens.get(
            origin, (float(self.SERVE_BURST), now))
        tokens = min(float(self.SERVE_BURST),
                     tokens + (now - last) * self.SERVE_RATE_PAGES_S)
        ok = tokens >= 1.0
        if ok:
            tokens -= 1.0
        self._serve_tokens[origin] = (tokens, now)
        while len(self._serve_tokens) > self.SERVE_TOKENS_MAX:
            self._serve_tokens.pop(next(iter(self._serve_tokens)))
        return ok

    def _handle_headers_reply(self, reply: M.HeadersReply) -> None:
        """Pin the verified skeleton: batch-verify every certificate in
        the reply (one device batch for the lot) and remember the header
        hashes, so arriving bodies only need to hash onto a pin.
        Uncertified headers (local empties, or certs that fail) are NOT
        pinned — their bodies take the fully-verified path."""
        pairs = [(h, c) for h, c in reply.headers
                 if h.number > self.chain.height()]
        if not pairs or not self._signing:
            return  # without signed votes there is nothing to pre-verify
        if len(self._sync_skel) + len(pairs) > self.SKEL_MAX:
            pairs = pairs[:max(0, self.SKEL_MAX - len(self._sync_skel))]
            if not pairs:
                return
        mask = self._certified_mask([(h.number, h.hash, c)
                                     for h, c in pairs])
        for (h, c), ok in zip(pairs, mask):
            # pin only hash-binding certificates: the mask has already
            # checked c.hash == h.hash for these, so the pin IS what the
            # quorum signed.  Recovery empties (sigs over the zero hash)
            # can't bind bytes and are never pinned.
            if (ok and c is not None and c.confidence > 0
                    and self._cert_binds_hash(c)):
                self._sync_skel[h.number] = h.hash
                if self._fs is not None:
                    # fast sync needs the certified HEADER (its root is
                    # what the downloaded state verifies against)
                    self._fs["headers"][h.number] = h
                    if h.number == self._fs["pivot"]:
                        self._fs["progress"] = True
                        self._fastsync_maybe_finish()

    def _filter_certified(self, blocks) -> list:
        """Drop backfilled blocks whose quorum confirm doesn't verify or
        doesn't certify THIS block — a sync peer must not be able to
        hand us fabricated "confirmed" history, including a fabricated
        block wearing a replayed genuine certificate.  Locally-forced
        empty blocks (confidence 0) are legitimately uncertified, and
        are exactly the blocks replace_suffix may later displace."""
        keep = self._certified_mask(
            [(b.number, b.hash, b.confirm) for b in blocks])
        out = []
        for b, k in zip(blocks, keep):
            if not k:
                continue
            c = b.confirm
            if (c is not None and c.confidence > 0
                    and not self._cert_binds_hash(c)
                    and (b.transactions or b.geec_txns or b.fake_txns)):
                continue  # recovery cert proves only "empty at N"
            out.append(b)
        return out

    def _handle_blocks_reply(self, reply: M.BlocksReply) -> None:
        """Backfilled canonical blocks: heal a local-empty-block fork via
        reorg, then extend normally.  If the fork is deeper than the
        reply's overlap, re-request further back (doubling window)."""
        blocks = sorted(reply.blocks, key=lambda b: b.number)
        if self._fs is not None:
            # a state download is in flight: block lanes only feed the
            # pivot; the tail re-fetches after adoption
            self._fastsync_take_blocks(blocks)
            return
        if self._signing:
            # header-first fast path: a body hashing onto a pinned
            # (pre-verified) skeleton entry needs no certificate work.
            # A body CONTRADICTING its pin falls back to full
            # certificate verification — and if its hash-bound
            # certificate verifies, the pin was wrong (equivocation or
            # poisoning upstream) and is evicted, so one bad pin can
            # never starve a height and wedge the sync.
            pinned, rest = [], []
            for b in blocks:
                pin = self._sync_skel.get(b.number)
                if pin is not None and b.hash == pin:
                    pinned.append(b)
                else:
                    rest.append(b)
            verified = self._filter_certified(rest)
            for b in verified:
                if self._sync_skel.get(b.number) not in (None, b.hash):
                    del self._sync_skel[b.number]
            blocks = sorted(pinned + verified, key=lambda b: b.number)
        if not blocks:
            return
        head = self.chain.height()
        conflict = [b for b in blocks if b.number <= head
                    and (local := self.chain.get_block_by_number(b.number))
                    is not None and local.hash != b.hash]
        if conflict:
            done = self.chain.replace_suffix(
                [b for b in blocks if b.number >= conflict[0].number])
            if not done and conflict[0].number == blocks[0].number:
                # fork point precedes the reply window — look deeper
                # (keep the target above our head or the tick no-ops)
                self._cancel_timer("backfill")
                self._sync_target = max(self._sync_target, head + 1)
                depth = 2 * max(head - blocks[0].number + 1, 8)
                self._sync_tick(start=max(1, head - depth + 1), retry=0)
                return
            if done:
                self._sync_progress = True
        for b in blocks:
            if b.number > self.chain.height() + 256:
                # beyond the insert funnel's buffer window: stage it
                # (concurrent lanes fetch ahead of the head)
                if (len(self._sync_stash) < self.SYNC_STASH_MAX
                        or b.number < max(self._sync_stash)):
                    self._sync_stash[b.number] = b
                    while len(self._sync_stash) > self.SYNC_STASH_MAX:
                        del self._sync_stash[max(self._sync_stash)]
            elif self.chain.offer(b):
                self._sync_progress = True
        # drain staged blocks that entered the window as the head moved
        while self._sync_stash:
            window_end = self.chain.height() + 256
            ready = [n for n in self._sync_stash if n <= window_end]
            if not ready:
                break
            progressed = False
            for n in sorted(ready):
                if self.chain.offer(self._sync_stash.pop(n)):
                    progressed = True
                    self._sync_progress = True
            if not progressed:
                break
        # continuation: more of the range outstanding -> next request now
        if (self._sync_progress
                and self.chain.height() < getattr(self, "_sync_target", 0)):
            self._cancel_timer("backfill")
            self._sync_tick(start=None, retry=0)
        elif self.chain.height() >= getattr(self, "_sync_target", 0):
            # target reached in this very reply: drop the skeleton now
            # rather than waiting for the timer's completion tick
            self._sync_skel.clear()
            self._skel_req_upto = 0

    # ------------------------------------------------------------------
    # chain listener (ref: handleNewBlock geec_state.go:964-1018 +
    # blockLoop geec_state.go:1132-1180)
    # ------------------------------------------------------------------

    def _on_new_block(self, blk: Block) -> None:  # api: _on_new_block
        with self._lock:
            self._timeout_times = 0
            self._arm_block_timeout()
            self._ingest_block(blk)

    def _ingest_block(self, blk: Block, replay: bool = False) -> None:
        """Consensus-state effects of a canonical block; also used to
        rebuild state from a durable chain on restart (the reference
        rebuilds GeecState "from genesis bootstrap list + replayed
        confirmed blocks", SURVEY §5 checkpoint/resume)."""
        self.trust_rands[blk.number] = blk.header.trust_rand
        if self.txpool is not None and blk.transactions:
            self.txpool.remove_included(blk.transactions, block=blk.number)
        if blk.geec_txns:
            # drop geec txns the landed block already included — from the
            # pending queue AND from any in-flight proposal's drained list
            # (the abort below would otherwise re-queue them after this
            # dedup already ran)
            included = {t.hash for t in blk.geec_txns}
            self.pending_geec_txns = deque(
                t for t in self.pending_geec_txns
                if t.hash not in included)
            if self._proposal_geec_txns:
                self._proposal_geec_txns = [
                    t for t in self._proposal_geec_txns
                    if t.hash not in included]
        if blk.header.coinbase == EMPTY_ADDR:
            if blk.number not in self.empty_block_list:
                self.empty_block_list.append(blk.number)
        self.unconfirmed.append(blk)
        # per-height bookkeeping is windowed: entries older than
        # HEIGHT_WINDOW heights cannot be referenced by any committee /
        # confirm path near the tip, so long runs hold steady memory
        while len(self.trust_rands) > self.HEIGHT_WINDOW:
            del self.trust_rands[next(iter(self.trust_rands))]
        while len(self.empty_block_list) > self.HEIGHT_WINDOW:
            self.empty_block_list.pop(0)
        if not replay:
            self._last_commit_t = self.clock.now()
            # per-block ingress provenance snapshot: one ingress_ledger
            # event when anything was charged since the last block —
            # the SLO engine keys on its admit/reject deltas
            self.ledger.journal_snapshot(self.journal, blk=blk.number)
        confidence = blk.confirm.confidence if blk.confirm else 0
        if confidence > CONFIDENCE_THRESHOLD:
            self._handle_confirmed_tail(blk)
        # drop pendings at or below the new height
        for n in list(self.pending_blocks):
            if n <= blk.number:
                del self.pending_blocks[n]
        if (not replay and self.cfg.checkpoint_every
                and blk.number % self.cfg.checkpoint_every == 0):
            # durable checkpoint cadence: every Nth committed block
            # snapshots state + consensus soft state to the store's
            # sidecar, so the NEXT restart replays only the tail
            self._write_checkpoint(blk)
        if blk.number >= self.wb.blk_num:
            if not replay:
                self._abort_proposal()
            self.wb.advance(blk.number + 1)
            if not replay:
                self._drain_deferred()
                self._try_propose()

    def _write_checkpoint(self, blk: Block) -> None:
        from eges_tpu.core import statesync as _ss
        from eges_tpu.utils.metrics import DEFAULT as metrics

        state = self.chain.state_at(blk.hash)
        if state is None:
            return  # state already pruned past the window; next cadence
        cons = {
            "members": [(m.addr, m.referee, m.ip, m.port, m.joined_block,
                         m.ttl, m.renewed_times)
                        for m in self.membership.members()],
            "trust_rands": sorted(self.trust_rands.items()),
            "empty_blocks": list(self.empty_block_list),
            "unconfirmed": [b.number for b in self.unconfirmed],
            "registered": self.registered,
        }
        try:
            payload = _ss.encode_checkpoint(blk.hash, state, cons)
            self.chain.store.put_snapshot(payload)
        except Exception as exc:
            # a failed checkpoint write must never stall consensus: the
            # previous sidecar (or full replay) still restarts this node
            self._log("checkpoint write failed", err=repr(exc))  # analysis: allow-swallow(checkpointing is a durability optimization; boot falls back to replay)
            return
        self.journal.record("statesync_checkpoint", blk=blk.number,
                            nbytes=len(payload))
        metrics.counter("statesync.checkpoints").inc()
        metrics.gauge("statesync.checkpoint_bytes").set(len(payload))

    def _handle_confirmed_tail(self, confirmed_blk: Block) -> None:
        """Apply effects of all now-confirmed blocks (ref:
        handleConfirmedBlock geec_state.go:1021-1082)."""
        for blk in self.unconfirmed:
            for reg in blk.header.regs:
                known = self.pending_regs.get(reg.account)
                if known is not None and known.renew <= reg.renew:
                    del self.pending_regs[reg.account]
                try:
                    port = int(reg.port)
                except ValueError:
                    continue  # geec_state.go:1049: unparsable port ignored
                self.membership.add(Member(
                    addr=reg.account, referee=reg.referee, ip=reg.ip,
                    port=port, joined_block=blk.number,
                    ttl=self.membership.initial_ttl,
                    renewed_times=reg.renew))
                if reg.account == self.coinbase:
                    self.registered = True
                    self._cancel_timer("register")
            for txn in blk.geec_txns:
                if self.geec_txn_sink is not None:
                    self.geec_txn_sink(txn)
            if self.cfg.failure_test:
                self._check_membership(blk)
        self.unconfirmed = []
        self.empty_block_list = []

    def _check_membership(self, blk: Block) -> None:
        """TTL economy per confirmed block (ref: CheckMembership
        geec_state.go:1088-1129)."""
        if blk.confirm is not None:
            self.membership.reward(list(blk.confirm.supporters)
                                   + [blk.header.coinbase])
        if blk.number % self.membership.ttl_interval == 0:
            self.membership.decay()
            if (self.membership.needs_renewal(self.coinbase)
                    and self.mine):
                me = self.membership.get(self.coinbase)
                self._start_registration(renew=me.renewed_times + 1)
            elif self.coinbase not in self.membership and self.registered:
                # our own TTL ran out — typically discovered while
                # replaying blocks missed behind a partition, where the
                # renewal window passed unseen (ref: the node-expiry
                # path, core/geec_state.go:706,1088).  Clear the stale
                # registered flag and rejoin from scratch so the heal
                # ends in clean re-registration, not a silent zombie.
                self.registered = False
                if self.mine and self.transport is not None:
                    self._start_registration(renew=0)

    # ------------------------------------------------------------------
    # registration (ref: Register geec_state.go:706-757)
    # ------------------------------------------------------------------

    def request_registration(self) -> None:  # thread-entry (RPC worker)
        """Public join-request trigger (the thw RPC namespace's Register,
        ref: consensus/geec/api.go)."""
        with self._lock:
            self._start_registration(renew=0)

    def _start_registration(self, renew: int) -> None:
        me = self.membership.get(self.coinbase)
        if me is not None and me.renewed_times >= renew > 0:
            return
        reg = Registration(account=self.coinbase, referee=self.coinbase,
                           ip=self.cfg.consensus_ip,
                           port=str(self.cfg.consensus_port),
                           renew=renew)
        self._registration_tick(reg, attempt=0)

    def _registration_tick(self, reg: Registration, attempt: int) -> None:
        if self.registered and reg.renew == 0:
            return
        self._append_reg_req(reg)  # local pending list too
        if self.transport is not None:
            # transport is None only during construction-time replay
            # (a restarted node re-discovering a pending renewal); the
            # timer below re-sends once the node is live on the net
            self.transport.gossip(M.pack_gossip(M.GOSSIP_REGISTER_REQ, reg))
        self._set_timer("register", self.ccfg.reg_timeout_s,
                        lambda: self._registration_tick(reg, attempt + 1))

    def _append_reg_req(self, reg: Registration) -> None:
        """(ref: AppendRegReq geec_state.go:669-683)"""
        known = self.pending_regs.get(reg.account)
        if (known is not None and known.ip == reg.ip and known.port == reg.port
                and known.renew >= reg.renew):
            return
        if (known is None
                and len(self.pending_regs) >= self.REG_PENDING_MAX):
            # a gossip flood of forged registrations evicts the oldest
            # pending request instead of growing the dict without bound
            self.pending_regs.pop(next(iter(self.pending_regs)))
            from eges_tpu.utils.metrics import DEFAULT as metrics
            metrics.counter("consensus.reg_req_dropped").inc()
            ledger.charge(drops=1)
        self.pending_regs[reg.account] = reg

    # ------------------------------------------------------------------
    # failure handling: timeout ladder (ref: blockLoop
    # geec_state.go:1140-1180)
    # ------------------------------------------------------------------

    def _arm_block_timeout(self) -> None:
        self._set_timer("block_timeout", self.cfg.block_timeout_s,
                        self._on_block_timeout)

    def _on_block_timeout(self) -> None:
        with self._lock:
            if self.wb.blk_num == 1:
                self._arm_block_timeout()  # no timeout during bootstrap
                return
            if self._timeout_times < 3:
                self._timeout_times += 1
                self._arm_block_timeout()
                self._handle_committee_timeout(self._timeout_times)
            else:
                self._timeout_times = 0
                self._arm_block_timeout()
                self._force_empty_block()

    def _force_empty_block(self) -> None:
        """(ref: HandleBlockTimeout geec_state.go:927-953)"""
        from eges_tpu.utils.metrics import DEFAULT as metrics
        metrics.counter("consensus.forced_empties").inc()
        empty = self.chain.make_empty_block()
        confirm = ConfirmBlockMsg(block_number=empty.number, hash=empty.hash,
                                  confidence=0, empty_block=True)
        self.empty_block_list.append(empty.number)
        while len(self.empty_block_list) > self.HEIGHT_WINDOW:
            self.empty_block_list.pop(0)
        self.chain.offer(empty.with_confirm(confirm))

    def _handle_committee_timeout(self, version: int) -> None:
        """Re-elect at a higher version then query what happened
        (ref: HandleCommitteeTimeout geec_state.go:1286-1405)."""
        blk_num = self.wb.blk_num
        if not self.is_committee(blk_num, version):
            return
        self._abort_proposal()
        self._try_propose(version)

    # -- query protocol (recovered leader side) -------------------------

    def _start_query(self, blk_num: int, version: int) -> None:
        wb = self.wb
        wb.query_threshold = self.membership.validate_threshold()
        wb.query_replies.clear()
        wb.query_empty_count = 0
        wb.query_nonempty_count = 0
        wb.query_recv_majority = False
        self._phase = VALIDATING  # reuse phase slot for retry gating
        self._query_retry(blk_num, version, 0)

    def _query_retry(self, blk_num: int, version: int, retry: int) -> None:
        if blk_num != self.wb.blk_num or self.wb.query_recv_majority:
            return
        q = QueryBlockMsg(block_number=blk_num, version=version,
                          ip=self.cfg.consensus_ip, retry=retry,
                          port=self.cfg.consensus_port)
        self.transport.gossip(M.pack_gossip(M.GOSSIP_QUERY, q))
        self._set_timer("query", self.ccfg.validate_timeout_ms / 1e3,
                        lambda: self._query_retry(blk_num, version, retry + 1))

    def _handle_query_reply(self, reply: M.QueryReply) -> None:
        """(ref: handleQueryReply geec_state.go:1231-1283).  Same
        acceptor-window gate as the ACK tally: only seeded acceptors may
        count toward the query quorum."""
        wb = self.wb
        if reply.block_num != wb.blk_num or reply.version != wb.max_version:
            return
        seed = self.seed_for(reply.block_num)
        if seed is None or not self.membership.is_acceptor(reply.author, seed):
            return
        lst = wb.query_replies.setdefault(reply.author, [])
        if len(lst) < 2 and all(r.sig != reply.sig for r in lst):
            lst.append(reply)
        if (len(wb.query_replies) >= wb.query_threshold
                and not wb.query_recv_majority):
            if self._signing:
                items = [(r.author, r.signing_hash(), r.sig)
                         for rl in wb.query_replies.values() for r in rl]
                cert = self._verify_quorum(items)
                for a in list(wb.query_replies):
                    if a not in cert:
                        del wb.query_replies[a]
                if len(wb.query_replies) < wb.query_threshold:
                    return  # keep collecting; query retry re-solicits
                wb.query_cert = cert
                # the verified reply per author = the one whose sig the
                # batch recovered
                wb.query_verified = {
                    a: next(r for r in rl if r.sig == cert[a])
                    for a, rl in wb.query_replies.items()}
            else:
                wb.query_verified = {a: rl[0]
                                     for a, rl in wb.query_replies.items()}
            # tally from the verified replies only
            replies = list(wb.query_verified.values())
            wb.query_empty_count = sum(1 for r in replies if r.empty)
            nonempty = [r.block_hash for r in replies if not r.empty]
            if nonempty:
                # majority hash among non-empty answers
                self._query_block_hash = max(set(nonempty),
                                             key=nonempty.count)
            if self._signing:
                # the cert must be coherent: only same-hash answers can
                # certify a non-empty outcome
                wb.query_nonempty_count = (
                    nonempty.count(self._query_block_hash) if nonempty else 0)
            else:
                wb.query_nonempty_count = len(nonempty)
            wb.query_recv_majority = True
            self._cancel_timer("query")
            self._resolve_query(reply.block_num, reply.version)

    def _resolve_query(self, blk_num: int, version: int) -> None:
        """(ref: QUERY_* decision geec_state.go:1339-1398)"""
        wb = self.wb
        head = self.chain.head()
        head_conf = head.confirm.confidence if head.confirm else 0
        def query_cert(members) -> tuple[tuple, tuple]:
            sups = tuple(members)
            sigs = (tuple(wb.query_cert.get(a, b"") for a in sups)
                    if self._signing else ())
            return sups, sigs

        if wb.query_empty_count >= wb.query_threshold:
            # nobody saw a block: confirm an empty one.  The quorum cert
            # is the empty-answering repliers' signatures (version > 0
            # marks it as a query cert for receivers).
            self._phase = IDLE
            empty = self.chain.make_empty_block()
            sups, sigs = query_cert(
                a for a, r in wb.query_verified.items() if r.empty)
            confirm = ConfirmBlockMsg(block_number=blk_num, hash=empty.hash,
                                      confidence=calc_confidence(head_conf),
                                      supporters=sups, empty_block=True,
                                      version=version, supporter_sigs=sigs)
            confirm = dataclasses.replace(
                confirm, sig=self._sign(confirm.signing_hash()))
            self.chain.offer(empty.with_confirm(confirm))
            self.transport.gossip(M.pack_gossip(M.GOSSIP_CONFIRM_BLOCK, confirm))
        elif wb.query_nonempty_count >= wb.query_threshold:
            # majority saw the block: confirm it
            self._phase = IDLE
            sups, sigs = query_cert(
                a for a, r in wb.query_verified.items()
                if not r.empty and r.block_hash == self._query_block_hash)
            confirm = ConfirmBlockMsg(block_number=blk_num,
                                      hash=self._query_block_hash,
                                      confidence=calc_confidence(head_conf),
                                      supporters=sups, empty_block=False,
                                      version=version, supporter_sigs=sigs)
            confirm = dataclasses.replace(
                confirm, sig=self._sign(confirm.signing_hash()))
            pending = self.pending_blocks.get(blk_num)
            if pending is not None and pending.hash == confirm.hash:
                self.chain.offer(pending.with_confirm(confirm))
            self.transport.gossip(M.pack_gossip(M.GOSSIP_CONFIRM_BLOCK, confirm))
        else:
            # mixed: re-run the ACK round for the pending block
            pending = self.pending_blocks.get(blk_num)
            if pending is None:
                self._phase = IDLE
                return
            req = M.ValidateRequest(
                block_num=blk_num, author=self.coinbase, block=pending,
                ip=self.cfg.consensus_ip, port=self.cfg.consensus_port,
                retry=0, version=version,
                empty_list=tuple(self.empty_block_list))
            req = dataclasses.replace(req, sig=self._sign(req.signing_hash()))
            self._proposal = pending
            self._proposal_version = version
            self._ask_for_ack(req)

    # -- query serving (ref: HandleQueryMsg eth/handler.go:897-997) ------

    def _handle_query(self, query: QueryBlockMsg) -> None:
        wb = self.wb
        verdict = wb.classify(query.block_number)
        if verdict == WB_PASSED:
            return
        if verdict == WB_FUTURE:
            self._defer(query.block_number, lambda: self._handle_query(query))
            return
        if query.version < wb.max_version:
            return
        if query.version > wb.max_version:
            self._bump_version(query.version)
            if self._phase in (ELECTING, VALIDATING):
                self._abort_proposal()
        if query.retry <= wb.max_query_retry:
            return
        wb.max_query_retry = query.retry
        self.transport.gossip(M.pack_gossip(M.GOSSIP_QUERY, query))
        if not self.is_acceptor(query.block_number):
            return
        pending = self.pending_blocks.get(query.block_number)
        reply = M.QueryReply(
            block_num=query.block_number, author=self.coinbase,
            version=query.version, retry=query.retry,
            empty=pending is None,
            block_hash=pending.hash if pending is not None else bytes(32))
        reply = dataclasses.replace(reply,
                                    sig=self._sign(reply.signing_hash()))
        self.transport.send_direct(
            query.ip, query.port,
            M.pack_direct(M.UDP_QUERY_REPLY, self.coinbase, reply))
