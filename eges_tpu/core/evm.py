"""EVM subset: contract create/call with gas metering and precompiles.

Fills the ``core/vm`` role for the capability set (ref: core/vm/evm.go,
core/vm/interpreter.go, core/vm/contracts.go, core/vm/gas_table.go).
This is a deliberate subset, not a consensus-grade mainnet EVM: the
homestead-era opcode set the reference's chain config enables, a
simplified-but-deterministic gas schedule (constants below; identical on
every node, which is what consensus needs), and the four classic
precompiles — with **ecrecover routed through the batch verifier** when
one is attached, so even in-contract signature checks ride the TPU path
(SURVEY §3.5's hot loop).

Design choices vs the reference:

* Frames run on a :class:`~eges_tpu.core.state.StateDB` overlay copy and
  either ``absorb`` (success) or drop (revert) — replacing geth's
  journal/revert machinery (core/state/journal.go) with the snapshot
  structure the chain layer already has.
* Storage writes accumulate in a per-frame cache and flush as one merge
  per touched account (``set_storage_many``), so SSTORE in a loop is
  O(1) amortized instead of O(account storage).
* The interpreter is a GENERATOR driven by an explicit frame trampoline
  (``_drive``): a CALL/CREATE opcode *yields* a sub-call request instead
  of recursing, so Python stack depth stays O(1) at any EVM depth — the
  full ``params.CallCreateDepth = 1024`` of the reference
  (core/vm/evm.go:44) with no ``setrecursionlimit`` hack and no
  interpreter-crash class (r5 verdict item 6).
* Byzantium-rule gas refund counter: 15 000 per SSTORE nonzero->zero
  (ref: core/vm/gas_table.go:117 gasSStore pre-Constantinople) and
  24 000 per first SELFDESTRUCT of an address (params.SuicideRefundGas),
  rolled back frame-wise on revert like the reference's journal; the
  txn-level cap of gas_used/2 is applied in
  :func:`eges_tpu.core.state.apply_txn` (core/state_transition.go
  refundGas).  No access lists (post-Berlin; out of the reference's
  chain-config scope).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from eges_tpu.core.state import StateError
from eges_tpu.crypto.keccak import keccak256

U256 = 1 << 256
MAXU = U256 - 1
STACK_LIMIT = 1024
CALL_DEPTH_LIMIT = 1024  # params.CallCreateDepth (core/vm/evm.go:44)


class EvmError(Exception):
    """Frame-aborting failure: out of gas, bad jump, stack violation…
    Consumes all gas passed to the frame (ref: vm.ErrOutOfGas class)."""


class Revert(Exception):
    def __init__(self, data: bytes):
        self.data = data


# -- gas schedule (simplified; ref role: core/vm/gas_table.go) -------------
G_ZERO_BYTE = 4
G_NONZERO_BYTE = 68
G_TX = 21_000
G_TX_CREATE = 53_000
G_BASE = 2
G_VERYLOW = 3
G_LOW = 5
G_MID = 8
G_HIGH = 10
G_EXP = 10
G_EXP_BYTE = 50
G_SHA3 = 30
G_SHA3_WORD = 6
G_COPY_WORD = 3
G_BALANCE = 400
G_SLOAD = 200
G_SSTORE_SET = 20_000
G_SSTORE_RESET = 5_000
G_JUMPDEST = 1
G_LOG = 375
G_LOG_TOPIC = 375
G_LOG_BYTE = 8
G_CREATE = 32_000
G_CALL = 700
G_CALL_VALUE = 9_000
G_CALL_STIPEND = 2_300
G_NEW_ACCOUNT = 25_000
G_CODE_DEPOSIT_BYTE = 200
G_MEMORY_WORD = 3
G_EXTCODE = 700
G_SELF_DESTRUCT = 5_000
# refunds (ref: params/protocol_params.go SstoreRefundGas /
# SuicideRefundGas; accounting in core/vm/gas_table.go:117)
R_SCLEAR = 15_000
R_SELFDESTRUCT = 24_000


@dataclass
class BlockCtx:
    """Execution environment of the enclosing block (ref: vm.Context)."""

    coinbase: bytes = bytes(20)
    number: int = 0
    time: int = 0
    difficulty: int = 1
    gas_limit: int = 30_000_000
    blockhash: object = None  # callable number -> 32 bytes, or None


@dataclass
class ExecResult:
    success: bool
    gas_used: int
    output: bytes = b""
    logs: tuple = ()
    created: bytes | None = None
    reverted: bool = False  # REVERT opcode vs any other failure — the
    #                         tracers report the two differently, as the
    #                         reference does (vm.ErrExecutionReverted)


@dataclass
class _Frame:
    code: bytes
    addr: bytes            # executing account (storage context)
    caller: bytes
    origin: bytes
    value: int
    data: bytes
    gas: int
    static: bool
    stack: list = field(default_factory=list)
    mem: bytearray = field(default_factory=bytearray)
    pc: int = 0
    ret: bytes = b""       # last sub-call return data
    swrites: dict = field(default_factory=dict)  # slot -> value cache


@dataclass
class _Task:
    """One live frame on the trampoline's explicit stack: the suspended
    interpreter generator plus everything needed to commit or roll back
    when it finishes (the per-frame half of geth's journal)."""

    kind: str              # "call" | "codecall" | "create"
    gen: object            # suspended _run generator
    frame: _Frame
    depth: int
    snapshot: object       # parent state: absorb target / restore point
    frame_state: object    # overlay this frame runs on
    log_mark: int
    refund_mark: int
    suicide_mark: frozenset
    gas: int               # gas handed to the frame
    to: bytes              # account that receives the storage write-set
    new_addr: bytes | None = None


def _words(n: int) -> int:
    return (n + 31) // 32


def _mem_gas(words: int) -> int:
    return G_MEMORY_WORD * words + (words * words) // 512


def _sha256(d: bytes) -> bytes:
    return hashlib.sha256(d).digest()


def _ripemd160(d: bytes) -> bytes:
    try:
        h = hashlib.new("ripemd160", d).digest()
    except Exception:  # openssl without legacy digests
        raise EvmError("ripemd160 unavailable")
    return bytes(12) + h


class EVM:
    """One instance per transaction execution (ref: vm.NewEVM)."""

    def __init__(self, state, ctx: BlockCtx, *, verifier=None, tracer=None):
        self.state = state        # the txn-level StateDB overlay
        self.ctx = ctx
        self.verifier = verifier
        self.logs: list = []
        # per-opcode hook (ref: vm.Config.Tracer -> interpreter.Run's
        # CaptureState) — see eges_tpu.core.tracer.StructLogTracer
        self.tracer = tracer
        # Byzantium refund counter + self-destruct set (ref:
        # state.GetRefund / HasSuicided); both roll back frame-wise on
        # revert via the per-task marks, like the reference's journal
        self.refund = 0
        self.suicides: set[bytes] = set()

    # -- precompiles (ref: core/vm/contracts.go) ------------------------

    def _precompile(self, addr_int: int, data: bytes, gas: int):
        if addr_int == 1:
            cost = 3000
            if gas < cost:
                raise EvmError("oog:precompile")
            out = self._ecrecover(data)
            return out, gas - cost
        if addr_int == 2:
            cost = 60 + 12 * _words(len(data))
            if gas < cost:
                raise EvmError("oog:precompile")
            return _sha256(data), gas - cost
        if addr_int == 3:
            cost = 600 + 120 * _words(len(data))
            if gas < cost:
                raise EvmError("oog:precompile")
            return _ripemd160(data), gas - cost
        if addr_int == 4:
            cost = 15 + 3 * _words(len(data))
            if gas < cost:
                raise EvmError("oog:precompile")
            return data, gas - cost
        if addr_int == 5:
            return self._modexp(data, gas)
        if addr_int in (6, 7, 8):
            return self._bn256(addr_int, data, gas)
        return None

    @staticmethod
    def _modexp(data: bytes, gas: int):
        """0x05 bigModExp (EIP-198; ref: core/vm/contracts.go bigModExp)."""
        d = data.ljust(96, b"\0")
        bl = int.from_bytes(d[:32], "big")
        el = int.from_bytes(d[32:64], "big")
        ml = int.from_bytes(d[64:96], "big")
        if max(bl, el, ml) > 1 << 20:  # 1 MiB operand cap
            raise EvmError("modexp: operand too large")
        body = data[96:].ljust(bl + el + ml, b"\0")
        base = int.from_bytes(body[:bl], "big")
        exp = int.from_bytes(body[bl : bl + el], "big")
        mod = int.from_bytes(body[bl + el : bl + el + ml], "big")
        # EIP-198 gas: mult_complexity(max(bl, ml)) * max(adj_exp_len, 1) / 20
        w = max(bl, ml)
        if w <= 64:
            mult = w * w
        elif w <= 1024:
            mult = w * w // 4 + 96 * w - 3072
        else:
            mult = w * w // 16 + 480 * w - 199_680
        if el <= 32:
            adj = max(exp.bit_length() - 1, 0)
        else:
            head = int.from_bytes(body[bl : bl + 32], "big")
            adj = 8 * (el - 32) + max(head.bit_length() - 1, 0)
        cost = max(mult * max(adj, 1) // 20, 200)
        if gas < cost:
            raise EvmError("oog:precompile")
        out = (b"" if ml == 0
               else (0 if mod == 0 else pow(base, exp, mod)
                     ).to_bytes(ml, "big"))
        return out, gas - cost

    # -- alt_bn128 precompiles (EIP-196/197; ref: core/vm/contracts.go
    # bn256Add/bn256ScalarMul/bn256Pairing over crypto/bn256) ------------

    @staticmethod
    def _bn_g1(data: bytes):
        from eges_tpu.crypto import bn254 as bn

        x = int.from_bytes(data[:32], "big")
        y = int.from_bytes(data[32:64], "big")
        if x == 0 and y == 0:
            return None
        pt = (x, y)
        if not bn.g1_is_on_curve(pt):
            raise EvmError("bn256: point not on curve")
        return pt

    @staticmethod
    def _bn_g2(data: bytes):
        from eges_tpu.crypto import bn254 as bn

        # EIP-197 encodes F_p2 elements imaginary-part first
        xi = int.from_bytes(data[:32], "big")
        xr = int.from_bytes(data[32:64], "big")
        yi = int.from_bytes(data[64:96], "big")
        yr = int.from_bytes(data[96:128], "big")
        if xi == xr == yi == yr == 0:
            return None
        if max(xi, xr, yi, yr) >= bn.P:
            raise EvmError("bn256: coordinate out of field")
        pt = ((xr, xi), (yr, yi))
        if not bn.g2_in_subgroup(pt):
            raise EvmError("bn256: G2 point not in subgroup")
        return pt

    def _bn256(self, addr_int: int, data: bytes, gas: int):
        from eges_tpu.crypto import bn254 as bn

        if addr_int == 6:  # ECADD
            cost = 500
            if gas < cost:
                raise EvmError("oog:precompile")
            d = data.ljust(128, b"\0")[:128]
            s = bn.g1_add(self._bn_g1(d[:64]), self._bn_g1(d[64:128]))
            out = (bytes(64) if s is None
                   else s[0].to_bytes(32, "big") + s[1].to_bytes(32, "big"))
            return out, gas - cost
        if addr_int == 7:  # ECMUL
            cost = 40_000
            if gas < cost:
                raise EvmError("oog:precompile")
            d = data.ljust(96, b"\0")[:96]
            k = int.from_bytes(d[64:96], "big")
            s = bn.g1_mul(k, self._bn_g1(d[:64]))
            out = (bytes(64) if s is None
                   else s[0].to_bytes(32, "big") + s[1].to_bytes(32, "big"))
            return out, gas - cost
        # ECPAIRING.  Priced WELL above mainnet (100k + 80k/pair): the
        # pairing here is pure Python (~0.1 s/pair incl. the G2 subgroup
        # check), and the gas schedule must make an adversarial
        # pairing-stuffed block expensive enough that the block gas cap
        # bounds validation time (this chain's schedule only needs to be
        # deterministic, not mainnet-equal)
        if len(data) % 192 != 0:
            raise EvmError("bn256: pairing input not a multiple of 192")
        k = len(data) // 192
        cost = 300_000 + 600_000 * k
        if gas < cost:
            raise EvmError("oog:precompile")
        pairs = []
        for i in range(k):
            chunk = data[192 * i : 192 * (i + 1)]
            pairs.append((self._bn_g1(chunk[:64]), self._bn_g2(chunk[64:])))
        ok = bn.pairing_check(pairs)
        return (1 if ok else 0).to_bytes(32, "big"), gas - cost

    def _ecrecover(self, data: bytes) -> bytes:
        """The 0x01 precompile, routed through the device batch verifier
        when attached (a 1-row batch; the pool pads it into a bucket) —
        in-contract signature checks take the same TPU path as txn
        senders (ref: core/vm/contracts.go ecrecover -> crypto.Ecrecover)."""
        d = data.ljust(128, b"\0")[:128]
        h, v, r, s = d[:32], d[32:64], d[64:96], d[96:128]
        if v[:31] != bytes(31) or v[31] not in (27, 28):
            return b""
        sig65 = r + s + bytes([v[31] - 27])
        if self.verifier is not None:
            import numpy as np

            sigs = np.frombuffer(sig65, np.uint8).reshape(1, 65)
            hs = np.frombuffer(h, np.uint8).reshape(1, 32)
            addrs, ok = self.verifier.recover_addresses(sigs, hs)
            if not ok[0]:
                return b""
            return bytes(12) + bytes(addrs[0])
        from eges_tpu.crypto import secp256k1 as host

        try:
            return bytes(12) + host.recover_address(h, sig65)
        except Exception:
            return b""

    # -- entry points ----------------------------------------------------
    #
    # call()/create() build a root request and hand it to the frame
    # trampoline.  All nesting happens on an EXPLICIT task stack — a
    # CALL opcode yields a request instead of recursing, so EVM depth
    # 1024 costs 1024 suspended generators, not 1024 * k Python stack
    # frames (the reference runs frames on goroutine stacks,
    # core/vm/evm.go Call -> interpreter.Run; goroutines grow, CPython
    # frames don't — hence this redesign rather than a recursion bump).

    def call(self, caller: bytes, to: bytes, value: int, data: bytes,
             gas: int, *, depth: int = 0, static: bool = False,
             origin: bytes | None = None) -> ExecResult:
        """Message call against ``to`` (ref: evm.Call, core/vm/evm.go)."""
        origin = origin if origin is not None else caller
        return self._drive(
            "call", (caller, to, value, data, gas, static, origin), depth,
            "CALL")

    def create(self, caller: bytes, value: int, init_code: bytes,
               gas: int, nonce: int, *, depth: int = 0,
               origin: bytes | None = None) -> ExecResult:
        """Contract creation (ref: evm.Create)."""
        origin = origin if origin is not None else caller
        return self._drive(
            "create", (caller, value, init_code, gas, nonce, origin), depth,
            "CREATE")

    # -- frame trampoline -------------------------------------------------

    def _trace_enter(self, kind: str, typ: str, args: tuple,
                     depth: int) -> None:
        """Frame-boundary tracer hook (ref: vm.EVMLogger CaptureEnter) —
        the call-tree tracers (callTracer/prestateTracer/4byteTracer)
        build on these rather than on per-opcode steps."""
        t = self.tracer
        if t is None or not hasattr(t, "on_enter"):
            return
        if kind == "create":
            from eges_tpu.core.state import contract_address

            caller, value, init_code, gas, nonce, _origin = args
            new_addr = contract_address(caller, nonce)
            # context = the address the init code's SSTOREs land on,
            # so prestate attribution is correct for creations too
            t.on_enter(dict(type=typ, frm=caller, to=None,
                            context=new_addr, value=value,
                            input=init_code, gas=gas, depth=depth))
        elif kind == "call":
            caller, to, value, data, gas, _st, _or = args
            t.on_enter(dict(type=typ, frm=caller, to=to, context=to,
                            value=value, input=data, gas=gas, depth=depth))
        else:  # codecall: callee code in the caller's storage context
            code_addr, storage_addr, value, data, gas, caller, _or, \
                _st = args
            t.on_enter(dict(type=typ, frm=caller, to=code_addr,
                            context=storage_addr, value=value, input=data,
                            gas=gas, depth=depth))

    def _trace_exit(self, res: ExecResult, depth: int) -> None:
        t = self.tracer
        if t is not None and hasattr(t, "on_exit"):
            t.on_exit(res, depth)

    def _drive(self, kind: str, args: tuple, depth: int,
               typ: str = "CALL") -> ExecResult:
        """Run the frame machine to completion.

        ``result`` carries a finished child's ExecResult into its
        suspended parent generator; ``None`` starts a fresh one (the
        two cases are exactly ``gen.send``'s contract)."""
        self._trace_enter(kind, typ, args, depth)
        first = self._begin(kind, args, depth)
        if isinstance(first, ExecResult):
            self._trace_exit(first, depth)
            return first
        stack: list[_Task] = [first]
        result = None
        while stack:
            task = stack[-1]
            try:
                req = task.gen.send(result)
                result = None
            except StopIteration as si:
                res = self._finish_ok(
                    task, si.value if si.value is not None else b"")
            except Revert as r:
                res = self._finish_revert(task, r)
            except (EvmError, StateError) as e:
                res = self._finish_err(task, e)
            else:
                self._trace_enter(req[0], req[2], req[1], task.depth + 1)
                sub = self._begin(req[0], req[1], task.depth + 1)
                if isinstance(sub, ExecResult):
                    self._trace_exit(sub, task.depth + 1)
                    result = sub       # fast path: deliver immediately
                else:
                    stack.append(sub)  # result stays None: start child
                continue
            stack.pop()
            self._trace_exit(res, task.depth)
            result = res
        return result

    def _begin(self, kind: str, args: tuple, depth: int):
        """Entry checks + frame setup for one call/create/codecall.

        Returns an ExecResult for the fast/failure paths (depth, balance,
        precompiles, empty code) or a :class:`_Task` to push.  Mirrors
        evm.Call / evm.CallCode / evm.DelegateCall / evm.Create.  Depth
        and balance failures RETURN the gas (gas_used = 0), per the
        reference's ErrDepth/ErrInsufficientBalance handling — the old
        depth path here consumed it, a parity bug."""
        if kind == "create":
            return self._begin_create(args, depth)
        if kind == "call":
            caller, to, value, data, gas, static, origin = args
            code_addr = storage_addr = to
        else:  # codecall: callee code in the caller's storage context
            code_addr, storage_addr, value, data, gas, caller, origin, \
                static = args
        if depth > CALL_DEPTH_LIMIT:
            return ExecResult(False, 0)
        if kind == "call" and value \
                and self.state.balance(caller) < value:
            return ExecResult(False, 0)
        snapshot = self.state
        frame_state = snapshot.copy()
        to_int = int.from_bytes(code_addr, "big")
        try:
            if kind == "call" and value:
                if static:
                    raise EvmError("static value transfer")
                frame_state.sub_balance(caller, value)
                frame_state.add_balance(to, value)
            if 1 <= to_int <= 8:
                out, gas_left = self._precompile(to_int, data, gas)
                snapshot.absorb(frame_state)
                return ExecResult(True, gas - gas_left, out)
        except (EvmError, StateError):
            return ExecResult(False, gas)
        code = frame_state.code(code_addr)
        if not code:
            snapshot.absorb(frame_state)
            return ExecResult(True, 0, b"")
        frame = _Frame(code=code, addr=storage_addr, caller=caller,
                       origin=origin, value=value, data=data, gas=gas,
                       static=static)
        self.state = frame_state
        return _Task(kind, self._run(frame, depth), frame, depth, snapshot,
                     frame_state, len(self.logs), self.refund,
                     frozenset(self.suicides), gas, storage_addr)

    def _begin_create(self, args: tuple, depth: int):
        from eges_tpu.core.state import contract_address

        caller, value, init_code, gas, nonce, origin = args
        if depth > CALL_DEPTH_LIMIT:
            return ExecResult(False, 0)
        if value and self.state.balance(caller) < value:
            return ExecResult(False, 0)
        new_addr = contract_address(caller, nonce)
        snapshot = self.state
        if snapshot.code(new_addr) or snapshot.nonce(new_addr):
            # collision consumes all gas (evm.Create
            # ErrContractAddressCollision)
            return ExecResult(False, gas)
        frame_state = snapshot.copy()
        if value:
            frame_state.sub_balance(caller, value)
            frame_state.add_balance(new_addr, value)
        frame_state.bump_nonce(new_addr)
        frame = _Frame(code=init_code, addr=new_addr, caller=caller,
                       origin=origin, value=value, data=b"", gas=gas,
                       static=False)
        self.state = frame_state
        return _Task("create", self._run(frame, depth), frame, depth,
                     snapshot, frame_state, len(self.logs), self.refund,
                     frozenset(self.suicides), gas, new_addr, new_addr)

    def _finish_ok(self, task: "_Task", out: bytes) -> ExecResult:
        f = task.frame
        if self.tracer is not None:
            self.tracer.on_frame_end(task.depth, f.gas)
        if task.kind == "create":
            deposit = G_CODE_DEPOSIT_BYTE * len(out)
            if f.gas < deposit:
                return self._finish_err(task, EvmError("oog:code deposit"))
            f.gas -= deposit
            task.frame_state.set_storage_many(task.to, f.swrites)
            task.frame_state.set_code(task.to, bytes(out))
            task.snapshot.absorb(task.frame_state)
            self.state = task.snapshot
            return ExecResult(True, task.gas - f.gas, b"",
                              created=task.new_addr)
        task.frame_state.set_storage_many(task.to, f.swrites)
        task.snapshot.absorb(task.frame_state)
        self.state = task.snapshot
        return ExecResult(True, task.gas - f.gas, out)

    def _finish_revert(self, task: "_Task", r: Revert) -> ExecResult:
        del self.logs[task.log_mark:]
        self.refund = task.refund_mark
        self.suicides = set(task.suicide_mark)
        gas_left = getattr(r, "gas_left", 0)
        if self.tracer is not None:
            self.tracer.on_fault(task.depth, gas_left, "execution reverted")
            if task.depth == 0:  # only the txn-level frame's revert data
                self.tracer.output = r.data  # is the trace's output
        self.state = task.snapshot
        return ExecResult(False, task.gas - gas_left, r.data,
                          reverted=True)

    def _finish_err(self, task: "_Task", e: Exception) -> ExecResult:
        del self.logs[task.log_mark:]
        self.refund = task.refund_mark
        self.suicides = set(task.suicide_mark)
        if self.tracer is not None:
            self.tracer.on_fault(task.depth, 0, str(e) or "evm error")
        self.state = task.snapshot
        return ExecResult(False, task.gas)  # all gas consumed

    def _flush_storage(self, f: "_Frame") -> None:
        """Push the frame's SSTORE cache into the live state before a
        sub-call, so reentrant frames observe and may overwrite it; the
        cache restarts empty (reads fall through to state)."""
        if f.swrites:
            self.state.set_storage_many(f.addr, dict(f.swrites))
            f.swrites.clear()

    # -- interpreter loop (ref: core/vm/interpreter.go Run) --------------

    def _run(self, f: _Frame, depth: int) -> bytes:
        jumpdests = None  # computed lazily on first JUMP
        code = f.code

        def use(n: int) -> None:
            if f.gas < n:
                raise EvmError("out of gas")
            f.gas -= n

        def grow(end: int) -> None:
            if end <= len(f.mem):
                return
            new_w = _words(end)
            use(_mem_gas(new_w) - _mem_gas(_words(len(f.mem))))
            f.mem.extend(bytes(new_w * 32 - len(f.mem)))

        def push(v: int) -> None:
            if len(f.stack) >= STACK_LIMIT:
                raise EvmError("stack overflow")
            f.stack.append(v & MAXU)

        def pop() -> int:
            if not f.stack:
                raise EvmError("stack underflow")
            return f.stack.pop()

        def mload(off: int, n: int) -> bytes:
            if n == 0:
                return b""
            grow(off + n)
            return bytes(f.mem[off : off + n])

        def mstore(off: int, data: bytes) -> None:
            if not data:
                return
            grow(off + len(data))
            f.mem[off : off + len(data)] = data

        def sgn(x: int) -> int:
            return x - U256 if x >> 255 else x

        while True:
            if f.pc >= len(code):
                return b""
            op = code[f.pc]
            if self.tracer is not None:
                self.tracer.on_step(f.pc, op, f.gas, depth, f.stack)
            f.pc += 1

            # PUSH1..PUSH32
            if 0x60 <= op <= 0x7F:
                n = op - 0x5F
                use(G_VERYLOW)
                push(int.from_bytes(code[f.pc : f.pc + n], "big"))
                f.pc += n
                continue
            # DUP1..DUP16
            if 0x80 <= op <= 0x8F:
                use(G_VERYLOW)
                i = op - 0x7F
                if len(f.stack) < i:
                    raise EvmError("stack underflow")
                push(f.stack[-i])
                continue
            # SWAP1..SWAP16
            if 0x90 <= op <= 0x9F:
                use(G_VERYLOW)
                i = op - 0x8F
                if len(f.stack) < i + 1:
                    raise EvmError("stack underflow")
                f.stack[-1], f.stack[-i - 1] = f.stack[-i - 1], f.stack[-1]
                continue

            if op == 0x00:  # STOP
                return b""
            elif op == 0x01:  # ADD
                use(G_VERYLOW); push(pop() + pop())
            elif op == 0x02:  # MUL
                use(G_LOW); push(pop() * pop())
            elif op == 0x03:  # SUB
                use(G_VERYLOW); a, b = pop(), pop(); push(a - b)
            elif op == 0x04:  # DIV
                use(G_LOW); a, b = pop(), pop(); push(a // b if b else 0)
            elif op == 0x05:  # SDIV
                use(G_LOW); a, b = sgn(pop()), sgn(pop())
                push(0 if b == 0 else abs(a) // abs(b) * (1 if a * b >= 0 else -1))
            elif op == 0x06:  # MOD
                use(G_LOW); a, b = pop(), pop(); push(a % b if b else 0)
            elif op == 0x07:  # SMOD
                use(G_LOW); a, b = sgn(pop()), sgn(pop())
                push(0 if b == 0 else (abs(a) % abs(b)) * (1 if a >= 0 else -1))
            elif op == 0x08:  # ADDMOD
                use(G_MID); a, b, m = pop(), pop(), pop()
                push((a + b) % m if m else 0)
            elif op == 0x09:  # MULMOD
                use(G_MID); a, b, m = pop(), pop(), pop()
                push((a * b) % m if m else 0)
            elif op == 0x0A:  # EXP
                a, e = pop(), pop()
                use(G_EXP + G_EXP_BYTE * ((e.bit_length() + 7) // 8))
                push(pow(a, e, U256))
            elif op == 0x0B:  # SIGNEXTEND
                use(G_LOW); k, x = pop(), pop()
                if k < 31:
                    bit = 8 * (k + 1) - 1
                    if x >> bit & 1:
                        x |= MAXU ^ ((1 << (bit + 1)) - 1)
                    else:
                        x &= (1 << (bit + 1)) - 1
                push(x)
            elif op == 0x10:  # LT
                use(G_VERYLOW); push(1 if pop() < pop() else 0)
            elif op == 0x11:  # GT
                use(G_VERYLOW); push(1 if pop() > pop() else 0)
            elif op == 0x12:  # SLT
                use(G_VERYLOW); push(1 if sgn(pop()) < sgn(pop()) else 0)
            elif op == 0x13:  # SGT
                use(G_VERYLOW); push(1 if sgn(pop()) > sgn(pop()) else 0)
            elif op == 0x14:  # EQ
                use(G_VERYLOW); push(1 if pop() == pop() else 0)
            elif op == 0x15:  # ISZERO
                use(G_VERYLOW); push(1 if pop() == 0 else 0)
            elif op == 0x16:  # AND
                use(G_VERYLOW); push(pop() & pop())
            elif op == 0x17:  # OR
                use(G_VERYLOW); push(pop() | pop())
            elif op == 0x18:  # XOR
                use(G_VERYLOW); push(pop() ^ pop())
            elif op == 0x19:  # NOT
                use(G_VERYLOW); push(MAXU ^ pop())
            elif op == 0x1A:  # BYTE
                use(G_VERYLOW); i, x = pop(), pop()
                push((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
            elif op == 0x1B:  # SHL
                use(G_VERYLOW); s, x = pop(), pop()
                push(x << s if s < 256 else 0)
            elif op == 0x1C:  # SHR
                use(G_VERYLOW); s, x = pop(), pop()
                push(x >> s if s < 256 else 0)
            elif op == 0x1D:  # SAR
                use(G_VERYLOW); s, x = pop(), sgn(pop())
                push((x >> s if s < 256 else (0 if x >= 0 else MAXU)))
            elif op == 0x20:  # SHA3
                off, n = pop(), pop()
                use(G_SHA3 + G_SHA3_WORD * _words(n))
                push(int.from_bytes(keccak256(mload(off, n)), "big"))
            elif op == 0x30:  # ADDRESS
                use(G_BASE); push(int.from_bytes(f.addr, "big"))
            elif op == 0x31:  # BALANCE
                use(G_BALANCE)
                push(self.state.balance(pop().to_bytes(32, "big")[12:]))
            elif op == 0x32:  # ORIGIN
                use(G_BASE); push(int.from_bytes(f.origin, "big"))
            elif op == 0x33:  # CALLER
                use(G_BASE); push(int.from_bytes(f.caller, "big"))
            elif op == 0x34:  # CALLVALUE
                use(G_BASE); push(f.value)
            elif op == 0x35:  # CALLDATALOAD
                use(G_VERYLOW); off = pop()
                push(int.from_bytes(f.data[off : off + 32].ljust(32, b"\0"),
                                    "big") if off < len(f.data) else 0)
            elif op == 0x36:  # CALLDATASIZE
                use(G_BASE); push(len(f.data))
            elif op == 0x37:  # CALLDATACOPY
                dst, src, n = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _words(n))
                chunk = f.data[src : src + n] if src < len(f.data) else b""
                mstore(dst, chunk.ljust(n, b"\0"))
            elif op == 0x38:  # CODESIZE
                use(G_BASE); push(len(code))
            elif op == 0x39:  # CODECOPY
                dst, src, n = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _words(n))
                chunk = code[src : src + n] if src < len(code) else b""
                mstore(dst, chunk.ljust(n, b"\0"))
            elif op == 0x3A:  # GASPRICE
                use(G_BASE); push(0)
            elif op == 0x3B:  # EXTCODESIZE
                use(G_EXTCODE)
                push(len(self.state.code(pop().to_bytes(32, "big")[12:])))
            elif op == 0x3C:  # EXTCODECOPY
                addr = pop().to_bytes(32, "big")[12:]
                dst, src, n = pop(), pop(), pop()
                use(G_EXTCODE + G_COPY_WORD * _words(n))
                c = self.state.code(addr)
                chunk = c[src : src + n] if src < len(c) else b""
                mstore(dst, chunk.ljust(n, b"\0"))
            elif op == 0x3D:  # RETURNDATASIZE
                use(G_BASE); push(len(f.ret))
            elif op == 0x3E:  # RETURNDATACOPY
                dst, src, n = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _words(n))
                if src + n > len(f.ret):
                    raise EvmError("returndata out of bounds")
                mstore(dst, f.ret[src : src + n])
            elif op == 0x40:  # BLOCKHASH
                use(G_HIGH + 10); n = pop()
                bh = self.ctx.blockhash
                # only the previous 256 ancestors — never the block
                # being executed, whose hash is not yet sealed
                # (ref core/vm/instructions.go opBlockhash: distance
                # 1..256, else zero)
                push(int.from_bytes(bh(n), "big")
                     if bh is not None and 1 <= self.ctx.number - n <= 256
                     else 0)
            elif op == 0x41:  # COINBASE
                use(G_BASE); push(int.from_bytes(self.ctx.coinbase, "big"))
            elif op == 0x42:  # TIMESTAMP
                use(G_BASE); push(self.ctx.time)
            elif op == 0x43:  # NUMBER
                use(G_BASE); push(self.ctx.number)
            elif op == 0x44:  # DIFFICULTY
                use(G_BASE); push(self.ctx.difficulty)
            elif op == 0x45:  # GASLIMIT
                use(G_BASE); push(self.ctx.gas_limit)
            elif op == 0x50:  # POP
                use(G_BASE); pop()
            elif op == 0x51:  # MLOAD
                use(G_VERYLOW); off = pop()
                push(int.from_bytes(mload(off, 32), "big"))
            elif op == 0x52:  # MSTORE
                use(G_VERYLOW); off, v = pop(), pop()
                mstore(off, v.to_bytes(32, "big"))
            elif op == 0x53:  # MSTORE8
                use(G_VERYLOW); off, v = pop(), pop()
                mstore(off, bytes([v & 0xFF]))
            elif op == 0x54:  # SLOAD
                use(G_SLOAD); slot = pop()
                v = f.swrites.get(slot)
                push(v if v is not None
                     else self.state.storage_at(f.addr, slot))
            elif op == 0x55:  # SSTORE
                if f.static:
                    raise EvmError("static sstore")
                slot, v = pop(), pop()
                cur = f.swrites.get(slot)
                if cur is None:
                    cur = self.state.storage_at(f.addr, slot)
                # pre-Constantinople rules (gas_table.go:117 gasSStore):
                # 0->nonzero SET, else RESET; nonzero->0 earns the
                # 15 000 clear refund
                if cur == 0 and v != 0:
                    use(G_SSTORE_SET)
                else:
                    use(G_SSTORE_RESET)
                    if cur != 0 and v == 0:
                        self.refund += R_SCLEAR
                f.swrites[slot] = v
            elif op == 0x56:  # JUMP
                use(G_MID); dst = pop()
                if jumpdests is None:
                    jumpdests = _jumpdests(code)
                if dst not in jumpdests:
                    raise EvmError("bad jump")
                f.pc = dst
            elif op == 0x57:  # JUMPI
                use(G_HIGH); dst, cond = pop(), pop()
                if cond:
                    if jumpdests is None:
                        jumpdests = _jumpdests(code)
                    if dst not in jumpdests:
                        raise EvmError("bad jump")
                    f.pc = dst
            elif op == 0x58:  # PC
                use(G_BASE); push(f.pc - 1)
            elif op == 0x59:  # MSIZE
                use(G_BASE); push(len(f.mem))
            elif op == 0x5A:  # GAS
                use(G_BASE); push(f.gas)
            elif op == 0x5B:  # JUMPDEST
                use(G_JUMPDEST)
            elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                if f.static:
                    raise EvmError("static log")
                n_topics = op - 0xA0
                off, n = pop(), pop()
                topics = tuple(pop().to_bytes(32, "big")
                               for _ in range(n_topics))
                use(G_LOG + G_LOG_TOPIC * n_topics + G_LOG_BYTE * n)
                self.logs.append((f.addr, topics, mload(off, n)))
            elif op == 0xF0:  # CREATE
                if f.static:
                    raise EvmError("static create")
                value, off, n = pop(), pop(), pop()
                use(G_CREATE)
                init = mload(off, n)
                gas_for = f.gas - f.gas // 64
                f.gas -= gas_for
                self._flush_storage(f)
                self.state.bump_nonce(f.addr)
                res = yield ("create", (f.addr, value, init, gas_for,
                                        self.state.nonce(f.addr) - 1,
                                        f.origin), "CREATE")
                f.gas += gas_for - res.gas_used
                f.ret = res.output if not res.success else b""
                push(int.from_bytes(res.created, "big")
                     if res.success and res.created else 0)
            elif op in (0xF1, 0xF2, 0xF4, 0xFA):  # CALL/CALLCODE/DELEGATECALL/STATICCALL
                gas_req = pop()
                to = pop().to_bytes(32, "big")[12:]
                if op in (0xF1, 0xF2):
                    value = pop()
                else:
                    value = 0
                in_off, in_n, out_off, out_n = pop(), pop(), pop(), pop()
                if op == 0xF1 and f.static and value:
                    raise EvmError("static call with value")
                base = G_CALL + (G_CALL_VALUE if value else 0)
                to_int = int.from_bytes(to, "big")
                if (op == 0xF1 and value
                        and self.state.account(to).balance == 0
                        and self.state.nonce(to) == 0
                        and not self.state.code(to)
                        and not (1 <= to_int <= 8)):
                    base += G_NEW_ACCOUNT
                use(base)
                data = mload(in_off, in_n)
                if out_n:
                    grow(out_off + out_n)
                avail = f.gas - f.gas // 64
                gas_for = min(gas_req, avail)
                f.gas -= gas_for
                stipend = G_CALL_STIPEND if value else 0
                # reentrancy: nested frames must see this frame's storage
                # writes, and may write our storage themselves — flush
                # the cache down and re-read from state afterwards
                self._flush_storage(f)
                if op == 0xF2 and value > self.state.balance(f.addr):
                    # CALLCODE checks but does not move the balance
                    # (ref: evm.CallCode CanTransfer); gas is returned
                    res = ExecResult(False, 0)
                elif op == 0xF1:  # CALL
                    res = yield ("call", (f.addr, to, value, data,
                                          gas_for + stipend, f.static,
                                          f.origin), "CALL")
                elif op == 0xF2:  # CALLCODE: callee code, our storage
                    res = yield ("codecall", (to, f.addr, value, data,
                                              gas_for + stipend, f.addr,
                                              f.origin, f.static),
                                 "CALLCODE")
                elif op == 0xF4:  # DELEGATECALL: keep caller+value
                    res = yield ("codecall", (to, f.addr, f.value, data,
                                              gas_for, f.caller,
                                              f.origin, f.static),
                                 "DELEGATECALL")
                else:  # STATICCALL
                    res = yield ("call", (f.addr, to, 0, data, gas_for,
                                          True, f.origin), "STATICCALL")
                # leftover callee gas (incl. unused stipend) returns to
                # the caller, matching the reference's accounting
                # (contract.Gas += returnGas, core/vm/evm.go Call)
                used = min(res.gas_used, gas_for + stipend)
                f.gas += (gas_for + stipend) - used
                f.ret = res.output
                if out_n:
                    # write only what the callee returned; the rest of
                    # the reserved region keeps its prior contents
                    # (ref: memory.Set in opCall — no zero-fill)
                    mstore(out_off, res.output[:out_n])
                push(1 if res.success else 0)
            elif op == 0xF3:  # RETURN
                off, n = pop(), pop()
                return mload(off, n)
            elif op == 0xFD:  # REVERT
                off, n = pop(), pop()
                r = Revert(mload(off, n))
                r.gas_left = f.gas
                raise r
            elif op == 0xFE:  # INVALID
                raise EvmError("invalid opcode 0xfe")
            elif op == 0xFF:  # SELFDESTRUCT
                if f.static:
                    raise EvmError("static selfdestruct")
                heir = pop().to_bytes(32, "big")[12:]
                bal = self.state.balance(f.addr)
                cost = G_SELF_DESTRUCT
                if bal and not self.state.nonce(heir) \
                        and not self.state.balance(heir) \
                        and not self.state.code(heir):
                    # sweeping into a non-existent account pays the
                    # account-creation surcharge (gas_table.go
                    # gasSelfdestruct, EIP-150 rules)
                    cost += G_NEW_ACCOUNT
                use(cost)
                if f.addr not in self.suicides:
                    # 24 000 once per address per txn
                    # (params.SuicideRefundGas via HasSuicided)
                    self.refund += R_SELFDESTRUCT
                    self.suicides.add(f.addr)
                if bal:
                    self.state.sub_balance(f.addr, bal)
                    self.state.add_balance(heir, bal)
                # the account itself is deleted at txn finalization
                # (state.apply_txn), matching Finalise-time deletion
                return b""
            else:
                raise EvmError(f"unknown opcode {op:#x}")

def _jumpdests(code: bytes) -> set[int]:
    """Valid JUMPDEST offsets (PUSH data bytes excluded)."""
    out = set()
    i = 0
    n = len(code)
    while i < n:
        op = code[i]
        if op == 0x5B:
            out.add(i)
        i += (op - 0x5E) if 0x60 <= op <= 0x7F else 1
    return out


def intrinsic_gas(data: bytes, is_create: bool) -> int:
    """(ref: core/state_transition.go IntrinsicGas)"""
    g = G_TX_CREATE if is_create else G_TX
    for b in data:
        g += G_NONZERO_BYTE if b else G_ZERO_BYTE
    return g
