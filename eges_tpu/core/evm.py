"""EVM subset: contract create/call with gas metering and precompiles.

Fills the ``core/vm`` role for the capability set (ref: core/vm/evm.go,
core/vm/interpreter.go, core/vm/contracts.go, core/vm/gas_table.go).
This is a deliberate subset, not a consensus-grade mainnet EVM: the
homestead-era opcode set the reference's chain config enables, a
simplified-but-deterministic gas schedule (constants below; identical on
every node, which is what consensus needs), and the four classic
precompiles — with **ecrecover routed through the batch verifier** when
one is attached, so even in-contract signature checks ride the TPU path
(SURVEY §3.5's hot loop).

Design choices vs the reference:

* Frames run on a :class:`~eges_tpu.core.state.StateDB` overlay copy and
  either ``absorb`` (success) or drop (revert) — replacing geth's
  journal/revert machinery (core/state/journal.go) with the snapshot
  structure the chain layer already has.
* Storage writes accumulate in a per-frame cache and flush as one merge
  per touched account (``set_storage_many``), so SSTORE in a loop is
  O(1) amortized instead of O(account storage).
* No gas refund counter, no SELFDESTRUCT refund, no access lists —
  documented simplifications that keep the schedule monotone.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from eges_tpu.core.state import StateError
from eges_tpu.crypto.keccak import keccak256

U256 = 1 << 256
MAXU = U256 - 1
STACK_LIMIT = 1024
CALL_DEPTH_LIMIT = 256  # the reference allows 1024 (params.CallCreateDepth);
#                         capped lower here to stay inside Python recursion

import sys as _sys

if _sys.getrecursionlimit() < 4000:
    # each EVM call level costs a handful of Python frames; the default
    # 1000-frame limit sits below CALL_DEPTH_LIMIT's worst case
    _sys.setrecursionlimit(4000)


class EvmError(Exception):
    """Frame-aborting failure: out of gas, bad jump, stack violation…
    Consumes all gas passed to the frame (ref: vm.ErrOutOfGas class)."""


class Revert(Exception):
    def __init__(self, data: bytes):
        self.data = data


# -- gas schedule (simplified; ref role: core/vm/gas_table.go) -------------
G_ZERO_BYTE = 4
G_NONZERO_BYTE = 68
G_TX = 21_000
G_TX_CREATE = 53_000
G_BASE = 2
G_VERYLOW = 3
G_LOW = 5
G_MID = 8
G_HIGH = 10
G_EXP = 10
G_EXP_BYTE = 50
G_SHA3 = 30
G_SHA3_WORD = 6
G_COPY_WORD = 3
G_BALANCE = 400
G_SLOAD = 200
G_SSTORE_SET = 20_000
G_SSTORE_RESET = 5_000
G_JUMPDEST = 1
G_LOG = 375
G_LOG_TOPIC = 375
G_LOG_BYTE = 8
G_CREATE = 32_000
G_CALL = 700
G_CALL_VALUE = 9_000
G_CALL_STIPEND = 2_300
G_NEW_ACCOUNT = 25_000
G_CODE_DEPOSIT_BYTE = 200
G_MEMORY_WORD = 3
G_EXTCODE = 700
G_SELF_DESTRUCT = 5_000


@dataclass
class BlockCtx:
    """Execution environment of the enclosing block (ref: vm.Context)."""

    coinbase: bytes = bytes(20)
    number: int = 0
    time: int = 0
    difficulty: int = 1
    gas_limit: int = 30_000_000
    blockhash: object = None  # callable number -> 32 bytes, or None


@dataclass
class ExecResult:
    success: bool
    gas_used: int
    output: bytes = b""
    logs: tuple = ()
    created: bytes | None = None


@dataclass
class _Frame:
    code: bytes
    addr: bytes            # executing account (storage context)
    caller: bytes
    origin: bytes
    value: int
    data: bytes
    gas: int
    static: bool
    stack: list = field(default_factory=list)
    mem: bytearray = field(default_factory=bytearray)
    pc: int = 0
    ret: bytes = b""       # last sub-call return data
    swrites: dict = field(default_factory=dict)  # slot -> value cache


def _words(n: int) -> int:
    return (n + 31) // 32


def _mem_gas(words: int) -> int:
    return G_MEMORY_WORD * words + (words * words) // 512


def _sha256(d: bytes) -> bytes:
    return hashlib.sha256(d).digest()


def _ripemd160(d: bytes) -> bytes:
    try:
        h = hashlib.new("ripemd160", d).digest()
    except Exception:  # openssl without legacy digests
        raise EvmError("ripemd160 unavailable")
    return bytes(12) + h


class EVM:
    """One instance per transaction execution (ref: vm.NewEVM)."""

    def __init__(self, state, ctx: BlockCtx, *, verifier=None, tracer=None):
        self.state = state        # the txn-level StateDB overlay
        self.ctx = ctx
        self.verifier = verifier
        self.logs: list = []
        # per-opcode hook (ref: vm.Config.Tracer -> interpreter.Run's
        # CaptureState) — see eges_tpu.core.tracer.StructLogTracer
        self.tracer = tracer

    # -- precompiles (ref: core/vm/contracts.go) ------------------------

    def _precompile(self, addr_int: int, data: bytes, gas: int):
        if addr_int == 1:
            cost = 3000
            if gas < cost:
                raise EvmError("oog:precompile")
            out = self._ecrecover(data)
            return out, gas - cost
        if addr_int == 2:
            cost = 60 + 12 * _words(len(data))
            if gas < cost:
                raise EvmError("oog:precompile")
            return _sha256(data), gas - cost
        if addr_int == 3:
            cost = 600 + 120 * _words(len(data))
            if gas < cost:
                raise EvmError("oog:precompile")
            return _ripemd160(data), gas - cost
        if addr_int == 4:
            cost = 15 + 3 * _words(len(data))
            if gas < cost:
                raise EvmError("oog:precompile")
            return data, gas - cost
        if addr_int == 5:
            return self._modexp(data, gas)
        if addr_int in (6, 7, 8):
            return self._bn256(addr_int, data, gas)
        return None

    @staticmethod
    def _modexp(data: bytes, gas: int):
        """0x05 bigModExp (EIP-198; ref: core/vm/contracts.go bigModExp)."""
        d = data.ljust(96, b"\0")
        bl = int.from_bytes(d[:32], "big")
        el = int.from_bytes(d[32:64], "big")
        ml = int.from_bytes(d[64:96], "big")
        if max(bl, el, ml) > 1 << 20:  # 1 MiB operand cap
            raise EvmError("modexp: operand too large")
        body = data[96:].ljust(bl + el + ml, b"\0")
        base = int.from_bytes(body[:bl], "big")
        exp = int.from_bytes(body[bl : bl + el], "big")
        mod = int.from_bytes(body[bl + el : bl + el + ml], "big")
        # EIP-198 gas: mult_complexity(max(bl, ml)) * max(adj_exp_len, 1) / 20
        w = max(bl, ml)
        if w <= 64:
            mult = w * w
        elif w <= 1024:
            mult = w * w // 4 + 96 * w - 3072
        else:
            mult = w * w // 16 + 480 * w - 199_680
        if el <= 32:
            adj = max(exp.bit_length() - 1, 0)
        else:
            head = int.from_bytes(body[bl : bl + 32], "big")
            adj = 8 * (el - 32) + max(head.bit_length() - 1, 0)
        cost = max(mult * max(adj, 1) // 20, 200)
        if gas < cost:
            raise EvmError("oog:precompile")
        out = (b"" if ml == 0
               else (0 if mod == 0 else pow(base, exp, mod)
                     ).to_bytes(ml, "big"))
        return out, gas - cost

    # -- alt_bn128 precompiles (EIP-196/197; ref: core/vm/contracts.go
    # bn256Add/bn256ScalarMul/bn256Pairing over crypto/bn256) ------------

    @staticmethod
    def _bn_g1(data: bytes):
        from eges_tpu.crypto import bn254 as bn

        x = int.from_bytes(data[:32], "big")
        y = int.from_bytes(data[32:64], "big")
        if x == 0 and y == 0:
            return None
        pt = (x, y)
        if not bn.g1_is_on_curve(pt):
            raise EvmError("bn256: point not on curve")
        return pt

    @staticmethod
    def _bn_g2(data: bytes):
        from eges_tpu.crypto import bn254 as bn

        # EIP-197 encodes F_p2 elements imaginary-part first
        xi = int.from_bytes(data[:32], "big")
        xr = int.from_bytes(data[32:64], "big")
        yi = int.from_bytes(data[64:96], "big")
        yr = int.from_bytes(data[96:128], "big")
        if xi == xr == yi == yr == 0:
            return None
        if max(xi, xr, yi, yr) >= bn.P:
            raise EvmError("bn256: coordinate out of field")
        pt = ((xr, xi), (yr, yi))
        if not bn.g2_in_subgroup(pt):
            raise EvmError("bn256: G2 point not in subgroup")
        return pt

    def _bn256(self, addr_int: int, data: bytes, gas: int):
        from eges_tpu.crypto import bn254 as bn

        if addr_int == 6:  # ECADD
            cost = 500
            if gas < cost:
                raise EvmError("oog:precompile")
            d = data.ljust(128, b"\0")[:128]
            s = bn.g1_add(self._bn_g1(d[:64]), self._bn_g1(d[64:128]))
            out = (bytes(64) if s is None
                   else s[0].to_bytes(32, "big") + s[1].to_bytes(32, "big"))
            return out, gas - cost
        if addr_int == 7:  # ECMUL
            cost = 40_000
            if gas < cost:
                raise EvmError("oog:precompile")
            d = data.ljust(96, b"\0")[:96]
            k = int.from_bytes(d[64:96], "big")
            s = bn.g1_mul(k, self._bn_g1(d[:64]))
            out = (bytes(64) if s is None
                   else s[0].to_bytes(32, "big") + s[1].to_bytes(32, "big"))
            return out, gas - cost
        # ECPAIRING.  Priced WELL above mainnet (100k + 80k/pair): the
        # pairing here is pure Python (~0.1 s/pair incl. the G2 subgroup
        # check), and the gas schedule must make an adversarial
        # pairing-stuffed block expensive enough that the block gas cap
        # bounds validation time (this chain's schedule only needs to be
        # deterministic, not mainnet-equal)
        if len(data) % 192 != 0:
            raise EvmError("bn256: pairing input not a multiple of 192")
        k = len(data) // 192
        cost = 300_000 + 600_000 * k
        if gas < cost:
            raise EvmError("oog:precompile")
        pairs = []
        for i in range(k):
            chunk = data[192 * i : 192 * (i + 1)]
            pairs.append((self._bn_g1(chunk[:64]), self._bn_g2(chunk[64:])))
        ok = bn.pairing_check(pairs)
        return (1 if ok else 0).to_bytes(32, "big"), gas - cost

    def _ecrecover(self, data: bytes) -> bytes:
        """The 0x01 precompile, routed through the device batch verifier
        when attached (a 1-row batch; the pool pads it into a bucket) —
        in-contract signature checks take the same TPU path as txn
        senders (ref: core/vm/contracts.go ecrecover -> crypto.Ecrecover)."""
        d = data.ljust(128, b"\0")[:128]
        h, v, r, s = d[:32], d[32:64], d[64:96], d[96:128]
        if v[:31] != bytes(31) or v[31] not in (27, 28):
            return b""
        sig65 = r + s + bytes([v[31] - 27])
        if self.verifier is not None:
            import numpy as np

            sigs = np.frombuffer(sig65, np.uint8).reshape(1, 65)
            hs = np.frombuffer(h, np.uint8).reshape(1, 32)
            addrs, ok = self.verifier.recover_addresses(sigs, hs)
            if not ok[0]:
                return b""
            return bytes(12) + bytes(addrs[0])
        from eges_tpu.crypto import secp256k1 as host

        try:
            return bytes(12) + host.recover_address(h, sig65)
        except Exception:
            return b""

    # -- entry points ----------------------------------------------------

    def call(self, caller: bytes, to: bytes, value: int, data: bytes,
             gas: int, *, depth: int = 0, static: bool = False,
             origin: bytes | None = None) -> ExecResult:
        """Message call against ``to`` (ref: evm.Call, core/vm/evm.go)."""
        origin = origin if origin is not None else caller
        if depth > CALL_DEPTH_LIMIT:
            return ExecResult(False, gas)
        if value and self.state.balance(caller) < value:
            # insufficient balance fails the call WITHOUT consuming gas
            # (ref: evm.Call ErrInsufficientBalance returns the gas)
            return ExecResult(False, 0)
        snapshot = self.state
        frame_state = snapshot.copy()
        prev_state, self.state = self.state, frame_state
        log_mark = len(self.logs)
        try:
            pre = self._precompile(int.from_bytes(to, "big"), data, gas) \
                if 1 <= int.from_bytes(to, "big") <= 8 else None
            if value:
                if static:
                    raise EvmError("static value transfer")
                frame_state.sub_balance(caller, value)
                frame_state.add_balance(to, value)
            if pre is not None:
                out, gas_left = pre
                snapshot.absorb(frame_state)
                return ExecResult(True, gas - gas_left, out)
            code = frame_state.code(to)
            if not code:
                snapshot.absorb(frame_state)
                return ExecResult(True, 0, b"")
            frame = _Frame(code=code, addr=to, caller=caller, origin=origin,
                           value=value, data=data, gas=gas, static=static)
            out = self._run(frame, depth)
            if self.tracer is not None:
                self.tracer.on_frame_end(depth, frame.gas)
            frame_state.set_storage_many(to, frame.swrites)
            snapshot.absorb(frame_state)
            return ExecResult(True, gas - frame.gas, out)
        except Revert as r:
            del self.logs[log_mark:]
            if self.tracer is not None:
                self.tracer.on_fault(depth, getattr(r, "gas_left", 0),
                                     "execution reverted")
                if depth == 0:  # only the txn-level frame's revert data
                    self.tracer.output = r.data  # is the trace's output
            return ExecResult(False, gas - getattr(r, "gas_left", 0),
                              r.data)
        except (EvmError, StateError) as e:
            del self.logs[log_mark:]
            if self.tracer is not None:
                self.tracer.on_fault(depth, 0, str(e) or "evm error")
            return ExecResult(False, gas)  # all gas consumed
        finally:
            self.state = prev_state

    def create(self, caller: bytes, value: int, init_code: bytes,
               gas: int, nonce: int, *, depth: int = 0,
               origin: bytes | None = None) -> ExecResult:
        """Contract creation (ref: evm.Create)."""
        from eges_tpu.core.state import contract_address

        origin = origin if origin is not None else caller
        if depth > CALL_DEPTH_LIMIT:
            return ExecResult(False, gas)
        if value and self.state.balance(caller) < value:
            return ExecResult(False, 0)  # gas returned, like evm.Create
        new_addr = contract_address(caller, nonce)
        snapshot = self.state
        frame_state = snapshot.copy()
        prev_state, self.state = self.state, frame_state
        log_mark = len(self.logs)
        try:
            if frame_state.code(new_addr) or frame_state.nonce(new_addr):
                raise EvmError("contract collision")
            if value:
                frame_state.sub_balance(caller, value)
                frame_state.add_balance(new_addr, value)
            frame_state.bump_nonce(new_addr)
            frame = _Frame(code=init_code, addr=new_addr, caller=caller,
                           origin=origin, value=value, data=b"", gas=gas,
                           static=False)
            out = self._run(frame, depth)
            if self.tracer is not None:
                self.tracer.on_frame_end(depth, frame.gas)
            deposit = G_CODE_DEPOSIT_BYTE * len(out)
            if frame.gas < deposit:
                raise EvmError("oog:code deposit")
            frame.gas -= deposit
            frame_state.set_storage_many(new_addr, frame.swrites)
            frame_state.set_code(new_addr, bytes(out))
            snapshot.absorb(frame_state)
            return ExecResult(True, gas - frame.gas, b"", created=new_addr)
        except Revert as r:
            del self.logs[log_mark:]
            if self.tracer is not None:
                self.tracer.on_fault(depth, getattr(r, "gas_left", 0),
                                     "execution reverted")
                if depth == 0:  # constructor revert reason, as in call()
                    self.tracer.output = r.data
            return ExecResult(False, gas - getattr(r, "gas_left", 0), r.data)
        except (EvmError, StateError) as e:
            del self.logs[log_mark:]
            if self.tracer is not None:
                self.tracer.on_fault(depth, 0, str(e) or "evm error")
            return ExecResult(False, gas)
        finally:
            self.state = prev_state

    def _flush_storage(self, f: "_Frame") -> None:
        """Push the frame's SSTORE cache into the live state before a
        sub-call, so reentrant frames observe and may overwrite it; the
        cache restarts empty (reads fall through to state)."""
        if f.swrites:
            self.state.set_storage_many(f.addr, dict(f.swrites))
            f.swrites.clear()

    # -- interpreter loop (ref: core/vm/interpreter.go Run) --------------

    def _run(self, f: _Frame, depth: int) -> bytes:
        jumpdests = None  # computed lazily on first JUMP
        code = f.code

        def use(n: int) -> None:
            if f.gas < n:
                raise EvmError("out of gas")
            f.gas -= n

        def grow(end: int) -> None:
            if end <= len(f.mem):
                return
            new_w = _words(end)
            use(_mem_gas(new_w) - _mem_gas(_words(len(f.mem))))
            f.mem.extend(bytes(new_w * 32 - len(f.mem)))

        def push(v: int) -> None:
            if len(f.stack) >= STACK_LIMIT:
                raise EvmError("stack overflow")
            f.stack.append(v & MAXU)

        def pop() -> int:
            if not f.stack:
                raise EvmError("stack underflow")
            return f.stack.pop()

        def mload(off: int, n: int) -> bytes:
            if n == 0:
                return b""
            grow(off + n)
            return bytes(f.mem[off : off + n])

        def mstore(off: int, data: bytes) -> None:
            if not data:
                return
            grow(off + len(data))
            f.mem[off : off + len(data)] = data

        def sgn(x: int) -> int:
            return x - U256 if x >> 255 else x

        while True:
            if f.pc >= len(code):
                return b""
            op = code[f.pc]
            if self.tracer is not None:
                self.tracer.on_step(f.pc, op, f.gas, depth, f.stack)
            f.pc += 1

            # PUSH1..PUSH32
            if 0x60 <= op <= 0x7F:
                n = op - 0x5F
                use(G_VERYLOW)
                push(int.from_bytes(code[f.pc : f.pc + n], "big"))
                f.pc += n
                continue
            # DUP1..DUP16
            if 0x80 <= op <= 0x8F:
                use(G_VERYLOW)
                i = op - 0x7F
                if len(f.stack) < i:
                    raise EvmError("stack underflow")
                push(f.stack[-i])
                continue
            # SWAP1..SWAP16
            if 0x90 <= op <= 0x9F:
                use(G_VERYLOW)
                i = op - 0x8F
                if len(f.stack) < i + 1:
                    raise EvmError("stack underflow")
                f.stack[-1], f.stack[-i - 1] = f.stack[-i - 1], f.stack[-1]
                continue

            if op == 0x00:  # STOP
                return b""
            elif op == 0x01:  # ADD
                use(G_VERYLOW); push(pop() + pop())
            elif op == 0x02:  # MUL
                use(G_LOW); push(pop() * pop())
            elif op == 0x03:  # SUB
                use(G_VERYLOW); a, b = pop(), pop(); push(a - b)
            elif op == 0x04:  # DIV
                use(G_LOW); a, b = pop(), pop(); push(a // b if b else 0)
            elif op == 0x05:  # SDIV
                use(G_LOW); a, b = sgn(pop()), sgn(pop())
                push(0 if b == 0 else abs(a) // abs(b) * (1 if a * b >= 0 else -1))
            elif op == 0x06:  # MOD
                use(G_LOW); a, b = pop(), pop(); push(a % b if b else 0)
            elif op == 0x07:  # SMOD
                use(G_LOW); a, b = sgn(pop()), sgn(pop())
                push(0 if b == 0 else (abs(a) % abs(b)) * (1 if a >= 0 else -1))
            elif op == 0x08:  # ADDMOD
                use(G_MID); a, b, m = pop(), pop(), pop()
                push((a + b) % m if m else 0)
            elif op == 0x09:  # MULMOD
                use(G_MID); a, b, m = pop(), pop(), pop()
                push((a * b) % m if m else 0)
            elif op == 0x0A:  # EXP
                a, e = pop(), pop()
                use(G_EXP + G_EXP_BYTE * ((e.bit_length() + 7) // 8))
                push(pow(a, e, U256))
            elif op == 0x0B:  # SIGNEXTEND
                use(G_LOW); k, x = pop(), pop()
                if k < 31:
                    bit = 8 * (k + 1) - 1
                    if x >> bit & 1:
                        x |= MAXU ^ ((1 << (bit + 1)) - 1)
                    else:
                        x &= (1 << (bit + 1)) - 1
                push(x)
            elif op == 0x10:  # LT
                use(G_VERYLOW); push(1 if pop() < pop() else 0)
            elif op == 0x11:  # GT
                use(G_VERYLOW); push(1 if pop() > pop() else 0)
            elif op == 0x12:  # SLT
                use(G_VERYLOW); push(1 if sgn(pop()) < sgn(pop()) else 0)
            elif op == 0x13:  # SGT
                use(G_VERYLOW); push(1 if sgn(pop()) > sgn(pop()) else 0)
            elif op == 0x14:  # EQ
                use(G_VERYLOW); push(1 if pop() == pop() else 0)
            elif op == 0x15:  # ISZERO
                use(G_VERYLOW); push(1 if pop() == 0 else 0)
            elif op == 0x16:  # AND
                use(G_VERYLOW); push(pop() & pop())
            elif op == 0x17:  # OR
                use(G_VERYLOW); push(pop() | pop())
            elif op == 0x18:  # XOR
                use(G_VERYLOW); push(pop() ^ pop())
            elif op == 0x19:  # NOT
                use(G_VERYLOW); push(MAXU ^ pop())
            elif op == 0x1A:  # BYTE
                use(G_VERYLOW); i, x = pop(), pop()
                push((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
            elif op == 0x1B:  # SHL
                use(G_VERYLOW); s, x = pop(), pop()
                push(x << s if s < 256 else 0)
            elif op == 0x1C:  # SHR
                use(G_VERYLOW); s, x = pop(), pop()
                push(x >> s if s < 256 else 0)
            elif op == 0x1D:  # SAR
                use(G_VERYLOW); s, x = pop(), sgn(pop())
                push((x >> s if s < 256 else (0 if x >= 0 else MAXU)))
            elif op == 0x20:  # SHA3
                off, n = pop(), pop()
                use(G_SHA3 + G_SHA3_WORD * _words(n))
                push(int.from_bytes(keccak256(mload(off, n)), "big"))
            elif op == 0x30:  # ADDRESS
                use(G_BASE); push(int.from_bytes(f.addr, "big"))
            elif op == 0x31:  # BALANCE
                use(G_BALANCE)
                push(self.state.balance(pop().to_bytes(32, "big")[12:]))
            elif op == 0x32:  # ORIGIN
                use(G_BASE); push(int.from_bytes(f.origin, "big"))
            elif op == 0x33:  # CALLER
                use(G_BASE); push(int.from_bytes(f.caller, "big"))
            elif op == 0x34:  # CALLVALUE
                use(G_BASE); push(f.value)
            elif op == 0x35:  # CALLDATALOAD
                use(G_VERYLOW); off = pop()
                push(int.from_bytes(f.data[off : off + 32].ljust(32, b"\0"),
                                    "big") if off < len(f.data) else 0)
            elif op == 0x36:  # CALLDATASIZE
                use(G_BASE); push(len(f.data))
            elif op == 0x37:  # CALLDATACOPY
                dst, src, n = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _words(n))
                chunk = f.data[src : src + n] if src < len(f.data) else b""
                mstore(dst, chunk.ljust(n, b"\0"))
            elif op == 0x38:  # CODESIZE
                use(G_BASE); push(len(code))
            elif op == 0x39:  # CODECOPY
                dst, src, n = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _words(n))
                chunk = code[src : src + n] if src < len(code) else b""
                mstore(dst, chunk.ljust(n, b"\0"))
            elif op == 0x3A:  # GASPRICE
                use(G_BASE); push(0)
            elif op == 0x3B:  # EXTCODESIZE
                use(G_EXTCODE)
                push(len(self.state.code(pop().to_bytes(32, "big")[12:])))
            elif op == 0x3C:  # EXTCODECOPY
                addr = pop().to_bytes(32, "big")[12:]
                dst, src, n = pop(), pop(), pop()
                use(G_EXTCODE + G_COPY_WORD * _words(n))
                c = self.state.code(addr)
                chunk = c[src : src + n] if src < len(c) else b""
                mstore(dst, chunk.ljust(n, b"\0"))
            elif op == 0x3D:  # RETURNDATASIZE
                use(G_BASE); push(len(f.ret))
            elif op == 0x3E:  # RETURNDATACOPY
                dst, src, n = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _words(n))
                if src + n > len(f.ret):
                    raise EvmError("returndata out of bounds")
                mstore(dst, f.ret[src : src + n])
            elif op == 0x40:  # BLOCKHASH
                use(G_HIGH + 10); n = pop()
                bh = self.ctx.blockhash
                # only the previous 256 ancestors — never the block
                # being executed, whose hash is not yet sealed
                # (ref core/vm/instructions.go opBlockhash: distance
                # 1..256, else zero)
                push(int.from_bytes(bh(n), "big")
                     if bh is not None and 1 <= self.ctx.number - n <= 256
                     else 0)
            elif op == 0x41:  # COINBASE
                use(G_BASE); push(int.from_bytes(self.ctx.coinbase, "big"))
            elif op == 0x42:  # TIMESTAMP
                use(G_BASE); push(self.ctx.time)
            elif op == 0x43:  # NUMBER
                use(G_BASE); push(self.ctx.number)
            elif op == 0x44:  # DIFFICULTY
                use(G_BASE); push(self.ctx.difficulty)
            elif op == 0x45:  # GASLIMIT
                use(G_BASE); push(self.ctx.gas_limit)
            elif op == 0x50:  # POP
                use(G_BASE); pop()
            elif op == 0x51:  # MLOAD
                use(G_VERYLOW); off = pop()
                push(int.from_bytes(mload(off, 32), "big"))
            elif op == 0x52:  # MSTORE
                use(G_VERYLOW); off, v = pop(), pop()
                mstore(off, v.to_bytes(32, "big"))
            elif op == 0x53:  # MSTORE8
                use(G_VERYLOW); off, v = pop(), pop()
                mstore(off, bytes([v & 0xFF]))
            elif op == 0x54:  # SLOAD
                use(G_SLOAD); slot = pop()
                v = f.swrites.get(slot)
                push(v if v is not None
                     else self.state.storage_at(f.addr, slot))
            elif op == 0x55:  # SSTORE
                if f.static:
                    raise EvmError("static sstore")
                slot, v = pop(), pop()
                cur = f.swrites.get(slot)
                if cur is None:
                    cur = self.state.storage_at(f.addr, slot)
                use(G_SSTORE_SET if (cur == 0 and v != 0) else G_SSTORE_RESET)
                f.swrites[slot] = v
            elif op == 0x56:  # JUMP
                use(G_MID); dst = pop()
                if jumpdests is None:
                    jumpdests = _jumpdests(code)
                if dst not in jumpdests:
                    raise EvmError("bad jump")
                f.pc = dst
            elif op == 0x57:  # JUMPI
                use(G_HIGH); dst, cond = pop(), pop()
                if cond:
                    if jumpdests is None:
                        jumpdests = _jumpdests(code)
                    if dst not in jumpdests:
                        raise EvmError("bad jump")
                    f.pc = dst
            elif op == 0x58:  # PC
                use(G_BASE); push(f.pc - 1)
            elif op == 0x59:  # MSIZE
                use(G_BASE); push(len(f.mem))
            elif op == 0x5A:  # GAS
                use(G_BASE); push(f.gas)
            elif op == 0x5B:  # JUMPDEST
                use(G_JUMPDEST)
            elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                if f.static:
                    raise EvmError("static log")
                n_topics = op - 0xA0
                off, n = pop(), pop()
                topics = tuple(pop().to_bytes(32, "big")
                               for _ in range(n_topics))
                use(G_LOG + G_LOG_TOPIC * n_topics + G_LOG_BYTE * n)
                self.logs.append((f.addr, topics, mload(off, n)))
            elif op == 0xF0:  # CREATE
                if f.static:
                    raise EvmError("static create")
                value, off, n = pop(), pop(), pop()
                use(G_CREATE)
                init = mload(off, n)
                gas_for = f.gas - f.gas // 64
                f.gas -= gas_for
                self._flush_storage(f)
                self.state.bump_nonce(f.addr)
                res = self.create(f.addr, value, init, gas_for,
                                  self.state.nonce(f.addr) - 1,
                                  depth=depth + 1, origin=f.origin)
                f.gas += gas_for - res.gas_used
                f.ret = res.output if not res.success else b""
                push(int.from_bytes(res.created, "big")
                     if res.success and res.created else 0)
            elif op in (0xF1, 0xF2, 0xF4, 0xFA):  # CALL/CALLCODE/DELEGATECALL/STATICCALL
                gas_req = pop()
                to = pop().to_bytes(32, "big")[12:]
                if op in (0xF1, 0xF2):
                    value = pop()
                else:
                    value = 0
                in_off, in_n, out_off, out_n = pop(), pop(), pop(), pop()
                if op == 0xF1 and f.static and value:
                    raise EvmError("static call with value")
                base = G_CALL + (G_CALL_VALUE if value else 0)
                to_int = int.from_bytes(to, "big")
                if (op == 0xF1 and value
                        and self.state.account(to).balance == 0
                        and self.state.nonce(to) == 0
                        and not self.state.code(to)
                        and not (1 <= to_int <= 8)):
                    base += G_NEW_ACCOUNT
                use(base)
                data = mload(in_off, in_n)
                if out_n:
                    grow(out_off + out_n)
                avail = f.gas - f.gas // 64
                gas_for = min(gas_req, avail)
                f.gas -= gas_for
                stipend = G_CALL_STIPEND if value else 0
                # reentrancy: nested frames must see this frame's storage
                # writes, and may write our storage themselves — flush
                # the cache down and re-read from state afterwards
                self._flush_storage(f)
                if op == 0xF2 and value > self.state.balance(f.addr):
                    # CALLCODE checks but does not move the balance
                    # (ref: evm.CallCode CanTransfer); gas is returned
                    res = ExecResult(False, 0)
                elif op == 0xF1:  # CALL
                    res = self.call(f.addr, to, value, data,
                                    gas_for + stipend, depth=depth + 1,
                                    static=f.static, origin=f.origin)
                elif op == 0xF2:  # CALLCODE: callee code, our storage
                    res = self._call_with_code(
                        f, to, f.addr, value, data, gas_for + stipend,
                        depth, caller=f.addr, static=f.static)
                elif op == 0xF4:  # DELEGATECALL: keep caller+value
                    res = self._call_with_code(
                        f, to, f.addr, f.value, data, gas_for, depth,
                        caller=f.caller, static=f.static)
                else:  # STATICCALL
                    res = self.call(f.addr, to, 0, data, gas_for,
                                    depth=depth + 1, static=True,
                                    origin=f.origin)
                # leftover callee gas (incl. unused stipend) returns to
                # the caller, matching the reference's accounting
                # (contract.Gas += returnGas, core/vm/evm.go Call)
                used = min(res.gas_used, gas_for + stipend)
                f.gas += (gas_for + stipend) - used
                f.ret = res.output
                if out_n:
                    # write only what the callee returned; the rest of
                    # the reserved region keeps its prior contents
                    # (ref: memory.Set in opCall — no zero-fill)
                    mstore(out_off, res.output[:out_n])
                push(1 if res.success else 0)
            elif op == 0xF3:  # RETURN
                off, n = pop(), pop()
                return mload(off, n)
            elif op == 0xFD:  # REVERT
                off, n = pop(), pop()
                r = Revert(mload(off, n))
                r.gas_left = f.gas
                raise r
            elif op == 0xFE:  # INVALID
                raise EvmError("invalid opcode 0xfe")
            elif op == 0xFF:  # SELFDESTRUCT (simplified: sweep balance)
                if f.static:
                    raise EvmError("static selfdestruct")
                use(G_SELF_DESTRUCT)
                heir = pop().to_bytes(32, "big")[12:]
                bal = self.state.balance(f.addr)
                if bal:
                    self.state.sub_balance(f.addr, bal)
                    self.state.add_balance(heir, bal)
                return b""
            else:
                raise EvmError(f"unknown opcode {op:#x}")

    def _call_with_code(self, parent: _Frame, code_addr: bytes,
                        storage_addr: bytes, value: int, data: bytes,
                        gas: int, depth: int, *, caller: bytes,
                        static: bool) -> ExecResult:
        """CALLCODE/DELEGATECALL: run ``code_addr``'s code in
        ``storage_addr``'s storage context (ref: evm.CallCode/DelegateCall)."""
        if depth + 1 > CALL_DEPTH_LIMIT:
            return ExecResult(False, gas)
        snapshot = self.state
        frame_state = snapshot.copy()
        prev, self.state = self.state, frame_state
        log_mark = len(self.logs)
        try:
            code = frame_state.code(code_addr)
            pre = self._precompile(int.from_bytes(code_addr, "big"), data,
                                   gas) \
                if 1 <= int.from_bytes(code_addr, "big") <= 8 else None
            if pre is not None:
                out, gas_left = pre
                snapshot.absorb(frame_state)
                return ExecResult(True, gas - gas_left, out)
            if not code:
                snapshot.absorb(frame_state)
                return ExecResult(True, 0, b"")
            frame = _Frame(code=code, addr=storage_addr, caller=caller,
                           origin=parent.origin, value=value, data=data,
                           gas=gas, static=static)
            out = self._run(frame, depth + 1)
            frame_state.set_storage_many(storage_addr, frame.swrites)
            snapshot.absorb(frame_state)
            return ExecResult(True, gas - frame.gas, out)
        except Revert as r:
            del self.logs[log_mark:]
            return ExecResult(False, gas - getattr(r, "gas_left", 0), r.data)
        except (EvmError, StateError):
            del self.logs[log_mark:]
            return ExecResult(False, gas)
        finally:
            self.state = prev


def _jumpdests(code: bytes) -> set[int]:
    """Valid JUMPDEST offsets (PUSH data bytes excluded)."""
    out = set()
    i = 0
    n = len(code)
    while i < n:
        op = code[i]
        if op == 0x5B:
            out.add(i)
        i += (op - 0x5E) if 0x60 <= op <= 0x7F else 1
    return out


def intrinsic_gas(data: bytes, is_create: bool) -> int:
    """(ref: core/state_transition.go IntrinsicGas)"""
    g = G_TX_CREATE if is_create else G_TX
    for b in data:
        g += G_NONZERO_BYTE if b else G_ZERO_BYTE
    return g
