"""Contract ABI encoding/decoding (ref role: accounts/abi/abi.go:1,
type.go, argument.go, event.go).

Fills the last user-facing gap between ``eth_call``/``eth_estimateGas``
and real contracts: without this, calldata had to be hand-packed
(r5 verdict item 9).  Scope matches the reference package's v1 ABI:

* elementary types — ``uint8..uint256``, ``int8..int256``, ``address``,
  ``bool``, ``bytes1..bytes32``, ``bytes``, ``string``
* composite types — fixed arrays ``T[k]``, dynamic arrays ``T[]``
  (arbitrarily nested), and tuples ``(T1,T2,…)``
* the head/tail encoding scheme: static values inline, dynamic values
  as a 32-byte offset into the tail region
* 4-byte function selectors (``keccak256(sig)[:4]``) and 32-byte event
  topics

Design vs the reference: geth builds reflection-driven Go struct
marshalling on top of the scheme; here the surface is plain Python
values (int/bytes/str/bool/list/tuple), which is what the RPC layer and
console hand around anyway — no reflection layer to port.
"""

from __future__ import annotations

import re

from eges_tpu.crypto.keccak import keccak256

__all__ = [
    "AbiError", "encode", "decode", "selector", "event_topic",
    "encode_call", "decode_output",
]


class AbiError(ValueError):
    pass


# -- type grammar -----------------------------------------------------------

_ELEM = re.compile(r"^(uint|int|bytes|address|bool|string)([0-9]*)$")


class _Type:
    """Parsed ABI type: kind + size + element type for composites."""

    __slots__ = ("kind", "size", "elem", "arity", "comps")

    def __init__(self, kind, size=0, elem=None, arity=-1, comps=()):
        self.kind = kind      # uint int address bool bytesN bytes string
        self.size = size      # bits for u/int, bytes for bytesN
        self.elem = elem      # element _Type for arrays
        self.arity = arity    # fixed length, -1 = dynamic array
        self.comps = comps    # component _Types for tuples

    @property
    def dynamic(self) -> bool:
        if self.kind in ("bytes", "string"):
            return True
        if self.kind == "array":
            return self.arity < 0 or self.elem.dynamic
        if self.kind == "tuple":
            return any(c.dynamic for c in self.comps)
        return False

    def head_words(self) -> int:
        """Static footprint in 32-byte words (dynamic types head = 1)."""
        if self.dynamic:
            return 1
        if self.kind == "array":
            return self.arity * self.elem.head_words()
        if self.kind == "tuple":
            return sum(c.head_words() for c in self.comps)
        return 1


def _split_tuple(s: str) -> list[str]:
    """Split 'a,b,(c,d)[2],e' at depth-0 commas."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur or parts:
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def parse_type(s: str) -> _Type:
    s = s.strip()
    # arrays bind outermost-last: strip ONE trailing [] / [k]
    m = re.search(r"\[([0-9]*)\]$", s)
    if m:
        elem = parse_type(s[: m.start()])
        return _Type("array", elem=elem,
                     arity=int(m.group(1)) if m.group(1) else -1)
    if s.startswith("(") and s.endswith(")"):
        return _Type("tuple",
                     comps=tuple(parse_type(p)
                                 for p in _split_tuple(s[1:-1])))
    m = _ELEM.match(s)
    if not m:
        raise AbiError(f"unsupported ABI type {s!r}")
    base, num = m.group(1), m.group(2)
    if base in ("address", "bool", "string"):
        if num:
            raise AbiError(f"unsupported ABI type {s!r}")
        return _Type(base)
    if base == "bytes":
        if not num:
            return _Type("bytes")
        n = int(num)
        if not 1 <= n <= 32:
            raise AbiError(f"bytes{n} out of range")
        return _Type("bytesN", size=n)
    n = int(num) if num else 256
    if n % 8 or not 8 <= n <= 256:
        raise AbiError(f"{base}{n} out of range")
    return _Type(base, size=n)


# -- encoding ---------------------------------------------------------------

def _enc_word(t: _Type, v) -> bytes:
    if t.kind == "uint":
        v = int(v)
        if not 0 <= v < (1 << t.size):
            raise AbiError(f"uint{t.size} out of range: {v}")
        return v.to_bytes(32, "big")
    if t.kind == "int":
        v = int(v)
        if not -(1 << (t.size - 1)) <= v < (1 << (t.size - 1)):
            raise AbiError(f"int{t.size} out of range: {v}")
        return (v % (1 << 256)).to_bytes(32, "big")
    if t.kind == "address":
        if isinstance(v, str):
            v = bytes.fromhex(v.removeprefix("0x"))
        if len(v) != 20:
            raise AbiError("address must be 20 bytes")
        return bytes(12) + bytes(v)
    if t.kind == "bool":
        return (1 if v else 0).to_bytes(32, "big")
    if t.kind == "bytesN":
        v = bytes(v)
        if len(v) != t.size:
            raise AbiError(f"bytes{t.size}: got {len(v)} bytes")
        return v.ljust(32, b"\0")
    raise AbiError(f"not a word type: {t.kind}")


def _encode_one(t: _Type, v) -> bytes:
    """Encode one value of (possibly composite, possibly dynamic) type
    ``t`` — the recursive head/tail scheme of abi.Arguments.Pack."""
    if t.kind in ("bytes", "string"):
        raw = v.encode() if isinstance(v, str) else bytes(v)
        n = len(raw)
        pad = (-n) % 32
        return n.to_bytes(32, "big") + raw + bytes(pad)
    if t.kind == "array":
        vs = list(v)
        if t.arity >= 0 and len(vs) != t.arity:
            raise AbiError(f"array arity {t.arity}, got {len(vs)}")
        body = _encode_seq([t.elem] * len(vs), vs)
        if t.arity < 0:
            return len(vs).to_bytes(32, "big") + body
        return body
    if t.kind == "tuple":
        vs = list(v)
        if len(vs) != len(t.comps):
            raise AbiError("tuple arity mismatch")
        return _encode_seq(list(t.comps), vs)
    return _enc_word(t, v)


def _encode_seq(types: list[_Type], values: list) -> bytes:
    """head || tail for a sequence (argument list / tuple / array)."""
    head_len = 32 * sum(t.head_words() for t in types)
    head, tail = [], []
    off = head_len
    for t, v in zip(types, values):
        enc = _encode_one(t, v)
        if t.dynamic:
            head.append(off.to_bytes(32, "big"))
            tail.append(enc)
            off += len(enc)
        else:
            head.append(enc)
    return b"".join(head) + b"".join(tail)


def encode(types: list[str], values: list) -> bytes:
    """ABI-encode ``values`` per ``types`` (abi.Arguments.Pack)."""
    ts = [parse_type(s) for s in types]
    if len(ts) != len(values):
        raise AbiError("types/values length mismatch")
    return _encode_seq(ts, list(values))


# -- decoding ---------------------------------------------------------------

def _word(data: bytes, off: int) -> bytes:
    if off + 32 > len(data):
        raise AbiError("ABI data truncated")
    return data[off : off + 32]


def _dec_word(t: _Type, w: bytes):
    u = int.from_bytes(w, "big")
    if t.kind == "uint":
        return u
    if t.kind == "int":
        return u - (1 << 256) if u >> 255 else u
    if t.kind == "address":
        return w[12:]
    if t.kind == "bool":
        return bool(u)
    if t.kind == "bytesN":
        return w[: t.size]
    raise AbiError(f"not a word type: {t.kind}")


def _decode_one(t: _Type, data: bytes, off: int):
    """Decode one value rooted at ``off`` (already offset-resolved)."""
    if t.kind in ("bytes", "string"):
        n = int.from_bytes(_word(data, off), "big")
        if off + 32 + n > len(data):
            raise AbiError("ABI data truncated")
        raw = data[off + 32 : off + 32 + n]
        return raw.decode("utf-8", "replace") if t.kind == "string" else raw
    if t.kind == "array":
        if t.arity < 0:
            n = int.from_bytes(_word(data, off), "big")
            if n > len(data) // 32:     # cheap bomb guard before alloc
                raise AbiError("ABI array length exceeds payload")
            return _decode_seq([t.elem] * n, data, off + 32)
        return _decode_seq([t.elem] * t.arity, data, off)
    if t.kind == "tuple":
        return tuple(_decode_seq(list(t.comps), data, off))
    return _dec_word(t, _word(data, off))


def _decode_seq(types: list[_Type], data: bytes, base: int) -> list:
    out = []
    off = base
    for t in types:
        if t.dynamic:
            rel = int.from_bytes(_word(data, off), "big")
            if rel > len(data):
                raise AbiError("ABI offset out of bounds")
            out.append(_decode_one(t, data, base + rel))
            off += 32
        else:
            out.append(_decode_one(t, data, off))
            off += 32 * t.head_words()
    return out


def decode(types: list[str], data: bytes) -> list:
    """ABI-decode ``data`` per ``types`` (abi.Arguments.Unpack)."""
    return _decode_seq([parse_type(s) for s in types], bytes(data), 0)


# -- selectors / call helpers ----------------------------------------------

_SIG = re.compile(r"^(\w+)\((.*)\)$")


def _canon_sig(sig: str) -> tuple[str, list[str]]:
    m = _SIG.match(sig.strip())
    if not m:
        raise AbiError(f"bad function signature {sig!r}")
    name, args = m.group(1), _split_tuple(m.group(2))
    # canonicalize the aliases solidity accepts in source
    canon = [re.sub(r"\bint\b", "int256",
                    re.sub(r"\buint\b", "uint256", a)) for a in args]
    return name, canon


def selector(sig: str) -> bytes:
    """4-byte function selector (abi.Method.ID)."""
    name, args = _canon_sig(sig)
    return keccak256(f"{name}({','.join(args)})".encode())[:4]


def event_topic(sig: str) -> bytes:
    """32-byte topic0 of an event (abi.Event.ID)."""
    name, args = _canon_sig(sig)
    return keccak256(f"{name}({','.join(args)})".encode())


def encode_call(sig: str, values: list) -> bytes:
    """selector ++ encoded args: ready-made ``eth_call`` calldata."""
    _, args = _canon_sig(sig)
    return selector(sig) + encode(args, values)


def decode_output(types: list[str], data: bytes):
    """Unpack an ``eth_call`` return; single-value results unwrap."""
    vals = decode(types, data)
    return vals[0] if len(vals) == 1 else vals
