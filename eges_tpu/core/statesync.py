"""Fast-sync state snapshots: serialize, page, rebuild, verify.

Fills the fast/state-sync role of the reference downloader
(eth/downloader/statesync.go:1, the pivot handling in
eth/downloader/downloader.go:1353): a late joiner downloads the state
AT a pivot block and root-verifies it instead of replaying the whole
chain — O(state), not O(chain).

Redesign vs the reference: geth syncs at TRIE-NODE granularity (each
response is a bag of hash-addressed nodes healed into a node database).
This build's state lives in in-memory persistent tries with no
node-hash database, so pages are ACCOUNT-granular: address-sorted
``(addr, nonce, balance, code_hash, ((hashed_slot, value_rlp)…))``
entries plus the referenced code blobs.  Verification is strictly
end-to-end — the joiner rebuilds the account and storage tries and
compares the final root against a quorum-certified pivot header, so a
byzantine serving peer can delay a fast sync but never poison one.

The same serialization doubles as the FileStore's durable snapshot
sidecar, which is what lets a fast-synced node RESTART without the
ancestors it never downloaded (SURVEY §5 checkpoint/resume).
"""

from __future__ import annotations

from eges_tpu.core import rlp
from eges_tpu.core.state import (
    Account, ContractStorage, EMPTY_CODE_HASH, StateDB,
)
from eges_tpu.core.trie import SecureIncrementalTrie


class StateSyncError(Exception):
    pass


def snapshot_accounts(state: StateDB) -> list[tuple]:
    """Address-sorted serializable view of a state snapshot:
    ``(addr, nonce, balance, code_hash, ((hashed_slot, value_rlp)…))``."""
    out = []
    for addr, a in sorted(state.iter_accounts()):
        slots = tuple(sorted(a.storage.items()))
        out.append((addr, a.nonce, a.balance, a.code_hash, slots))
    return out


def codes_for(state: StateDB, accounts) -> tuple[bytes, ...]:
    """Deduped bytecode blobs referenced by ``accounts`` (one page)."""
    seen: dict[bytes, bytes] = {}
    for addr, _n, _b, ch, _s in accounts:
        if ch != EMPTY_CODE_HASH and ch not in seen:
            code = state.code(addr)
            if code:
                seen[ch] = code
    return tuple(seen.values())


def assemble(accounts, codes) -> StateDB:
    """Rebuild a StateDB from downloaded pages.

    Storage tries are rebuilt from their hashed-key pairs; code blobs
    re-hash through ``set_code``.  NOTHING here is trusted — a wrong
    slot, balance, or code blob lands in the rebuilt tries and shifts
    ``root()``, which the caller must compare against a certified
    header before adopting."""
    from eges_tpu.crypto.keccak import keccak256

    code_by_hash = {keccak256(c): c for c in codes}
    accts: dict[bytes, Account] = {}
    for addr, nonce, balance, ch, slots in accounts:
        storage = (ContractStorage(
            SecureIncrementalTrie.from_hashed_pairs(slots))
            if slots else Account().storage)
        accts[bytes(addr)] = Account(nonce=nonce, balance=balance,
                                     code_hash=bytes(ch), storage=storage)
    st = StateDB(accts)
    for addr, _n, _b, ch, _s in accounts:
        if ch != EMPTY_CODE_HASH:
            # a missing/corrupt blob makes code_hash diverge -> the
            # final root check rejects the whole snapshot
            st.set_code(bytes(addr), code_by_hash.get(bytes(ch), b""))
    return st


# -- durable snapshot sidecar (FileStore restart path) ----------------------
#
# Two wire shapes share the sidecar slot:
#   legacy    rlp [block_hash, accounts, codes]           (fast-sync adopt)
#   checkpoint rlp [MAGIC, version, keccak(body), body]   (periodic cadence)
# where body is itself rlp [block_hash, accounts, codes, consensus].  The
# checkpoint adds a whole-blob checksum (a torn/bit-flipped sidecar is
# DETECTED before any account decodes) and an optional consensus section
# so a restart re-seeds membership/trust-rand soft state instead of
# replaying the whole chain to rebuild it.  ``decode_checkpoint`` sniffs
# the shape, so either generation of sidecar boots either generation of
# node.

CHECKPOINT_MAGIC = b"geec-ckpt"
CHECKPOINT_VERSION = 1


def _encode_accounts(accounts) -> list:
    return [[a, n, b, ch, [[k, v] for k, v in slots]]
            for a, n, b, ch, slots in accounts]


def _decode_accounts(accounts) -> list[tuple]:
    """Decode + validate the account page list: addresses must be
    strictly increasing (sorted, no duplicates) — the invariant every
    writer holds, so a mutated sidecar trips here instead of quietly
    rebuilding a different state."""
    items = []
    prev = None
    for a, n, b, ch, slots in accounts:
        addr = bytes(a)
        if prev is not None and addr <= prev:
            raise StateSyncError("accounts out of order or duplicated")
        prev = addr
        items.append((addr, rlp.decode_uint(n), rlp.decode_uint(b),
                      bytes(ch),
                      tuple((bytes(k), bytes(v)) for k, v in slots)))
    return items


def _encode_consensus(cons: dict) -> bytes:
    return rlp.encode([
        [[m[0], m[1], str(m[2]).encode(), int(m[3]), int(m[4]),
          int(m[5]), int(m[6])] for m in cons.get("members", ())],
        [[int(k), int(v)] for k, v in cons.get("trust_rands", ())],
        [int(n) for n in cons.get("empty_blocks", ())],
        [int(n) for n in cons.get("unconfirmed", ())],
        1 if cons.get("registered") else 0,
    ])


def _decode_consensus(blob: bytes) -> dict:
    members, rands, empties, unconfirmed, registered = rlp.decode(blob)
    return {
        "members": [(bytes(a), bytes(ref), bytes(ip).decode(),
                     rlp.decode_uint(port), rlp.decode_uint(joined),
                     rlp.decode_uint(ttl), rlp.decode_uint(renewed))
                    for a, ref, ip, port, joined, ttl, renewed in members],
        "trust_rands": [(rlp.decode_uint(k), rlp.decode_uint(v))
                        for k, v in rands],
        "empty_blocks": [rlp.decode_uint(n) for n in empties],
        "unconfirmed": [rlp.decode_uint(n) for n in unconfirmed],
        "registered": bool(rlp.decode_uint(registered)),
    }


def encode_snapshot(block_hash: bytes, state: StateDB) -> bytes:
    accounts = snapshot_accounts(state)
    codes = codes_for(state, accounts)
    return rlp.encode([
        block_hash, _encode_accounts(accounts), list(codes)])


def encode_checkpoint(block_hash: bytes, state: StateDB,
                      consensus: dict | None = None) -> bytes:
    """Versioned, checksummed sidecar blob (state + optional consensus
    soft state) for the periodic durability cadence."""
    from eges_tpu.crypto.keccak import keccak256

    accounts = snapshot_accounts(state)
    codes = codes_for(state, accounts)
    body = rlp.encode([
        block_hash, _encode_accounts(accounts), list(codes),
        _encode_consensus(consensus) if consensus is not None else b""])
    return rlp.encode([CHECKPOINT_MAGIC, CHECKPOINT_VERSION,
                       keccak256(body), body])


def decode_checkpoint(data: bytes) -> tuple[bytes, StateDB, dict | None]:
    """Decode either sidecar generation; any corruption — torn tail,
    bit flip, bad checksum, unsorted accounts — raises
    :class:`StateSyncError` so the boot path falls back to full replay
    instead of adopting a damaged state."""
    from eges_tpu.crypto.keccak import keccak256

    try:
        top = rlp.decode(data)
        if (isinstance(top, (list, tuple)) and len(top) == 4
                and bytes(top[0]) == CHECKPOINT_MAGIC):
            _magic, version, checksum, body = top
            if rlp.decode_uint(version) != CHECKPOINT_VERSION:
                raise StateSyncError("unknown checkpoint version")
            body = bytes(body)
            if keccak256(body) != bytes(checksum):
                raise StateSyncError("checkpoint checksum mismatch")
            block_hash, accounts, codes, cons_blob = rlp.decode(body)
            cons = (_decode_consensus(bytes(cons_blob))
                    if bytes(cons_blob) else None)
        else:
            block_hash, accounts, codes = top
            cons = None
        items = _decode_accounts(accounts)
        state = assemble(items, [bytes(c) for c in codes])
        return bytes(block_hash), state, cons
    except StateSyncError:
        raise
    except Exception as exc:
        raise StateSyncError(f"corrupt snapshot sidecar: {exc!r}") from exc


def decode_snapshot(data: bytes) -> tuple[bytes, StateDB]:
    block_hash, state, _cons = decode_checkpoint(data)
    return block_hash, state


# -- staged-page codec (mid-sync crash resume) ------------------------------

def encode_page(pivot: int, root: bytes, cursor: int, total,
                accounts, codes) -> bytes:
    """One accepted live-sync page, framed for the store's sync staging
    log so a crash mid-download resumes instead of restarting."""
    return rlp.encode([int(pivot), root, int(cursor), int(total or 0),
                       _encode_accounts(accounts),
                       [bytes(c) for c in codes]])


def decode_page(blob: bytes) -> tuple:
    """-> ``(pivot, root, cursor, total|None, accounts, codes)``;
    raises :class:`StateSyncError` on any corruption, so a torn staged
    tail truncates the resume instead of poisoning it."""
    try:
        pivot, root, cursor, total, accounts, codes = rlp.decode(blob)
        return (rlp.decode_uint(pivot), bytes(root),
                rlp.decode_uint(cursor), rlp.decode_uint(total) or None,
                _decode_accounts(accounts), [bytes(c) for c in codes])
    except StateSyncError:
        raise
    except Exception as exc:
        raise StateSyncError(f"corrupt staged page: {exc!r}") from exc
