"""Fast-sync state snapshots: serialize, page, rebuild, verify.

Fills the fast/state-sync role of the reference downloader
(eth/downloader/statesync.go:1, the pivot handling in
eth/downloader/downloader.go:1353): a late joiner downloads the state
AT a pivot block and root-verifies it instead of replaying the whole
chain — O(state), not O(chain).

Redesign vs the reference: geth syncs at TRIE-NODE granularity (each
response is a bag of hash-addressed nodes healed into a node database).
This build's state lives in in-memory persistent tries with no
node-hash database, so pages are ACCOUNT-granular: address-sorted
``(addr, nonce, balance, code_hash, ((hashed_slot, value_rlp)…))``
entries plus the referenced code blobs.  Verification is strictly
end-to-end — the joiner rebuilds the account and storage tries and
compares the final root against a quorum-certified pivot header, so a
byzantine serving peer can delay a fast sync but never poison one.

The same serialization doubles as the FileStore's durable snapshot
sidecar, which is what lets a fast-synced node RESTART without the
ancestors it never downloaded (SURVEY §5 checkpoint/resume).
"""

from __future__ import annotations

from eges_tpu.core import rlp
from eges_tpu.core.state import (
    Account, ContractStorage, EMPTY_CODE_HASH, StateDB,
)
from eges_tpu.core.trie import SecureIncrementalTrie


class StateSyncError(Exception):
    pass


def snapshot_accounts(state: StateDB) -> list[tuple]:
    """Address-sorted serializable view of a state snapshot:
    ``(addr, nonce, balance, code_hash, ((hashed_slot, value_rlp)…))``."""
    out = []
    for addr, a in sorted(state.iter_accounts()):
        slots = tuple(sorted(a.storage.items()))
        out.append((addr, a.nonce, a.balance, a.code_hash, slots))
    return out


def codes_for(state: StateDB, accounts) -> tuple[bytes, ...]:
    """Deduped bytecode blobs referenced by ``accounts`` (one page)."""
    seen: dict[bytes, bytes] = {}
    for addr, _n, _b, ch, _s in accounts:
        if ch != EMPTY_CODE_HASH and ch not in seen:
            code = state.code(addr)
            if code:
                seen[ch] = code
    return tuple(seen.values())


def assemble(accounts, codes) -> StateDB:
    """Rebuild a StateDB from downloaded pages.

    Storage tries are rebuilt from their hashed-key pairs; code blobs
    re-hash through ``set_code``.  NOTHING here is trusted — a wrong
    slot, balance, or code blob lands in the rebuilt tries and shifts
    ``root()``, which the caller must compare against a certified
    header before adopting."""
    from eges_tpu.crypto.keccak import keccak256

    code_by_hash = {keccak256(c): c for c in codes}
    accts: dict[bytes, Account] = {}
    for addr, nonce, balance, ch, slots in accounts:
        storage = (ContractStorage(
            SecureIncrementalTrie.from_hashed_pairs(slots))
            if slots else Account().storage)
        accts[bytes(addr)] = Account(nonce=nonce, balance=balance,
                                     code_hash=bytes(ch), storage=storage)
    st = StateDB(accts)
    for addr, _n, _b, ch, _s in accounts:
        if ch != EMPTY_CODE_HASH:
            # a missing/corrupt blob makes code_hash diverge -> the
            # final root check rejects the whole snapshot
            st.set_code(bytes(addr), code_by_hash.get(bytes(ch), b""))
    return st


# -- durable snapshot sidecar (FileStore restart path) ----------------------

def encode_snapshot(block_hash: bytes, state: StateDB) -> bytes:
    accounts = snapshot_accounts(state)
    codes = codes_for(state, accounts)
    return rlp.encode([
        block_hash,
        [[a, n, b, ch, [[k, v] for k, v in slots]]
         for a, n, b, ch, slots in accounts],
        list(codes)])


def decode_snapshot(data: bytes) -> tuple[bytes, StateDB]:
    block_hash, accounts, codes = rlp.decode(data)
    items = [(bytes(a), rlp.decode_uint(n), rlp.decode_uint(b), bytes(ch),
              tuple((bytes(k), bytes(v)) for k, v in slots))
             for a, n, b, ch, slots in accounts]
    return bytes(block_hash), assemble(items, [bytes(c) for c in codes])
