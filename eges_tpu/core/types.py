"""Chain data model: blocks, headers, transactions, Geec wire types.

Capability-parity port of the reference's ``core/types`` layer with the
Geec fork's extensions:

* Header carries ``regs`` (membership registrations confirmed by this
  block) and ``trust_rand`` (the committee seed for the *next* block)
  (ref: core/types/block.go:87-89).
* Block carries ``geec_txns`` / ``fake_txns`` / ``confirm`` outside the
  transaction root (ref: core/types/block.go:154-159, extblock 187-194 —
  note they are deliberately NOT under ``TxHash``; the validator only
  roots ``transactions``, core/block_validator.go:72).
* Transaction has the ``is_geec`` marker (ref: core/types/transaction.go:66)
  and EIP155/Homestead signing with cached sender
  (ref: core/types/transaction_signing.go:72-88).
* Geec wire records ``Registration`` / ``ConfirmBlockMsg`` /
  ``QueryBlockMsg`` and the sentinel addresses
  (ref: core/types/geec.go:13-44; the reference misspells
  "Registratoin" — the name, not the semantics, is fixed here).

Sender recovery delegates to the batched TPU verifier when one is
installed (see :mod:`eges_tpu.crypto.verifier`); single host-side
recovery is the fallback, mirroring the reference's cgo-vs-nocgo split
(crypto/signature_cgo.go vs signature_nocgo.go).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

from eges_tpu.core import rlp
from eges_tpu.core.trie import derive_sha, EMPTY_ROOT
from eges_tpu.crypto import secp256k1 as _secp
from eges_tpu.crypto.keccak import keccak256

# Sentinels (ref: core/types/geec.go:13-16)
REG_ADDR = bytes([0xFF] * 20)
EMPTY_ADDR = bytes([0xFF, 0x00] * 10)
FAKE_SIGNATURE = bytes([0x00, 0x01, 0x02, 0x03, 0x04])

EMPTY_UNCLE_HASH = keccak256(rlp.encode([]))
ZERO_HASH = bytes(32)
ZERO_ADDR = bytes(20)


def _addr(b: bytes) -> bytes:
    if len(b) != 20:
        raise ValueError("address must be 20 bytes")
    return bytes(b)


@dataclass(frozen=True)
class Registration:
    """Membership join request (ref: core/types/geec.go:19-28)."""

    account: bytes
    referee: bytes = ZERO_ADDR
    ip: str = ""
    port: str = ""
    signature: bytes = FAKE_SIGNATURE
    renew: int = 0

    def to_rlp(self) -> list:
        return [self.account, self.referee, self.ip.encode(), self.port.encode(),
                self.signature, self.renew]

    @classmethod
    def from_rlp(cls, item: list) -> "Registration":
        acc, ref, ip, port, sig, renew = item
        return cls(_addr(acc), _addr(ref), ip.decode(), port.decode(),
                   bytes(sig), rlp.decode_uint(renew))


@dataclass(frozen=True)
class ConfirmBlockMsg:
    """Leader's confirmation broadcast (ref: core/types/geec.go:30-36).

    This build's upgrade over the reference's trustedHW assumption: in
    signed-vote mode the confirm is a **quorum certificate** — beside the
    proposer's own ``sig``, ``supporter_sigs[i]`` is ``supporters[i]``'s
    signature over its ACK (``version == 0``) or query reply
    (``version > 0``, the timeout-recovery path), so ANY receiver can
    re-verify the whole quorum as one device batch without trusting the
    proposer.  All three extra fields are empty in unsigned deployments."""

    block_number: int
    hash: bytes
    confidence: int
    supporters: tuple[bytes, ...] = ()
    empty_block: bool = False
    sig: bytes = b""
    version: int = 0
    supporter_sigs: tuple[bytes, ...] = ()

    def to_rlp(self) -> list:
        return [self.block_number, self.hash, self.confidence,
                list(self.supporters), int(self.empty_block), self.sig,
                self.version, list(self.supporter_sigs)]

    @classmethod
    def from_rlp(cls, item: list) -> "ConfirmBlockMsg":
        # tolerate the shorter pre-signature wire forms (old stored blocks)
        num, h, conf, sup, empty = item[:5]
        return cls(rlp.decode_uint(num), bytes(h), rlp.decode_uint(conf),
                   tuple(_addr(a) for a in sup), bool(rlp.decode_uint(empty)),
                   sig=bytes(item[5]) if len(item) > 5 else b"",
                   version=rlp.decode_uint(item[6]) if len(item) > 6 else 0,
                   supporter_sigs=tuple(bytes(s) for s in item[7])
                   if len(item) > 7 else ())

    def signing_hash(self) -> bytes:
        return keccak256(b"geec/confirm" + rlp.encode(
            self.to_rlp()[:5] + [self.version]))


@dataclass(frozen=True)
class QueryBlockMsg:
    """Timeout-recovery block query (ref: core/types/geec.go:38-44)."""

    block_number: int
    version: int
    ip: str
    retry: int
    port: int

    def to_rlp(self) -> list:
        return [self.block_number, self.version, self.ip.encode(), self.retry, self.port]

    @classmethod
    def from_rlp(cls, item: list) -> "QueryBlockMsg":
        num, ver, ip, retry, port = item
        return cls(rlp.decode_uint(num), rlp.decode_uint(ver), ip.decode(),
                   rlp.decode_uint(retry), rlp.decode_uint(port))


@dataclass(frozen=True)
class Transaction:
    """A transaction; Geec txns are unsigned UDP-ingested payload carriers
    flagged ``is_geec`` (ref: core/types/transaction.go:52-80)."""

    nonce: int = 0
    gas_price: int = 0
    gas_limit: int = 0
    to: bytes | None = None  # None = contract creation
    value: int = 0
    payload: bytes = b""
    is_geec: bool = False
    v: int = 0
    r: int = 0
    s: int = 0

    _SENDER_CACHE: dict = dataclasses.field(default=None, repr=False, compare=False)

    def __post_init__(self):
        object.__setattr__(self, "_SENDER_CACHE", {})

    def to_rlp(self) -> list:
        to = self.to if self.to is not None else b""
        return [self.nonce, self.gas_price, self.gas_limit, to, self.value,
                self.payload, int(self.is_geec), self.v, self.r, self.s]

    @classmethod
    def from_rlp(cls, item: list) -> "Transaction":
        nonce, price, gas, to, value, payload, is_geec, v, r, s = item
        # r/s must fit 256 bits and v 64 bits, like geth's typed decode
        # into uint256/uint64 fields — a wire blob can't smuggle wider
        # ints into the verify paths.
        if len(r) > 32 or len(s) > 32:
            raise rlp.RLPError("signature scalar wider than 256 bits")
        if len(v) > 8:
            raise rlp.RLPError("v wider than 64 bits")
        return cls(
            nonce=rlp.decode_uint(nonce), gas_price=rlp.decode_uint(price),
            gas_limit=rlp.decode_uint(gas), to=_addr(to) if to else None,
            value=rlp.decode_uint(value), payload=bytes(payload),
            is_geec=bool(rlp.decode_uint(is_geec)), v=rlp.decode_uint(v),
            r=rlp.decode_uint(r), s=rlp.decode_uint(s),
        )

    def encode(self) -> bytes:
        return rlp.encode(self.to_rlp())

    @classmethod
    def decode(cls, data: bytes) -> "Transaction":
        return cls.from_rlp(rlp.decode(data))

    @property
    def hash(self) -> bytes:
        # memoized: admission touches the hash several times per row
        # (dedup keys, ledger billing, trace tags) and each recompute
        # is a full RLP re-encode + keccak.  The instance is frozen, so
        # the cached digest can never go stale; the columnar ingest
        # decoder seeds it straight from the wire frame's keccak
        # (keccak256(frame) == keccak256(encode()) because RLP is
        # strictly canonical) so window rows never re-encode at all.
        h = self._SENDER_CACHE.get("hash")
        if h is None:
            h = keccak256(self.encode())
            self._SENDER_CACHE["hash"] = h
        return h

    # -- signing ----------------------------------------------------------

    def sighash(self, chain_id: int | None = None) -> bytes:
        """EIP155 (chain_id) or Homestead (None) signing hash
        (ref: core/types/transaction_signing.go:146,207)."""
        to = self.to if self.to is not None else b""
        fields = [self.nonce, self.gas_price, self.gas_limit, to, self.value,
                  self.payload]
        if chain_id is not None:
            fields += [chain_id, 0, 0]
        return keccak256(rlp.encode(fields))

    @property
    def protected(self) -> bool:
        return self.v not in (27, 28) and self.v != 0

    @property
    def chain_id(self) -> int | None:
        if not self.protected:
            return None
        if self.v < 35:
            raise ValueError("invalid protected v (29..34 unassigned)")
        return (self.v - 35) // 2

    def signed(self, priv: bytes, chain_id: int | None = None) -> "Transaction":
        sig = _secp.ecdsa_sign(self.sighash(chain_id), priv)
        r = int.from_bytes(sig[0:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        recid = sig[64]
        v = recid + 27 if chain_id is None else recid + 35 + 2 * chain_id
        return dataclasses.replace(self, v=v, r=r, s=s)

    def signature_parts(self) -> tuple[bytes, bytes] | None:
        """(65-byte wire sig, 32-byte sighash) for the batch verifier, or
        ``None`` if the v/r/s values cannot form a wire signature (the
        batch contract is mask-don't-raise; a malformed remote txn must
        not take down a verify path)."""
        try:
            cid = self.chain_id
        except ValueError:
            return None
        recid = self.v - 27 if cid is None else self.v - 35 - 2 * cid
        if not (0 <= recid <= 3 and 0 < self.r < (1 << 256)
                and 0 < self.s < (1 << 256)):
            return None
        sig = (self.r.to_bytes(32, "big") + self.s.to_bytes(32, "big")
               + bytes([recid]))
        return sig, self.sighash(cid)

    def sender(self) -> bytes:
        """Host-side single recovery with per-tx cache
        (ref: transaction_signing.go:72-88).  Batch paths should use
        ``signature_parts`` + the TPU verifier instead."""
        if self.is_geec or (self.v == 0 and self.r == 0 and self.s == 0):
            return EMPTY_ADDR
        cached = self._SENDER_CACHE.get("from")
        if cached is not None:
            return cached
        parts = self.signature_parts()
        if parts is None:
            raise ValueError("invalid transaction v, r, s values")
        sig, h = parts
        addr = _secp.recover_address(h, sig)
        self._SENDER_CACHE["from"] = addr
        return addr


def geec_txn(payload: bytes) -> Transaction:
    """An unsigned Geec transaction as built from a UDP datagram
    (ref: consensus/geec/geec_api.go:28-41)."""
    return Transaction(to=REG_ADDR, payload=payload, is_geec=True)


def fake_txn(size: int, seq: int = 0) -> Transaction:
    """Throughput-test padding txn (ref: consensus/geec/geec.go:333-339)."""
    body = seq.to_bytes(8, "big")
    return Transaction(to=EMPTY_ADDR, payload=(body * (size // 8 + 1))[:size],
                       is_geec=True)


@dataclass(frozen=True)
class Header:
    """Block header with Geec extensions (ref: core/types/block.go:71-90)."""

    parent_hash: bytes = ZERO_HASH
    uncle_hash: bytes = EMPTY_UNCLE_HASH
    coinbase: bytes = ZERO_ADDR
    root: bytes = EMPTY_ROOT  # empty-state root (L3 checks it on insert)
    tx_hash: bytes = EMPTY_ROOT
    receipt_hash: bytes = EMPTY_ROOT
    bloom: bytes = bytes(256)
    difficulty: int = 1
    number: int = 0
    gas_limit: int = 0
    gas_used: int = 0
    time: int = 0
    extra: bytes = b""
    mix_digest: bytes = ZERO_HASH
    nonce: bytes = bytes(8)
    regs: tuple[Registration, ...] = ()
    trust_rand: int = 0

    def to_rlp(self) -> list:
        return [self.parent_hash, self.uncle_hash, self.coinbase, self.root,
                self.tx_hash, self.receipt_hash, self.bloom, self.difficulty,
                self.number, self.gas_limit, self.gas_used, self.time,
                self.extra, self.mix_digest, self.nonce,
                [r.to_rlp() for r in self.regs], self.trust_rand]

    @classmethod
    def from_rlp(cls, item: list) -> "Header":
        (parent, uncle, coin, root, txh, rch, bloom, diff, num, gl, gu, tm,
         extra, mix, nonce, regs, trand) = item
        return cls(
            parent_hash=bytes(parent), uncle_hash=bytes(uncle),
            coinbase=_addr(coin), root=bytes(root), tx_hash=bytes(txh),
            receipt_hash=bytes(rch), bloom=bytes(bloom),
            difficulty=rlp.decode_uint(diff), number=rlp.decode_uint(num),
            gas_limit=rlp.decode_uint(gl), gas_used=rlp.decode_uint(gu),
            time=rlp.decode_uint(tm), extra=bytes(extra),
            mix_digest=bytes(mix), nonce=bytes(nonce),
            regs=tuple(Registration.from_rlp(r) for r in regs),
            trust_rand=rlp.decode_uint(trand),
        )

    def encode(self) -> bytes:
        return rlp.encode(self.to_rlp())

    @property
    def hash(self) -> bytes:
        """keccak256 of the RLP header (ref: core/types/block.go:105)."""
        return keccak256(self.encode())


@dataclass(frozen=True)
class Block:
    """Block = header + txs + Geec bodies (ref: core/types/block.go:146-159).

    ``geec_txns``/``fake_txns``/``confirm`` ride beside the rooted
    transaction list, exactly like the reference's extblock wire encoding
    (block.go:187-194) and ``WithGeecBody`` DB read path
    (core/database_util.go:243, block.go:383-403).
    """

    header: Header
    transactions: tuple[Transaction, ...] = ()
    uncles: tuple[Header, ...] = ()
    geec_txns: tuple[Transaction, ...] = ()
    fake_txns: tuple[Transaction, ...] = ()
    confirm: ConfirmBlockMsg | None = None

    @property
    def number(self) -> int:
        return self.header.number

    @property
    def hash(self) -> bytes:
        return self.header.hash

    def to_rlp(self) -> list:
        # extblock order: Header, FakeTxs, GeecTxs, Txs, Uncles, Confirm
        return [
            self.header.to_rlp(),
            [t.to_rlp() for t in self.fake_txns],
            [t.to_rlp() for t in self.geec_txns],
            [t.to_rlp() for t in self.transactions],
            [u.to_rlp() for u in self.uncles],
            [] if self.confirm is None else self.confirm.to_rlp(),
        ]

    @classmethod
    def from_rlp(cls, item: list) -> "Block":
        header, fakes, geecs, txs, uncles, confirm = item
        return cls(
            header=Header.from_rlp(header),
            transactions=tuple(Transaction.from_rlp(t) for t in txs),
            uncles=tuple(Header.from_rlp(u) for u in uncles),
            geec_txns=tuple(Transaction.from_rlp(t) for t in geecs),
            fake_txns=tuple(Transaction.from_rlp(t) for t in fakes),
            confirm=ConfirmBlockMsg.from_rlp(confirm) if confirm else None,
        )

    def encode(self) -> bytes:
        return rlp.encode(self.to_rlp())

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        return cls.from_rlp(rlp.decode(data))

    def with_confirm(self, confirm: ConfirmBlockMsg) -> "Block":
        return dataclasses.replace(self, confirm=confirm)


def new_block(header: Header, txs=(), uncles=(), geec_txns=(), fake_txns=(),
              confirm=None) -> Block:
    """Assemble a block, deriving the tx root into the header
    (ref: core/types/block.go NewBlock; only ``txs`` is rooted)."""
    txs = tuple(txs)
    header = dataclasses.replace(
        header,
        tx_hash=derive_sha([t.encode() for t in txs]) if txs else EMPTY_ROOT,
        uncle_hash=keccak256(rlp.encode([u.to_rlp() for u in uncles])),
    )
    return Block(header=header, transactions=txs, uncles=tuple(uncles),
                 geec_txns=tuple(geec_txns), fake_txns=tuple(fake_txns),
                 confirm=confirm)
