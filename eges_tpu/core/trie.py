"""Merkle-Patricia trie: root hashing and key/value proofs-of-inclusion.

Fills the role of the reference's ``trie/`` package for the paths the
consensus capability set needs: ``DeriveSha`` over transactions/receipts
(ref: core/types/derive_sha.go) and a generic secure-keyed KV trie for
state roots (ref: trie/trie.go, trie/secure_trie.go).  This is a batch
builder — it materialises the node structure for a key set and folds it
into the keccak root — rather than a journaled incremental trie; the
chain layer rebuilds roots per block, which at Geec's 1000-txn operating
point is microseconds of host work and keeps the structure immutable
(functional style, no in-place node mutation).
"""

from __future__ import annotations

from eges_tpu.core import rlp
from eges_tpu.crypto.keccak import keccak256

EMPTY_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)  # keccak256(rlp(b''))


def _nibbles(key: bytes) -> list[int]:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0xF)
    return out


def _hp_encode(nibbles: list[int], terminal: bool) -> bytes:
    """Hex-prefix encoding (ref: trie/encoding.go hexToCompact)."""
    flag = 2 if terminal else 0
    if len(nibbles) % 2:
        head = [flag + 1] + nibbles
    else:
        head = [flag, 0] + nibbles
    return bytes(
        (head[i] << 4) | head[i + 1] for i in range(0, len(head), 2)
    )


def _node_ref(encoded: bytes):
    """Nodes < 32 bytes embed in the parent; otherwise refer by hash."""
    if len(encoded) < 32:
        return rlp.decode(encoded)
    return keccak256(encoded)


def _lcp_below(items, depth: int) -> int:
    """Longest common nibble prefix of ``items`` at/below ``depth``."""
    first = items[0][0]
    lcp = len(first)
    for nib, _ in items[1:]:
        i = depth
        limit = min(len(first), len(nib))
        while i < limit and nib[i] == first[i]:
            i += 1
        lcp = min(lcp, i)
    return lcp


def _build(items: list[tuple[list[int], bytes]], depth: int):
    """Build the node for items sharing a prefix of length ``depth``.

    Returns the RLP *structure* of the node (to be encoded / hashed by
    the caller).  ``items`` must be sorted and have distinct keys.
    """
    if not items:
        return b""
    if len(items) == 1:
        nib, val = items[0]
        return [_hp_encode(nib[depth:], True), val]

    # longest common prefix below depth
    first = items[0][0]
    lcp = _lcp_below(items, depth)
    if lcp > depth:
        child = _build(items, lcp)
        return [_hp_encode(first[depth:lcp], False), _node_ref(rlp.encode(child))]

    # branch node
    children = [b""] * 16
    value = b""
    buckets: dict[int, list] = {}
    for nib, val in items:
        if len(nib) == depth:
            value = val
        else:
            buckets.setdefault(nib[depth], []).append((nib, val))
    for idx, bucket in buckets.items():
        child = _build(bucket, depth + 1)
        children[idx] = _node_ref(rlp.encode(child))
    return children + [value]


def trie_root(pairs: dict[bytes, bytes]) -> bytes:
    """Root hash of the MPT holding ``pairs`` (raw keys)."""
    if not pairs:
        return EMPTY_ROOT
    items = sorted((_nibbles(k), v) for k, v in pairs.items())
    node = _build(items, 0)
    return keccak256(rlp.encode(node))


def secure_trie_root(pairs: dict[bytes, bytes]) -> bytes:
    """Root with keccak-hashed keys (ref: trie/secure_trie.go)."""
    return trie_root({keccak256(k): v for k, v in pairs.items()})


def derive_sha(encoded_items: list[bytes]) -> bytes:
    """Tx/receipt root: trie keyed by rlp(index) (ref: core/types/derive_sha.go:30)."""
    return trie_root({rlp.encode(i): item for i, item in enumerate(encoded_items)})


# ---------------------------------------------------------------------------
# proofs of inclusion / exclusion (ref: trie/proof.go Prove/VerifyProof)
# ---------------------------------------------------------------------------

def _hp_decode(data: bytes) -> tuple[list[int], bool]:
    nibs = _nibbles(data)
    flag = nibs[0]
    terminal = flag >= 2
    skip = 1 if flag % 2 else 2
    return nibs[skip:], terminal


def trie_prove(pairs: dict[bytes, bytes], key: bytes) -> list[bytes]:
    """Merkle proof for ``key`` against ``trie_root(pairs)``: the encoded
    nodes on the key's path that are referenced by hash (embedded short
    nodes travel inside their parent, as in the reference's proof lists).
    Valid for absent keys too (an exclusion proof)."""
    if not pairs:
        return []
    nib = _nibbles(key)
    items = sorted((_nibbles(k), v) for k, v in pairs.items())
    depth = 0
    proof: list[bytes] = []
    enc = rlp.encode(_build(items, depth))  # root node
    hashed = True  # the root is always by-hash
    while True:
        if hashed:
            proof.append(enc)
        if len(items) == 1:
            return proof
        lcp = _lcp_below(items, depth)
        if lcp > depth:  # extension node
            if nib[depth:lcp] != items[0][0][depth:lcp]:
                return proof  # diverges here: exclusion proven
            depth = lcp
            enc = rlp.encode(_build(items, depth))
            hashed = len(enc) >= 32
            continue
        # branch node
        if len(nib) == depth:
            return proof  # value (or absence) sits in this branch
        bucket = [(n, v) for n, v in items
                  if len(n) > depth and n[depth] == nib[depth]]
        if not bucket:
            return proof  # empty child slot: exclusion proven
        items = bucket
        depth += 1
        enc = rlp.encode(_build(items, depth))
        hashed = len(enc) >= 32


def verify_proof(root: bytes, key: bytes, proof: list[bytes]):
    """Walk ``proof`` from ``root``; returns the proven value, or None
    when the proof shows the key absent.  Raises ValueError on any
    inconsistency (a forged proof)."""
    if root == EMPTY_ROOT:
        if proof:
            raise ValueError("non-empty proof for the empty trie")
        return None
    nib = _nibbles(key)
    it = iter(proof)

    def load(ref):
        if isinstance(ref, (bytes, bytearray)) and len(ref) == 32:
            enc = next(it, None)
            if enc is None:
                raise ValueError("proof truncated")
            if keccak256(enc) != bytes(ref):
                raise ValueError("proof node hash mismatch")
            return rlp.decode(enc)
        return ref  # embedded node (list) or empty slot (b"")

    node = load(root)
    i = 0
    while True:
        if isinstance(node, (bytes, bytearray)):
            if len(node) == 0:
                return None  # empty slot: key absent
            raise ValueError("malformed proof node")
        if len(node) == 17:  # branch
            if i == len(nib):
                val = bytes(node[16])
                return val if val else None
            node = load(node[nib[i]])
            i += 1
            continue
        if len(node) != 2:
            raise ValueError("malformed proof node")
        path, terminal = _hp_decode(bytes(node[0]))
        if terminal:
            return bytes(node[1]) if nib[i:] == path else None
        if nib[i:i + len(path)] != path:
            return None  # extension diverges: key absent
        i += len(path)
        node = load(node[1])


def secure_trie_prove(pairs: dict[bytes, bytes], key: bytes) -> list[bytes]:
    """Proof against :func:`secure_trie_root` (keccak-hashed keys)."""
    return trie_prove({keccak256(k): v for k, v in pairs.items()},
                      keccak256(key))


def verify_secure_proof(root: bytes, key: bytes, proof: list[bytes]):
    return verify_proof(root, keccak256(key), proof)


# ---------------------------------------------------------------------------
# persistent incremental trie (ref: trie/trie.go insert/delete — redesigned
# as an immutable structure-sharing tree instead of geth's mutable nodes +
# journal, so every chain snapshot holds a root pointer and per-block cost
# is O(dirty keys x depth), round-2 verdict item 10)
# ---------------------------------------------------------------------------

class _Leaf:
    __slots__ = ("path", "value", "_enc")

    def __init__(self, path: tuple[int, ...], value: bytes):
        self.path = path
        self.value = value
        self._enc = None


class _Ext:
    __slots__ = ("path", "child", "_enc")

    def __init__(self, path: tuple[int, ...], child):
        self.path = path
        self.child = child
        self._enc = None


class _Branch:
    __slots__ = ("children", "value", "_enc")

    def __init__(self, children: tuple, value: bytes):
        self.children = children  # 16-tuple of nodes | None
        self.value = value
        self._enc = None


def _encode_node(node) -> bytes:
    """RLP encoding of a node, memoized on the (immutable) node object."""
    if node._enc is None:
        if isinstance(node, _Leaf):
            s = [_hp_encode(list(node.path), True), node.value]
        elif isinstance(node, _Ext):
            s = [_hp_encode(list(node.path), False),
                 _node_ref(_encode_node(node.child))]
        else:
            s = [(b"" if c is None else _node_ref(_encode_node(c)))
                 for c in node.children] + [node.value]
        node._enc = rlp.encode(s)
    return node._enc


def _insert(node, nibs: tuple[int, ...], value: bytes):
    """Insert/overwrite; returns the new node (shares unchanged subtrees)."""
    if node is None:
        return _Leaf(nibs, value)
    if isinstance(node, _Leaf):
        if node.path == nibs:
            return _Leaf(nibs, value)
        # branch at the divergence point, extension over the shared
        # prefix (a chain of single-child branches would hash to a
        # non-canonical root)
        n = _common_len(node.path, nibs)
        children: list = [None] * 16
        bval = b""
        for path, val in ((node.path, node.value), (nibs, value)):
            if len(path) == n:
                bval = val
            else:
                children[path[n]] = _Leaf(path[n + 1:], val)
        return _make_ext(node.path[:n], _Branch(tuple(children), bval))
    if isinstance(node, _Ext):
        p = node.path
        n = _common_len(p, nibs)
        if n == len(p):
            return _make_ext(p, _insert(node.child, nibs[n:], value))
        # split the extension at n
        below = node.child if len(p) == n + 1 else _Ext(p[n + 1:], node.child)
        children: list = [None] * 16
        children[p[n]] = below
        branch = _Branch(tuple(children), b"")
        branch = _insert(branch, nibs[n:], value)
        return _make_ext(p[:n], branch) if n else branch
    # branch
    if not nibs:
        return _Branch(node.children, value)
    i = nibs[0]
    new_child = _insert(node.children[i], nibs[1:], value)
    ch = list(node.children)
    ch[i] = new_child
    return _Branch(tuple(ch), node.value)


def _common_len(a, b) -> int:
    n = 0
    m = min(len(a), len(b))
    while n < m and a[n] == b[n]:
        n += 1
    return n


def _make_ext(path: tuple[int, ...], child):
    """Extension constructor that collapses degenerate shapes."""
    if not path:
        return child
    if isinstance(child, _Ext):
        return _Ext(path + child.path, child.child)
    if isinstance(child, _Leaf):
        return _Leaf(path + child.path, child.value)
    return _Ext(path, child)


def _delete(node, nibs: tuple[int, ...]):
    """Delete; returns the new node or None.  Missing keys are a no-op."""
    if node is None:
        return None
    if isinstance(node, _Leaf):
        return None if node.path == nibs else node
    if isinstance(node, _Ext):
        n = _common_len(node.path, nibs)
        if n != len(node.path):
            return node  # key not present
        child = _delete(node.child, nibs[n:])
        if child is node.child:
            return node
        if child is None:
            return None
        return _make_ext(node.path, child)
    # branch
    if not nibs:
        if not node.value:
            return node
        new = _Branch(node.children, b"")
    else:
        i = nibs[0]
        child = _delete(node.children[i], nibs[1:])
        if child is node.children[i]:
            return node
        ch = list(node.children)
        ch[i] = child
        new = _Branch(tuple(ch), node.value)
    # collapse if degenerate
    live = [(i, c) for i, c in enumerate(new.children) if c is not None]
    if new.value and not live:
        return _Leaf((), new.value)
    if not new.value and len(live) == 1:
        i, c = live[0]
        return _make_ext((i,), c)
    if not new.value and not live:
        return None
    return new


def _get(node, nibs: tuple[int, ...]):
    while node is not None:
        if isinstance(node, _Leaf):
            return node.value if node.path == nibs else None
        if isinstance(node, _Ext):
            n = _common_len(node.path, nibs)
            if n != len(node.path):
                return None
            node, nibs = node.child, nibs[n:]
            continue
        if not nibs:
            return node.value or None
        node, nibs = node.children[nibs[0]], nibs[1:]
    return None


class IncrementalTrie:
    """Immutable MPT handle: ``update``/``delete`` return NEW handles that
    share structure with the old one, so chain snapshots are cheap and a
    block's root costs O(dirty keys x depth) rehashing (node encodings
    memoize on the shared immutable nodes)."""

    __slots__ = ("_root",)

    def __init__(self, _root=None):
        self._root = _root

    @classmethod
    def from_pairs(cls, pairs: dict[bytes, bytes]) -> "IncrementalTrie":
        t = cls()
        for k, v in pairs.items():
            t = t.update(k, v)
        return t

    def update(self, key: bytes, value: bytes) -> "IncrementalTrie":
        if not value:
            return self.delete(key)
        return IncrementalTrie(
            _insert(self._root, tuple(_nibbles(key)), value))

    def delete(self, key: bytes) -> "IncrementalTrie":
        return IncrementalTrie(_delete(self._root, tuple(_nibbles(key))))

    def get(self, key: bytes):
        return _get(self._root, tuple(_nibbles(key)))

    def items(self):
        """Yield ``(key, value)`` over every leaf, keys re-packed from
        nibble paths.  This is the state-sync SERVING walk (ref role:
        trie.Iterator in eth/downloader/statesync.go's source side); on
        a secure trie the keys that come back are the hashed ones."""
        def walk(node, path):
            if node is None:
                return
            if isinstance(node, _Leaf):
                yield path + node.path, node.value
            elif isinstance(node, _Ext):
                yield from walk(node.child, path + node.path)
            else:  # _Branch
                if node.value:
                    yield path, node.value
                for i, ch in enumerate(node.children):
                    if ch is not None:
                        yield from walk(ch, path + (i,))
        for nibs, val in walk(self._root, ()):
            yield (bytes((nibs[i] << 4) | nibs[i + 1]
                         for i in range(0, len(nibs), 2)), val)

    def root(self) -> bytes:
        if self._root is None:
            return EMPTY_ROOT
        return keccak256(_encode_node(self._root))


class SecureIncrementalTrie:
    """Secure-keyed wrapper (keys pre-hashed, ref: trie/secure_trie.go)."""

    __slots__ = ("_t",)

    def __init__(self, _t: IncrementalTrie | None = None):
        self._t = _t if _t is not None else IncrementalTrie()

    def update(self, key: bytes, value: bytes) -> "SecureIncrementalTrie":
        return SecureIncrementalTrie(self._t.update(keccak256(key), value))

    def delete(self, key: bytes) -> "SecureIncrementalTrie":
        return SecureIncrementalTrie(self._t.delete(keccak256(key)))

    def get(self, key: bytes):
        return self._t.get(keccak256(key))

    def items(self):
        """(hashed_key, value) pairs — see IncrementalTrie.items."""
        return self._t.items()

    @classmethod
    def from_hashed_pairs(cls, pairs) -> "SecureIncrementalTrie":
        """Rebuild from ``(hashed_key, value)`` pairs as served by
        ``items()`` — the state-sync RECEIVING side.  The caller proves
        integrity by comparing ``root()`` against a certified
        commitment; nothing here trusts the pairs."""
        return cls(IncrementalTrie.from_pairs(dict(pairs)))

    def root(self) -> bytes:
        return self._t.root()
