"""Merkle-Patricia trie: root hashing and key/value proofs-of-inclusion.

Fills the role of the reference's ``trie/`` package for the paths the
consensus capability set needs: ``DeriveSha`` over transactions/receipts
(ref: core/types/derive_sha.go) and a generic secure-keyed KV trie for
state roots (ref: trie/trie.go, trie/secure_trie.go).  This is a batch
builder — it materialises the node structure for a key set and folds it
into the keccak root — rather than a journaled incremental trie; the
chain layer rebuilds roots per block, which at Geec's 1000-txn operating
point is microseconds of host work and keeps the structure immutable
(functional style, no in-place node mutation).
"""

from __future__ import annotations

from eges_tpu.core import rlp
from eges_tpu.crypto.keccak import keccak256

EMPTY_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)  # keccak256(rlp(b''))


def _nibbles(key: bytes) -> list[int]:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0xF)
    return out


def _hp_encode(nibbles: list[int], terminal: bool) -> bytes:
    """Hex-prefix encoding (ref: trie/encoding.go hexToCompact)."""
    flag = 2 if terminal else 0
    if len(nibbles) % 2:
        head = [flag + 1] + nibbles
    else:
        head = [flag, 0] + nibbles
    return bytes(
        (head[i] << 4) | head[i + 1] for i in range(0, len(head), 2)
    )


def _node_ref(encoded: bytes):
    """Nodes < 32 bytes embed in the parent; otherwise refer by hash."""
    if len(encoded) < 32:
        return rlp.decode(encoded)
    return keccak256(encoded)


def _lcp_below(items, depth: int) -> int:
    """Longest common nibble prefix of ``items`` at/below ``depth``."""
    first = items[0][0]
    lcp = len(first)
    for nib, _ in items[1:]:
        i = depth
        limit = min(len(first), len(nib))
        while i < limit and nib[i] == first[i]:
            i += 1
        lcp = min(lcp, i)
    return lcp


def _build(items: list[tuple[list[int], bytes]], depth: int):
    """Build the node for items sharing a prefix of length ``depth``.

    Returns the RLP *structure* of the node (to be encoded / hashed by
    the caller).  ``items`` must be sorted and have distinct keys.
    """
    if not items:
        return b""
    if len(items) == 1:
        nib, val = items[0]
        return [_hp_encode(nib[depth:], True), val]

    # longest common prefix below depth
    first = items[0][0]
    lcp = _lcp_below(items, depth)
    if lcp > depth:
        child = _build(items, lcp)
        return [_hp_encode(first[depth:lcp], False), _node_ref(rlp.encode(child))]

    # branch node
    children = [b""] * 16
    value = b""
    buckets: dict[int, list] = {}
    for nib, val in items:
        if len(nib) == depth:
            value = val
        else:
            buckets.setdefault(nib[depth], []).append((nib, val))
    for idx, bucket in buckets.items():
        child = _build(bucket, depth + 1)
        children[idx] = _node_ref(rlp.encode(child))
    return children + [value]


def trie_root(pairs: dict[bytes, bytes]) -> bytes:
    """Root hash of the MPT holding ``pairs`` (raw keys)."""
    if not pairs:
        return EMPTY_ROOT
    items = sorted((_nibbles(k), v) for k, v in pairs.items())
    node = _build(items, 0)
    return keccak256(rlp.encode(node))


def secure_trie_root(pairs: dict[bytes, bytes]) -> bytes:
    """Root with keccak-hashed keys (ref: trie/secure_trie.go)."""
    return trie_root({keccak256(k): v for k, v in pairs.items()})


def derive_sha(encoded_items: list[bytes]) -> bytes:
    """Tx/receipt root: trie keyed by rlp(index) (ref: core/types/derive_sha.go:30)."""
    return trie_root({rlp.encode(i): item for i, item in enumerate(encoded_items)})


# ---------------------------------------------------------------------------
# proofs of inclusion / exclusion (ref: trie/proof.go Prove/VerifyProof)
# ---------------------------------------------------------------------------

def _hp_decode(data: bytes) -> tuple[list[int], bool]:
    nibs = _nibbles(data)
    flag = nibs[0]
    terminal = flag >= 2
    skip = 1 if flag % 2 else 2
    return nibs[skip:], terminal


def trie_prove(pairs: dict[bytes, bytes], key: bytes) -> list[bytes]:
    """Merkle proof for ``key`` against ``trie_root(pairs)``: the encoded
    nodes on the key's path that are referenced by hash (embedded short
    nodes travel inside their parent, as in the reference's proof lists).
    Valid for absent keys too (an exclusion proof)."""
    if not pairs:
        return []
    nib = _nibbles(key)
    items = sorted((_nibbles(k), v) for k, v in pairs.items())
    depth = 0
    proof: list[bytes] = []
    enc = rlp.encode(_build(items, depth))  # root node
    hashed = True  # the root is always by-hash
    while True:
        if hashed:
            proof.append(enc)
        if len(items) == 1:
            return proof
        lcp = _lcp_below(items, depth)
        if lcp > depth:  # extension node
            if nib[depth:lcp] != items[0][0][depth:lcp]:
                return proof  # diverges here: exclusion proven
            depth = lcp
            enc = rlp.encode(_build(items, depth))
            hashed = len(enc) >= 32
            continue
        # branch node
        if len(nib) == depth:
            return proof  # value (or absence) sits in this branch
        bucket = [(n, v) for n, v in items
                  if len(n) > depth and n[depth] == nib[depth]]
        if not bucket:
            return proof  # empty child slot: exclusion proven
        items = bucket
        depth += 1
        enc = rlp.encode(_build(items, depth))
        hashed = len(enc) >= 32


def verify_proof(root: bytes, key: bytes, proof: list[bytes]):
    """Walk ``proof`` from ``root``; returns the proven value, or None
    when the proof shows the key absent.  Raises ValueError on any
    inconsistency (a forged proof)."""
    if root == EMPTY_ROOT:
        if proof:
            raise ValueError("non-empty proof for the empty trie")
        return None
    nib = _nibbles(key)
    it = iter(proof)

    def load(ref):
        if isinstance(ref, (bytes, bytearray)) and len(ref) == 32:
            enc = next(it, None)
            if enc is None:
                raise ValueError("proof truncated")
            if keccak256(enc) != bytes(ref):
                raise ValueError("proof node hash mismatch")
            return rlp.decode(enc)
        return ref  # embedded node (list) or empty slot (b"")

    node = load(root)
    i = 0
    while True:
        if isinstance(node, (bytes, bytearray)):
            if len(node) == 0:
                return None  # empty slot: key absent
            raise ValueError("malformed proof node")
        if len(node) == 17:  # branch
            if i == len(nib):
                val = bytes(node[16])
                return val if val else None
            node = load(node[nib[i]])
            i += 1
            continue
        if len(node) != 2:
            raise ValueError("malformed proof node")
        path, terminal = _hp_decode(bytes(node[0]))
        if terminal:
            return bytes(node[1]) if nib[i:] == path else None
        if nib[i:i + len(path)] != path:
            return None  # extension diverges: key absent
        i += len(path)
        node = load(node[1])


def secure_trie_prove(pairs: dict[bytes, bytes], key: bytes) -> list[bytes]:
    """Proof against :func:`secure_trie_root` (keccak-hashed keys)."""
    return trie_prove({keccak256(k): v for k, v in pairs.items()},
                      keccak256(key))


def verify_secure_proof(root: bytes, key: bytes, proof: list[bytes]):
    return verify_proof(root, keccak256(key), proof)
