"""RLP — recursive length prefix serialization.

Canonical wire/storage encoding for every block, transaction, consensus
message and DB record, same role as the reference's ``rlp/`` package
(ref: rlp/encode.go, rlp/decode.go; Geec messages ride it too,
core/geec_state.go:569, consensus/geec/election/election_go.go:104).

Value model: an *item* is ``bytes`` or a ``list`` of items.  Helpers map
Python ints and fixed-width fields to the canonical big-endian-no-leading-
zero byte form geth uses.  Decoding is strict: non-canonical encodings
(leading zeros in lengths, single bytes < 0x80 wrapped in a string header)
are rejected, matching the reference's canonicality rules.
"""

from __future__ import annotations

Item = "bytes | list[Item]"


class RLPError(ValueError):
    pass


def encode_uint(x: int) -> bytes:
    """Int -> minimal big-endian bytes (0 -> b'')."""
    if x < 0:
        raise RLPError("negative integer")
    if x == 0:
        return b""
    return x.to_bytes((x.bit_length() + 7) // 8, "big")


def decode_uint(b: bytes) -> int:
    if b[:1] == b"\x00":
        raise RLPError("non-canonical integer (leading zero)")
    return int.from_bytes(b, "big")


def _encode_length(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    lb = encode_uint(length)
    return bytes([offset + 55 + len(lb)]) + lb


def encode(item) -> bytes:
    """Encode bytes / int / list (nested) to RLP."""
    if isinstance(item, int):
        item = encode_uint(item)
    if isinstance(item, (bytes, bytearray, memoryview)):
        b = bytes(item)
        if len(b) == 1 and b[0] < 0x80:
            return b
        return _encode_length(len(b), 0x80) + b
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(x) for x in item)
        return _encode_length(len(payload), 0xC0) + payload
    raise RLPError(f"cannot RLP-encode {type(item)!r}")


def _decode_at(data: bytes, pos: int):
    """Decode one item at ``pos``; returns (item, next_pos)."""
    if pos >= len(data):
        raise RLPError("truncated input")
    b0 = data[pos]
    if b0 < 0x80:
        return bytes([b0]), pos + 1
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        end = pos + 1 + n
        if end > len(data):
            raise RLPError("truncated string")
        s = data[pos + 1 : end]
        if n == 1 and s[0] < 0x80:
            raise RLPError("non-canonical single byte")
        return s, end
    if b0 < 0xC0:  # long string
        ln = b0 - 0xB7
        if pos + 1 + ln > len(data):
            raise RLPError("truncated length")
        lb = data[pos + 1 : pos + 1 + ln]
        if lb[:1] == b"\x00":
            raise RLPError("non-canonical length")
        n = int.from_bytes(lb, "big")
        if n < 56:
            raise RLPError("non-canonical long string")
        end = pos + 1 + ln + n
        if end > len(data):
            raise RLPError("truncated string")
        return data[pos + 1 + ln : end], end
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        end = pos + 1 + n
        if end > len(data):
            raise RLPError("truncated list")
        return _decode_list(data, pos + 1, end), end
    # long list
    ln = b0 - 0xF7
    if pos + 1 + ln > len(data):
        raise RLPError("truncated length")
    lb = data[pos + 1 : pos + 1 + ln]
    if lb[:1] == b"\x00":
        raise RLPError("non-canonical length")
    n = int.from_bytes(lb, "big")
    if n < 56:
        raise RLPError("non-canonical long list")
    end = pos + 1 + ln + n
    if end > len(data):
        raise RLPError("truncated list")
    return _decode_list(data, pos + 1 + ln, end), end


def _decode_list(data: bytes, pos: int, end: int) -> list:
    out = []
    while pos < end:
        item, pos = _decode_at(data, pos)
        out.append(item)
    if pos != end:
        raise RLPError("list payload overrun")
    return out


def decode(data: bytes):
    """Decode a single RLP item; trailing bytes are an error."""
    item, end = _decode_at(bytes(data), 0)
    if end != len(data):
        raise RLPError("trailing bytes")
    return item


def peek_first_uint(data: bytes) -> int | None:
    """First element of an RLP ``[uint, ...]`` frame, WITHOUT decoding
    the body — the message-code peek the gossip mux runs on every
    inbound frame (a full :func:`decode` of a megabyte block reply just
    to route it would double the parse cost of the hot path).  Returns
    None for anything that isn't a list opening with a small canonical
    uint."""
    data = bytes(data)
    if not data or data[0] < 0xC0:
        return None
    pos = 1 if data[0] < 0xF8 else 1 + (data[0] - 0xF7)
    if pos >= len(data):
        return None
    h = data[pos]
    if h < 0x80:
        # raw single byte; 0x00 is the non-canonical zero (canonical
        # zero is the empty string 0x80), mirroring decode_uint
        return h if h else None
    if h < 0xB8:
        v = data[pos + 1 : pos + 1 + (h - 0x80)]
        if len(v) != h - 0x80 or v[:1] == b"\x00" \
                or (len(v) == 1 and v[0] < 0x80):
            return None
        return int.from_bytes(v, "big")
    return None
