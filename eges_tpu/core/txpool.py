"""Transaction pool with a device-batched verification window.

Role parity with the reference's ``core/tx_pool.go`` for the Geec
capability set: remote txns are validated (signature -> sender) before
entering the pending set the proposer drains (ref: validateTx's
``types.Sender`` call, core/tx_pool.go:571-573 — the "second TPU
batch-verify target", SURVEY §2.2).

TPU-first redesign (SURVEY §7 step 5): instead of one ecrecover per
``add``, incoming txns accumulate in a verify queue that is flushed as
ONE device batch when either ``max_batch`` rows are waiting or the
``window_ms`` timer fires — the classic latency/occupancy batching
window.  Senders come back from the same batch (recover_addresses), so
admission costs one device call per window regardless of txn rate.
"""

from __future__ import annotations

import threading

from eges_tpu.core.types import Transaction
from eges_tpu.utils import ledger
from eges_tpu.utils import tracing


class _WindowChunk:
    """A columnar window's fresh rows queued for the verify flush.

    Rides the same ``_queue`` as scalar ``Transaction`` entries so
    mixed arrivals (windows from gossip, singletons from RPC) flush in
    strict arrival order; ``rows`` indexes the still-live rows of the
    shared ``TxColumns`` and shrinks in place when a flush slice splits
    the chunk at a ``max_batch`` boundary."""

    __slots__ = ("cols", "rows")

    def __init__(self, cols, rows):
        self.cols = cols
        self.rows = rows  # list of row indices into cols, arrival order


class TxPool:
    def __init__(self, clock, verifier=None, *, window_ms: float = 5.0,
                 max_batch: int = 1024, max_pending: int = 100_000,
                 on_admitted=None, journal_path: str | None = None):
        self.clock = clock
        self.verifier = verifier
        self.window_ms = window_ms
        self.max_batch = max_batch
        self.max_pending = max_pending
        self.on_admitted = on_admitted
        # One re-entrant monitor guards every mutable structure below:
        # add_locals arrives on the RPC thread while the window flush
        # fires on the clock thread.  A GeecNode that adopts this pool
        # REPLACES this lock with its own (GeecNode.txpool setter) so
        # node + pool form a single lock domain — the on_admitted hook
        # re-enters the node from inside a flush, and two separate locks
        # would be acquired in opposite orders on that path.
        self._lock = threading.RLock()
        # local-txn journal (ref: core/tx_pool.go journal — locally
        # submitted txns survive a restart): append-only RLP records,
        # rotated to the still-pending set when it grows stale
        self.journal_path = journal_path
        self._journal = None
        self._journal_count = 0
        # sender -> {nonce -> txn}; admission order preserved separately
        # as (sender, txn) so selection never rescans the whole pool
        self.pending: dict[bytes, dict[int, Transaction]] = {}  # guarded-by: _lock
        self._order: list[tuple[bytes, Transaction]] = []  # guarded-by: _lock
        # hash -> (sender, nonce)
        self._by_hash: dict[bytes, tuple[bytes, int]] = {}  # guarded-by: _lock
        self._dead: set[bytes] = set()  # guarded-by: _lock
        self._known: set[bytes] = set()  # guarded-by: _lock
        # verify queue: scalar Transactions interleaved with columnar
        # _WindowChunk entries in strict arrival order (mixed arrivals
        # must flush exactly like an all-scalar stream); _queue_rows is
        # the ROW count (a chunk is many rows), the unit max_batch and
        # the flush trigger are denominated in
        self._queue: list = []  # guarded-by: _lock
        self._queue_rows = 0  # guarded-by: _lock
        self._window_chunks = 0  # guarded-by: _lock
        self._timer = None
        self.stats = {"admitted": 0, "rejected": 0, "duplicate": 0,  # guarded-by: _lock
                      "batches": 0, "replaced": 0}
        # distributed-tracing linkage: per-txn SpanContext captured at
        # ingest.  The flush runs on a clock callback where contextvars
        # don't survive, so the context is carried here explicitly and
        # re-parented at admit / commit time.
        self.owner = ""  # identifies this pool's node in span attrs
        self._ingest_ctx: dict[bytes, tracing.SpanContext] = {}  # guarded-by: _lock
        self._INGEST_CTX_CAP = 8192
        self._KNOWN_CAP = 1 << 16  # dedup-history bound (maxKnownTxs role)
        # commit-anatomy linkage: per-txn ingest/admit timestamps on the
        # node clock (virtual under the simulator), emitted as one
        # ``commit_anatomy`` stage="pool" event when a block includes
        # the txns — the ingest->admission leg of the per-block
        # critical path (harness/anatomy.py).  Same cap discipline as
        # ``_ingest_ctx``: entries die at eviction.
        self._ingest_t: dict[bytes, float] = {}  # guarded-by: _lock
        self._admit_t: dict[bytes, float] = {}  # guarded-by: _lock
        # ingress-provenance linkage: per-txn (ledger, origin) captured
        # at ingest (utils/ledger.py ambient context) — the window flush
        # runs on a clock callback where the ambient binding is gone, so
        # admit/reject outcomes charge the captured pair.  Same cap
        # discipline as ``_ingest_ctx``; entries pop at their outcome.
        self._ingest_origin: dict[bytes, tuple] = {}
        # consensus event journal (utils/journal.py), attached by the
        # owning GeecNode; distinct from the RLP txn journal above
        self.event_journal = None
        self._depth_gauge()  # register txpool.pending at 0

    def _depth_gauge(self) -> None:
        from eges_tpu.utils import metrics

        metrics.DEFAULT.gauge("txpool.pending").set(len(self._by_hash))

    # -- ingest -----------------------------------------------------------

    def add_remotes(self, txns) -> None:  # thread-entry (RPC via add_locals); ingress-entry:bounded
        """Queue remote txns for batched admission
        (ref: TxPool.AddRemotes core/tx_pool.go:551)."""
        fresh = 0
        with self._lock, \
                tracing.DEFAULT.span("txpool.ingest", owner=self.owner) as sp:
            ctx = sp.context()
            for t in txns:
                h = t.hash
                if h in self._known:
                    self.stats["duplicate"] += 1
                    # ambient charge: a re-delivered txn is pure waste
                    # billed to whoever delivered THIS copy
                    ledger.charge(drops=1)
                    continue
                if len(self._known) >= self._KNOWN_CAP:
                    # coarse clear at the cap (geth's maxKnownTxs
                    # idiom): briefly losing dedup history is cheaper
                    # than letting a hash flood grow the set forever
                    self._known.clear()
                    from eges_tpu.utils import metrics
                    metrics.DEFAULT.counter("txpool.known_clears").inc()
                self._known.add(h)
                self._queue.append(t)
                self._queue_rows += 1
                # one capacity probe covers all three bookkeeping maps:
                # they fill together here and the thread-hygiene counter
                # reconciliation assumes a uniform cap across them
                if len(self._ingest_ctx) < self._INGEST_CTX_CAP:
                    self._ingest_ctx[h] = ctx
                    self._ingest_t[h] = self.clock.now()
                    rec = ledger.current()
                    if rec is not None:
                        self._ingest_origin[h] = rec
                fresh += 1
            sp.set_attr("fresh", fresh)
            if self._queue_rows >= self.max_batch:
                self._flush()
            elif self._queue and self._timer is None:
                self._timer = self.clock.call_later(self.window_ms / 1e3,
                                                    self._on_window)

    def add_remotes_window(self, cols) -> None:  # thread-entry (gossip relay); ingress-entry:bounded
        """Columnar window admission: ONE lock hold and ONE tracing span
        for the whole window, dedup against ``_known`` via set ops, and
        per-window (not per-tx) bookkeeping — the batched sibling of
        :meth:`add_remotes` with row-for-row identical admission
        outcomes, journal events and ledger billing (the differential
        test's contract).  ``cols`` is an ``ingress.columnar.TxColumns``
        duck type: this layer consumes the arrays, it never imports the
        decoder (core stays below ingress in the layer map)."""
        with self._lock, \
                tracing.DEFAULT.span("txpool.ingest", owner=self.owner) as sp:
            ctx = sp.context()
            hashes = cols.hashes
            n_undec = cols.n - int(cols.decoded.sum())
            if n_undec:
                # no identity survives a failed decode: billed to the
                # deliverer as pure waste, dropped pre-queue (the legacy
                # path never sees such rows — its codec drops them)
                ledger.charge(drops=n_undec)
                from eges_tpu.utils import metrics
                metrics.DEFAULT.counter("txpool.window_undecoded").inc(
                    n_undec)
            hs = hashes if not n_undec else \
                [h for h in hashes if h is not None]
            known = self._known
            dup = 0
            if len(known) + len(hs) < self._KNOWN_CAP:
                # fast path: the cap cannot trip mid-window, so dedup is
                # two C-level set ops instead of a per-row probe loop
                uniq = set(hs)
                if len(uniq) == len(hs):
                    dups = uniq & known
                    if dups:
                        dup = len(dups)
                        fresh_rows = [i for i, h in enumerate(hashes)
                                      if h is not None and h not in dups]
                    elif not n_undec:
                        fresh_rows = list(range(cols.n))  # bounded-by: cols.n == len of ONE delivered gossip window (pre-decode INGRESS_MAX_BYTES datagram cap upstream)
                    else:
                        fresh_rows = [i for i, h in enumerate(hashes)
                                      if h is not None]
                    known.update(uniq)
                else:
                    fresh_rows = self._dedup_rows_slow(hashes)
                    dup = len(hs) - len(fresh_rows)
            else:
                # cap boundary: replicate the per-row coarse-clear
                # semantics exactly (a clear mid-window re-admits
                # earlier duplicates, same as the scalar path would)
                fresh_rows = self._dedup_rows_slow(hashes)
                dup = len(hs) - len(fresh_rows)
            if dup:
                self.stats["duplicate"] += dup
                # ambient charge, aggregated: N same-origin unit drops
                # at one timestamp equal one summed drop charge
                ledger.charge(drops=dup)
            if fresh_rows:
                now = self.clock.now()
                rec = ledger.current()
                room = self._INGEST_CTX_CAP - len(self._ingest_ctx)
                book = fresh_rows[:room] if room < len(fresh_rows) \
                    else fresh_rows
                if book:
                    self._ingest_ctx.update((hashes[i], ctx) for i in book)
                    self._ingest_t.update((hashes[i], now) for i in book)
                    if rec is not None:
                        self._ingest_origin.update(
                            (hashes[i], rec) for i in book)
                self._queue.append(_WindowChunk(cols, fresh_rows))
                self._window_chunks += 1
                self._queue_rows += len(fresh_rows)
            sp.set_attr("fresh", len(fresh_rows) if fresh_rows else 0)
            if self._queue_rows >= self.max_batch:
                self._flush()
            elif self._queue and self._timer is None:
                self._timer = self.clock.call_later(self.window_ms / 1e3,
                                                    self._on_window)

    def _dedup_rows_slow(self, hashes) -> list[int]:
        """Per-row dedup replica of the scalar loop — the path taken
        when the window carries intra-window duplicates or could trip
        the ``_KNOWN_CAP`` coarse clear mid-window."""
        fresh_rows = []
        known = self._known
        for i, h in enumerate(hashes):
            if h is None or h in known:
                continue
            if len(known) >= self._KNOWN_CAP:
                known.clear()
                from eges_tpu.utils import metrics
                metrics.DEFAULT.counter("txpool.known_clears").inc()
            known.add(h)
            fresh_rows.append(i)
        return fresh_rows

    def _on_window(self) -> None:
        with self._lock:
            self._timer = None
            self._flush()

    def _flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._window_chunks:
            self._flush_mixed()
            return
        batch, self._queue = self._queue[: self.max_batch], \
            self._queue[self.max_batch:]
        if not batch:
            return
        self._queue_rows -= len(batch)
        self.stats["batches"] += 1
        parts = [t.signature_parts() for t in batch]
        senders: list[bytes | None] = [None] * len(batch)
        rows = [(i, p) for i, p in enumerate(parts) if p is not None]
        if rows:
            # one shared recovery path for all three verifier shapes:
            # a VerifierScheduler (window coalescing across callers +
            # the sender cache, so a re-gossiped txn costs a lookup),
            # a plain batch verifier (one device batch), or None (the
            # per-entry host fallback, signature_nocgo.go role)
            from eges_tpu.crypto.verify_host import recover_signers
            rec = recover_signers([(h, sig) for _, (sig, h) in rows],
                                  self.verifier)
            for (i, _), sender in zip(rows, rec):
                senders[i] = sender
        for t, sender in zip(batch, senders):
            if sender is None:
                self.stats["rejected"] += 1
                # invalid signature: the cheap-reject path an ingress
                # flood rides — billed to the captured ingest origin
                self._ledger_charge(t.hash, rejects=1)
                continue
            self._admit(t, sender)
        if self._queue:
            self._flush()

    def _flush_mixed(self) -> None:
        """Row-granular flush for a queue holding columnar window
        chunks (possibly interleaved with scalar txns): each
        ``max_batch``-row slice makes ONE ``recover_signers_window``
        call over arrays gathered straight out of the columns — no
        per-row ``signature_parts``, no per-row entry tuples — and
        ``Transaction`` objects materialize only for rows that admit.
        Outcome order matches the scalar ``_flush`` row for row."""
        import numpy as np

        while self._queue:
            take: list = []
            rows_n = 0
            qi = 0
            consumed_chunks = 0
            while qi < len(self._queue) and rows_n < self.max_batch:
                item = self._queue[qi]
                if isinstance(item, _WindowChunk):
                    need = self.max_batch - rows_n
                    if len(item.rows) <= need:
                        take.append(item)
                        rows_n += len(item.rows)
                        consumed_chunks += 1
                        qi += 1
                    else:  # split: head flushes now, tail stays queued
                        take.append(_WindowChunk(item.cols,
                                                 item.rows[:need]))
                        item.rows = item.rows[need:]
                        rows_n += need
                else:
                    take.append(item)
                    rows_n += 1
                    qi += 1
            self._queue = self._queue[qi:]
            self._window_chunks -= consumed_chunks
            self._queue_rows -= rows_n
            self.stats["batches"] += 1
            # flat row map in arrival order; gather valid rows' arrays
            flat: list = []  # (cols|txn, row_index|None) per output row
            vh, vs, vpos = [], [], []
            for item in take:
                if isinstance(item, _WindowChunk):
                    c, rs = item.cols, item.rows
                    base = len(flat)
                    flat.extend((c, i) for i in rs)
                    rs_arr = np.asarray(rs, dtype=np.int64)
                    mask = c.valid[rs_arr]
                    sel = rs_arr[mask]
                    if sel.size:
                        vh.append(c.sighash[sel])
                        vs.append(c.sig[sel])
                        vpos.extend(
                            (base + np.nonzero(mask)[0]).tolist())
                else:
                    pos = len(flat)
                    flat.append((item, None))
                    p = item.signature_parts()
                    if p is not None:
                        sig, h = p
                        vh.append(np.frombuffer(h, np.uint8)
                                  .reshape(1, 32))
                        vs.append(np.frombuffer(sig, np.uint8)
                                  .reshape(1, 65))
                        vpos.append(pos)
            senders: list = [None] * len(flat)
            if vpos:
                from eges_tpu.crypto.verify_host import \
                    recover_signers_window
                rec = recover_signers_window(
                    vh[0] if len(vh) == 1 else np.concatenate(vh),
                    vs[0] if len(vs) == 1 else np.concatenate(vs),
                    self.verifier)
                for pos, sender in zip(vpos, rec):
                    senders[pos] = sender
            rej: list = []
            # ONE admit span for the whole slice's window rows (spans
            # are ring-buffer telemetry, never journaled — admission
            # outcomes, billing and relay order stay per-row identical
            # to the scalar path); scalar interlopers keep their own
            # per-row span via _admit
            wcm = wsp = None
            amb = ledger.current()  # stable for the whole slice
            try:
                for j, (obj, li) in enumerate(flat):
                    sender = senders[j]
                    if sender is None:
                        self.stats["rejected"] += 1
                        rej.append(obj.hash if li is None
                                   else obj.hashes[li])
                    elif li is None:
                        self._admit(obj, sender)
                    else:
                        t = obj.txn(li)
                        if wcm is None:
                            ctx = self._ingest_ctx.get(t.hash) \
                                or tracing.DEFAULT.current_context()
                            wcm = tracing.DEFAULT.span(
                                "txpool.admit_window", parent=ctx,
                                owner=self.owner, rows=len(flat))
                            wsp = wcm.__enter__()
                        self._admit_traced(t, sender, wsp, batched=True,
                                           amb=amb)
            finally:
                if wcm is not None:
                    wcm.__exit__(None, None, None)
                    # slice-deferred housekeeping (see _admit_traced)
                    self._maybe_compact()
                    self._depth_gauge()
            if rej:
                self._ledger_charge_many(rej, rejects=1)

    def _ledger_charge_many(self, hashes, **counts) -> None:
        """Aggregated flush billing: ONE ``charge()`` per (ledger,
        origin) group — N same-origin unit outcomes at one virtual
        timestamp sum to the same ledger state as N unit charges (the
        decay is lazy, applied per charge timestamp)."""
        amb = ledger.current()
        groups: dict = {}
        order: list = []
        for h in hashes:
            rec = self._ingest_origin.pop(h, None) or amb
            if rec is None:
                continue
            key = (id(rec[0]), rec[1])
            slot = groups.get(key)
            if slot is None:
                groups[key] = [rec, 1]
                order.append(key)
            else:
                slot[1] += 1
        for key in order:
            (led, origin), n = groups[key]
            led.charge(origin, **{k: v * n for k, v in counts.items()})

    # sentinel: "caller did not pre-resolve the ambient ledger pair"
    _NO_AMB = object()

    def _ledger_charge(self, h: bytes, _amb=_NO_AMB, **counts) -> None:
        """Charge a flush outcome to the origin captured at ingest (the
        flush runs on a clock callback with no ambient binding); falls
        back to the ambient pair, no-op when neither exists.  ``_amb``
        lets a window flush resolve :func:`ledger.current` once per
        slice instead of per row — the ambient binding cannot change
        mid-flush (one clock callback, one thread)."""
        rec = self._ingest_origin.pop(h, None)
        if rec is None:
            rec = ledger.current() if _amb is self._NO_AMB else _amb
        if rec is not None:
            led, origin = rec
            led.charge(origin, **counts)

    # a replacement for a (sender, nonce) slot must bid >= 10% more gas
    # price (ref: core/tx_pool.go PriceBump default 10)
    PRICE_BUMP_PCT = 10

    def _admit(self, t: Transaction, sender: bytes) -> None:
        # re-enter the txn's ingest trace: the flush that got us here ran
        # on a clock callback, outside any ambient span context
        ctx = self._ingest_ctx.get(t.hash) \
            or tracing.DEFAULT.current_context()
        with tracing.DEFAULT.span("txpool.admit", parent=ctx,
                                  owner=self.owner,
                                  tx=t.hash.hex()[:16]) as sp:
            self._admit_traced(t, sender, sp)

    def _admit_traced(self, t: Transaction, sender: bytes, sp,
                      batched: bool = False, amb=_NO_AMB) -> None:
        """Admission body.  ``batched=True`` (the window flush) defers
        the per-row housekeeping that is slice-equivalent: the depth
        gauge and ``_order`` compaction run once after the slice, and
        the shared window span skips per-row outcome attrs (on a
        shared span they are last-write-wins noise; the per-row
        outcomes live in ``stats`` and the ledger either way)."""
        by_nonce = self.pending.setdefault(sender, {})
        old = by_nonce.get(t.nonce)
        if old is None and len(self._by_hash) >= self.max_pending:
            # capacity only limits NEW slots: a price-bump replacement
            # keeps the pool size constant and must stay possible even
            # when full (ref: core/tx_pool.go admits replacements)
            self.stats["rejected"] += 1
            self._ledger_charge(t.hash, amb, rejects=1, sender=sender)
            if not batched:
                sp.set_attr("outcome", "rejected")
            if not by_nonce:
                del self.pending[sender]
            return
        if old is not None:
            # price-bump replacement (ref: core/tx_pool.go:571+)
            if t.gas_price * 100 < old.gas_price * (100 + self.PRICE_BUMP_PCT):
                self.stats["duplicate"] += 1
                self._ledger_charge(t.hash, amb, drops=1, sender=sender)
                if not batched:
                    sp.set_attr("outcome", "duplicate")
                return
            self._by_hash.pop(old.hash, None)
            self._dead.add(old.hash)
            self.stats["replaced"] += 1
        by_nonce[t.nonce] = t
        self._order.append((sender, t))
        self._by_hash[t.hash] = (sender, t.nonce)
        if len(self._admit_t) < self._INGEST_CTX_CAP:
            self._admit_t[t.hash] = self.clock.now()
        self.stats["admitted"] += 1
        self._ledger_charge(t.hash, amb, admits=1, sender=sender)
        if not batched:
            self._maybe_compact()
            self._depth_gauge()
            sp.set_attr("outcome", "admitted")
        if self.on_admitted is not None:
            # still inside the admit span: a broadcast hook fired here
            # injects this trace into the outbound gossip envelope
            self.on_admitted(t, sender)

    def _maybe_compact(self) -> None:
        """Compact ``_order`` when mostly tombstones — reachable from
        both eviction AND replacement-heavy ingest (a replacement storm
        with no block inclusions must not grow memory unboundedly)."""
        if len(self._dead) * 2 > max(len(self._order), 64):
            self._order = [(s, t) for s, t in self._order
                           if t.hash not in self._dead]
            self._dead.clear()

    # -- drain ------------------------------------------------------------

    def pending_txns(self, limit: int | None = None,
                     state=None) -> list[Transaction]:
        """Executable-ordered pending txns for block building: senders in
        first-admission order, each sender's txns nonce-ascending
        (ref: TxPool.Pending + types.TxsByPriceAndNonce,
        miner/worker.go:463).

        With ``state`` (a StateDB), only the currently *executable*
        contiguous run per sender is returned — starting at the sender's
        state nonce and staying within its balance — and already-mined
        nonces are evicted.  This is the promote/demote split of the
        reference pool (pending vs queued, core/tx_pool.go): a sender
        with a nonce gap or empty purse no longer starves other senders
        out of the per-block limit."""
        with self._lock:
            seen: set[bytes] = set()
            out: list[Transaction] = []
            for s, _ in list(self._order):
                if s in seen:
                    continue
                seen.add(s)
                by_nonce = self.pending.get(s)
                if not by_nonce:
                    continue
                run = sorted(by_nonce.items())
                if state is not None:
                    start = state.nonce(s)
                    stale = [t for n, t in run if n < start]
                    if stale:
                        self._evict(stale)
                        run = [(n, t) for n, t in run if n >= start]
                    spendable = state.balance(s)
                    picked = []
                    want = start
                    for n, t in run:
                        if n != want:
                            break  # nonce gap: rest is non-executable
                        from eges_tpu.core.state import INTRINSIC_GAS
                        cost = t.value + t.gas_price * INTRINSIC_GAS
                        if cost > spendable:
                            break
                        spendable -= cost
                        picked.append(t)
                        want += 1
                    out.extend(picked)
                else:
                    out.extend(t for _, t in run)
                if limit and len(out) >= limit:
                    break
            return out[:limit] if limit else out

    def _evict(self, txns) -> None:
        """O(evicted) eviction: the ``_by_hash`` index locates each txn's
        (sender, nonce) slot directly, and ``_order`` compacts lazily via
        a tombstone set only when mostly dead (round-2 verdict weak #8:
        the old path rebuilt the whole order list per block)."""
        for t in txns:
            loc = self._by_hash.pop(t.hash, None)
            if loc is None:
                continue
            sender, nonce = loc
            by_nonce = self.pending.get(sender)
            if by_nonce is not None:
                cur = by_nonce.get(nonce)
                if cur is not None and cur.hash == t.hash:
                    del by_nonce[nonce]
                    if not by_nonce:
                        del self.pending[sender]
            self._dead.add(t.hash)  # bounded-by: _maybe_compact clears when dead > live (called below)
            self._ingest_ctx.pop(t.hash, None)
            self._ingest_t.pop(t.hash, None)
            self._admit_t.pop(t.hash, None)
            self._ingest_origin.pop(t.hash, None)
        self._maybe_compact()
        self._depth_gauge()

    def remove_included(self, txns, block: int | None = None) -> None:
        """Drop txns included in a canonical block; closes each txn's
        trace with a ``tx.commit`` span so ingest -> admit -> commit is
        one linked trace even across nodes."""
        with self._lock:
            for t in txns:
                ctx = self._ingest_ctx.get(t.hash)
                if ctx is not None:
                    tracing.DEFAULT.record_span(
                        "tx.commit", 0.0, parent=ctx, owner=self.owner,
                        tx=t.hash.hex()[:16],
                        **({"block": block} if block is not None else {}))
            # commit-anatomy pool stage: the ingest->admission leg of
            # this block's critical path, on the node clock (virtual
            # under the simulator, so deterministic in sims).  Emitted
            # BEFORE eviction drops the per-txn timestamps.
            if self.event_journal is not None and txns:
                ing = [self._ingest_t[t.hash] for t in txns
                       if t.hash in self._ingest_t]
                adm = [self._admit_t[t.hash] for t in txns
                       if t.hash in self._admit_t]
                if ing and adm:
                    self.event_journal.record(
                        "commit_anatomy", blk=block, stage="pool",
                        count=len(txns),
                        t_first_ingest=round(min(ing), 6),
                        t_last_admit=round(max(adm), 6),
                        ingest_to_admit_s=round(max(adm) - min(ing), 6))
            self._evict(txns)
            if self.event_journal is not None and txns:
                self.event_journal.record("txns_included", blk=block,
                                          count=len(txns))
            if (self.journal_path and
                    self._journal_count > max(64, 4 * len(self._by_hash))):
                self._rotate_journal()

    # -- local-txn journal (ref: core/tx_pool.go newTxJournal) ------------

    def add_locals(self, txns) -> None:  # thread-entry (RPC worker); ingress-entry:bounded
        """Admit locally-submitted txns AND journal them so they survive
        a node restart (remote gossip txns are not journaled).  Only
        FRESH txns journal — resubmitting the same txn N times must not
        grow the file — and a journal that outgrows the live pool 4x
        rotates even on a quiet chain."""
        with self._lock:
            fresh = [t for t in txns if t.hash not in self._known]
            if self.journal_path and fresh:
                import struct

                if self._journal is None:
                    self._journal = open(self.journal_path, "ab")
                for t in fresh:
                    raw = t.encode()
                    self._journal.write(struct.pack("<I", len(raw)) + raw)
                    self._journal_count += 1
                self._journal.flush()
                if self._journal_count > max(64, 4 * (len(self._by_hash)
                                                      + len(fresh))):
                    self._rotate_journal()
            self.add_remotes(txns)

    def load_journal(self) -> int:
        """Re-queue journaled local txns (stale nonces fall out at
        selection); returns how many were loaded.  A torn tail is
        repaired by rewriting the parsed prefix — otherwise every
        append after the tear would be unreadable forever."""
        import os
        import struct

        if not self.journal_path or not os.path.exists(self.journal_path):
            return 0
        with self._lock:
            with open(self.journal_path, "rb") as f:
                data = f.read()
            txns = []
            pos = 0
            good_end = 0
            while pos + 4 <= len(data):
                (n,) = struct.unpack("<I", data[pos : pos + 4])
                if pos + 4 + n > len(data):
                    break  # torn tail
                try:
                    txns.append(
                        Transaction.decode(data[pos + 4 : pos + 4 + n]))
                except Exception:
                    break  # torn/corrupt record: keep the parsed prefix
                pos += 4 + n
                good_end = pos
            if good_end != len(data):
                with open(self.journal_path, "r+b") as f:
                    f.truncate(good_end)
            self._journal_count = len(txns)
            if txns:
                self.add_remotes(txns)
                self._flush()
            return len(txns)

    def _rotate_journal(self) -> None:
        """Rewrite the journal with the still-pending set (a superset of
        the locals — geth rotates locals only; re-journaling a remote is
        harmless and keeps the rotation logic index-free)."""
        import os
        import struct

        if self._journal is not None:
            self._journal.close()
            self._journal = None
        tmp = self.journal_path + ".tmp"
        kept = 0
        with open(tmp, "wb") as f:
            for s, t in self._order:
                if t.hash in self._dead or t.hash not in self._by_hash:
                    continue
                raw = t.encode()
                f.write(struct.pack("<I", len(raw)) + raw)
                kept += 1
        os.replace(tmp, self.journal_path)
        self._journal_count = kept

    def close(self) -> None:
        with self._lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_hash)
