"""Pluggable consensus-engine seam.

Role parity with the reference's ``consensus.Engine`` interface
(ref: consensus/consensus.go:57 — VerifyHeader/Prepare/Finalize/Seal,
implemented by ethash, clique and geec): the chain layer calls the
engine for header verification and block assembly, so the Geec state
machine is ONE engine rather than a hardwired assumption.

This module lives in ``core`` — the interface belongs to the layer
that CONSUMES it (the chain calls the engine, never the reverse), so
L1 ``core.chain`` depending on an L2 ``consensus`` module would invert
the declared layer map.  ``eges_tpu.consensus.engine`` re-exports the
same names for the consensus layer and existing callers.

Engines here:

* :class:`GeecEngine` — the production engine: header verification is
  intentionally near-no-op (ancestry only, ref: consensus/geec/
  geec.go:186-210 verifyHeader); sealing is driven by the event-loop
  consensus node (:mod:`eges_tpu.consensus.node`), not a Seal() call.
* :class:`DevEngine` — single-authority instant-seal PoA (the clique
  role, ref: consensus/clique/clique.go's signed-extra scheme,
  re-designed: one signer, no epoch/voting): every sealed header
  carries the authority's signature over the header's signing hash in
  ``extra``; verification recovers and checks the signer.  This is the
  dev-chain mode (geth --dev analogue) and proves the seam carries a
  second, structurally different engine.
* :class:`PowEngine` — the ethash ROLE (ref: consensus/ethash/
  consensus.go VerifyHeader + sealer.go mine): nonce-searched
  keccak proof-of-work with parent-relative difficulty retargeting.
  NOT ethash's DAG/hashimoto (memory-hardness buys nothing in a
  permissioned deployment) — the TPU-first redesign instead makes the
  *search* the interesting part: candidate nonces are swept in device
  batches through :func:`eges_tpu.ops.keccak_tpu.keccak256_fixed`,
  thousands of hashes per dispatch, with a host fallback.
"""

from __future__ import annotations

import dataclasses

from eges_tpu.core.types import Block, Header, new_block


class EngineError(Exception):
    """Header/seal verification failure."""


class Engine:
    """The minimal engine surface the chain layer consumes."""

    name = "base"

    def verify_header(self, chain, header: Header) -> None:
        """Raise :class:`EngineError` on a bad header.  Ancestry/number
        checks are the chain layer's; engines add their own rules."""

    def prepare(self, chain, header: Header) -> Header:
        """Fill engine-owned header fields before execution."""
        return header

    def seal(self, chain, block: Block) -> Block:
        """Produce the sealed block (synchronous engines only)."""
        return block


class GeecEngine(Engine):
    """Geec: verification rides the quorum certificates, not the header
    (ref: geec.go:186-210 — the header check is deliberately minimal;
    VerifySeal is a stub, geec.go:223-226).  Sealing happens in the
    consensus node's phase machine, so :meth:`seal` is unused."""

    name = "geec"

    def verify_header(self, chain, header: Header) -> None:
        if header.number > 0 and header.time == 0:
            raise EngineError("missing timestamp")


class DevEngine(Engine):
    """Single-authority instant seal.  ``extra`` carries the 65-byte
    authority signature over the unsigned header hash."""

    name = "dev"

    def __init__(self, authority: bytes, priv: bytes | None = None):
        self.authority = authority  # 20-byte address
        self.priv = priv            # present on the sealing node only

    @staticmethod
    def _signing_hash(header: Header) -> bytes:
        from eges_tpu.core import rlp
        from eges_tpu.crypto.keccak import keccak256

        bare = dataclasses.replace(header, extra=b"")
        return keccak256(rlp.encode(bare.to_rlp()))

    def verify_header(self, chain, header: Header) -> None:
        from eges_tpu.crypto import secp256k1 as secp

        if header.number == 0:
            return
        if len(header.extra) != 65:
            raise EngineError("dev seal missing")
        try:
            signer = secp.recover_address(self._signing_hash(header),
                                          header.extra)
        except Exception:
            raise EngineError("unrecoverable dev seal")
        if signer != self.authority:
            raise EngineError("dev seal from a non-authority signer")

    def seal(self, chain, block: Block) -> Block:
        from eges_tpu.crypto import secp256k1 as secp

        if self.priv is None:
            raise EngineError("not the authority (no key)")
        sig = secp.ecdsa_sign(self._signing_hash(block.header), self.priv)
        header = dataclasses.replace(block.header, extra=sig)
        return dataclasses.replace(block, header=header)

    def seal_next(self, chain, txs=(), coinbase: bytes | None = None) -> Block:
        """Convenience dev-chain block producer: preview ``txs`` on the
        head state, assemble, seal, and offer — the geth --dev
        instant-mining loop collapsed to one call."""
        coinbase = coinbase if coinbase is not None else self.authority
        parent = chain.head()
        kept, root, receipt_hash, gas, bloom = chain.execute_preview(
            list(txs), coinbase)
        header = Header(parent_hash=parent.hash, number=parent.number + 1,
                        coinbase=coinbase, time=parent.header.time + 1,
                        root=root, receipt_hash=receipt_hash, gas_used=gas,
                        bloom=bloom)
        block = self.seal(chain, new_block(header, txs=kept))
        inserted = chain.offer(block)
        if not inserted:
            raise EngineError(f"dev block rejected: {chain.last_error}")
        return block


class PowEngine(Engine):
    """Keccak proof-of-work with device-batched nonce search.

    Verification (ref role: consensus/ethash/consensus.go
    verifyHeader + VerifySeal): ``keccak256(seal_hash || nonce)``
    interpreted big-endian must not exceed ``2**256 // difficulty``,
    and the header's difficulty must equal the parent-relative
    retarget.  Sealing sweeps nonce candidates in batches — on an
    accelerator via the batched Keccak graph (one dispatch hashes
    ``sweep_batch`` candidates), else a host loop."""

    name = "pow"

    TARGET_BLOCK_S = 13          # retarget setpoint (ethash's cadence)
    MIN_DIFFICULTY = 1

    def __init__(self, sweep_batch: int = 4096, use_device: bool = True,
                 max_sweeps: int = 1 << 16, clock=None):
        self.sweep_batch = sweep_batch
        self.use_device = use_device
        self.max_sweeps = max_sweeps  # gives up (re-prepare with new time)
        self._jit_sweep = None
        # injectable wall-clock for the future-drift bound: sims hand in
        # their virtual clock so a chaos run's accept/reject decisions
        # replay byte-identically regardless of host time
        if clock is None:
            import time as _time
            clock = _time.time
        self.clock = clock

    # -- difficulty ----------------------------------------------------

    @classmethod
    def calc_difficulty(cls, parent: Header, time: int) -> int:
        """Parent-relative retarget (the Homestead-family rule shape,
        ref: consensus/ethash/consensus.go CalcDifficulty — re-derived,
        no bomb: permissioned chains do not schedule their own
        obsolescence): faster than the setpoint raises difficulty by
        parent/2048, slower lowers it, clamped to the minimum."""
        delta = max(1 - (time - parent.time) // cls.TARGET_BLOCK_S, -99)
        return max(parent.difficulty + delta * (parent.difficulty // 2048 + 1),
                   cls.MIN_DIFFICULTY)

    # -- hashing -------------------------------------------------------

    @staticmethod
    def seal_hash(header: Header) -> bytes:
        """Hash of the header with the engine-owned fields zeroed."""
        from eges_tpu.core import rlp
        from eges_tpu.crypto.keccak import keccak256

        bare = dataclasses.replace(header, nonce=bytes(8),
                                   mix_digest=bytes(32))
        return keccak256(rlp.encode(bare.to_rlp()))

    @staticmethod
    def _target(difficulty: int) -> int:
        return (1 << 256) // max(difficulty, 1)

    @staticmethod
    def pow_value(seal_hash: bytes, nonce: bytes) -> int:
        from eges_tpu.crypto.keccak import keccak256

        return int.from_bytes(keccak256(seal_hash + nonce), "big")

    FUTURE_DRIFT_S = 15          # max claimable lead over wall clock
    #                              (ref: consensus/ethash allowedFutureBlockTime
    #                              role — without it, a far-future
    #                              timestamp grinds difficulty to the
    #                              floor and seals for free)

    def verify_header(self, chain, header: Header) -> None:
        if header.number == 0:
            return
        if header.time > self.clock() + self.FUTURE_DRIFT_S:
            raise EngineError("pow timestamp too far in the future")
        parent = chain.get_block_by_number(header.number - 1)
        if parent is not None:  # behind-sync callers may lack the parent
            if header.time <= parent.header.time:
                raise EngineError("pow timestamp not after parent")
            want = self.calc_difficulty(parent.header, header.time)
            if header.difficulty != want:
                raise EngineError(
                    f"pow difficulty {header.difficulty} != retarget {want}")
        if header.mix_digest != bytes(32):
            raise EngineError("pow mix_digest must be zero")
        if self.pow_value(self.seal_hash(header), header.nonce) \
                > self._target(header.difficulty):
            raise EngineError("pow seal below difficulty")

    def prepare(self, chain, header: Header) -> Header:
        parent = chain.get_block_by_number(header.number - 1)
        if parent is None:
            raise EngineError("unknown parent")
        return dataclasses.replace(
            header,
            difficulty=self.calc_difficulty(parent.header, header.time))

    # -- sealing -------------------------------------------------------

    def _sweep_device(self, sh: bytes, start: int, target: int):
        """One device dispatch: hash ``sweep_batch`` consecutive nonces,
        return the first winning nonce or None."""
        import numpy as np

        if self._jit_sweep is None:
            import jax

            from eges_tpu.ops.keccak_tpu import keccak256_fixed
            self._jit_sweep = jax.jit(keccak256_fixed)
        n = self.sweep_batch
        msgs = np.zeros((n, 40), np.uint8)
        msgs[:, :32] = np.frombuffer(sh, np.uint8)
        nonces = (start + np.arange(n, dtype=np.uint64))
        msgs[:, 32:] = (nonces[:, None]
                        >> np.arange(56, -8, -8, dtype=np.uint64)
                        ).astype(np.uint8)
        digests = np.asarray(self._jit_sweep(msgs))
        tbytes = (target.to_bytes(33, "big")[-32:]
                  if target < (1 << 256) else b"\xff" * 32)
        for i in range(n):  # host compare; n is small
            if bytes(digests[i]) <= tbytes:
                return int(nonces[i])
        return None

    def seal(self, chain, block: Block) -> Block:
        sh = self.seal_hash(block.header)
        target = self._target(block.header.difficulty)
        start = int.from_bytes(sh[:8], "big")  # deterministic start
        for sweep in range(self.max_sweeps):
            base = (start + sweep * self.sweep_batch) % (1 << 64)
            nonce = None
            if self.use_device:
                try:
                    nonce = self._sweep_device(sh, base, target)
                    if nonce is None:
                        continue
                except Exception as e:
                    # no backend (or a device fault): fall back — loudly,
                    # because the host loop is orders of magnitude slower
                    from eges_tpu.utils.log import get_logger
                    get_logger("engine.pow").warn(
                        f"device nonce sweep unavailable ({e!r}); "
                        "falling back to host search")
                    self.use_device = False
            if nonce is None:
                for i in range(self.sweep_batch):
                    cand = ((base + i) % (1 << 64)).to_bytes(8, "big")
                    if self.pow_value(sh, cand) <= target:
                        nonce = int.from_bytes(cand, "big")
                        break
                if nonce is None:
                    continue
            header = dataclasses.replace(
                block.header, nonce=int(nonce).to_bytes(8, "big"),
                mix_digest=bytes(32))
            return dataclasses.replace(block, header=header)
        raise EngineError("pow search exhausted; re-prepare with new time")

    def mine_next(self, chain, txs=(),
                  coinbase: bytes = bytes(20)) -> Block:
        """The miner loop collapsed to one call (ref role:
        miner/worker.go commit + ethash sealer): retarget, preview under
        the EXACT ctx the sealed header will carry (validation
        re-executes with block_ctx(header) — a contract reading
        TIMESTAMP/DIFFICULTY must see the same values or the committed
        root is unreproducible), seal, offer."""
        from eges_tpu.core.evm import BlockCtx

        parent = chain.head()
        time = parent.header.time + self.TARGET_BLOCK_S
        difficulty = self.calc_difficulty(parent.header, time)
        ctx = BlockCtx(coinbase=coinbase, number=parent.number + 1,
                       time=time, difficulty=difficulty)
        kept, root, receipt_hash, gas, bloom = chain.execute_preview(
            list(txs), coinbase, ctx=ctx)
        header = Header(parent_hash=parent.hash, number=parent.number + 1,
                        coinbase=coinbase, time=time, difficulty=difficulty,
                        root=root, receipt_hash=receipt_hash, gas_used=gas,
                        bloom=bloom)
        block = self.seal(chain, new_block(header, txs=kept))
        if not chain.offer(block):
            raise EngineError(f"pow block rejected: {chain.last_error}")
        return block
