"""Canonical chain management.

Covers the reference's L4 for the Geec capability set: ordered insertion
with header verification, body validation, batched sender recovery, a
durable block store, and the new-block notification hook that drives the
consensus state machine (ref: core/blockchain.go:1096 InsertChain,
:526-527 insert -> GeecState.NotifyNewBlock).

Deliberate TPU-first redesign (SURVEY §7.5): the reference funnels all
blocks through the fetcher queue then verifies/recovers senders one tx at
a time via cgo (core/state_processor.go:93).  Here insertion is a single
ordered funnel too (``offer`` buffers out-of-order arrivals), but sender
recovery for an entire block is ONE device batch via
:class:`~eges_tpu.crypto.verifier.BatchVerifier`, and verification of
header links is host-side (they are near-no-ops in Geec,
consensus/geec/geec.go:186-210).
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass

from eges_tpu.core import rlp
from eges_tpu.core.types import (
    Block, Header, new_block, EMPTY_ADDR, ZERO_HASH,
)


class ChainError(Exception):
    pass


class MemoryStore:
    """In-memory block store (the reference's ethdb.MemDatabase role,
    ethdb/memory_database.go — used by all unit tests)."""

    def __init__(self):
        self._by_hash: dict[bytes, bytes] = {}
        self._hash_by_number: dict[int, bytes] = {}
        self._head: bytes | None = None
        # durable-lookup roles (ref: core/database_util.go
        # WriteReceipts + WriteTxLookupEntries): receipts by block hash,
        # txn hash -> block number — never pruned, unlike the chain's
        # in-memory state window
        self._receipts: dict[bytes, list[bytes]] = {}
        self._tx_loc: dict[bytes, int] = {}

    def put_block(self, block: Block) -> None:
        raw = block.encode()
        h = block.hash
        self._by_hash[h] = raw
        self._hash_by_number[block.number] = h

    def get_block(self, h: bytes) -> Block | None:
        raw = self._by_hash.get(h)
        return Block.decode(raw) if raw is not None else None

    def get_hash_by_number(self, n: int) -> bytes | None:
        return self._hash_by_number.get(n)

    def set_head(self, h: bytes) -> None:
        self._head = h

    def get_head(self) -> bytes | None:
        return self._head

    def put_receipts(self, block_hash: bytes, encoded: list[bytes],
                     tx_locs) -> None:
        self._receipts[block_hash] = list(encoded)
        for th, n in tx_locs:
            self._tx_loc[th] = n

    def get_receipts(self, block_hash: bytes) -> list[bytes] | None:
        return self._receipts.get(block_hash)

    def put_snapshot(self, payload: bytes) -> None:
        """Durable fast-sync state snapshot (one, latest wins) — what a
        fast-synced node restarts from in place of the ancestors it
        never downloaded (statesync sidecar; see core/statesync.py)."""
        self._snapshot = payload

    def get_snapshot(self) -> bytes | None:
        return getattr(self, "_snapshot", None)

    def tx_loc(self, txn_hash: bytes) -> int | None:
        return self._tx_loc.get(txn_hash)

    # -- fast-sync page staging (mid-sync crash resume) ----------------
    # One append-only slot of raw page blobs written as the live sync
    # accepts pages, cleared on adoption/abort.  A node that crashes
    # mid-download restarts, finds consistent staged pages, and resumes
    # the download from the staged cursor instead of from zero.

    def append_sync_page(self, blob: bytes) -> None:
        if not hasattr(self, "_sync_pages"):
            self._sync_pages: list[bytes] = []
        self._sync_pages.append(blob)

    def load_sync_pages(self) -> list[bytes]:
        return list(getattr(self, "_sync_pages", ()))

    def clear_sync_staging(self) -> None:
        self._sync_pages = []

    def close(self) -> None:
        pass


class FileStore(MemoryStore):
    """Append-only log + index — the durable store (the reference's
    LevelDB role, ethdb/database.go, for the write/read-back/restart
    paths Geec actually uses: blocks by hash/number + head tracking,
    core/database_util.go).

    Layout: ``blocks.log`` is a sequence of [u32 len][rlp block] records;
    ``HEAD`` holds the head hash.  Restart replays the log to rebuild the
    in-memory index (crash-safe: a torn tail record is truncated).
    """

    def __init__(self, path: str):
        super().__init__()
        os.makedirs(path, exist_ok=True)
        self._dir = path
        self._log_path = os.path.join(path, "blocks.log")
        self._head_path = os.path.join(path, "HEAD")
        self._replay()
        self._replay_receipts()
        self._log = open(self._log_path, "ab")
        self._rlog = open(os.path.join(path, "receipts.log"), "ab")

    def _replay(self) -> None:
        if not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as f:
            data = f.read()
        pos = 0
        good_end = 0
        while pos + 4 <= len(data):
            (n,) = struct.unpack("<I", data[pos : pos + 4])
            if pos + 4 + n > len(data):
                break  # torn tail
            raw = data[pos + 4 : pos + 4 + n]
            try:
                block = Block.decode(raw)
            except Exception:
                break
            self._by_hash[block.hash] = raw
            self._hash_by_number[block.number] = block.hash
            pos += 4 + n
            good_end = pos
        if good_end != len(data):
            with open(self._log_path, "r+b") as f:
                f.truncate(good_end)
        if os.path.exists(self._head_path):
            with open(self._head_path, "rb") as f:
                h = f.read()
            if h in self._by_hash:
                self._head = h

    def put_block(self, block: Block) -> None:
        if block.hash in self._by_hash:
            return
        raw = block.encode()
        self._log.write(struct.pack("<I", len(raw)) + raw)
        self._log.flush()
        os.fsync(self._log.fileno())
        self._by_hash[block.hash] = raw
        self._hash_by_number[block.number] = block.hash

    def put_snapshot(self, payload: bytes) -> None:
        # atomic tmp+rename: a crash mid-write must leave the previous
        # snapshot (or none), never a torn one
        tmp = os.path.join(self._dir, "snapshot.rlp.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, "snapshot.rlp"))

    def get_snapshot(self) -> bytes | None:
        try:
            with open(os.path.join(self._dir, "snapshot.rlp"), "rb") as f:
                return f.read()
        except OSError:
            return None

    def set_head(self, h: bytes) -> None:
        super().set_head(h)
        tmp = self._head_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(h)
        os.replace(tmp, self._head_path)

    def put_receipts(self, block_hash: bytes, encoded: list[bytes],
                     tx_locs) -> None:
        """Durable receipts + txn-lookup entries (the LevelDB
        WriteReceipts/WriteTxLookupEntries role) — an append-only
        sidecar log so historical receipts survive the in-memory state
        window AND restarts.  Non-fsynced: derived data, rebuilt from
        block replay if a tail is torn."""
        if block_hash in self._receipts:
            return
        rec = rlp.encode([block_hash, list(encoded),
                          [[th, n] for th, n in tx_locs]])
        self._rlog.write(struct.pack("<I", len(rec)) + rec)
        self._rlog.flush()
        super().put_receipts(block_hash, encoded, tx_locs)

    def _replay_receipts(self) -> None:
        path = os.path.join(self._dir, "receipts.log")
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        good_end = 0
        while pos + 4 <= len(data):
            (n,) = struct.unpack("<I", data[pos : pos + 4])
            if pos + 4 + n > len(data):
                break  # torn tail
            try:
                bh, encoded, locs = rlp.decode(data[pos + 4 : pos + 4 + n])
            except Exception:
                break
            super().put_receipts(
                bytes(bh), [bytes(e) for e in encoded],
                [(bytes(th), rlp.decode_uint(num)) for th, num in locs])
            pos += 4 + n
            good_end = pos
        if good_end != len(data):
            # truncate the tear (mirror _replay): appends after a torn
            # record would be unreadable forever, and each restart would
            # re-append the whole post-tear suffix unboundedly
            with open(path, "r+b") as f:
                f.truncate(good_end)

    def append_sync_page(self, blob: bytes) -> None:
        """Durable sync-page staging: same [u32 len][blob] framing as
        blocks.log, torn-tail tolerant on load.  Non-fsynced — staging
        is an optimization; a lost tail just re-downloads those pages."""
        super().append_sync_page(blob)
        with open(os.path.join(self._dir, "sync_pages.log"), "ab") as f:
            f.write(struct.pack("<I", len(blob)) + blob)
            f.flush()

    def load_sync_pages(self) -> list[bytes]:
        path = os.path.join(self._dir, "sync_pages.log")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return []
        out: list[bytes] = []
        pos = 0
        while pos + 4 <= len(data):
            (n,) = struct.unpack("<I", data[pos : pos + 4])
            if pos + 4 + n > len(data):
                break  # torn tail
            out.append(data[pos + 4 : pos + 4 + n])
            pos += 4 + n
        return out

    def clear_sync_staging(self) -> None:
        super().clear_sync_staging()
        try:
            os.remove(os.path.join(self._dir, "sync_pages.log"))
        except OSError:
            pass

    def close(self) -> None:
        self._log.close()
        self._rlog.close()


def make_genesis(extra: bytes = b"geec-genesis", time: int = 0,
                 alloc: dict[bytes, int] | None = None) -> Block:
    """Genesis block; the ``"thw"`` consensus config lives in the genesis
    JSON beside it (ref: core/genesis.go SetupGenesisBlock +
    params/config.go:124).  ``alloc`` (address -> balance) sets the
    genesis state root (ref: GenesisAlloc, core/genesis.go:228)."""
    from eges_tpu.core.state import StateDB
    root = StateDB.from_alloc(alloc or {}).root()
    return new_block(Header(number=0, time=time, extra=extra,
                            parent_hash=ZERO_HASH, trust_rand=0, root=root))


class BlockChain:
    """Ordered canonical chain with an insert funnel.

    All block sources (proposer's own sealed block, confirmed pending
    blocks, synthesized empty blocks, sync backfill) converge here, the
    way every Geec path converges on fetcher.Enqueue -> insertChain in
    the reference (SURVEY §3.3, eth/fetcher/fetcher.go:647-684).  Blocks
    arriving out of order are buffered and inserted once their parent
    lands, preserving the reference's "blocks come in order" invariant
    (core/geec_state.go:962).
    """

    _MAX_CANDIDATES = 4  # buffered blocks per height (distinct hashes)

    # keep a state snapshot for this many recent blocks (older heights
    # are final many times over; restart replays from genesis anyway)
    _STATE_KEEP = 1024

    def __init__(self, store=None, genesis: Block | None = None,
                 verifier=None, listeners=(), alloc=None, engine=None):
        from eges_tpu.core.state import StateDB

        self.store = store if store is not None else MemoryStore()
        self.verifier = verifier
        if engine is None:
            from eges_tpu.core.engine import GeecEngine
            engine = GeecEngine()
        self.engine = engine
        self._listeners = list(listeners)
        self._lock = threading.RLock()
        # out-of-order buffer: up to _MAX_CANDIDATES first-seen distinct
        # blocks per height, so neither "stale block squats the slot" nor
        # "late conflicting offer displaces the good block" can stall the
        # funnel — insertion tries every candidate when the height opens
        self._future: dict[int, list[Block]] = {}
        self.bad_blocks = 0
        # owning GeecNode attaches its event journal (utils/journal.py)
        self.journal = None
        self.last_error: str | None = None
        self.alloc = dict(alloc or {})
        # state snapshots + receipts per canonical block hash (L3)
        self._states: dict[bytes, object] = {}
        self._state_height: dict[bytes, int] = {}
        self._receipts: dict[bytes, tuple] = {}
        # txn-hash -> (block number, index): the LevelDB txn-lookup
        # index role (ref: core/database_util.go WriteTxLookupEntries),
        # pruned in step with the state snapshots
        self._tx_index: dict[bytes, tuple[int, int]] = {}
        self._txs_by_height: dict[int, list[bytes]] = {}
        # sectioned bitsliced log-bloom index (core/bloombits role):
        # getLogs reads 3 index rows per filter value instead of walking
        # every header in range
        from eges_tpu.core.bloomindex import BloomIndex
        self.bloom_index = BloomIndex()

        head_hash = self.store.get_head()
        if head_hash is None:
            self.genesis = genesis if genesis is not None else make_genesis(
                alloc=self.alloc)
            self.store.put_block(self.genesis)
            self.store.set_head(self.genesis.hash)
            self._head = self.genesis
        else:
            self._head = self.store.get_block(head_hash)
            g = self.store.get_block(self.store.get_hash_by_number(0))
            self.genesis = g if g is not None else genesis

        if self.genesis is None:
            raise ChainError("store has a head but no genesis block")
        gstate = StateDB.from_alloc(self.alloc)
        if self.genesis.header.root != gstate.root():
            raise ChainError("genesis state root does not match alloc")
        self._remember_state(self.genesis.hash, 0, gstate, ())
        self.bloom_index.add(0, self.genesis.header.bloom)
        # restart: rebuild state snapshots by replaying the stored chain
        # (the reference replays into StateDB from LevelDB; here states
        # are in-memory and derived, SURVEY §5 checkpoint/resume).  A
        # fast-synced node has no ancestors below its pivot — its replay
        # anchors on the durable snapshot sidecar instead (root-checked
        # against the pivot block it claims to be; see adopt_snapshot).
        start = 1
        snap_err = None
        # O(tail) restart surface read by the owning GeecNode: the
        # root-verified anchor height (0 = full replay) and the
        # checkpoint's consensus soft-state section, if any
        self.snapshot_anchor = 0
        self.snapshot_consensus: dict | None = None
        snap_raw = self.store.get_snapshot()
        if snap_raw is not None:
            from eges_tpu.core import statesync as _ss

            try:
                sh, sstate, scons = _ss.decode_checkpoint(snap_raw)
                sblk = self.store.get_block(sh)
                if (sblk is not None and 0 < sblk.number <= self._head.number
                        and sstate.root() == sblk.header.root):
                    self._remember_state(sblk.hash, sblk.number, sstate, ())
                    self.bloom_index.add(sblk.number, sblk.header.bloom)
                    start = sblk.number + 1
                    self.snapshot_anchor = sblk.number
                    # consensus section only trusted on the verified path
                    self.snapshot_consensus = scons
                else:
                    snap_err = "snapshot does not match its pivot block"
            except Exception as exc:  # corrupt sidecar
                snap_err = f"snapshot sidecar unreadable ({exc!r})"
        for n in range(start, self._head.number + 1):
            blk = self.get_block_by_number(n)
            if blk is None:
                # a fast-synced store has no ancestors below its pivot:
                # with the sidecar invalid there is nothing to replay
                # from — fail LOUDLY with the reason, not an
                # AttributeError mid-init (r5 review finding)
                raise ChainError(
                    f"block {n} missing during restart replay"
                    + (f"; {snap_err}" if snap_err else "")
                    + "; wipe the datadir and resync")
            parent_state = self._states[blk.header.parent_hash]
            state, receipts, _ = self._process(blk, parent_state)
            self._remember_state(blk.hash, n, state, receipts)
            self._index_txns(blk, receipts)
            self.bloom_index.add(n, blk.header.bloom)

    # -- reads ------------------------------------------------------------

    def head(self) -> Block:
        return self._head

    def height(self) -> int:
        return self._head.number

    def get_block_by_number(self, n: int) -> Block | None:
        h = self.store.get_hash_by_number(n)
        return self.store.get_block(h) if h is not None else None

    def get_block(self, h: bytes) -> Block | None:
        return self.store.get_block(h)

    def has_block(self, h: bytes) -> bool:
        return self.store.get_block(h) is not None

    # -- listeners --------------------------------------------------------

    def add_listener(self, fn) -> None:
        """``fn(block)`` fires after each canonical insert — the
        NotifyNewBlock hook (ref: core/blockchain.go:526-527)."""
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    # -- verification -----------------------------------------------------

    def _verify_header(self, header: Header) -> None:
        """Ancestry checks plus the engine's own rules (the
        consensus.Engine seam — ref: consensus/consensus.go:57; Geec's
        check is intentionally minimal, geec.go:186-210)."""
        if header.number != self._head.number + 1:
            raise ChainError(
                f"non-sequential insert: {header.number} onto {self._head.number}")
        if header.parent_hash != self._head.hash:
            raise ChainError("unknown ancestor")
        from eges_tpu.core.engine import EngineError
        try:
            self.engine.verify_header(self, header)
        except EngineError as e:
            raise ChainError(f"engine: {e}")

    def _verify_body(self, block: Block) -> None:
        """Uncle/tx-root checks (ref: core/block_validator.go:51-76;
        Geec/fake txns are outside TxHash by design)."""
        if block.uncles:
            raise ChainError("uncles not allowed")  # geec.go:215-219
        from eges_tpu.core.trie import derive_sha, EMPTY_ROOT
        want = (derive_sha([t.encode() for t in block.transactions])
                if block.transactions else EMPTY_ROOT)
        if block.header.tx_hash != want:
            raise ChainError("transaction root mismatch")

    def _process(self, block: Block, parent_state):
        """Batched sender recovery (the TPU hot path, SURVEY §3.5) +
        transaction application; validates state/receipt/gas commitments
        (ref: core/block_validator.go:82-105 ValidateState)."""
        from eges_tpu.core.state import (
            StateError, process_block, receipts_root, recover_senders,
        )
        try:
            senders = recover_senders(block.transactions, self.verifier)
            state, receipts, gas = process_block(parent_state, block,
                                                 senders, self.verifier)
        except StateError as e:
            raise ChainError(str(e))
        if block.header.root != state.root():
            raise ChainError("state root mismatch")
        if block.header.receipt_hash != receipts_root(receipts):
            raise ChainError("receipt root mismatch")
        if block.header.gas_used != gas:
            raise ChainError("gas used mismatch")
        from eges_tpu.core.state import receipts_bloom
        if block.header.bloom != receipts_bloom(receipts):
            raise ChainError("log bloom mismatch")
        return state, receipts, gas

    def _remember_state(self, block_hash: bytes, height: int, state,
                        receipts) -> None:
        self._states[block_hash] = state
        self._state_height[block_hash] = height
        self._receipts[block_hash] = tuple(receipts)
        if len(self._states) > self._STATE_KEEP + 64:
            # prune relative to the height being remembered, NOT the
            # stored head: during restart replay the head is already at
            # its final height while replay is still early, and pruning
            # by the final head would delete the parent state the next
            # replay iteration needs
            floor = height - self._STATE_KEEP
            for h, n in list(self._state_height.items()):
                if 0 < n < floor:
                    self._states.pop(h, None)
                    self._state_height.pop(h, None)
                    self._receipts.pop(h, None)
            for n in [k for k in self._txs_by_height if 0 < k < floor]:
                for th in self._txs_by_height.pop(n):
                    self._tx_index.pop(th, None)

    def _index_txns(self, block: Block, receipts=()) -> None:
        if not block.transactions:
            return
        hashes = []
        for i, t in enumerate(block.transactions):
            self._tx_index[t.hash] = (block.number, i)
            hashes.append(t.hash)
        self._txs_by_height[block.number] = hashes
        # durable sidecar (the LevelDB receipts + tx-lookup role): the
        # in-memory window prunes, the store does not
        self.store.put_receipts(block.hash, [r.encode() for r in receipts],
                                [(h, block.number) for h in hashes])

    def lookup_txn(self, txn_hash: bytes):
        """``(block, index, receipt) | None`` via the txn index, falling
        back to the store for history outside the in-memory window."""
        loc = self._tx_index.get(txn_hash)
        if loc is None:
            n = self.store.tx_loc(txn_hash)
            if n is None:
                return None
            blk = self.get_block_by_number(n)
            if blk is None:
                return None
            for i, t in enumerate(blk.transactions):
                if t.hash == txn_hash:
                    receipts = self.receipts_of(blk.hash)
                    return blk, i, (receipts[i] if i < len(receipts)
                                    else None)
            return None
        n, i = loc
        blk = self.get_block_by_number(n)
        if blk is None or i >= len(blk.transactions) \
                or blk.transactions[i].hash != txn_hash:
            return None  # displaced by a reorg
        receipts = self.receipts_of(blk.hash)
        return blk, i, receipts[i] if i < len(receipts) else None

    # -- state reads (L3 surface for RPC / txpool / acceptors) ------------

    def state_at(self, block_hash: bytes):
        return self._states.get(block_hash)

    def head_state(self):
        return self._states[self._head.hash]

    def receipts_of(self, block_hash: bytes) -> tuple:
        got = self._receipts.get(block_hash)
        if got is not None:
            return got
        # outside the pruned window: the durable sidecar still has them
        stored = self.store.get_receipts(block_hash)
        if stored is None:
            return ()
        from eges_tpu.core.state import Receipt
        return tuple(Receipt.from_rlp(rlp.decode(e)) for e in stored)

    def execute_preview(self, txs, coinbase: bytes = bytes(20),
                        ctx=None) -> tuple:
        """Proposer-side dry run on top of the head state: greedily apply
        ``txs``, dropping any that cannot execute, and return
        ``(kept_txs, root, receipt_root, gas_used)`` for the new header
        (the role of the worker's commitTransactions loop,
        ref: miner/worker.go:463-467).  ``coinbase`` is the PROPOSED
        block's fee recipient and ``ctx`` MUST carry the exact
        time/difficulty/number the sealed header will — validation
        re-executes with ``block_ctx(header)``, so any divergence (a
        contract reading TIMESTAMP, say) makes the committed state root
        unreproducible."""
        from eges_tpu.core.evm import BlockCtx
        from eges_tpu.core.state import (
            StateError, apply_txn, receipts_root, recover_senders,
        )
        with self._lock:
            state = self.head_state().copy()
            try:
                senders = recover_senders(txs, self.verifier)
            except StateError:
                senders = [None] * len(txs)
            kept, receipts, gas = [], [], 0
            if ctx is None:
                ctx = BlockCtx(coinbase=coinbase,
                               number=self._head.number + 1,
                               time=self._head.header.time + 1)
            for t, sender in zip(txs, senders):
                if sender is None:
                    continue
                try:
                    r = apply_txn(state, t, sender, coinbase, gas,
                                  ctx=ctx, verifier=self.verifier)
                except StateError:
                    continue
                gas = r.cumulative_gas_used
                receipts.append(r)
                kept.append(t)
            from eges_tpu.core.state import receipts_bloom
            return (kept, state.root(), receipts_root(receipts), gas,
                    receipts_bloom(receipts))

    def validate_candidate(self, block: Block) -> bool:
        """Full acceptor-side validation of a proposed block WITHOUT
        inserting: ancestry, tx root, signatures, state/receipt/gas
        commitments — the checks the insert path will make, run before
        ACKing (the reference acceptor ACKs unconditionally,
        geec_state.go:545).  Falls back to body+signature checks when the
        parent state is unknown (we are behind)."""
        with self._lock:
            try:
                self._verify_body(block)
            except ChainError:
                return False
            parent_state = self._states.get(block.header.parent_hash)
            if parent_state is None:
                # parent unknown: we are behind — signature checks only
                from eges_tpu.crypto.verify_host import batch_verify_txns
                return batch_verify_txns(block.transactions, self.verifier)
            # parent known: the proposal must extend OUR head, or the
            # insert path would reject what we ACKed ("non-sequential
            # insert") and the quorum round is wasted on a stale parent
            if (block.header.parent_hash != self._head.hash
                    or block.header.number != self._head.number + 1):
                return False
            try:
                self._process(block, parent_state)
            except ChainError:
                return False
            return True

    # -- insert funnel ----------------------------------------------------

    def offer(self, block: Block) -> list[Block]:
        """Submit a block from any source; inserts it (and any buffered
        successors) when in order.  Returns the blocks inserted.

        Never raises on a bad block: like the fetcher funnel it came from
        (eth/fetcher/fetcher.go:647-684 drops blocks that fail import), a
        block that fails verification is dropped and counted — an invalid
        or conflicting gossip block must not take down the caller's event
        loop.
        """
        with self._lock:
            inserted = []
            if block.number <= self._head.number:
                return inserted  # duplicate/old — fetcher-style dedup
            if block.number > self._head.number + 256:
                return inserted  # beyond the buffer window: sync, don't buffer
            cands = self._future.setdefault(block.number, [])
            if (len(cands) < self._MAX_CANDIDATES
                    and all(b.hash != block.hash for b in cands)):
                cands.append(block)
            while (cands := self._future.get(self._head.number + 1)):
                del self._future[self._head.number + 1]
                ok = None
                for cand in cands:
                    try:
                        self._insert(cand)
                        ok = cand
                        break
                    except ChainError as e:
                        self.bad_blocks += 1
                        self.last_error = str(e)
                        from eges_tpu.utils.metrics import DEFAULT as metrics
                        metrics.counter("chain.bad_blocks").inc()
                if ok is None:
                    break
                inserted.append(ok)
            # memory bound: 256-height window x _MAX_CANDIDATES per height
            return inserted

    def replace_suffix(self, blocks: list[Block]) -> bool:
        """Reorg: replace our chain suffix with a confirmed alternative.

        Geec forks arise one way only: a partitioned node forced local
        empty blocks (confidence 0, HandleBlockTimeout semantics) while
        the quorum confirmed real ones.  The quorum chain wins — but ONLY
        ever displacing locally-forced empty blocks; confirmed non-empty
        history is immutable.  (The reference leans on geth's
        total-difficulty reorg in core/blockchain.go:927+; Geec confidence
        replaces difficulty here.)

        ``blocks``: contiguous ascending, parented into our chain.
        Returns True if the reorg was applied.
        """
        with self._lock:
            if not blocks:
                return False
            first = blocks[0]
            if first.number > self._head.number:
                return False  # nothing to displace; use offer()
            anchor = self.get_block_by_number(first.number - 1)
            if anchor is None or first.header.parent_hash != anchor.hash:
                return False
            # every displaced block must be a local empty (EmptyAddr
            # coinbase) with no quorum confidence
            for n in range(first.number, self._head.number + 1):
                displaced = self.get_block_by_number(n)
                conf = displaced.confirm.confidence if displaced.confirm else 0
                if displaced.header.coinbase != EMPTY_ADDR or conf > 0:
                    return False
            # replacements must be confirmed and well-linked
            prev = anchor
            for b in blocks:
                if (b.number != prev.number + 1
                        or b.header.parent_hash != prev.hash
                        or b.confirm is None):
                    return False
                prev = b
            # rewind + replay (the bloom index rewinds too; each insert
            # re-adds its height with the replacement bloom)
            self._head = anchor
            self.bloom_index.truncate(first.number)
            for b in blocks:
                try:
                    self._insert(b)
                except ChainError as e:
                    self.bad_blocks += 1
                    self.last_error = str(e)
                    return False
            self._future.clear()
            return True

    def _insert(self, block: Block) -> None:
        import time

        from eges_tpu.utils.metrics import DEFAULT as metrics

        # analysis: allow-determinism(insert dt is metrics/volatile-only)
        t0 = time.monotonic()
        self._verify_header(block.header)
        self._verify_body(block)
        parent_state = self._states.get(block.header.parent_hash)
        if parent_state is None:
            raise ChainError("no state for parent")  # cannot happen in-order
        state, receipts, _ = self._process(block, parent_state)
        self.store.put_block(block)
        self.store.set_head(block.hash)
        self._head = block
        self._remember_state(block.hash, block.number, state, receipts)
        self._index_txns(block, receipts)
        self.bloom_index.add(block.number, block.header.bloom)
        from eges_tpu.utils import tracing

        # analysis: allow-determinism(insert dt is metrics/volatile-only)
        dt = time.monotonic() - t0
        metrics.timer("chain.insert").update(dt)
        metrics.histogram("chain.insert_seconds").observe(dt)
        metrics.counter("chain.blocks").inc()
        metrics.counter("chain.txns").inc(len(block.transactions))
        metrics.counter("chain.geec_txns").inc(len(block.geec_txns))
        metrics.gauge("chain.height").set(block.number)
        tracing.DEFAULT.record_span("chain.insert", dt, number=block.number,
                                    txns=len(block.transactions))
        if self.journal is not None:
            self.journal.record("block_committed", blk=block.number,
                                txns=len(block.transactions),
                                dt=round(dt, 6))
        for fn in self._listeners:
            fn(block)

    def adopt_snapshot(self, block: Block, state) -> None:
        """Install a root-verified state snapshot as the new head
        WITHOUT its ancestry — the fast-sync pivot adoption (ref:
        eth/downloader/downloader.go:1353 pivot commit +
        statesync.go:1).  The caller is responsible for having verified
        ``block`` against a quorum certificate; this method enforces the
        state<->header binding and persists the snapshot sidecar so a
        restart can anchor on it (no ancestors exist to replay)."""
        from eges_tpu.core import statesync as _ss

        with self._lock:
            if state.root() != block.header.root:
                raise ChainError("snapshot root does not match pivot header")
            if block.number <= self._head.number:
                raise ChainError("pivot not ahead of head")
            self.store.put_block(block)
            self.store.set_head(block.hash)
            self._head = block
            self._remember_state(block.hash, block.number, state, ())
            self._index_txns(block)
            self.bloom_index.add(block.number, block.header.bloom)
            self.store.put_snapshot(_ss.encode_snapshot(block.hash, state))
            from eges_tpu.utils.metrics import DEFAULT as metrics

            metrics.gauge("chain.height").set(block.number)
            metrics.counter("chain.fastsync_adoptions").inc()
        for fn in self._listeners:
            fn(block)

    def make_empty_block(self) -> Block:
        """Empty block atop the current head, keeping numbers dense
        (ref: core/geec_state.go:885-920 GenerateEmptyBlock —
        coinbase=EmptyAddr marks it; state root carried forward)."""
        parent = self._head
        return new_block(Header(
            parent_hash=parent.hash,
            number=parent.number + 1,
            time=parent.header.time + 1,
            coinbase=EMPTY_ADDR,
            root=parent.header.root,
            difficulty=1,
        ))
