"""EVM execution tracing (the eth/tracers + vm.Config.Tracer role).

The reference hooks a ``Tracer`` into the interpreter loop
(core/vm/interpreter.go calls tracer.CaptureState per opcode;
eth/tracers/tracer.go + internal/ethapi expose it as
``debug_traceTransaction``).  Same seam here: :class:`StructLogTracer`
receives one callback per executed opcode from ``EVM._run`` and
produces geth-shaped struct logs — pc, op name, remaining gas, gas cost,
call depth, stack — so a failing contract call can be debugged from the
RPC instead of by reading the interpreter.

Gas cost per step is derived retroactively: a step's cost is its gas
minus the gas at the NEXT step observed at the same depth (for CALL-family
ops that spans the whole sub-call, which is what gas attribution at the
call site means); the final pending step of each depth settles against
the frame's end-of-run gas.
"""

from __future__ import annotations

OPNAMES: dict[int, str] = {
    0x00: "STOP", 0x01: "ADD", 0x02: "MUL", 0x03: "SUB", 0x04: "DIV",
    0x05: "SDIV", 0x06: "MOD", 0x07: "SMOD", 0x08: "ADDMOD",
    0x09: "MULMOD", 0x0A: "EXP", 0x0B: "SIGNEXTEND",
    0x10: "LT", 0x11: "GT", 0x12: "SLT", 0x13: "SGT", 0x14: "EQ",
    0x15: "ISZERO", 0x16: "AND", 0x17: "OR", 0x18: "XOR", 0x19: "NOT",
    0x1A: "BYTE",
    0x20: "SHA3",
    0x30: "ADDRESS", 0x31: "BALANCE", 0x32: "ORIGIN", 0x33: "CALLER",
    0x34: "CALLVALUE", 0x35: "CALLDATALOAD", 0x36: "CALLDATASIZE",
    0x37: "CALLDATACOPY", 0x38: "CODESIZE", 0x39: "CODECOPY",
    0x3A: "GASPRICE", 0x3B: "EXTCODESIZE", 0x3C: "EXTCODECOPY",
    0x3D: "RETURNDATASIZE", 0x3E: "RETURNDATACOPY",
    0x40: "BLOCKHASH", 0x41: "COINBASE", 0x42: "TIMESTAMP",
    0x43: "NUMBER", 0x44: "DIFFICULTY", 0x45: "GASLIMIT",
    0x50: "POP", 0x51: "MLOAD", 0x52: "MSTORE", 0x53: "MSTORE8",
    0x54: "SLOAD", 0x55: "SSTORE", 0x56: "JUMP", 0x57: "JUMPI",
    0x58: "PC", 0x59: "MSIZE", 0x5A: "GAS", 0x5B: "JUMPDEST",
    0xF0: "CREATE", 0xF1: "CALL", 0xF2: "CALLCODE", 0xF3: "RETURN",
    0xF4: "DELEGATECALL", 0xFA: "STATICCALL", 0xFD: "REVERT",
    0xFE: "INVALID", 0xFF: "SELFDESTRUCT",
}
for _i in range(32):
    OPNAMES[0x60 + _i] = f"PUSH{_i + 1}"
for _i in range(16):
    OPNAMES[0x80 + _i] = f"DUP{_i + 1}"
    OPNAMES[0x90 + _i] = f"SWAP{_i + 1}"
for _i in range(5):
    OPNAMES[0xA0 + _i] = f"LOG{_i}"


def op_name(op: int) -> str:
    return OPNAMES.get(op, f"opcode {op:#x}")


class StructLogTracer:
    """Per-opcode struct logger (ref: core/vm/logger.go StructLogger).

    ``on_step`` fires from the interpreter before each opcode executes;
    ``on_fault`` tags the most recent step with the error that unwound
    the frame; ``result`` settles pending gas costs and returns the
    RPC-shaped trace."""

    MAX_STEPS = 200_000  # bound adversarial traces (geth caps via timeout)

    def __init__(self, with_stack: bool = True):
        self.logs: list[dict] = []
        self.with_stack = with_stack
        self._pending: dict[int, dict] = {}  # depth -> unsettled entry
        self.truncated = False
        self.output = b""  # revert data / return data when the EVM has it

    def on_step(self, pc: int, op: int, gas: int, depth: int,
                stack: list) -> None:
        if len(self.logs) >= self.MAX_STEPS:
            self.truncated = True
            return
        # settle the previous entry at this depth: its cost is the gas
        # drop to now (spans the sub-call for CALL-family ops); a depth
        # we returned from deeper than this one settles on frame end
        prev = self._pending.get(depth)
        if prev is not None:
            prev["gasCost"] = prev["gas"] - gas
        for d in [d for d in self._pending if d > depth]:
            del self._pending[d]
        entry = {"pc": pc, "op": op_name(op), "gas": gas, "gasCost": 0,
                 "depth": depth + 1}  # geth depth is 1-based
        if self.with_stack:
            entry["stack"] = [hex(v) for v in stack]  # bottom -> top
        self.logs.append(entry)
        self._pending[depth] = entry

    def on_fault(self, depth: int, gas_left: int, error: str) -> None:
        prev = self._pending.pop(depth, None)
        if prev is not None:
            prev["gasCost"] = prev["gas"] - gas_left
            prev["error"] = error
        elif self.logs:
            self.logs[-1].setdefault("error", error)

    def on_frame_end(self, depth: int, gas_left: int) -> None:
        """Settle the frame's terminal opcode (RETURN/STOP/implicit end)
        against the gas the frame finished with — on_step can only
        settle a step once a LATER step at the same depth arrives."""
        prev = self._pending.pop(depth, None)
        if prev is not None:
            prev["gasCost"] = prev["gas"] - gas_left

    def result(self, *, gas_used: int, failed: bool,
               output: bytes) -> dict:
        self._pending.clear()
        out = {
            "gas": gas_used,
            "failed": failed,
            "returnValue": (output or self.output).hex(),
            "structLogs": self.logs,
        }
        if self.truncated:
            out["truncated"] = True
        return out
